"""Phase-taxonomy timers and profiler hooks.

Capability parity: the reference's TIMING accumulators
(cblas_alltoalltime / allgathertime / localspmvtime / mergeconttime /
transvectime, CombBLAS.h:78-100, stamped around each SpMV/SpGEMM phase
e.g. ParFriends.h:1743-1879) and its Fan-Out/LocalSpMV/Fan-In/Merge
PAPI phase matrices (papi_combblas_globals.h).

TPU-native re-design: inside one jitted program XLA fuses the phases,
so wall-clock attribution happens at two levels: (1) host-level named
accumulators (`Timers`) around eager or per-call stages — the
MPI_Wtime analogue; (2) `trace()` wraps `jax.profiler` so the XLA
op-level breakdown (the true fan-out/local/fan-in/merge split of a
fused step) lands in a TensorBoard-readable trace directory.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Callable

import jax

#: the reference's phase taxonomy (papi_combblas_globals.h)
PHASES = ("fan_out", "local", "fan_in", "merge")


class Timers:
    """Named wall-clock accumulators (≅ the cblas_* globals)."""

    def __init__(self):
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def timed(self, name: str, fn: Callable, *args, **kw):
        """Run fn, blocking on its outputs so device time is included
        (without block_until_ready a dispatch returns immediately and
        the phase under-reports)."""
        with self.phase(name):
            out = fn(*args, **kw)
            jax.block_until_ready(out)
        return out

    def report(self) -> dict:
        return {k: {"total_s": round(self.totals[k], 6),
                    "calls": self.counts[k],
                    "mean_ms": round(1e3 * self.totals[k]
                                     / max(1, self.counts[k]), 3)}
                for k in sorted(self.totals)}

    def print_report(self, header: str = "timers"):
        print(f"== {header} ==")
        for k, v in self.report().items():
            print(f"  {k:<24} {v['total_s']:>9.4f}s  x{v['calls']}"
                  f"  ({v['mean_ms']:.3f} ms/call)")


#: process-wide accumulators, stamped by the instrumented drivers
#: (spmv.spmsv_timed, spgemm's phased paths, models.mcl) — the
#: cblas_* globals analogue. Callers snapshot/reset around a region:
#:     GLOBAL.totals.clear(); GLOBAL.counts.clear()
GLOBAL = Timers()

#: phase SYNC gate (≅ compiling the reference with -DTIMING): when
#: off (default), instrumented drivers stamp dispatch-time only and
#: skip their forced device syncs — production calls pay nothing.
_ENABLED = False


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = on


def sync(x) -> None:
    """Force completion with a tiny data-DEPENDENT readback: on
    remote-TPU relays block_until_ready can ack before execution
    finishes, so honest phase boundaries fetch a value (one element,
    via a device-side slice — not the whole array). No-op when phase
    timing is disabled."""
    if not _ENABLED:
        return
    import numpy as np
    for leaf in jax.tree_util.tree_leaves(x):
        if hasattr(leaf, "ravel") and getattr(leaf, "size", 0) > 0:
            np.asarray(leaf.ravel()[0])
            return


@contextlib.contextmanager
def trace(logdir: str):
    """jax.profiler trace context — the XLA-level phase breakdown
    (open the logdir with TensorBoard / xprof)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
