"""Phase-taxonomy timers — THIN COMPATIBILITY SHIM over `combblas_tpu.obs`.

The span tracer (`obs.trace`), metrics registry (`obs.metrics`) and
exporters (`obs.export`) supersede this module; it remains so existing
callers keep working unchanged:

* `Timers` / `GLOBAL` — the named wall-clock accumulators (≅ the
  reference's cblas_* TIMING globals, CombBLAS.h:78-100), still a
  standalone implementation (spmv.spmsv_timed and tests use it
  directly).
* `enabled` / `set_enabled` / `sync` — delegate to `obs.trace`: ONE
  process-wide flag arms both the legacy accumulators' device syncs
  and the span tracer.
* `trace` — the jax.profiler bridge, now `obs.export.profiler_trace`.

New instrumentation should open `obs.span(...)` regions instead; see
`combblas_tpu/obs/__init__.py`.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Callable

import jax

from combblas_tpu.obs import trace as _trace
from combblas_tpu.obs.export import profiler_trace as trace  # noqa: F401

#: the reference's phase taxonomy (papi_combblas_globals.h)
PHASES = ("fan_out", "local", "fan_in", "merge")


class Timers:
    """Named wall-clock accumulators (≅ the cblas_* globals)."""

    def __init__(self):
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def timed(self, name: str, fn: Callable, *args, **kw):
        """Run fn, blocking on its outputs so device time is included
        (without block_until_ready a dispatch returns immediately and
        the phase under-reports)."""
        with self.phase(name):
            out = fn(*args, **kw)
            jax.block_until_ready(out)
        return out

    def report(self) -> dict:
        return {k: {"total_s": round(self.totals[k], 6),
                    "calls": self.counts[k],
                    "mean_ms": round(1e3 * self.totals[k]
                                     / max(1, self.counts[k]), 3)}
                for k in sorted(self.totals)}

    def print_report(self, header: str = "timers"):
        print(f"== {header} ==")
        for k, v in self.report().items():
            print(f"  {k:<24} {v['total_s']:>9.4f}s  x{v['calls']}"
                  f"  ({v['mean_ms']:.3f} ms/call)")


#: process-wide accumulators — kept for direct users (spmsv_timed,
#: scripts); the instrumented drivers now record obs spans instead
GLOBAL = Timers()

#: the sync/span gate moved to obs.trace (one switch for both systems)
enabled = _trace.enabled
set_enabled = _trace.set_enabled
sync = _trace.sync
