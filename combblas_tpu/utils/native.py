"""Build-and-load helper for the native (C++) runtime pieces.

The reference builds its C support libraries (mmio, graph500
generator, usort) with CMake (CMakeLists.txt:115-124); here each
single-file component compiles on first use with g++ into a _build/
directory next to its source and loads via ctypes (no pybind11 in
this environment). A missing toolchain degrades gracefully to None —
callers fall back to their pure-Python paths.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import pathlib
import subprocess


def load_native(src: pathlib.Path, configure) -> ctypes.CDLL | None:
    """Compile ``src`` (if not cached) and return the loaded CDLL with
    ``configure(lib)`` applied; None when the toolchain is missing or
    the build fails. The cache key is the source hash, so edits
    rebuild automatically."""
    try:
        tag = hashlib.sha1(src.read_bytes()).hexdigest()[:12]
        build = src.parent / "_build"
        so = build / f"{src.stem}_{tag}.so"
        if not so.exists():
            build.mkdir(exist_ok=True)
            tmp = so.with_suffix(f".{os.getpid()}.tmp")
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-pthread",
                 str(src), "-o", str(tmp)],
                check=True, capture_output=True, timeout=120)
            tmp.replace(so)  # atomic: concurrent builders race safely
        lib = ctypes.CDLL(str(so))
        configure(lib)
        return lib
    except Exception:
        return None
