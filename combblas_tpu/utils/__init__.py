"""Auxiliary subsystems: timing/profiling phase taxonomy and typed
configuration (SURVEY §5 parity)."""

from combblas_tpu.utils.timing import Timers, trace, PHASES
from combblas_tpu.utils.config import (
    BfsConfig, SpGemmBenchConfig, parse_cli,
)
