"""Typed configuration for the applications (the flag system).

Capability parity: the reference's three config levels (SURVEY §5) —
compile-time macros, per-app hand-rolled argv parsing (MCL's
`ProcessParam`, MCL.cpp:233-296, is the richest), and environment
variables. Here: frozen dataclasses per app + one generic
dataclass->argparse bridge (`parse_cli`), so every knob is typed,
defaulted, and discoverable (`--help`).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from typing import Optional, Type, TypeVar

from combblas_tpu.models.mcl import MclParams

T = TypeVar("T")


# ---------------------------------------------------------------------------
# Roofline peak table (obs.costmodel's denominator)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class BackendPeaks:
    """Per-backend roofline ceilings. These are deliberately coarse,
    DOCUMENTED estimates — the cost model classifies dispatches as
    compute-/memory-/ICI-bound and reports an efficiency FRACTION, so
    only the ratios between the three ceilings need to be in the right
    ballpark, not the absolute numbers."""

    name: str
    flops_per_s: float      # sustained f32 FLOP/s (MXU for TPU)
    mem_bytes_per_s: float  # HBM / main-memory stream bandwidth
    ici_bytes_per_s: float  # per-link interconnect bandwidth
    hbm_bytes: float = 1.6e10  # per-chip memory CAPACITY (the third
    #                            roofline axis: footprints and live-
    #                            buffer watermarks gate against this)


#: name -> peaks. "cpu" models the single-process XLA:CPU backend the
#: tests/benches run on (a few vectorized cores, host-RAM capacity);
#: "tpu" models a v5e-class chip (f32 MXU ~49 TFLOP/s, 819 GB/s HBM,
#: ~160 GB/s ICI per link, 16 GB HBM). Unknown platforms fall back to
#: "cpu".
PEAKS = {
    "cpu": BackendPeaks("cpu", 5.0e10, 2.0e10, 1.0e10, 6.4e10),
    "tpu": BackendPeaks("tpu", 4.9e13, 8.2e11, 1.6e11, 1.6e10),
}


def backend_peaks(platform: Optional[str] = None) -> BackendPeaks:
    """Resolve the roofline peak row for ``platform`` (default: jax's
    default backend; the experimental relay platform counts as TPU).
    COMBBLAS_TPU_PEAKS may carry a JSON object overriding any field,
    e.g. '{"flops_per_s": 1e12}' — measured-machine calibration
    without a code change."""
    if platform is None:
        try:
            import jax
            platform = jax.default_backend()
        except Exception:
            platform = "cpu"
    platform = (platform or "cpu").lower()
    if platform not in PEAKS:
        platform = "tpu" if platform in ("axon", "tpu_relay") else "cpu"
    base = PEAKS[platform]
    raw = os.environ.get("COMBBLAS_TPU_PEAKS", "")
    if raw:
        try:
            override = json.loads(raw)
            base = dataclasses.replace(
                base, **{k: float(v) for k, v in override.items()
                         if k in ("flops_per_s", "mem_bytes_per_s",
                                  "ici_bytes_per_s", "hbm_bytes")})
        except (ValueError, TypeError):
            pass                    # malformed override: keep the table
    return base


def setup_compilation_cache(path: Optional[str] = None) -> Optional[str]:
    """Point jax's persistent compilation cache at a directory so repeat
    bench/driver runs skip XLA compiles entirely (iterations 1-2 of the
    n=65536 MCL run carry ~40 min of relay compiles a warm cache skips).

    ``path`` defaults to the COMBBLAS_TPU_COMPILE_CACHE env var; unset
    or "0" leaves caching off (no behavior change). Returns the active
    cache dir or None. Thresholds are lowered so the many small-but-
    remote-compiled kernels of the phased pipelines are cached too, not
    just the headline SUMMA."""
    if path is None:
        path = os.environ.get("COMBBLAS_TPU_COMPILE_CACHE", "")
    if not path or path == "0":
        return None
    import jax
    path = os.path.abspath(os.path.expanduser(path))
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return path


@dataclasses.dataclass
class BfsConfig:
    """Graph500 BFS harness knobs (≅ TopDownBFS/DirOptBFS argv)."""
    scale: int = 22
    edgefactor: int = 16
    nroots: int = 64
    seed: int = 1
    alpha: int = 8                  # direction-switch threshold
    validate_roots: int = 1         # spec-validate this many roots
    verbose: bool = False


@dataclasses.dataclass
class SpGemmBenchConfig:
    """A*A benchmark knobs (≅ the SpGEMM driver CLIs)."""
    scale: int = 16
    edgefactor: int = 16
    phase_flop_budget: int = 2 ** 27
    seed: int = 1


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """`serve.GraphService` knobs (queue, batcher, deadlines)."""
    max_queue_depth: int = 512      # admission control: above -> shed
    buckets: tuple = (1, 2, 4, 8, 16, 32)   # batch-width jit buckets
    batch_wait_s: float = 0.002     # max linger waiting to fill a batch
    default_deadline_s: Optional[float] = None  # per-request override wins
    bfs_level_est_s: float = 2e-3   # EWMA seed for per-level wall time
    bfs_max_levels: int = 0         # 0 = unbounded (deadline may cap)
    drain_poll_s: float = 0.05      # shutdown drain poll interval
    # BFS batch path: "auto" uses the packed-bit bitplane kernel
    # (models.bfs.bfs_batch_bits) when the matrix is eligible
    # (single-tile, routed, pattern-symmetric), "on" requires it
    # (ValueError when ineligible), "off" forces the dense-column
    # bfs_batch. COMBBLAS_TPU_SERVE_BITS=0 in the environment
    # overrides to "off" without a config change.
    bfs_bits: str = "auto"
    # shed-before-dispatch: reject cc/spmv requests whose remaining
    # deadline is below the kind's EWMA dispatch-cost estimate instead
    # of running a doomed dispatch (BFS keeps its finer level-budget
    # degradation)
    predictive_shed: bool = True
    # serve.latency_s histogram percentiles: True switches the metric
    # to streaming P² sketches (full-run p50/p90/p99 on unbounded
    # soaks); False keeps the sliding 2048-sample reservoir
    latency_sketch: bool = False
    # SLO accounting: a request is "good" when it completes (not shed)
    # within slo_latency_s of enqueue; slo_target is the good-fraction
    # objective. burn rate = (bad_frac)/(1 - slo_target): 1.0 burns
    # the error budget exactly at sustainable rate, >1 exhausts it
    # (gauges `serve.slo_burn_rate{kind}` on /metrics and /varz)
    slo_latency_s: float = 0.25
    slo_target: float = 0.99
    # --- resilience layer (combblas_tpu.resilience) -------------------
    # worker supervision: a crashed worker thread drains every queued
    # future with WorkerCrashedError (nothing hangs) and restarts up to
    # this many times; beyond it the service is dead (/healthz false,
    # submissions refused). 0 = fail permanently on the first crash.
    worker_max_restarts: int = 2
    # per-kind circuit breaker layered on the predictive shed:
    # breaker_threshold CONSECUTIVE dispatch failures open the kind
    # (requests fail fast with CircuitOpenError, shed reason
    # "breaker"); after breaker_recovery_s one half-open probe batch is
    # admitted. 0 disables the breaker entirely.
    breaker_threshold: int = 5
    breaker_recovery_s: float = 1.0
    breaker_half_open_max: int = 1
    # dispatch retry: transient failures (resilience.faults
    # classification) re-dispatch with deterministic exponential
    # backoff, re-materializing the batch's device arrays from the
    # host-side payloads each attempt (serve dispatches never donate,
    # so re-dispatch is always safe). 1 = no retry.
    retry_max_attempts: int = 2
    retry_backoff_s: float = 0.02


def parse_cli(cls: Type[T], argv: Optional[list] = None,
              prog: Optional[str] = None) -> T:
    """Build an argparse CLI from a config dataclass: every field
    becomes `--name` with its default and type (bools become
    store_true flags). ≅ ProcessParam, generically."""
    ap = argparse.ArgumentParser(prog=prog or cls.__name__)
    for f in dataclasses.fields(cls):
        name = "--" + f.name.replace("_", "-")
        if f.type in (bool, "bool"):
            ap.add_argument(name, action="store_true",
                            default=f.default)
        else:
            typ = f.type if callable(f.type) else _resolve(f.type)
            ap.add_argument(name, type=typ, default=f.default)
    ns = ap.parse_args(argv)
    return cls(**{f.name: getattr(ns, f.name)
                  for f in dataclasses.fields(cls)})


def _resolve(t):
    return {"int": int, "float": float, "str": str}.get(t, str)


__all__ = ["BfsConfig", "SpGemmBenchConfig", "ServeConfig", "MclParams",
           "BackendPeaks", "backend_peaks",
           "parse_cli", "setup_compilation_cache"]
