"""JAX version-compatibility shims, installed at package import.

The codebase targets the current JAX surface (`jax.shard_map` with
``check_vma``, `lax.pvary`); the pinned environment may carry an older
release where shard_map still lives under `jax.experimental.shard_map`
(with ``check_rep`` in place of ``check_vma``) and `pvary`/`pcast` do
not exist. Each shim is installed only when the attribute is missing,
so on a new-enough JAX this module is a no-op — the shims can be
deleted wholesale once the pinned JAX catches up.
"""

from __future__ import annotations

import jax
from jax import lax


def install() -> None:
    """Idempotently install the shims onto the jax modules."""
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      check_vma=None, **kw):
            # the new API's check_vma plays the old check_rep's role.
            # Default to False: the old checker has no replication
            # rule for while/cond (NotImplementedError on bodies the
            # new-JAX checker accepts), so code written against the
            # new default can't run checked here anyway.
            if "check_rep" not in kw:
                kw["check_rep"] = bool(check_vma) if check_vma is not None \
                    else False
            return _shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)

        jax.shard_map = shard_map

    if not hasattr(lax, "pvary") and not hasattr(lax, "pcast"):
        # pvary only re-annotates varying mesh axes for the new
        # shard_map type system; data-wise it is the identity, which
        # is exactly right under the old check_rep machinery
        def pvary(x, axis_name=None):
            return x

        lax.pvary = pvary


install()
