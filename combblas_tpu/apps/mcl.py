"""MCL clustering driver (≅ Applications/MCL.cpp main + ProcessParam:
read a graph, cluster, write label file).

    python -m combblas_tpu.apps.mcl --mtx graph.mtx --o clusters.txt
    python -m combblas_tpu.apps.mcl --scale 10 --inflation 2.0
"""

import dataclasses
import json


@dataclasses.dataclass
class Config:
    mtx: str = ""                   # input Matrix Market file
    labeled: str = ""               # or: string-labeled edge list
    scale: int = 10                 # else: R-MAT
    edgefactor: int = 8
    seed: int = 1
    inflation: float = 2.0          # -I
    prune_threshold: float = 1e-4   # -p
    select: int = 1100              # -S
    recover_num: int = 1400         # -R
    recover_pct: float = 0.9
    phases: int = 0                 # 0 = auto
    per_process_mem_gb: float = 0.0  # -per-process-mem (0 = unset)
    max_iters: int = 60
    o: str = ""                     # output cluster file
    verbose: bool = False


def main(argv=None):
    from combblas_tpu.utils.config import parse_cli
    cfg = parse_cli(Config, argv, prog="mcl")

    import jax.numpy as jnp
    import numpy as np
    from combblas_tpu.apps import load_graph
    from combblas_tpu.models import mcl as M
    from combblas_tpu.ops import semiring as S
    from combblas_tpu.parallel.grid import ProcGrid

    grid = ProcGrid.make()
    labels_txt = None
    if cfg.labeled:
        from combblas_tpu.io import mmio
        a, labels_txt = mmio.read_labeled_tuples(S.PLUS, grid, cfg.labeled)
    else:
        a = load_graph(grid, mtx=cfg.mtx, scale=cfg.scale,
                       edgefactor=cfg.edgefactor, seed=cfg.seed,
                       add=S.PLUS, dtype=jnp.float32,
                       symmetrize=not cfg.mtx)
    params = M.MclParams(
        inflation=cfg.inflation, prune_threshold=cfg.prune_threshold,
        select=cfg.select, recover_num=cfg.recover_num,
        recover_pct=cfg.recover_pct,
        phases=cfg.phases or None,
        per_process_mem_gb=cfg.per_process_mem_gb or None,
        max_iters=cfg.max_iters)
    labels, ncl, iters = M.mcl(a, params, verbose=cfg.verbose)
    lg = np.asarray(labels.to_global())
    if cfg.o:
        # one cluster per line (≅ WriteMCLClusters.h output format);
        # one argsort + split, not a per-cluster scan
        order = np.argsort(lg, kind="stable")
        bounds = np.searchsorted(lg[order], np.arange(1, ncl))
        with open(cfg.o, "w") as f:
            for members in np.split(order, bounds):
                names = (members if labels_txt is None
                         else [labels_txt[int(m)] for m in members])
                f.write(" ".join(str(x) for x in names) + "\n")
    print(json.dumps({"n": a.nrows, "clusters": ncl, "iterations": iters}))


if __name__ == "__main__":
    main()
