"""Betweenness-centrality driver (≅ BetwCent.cpp main: batched
Brandes over a fraction of sources).

    python -m combblas_tpu.apps.bc --scale 10 --batch-size 16
"""

import dataclasses
import json


@dataclasses.dataclass
class Config:
    scale: int = 10
    edgefactor: int = 8
    seed: int = 1
    batch_size: int = 16
    sample: float = 1.0             # fraction of vertices as sources
    mtx: str = ""
    top: int = 5


def main(argv=None):
    from combblas_tpu.utils.config import parse_cli
    cfg = parse_cli(Config, argv, prog="bc")

    import numpy as np
    from combblas_tpu.apps import load_graph
    from combblas_tpu.models import bc as BC
    from combblas_tpu.parallel.grid import ProcGrid

    grid = ProcGrid.make()
    # BC is defined on the directed graph as given (no symmetrization)
    a = load_graph(grid, mtx=cfg.mtx, scale=cfg.scale,
                   edgefactor=cfg.edgefactor, seed=cfg.seed)
    sources = None
    if cfg.sample < 1.0:
        rng = np.random.default_rng(cfg.seed)
        k = max(1, int(cfg.sample * a.nrows))
        sources = rng.choice(a.nrows, k, replace=False)
    scores = BC.betweenness_centrality(a, batch_size=cfg.batch_size,
                                       sources=sources)
    top = np.argsort(scores)[::-1][:cfg.top]
    print(json.dumps({"n": a.nrows,
                      "top_vertices": [int(v) for v in top],
                      "top_scores": [round(float(scores[v]), 3)
                                     for v in top]}))


if __name__ == "__main__":
    main()
