"""Runnable application drivers (≅ the reference's Applications/ CLI
executables): `python -m combblas_tpu.apps.<name> --help`.

Each driver is a thin main() over the models API with a typed config
(utils.config), mirroring how the reference's mains wrap the library.
"""

from __future__ import annotations


def load_graph(grid, *, mtx: str = "", scale: int = 10,
               edgefactor: int = 8, seed: int = 1, add=None,
               dtype=None, symmetrize: bool = False):
    """Shared driver-side graph construction: a Matrix Market file or
    an R-MAT generation, optionally symmetrized (BFS/CC need the
    undirected orientation; a 'general' mtx is completed A|A^T exactly
    like the reference mains symmetricize their inputs)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from combblas_tpu.ops import generate, semiring as S
    from combblas_tpu.parallel import distmat as dm

    add = add if add is not None else S.LOR
    dtype = dtype if dtype is not None else jnp.bool_
    if mtx:
        from combblas_tpu.io import mmio
        rows, cols, vals, h = mmio.read_mm_coo(mtx)
        already_sym = h.symmetric or h.skew or h.hermitian
        if symmetrize and not already_sym:
            off = rows != cols
            r0, c0 = rows, cols
            rows = np.concatenate([r0, c0[off]])
            cols = np.concatenate([c0, r0[off]])
            vals = np.concatenate([vals, vals[off]])
        return dm.from_global_coo(
            add, grid, rows, cols, jnp.asarray(vals.astype(dtype)),
            h.nrows, h.ncols)
    n = 1 << scale
    r, c = generate.rmat_edges(jax.random.key(seed), scale, edgefactor)
    if symmetrize:
        r, c = generate.symmetrize(r, c)
    return dm.from_global_coo(add, grid, r, c,
                              jnp.ones_like(r, dtype), n, n)
