"""Graph500 BFS driver (≅ TopDownBFS.cpp / DirOptBFS.cpp mains):
generate R-MAT (or read a file), run BFS from random roots, print the
Graph500 statistics line.

    python -m combblas_tpu.apps.bfs --scale 16 --nroots 8
    python -m combblas_tpu.apps.bfs --mtx graph.mtx --nroots 4
"""

import dataclasses
import json

from combblas_tpu.utils.config import BfsConfig


@dataclasses.dataclass
class Config(BfsConfig):
    """BfsConfig (scale/edgefactor/nroots/seed/alpha/validate_roots/
    verbose) plus file input. Defaults are interactive-friendly —
    the bench harness (bench.py) owns the scale-22/64-root config."""
    scale: int = 16
    nroots: int = 8
    mtx: str = ""                   # read this file instead of generating


def main(argv=None):
    from combblas_tpu.utils.config import parse_cli
    cfg = parse_cli(Config, argv, prog="bfs")

    import jax
    import jax.numpy as jnp
    import numpy as np
    from combblas_tpu.apps import load_graph
    from combblas_tpu.models import bfs as B
    from combblas_tpu.parallel import algebra as alg
    from combblas_tpu.parallel import distvec as dv
    from combblas_tpu.ops import semiring as S
    from combblas_tpu.parallel.grid import ProcGrid

    grid = ProcGrid.make()
    if cfg.mtx:
        # BFS needs the undirected (symmetrized) orientation; a
        # 'general' file is completed A|A^T like the reference mains
        a = load_graph(grid, mtx=cfg.mtx, symmetrize=True)
        plan = B.plan_bfs(a)
        # degree-filtered random roots (the SelectCandidates pattern)
        deg = alg.reduce(S.PLUS, a.astype(jnp.int32), "row")
        roots = dv.select_candidates(jax.random.key(cfg.seed), deg,
                                     cfg.nroots)
        if len(roots) == 0:
            raise SystemExit("graph has no edges")
        import time
        # untimed warm-up compile (the reference's untimed iteration 0)
        B.bfs(a, jnp.int32(roots[0]), plan,
              alpha=cfg.alpha).data.block_until_ready()
        teps = []
        for root in roots:
            t0 = time.perf_counter()
            parents = B.bfs(a, jnp.int32(root), plan, alpha=cfg.alpha)
            parents.data.block_until_ready()
            dt = time.perf_counter() - t0
            visited = int((parents.to_global() >= 0).sum())
            teps.append(visited / dt)
            if cfg.verbose:
                print(f"root {root}: {visited} visited, {dt * 1e3:.1f} ms")
        print(json.dumps({"median_vertices_per_s":
                          float(np.median(teps))}))
        return
    stats = B.graph500_run(grid, scale=cfg.scale,
                           edgefactor=cfg.edgefactor, nroots=cfg.nroots,
                           seed=cfg.seed, alpha=cfg.alpha,
                           validate_roots=cfg.validate_roots,
                           verbose=cfg.verbose)
    print(json.dumps(stats.summary()))


if __name__ == "__main__":
    main()
