"""Connected-components driver (≅ FastSV.cpp / CC.cpp mains).

    python -m combblas_tpu.apps.cc --scale 14
    python -m combblas_tpu.apps.cc --mtx graph.mtx --algo lacc
"""

import dataclasses
import json


@dataclasses.dataclass
class Config:
    scale: int = 14
    edgefactor: int = 16
    seed: int = 1
    algo: str = "fastsv"            # fastsv | lacc
    mtx: str = ""


def main(argv=None):
    from combblas_tpu.utils.config import parse_cli
    cfg = parse_cli(Config, argv, prog="cc")

    import numpy as np
    from combblas_tpu.apps import load_graph
    from combblas_tpu.models import cc as CC
    from combblas_tpu.parallel.grid import ProcGrid

    grid = ProcGrid.make()
    # CC requires the symmetric orientation regardless of input source
    a = load_graph(grid, mtx=cfg.mtx, scale=cfg.scale,
                   edgefactor=cfg.edgefactor, seed=cfg.seed,
                   symmetrize=True)
    algo = CC.fastsv if cfg.algo == "fastsv" else CC.lacc
    labels, ncomp = CC.label_cc(algo(a))
    lg = labels.to_global()
    sizes = np.bincount(lg)
    print(json.dumps({"n": a.nrows, "nnz": a.getnnz(),
                      "components": ncomp,
                      "largest": int(sizes.max()),
                      "algo": cfg.algo}))


if __name__ == "__main__":
    main()
