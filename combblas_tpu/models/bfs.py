"""Graph500 breadth-first search.

Capability parity: Applications/TopDownBFS.cpp — generate→symmetricize→
per-root loop of { setNumToInd; SpMV with SelectMax semiring;
EWiseMult(fringe, parents, exclude); parents.Set } (:437-442), plus the
tree validation and TEPS statistics (:452-524).

TPU-native re-design: the whole per-root BFS is ONE jitted
`lax.while_loop` with zero host round-trips (the BASELINE.json north
star). The fringe is a masked dense vector (distvec design note), so
`setNumToInd` is an iota, `EWiseMult(..., exclude)` is a mask-and, and
`parents.Set` is a `where`. The SpMV fan-in/fan-out runs on mesh
collectives via parallel.spmv.spmsv.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from combblas_tpu.ops import generate
from combblas_tpu.ops import semiring as S
from combblas_tpu.ops import tile as tl
from combblas_tpu.parallel import distmat as dm
from combblas_tpu.parallel import distvec as dv
from combblas_tpu.parallel import spmv as pspmv
from combblas_tpu.parallel.grid import ProcGrid, ROW_AXIS, COL_AXIS

# NB: python ints, NOT jnp scalars — a committed device array captured in
# a jit closure forces a per-call constant re-upload on remote-TPU
# backends (~400ms/call); see .claude/skills/verify/SKILL.md.
NO_PARENT = -1
_IDENT = jnp.iinfo(jnp.int32).min  # add-identity of the Max monoid


@partial(jax.jit, static_argnames=())
def bfs(a: dm.DistSpMat, root) -> dv.DistVec:
    """Top-down BFS; returns the parents vector (r-aligned, int32).

    ``a`` must hold the *incoming*-edge orientation (a[i, j] nonzero
    means edge j→i reaches i) — symmetric Graph500 graphs satisfy this
    trivially; otherwise pass `distmat.transpose(a)` (the reference's
    OptimizeForGraph500 does the same transpose once, SpParMat.cpp:3285).
    """
    n = a.nrows
    grid = a.grid
    root = jnp.asarray(root, jnp.int32)

    parents0 = jnp.full((grid.pr, a.tile_m), NO_PARENT, jnp.int32)
    parents0 = parents0.at[root // a.tile_m, root % a.tile_m].set(root)
    # fringe activity, column-aligned
    act0 = jnp.zeros((grid.pc, a.tile_n), bool)
    act0 = act0.at[root // a.tile_n, root % a.tile_n].set(True)

    # x values = own global vertex id (≅ fringe.setNumToInd());
    # computed inline (trace-time), never closed-over device data
    xval = (jnp.arange(grid.pc, dtype=jnp.int32)[:, None] * a.tile_n
            + jnp.arange(a.tile_n, dtype=jnp.int32)[None, :])

    def cond(carry):
        _, _, cont = carry
        return cont

    def body(carry):
        parents, act_c, _ = carry
        fringe = dv.DistSpVec(xval, act_c, grid, COL_AXIS, n)
        y = pspmv.spmsv(S.SELECT2ND_MAX_I32, a, fringe)
        fresh = y.active & (parents == NO_PARENT)
        parents = jnp.where(fresh, y.data, parents)
        new_r = dv.DistVec(fresh, grid, ROW_AXIS, n)
        act_c = dv.realign(new_r, COL_AXIS, block=a.tile_n,
                           fill=False).data
        return parents, act_c, jnp.any(fresh)

    parents, _, _ = lax.while_loop(cond, body, (parents0, act0, jnp.bool_(True)))
    return dv.DistVec(parents, grid, ROW_AXIS, n)


# ---------------------------------------------------------------------------
# Validation + statistics (≅ TopDownBFS.cpp:452-524)
# ---------------------------------------------------------------------------

def validate_bfs(edges_r: np.ndarray, edges_c: np.ndarray, n: int,
                 root: int, parents: np.ndarray) -> dict:
    """Graph500-style host-side spec check of a parents array:
    (1) parents[root] == root; (2) every tree edge (parent[v], v) is a
    graph edge; (3) tree levels differ by exactly 1 along tree edges;
    (4) exactly the root's connected component is visited."""
    assert parents[root] == root, "root not its own parent"
    visited = parents >= 0
    # component via union-find on host
    import scipy.sparse as sp
    import scipy.sparse.csgraph as csg
    g = sp.coo_matrix((np.ones(len(edges_r)), (edges_r, edges_c)),
                      shape=(n, n)).tocsr()
    ncomp, labels = csg.connected_components(g, directed=False)
    comp_mask = labels == labels[root]
    assert (visited == comp_mask).all(), "visited set != root's component"
    # levels by parent-chasing
    level = np.full(n, -1, np.int64)
    level[root] = 0
    frontier = [root]
    children = {}
    for v in np.nonzero(visited)[0]:
        if v != root:
            children.setdefault(parents[v], []).append(v)
    depth = 0
    while frontier:
        depth += 1
        nxt = []
        for u in frontier:
            for v in children.get(u, ()):  # tree edges
                level[v] = level[u] + 1
                nxt.append(v)
        frontier = nxt
    assert (level[visited] >= 0).all(), "parent pointers contain a cycle"
    # every tree edge must exist in the graph
    tv = np.nonzero(visited & (np.arange(n) != root))[0]
    tp = parents[tv]
    has_edge = np.asarray(g[tp, tv]).ravel() != 0
    has_edge |= np.asarray(g[tv, tp]).ravel() != 0
    assert has_edge.all(), "tree edge not in graph"
    nedges = int(comp_mask[edges_r].sum() // 2)  # sym edge list counted once
    return {"visited": int(visited.sum()), "depth": int(level.max()),
            "nedges": nedges}


@dataclasses.dataclass
class BfsRunStats:
    teps: list
    times: list
    visited: list

    def summary(self) -> dict:
        teps = np.asarray(self.teps)
        return {
            "min_teps": float(teps.min()),
            "median_teps": float(np.median(teps)),
            "max_teps": float(teps.max()),
            "harmonic_mean_teps": float(1.0 / np.mean(1.0 / teps)),
            "mean_time": float(np.mean(self.times)),
        }


def graph500_run(grid: ProcGrid, scale: int, edgefactor: int = 16,
                 nroots: int = 16, seed: int = 1, cap_slack: float = 1.15,
                 validate: bool = False, verbose: bool = False) -> BfsRunStats:
    """End-to-end Graph500 kernel-2 harness: generate R-MAT, build the
    symmetric adjacency matrix, run BFS from random roots, report TEPS
    (edges in the traversed component / time, per the reference's
    counting recipe — BASELINE.md notes)."""
    import time

    key = jax.random.key(seed)
    kgen, kroots = jax.random.split(key)
    n = 1 << scale
    r, c = generate.rmat_edges(kgen, scale, edgefactor)
    r, c = generate.symmetrize(r, c)
    # initial cap is a guess from the average tile; from_global_coo
    # detects overflow against the true per-tile counts and re-plans
    # with an exact cap (no silent edge dropping under R-MAT skew)
    a = dm.from_global_coo(S.LOR, grid, r, c,
                           jnp.ones_like(r, jnp.bool_), n, n,
                           cap=int(cap_slack * (r.shape[0] //
                                                (grid.pr * grid.pc))))
    jax.block_until_ready(a.rows)
    if verbose:
        a.print_info("A")

    # degrees for root selection (roots must have degree > 0)
    deg = np.zeros(n, np.int64)
    np.add.at(deg, np.asarray(r), 1)
    candidates = np.nonzero(deg > 0)[0]
    roots = np.asarray(jax.random.choice(
        kroots, jnp.asarray(candidates), (nroots,), replace=False))

    er = ec = None
    if validate:
        er, ec = np.asarray(r), np.asarray(c)

    stats = BfsRunStats([], [], [])
    # warm-up compile (not timed, like the reference's untimed iteration 0)
    bfs(a, jnp.int32(roots[0])).data.block_until_ready()
    for root in roots:
        t0 = time.perf_counter()
        parents = bfs(a, jnp.int32(root))
        parents.data.block_until_ready()
        dt = time.perf_counter() - t0
        pg = parents.to_global()
        visited = int((pg >= 0).sum())
        if validate:
            info = validate_bfs(er, ec, n, int(root), pg)
            nedges = info["nedges"]
        else:
            nedges = int(deg[pg >= 0].sum() // 2)
        stats.teps.append(nedges / dt)
        stats.times.append(dt)
        stats.visited.append(visited)
        if verbose:
            print(f"root {int(root)}: {visited} visited, "
                  f"{nedges} edges, {dt*1e3:.1f} ms, "
                  f"{nedges/dt/1e6:.1f} MTEPS")
    return stats
