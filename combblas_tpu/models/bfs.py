"""Graph500 breadth-first search, direction-optimizing.

Capability parity: Applications/TopDownBFS.cpp (generate→symmetricize→
per-root loop of { setNumToInd; SpMV with SelectMax semiring;
EWiseMult(fringe, parents, exclude); parents.Set } :437-442) and
DirOptBFS.cpp (the top-down/bottom-up switch :386-409 with the
BitMapCarousel bottom-up step BFSFriends.h:458), plus tree validation
and TEPS statistics (TopDownBFS.cpp:452-524).

TPU-native re-design. The whole per-root BFS is ONE jitted
`lax.while_loop` with zero host round-trips. Each level picks a
stepper via `lax.switch` over the sparse budget tiers plus the dense
fallback (the direction-optimizing switch):

* **dense step** (heavy levels; plays the role of the reference's
  bottom-up scan): one full pass over the tile's sorted edges — gather
  frontier bits at the source columns, contribute the *global column
  id* where active (the index-as-value trick of ParFriends.h:1370: a
  boolean matrix never materializes values), reduce per destination row
  with the scatter-free segmented-scan kernel (tile.seg_reduce_sorted).
  Cost: O(nnz) fully-vectorized VPU work, no scatter.

* **sparse step** (light levels; work-proportional top-down push):
  compact the frontier into an index list (static cap F), expand their
  adjacency ranges from the column-sorted structure (static budget E
  slots), and scatter-max parent ids into the fresh vector. The only
  scatter in the program, sized E ≪ nnz.

The switch predicate is exact-safe: the sparse step is chosen only
when its static caps provably fit (per-tile frontier degree ≤ E,
frontier size ≤ F) *and* the Beamer-style heuristic favors it
(frontier degree · alpha < nnz, ≅ DirOptBFS.cpp:386-409).
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from combblas_tpu import obs
from combblas_tpu.obs import metrics as obm
from combblas_tpu.ops import bitseg as bs
from combblas_tpu.ops import generate
from combblas_tpu.ops import route as rt
from combblas_tpu.ops import semiring as S
from combblas_tpu.ops import tile as tl
from combblas_tpu.parallel import distmat as dm
from combblas_tpu.parallel import distvec as dv
from combblas_tpu.parallel.grid import ProcGrid, ROW_AXIS, COL_AXIS

# NB: python ints, NOT jnp scalars — a committed device array captured in
# a jit closure forces a per-call constant re-upload on remote-TPU
# backends (~400ms/call); see .claude/skills/verify/SKILL.md.
NO_PARENT = -1
_IDENT = jnp.iinfo(jnp.int32).min  # add-identity of the Max monoid
_SAT = 2**30 - 1


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BfsPlan:
    """Level-invariant traversal metadata, computed once per matrix
    (≅ OptimizeForGraph500, SpParMat.cpp:3285). All arrays stacked
    (pr, pc, ·) and sharded like the matrix. The dense-step arrays are
    stored in the chunk-column layout (tile.to_chunked, flattened) so
    no per-level transpose is needed."""

    cols_t: jax.Array     # (pr, pc, capp) int32 — cols, chunked layout
    starts_t: jax.Array   # (pr, pc, capp) bool — row-run starts, chunked
    valid_t: jax.Array    # (pr, pc, capp) bool — live-entry mask, chunked
    ends_m: jax.Array     # (pr, pc, tile_m) int32 — row-end offsets, mapped
    nonempty: jax.Array   # (pr, pc, tile_m) bool
    crows: jax.Array      # (pr, pc, cap) int32 — rows sorted by column
    ccols: jax.Array      # (pr, pc, cap) int32 — cols sorted by column
    cstarts: jax.Array    # (pr, pc, tile_n+1) int32 — CSC pointers
    cdeg: jax.Array       # (pr, pc, tile_n) int32 — per-column degree
    crun_t: jax.Array     # (pr, pc, capp) bool — column-run starts, chunked
    c2r: jax.Array        # (pr, pc, cap) int32 — col-order -> row-order key
    # Beneš route masks for the static col->row edge permutation
    # (ops/route.py): (pr, pc, nstages, npad/32) uint32, or None when
    # the plan was built without routing (the dense stepper then falls
    # back to the permute-by-sort path). Built host-side by plan_bfs
    # once per matrix — the untimed Graph500 kernel-1 analogue of
    # OptimizeForGraph500 (SpParMat.cpp:3285).
    route_masks: jax.Array | None = None
    # packed-bit row structure for the edge-space BFS (bfs_bits):
    # (pr, pc, npad/32) uint32 — row-run start bits and live-slot bits
    # in FLAT row-sorted edge order; rstarts: (pr, pc, tile_m+1) int32
    # flat row-start offsets. Present iff route_masks is.
    starts_bits: jax.Array | None = None
    valid_bits: jax.Array | None = None
    rstarts: jax.Array | None = None
    # packed col-run start bits in COLUMN-sorted edge order (for the
    # mesh bit BFS's vertex->edge frontier expansion; valid_bits covers
    # both orders since padding sorts last either way)
    cstart_bits: jax.Array | None = None
    # gather-free parent extraction (single-tile bfs_bits): column-id
    # bitplanes (pr, pc, nbits, npad/32); start-compact route masks
    # (slot rstarts[r] -> r; same storage convention as route_masks);
    # packed nonempty-row bits (pr, pc, ceil(tile_m/32))
    colbits: jax.Array | None = None
    srt_masks: jax.Array | None = None
    rnon_bits: jax.Array | None = None
    # consistency token: the source matrix's static signature. A plan is
    # valid ONLY for the exact matrix it was built from (same tiles, same
    # nnz, same entry order); `bfs` asserts the static part at trace time.
    sig: tuple = dataclasses.field(default=(), metadata=dict(static=True))
    # pattern-symmetry, verified on device at plan time (route=True,
    # single tile): bfs_bits' col-order==row-order bit identity holds
    # ONLY for symmetric matrices, so it refuses to run without this
    # flag (advisor round-3: symmetry was docstring-only before)
    symmetric: bool = dataclasses.field(default=False,
                                        metadata=dict(static=True))
    # whether route_masks are stored 2:1-packed (route.compact_masks);
    # npad is then words*64, not *32. Mask tensors are stored PRE-TILED
    # — (pr, pc, nstages, words/128, 128) — whenever words % 128 == 0:
    # the flat->tiled reshape is a full relayout copy on TPU (~424 MB
    # of mask traffic at scale 22), and storing flat made every root's
    # traversal re-pay it (ADVICE r4). `_mask_words` abstracts the two
    # layouts.
    route_compact: bool = dataclasses.field(default=False,
                                            metadata=dict(static=True))

    @property
    def chunk_len(self) -> int:
        return self.cols_t.shape[-1] // 128


def _mask_words(masks: jax.Array) -> int:
    """uint32 word count of one stored mask row, for either layout:
    (pr, pc, nstages, words) flat or (pr, pc, nstages, words/128, 128)
    pre-tiled (see BfsPlan.route_compact note)."""
    if masks.ndim == 5:
        return masks.shape[-2] * masks.shape[-1]
    return masks.shape[-1]


@jax.jit
def _plan_bfs_core(a: dm.DistSpMat) -> BfsPlan:
    pr, pc, cap = a.grid.pr, a.grid.pc, a.cap

    def one(rows, cols, vals, nnz):
        t = tl.Tile(rows, cols, vals, nnz, a.tile_m, a.tile_n)
        starts, ends, nonempty = tl.row_structure(t)
        valid = t.valid()
        cols_t = tl.to_chunked(cols, fill=a.tile_n).reshape(-1)
        starts_t = tl.to_chunked(starts, fill=True).reshape(-1)
        valid_t = tl.to_chunked(valid, fill=False).reshape(-1)
        ends_m = tl.chunked_pos(jnp.clip(ends, 0, cap - 1), cap)
        crows, ccols, cstarts, cdeg, corder = tl.col_structure(t)
        prevc = jnp.concatenate([jnp.full((1,), -1, jnp.int32), ccols[:-1]])
        crun_t = tl.to_chunked(ccols != prevc, fill=True).reshape(-1)
        return (cols_t, starts_t, valid_t, ends_m, nonempty,
                crows, ccols, cstarts, cdeg, crun_t, corder)

    out = jax.vmap(one)(a.rows.reshape(-1, cap), a.cols.reshape(-1, cap),
                        a.vals.reshape(-1, cap), a.nnz.reshape(-1))
    shard = a.grid.sharding(ROW_AXIS, COL_AXIS, None)
    fields = [lax.with_sharding_constraint(x.reshape(pr, pc, -1), shard)
              for x in out]
    return BfsPlan(*fields, sig=(pr, pc, cap, a.tile_m, a.tile_n))


def plan_bfs(a: dm.DistSpMat, route: bool | str = False,
             route_budget_s: float = 900.0) -> BfsPlan:
    """Build the BFS traversal plan (device part jitted).

    ``route=True`` additionally compiles the static col->row edge
    permutation of every tile into Beneš swap masks (ops/route.py) so
    the dense stepper routes frontier bits with word-parallel
    delta-swaps instead of a per-level O(cap) int32 sort.  The mask
    computation is host-side O(cap log cap) per tile — one-off per
    matrix, amortized over roots (Graph500 kernel-1 is untimed).
    ``route="auto"`` enables it only when the estimated planning time
    fits ``route_budget_s`` (calibrated ~60ns per slot-depth on one
    host core)."""
    # plan time is the one place the host already knows nnz: register
    # the nnz-proportional roofline costs of every bfs.*/spmv.* ledger
    # name so traversal dispatch walls grade against expected work
    obs.costmodel.annotate_matrix(a)
    if not isinstance(a.nnz, jax.core.Tracer):  # mesh obs: eager plans only
        annz = np.asarray(a.nnz)  # analysis: allow(sync-in-async) plan-time, once per matrix
        for nm in ("bfs.bits_mesh", "bfs.batch_bits_mesh"):
            obs.meshobs.register_device_loads(nm, nnz=annz)
        _register_bits_mesh_collectives(a, "bfs.bits_mesh", 1)
    plan = _plan_bfs_core(a)
    if not route:
        return plan
    pr, pc, cap = a.grid.pr, a.grid.pc, a.cap
    npad = 1 << max(5, (cap - 1).bit_length())
    if route == "auto":
        # Planning cost model: ~60ns/slot-depth mask computation on one
        # host core (native router; the pure-Python fallback is ~3
        # orders slower, so auto requires the native library), plus the
        # host<->device transfers — c2r down, masks up — at a
        # pessimistic 5 MB/s (remote-TPU tunnels are slow; local
        # devices only finish sooner than estimated).
        nstages = 2 * (npad.bit_length() - 1) - 1
        est = 60e-9 * npad * npad.bit_length() * pr * pc
        est += (cap * 4 + nstages * npad // 8) * pr * pc / 5e6
        if est > route_budget_s or rt._load() is None:
            return plan
    compact = npad >= rt._COMPACT_MIN_NPAD
    c2r = np.asarray(plan.c2r)  # (pr, pc, cap) # analysis: allow(sync-in-async) plan-time, once per matrix
    tiles = []
    for i in range(pr):
        for j in range(pc):
            tiles.append(_cached_route_masks(c2r[i, j], compact))
    npad_r = rt.mask_npad(tiles[0].shape[-1], compact)
    masks = rt.tile_masks_batched(np.stack(tiles).reshape(
        pr, pc, *tiles[0].shape))
    # device_put straight from numpy: resharding an already-committed
    # array would stage the full mask tensor on one device first — an
    # HBM spike at exactly the scales routing is for
    masks = jax.device_put(
        masks, a.grid.sharding(ROW_AXIS, COL_AXIS,
                               *([None] * (masks.ndim - 2))))
    sb, vb, rs = _bit_structure(a, npad_r)
    cb = _col_bit_structure(plan.ccols, a.nnz, a.grid, npad_r)
    sym = False
    if pr == 1 and pc == 1 and a.tile_m == a.tile_n:
        sym = bool(np.asarray(_pattern_symmetric(  # analysis: allow(sync-in-async) plan-time, once per matrix
            a.rows[0, 0], a.cols[0, 0], a.nnz[0, 0], a.tile_m)))
    plan = dataclasses.replace(plan, route_masks=masks, starts_bits=sb,
                               valid_bits=vb, rstarts=rs, cstart_bits=cb,
                               symmetric=sym, route_compact=compact)
    if pr == 1 and pc == 1:
        plan = _plan_parent_extract(a, plan, npad_r, compact)
    return plan


def _plan_parent_extract(a: dm.DistSpMat, plan: BfsPlan, npad: int,
                         compact: bool) -> BfsPlan:
    """Single-tile gather-free parent extraction structures:
    column-id bitplanes of the row-sorted edge order, the
    start-compact Beneš route (slot rstarts[r] -> r), and the
    packed nonempty-row mask. Per-row gathers measured ~73 ms for 4M
    rows at scale 22 — these turn the extraction into streamed bit
    kernels + one more static route."""
    tile_m = a.tile_m
    if tile_m > npad:
        # the start-compact permutation maps slot rstarts[r] -> r and
        # needs every row index to be a valid slot; a matrix with more
        # rows than padded edge slots keeps the gather extraction
        return plan
    nbits = max(1, (a.tile_n - 1).bit_length())
    cols = a.cols[0, 0]
    colbits = jnp.stack([
        rt.pack_bits(((cols >> b) & 1).astype(jnp.int8), npad)
        for b in range(nbits)])
    rstarts = np.asarray(plan.rstarts[0, 0])  # analysis: allow(sync-in-async) plan-time, once per matrix
    nonempty = rstarts[1:] > rstarts[:-1]
    rows_ne = np.flatnonzero(nonempty).astype(np.int32)
    src = rstarts[:-1][nonempty].astype(np.int32)
    perm = np.full(npad, -1, np.int32)
    perm[src] = rows_ne
    # filler destinations = row ids NOT already used, via a boolean
    # occupancy mask + chunked int32 flatnonzero — the int64 arange +
    # setdiff1d sort this replaces was ~12 GB of transient host memory
    # at scale 24 (npad = 2^29), undermining the chunked-ingestion
    # memory story (ADVICE r4)
    occupied = np.zeros(npad, bool)
    occupied[rows_ne] = True
    free_dst = np.empty(npad - len(rows_ne), np.int32)
    o = 0
    ch = 1 << 24
    for s in range(0, npad, ch):
        f = np.flatnonzero(~occupied[s:s + ch])
        free_dst[o:o + f.size] = (f + s).astype(np.int32)
        o += f.size
    del occupied
    perm[perm < 0] = free_dst
    del free_dst
    srt = rt.tile_masks_batched(_cached_route_masks(perm, compact))
    nwm = -(-tile_m // 32)
    rnon = np.asarray(rt.pack_bits(jnp.asarray(nonempty.astype(np.int8)),  # analysis: allow(sync-in-async) plan-time, once per matrix
                                   nwm * 32))
    return dataclasses.replace(
        plan,
        colbits=jax.device_put(colbits)[None, None],
        srt_masks=jax.device_put(jnp.asarray(srt))[None, None],
        rnon_bits=jax.device_put(jnp.asarray(rnon))[None, None])


def _cached_route_masks(c2r_tile: np.ndarray,
                        compact: bool = False) -> np.ndarray:
    """plan_route_masks with a host disk cache keyed by the
    permutation's content hash: Beneš planning is minutes of one-core
    work at bench scales, and repeated runs on the same generated
    graph (fixed seed) rebuild the identical permutation.
    COMBBLAS_TPU_ROUTE_CACHE overrides the location; empty disables.
    ``compact`` stores/loads the 2:1-packed form (route.compact_masks)
    under a distinct cache name."""
    import hashlib
    import os
    import pathlib
    import tempfile

    def _plan():
        masks, _, npad = rt.plan_route_masks(c2r_tile)
        return rt.compact_masks(masks, npad) if compact else masks

    # default to a user-owned location (XDG cache, else a uid-suffixed
    # tempdir created 0700): a world-writable shared default would let
    # another user pre-plant mask files that silently corrupt routing
    # (advisor round-3 finding)
    cdir = os.environ.get("COMBBLAS_TPU_ROUTE_CACHE")  # analysis: allow(env-in-trace) host cache location, never affects traced values
    explicit = cdir is not None
    if cdir is None:
        xdg = os.environ.get("XDG_CACHE_HOME",  # analysis: allow(env-in-trace) host cache location, never affects traced values
                             os.path.expanduser("~/.cache"))
        if xdg and not xdg.startswith("~"):
            cdir = os.path.join(xdg, "combblas_tpu", "route")
        else:
            cdir = os.path.join(tempfile.gettempdir(),
                                f"combblas_route_cache_{os.getuid()}")
    if not cdir:
        return _plan()
    key = hashlib.sha1(np.ascontiguousarray(c2r_tile).view(
        np.uint8)).hexdigest()[:20]
    root = pathlib.Path(cdir)
    suff = "_c1" if compact else ""
    path = root / f"benes_{key}_{len(c2r_tile)}{suff}.npy"
    try:
        root.mkdir(parents=True, exist_ok=True, mode=0o700)
        if not explicit and os.stat(root).st_uid != os.getuid():
            # implicit default pre-created by another user: don't trust
            # it (an explicitly configured shared cache is the
            # operator's own call)
            return _plan()
    except Exception:
        return _plan()
    if path.exists():
        try:
            return np.load(path)
        except Exception:
            pass                       # corrupt cache entry: recompute
    masks = _plan()
    try:
        tmp = path.with_name(f"{path.stem}.{os.getpid()}.npy")
        np.save(tmp, masks)
        tmp.replace(path)
    except Exception:
        pass                           # cache is best-effort only
    return masks


@partial(jax.jit, static_argnames=("n",))
def _pattern_symmetric(rows, cols, nnz, n) -> jax.Array:
    """Whether a square tile's sparsity pattern equals its transpose's
    (one sort + compare; sentinels match because the tile is square)."""
    v = jnp.arange(rows.shape[0], dtype=jnp.int32) < nnz
    r2 = jnp.where(v, cols, n)
    c2 = jnp.where(v, rows, n)
    r2, c2 = lax.sort((r2, c2), num_keys=2)
    return jnp.all((r2 == rows) & (c2 == cols))


@partial(jax.jit, static_argnames=("npad",))
def _bit_structure(a: dm.DistSpMat, npad: int):
    """Packed row-run structure for the edge-space BFS: per tile, the
    FLAT row-order bit vectors (row-run starts, live slots) and the
    flat row-start offsets."""
    cap, tile_m = a.cap, a.tile_m

    def one(rows, nnz):
        k = jnp.arange(cap, dtype=jnp.int32)
        valid = k < nnz
        prev = jnp.concatenate([jnp.full((1,), -1, jnp.int32), rows[:-1]])
        starts = valid & ((k == 0) | (rows != prev))
        rows_sane = jnp.where(valid, rows, tile_m)
        rstarts = jnp.searchsorted(
            rows_sane, jnp.arange(tile_m + 1, dtype=jnp.int32)
        ).astype(jnp.int32)
        return (rt.pack_bits(starts, npad), rt.pack_bits(valid, npad),
                rstarts)

    pr, pc = a.grid.pr, a.grid.pc
    sb, vb, rs = jax.vmap(one)(a.rows.reshape(-1, cap),
                               a.nnz.reshape(-1))
    shard = a.grid.sharding(ROW_AXIS, COL_AXIS, None)
    return (lax.with_sharding_constraint(sb.reshape(pr, pc, -1), shard),
            lax.with_sharding_constraint(vb.reshape(pr, pc, -1), shard),
            lax.with_sharding_constraint(rs.reshape(pr, pc, -1), shard))


@partial(jax.jit, static_argnames=("npad", "grid"))
def _col_bit_structure(ccols: jax.Array, nnz: jax.Array, grid: ProcGrid,
                       npad: int) -> jax.Array:
    """Packed column-run start bits in column-sorted edge order (the
    col-side twin of _bit_structure's starts_bits)."""
    cap = ccols.shape[-1]

    def one(cc, nz):
        k = jnp.arange(cap, dtype=jnp.int32)
        valid = k < nz
        prev = jnp.concatenate([jnp.full((1,), -1, jnp.int32), cc[:-1]])
        return rt.pack_bits(valid & ((k == 0) | (cc != prev)), npad)

    cb = jax.vmap(one)(ccols.reshape(-1, cap), nnz.reshape(-1))
    return lax.with_sharding_constraint(
        cb.reshape(grid.pr, grid.pc, -1),
        grid.sharding(ROW_AXIS, COL_AXIS, None))


def _caps(a: dm.DistSpMat) -> list[tuple[int, int]]:
    """Static (E, F) budget tiers for the sparse stepper, smallest
    first. Static shapes mean a sparse level pays its whole tier's
    gather cost even for a tiny frontier, so several tiers keep light
    levels cheap. Budgets are ABSOLUTE, not cap-fractions: the sparse
    stepper's cost is ~4 serialized accesses per slot (~65ns/slot
    measured on v5e), so above ~256K slots the dense full scan wins
    regardless of matrix size — a cap-relative tier on a single-chip
    scale-22 tile would cost more than the scan it bypasses. Frontiers
    too heavy for the largest tier take the dense stepper."""
    tiers = []
    for e_abs in (4096, 32768, 262144):
        e_cap = max(1024, min(e_abs, (a.cap // 8 // 128) * 128))
        f_cap = max(128, min(a.tile_n, e_cap))
        tiers.append((e_cap, f_cap))
    return tiers


@partial(jax.jit, static_argnames=("alpha",))
def bfs(a: dm.DistSpMat, root, plan: BfsPlan | None = None,
        alpha: int = 8) -> dv.DistVec:
    """Direction-optimizing BFS; returns the parents vector (r-aligned).

    ``a`` must hold the *incoming*-edge orientation (a[i, j] nonzero
    means edge j→i reaches i) — symmetric Graph500 graphs satisfy this
    trivially. Pass a precomputed ``plan`` (plan_bfs) when running many
    roots on one matrix; otherwise it is built in-trace.

    INVARIANT: a supplied ``plan`` must have been built by `plan_bfs`
    from this exact ``a`` (same tiles AND same entry content) — a stale
    plan after rebuilding ``a`` silently yields wrong parents. The
    static signature (grid/cap/tile dims) is asserted at trace time;
    the content identity cannot be checked cheaply and is on the caller.
    """
    if plan is None:
        plan = plan_bfs(a)
    elif plan.sig and plan.sig != (a.grid.pr, a.grid.pc, a.cap,
                                   a.tile_m, a.tile_n):
        raise ValueError(
            f"BfsPlan signature {plan.sig} does not match matrix "
            f"{(a.grid.pr, a.grid.pc, a.cap, a.tile_m, a.tile_n)}: the "
            "plan was built for a different matrix (plan_bfs(a) rebuilds)")
    n = a.nrows
    grid = a.grid
    tile_m, tile_n = a.tile_m, a.tile_n
    root = jnp.asarray(root, jnp.int32)
    nnz_total = jnp.sum(a.nnz).astype(jnp.float32)

    parents0 = jnp.full((grid.pr, tile_m), NO_PARENT, jnp.int32)
    parents0 = parents0.at[root // tile_m, root % tile_m].set(root)
    act0 = jnp.zeros((grid.pc, tile_n), bool)
    act0 = act0.at[root // tile_n, root % tile_n].set(True)

    tiers, branches = build_steppers(a, plan)
    return _bfs_loop(plan, grid, tile_n, tiers, branches,
                     parents0, act0, nnz_total, alpha, n)


def build_steppers(a: dm.DistSpMat, plan: BfsPlan):
    """(tiers, steppers): the (E, F) budget list and the level
    steppers built to those budgets — smallest sparse tier first,
    dense full-scan last. Each stepper is a jitted ``act -> y``
    callable (act: (pc, tile_n) c-aligned frontier mask; y:
    (pr, tile_m) r-aligned parent candidates, _IDENT where none).
    Returned together so the switch's fit check and the compiled
    budgets can never desynchronize; exposed so tests can force every
    branch on one frontier and cross-check (the reference's
    SpMSpV-variant consistency checks, SpMSpVBench.cpp:531-539)."""
    grid = a.grid
    mesh = grid.mesh
    tile_m, tile_n, cap = a.tile_m, a.tile_n, a.cap
    if cap > 2 ** 30:
        raise ValueError(
            f"tile cap {cap} > 2^30: the dense stepper packs the "
            "frontier bit into the low bit of an int32 routing key "
            "(c2r << 1); shard the matrix over more devices")
    tiers = _caps(a)

    spec3 = P(ROW_AXIS, COL_AXIS, None)
    spec_act = P(COL_AXIS, None)
    spec_y = P(ROW_AXIS, None)

    capp = plan.cols_t.shape[-1]
    chunk_len = capp // 128

    # ---- dense stepper: full edge scan, gather-free -----------------------
    # Random per-edge gathers cost ~11ns/element on TPU (serialized),
    # so the frontier bits are instead (1) RLE-broadcast over the
    # column-sorted edge order — one tile_n-sized scatter plus a
    # segmented copy-scan, no random access — then (2) routed to row
    # order: through the precompiled Beneš bit network when the plan
    # carries route masks (word-parallel delta-swaps on packed bits,
    # ops/route.py), else by sorting against the static col→row key
    # (~3x cheaper than the equivalent gather, but ~30x the traffic of
    # the bit route), then (3) max-scanned per row.
    use_route = plan.route_masks is not None
    npad = (rt.mask_npad(_mask_words(plan.route_masks), plan.route_compact)
            if use_route else 0)

    def dense_step(act):
        def f(cols_t, starts_t, valid_t, ends_m, nonempty, cstarts, cdeg,
              crun_t, c2r, rmasks, actb):
            cols_t, starts_t = cols_t[0, 0], starts_t[0, 0]
            valid_t, ends_m, nonempty = (valid_t[0, 0], ends_m[0, 0],
                                         nonempty[0, 0])
            cstarts, cdeg = cstarts[0, 0], cdeg[0, 0]
            crun_t, c2r = crun_t[0, 0], c2r[0, 0]
            j = lax.axis_index(COL_AXIS)
            # (1) RLE-broadcast act over column runs
            tgt = jnp.where(cdeg > 0, cstarts[:-1], cap)
            seed = jnp.zeros((cap + 1,), jnp.int8)
            seed = seed.at[tgt].set(actb[0].astype(jnp.int8),
                                    mode="drop")[:cap]
            seed_t = tl.to_chunked(seed, fill=0)
            eact_c = tl.seg_scan_values(
                S.MAX, seed_t, crun_t.reshape(chunk_len, 128))
            # (2) bits from col order to row order
            if use_route:
                rp = rt.RoutePlan(rt.tile_masks(rmasks[0, 0]), cap, npad,
                                  plan.route_compact)
                words = rt.pack_bits(eact_c.T.reshape(-1)[:cap], npad)
                eact_r = rt.unpack_bits(rt.apply_route_best(rp, words), cap)
            else:
                # pack the frontier bit into the low bit of the
                # (distinct) col->row key and sort ONE int32 array —
                # half the sort payload of a (key, value) pair sort.
                # cap <= 2^30 so the shift never overflows.
                packed = (c2r << 1) | eact_c.T.reshape(-1)[:cap].astype(
                    jnp.int32)
                eact_r = (lax.sort(packed) & 1).astype(jnp.int8)
            # (3) per-row max-scan of parent candidates
            eb = tl.to_chunked(eact_r, fill=0).reshape(-1)
            e_act = (eb > 0) & valid_t
            contrib = jnp.where(
                e_act, cols_t + j.astype(jnp.int32) * tile_n, _IDENT)
            y = tl.seg_reduce_pre(S.MAX, contrib.reshape(chunk_len, 128),
                                  starts_t.reshape(chunk_len, 128),
                                  ends_m, nonempty)
            return lax.pmax(y, COL_AXIS)[None]

        rmasks = (plan.route_masks if use_route else
                  jnp.zeros((grid.pr, grid.pc, 1, 1), jnp.uint32))
        rspec = P(ROW_AXIS, COL_AXIS, *([None] * (rmasks.ndim - 2)))
        return jax.shard_map(
            f, mesh=mesh,
            in_specs=(spec3,) * 4 + (spec3, P(ROW_AXIS, COL_AXIS, None),
                                     spec3, spec3, spec3,
                                     rspec,
                                     spec_act),
            out_specs=spec_y,
        )(plan.cols_t, plan.starts_t, plan.valid_t, plan.ends_m,
          plan.nonempty, plan.cstarts, plan.cdeg, plan.crun_t, plan.c2r,
          rmasks, act)

    # ---- sparse stepper: frontier push with bounded scatter ---------------
    # Per expanded slot: 1 gather for the base offset, 2 for the edge
    # (dest row + parent col), 1 scatter-max — ~4 random accesses/slot
    # vs the dense step's 1/edge, so sparse wins when the frontier
    # degree is < nnz/alpha (alpha defaults to 8).
    def make_sparse_step(e_cap, f_cap):
        def sparse_step(act):
            def f(crows, ccols, cstarts, actb):
                crows, ccols, cstarts = crows[0, 0], ccols[0, 0], cstarts[0, 0]
                j = lax.axis_index(COL_AXIS)
                idxs = jnp.nonzero(actb[0], size=f_cap,
                                   fill_value=tile_n)[0].astype(jnp.int32)
                safe = jnp.clip(idxs, 0, tile_n - 1)
                st = cstarts[safe]
                deg = jnp.where(idxs < tile_n, cstarts[safe + 1] - st, 0)
                e_of_slot, offs, total = tl.expand_indices(deg, e_cap)
                slots = jnp.arange(e_cap, dtype=jnp.int32)
                e = jnp.clip(e_of_slot, 0, f_cap - 1)
                live = slots < total
                base = st - offs                  # (f_cap,) fused offset
                pos = jnp.clip(base[e] + slots, 0, cap - 1)
                nbr = crows[pos]                  # destination rows
                par = ccols[pos] + j.astype(jnp.int32) * tile_n
                tgt = jnp.where(live & (nbr < tile_m), nbr, tile_m)
                fresh = jnp.full((tile_m + 1,), _IDENT, jnp.int32)
                fresh = fresh.at[tgt].max(jnp.where(live, par, _IDENT),
                                          mode="drop")
                return lax.pmax(fresh[:tile_m], COL_AXIS)[None]

            return jax.shard_map(
                f, mesh=mesh,
                in_specs=(spec3, spec3, spec3, spec_act),
                out_specs=spec_y,
            )(plan.crows, plan.ccols, plan.cstarts, act)
        return sparse_step

    # jitted so standalone calls (cross-check tests, the SpMSpV bench
    # driver) compile once instead of retracing per call; inside the
    # jitted BFS while_loop the wrapper is transparent
    return tiers, ([jax.jit(make_sparse_step(ec, fc)) for ec, fc in tiers]  # analysis: allow(cache-key-unstable) per-plan steppers, cached in the plan
                   + [jax.jit(dense_step)])  # analysis: allow(cache-key-unstable) per-plan steppers, cached in the plan


def _bfs_loop(plan, grid, tile_n, tiers, branches, parents0,
              act0, nnz_total, alpha, n):
    def cond(carry):
        _, _, cont = carry
        return cont

    def body(carry):
        parents, act, _ = carry
        # direction-optimizing switch (≅ DirOptBFS.cpp:386-409): pick
        # the smallest sparse tier whose static budgets provably fit
        # the frontier (per-tile degree, exact int32) — or the dense
        # full-scan when no tier fits or sparse isn't worth it.
        actdeg = jnp.einsum("ijk,jk->ij", plan.cdeg,
                            act.astype(jnp.int32))
        # the sparse stepper compacts each column *block* separately, so
        # the F-cap constraint is the per-block max active count, not
        # the global frontier size (a wide low-degree frontier spread
        # over pc blocks stays eligible for the sparse tiers)
        nact_blk = jnp.max(jnp.sum(act, axis=1))
        tier_idx = jnp.int32(0)
        for ec, fc in tiers:
            fits = (jnp.max(actdeg) <= ec) & (nact_blk <= fc)
            tier_idx = tier_idx + (~fits).astype(jnp.int32)
        worth = jnp.sum(actdeg).astype(jnp.float32) * alpha < nnz_total
        tier_idx = jnp.where(worth, tier_idx, len(tiers))
        y = lax.switch(tier_idx, branches, act)
        fresh = (y != _IDENT) & (parents == NO_PARENT)
        parents = jnp.where(fresh, y, parents)
        act_c = dv.realign(dv.DistVec(fresh, grid, ROW_AXIS, n), COL_AXIS,
                           block=tile_n, fill=False).data
        return parents, act_c, jnp.any(fresh)

    parents, _, _ = lax.while_loop(cond, body,
                                   (parents0, act0, jnp.bool_(True)))
    return dv.DistVec(parents, grid, ROW_AXIS, n)


# ---------------------------------------------------------------------------
# Batched multi-source BFS (the serve batcher's device kernel)
# ---------------------------------------------------------------------------

@jax.jit
def bfs_batch(a: dm.DistSpMat, roots, max_levels=None, plan=None):
    """W simultaneous BFS traversals in ONE jitted while_loop: the
    frontiers ride the columns of a `DistMultiVec` and every level is
    one select2nd-max SpMM over the plan's precomputed chunked edge
    structure (≅ BetwCent's batch-of-roots framing, BetwCent.cpp:146;
    the tall-and-skinny multiply of arXiv:2408.11988).

    Bit-exact vs per-root `bfs`: per level the dense stepper computes
    y[i] = max over active in-neighbors j of the global column id — and
    the chunked segmented max with x[j, w] = (act ? global col id :
    MAX-identity) is that exact reduction, column-wise. Columns are
    independent, so duplicate roots are just repeated columns.

    ``plan`` (a `BfsPlan`, routed or not) supplies the level-invariant
    row structure so repeated calls never re-derive it per level; when
    None it is built in-trace (`_plan_bfs_core` — one extra device
    pass, amortized away by the serve engine, which passes its cached
    plan).

    ``max_levels`` (dynamic int32, no recompile per value; None/0 =
    unbounded) caps the number of levels — the serve engine's deadline
    degradation: expired requests return the partial parents computed
    so far. Returns (parents r-aligned DistMultiVec, levels run,
    done (W,) bool — False where the traversal was truncated)."""
    from combblas_tpu.parallel import densemat as dmm
    grid = a.grid
    tile_m, tile_n = a.tile_m, a.tile_n
    if plan is None:
        plan = _plan_bfs_core(a)
    elif plan.sig and plan.sig != (grid.pr, grid.pc, a.cap,
                                   a.tile_m, a.tile_n):
        raise ValueError(
            f"BfsPlan signature {plan.sig} does not match matrix "
            f"{(grid.pr, grid.pc, a.cap, a.tile_m, a.tile_n)}: the "
            "plan was built for a different matrix")
    chunk_len = plan.chunk_len
    roots = jnp.asarray(roots, jnp.int32)
    w = roots.shape[0]
    w_ix = jnp.arange(w, dtype=jnp.int32)
    parents0 = jnp.full((grid.pr, tile_m, w), NO_PARENT, jnp.int32)
    parents0 = parents0.at[roots // tile_m, roots % tile_m, w_ix].set(roots)
    act0 = jnp.zeros((grid.pc, tile_n, w), bool)
    act0 = act0.at[roots // tile_n, roots % tile_n, w_ix].set(True)
    if max_levels is None:
        ml = jnp.int32(_SAT)
    else:
        ml = jnp.asarray(max_levels, jnp.int32)
        ml = jnp.where(ml <= 0, jnp.int32(_SAT), ml)
    gcol = (jnp.arange(grid.pc, dtype=jnp.int32)[:, None] * tile_n
            + jnp.arange(tile_n, dtype=jnp.int32)[None, :])

    def step(cols_t, starts_t, valid_t, ends_m, nonempty, xb):
        # one tile's level reduction over the PRECOMPUTED chunked
        # structure: gather the frontier's global column ids at the
        # (chunk-ordered) edge columns and segment-max per row —
        # spmm(SELECT2ND_MAX_I32)'s exact contribution multiset, with
        # the per-level row_structure() re-derivation gone.
        xx = xb[0]                                      # (tile_n, W)
        cg = jnp.clip(cols_t[0, 0], 0, tile_n - 1)
        contrib = jnp.where(valid_t[0, 0][:, None], xx[cg], _IDENT)
        st2 = starts_t[0, 0].reshape(chunk_len, 128)
        y = jax.vmap(lambda col: tl.seg_reduce_pre(
            S.MAX, col.reshape(chunk_len, 128), st2,
            ends_m[0, 0], nonempty[0, 0]),
            in_axes=1, out_axes=1)(contrib)             # (tile_m, W)
        return S.MAX.axis_reduce(y, COL_AXIS)[None]

    def cond(carry):
        _, act, lvl = carry
        return jnp.any(act) & (lvl < ml)

    def body(carry):
        parents, act, lvl = carry
        x = jnp.where(act, gcol[:, :, None], _IDENT)
        y = jax.shard_map(
            step, mesh=grid.mesh,
            in_specs=(P(ROW_AXIS, COL_AXIS, None),) * 5
                     + (P(COL_AXIS, None, None),),
            out_specs=P(ROW_AXIS, None, None),
        )(plan.cols_t, plan.starts_t, plan.valid_t, plan.ends_m,
          plan.nonempty, x)
        fresh = (y != _IDENT) & (parents == NO_PARENT)
        parents = jnp.where(fresh, y, parents)
        actn = dmm.mv_realign(
            dmm.DistMultiVec(fresh, grid, ROW_AXIS, a.nrows),
            COL_AXIS, block=tile_n, fill=False).data
        return parents, actn, lvl + 1

    parents, act, lvl = lax.while_loop(cond, body,
                                       (parents0, act0, jnp.int32(0)))
    done = ~jnp.any(act, axis=(0, 1))
    return (dmm.DistMultiVec(parents, grid, ROW_AXIS, a.nrows), lvl, done)


# ---------------------------------------------------------------------------
# Validation + statistics (≅ TopDownBFS.cpp:452-524)
# ---------------------------------------------------------------------------

def validate_bfs(edges_r: np.ndarray, edges_c: np.ndarray, n: int,
                 root: int, parents: np.ndarray) -> dict:
    """Graph500-style host-side spec check of a parents array:
    (1) parents[root] == root; (2) every tree edge (parent[v], v) is a
    graph edge; (3) tree levels differ by exactly 1 along tree edges;
    (4) exactly the root's connected component is visited."""
    assert parents[root] == root, "root not its own parent"
    visited = parents >= 0
    # component via union-find on host
    import scipy.sparse as sp
    import scipy.sparse.csgraph as csg
    g = sp.coo_matrix((np.ones(len(edges_r)), (edges_r, edges_c)),
                      shape=(n, n)).tocsr()
    ncomp, labels = csg.connected_components(g, directed=False)
    comp_mask = labels == labels[root]
    assert (visited == comp_mask).all(), "visited set != root's component"
    # levels by parent-chasing
    level = np.full(n, -1, np.int64)
    level[root] = 0
    frontier = [root]
    children = {}
    for v in np.nonzero(visited)[0]:
        if v != root:
            children.setdefault(parents[v], []).append(v)
    depth = 0
    while frontier:
        depth += 1
        nxt = []
        for u in frontier:
            for v in children.get(u, ()):  # tree edges
                level[v] = level[u] + 1
                nxt.append(v)
        frontier = nxt
    assert (level[visited] >= 0).all(), "parent pointers contain a cycle"
    # every tree edge must exist in the graph
    tv = np.nonzero(visited & (np.arange(n) != root))[0]
    tp = parents[tv]
    if tv.size:      # scipy returns a sparse (not dense) result for an
        #              empty fancy index — an isolated root has no tree
        #              edges and trivially passes
        has_edge = np.asarray(g[tp, tv]).ravel() != 0
        has_edge |= np.asarray(g[tv, tp]).ravel() != 0
        assert has_edge.all(), "tree edge not in graph"
    # Graph500 spec rule 3: every GRAPH edge connects vertices whose
    # BFS levels differ by at most one (a spanning tree with wrong
    # levels passes the checks above but is not a BFS tree)
    lr, lc = level[edges_r], level[edges_c]
    both = (lr >= 0) & (lc >= 0)
    assert (np.abs(lr[both] - lc[both]) <= 1).all(), \
        "graph edge spans BFS levels differing by more than 1"
    nedges = int(comp_mask[edges_r].sum() // 2)  # sym edge list counted once
    return {"visited": int(visited.sum()), "depth": int(level.max()),
            "nedges": nedges}


def _row_run_bits(rstarts: jax.Array, nwords: int, r) -> jax.Array:
    """Packed (nwords,) uint32 bits covering row r's flat slot range
    [rstarts[r], rstarts[r+1]) of the row-sorted edge order."""
    lo, hi = rstarts[r], rstarts[r + 1]
    w32 = jnp.arange(nwords, dtype=jnp.int32) * 32
    x_hi = jnp.clip(hi - w32, 0, 32)
    x_lo = jnp.clip(lo - w32, 0, 32)

    def msk(x):
        full = jnp.uint32(0xFFFFFFFF)
        part = (jnp.uint32(1) << jnp.clip(x, 0, 31).astype(
            jnp.uint32)) - jnp.uint32(1)
        return jnp.where(x >= 32, full, part)

    return msk(x_hi) & ~msk(x_lo)


def _extract_parents_bits(plan: BfsPlan, pcand: jax.Array, sb: jax.Array,
                          cap: int, tile_m: int, npad: int,
                          fused: bool) -> jax.Array:
    """Parents (tile_m,) int32 (NO_PARENT where unreached) from one
    lane's accumulated parent-candidate edge bits: max column id over
    marked edges, per row. Shared by `bfs_bits` and the batched
    `bfs_batch_bits` (which maps it over lanes).

    Gather-free fast path (see _plan_parent_extract): the tile is
    (row, col)-sorted, so the row's max candidate is its HIGHEST
    pcand bit; one reverse-streamed kernel isolates it and
    backward-fills the column-id bitplanes to every row's start
    slot; the start-compact Beneš route then lands start-slot bits
    at row positions, and the parent ids assemble from bitplanes
    with dense word ops. Replaces an unpack + chunk-transpose +
    segmented scan + 4M-row gather pipeline measured at 96 ms/root
    (of a 118 ms traversal) at scale 22."""
    if fused:
        planes = bs.parent_planes_pallas(pcand, sb,
                                         plan.colbits[0, 0])
        srt = rt.RoutePlan(rt.tile_masks(plan.srt_masks[0, 0]), cap,
                           npad, plan.route_compact)
        nwm = plan.rnon_bits.shape[-1]
        nbits = planes.shape[0] - 1
        # planes route in PAIRS through one shared mask stream
        # (apply_route_pallas_pair) under lax.map, so the executable
        # holds one kernel instance and each launch amortizes the
        # mask stream over two planes: 23 single launches measured
        # 51 ms vs 18 ms paired at scale 22. Odd plane count: the
        # last pair duplicates the final plane.
        npl = planes.shape[0]
        if rt.route_pallas_ok(srt, extra_arrays=2):
            # pair kernel holds 2 in + 2 out full planes + masks
            if npl % 2:
                planes = jnp.concatenate([planes, planes[-1:]])
            pairs = planes.reshape(-1, 2, planes.shape[-1])
            routed = lax.map(
                lambda w2: rt.apply_route_pallas_pair(srt, w2)[:, :nwm],
                pairs).reshape(-1, nwm)[:npl]
        else:
            routed = lax.map(
                lambda w: rt.apply_route_pallas(srt, w)[:nwm], planes)
        hasc = routed[nbits] & plan.rnon_bits[0, 0]
        parents = jnp.zeros((tile_m,), jnp.int32)
        for b in range(nbits):
            pb = rt.unpack_bits(routed[b] & hasc, tile_m)
            parents = parents | (pb.astype(jnp.int32) << b)
        hc8 = rt.unpack_bits(hasc, tile_m)
        return jnp.where(hc8 > 0, parents, NO_PARENT)
    pc8 = rt.unpack_bits(pcand, cap)
    chunk_len = plan.cols_t.shape[-1] // 128
    eb = tl.to_chunked(pc8, fill=0).reshape(-1)
    e_act = (eb > 0) & plan.valid_t[0, 0]
    contrib = jnp.where(e_act, plan.cols_t[0, 0], _IDENT)
    y = tl.seg_reduce_pre(S.MAX, contrib.reshape(chunk_len, 128),
                          plan.starts_t[0, 0].reshape(chunk_len, 128),
                          plan.ends_m[0, 0], plan.nonempty[0, 0])
    return jnp.where(y != _IDENT, y, NO_PARENT)


@jax.jit
def bfs_bits(a: dm.DistSpMat, root, plan: BfsPlan) -> dv.DistVec:
    """Edge-space BFS for SYMMETRIC single-tile matrices: the whole
    traversal state lives in 32x-packed per-edge bits in flat
    row-sorted order, so every level is one Beneš route plus two
    word-parallel segmented bit scans — no sort, no scatter, no
    per-level realign, no int32 edge arrays until the single
    parent-extraction pass at the end.

    Key identities (A symmetric, proven by the sortedness bijection
    (i,j)<->(j,i)): the column-sorted edge sequence equals the
    row-sorted sequence with endpoints swapped, so (1) the router
    input "act at my column, in column order" IS the row-filled
    new-frontier bit vector, and (2) the existing col->row Beneš
    masks route it to "act at my column, in row order". Parent
    recovery needs no level array: each row is new at exactly one
    level, so OR-accumulating (active-neighbor & newly-reached) bits
    marks exactly the valid parent edges; one segmented max over
    their column ids at the end yields Graph500-valid parents
    (validated by validate_bfs / validate_bfs_on_device).

    ≅ DirOptBFS's bottom-up phase (BFSFriends.h:458) with the bitmap
    machinery (BitMap.h) promoted from per-rank words to the whole
    edge space."""
    if a.grid.pr != 1 or a.grid.pc != 1:
        raise ValueError("bfs_bits is the single-tile fast path; use "
                         "bfs_bits_mesh (routed square meshes) or bfs()")
    if plan.route_masks is None:
        raise ValueError("bfs_bits needs a routed plan "
                         "(plan_bfs(a, route=True))")
    if not plan.symmetric:
        raise ValueError(
            "bfs_bits requires a pattern-symmetric matrix (the whole "
            "algorithm rests on the col-order==row-order bit identity); "
            "plan_bfs verified the pattern is NOT symmetric — use "
            "bfs() or symmetrize the graph")
    if plan.sig and plan.sig != (a.grid.pr, a.grid.pc, a.cap,
                                 a.tile_m, a.tile_n):
        raise ValueError(
            f"BfsPlan signature {plan.sig} does not match matrix "
            f"{(a.grid.pr, a.grid.pc, a.cap, a.tile_m, a.tile_n)}: the "
            "plan was built for a different matrix")
    cap, tile_m = a.cap, a.tile_m
    npad = rt.mask_npad(_mask_words(plan.route_masks), plan.route_compact)
    nwords = npad >> 5
    rp = rt.RoutePlan(rt.tile_masks(plan.route_masks[0, 0]), cap, npad,
                      plan.route_compact)
    sb = plan.starts_bits[0, 0]
    vb = plan.valid_bits[0, 0]
    rstarts = plan.rstarts[0, 0]
    root = jnp.asarray(root, jnp.int32)

    def row_run_bits(r):
        return _row_run_bits(rstarts, nwords, r)

    # NB round-4 lesson (measured, scale 22): a direction-optimizing
    # sparse/dense hybrid of this loop is a LOSS on this hardware —
    # any vertex-granular step costs cap-sized unpacks or tile_m-sized
    # gathers (~10-40 ms) against a dense level's ~15 ms, and the
    # sparse<->dense transitions must reconstruct row-filled frontier/
    # visited bits (seed+fill each). The tried hybrid ran 3.6x slower
    # (69 vs 256 MTEPS). The uniform edge-space loop below is the fast
    # form; light levels' route+fill (~30% of a root) are already
    # near the packed-word cost floor.
    new0 = row_run_bits(root)
    visited0 = new0
    pcand0 = jnp.zeros_like(new0)

    # fused level path: 3 Pallas launches (route&vb, fwd fill, bwd
    # fill + frontier update + nonempty flag) instead of ~11 kernels —
    # launch overhead dominated the unfused level (1.37 ms XLA glue vs
    # 0.44 ms route+fill, measured at scale 20). extra_arrays=1: the
    # and_mask input is one more full-size VMEM resident.
    fused = nwords % 128 == 0 and rt.route_pallas_ok(rp, extra_arrays=1)

    def cond(carry):
        _, _, _, flag, it = carry
        # the level cap is a device-side safety net: a BFS level count
        # can never exceed the vertex count, and a runaway loop on a
        # remote accelerator is undebuggable
        return (flag != 0) & (it < jnp.int32(tile_m))

    def body(carry):
        new, visited, pcand, _, it = carry
        # route: row-filled frontier bits ARE the column-order
        # sequence (symmetry); masks deliver "my column is active"
        # bits in row order
        if fused:
            hit = rt.apply_route_pallas(rp, new, and_mask=vb)
            new2, visited, pcand, flagw = bs.seg_or_fill_bfs_pallas(
                hit, sb, vb, visited, pcand)
            return new2, visited, pcand, flagw[0, 0], it + 1
        eact = rt.apply_route_best(rp, new)
        hit = eact & vb
        reached = bs.seg_or_fill_best(hit, sb)
        new2 = reached & ~visited & vb
        flag = jnp.any(new2 != 0).astype(jnp.uint32)
        return new2, visited | new2, pcand | (hit & new2), flag, it + 1

    flag0 = jnp.any(new0 != 0).astype(jnp.uint32)
    _, _, pcand, _, _ = lax.while_loop(
        cond, body, (new0, visited0, pcand0, flag0, jnp.int32(0)))

    # parent extraction: max column id over marked edges, per row
    # (shared with bfs_batch_bits — see _extract_parents_bits).
    parents = _extract_parents_bits(
        plan, pcand, sb, cap, tile_m, npad,
        fused=fused and plan.colbits is not None)
    parents = parents.at[root].set(root)
    return dv.DistVec(parents[None, :], a.grid, ROW_AXIS, a.nrows)


#: why a batch fell off the 32x bits path — the labels on
#: `bfs.bits_fallback` (metric + ledger records + serve /varz)
BITS_FALLBACK_REASONS = ("unrouted", "asymmetric", "mesh")

_M_BITS_FALLBACK = obm.counter(
    "bfs.bits_fallback",
    "batches that silently degraded from the packed-bit path to dense "
    "bfs_batch (kind=unrouted|asymmetric|mesh) — each one pays ~32x "
    "the per-root frontier traffic")


def bits_fallback_reason(a: dm.DistSpMat,
                         plan: BfsPlan | None) -> str | None:
    """None when the bitplane batched BFS applies, else the reason the
    batch will ride dense `bfs_batch`: ``unrouted`` (no plan or no
    Beneš masks), ``asymmetric`` (1x1 grid but the col-order==row-order
    bit identity is unverified), ``mesh`` (multi-tile grid that is not
    a square routed mesh with square vertex blocks — the transpose
    exchange needs (i,j)<->(j,i) pairing)."""
    if plan is None or plan.route_masks is None:
        return "unrouted"
    if a.grid.pr == 1 and a.grid.pc == 1:
        return None if plan.symmetric else "asymmetric"
    return None if _bits_mesh_ok(a, plan) else "mesh"


def bits_batch_ok(a: dm.DistSpMat, plan: BfsPlan | None) -> bool:
    """Whether a bitplane batched BFS path applies: on a 1x1 grid the
    single-tile core (routed plan + verified pattern symmetry, the
    same guards as `bfs_bits`); on a multi-tile grid the mesh core
    (`_bits_mesh_ok`: routed square mesh with column-run bits — no
    symmetry needed there, the frontier expansion is explicit)."""
    return bits_fallback_reason(a, plan) is None


def bfs_batch_bits(a: dm.DistSpMat, roots, max_levels=None, plan=None):
    """Batched multi-source BFS with PACKED-BIT frontiers: lane w of
    an (nwords, W) uint32 bitplane matrix is root w's edge-space
    frontier, so one shared Beneš route + one lane-parallel segmented
    OR fill advances ALL W roots one level — 1 bit of frontier traffic
    per root per edge slot where `bfs_batch` moves a full i32 column
    (the CombBLAS-2.0 batched-traversal win, arXiv:2106.14402, on the
    `bfs_bits` edge-space machinery).

    Host-level wrapper: validates roots (any root outside [0, n) is a
    ValueError), then dispatches to the jitted bitplane core when
    `bits_batch_ok` holds — the single-tile core on a 1x1 grid, the
    mesh core (`_bfs_batch_bits_mesh_core`: lane-packed frontier words
    in the explicit transpose exchange) on square routed meshes — else
    falls back to dense `bfs_batch` (unrouted plan, pattern-asymmetric
    1x1 matrix, or an ineligible mesh; a batch endpoint degrades
    instead of raising, and each degradation is counted + ledgered as
    `bfs.bits_fallback{reason}`).

    Returns the `bfs_batch` triple (parents r-aligned DistMultiVec,
    levels, done (W,) bool), with ``levels`` PER-LANE on the bits
    path: lane w's count of levels actually advanced (its root's
    truncated eccentricity), a (W,) int32 — the dense fallback
    broadcasts its scalar wave count. Parents are a valid BFS tree
    per lane (validate_bfs) with levels identical to per-root `bfs`;
    the parent CHOICE may differ (both pick a max-id parent, over
    differently-ordered candidate sets)."""
    roots_np = np.asarray(roots, np.int64)  # analysis: allow(sync-in-async) host argument validation, pre-dispatch
    if roots_np.ndim != 1 or roots_np.size == 0:
        raise ValueError("roots must be a non-empty 1-D array")
    if roots_np.min() < 0 or roots_np.max() >= a.nrows:
        bad = roots_np[(roots_np < 0) | (roots_np >= a.nrows)]
        raise ValueError(f"roots {bad.tolist()} outside [0, {a.nrows})")
    roots32 = jnp.asarray(roots_np, jnp.int32)
    reason = bits_fallback_reason(a, plan)
    if reason is not None:
        # ledger-visible degradation: fleet dashboards see the 32x
        # economics being lost, by reason, in every dispatch_summary
        _M_BITS_FALLBACK.inc(kind=reason)
        obs.ledger.record(f"bfs.bits_fallback/{reason}", "dispatch",
                          time.perf_counter(), 0.0)
        mv, lvl, done = bfs_batch(a, roots32, max_levels, plan=plan)
        return mv, jnp.broadcast_to(lvl, done.shape), done
    if plan.sig and plan.sig != (a.grid.pr, a.grid.pc, a.cap,
                                 a.tile_m, a.tile_n):
        raise ValueError(
            f"BfsPlan signature {plan.sig} does not match matrix "
            f"{(a.grid.pr, a.grid.pc, a.cap, a.tile_m, a.tile_n)}: the "
            "plan was built for a different matrix")
    if max_levels is None:
        ml = jnp.int32(_SAT)
    else:
        ml = jnp.asarray(max_levels, jnp.int32)
        ml = jnp.where(ml <= 0, jnp.int32(_SAT), ml)
    if a.grid.pr == 1 and a.grid.pc == 1:
        return _bfs_batch_bits_core(a, plan, roots32, ml)
    return _bfs_batch_bits_mesh_core(a, plan, roots32, ml)


@jax.jit
def _bfs_batch_bits_core(a: dm.DistSpMat, plan: BfsPlan, roots, ml):
    """The bitplane wave loop (see bfs_batch_bits). One while_loop
    iteration = one level for every lane: multi-lane route, AND with
    the live-slot mask, lane-parallel segment fill, frontier/visited/
    parent-candidate updates — all (nwords, W) word arithmetic. A lane
    whose frontier empties goes inert (all-zero bits route to all-zero)
    while the wave serves the rest; per-lane level counters stop with
    it."""
    from combblas_tpu.parallel import densemat as dmm
    cap, tile_m = a.cap, a.tile_m
    npad = rt.mask_npad(_mask_words(plan.route_masks), plan.route_compact)
    nwords = npad >> 5
    rp = rt.RoutePlan(rt.tile_masks(plan.route_masks[0, 0]), cap, npad,
                      plan.route_compact)
    sb = plan.starts_bits[0, 0]
    vb = plan.valid_bits[0, 0]
    rstarts = plan.rstarts[0, 0]
    w = roots.shape[0]

    # lane seeds: root w's row-run bits in lane w (an isolated root's
    # run is empty — the lane is born inert, exactly the dense path's
    # immediately-empty frontier)
    new0 = jax.vmap(lambda r: _row_run_bits(rstarts, nwords, r),
                    out_axes=1)(roots)               # (nwords, W)
    visited0 = new0
    pcand0 = jnp.zeros_like(new0)
    lanelvl0 = jnp.zeros((w,), jnp.int32)

    def cond(carry):
        new, _, _, _, lvl = carry
        # tile_m cap: a BFS level count can never exceed the vertex
        # count — device-side safety net against a runaway loop
        return jnp.any(new != 0) & (lvl < ml) & (lvl < jnp.int32(tile_m))

    def body(carry):
        new, visited, pcand, lanelvl, lvl = carry
        eact = rt.apply_route_multi_best(rp, new)
        hit = eact & vb[:, None]
        reached = bs.seg_or_fill_multi_best(hit, sb)
        new2 = reached & ~visited & vb[:, None]
        adv = jnp.any(new2 != 0, axis=0)             # (W,) lane advanced?
        return (new2, visited | new2, pcand | (hit & new2),
                lanelvl + adv.astype(jnp.int32), lvl + 1)

    new, _, pcand, lanelvl, _ = lax.while_loop(
        cond, body, (new0, visited0, pcand0, lanelvl0, jnp.int32(0)))
    # per-lane done: complete iff the lane's frontier was empty when
    # the wave stopped (matches bfs_batch's per-column act check)
    done = ~jnp.any(new != 0, axis=0)

    # parent extraction per lane, via the shared single-lane helper:
    # vmap on the XLA fallback (seg_reduce_pre is vmap-safe), lax.map
    # over lanes on the Pallas fast path (kernels don't vmap).
    fused = (nwords % 128 == 0 and rt.route_pallas_ok(rp, extra_arrays=1)
             and plan.colbits is not None)
    if fused:
        parents = lax.map(
            lambda pcw: _extract_parents_bits(plan, pcw, sb, cap,
                                              tile_m, npad, True),
            pcand.T).T                               # (tile_m, W)
    else:
        parents = jax.vmap(
            lambda pcw: _extract_parents_bits(plan, pcw, sb, cap,
                                              tile_m, npad, False),
            in_axes=1, out_axes=1)(pcand)
    w_ix = jnp.arange(w, dtype=jnp.int32)
    parents = parents.at[roots, w_ix].set(roots)
    return (dmm.DistMultiVec(parents[None], a.grid, ROW_AXIS, a.nrows),
            lanelvl, done)


# flight-recorder boundaries (ledger.instrument): eager calls of the
# per-root / batched traversal drivers record one DispatchRecord each;
# in-trace composition (bfs_bits inside bfs_bits_mesh, plan_bfs inside
# bfs) passes through untouched. Async on purpose — the g500 harness
# overlaps dispatch with the stats drain, and the drain's readback
# records carry the device wall.
bfs = obs.instrument(bfs, "bfs.bfs")
bfs_batch = obs.instrument(bfs_batch, "bfs.batch")
bfs_bits = obs.instrument(bfs_bits, "bfs.bits")
_bfs_batch_bits_core = obs.instrument(_bfs_batch_bits_core,
                                      "bfs.batch_bits")
_plan_bfs_core = obs.instrument(_plan_bfs_core, "bfs.plan_core",
                                sync=True)


def _bits_mesh_ok(a: dm.DistSpMat, plan: BfsPlan) -> bool:
    """Whether the distributed edge-space bit BFS applies: routed plan
    with col-run bits, square mesh (the packed vertex-bit transpose
    exchange pairs tile (i,j) with (j,i)), square vertex blocks."""
    return (plan.route_masks is not None and plan.cstart_bits is not None
            and a.grid.pr == a.grid.pc and a.tile_m == a.tile_n)


@jax.jit
def bfs_bits_mesh(a: dm.DistSpMat, root, plan: BfsPlan) -> dv.DistVec:
    """Distributed edge-space bit BFS: the mesh generalization of
    `bfs_bits` (≅ the distributed role of BFSFriends.h:458's carousel
    bottom-up step, with BitMap.h's words promoted to the whole edge
    space of every tile).

    Per level, per tile, everything stays 32x-packed:
      1. transpose-exchange the new-frontier VERTEX bits (row block i
         -> column block j) as packed words via one `ppermute` — 32x
         less ICI traffic than the stepper path's bool realign;
      2. expand to edge space: scatter each active column's bit at its
         column-run start (static positions from cstarts), segment-OR
         fill along cstart_bits;
      3. route column-order edge bits to row order through the tile's
         Beneš masks (the same masks the single-tile path uses — but
         no symmetry assumption: the frontier expansion is explicit
         here, so asymmetric matrices are fine);
      4. hit/reached via the packed segmented OR fill over row runs,
         extract one bit per row (gather of tile_m words), OR-combine
         across the mesh row (all_gather of packed words + OR);
      5. accumulate parent-candidate edge bits (hit & newly-reached
         row fill) — parents are extracted once, after the loop, by
         the segmented max over column ids + pmax along the mesh row.

    Cross-check: tests force this path against `bfs`'s stepper parents
    on the CPU mesh (the reference's SpMSpV-variant consistency
    pattern, SpMSpVBench.cpp:531-539).
    """
    if a.grid.pr == 1 and a.grid.pc == 1:
        return bfs_bits(a, root, plan)
    if not _bits_mesh_ok(a, plan):
        raise ValueError(
            "bfs_bits_mesh needs a routed plan (plan_bfs(a, route=True)) "
            "on a square mesh with square vertex blocks; use bfs() "
            "otherwise")
    if plan.sig and plan.sig != (a.grid.pr, a.grid.pc, a.cap,
                                 a.tile_m, a.tile_n):
        raise ValueError(
            f"BfsPlan signature {plan.sig} does not match matrix "
            f"{(a.grid.pr, a.grid.pc, a.cap, a.tile_m, a.tile_n)}: the "
            "plan was built for a different matrix")
    grid = a.grid
    pr, pc = grid.pr, grid.pc
    cap, tile_m, tile_n = a.cap, a.tile_m, a.tile_n
    npad = rt.mask_npad(_mask_words(plan.route_masks), plan.route_compact)
    nwv = -(-tile_m // 32)               # vertex-bit words per block
    root = jnp.asarray(root, jnp.int32)
    capp = plan.cols_t.shape[-1]
    chunk_len = capp // 128
    # transpose-pair exchange (i,j) <-> (j,i); shard_map linearizes
    # (ROW_AXIS, COL_AXIS) with the leading axis slowest
    tperm = [(j * pc + i, i * pc + j) for i in range(pr) for j in range(pc)]

    def f(cols_t, starts_t, valid_t, ends_m, nonempty, cstarts, cdeg,
          rmasks, sb, vb, cb, rstarts):
        i = lax.axis_index(ROW_AXIS)
        j = lax.axis_index(COL_AXIS)
        cols_t, starts_t, valid_t = cols_t[0, 0], starts_t[0, 0], valid_t[0, 0]
        ends_m, nonempty = ends_m[0, 0], nonempty[0, 0]
        cstarts, cdeg = cstarts[0, 0], cdeg[0, 0]
        sb, vb, cb, rstarts = sb[0, 0], vb[0, 0], cb[0, 0], rstarts[0, 0]
        rp = rt.RoutePlan(rt.tile_masks(rmasks[0, 0]), cap, npad,
                          plan.route_compact)
        row_nonempty = rstarts[1:] > rstarts[:-1]
        rs_lo = jnp.clip(rstarts[:-1], 0, npad - 1)   # (tile_m,)

        inblk = (root >= i * tile_m) & (root < (i + 1) * tile_m)
        rloc = jnp.clip(root - i * tile_m, 0, tile_m - 1)
        seedw = jnp.zeros((nwv,), jnp.uint32).at[rloc >> 5].set(
            jnp.uint32(1) << (rloc & 31).astype(jnp.uint32))
        newv0 = jnp.where(inblk, seedw, jnp.zeros_like(seedw))
        pcand0 = jnp.zeros((npad // 32,), jnp.uint32)

        def extract_row_bits(filled):
            """One bit per row out of run-filled edge bits (the fill
            makes any slot of the run representative; take the start)."""
            w = filled[rs_lo >> 5]
            bit = (w >> (rs_lo & 31).astype(jnp.uint32)) & jnp.uint32(1)
            return rt.pack_bits(
                jnp.where(row_nonempty, bit.astype(jnp.int32), 0), nwv * 32)

        def expand_runs(vbits, n_v, run_starts, run_nonempty, run_bits):
            """Vertex bits -> run-filled edge bits: scatter each
            vertex's bit at its run start, segment-OR fill (shared by
            the row side (rstarts/sb) and the column side (cstarts/cb))."""
            v8 = rt.unpack_bits(vbits, n_v)
            seed = jnp.zeros((cap + 1,), jnp.int8).at[
                jnp.where(run_nonempty, run_starts, cap)].set(
                v8, mode="drop")[:cap]
            return bs.seg_or_fill_best(rt.pack_bits(seed, npad), run_bits)

        def body(carry):
            newv, visited, pcand, _ = carry
            # (1) vertex bits to the transpose position: block j arrives
            newc = lax.ppermute(newv, (ROW_AXIS, COL_AXIS), tperm)
            # (2) expand over column runs
            eact_c = expand_runs(newc, tile_n, cstarts[:-1], cdeg > 0, cb)
            # (3) to row order
            eact_r = rt.apply_route_best(rp, eact_c)
            hit = eact_r & vb
            # (4) per-row reached, combined across the mesh row
            reached_e = bs.seg_or_fill_best(hit, sb)
            rbits = extract_row_bits(reached_e)
            allv = lax.all_gather(rbits, COL_AXIS)      # (pc, nwv)
            reached = allv[0]
            for k in range(1, pc):
                reached = reached | allv[k]
            new2v = reached & ~visited
            # (5) parent candidates in edge space
            new2_e = expand_runs(new2v, tile_m, rstarts[:-1],
                                 row_nonempty, sb)
            pcand = pcand | (hit & new2_e)
            anyb = jnp.any(new2v != 0).astype(jnp.int32)
            cont = lax.pmax(anyb, (ROW_AXIS, COL_AXIS)) > 0
            return new2v, visited | new2v, pcand, cont

        # the initial carries vary only over ROW_AXIS (built from i);
        # the loop body's collectives make them vary over both mesh
        # axes, and shard_map requires matching varying-axis sets
        _pvary = (partial(lax.pcast, to="varying")
                  if hasattr(lax, "pcast") else lax.pvary)
        newv0v = _pvary(newv0, (COL_AXIS,))
        pcand0v = _pvary(pcand0, (ROW_AXIS, COL_AXIS))
        _, _, pcand, _ = lax.while_loop(
            lambda c: c[3], body,
            (newv0v, newv0v, pcand0v, jnp.bool_(True)))

        # parent extraction: segmented max of global column ids over
        # the candidate edges, pmax along the mesh row
        pc8 = rt.unpack_bits(pcand, cap)
        eb = tl.to_chunked(pc8, fill=0).reshape(-1)
        e_act = (eb > 0) & valid_t
        contrib = jnp.where(e_act, cols_t + j.astype(jnp.int32) * tile_n,
                            _IDENT)
        y = tl.seg_reduce_pre(S.MAX, contrib.reshape(chunk_len, 128),
                              starts_t.reshape(chunk_len, 128),
                              ends_m, nonempty)
        y = lax.pmax(y, COL_AXIS)
        parents = jnp.where(y != _IDENT, y, NO_PARENT)
        parents = jnp.where(
            inblk, parents.at[rloc].set(root), parents)
        return parents[None]

    spec3 = P(ROW_AXIS, COL_AXIS, None)
    rspec = P(ROW_AXIS, COL_AXIS,
              *([None] * (plan.route_masks.ndim - 2)))
    parents = jax.shard_map(
        f, mesh=grid.mesh,
        in_specs=(spec3,) * 7 + (rspec,) + (spec3,) * 4,
        out_specs=P(ROW_AXIS, None),
    )(plan.cols_t, plan.starts_t, plan.valid_t, plan.ends_m, plan.nonempty,
      plan.cstarts, plan.cdeg, plan.route_masks, plan.starts_bits,
      plan.valid_bits, plan.cstart_bits, plan.rstarts)
    return dv.DistVec(parents, grid, ROW_AXIS, a.nrows)


bfs_bits_mesh = obs.instrument(bfs_bits_mesh, "bfs.bits_mesh")


def _register_bits_mesh_collectives(a: dm.DistSpMat, name: str,
                                    w: int) -> None:
    """Register one LEVEL's collective descriptors for the bits-mesh
    BFS drivers with the mesh observatory.  The wave loop runs a
    data-dependent number of levels inside ``lax.while_loop``, so a
    static per-dispatch byte total is unknowable at plan time; by
    convention the registered set describes ONE level (plus the single
    post-loop parents reduction) and budgets/mesh.json does not band
    the drift ratio for bfs.* names.  ``w`` is the lane count (roots
    per batch; 1 for the single-root driver)."""
    nwv = -(-a.tile_m // 32)  # vertex-bit words per block
    pc = a.grid.pc
    both = ROW_AXIS + COL_AXIS
    obs.meshobs.register_collectives(name, (
        # transpose-route the new-frontier vertex words
        dict(collective="ppermute", axis=both, dtype="uint32",
             shape=(nwv, w), rung=0, bytes=4 * nwv * w),
        # gather row-reached words across the process column
        dict(collective="all_gather", axis=COL_AXIS, dtype="uint32",
             shape=(pc, nwv, w), rung=1, bytes=(pc - 1) * 4 * nwv * w),
        # frontier-empty vote
        dict(collective="pmax", axis=both, dtype="int32",
             shape=(w,), rung=2, bytes=4 * w),
        # post-loop parents reduction (once per dispatch, not per level)
        dict(collective="pmax", axis=COL_AXIS, dtype="int32",
             shape=(a.tile_m, w), rung=3, bytes=4 * a.tile_m * w),
    ))


def bfs_batch_bits_mesh(a: dm.DistSpMat, roots, max_levels=None,
                        plan: BfsPlan | None = None):
    """Batched packed-bit BFS on a multi-tile routed mesh: the
    32-roots-per-word bitplane machinery of `bfs_batch_bits` lifted
    onto the explicit frontier exchange of `bfs_bits_mesh` — every
    exchanged quantity is a lane-packed WORD matrix (one uint32 per 32
    lanes per slot), so the per-level ppermute/all_gather volume per
    root is 1 bit per vertex/edge slot where dense `bfs_batch` moves a
    full i32 column. Raises on ineligible inputs (use `bfs_batch_bits`
    for the degrading endpoint); returns the `bfs_batch` triple with
    PER-LANE levels, exactly like the single-tile bits path."""
    if plan is None or not _bits_mesh_ok(a, plan):
        raise ValueError(
            "bfs_batch_bits_mesh needs a routed plan "
            "(plan_bfs(a, route=True)) on a square mesh with square "
            "vertex blocks; use bfs_batch_bits (degrading) or "
            "bfs_batch otherwise")
    roots_np = np.asarray(roots, np.int64)
    if roots_np.ndim != 1 or roots_np.size == 0:
        raise ValueError("roots must be a non-empty 1-D array")
    if roots_np.min() < 0 or roots_np.max() >= a.nrows:
        bad = roots_np[(roots_np < 0) | (roots_np >= a.nrows)]
        raise ValueError(f"roots {bad.tolist()} outside [0, {a.nrows})")
    if plan.sig and plan.sig != (a.grid.pr, a.grid.pc, a.cap,
                                 a.tile_m, a.tile_n):
        raise ValueError(
            f"BfsPlan signature {plan.sig} does not match matrix "
            f"{(a.grid.pr, a.grid.pc, a.cap, a.tile_m, a.tile_n)}: the "
            "plan was built for a different matrix")
    roots32 = jnp.asarray(roots_np, jnp.int32)
    if max_levels is None:
        ml = jnp.int32(_SAT)
    else:
        ml = jnp.asarray(max_levels, jnp.int32)
        ml = jnp.where(ml <= 0, jnp.int32(_SAT), ml)
    _register_bits_mesh_collectives(a, "bfs.batch_bits_mesh",
                                    int(roots_np.size))
    return _bfs_batch_bits_mesh_core(a, plan, roots32, ml)


@jax.jit
def _bfs_batch_bits_mesh_core(a: dm.DistSpMat, plan: BfsPlan, roots, ml):
    """The mesh bitplane wave loop (see bfs_batch_bits_mesh): the
    level body of `bfs_bits_mesh` with every carry widened to an
    (nwords, W) lane matrix. One while_loop iteration advances ALL W
    roots one level on every tile:

      1. `ppermute` the (nwv, W) new-frontier vertex WORDS to the
         transpose position — 32 roots per uint32 on the wire;
      2. lane-scatter each active column's bits at its column-run
         start, lane-parallel segment-OR fill (`seg_or_fill_multi`);
      3. route all W planes through the shared Beneš masks
         (`apply_route_multi_best` — pair-kernel on TPU);
      4. per-row reached bits per lane, OR-combined across the mesh
         row via one packed `all_gather`;
      5. accumulate per-lane parent-candidate edge bits.

    Per-lane level counters advance only for lanes that discovered a
    vertex anywhere on the mesh (one pmax per level); inert lanes ride
    along as all-zero planes. Parents extract once after the loop —
    per-lane segmented max over global column ids, pmax along the mesh
    row — exactly the single-root extraction vmapped over lanes."""
    from combblas_tpu.parallel import densemat as dmm
    grid = a.grid
    pr, pc = grid.pr, grid.pc
    cap, tile_m, tile_n = a.cap, a.tile_m, a.tile_n
    npad = rt.mask_npad(_mask_words(plan.route_masks), plan.route_compact)
    nwv = -(-tile_m // 32)               # vertex-bit words per block
    w_lanes = roots.shape[0]
    capp = plan.cols_t.shape[-1]
    chunk_len = capp // 128
    tperm = [(j * pc + i, i * pc + j) for i in range(pr) for j in range(pc)]
    lvl_cap = jnp.int32(min(pr * tile_m, _SAT))

    def f(cols_t, starts_t, valid_t, ends_m, nonempty, cstarts, cdeg,
          rmasks, sb, vb, cb, rstarts):
        i = lax.axis_index(ROW_AXIS)
        j = lax.axis_index(COL_AXIS)
        cols_t, starts_t, valid_t = cols_t[0, 0], starts_t[0, 0], valid_t[0, 0]
        ends_m, nonempty = ends_m[0, 0], nonempty[0, 0]
        cstarts, cdeg = cstarts[0, 0], cdeg[0, 0]
        sb, vb, cb, rstarts = sb[0, 0], vb[0, 0], cb[0, 0], rstarts[0, 0]
        rp = rt.RoutePlan(rt.tile_masks(rmasks[0, 0]), cap, npad,
                          plan.route_compact)
        row_nonempty = rstarts[1:] > rstarts[:-1]
        rs_lo = jnp.clip(rstarts[:-1], 0, npad - 1)   # (tile_m,)

        inblk_l = (roots >= i * tile_m) & (roots < (i + 1) * tile_m)
        rloc_l = jnp.clip(roots - i * tile_m, 0, tile_m - 1)
        w_ix = jnp.arange(w_lanes, dtype=jnp.int32)

        # lane seeds: root w's vertex bit in lane w of its owning row
        # block (duplicate roots seed identical independent lanes)
        def seed_lane(r):
            inb = (r >= i * tile_m) & (r < (i + 1) * tile_m)
            rl = jnp.clip(r - i * tile_m, 0, tile_m - 1)
            s = jnp.zeros((nwv,), jnp.uint32).at[rl >> 5].set(
                jnp.uint32(1) << (rl & 31).astype(jnp.uint32))
            return jnp.where(inb, s, jnp.zeros_like(s))

        newv0 = jax.vmap(seed_lane, out_axes=1)(roots)   # (nwv, W)
        pcand0 = jnp.zeros((npad // 32, w_lanes), jnp.uint32)
        lanelvl0 = jnp.zeros((w_lanes,), jnp.int32)

        def extract_row_bits_multi(filled):
            w = filled[rs_lo >> 5]                       # (tile_m, W)
            bit = (w >> (rs_lo & 31).astype(jnp.uint32)[:, None]) \
                & jnp.uint32(1)
            return rt.pack_bits_multi(
                jnp.where(row_nonempty[:, None], bit.astype(jnp.int8), 0),
                nwv * 32)

        def expand_runs_multi(vbits, n_v, run_starts, run_nonempty,
                              run_bits):
            v8 = rt.unpack_bits_multi(vbits, n_v)        # (n_v, W)
            seed = jnp.zeros((cap + 1, w_lanes), jnp.int8).at[
                jnp.where(run_nonempty, run_starts, cap)].set(
                v8, mode="drop")[:cap]
            return bs.seg_or_fill_multi_best(
                rt.pack_bits_multi(seed, npad), run_bits)

        def body(carry):
            newv, visited, pcand, lanelvl, lvl, _ = carry
            newc = lax.ppermute(newv, (ROW_AXIS, COL_AXIS), tperm)
            eact_c = expand_runs_multi(newc, tile_n, cstarts[:-1],
                                       cdeg > 0, cb)
            eact_r = rt.apply_route_multi_best(rp, eact_c)
            hit = eact_r & vb[:, None]
            reached_e = bs.seg_or_fill_multi_best(hit, sb)
            rbits = extract_row_bits_multi(reached_e)
            allv = lax.all_gather(rbits, COL_AXIS)       # (pc, nwv, W)
            reached = allv[0]
            for k in range(1, pc):
                reached = reached | allv[k]
            new2v = reached & ~visited
            new2_e = expand_runs_multi(new2v, tile_m, rstarts[:-1],
                                       row_nonempty, sb)
            pcand = pcand | (hit & new2_e)
            adv = lax.pmax(
                jnp.any(new2v != 0, axis=0).astype(jnp.int32),
                (ROW_AXIS, COL_AXIS))                    # (W,) global
            return (new2v, visited | new2v, pcand,
                    lanelvl + adv, lvl + 1, jnp.any(adv > 0))

        _pvary = (partial(lax.pcast, to="varying")
                  if hasattr(lax, "pcast") else lax.pvary)
        newv0v = _pvary(newv0, (COL_AXIS,))
        pcand0v = _pvary(pcand0, (ROW_AXIS, COL_AXIS))
        lanelvl0v = _pvary(lanelvl0, (ROW_AXIS, COL_AXIS))
        newv_f, _, pcand, lanelvl, _, _ = lax.while_loop(
            lambda c: c[5] & (c[4] < ml) & (c[4] < lvl_cap), body,
            (newv0v, newv0v, pcand0v, lanelvl0v, jnp.int32(0),
             jnp.bool_(True)))
        # per-lane done: the lane's frontier was empty ANYWHERE on the
        # mesh when the wave stopped (ml truncation leaves it live)
        anyfront = lax.pmax(
            jnp.any(newv_f != 0, axis=0).astype(jnp.int32),
            (ROW_AXIS, COL_AXIS))

        # parent extraction: the single-root segmented max over global
        # column ids, vmapped over lanes, then pmax along the mesh row
        def extract_lane(pcw):
            pc8 = rt.unpack_bits(pcw, cap)
            eb = tl.to_chunked(pc8, fill=0).reshape(-1)
            e_act = (eb > 0) & valid_t
            contrib = jnp.where(
                e_act, cols_t + j.astype(jnp.int32) * tile_n, _IDENT)
            return tl.seg_reduce_pre(
                S.MAX, contrib.reshape(chunk_len, 128),
                starts_t.reshape(chunk_len, 128), ends_m, nonempty)

        y = jax.vmap(extract_lane, in_axes=1, out_axes=1)(pcand)
        y = lax.pmax(y, COL_AXIS)                        # (tile_m, W)
        parents = jnp.where(y != _IDENT, y, NO_PARENT)
        # roots self-parent, per lane where the root lives in this block
        pp = jnp.concatenate(
            [parents, jnp.zeros((1, w_lanes), jnp.int32)])
        pp = pp.at[jnp.where(inblk_l, rloc_l, tile_m), w_ix].set(roots)
        return pp[None, :tile_m], lanelvl[None], anyfront[None]

    spec3 = P(ROW_AXIS, COL_AXIS, None)
    rspec = P(ROW_AXIS, COL_AXIS,
              *([None] * (plan.route_masks.ndim - 2)))
    parents, lanelvl, anyfront = jax.shard_map(
        f, mesh=grid.mesh,
        in_specs=(spec3,) * 7 + (rspec,) + (spec3,) * 4,
        out_specs=(P(ROW_AXIS, None, None), P(ROW_AXIS, None),
                   P(ROW_AXIS, None)),
    )(plan.cols_t, plan.starts_t, plan.valid_t, plan.ends_m, plan.nonempty,
      plan.cstarts, plan.cdeg, plan.route_masks, plan.starts_bits,
      plan.valid_bits, plan.cstart_bits, plan.rstarts)
    return (dmm.DistMultiVec(parents, grid, ROW_AXIS, a.nrows),
            lanelvl[0], anyfront[0] == 0)


_bfs_batch_bits_mesh_core = obs.instrument(_bfs_batch_bits_mesh_core,
                                           "bfs.batch_bits_mesh")


@jax.jit
def row_degrees(a: dm.DistSpMat) -> jax.Array:
    """(pr, tile_m) int32 per-row degree of the (deduplicated) matrix,
    on device — no edge-list fetch to host."""
    def f(rows, nnz):
        rows, nnz = rows[0, 0], nnz[0, 0]
        valid = jnp.arange(rows.shape[0], dtype=jnp.int32) < nnz
        tgt = jnp.where(valid, rows, a.tile_m)
        d = jnp.zeros((a.tile_m + 1,), jnp.int32)
        d = d.at[tgt].add(1, mode="drop")[:a.tile_m]
        return lax.psum(d, COL_AXIS)[None]

    return jax.shard_map(
        f, mesh=a.grid.mesh,
        in_specs=(P(ROW_AXIS, COL_AXIS, None), P(ROW_AXIS, COL_AXIS)),
        out_specs=P(ROW_AXIS, None),
    )(a.rows.reshape(a.grid.pr, a.grid.pc, -1),
      a.nnz.reshape(a.grid.pr, a.grid.pc))


@jax.jit
def run_stats(deg: jax.Array, parents: dv.DistVec):
    """(visited, nedges) of the traversed component, on device.
    ``nedges`` follows the Graph500 counting recipe on the
    deduplicated graph (sum of component degrees / 2 — conservative
    vs counting raw generator edges; TopDownBFS.cpp:452-524)."""
    vis = parents.data >= 0
    visited = jnp.sum(vis)
    nedges = jnp.sum(jnp.where(vis, deg, 0)) // 2
    return visited, nedges


@partial(jax.jit, static_argnames=("tile_n", "capbits"))
def _vchecks(p, root, crows, ccols, cstarts, nnz, tile_n, capbits):
    """Jitted spec checks (module-level so 64 validated roots compile
    once, not 64 times)."""
    n = p.shape[0]
    vis = p >= 0
    ok_root = p[root] == root
    # tree edges (p[v], v) must be matrix entries a[v, p[v]]:
    # bisect v in column p[v]'s row list (crows sorted within each
    # column run; int32-safe — no packed 2d keys, x64 is off)
    v = jnp.arange(n, dtype=jnp.int32)
    need = vis & (v != root)
    ps = jnp.clip(p, 0, tile_n - 1)
    lo = jnp.minimum(cstarts[ps], nnz)
    hi = jnp.minimum(cstarts[ps + 1], nnz)

    def bis(_, lh):
        lo, hi = lh
        mid = (lo + hi) >> 1
        less = crows[jnp.clip(mid, 0, crows.shape[0] - 1)] < v
        return (jnp.where((lo < hi) & less, mid + 1, lo),
                jnp.where((lo < hi) & ~less, mid, hi))

    lo, hi = lax.fori_loop(0, capbits + 1, bis, (lo, hi))
    found = (lo < jnp.minimum(cstarts[ps + 1], nnz)) & \
        (crows[jnp.clip(lo, 0, crows.shape[0] - 1)] == v)
    ok_tree = jnp.all(~need | found)
    # cycle-free chase: levels converge within n iterations
    lev0 = jnp.where(v == root, 0, -1)

    def body(carry):
        lev, _ = carry
        pl_ = lev[jnp.clip(p, 0, n - 1)]
        newly = vis & (lev < 0) & (pl_ >= 0)
        lev2 = jnp.where(newly, pl_ + 1, lev)
        return lev2, jnp.any(newly)

    lev, _ = lax.while_loop(lambda c: c[1], body,
                            (lev0, jnp.bool_(True)))
    ok_levels = jnp.all(~vis | (lev >= 0))
    depth = jnp.max(lev)
    # Graph500 spec rule 3 over ALL graph edges: endpoints' BFS levels
    # differ by at most one (catches non-BFS spanning trees that pass
    # the tree/cycle checks; advisor round-3 finding). Edges touching
    # unvisited vertices are the closure check's job.
    k = jnp.arange(crows.shape[0], dtype=jnp.int32)
    evalid = k < nnz
    lr = lev[jnp.clip(crows, 0, n - 1)]
    lc = lev[jnp.clip(ccols, 0, n - 1)]
    both = evalid & (lr >= 0) & (lc >= 0)
    ok_edge_levels = jnp.all(~both | (jnp.abs(lr - lc) <= 1))
    return ok_root, ok_tree, ok_levels, ok_edge_levels, vis, depth


@jax.jit
def _dense_reach(a: dm.DistSpMat, plan: BfsPlan, act):
    """Jitted dense-step application for the closure check (cached
    across validated roots)."""
    _, steppers = build_steppers(a, plan)
    return steppers[-1](act)


def validate_bfs_on_device(a: dm.DistSpMat, plan: BfsPlan, root,
                           parents: dv.DistVec, deg: jax.Array) -> dict:
    """Graph500 spec check of a parents vector WITHOUT fetching the
    edge list to host (the reference validates distributed too,
    TopDownBFS.cpp:452-524). Single-tile grids only (the bench
    config); multi-tile tests use the host `validate_bfs`.

    Checks: root self-parent; every tree edge is a matrix entry
    (searchsorted on the column-sorted tile); parent chase terminates
    (cycle-free) and covers exactly the visited set; the visited set
    is closed under adjacency (== the root's component, since the
    tree connects it)."""
    if a.grid.pr != 1 or a.grid.pc != 1:
        raise ValueError("device validator supports 1x1 grids; use "
                         "validate_bfs on fetched edges for meshes")
    p = parents.data.reshape(-1)[:a.nrows]
    root = jnp.asarray(root, jnp.int32)
    ok_root, ok_tree, ok_levels, ok_edge_levels, vis, depth = _vchecks(
        p, root, plan.crows[0, 0], plan.ccols[0, 0], plan.cstarts[0, 0],
        a.nnz.reshape(-1)[0], a.tile_n, int(a.cap).bit_length())
    # closure: one dense step from the visited set must stay inside it
    act = dv.realign(dv.DistVec(vis.reshape(1, -1), a.grid, ROW_AXIS,
                                a.nrows), COL_AXIS, block=a.tile_n,
                     fill=False).data
    reached = _dense_reach(a, plan, act) != _IDENT
    ok_closed = bool(np.asarray(
        jnp.all(~reached.reshape(-1)[:a.nrows] | vis)))
    assert bool(np.asarray(ok_root)), "root not its own parent"
    assert bool(np.asarray(ok_tree)), "tree edge not in graph"
    assert bool(np.asarray(ok_levels)), "parent pointers contain a cycle"
    assert bool(np.asarray(ok_edge_levels)), \
        "graph edge spans BFS levels differing by more than 1"
    assert ok_closed, "visited set not closed: != root's component"
    visited, nedges = run_stats(deg, parents)
    return {"visited": int(np.asarray(visited)),
            "depth": int(np.asarray(depth)),
            "nedges": int(np.asarray(nedges))}


@dataclasses.dataclass
class BfsRunStats:
    teps: list
    times: list
    visited: list
    # wall time of each dispatch->drain window and how many roots it
    # covered — the unit of genuine measurement on a tunneled TPU
    # (per-root arrival deltas are relay artifacts, see graph500_run)
    window_times: list = dataclasses.field(default_factory=list)
    window_sizes: list = dataclasses.field(default_factory=list)

    def summary(self) -> dict:
        teps = np.asarray(self.teps)
        q1, q3 = float(np.quantile(teps, 0.25)), float(np.quantile(teps, 0.75))
        return {
            "min_teps": float(teps.min()),
            "q1_teps": q1,
            "median_teps": float(np.median(teps)),
            "q3_teps": q3,
            "max_teps": float(teps.max()),
            "harmonic_mean_teps": float(1.0 / np.mean(1.0 / teps)),
            "mean_time": float(np.mean(self.times)),
            "n_windows": len(self.window_times),
        }


@obs.traced("graph500_run")
def graph500_run(grid: ProcGrid, scale: int, edgefactor: int = 16,
                 nroots: int = 16, seed: int = 1, cap_slack: float = 0.98,
                 validate: bool = False, validate_roots: int = 0,
                 alpha: int = 8, route: bool | str = "auto",
                 route_budget_s: float = 900.0, root_windows: int = 8,
                 mesh_kernel: str = "auto",
                 verbose: bool = False) -> BfsRunStats:
    """End-to-end Graph500 kernel-2 harness: generate R-MAT, build the
    symmetric adjacency matrix, run BFS from random roots, report TEPS
    (edges in the traversed component / time, per the reference's
    counting recipe — BASELINE.md notes). ``validate=True`` spec-checks
    every root; ``validate_roots=k`` checks the first k (validation is
    outside the timed region either way, like the reference's untimed
    kernel-2 verification, TopDownBFS.cpp:452-524)."""
    import time

    key = jax.random.key(seed)
    kgen, _ = jax.random.split(key)   # second stream kept for seed compat
    n = 1 << scale
    with obs.span("g500_generate", category="device_execute"):
        r, c = generate.rmat_edges(kgen, scale, edgefactor)
        r, c = generate.symmetrize(r, c)
        obs.sync(r)
    # initial cap is a guess from the average tile; from_global_coo
    # detects overflow against the true per-tile counts and re-plans
    # with an exact cap (no silent edge dropping under R-MAT skew)
    with obs.span("g500_build", category="device_execute"):
        a = dm.from_global_coo(S.LOR, grid, r, c,
                               jnp.ones_like(r, jnp.bool_), n, n,
                               cap=int(cap_slack * (r.shape[0] //
                                                    (grid.pr * grid.pc))))
        jax.block_until_ready(a.rows)
    if verbose:
        a.print_info("A")
    t_plan = time.perf_counter()
    with obs.span("g500_plan", category="host_compute"):
        plan = plan_bfs(a, route=route, route_budget_s=route_budget_s)
        jax.block_until_ready(plan.crows)
    if verbose:
        routed = plan.route_masks is not None
        print(f"plan: {time.perf_counter() - t_plan:.1f}s "
              f"(route={'benes' if routed else 'sort'})")

    # Root selection with deg>0, WITHOUT fetching the edge list: draw
    # candidate vertices on host, fetch only their (tiny) degree rows.
    # Everything big stays on device — the host<->TPU link is slow.
    deg = row_degrees(a)                      # (pr, tile_m) device
    rng_np = np.random.default_rng(seed + 1)
    roots: list[int] = []
    for _attempt in range(64):
        cand = rng_np.choice(n, size=min(n, 4 * nroots), replace=False)
        with obs.ledger.readback("bfs.degree_readback", 4 * len(cand)):
            dvals = np.asarray(deg.reshape(-1)[jnp.asarray(cand)])
        for v, dv_ in zip(cand, dvals):
            if dv_ > 0 and int(v) not in roots:
                roots.append(int(v))
                if len(roots) == nroots:
                    break
        if len(roots) == nroots:
            break
    else:
        raise ValueError(
            f"could not find {nroots} distinct roots with degree > 0 "
            f"(found {len(roots)}); lower nroots for this graph")

    if validate:
        validate_roots = len(roots)
    er = ec = None    # host edge copy, fetched only if a mesh validates
    if grid.pr == 1 and grid.pc == 1 or validate_roots == 0:
        r = c = None  # drop ~1 GB of device edge buffers at bench
        #               scales; the matrix + plan carry everything

    # the edge-space bit BFS is the fast path when it applies: routed
    # plan + single tile (symmetric adjacency — Graph500 graphs are),
    # or routed plan + square TPU mesh (the distributed variant, which
    # needs no symmetry). The mesh criterion is backend-aware — see
    # "Mesh BFS kernel dispatch (v5e decision memo)" in PARITY.md,
    # which records the measurements behind it: single-chip bit path
    # 2.4x faster than the stepper on TPU, but 3-5x SLOWER under
    # XLA-CPU's emulated word rolls, so CPU meshes (the correctness
    # rig) default to the stepper. ``mesh_kernel`` overrides for
    # profiling either path. NB: kernels take (a, plan, root) as ARGS —
    # closing over the committed matrix would inline it as jaxpr
    # constants (per-call re-upload / oversized HLO on remote TPUs).
    if mesh_kernel not in ("auto", "bits", "stepper"):
        raise ValueError(f"mesh_kernel must be 'auto', 'bits' or "
                         f"'stepper', got {mesh_kernel!r}")
    on_tpu = jax.default_backend() not in ("cpu", "gpu")
    if (plan.starts_bits is not None and grid.pr == 1 and grid.pc == 1
            and mesh_kernel != "stepper"):
        kernel = lambda a_, p_, r_: bfs_bits(a_, r_, p_)  # noqa: E731
        if verbose:
            print("kernel: edge-space bit BFS", flush=True)
    elif _bits_mesh_ok(a, plan) and (
            mesh_kernel == "bits" or (mesh_kernel == "auto" and on_tpu)):
        kernel = lambda a_, p_, r_: bfs_bits_mesh(a_, r_, p_)  # noqa: E731
        if verbose:
            print("kernel: distributed edge-space bit BFS", flush=True)
    elif mesh_kernel == "bits":
        # an explicit override that cannot be honored must not
        # silently measure the wrong kernel
        raise ValueError(
            "mesh_kernel='bits' requires a routed plan on a square "
            "mesh with square vertex blocks (_bits_mesh_ok)")
    else:
        kernel = lambda a_, p_, r_: bfs(a_, r_, p_, alpha=alpha)  # noqa: E731

    stats = BfsRunStats([], [], [])

    # ONE dispatch + ONE readback per timed root: the traversal and
    # its stats fuse into a single executable, and both stat scalars
    # come back in one transfer — each extra dispatch/readback costs
    # the full relay round trip (~85-120 ms) on tunneled TPUs, which
    # at scale 22 was ~40% of the per-root time
    @jax.jit  # analysis: allow(cache-key-unstable) one-shot bench harness closure, built once per run
    def run_with_stats(a_, plan_, deg_, rt_):
        parents = kernel(a_, plan_, rt_)
        visited_d, nedges_d = run_stats(deg_, parents)
        return parents, jnp.stack([visited_d, nedges_d])

    # the one executable the timed windows actually dispatch; async so
    # windows keep their overlap (the drain records the arrival wall)
    run_with_stats = obs.ledger.instrument(run_with_stats,
                                           "bfs.run_with_stats")

    # warm-up compile (not timed, like the reference's untimed iteration 0)
    with obs.span("g500_warmup", category="compile"):
        _ = np.asarray(run_with_stats(a, plan, deg, jnp.int32(roots[0]))[1])

    # Windowed per-root timing. A tunneled TPU pays a ~85-120 ms relay
    # round trip on every synchronous stats readback; timing
    # dispatch->readback per root adds that constant WAN latency to
    # every measurement (the reference's MPI_Wtime around each search
    # has no such link, TopDownBFS.cpp:437), and individual arrival
    # deltas are relay artifacts (results arrive in bursts). The unit
    # of genuine measurement is a WINDOW: the roots are split into
    # ``root_windows`` batches; each batch is dispatched back-to-back
    # with its 2-scalar stats on the async copy-back stream and the
    # [first-dispatch, last-arrival] wall time is recorded per batch.
    # Each batch pays ONE relay round trip (conservative: it inflates,
    # never deflates, the reported times), and the min/quartile/median/
    # harmonic statistics over batches are REAL spread — restoring the
    # Graph500 recipe's distribution reporting (TopDownBFS.cpp:452-524)
    # that a single all-roots window degenerates to one number.
    # Memory stays flat: parents buffers are dropped at dispatch
    # except for the validated roots.
    queue: list = []    # (root_idx, parents|None, stats)

    def dispatch(ri, root):
        p, vn = run_with_stats(a, plan, deg, jnp.int32(root))
        try:
            vn.copy_to_host_async()
        except Exception:
            pass                   # stream hint only; asarray still works
        keep_p = p if ri < validate_roots else None
        queue.append((ri, keep_p, vn))

    vparents: dict = {}
    nwin = max(1, min(root_windows, len(roots)))
    windows = np.array_split(np.arange(len(roots)), nwin)
    for wi, w in enumerate(windows):
        t0 = time.perf_counter()   # chip is idle (previous batch drained)
        # spans only bracket perf_counter calls — the timed window
        # gains no syncs and no measurable overhead from them
        with obs.span("bfs_window", size=len(w), window=wi):
            with obs.span("dispatch", category="dispatch"):
                for ri in w:
                    dispatch(int(ri), roots[int(ri)])
            per_root: list = []
            with obs.span("drain", category="host_readback"):
                while queue:
                    ri, kp, vn = queue.pop(0)
                    with obs.ledger.readback("bfs.stats_readback", 8):
                        vnv = np.asarray(vn)        # waits for arrival
                    per_root.append((ri, int(vnv[0]), int(vnv[1])))
                    if kp is not None:
                        vparents[ri] = kp
        t_win = time.perf_counter() - t0
        stats.window_times.append(t_win)
        stats.window_sizes.append(len(w))
        dt = t_win / max(1, len(per_root))
        for ri, visited, nedges in per_root:
            stats.teps.append(nedges / dt)
            stats.times.append(dt)
            stats.visited.append(visited)
            if verbose:
                print(f"root {int(roots[ri])}: {visited} visited, "
                      f"{nedges} edges, {dt*1e3:.1f} ms (window avg), "
                      f"{nedges/dt/1e6:.1f} MTEPS", flush=True)

    # validation (untimed, after the timed stream — kernel-2
    # verification is outside the clock either way)
    for ri in range(min(validate_roots, len(roots))):
        root = roots[ri]
        parents = vparents.pop(ri)
        if grid.pr == 1 and grid.pc == 1:
            validate_bfs_on_device(a, plan, root, parents, deg)
        else:
            if er is None:
                er, ec = np.asarray(r), np.asarray(c)
            validate_bfs(er, ec, n, int(root), parents.to_global())
    return stats
