"""Fill/bandwidth-reducing orderings: RCM and minimum degree.

Capability parity: Ordering/RCM.cpp:332 (pseudo-peripheral vertex
search by repeated level-BFS :361, then level-by-level ordering keyed
on (parent position, degree), reversed) and Ordering/MD.cpp (approximate
minimum-degree by repeated elimination, main :61).

TPU-native re-design: the O(nnz) work — level BFS waves and the
min-parent-position SpMV per level — runs distributed
(models.bfs_variants.bfs_levels and a Select2ndMin SpMSpV); the O(n)
per-level sorting and the MD elimination bookkeeping run on host
(the reference's distributed order-by-degree sort exists for
million-rank MPI jobs; a TPU host handles O(n log n) directly).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from combblas_tpu.ops import semiring as S
from combblas_tpu.models import bfs_variants as bv
from combblas_tpu.parallel import algebra as alg
from combblas_tpu.parallel import distmat as dm
from combblas_tpu.parallel import distvec as dv
from combblas_tpu.parallel import spmv as pspmv
from combblas_tpu.parallel.grid import COL_AXIS

_I32MAX = jnp.iinfo(jnp.int32).max


def _degrees(a: dm.DistSpMat) -> np.ndarray:
    return np.asarray(
        alg.reduce(S.PLUS, a.astype(jnp.int32), "row",
                   map_val=_one).to_global())


def _one(v):
    return jnp.ones_like(v)


def pseudo_peripheral_vertex(a: dm.DistSpMat,
                             start: int = 0) -> tuple[int, np.ndarray]:
    """(vertex, its level vector) with near-maximal eccentricity
    (≅ the George-Liu search in RCM.cpp:332): hop to a minimum-degree
    vertex of the farthest level until eccentricity stops growing."""
    deg = _degrees(a)
    v = int(start)
    ecc = -1
    best_v, best_levels = v, None
    for _ in range(a.nrows):
        lv = np.asarray(bv.bfs_levels(a, jnp.int32(v)).to_global())
        e = int(lv.max())
        if e <= ecc:
            break
        ecc = e
        best_v, best_levels = v, lv      # levels MUST match the vertex
        last = np.nonzero(lv == e)[0]
        v = int(last[np.argmin(deg[last])])
    return best_v, best_levels


def rcm(a: dm.DistSpMat) -> np.ndarray:
    """Reverse Cuthill-McKee permutation: perm[k] = old index of the
    k-th vertex in the new order (≅ RCM.cpp ordering semantics).
    Unreached vertices (other components) are appended by the same
    procedure from a fresh peripheral vertex.
    """
    n = a.nrows
    deg = _degrees(a)
    grid = a.grid
    tile_n = a.tile_n
    cpad = grid.pc * tile_n - n
    order = []
    done = np.zeros(n, bool)

    def min_parent_pos(pos, prev_mask):
        """Per vertex: min order-position over neighbors in the
        previous level (one Select2ndMin SpMSpV)."""
        vv = jnp.pad(jnp.asarray(pos, jnp.int32), (0, cpad),
                     constant_values=_I32MAX)
        aa = jnp.pad(jnp.asarray(prev_mask), (0, cpad),
                     constant_values=False)
        x = dv.DistSpVec(vv.reshape(grid.pc, tile_n),
                         aa.reshape(grid.pc, tile_n), grid, COL_AXIS, n)
        y = pspmv.spmsv(S.SELECT2ND_MIN_I32, a, x)
        return np.asarray(y.data.reshape(-1)[:n])

    while not done.all():
        comp_start = int(np.nonzero(~done)[0][0])
        v, levels = pseudo_peripheral_vertex(a, comp_start)
        maxlev = int(levels.max())
        pos = np.full(n, _I32MAX, np.int64)
        order.append(v)
        pos[v] = len(order) - 1
        done[v] = True
        prev = np.zeros(n, bool)
        prev[v] = True
        for d in range(1, maxlev + 1):
            cand = (levels == d) & ~done
            if not cand.any():
                continue
            pp = min_parent_pos(pos.clip(0, _I32MAX - 1), prev)
            idx = np.nonzero(cand)[0]
            key = np.lexsort((deg[idx], pp[idx]))
            for u in idx[key]:
                order.append(int(u))
                pos[u] = len(order) - 1
                done[u] = True
            prev = cand
    return np.asarray(order[::-1], np.int64)      # the Reverse in RCM


def bandwidth(dense: np.ndarray) -> int:
    r, c = np.nonzero(dense)
    return int(np.abs(r - c).max()) if len(r) else 0


def minimum_degree(a: dm.DistSpMat) -> np.ndarray:
    """Minimum-degree elimination order (≅ Ordering/MD.cpp:61).

    The elimination updates a host quotient-graph (adjacency sets) —
    the reference performs the analogous updates as distributed
    rank-1 matrix ops, which on a single-host mesh is strictly slower
    than the O(n + fill) set updates here.
    """
    n = a.nrows
    rows, cols, _ = dm.to_global_coo(a)
    adj = [set() for _ in range(n)]
    for r, c in zip(rows, cols):
        if r != c:
            adj[int(r)].add(int(c))
            adj[int(c)].add(int(r))
    alive = np.ones(n, bool)
    deg = np.array([len(adj[i]) for i in range(n)], np.int64)
    order = []
    for _ in range(n):
        cand = np.nonzero(alive)[0]
        v = int(cand[np.argmin(deg[cand])])
        order.append(v)
        alive[v] = False
        nbrs = [u for u in adj[v] if alive[u]]
        for u in nbrs:
            adj[u].discard(v)
        for i, u in enumerate(nbrs):         # clique the neighborhood
            for w in nbrs[i + 1:]:
                if w not in adj[u]:
                    adj[u].add(w)
                    adj[w].add(u)
        for u in nbrs:
            deg[u] = len(adj[u])
    return np.asarray(order, np.int64)
