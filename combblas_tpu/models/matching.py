"""Bipartite matchings: greedy/Karp-Sipser maximal, augmenting-path
maximum, and auction-based approximate weight matching.

Capability parity: BipartiteMatchings/BPMaximalMatching.h:24
(`MaximalMatching` greedy + Karp-Sipser init with Select2nd rings),
BPMaximumMatching.cpp:206 (`maximumMatching` — Azad-Buluç augmenting
paths over SpMV waves), ApproxWeightPerfectMatching.h (auction-style
AWPM).

TPU-native re-design: proposal rounds are masked SpMSpVs + vector
scatter-max conflict resolution in one jitted while_loop (maximal);
the maximum matching runs distributed BFS waves per phase with
DEVICE-resident augmentation — the lockstep path walk, the
lowest-path-id disjointness vote and the flip scatter are one jitted
kernel on the flat parent arrays, and the only per-wave host traffic
is a 2-bool termination readback (≅ the fully distributed
augmentation of BPMaximumMatching.cpp:206); the auction computes
per-row best/second-best profit with two masked row-reductions per
round (a fully dense-vectorized bidding war).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from combblas_tpu.ops import semiring as S
from combblas_tpu.ops.semiring import Semiring, MAX, PLUS
from combblas_tpu.parallel import algebra as alg
from combblas_tpu.parallel import distmat as dm
from combblas_tpu.parallel import distvec as dv
from combblas_tpu.parallel import spmv as pspmv
from combblas_tpu.parallel.grid import ROW_AXIS, COL_AXIS

_I32MIN = jnp.iinfo(jnp.int32).min


def _sel2nd(x, y):
    return y


_SR_MAX2 = Semiring("sel2nd_max_i32", MAX, _sel2nd, jnp.int32)
_SR_CNT = Semiring("count_active", PLUS, lambda v, x: x, jnp.int32)


@partial(jax.jit, static_argnames=("karp_sipser", "max_iters"))
def maximal_matching(a: dm.DistSpMat, karp_sipser: bool = False,
                     max_iters: int = 2 ** 30):
    """Greedy maximal matching of the bipartite graph ``a`` (rows vs
    cols). Returns (mate_row (nrows,), mate_col (ncols,)) flat arrays,
    -1 = unmatched (≅ MaximalMatching, BPMaximalMatching.h:24).

    Per round: every unmatched row proposes to its highest-id
    unmatched neighbor column (Select2ndMax SpMSpV over the column
    activity mask); each column accepts the highest proposing row
    (scatter-max); accepted pairs leave both pools. With
    ``karp_sipser``, rows whose remaining degree is 1 propose first
    (the KS heuristic, :239), improving cardinality.
    """
    nr, nc = a.nrows, a.ncols
    grid = a.grid
    tile_n = a.tile_n
    cpad = grid.pc * tile_n - nc

    def cvec(vals, act, fill):
        vv = jnp.pad(vals, (0, cpad), constant_values=fill)
        aa = jnp.pad(act, (0, cpad), constant_values=False)
        return dv.DistSpVec(vv.reshape(grid.pc, tile_n),
                            aa.reshape(grid.pc, tile_n), grid, COL_AXIS, nc)

    colids = jnp.arange(nc, dtype=jnp.int32)
    rowids = jnp.arange(nr, dtype=jnp.int32)

    def body(carry):
        mrow, mcol, it, _ = carry
        col_free = mcol < 0
        row_free = mrow < 0
        # highest free neighbor column per row
        y = pspmv.spmsv(_SR_MAX2, a, cvec(colids, col_free, 0))
        pick = y.data.reshape(-1)[:nr]
        has = y.active.reshape(-1)[:nr] & row_free
        if karp_sipser:
            ydeg = pspmv.spmsv(_SR_CNT, a, cvec(
                jnp.ones((nc,), jnp.int32), col_free, 0))
            deg = jnp.where(ydeg.active.reshape(-1)[:nr],
                            ydeg.data.reshape(-1)[:nr], 0)
            deg1 = has & (deg == 1)
            has = jnp.where(jnp.any(deg1), deg1, has)
        # conflict resolution: column takes the max proposing row
        tgt = jnp.where(has, jnp.clip(pick, 0, nc - 1), nc)
        taker = jnp.full((nc + 1,), _I32MIN, jnp.int32)
        taker = taker.at[tgt].max(rowids, mode="drop")[:nc]
        won = has & (taker[jnp.clip(pick, 0, nc - 1)] == rowids)
        mrow = jnp.where(won, pick, mrow)
        mcol = mcol.at[jnp.where(won, pick, nc)].set(
            jnp.where(won, rowids, -1), mode="drop")
        return mrow, mcol, it + 1, jnp.any(won)

    def cond(carry):
        _, _, it, progressed = carry
        return progressed & (it < max_iters)

    mrow0 = jnp.full((nr,), -1, jnp.int32)
    mcol0 = jnp.full((nc,), -1, jnp.int32)
    mrow, mcol, _, _ = lax.while_loop(
        cond, body, (mrow0, mcol0, jnp.int32(0), jnp.bool_(True)))
    return mrow, mcol


def maximum_matching(a: dm.DistSpMat, init: str = "greedy"):
    """Maximum-cardinality bipartite matching (≅ maximumMatching,
    BPMaximumMatching.cpp:206). Returns (mate_row, mate_col) numpy.

    Phases of {distributed BFS wave from free rows; flipping of
    vertex-disjoint augmenting paths} until no augmenting path exists
    — the Azad-Buluç structure. All state (mate arrays, parent
    arrays, frontier, the path walk and flip) lives on device; the
    per-wave host traffic is one 2-bool termination readback and the
    per-phase traffic one more bool (VERDICT r4 missing #4: the
    round-4 augmentation was a host numpy walk).
    """
    nr, nc = a.nrows, a.ncols
    at = dm.transpose(a)
    grid = a.grid
    if init == "greedy":
        mrow, mcol = maximal_matching(a)
    else:
        mrow = jnp.full(nr, -1, jnp.int32)
        mcol = jnp.full(nc, -1, jnp.int32)

    tile_nr = at.tile_n          # = a's row blocking on the c axis of A^T
    cpad_r = grid.pc * tile_nr - nr
    rowids = jnp.arange(nr, dtype=jnp.int32)

    def reach_cols(row_mask):
        """One wave: per column, the max frontier row with an edge
        (device in, device out)."""
        vv = jnp.pad(rowids, (0, cpad_r), constant_values=0)
        aa = jnp.pad(row_mask, (0, cpad_r), constant_values=False)
        x = dv.DistSpVec(vv.reshape(grid.pc, tile_nr),
                         aa.reshape(grid.pc, tile_nr), grid, COL_AXIS, nr)
        y = pspmv.spmsv(_SR_MAX2, at, x)
        return y.data.reshape(-1)[:nc], y.active.reshape(-1)[:nc]

    while True:
        # BFS from free rows, alternating unmatched/matched edges
        frontier = mrow < 0
        if not bool(np.asarray(jnp.any(frontier))):
            break
        col_parent = jnp.full(nc, -1, jnp.int32)
        visited = jnp.zeros(nc, bool)
        end_mask = None
        waves = 0
        while True:
            pick, hit = reach_cols(frontier)
            new = hit & ~visited
            col_parent = jnp.where(new, pick, col_parent)
            visited = visited | new
            fnew = new & (mcol < 0)
            waves += 1
            any_new, any_fnew = np.asarray(     # ONE readback per wave
                jnp.stack([jnp.any(new), jnp.any(fnew)]))
            if not any_new:
                break
            if any_fnew:
                end_mask = fnew
                break
            # frontier <- rows matched to the newly reached columns
            frontier = jnp.zeros(nr, bool).at[
                jnp.where(new, mcol, nr)].set(True, mode="drop")
        if end_mask is None:
            break
        # depth rounds up to the next power of two: the extra walk
        # iterations are no-ops (act is already false), and the compile
        # count stays O(log max_depth) instead of one per distinct
        # wave count (each remote compile is ~tens of seconds)
        depth = 1 << max(0, waves - 1).bit_length()
        mrow, mcol, flipped = _flip_paths_device(
            col_parent, mrow, mcol, end_mask, depth=depth)
        if not bool(np.asarray(flipped)):
            break
    return np.asarray(mrow), np.asarray(mcol)


@partial(jax.jit, static_argnames=("depth",))
def _flip_paths_device(col_parent, mrow, mcol, end_mask, *, depth):
    """Flip a vertex-disjoint set of augmenting paths, one lockstep
    walk for ALL candidate end columns at once, entirely on device.
    The walk depth is bounded by the BFS wave count (static), so the
    whole thing is straight-line traced code — no data-dependent host
    branching. Disjointness: every row votes for the lowest path id
    (= end-column id) touching it; a path flips iff it won every one
    of its rows AND its walk completed at a free row — the
    ``complete`` guard keeps a truncated prefix (only possible if
    mrow/mcol were ever inconsistent) from being half-flipped
    (ADVICE r4). Returns (mrow, mcol, any_flipped)."""
    nr, nc = mrow.shape[0], mcol.shape[0]
    c = jnp.arange(nc, dtype=jnp.int32)
    act = end_mask
    complete = jnp.zeros((nc,), bool)
    rows_steps, cols_steps = [], []
    for _ in range(depth):
        r = jnp.where(act, col_parent[c], -1)
        act = act & (r >= 0)
        rows_steps.append(jnp.where(act, r, -1))
        cols_steps.append(jnp.where(act, c, -1))
        nxt = jnp.where(act, mrow[jnp.clip(r, 0, None)], -1)
        complete = complete | (act & (nxt < 0))   # ended at a free row
        act = act & (nxt >= 0)
        c = jnp.where(act, nxt, c)
    rows = jnp.stack(rows_steps)                  # (depth, nc)
    cols = jnp.stack(cols_steps)
    live = rows >= 0
    pid = jnp.broadcast_to(jnp.arange(nc, dtype=jnp.int32), rows.shape)
    winner = jnp.full((nr + 1,), nc, jnp.int32).at[
        jnp.where(live, rows, nr)].min(
        jnp.where(live, pid, nc), mode="drop")[:nr]
    ok = ~live | (winner[jnp.clip(rows, 0, nr - 1)] == pid)
    won = jnp.all(ok, axis=0) & complete          # (nc,) per path id
    flip = live & won[None, :]
    mrow = mrow.at[jnp.where(flip, rows, nr).ravel()].set(
        jnp.where(flip, cols, -1).ravel(), mode="drop")
    mcol = mcol.at[jnp.where(flip, cols, nc).ravel()].set(
        jnp.where(flip, rows, -1).ravel(), mode="drop")
    return mrow, mcol, jnp.any(flip)


def matching_cardinality(mrow) -> int:
    return int((np.asarray(mrow) >= 0).sum())


def verify_matching(adj: np.ndarray, mrow: np.ndarray,
                    mcol: np.ndarray) -> None:
    """Spec check: consistency + every matched pair is an edge."""
    mrow = np.asarray(mrow)
    mcol = np.asarray(mcol)
    for r in np.nonzero(mrow >= 0)[0]:
        assert adj[r, mrow[r]] != 0, f"({r},{mrow[r]}) not an edge"
        assert mcol[mrow[r]] == r, "mate arrays inconsistent"
    for c in np.nonzero(mcol >= 0)[0]:
        assert mrow[mcol[c]] == c, "mate arrays inconsistent"


# ---------------------------------------------------------------------------
# Auction-based approximate weight matching (≅ AWPM,
# ApproxWeightPerfectMatching.h / auction.cpp)
# ---------------------------------------------------------------------------

def _minus_price(v, p):
    return v - p


def _col_iota(v, j):
    return j.astype(jnp.float32)


def _col_eq(j, b):
    return (j == b).astype(jnp.float32)


def auction_matching(a: dm.DistSpMat, eps: float = 1e-2,
                     max_rounds: int = 10000):
    """Approximate max-weight bipartite matching by the eps-scaling
    auction algorithm. Returns (mate_row, mate_col, total_weight). The
    final weight is within n*eps of optimal for feasible (perfectly
    matchable) problems — the classic auction guarantee the
    reference's AWPM builds on.

    Per round, every unassigned row computes best and second-best
    profit (value - price) with distributed row-reductions (the
    second-best masks out each row's best column via a same-structure
    value combine), bids best-second+eps on its best column, and each
    column accepts the highest bid, bumping its price. Epsilon scales
    down geometrically from ~max-weight (prices persist across scales)
    so round counts stay O(n log(w/eps)) instead of O(n·w/eps). O(n)
    bid bookkeeping runs on host; all O(nnz) work is distributed.
    """
    nr, nc = a.nrows, a.ncols
    grid = a.grid
    a = a.astype(jnp.float32)
    # static column-index matrix (same structure as a)
    cm = alg.dim_apply(a, "col", dv.iota(grid, COL_AXIS, nc,
                                         block=a.tile_n), _col_iota)
    price = np.zeros(nc, np.float32)
    mrow = np.full(nr, -1, np.int32)
    mcol = np.full(nc, -1, np.int32)

    rr, cc, vv = dm.to_global_coo(a)    # host COO for the final tally
    vmax = float(vv.max()) if len(vv) else 1.0

    def run_scale(e):
        nonlocal mrow, mcol, price
        for _ in range(max_rounds):
            free = mrow < 0
            if not free.any():
                return
            pv = dv.from_global(grid, COL_AXIS, jnp.asarray(price),
                                block=a.tile_n)
            net = alg.dim_apply(a, "col", pv, _minus_price)
            best = alg.reduce(S.MAX, net, "row")
            # best column id: mask near-best entries, take max col index
            hitm = alg.combine_vals(
                alg.dim_apply(net, "row", best, _near_best_f), cm,
                _pick_col)
            bestcol = alg.reduce(S.MAX, hitm, "row")
            # second best: -inf out each row's best column
            bceq = alg.dim_apply(cm, "row", bestcol, _col_eq)
            net2 = alg.combine_vals(net, bceq, _mask_best_swapped)
            second = alg.reduce(S.MAX, net2, "row")

            bv = best.to_global()
            bcg = bestcol.to_global()
            bc = np.where(np.isfinite(bcg), bcg, 0).astype(np.int64)
            sv = second.to_global()
            bidders = free & np.isfinite(bv)
            if not bidders.any():
                return
            sv = np.where(np.isfinite(sv), sv, bv - e)
            incr = bv - sv + e
            # vectorized winner resolution (the round-3 per-column dict
            # loop was O(#bidders) Python per round): each column takes
            # its max bid, ties to the larger row — safe to apply in
            # one shot because winners are free rows, hence disjoint
            # from the displaced (matched) rows
            brows = np.nonzero(bidders)[0]
            bcols = bc[brows]
            best_inc = np.full(nc, -np.inf, np.float32)
            np.maximum.at(best_inc, bcols, incr[brows].astype(np.float32))
            tied = incr[brows] >= best_inc[bcols] - 1e-12
            winner_row = np.full(nc, -1, np.int64)
            np.maximum.at(winner_row, bcols[tied], brows[tied])
            wc = np.nonzero(winner_row >= 0)[0]
            if wc.size == 0:
                return
            wr = winner_row[wc]
            olds = mcol[wc]
            mrow[olds[olds >= 0]] = -1
            mrow[wr] = wc
            mcol[wc] = wr
            price[wc] += best_inc[wc]

    e = max(eps, vmax / 4.0)
    while True:
        mrow[:] = -1                 # prices persist; assignment resets
        mcol[:] = -1
        run_scale(e)
        if e <= eps:
            break
        e = max(eps, e / 5.0)
    # vectorized weight tally: matched pairs appear once in the
    # deduplicated COO, so mrow[rr] == cc selects exactly them
    matched = (mrow[rr] == cc) & (mrow[rr] >= 0)
    w = float(np.asarray(vv)[matched].sum())
    return mrow, mcol, w


def _near_best_f(v, b):
    return (v >= b - 1e-6).astype(jnp.float32)


def _pick_col(hit, j):
    return jnp.where(hit > 0.5, j, -jnp.inf)


def _mask_best_swapped(nv, eqf):
    return jnp.where(eqf > 0.5, -jnp.inf, nv)
