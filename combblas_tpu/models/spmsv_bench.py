"""SpMSpV strategy benchmark — the kernel-comparison driver.

Capability parity: SpMSpV-IPDPS2017/SpMSpVBench.cpp (compares the
bucket / heapsort / SPA SpMSpV algorithms on a BFS workload with
cross-validation, :531-539).

TPU-native re-design: the competing strategies are the framework's
actual traversal kernels — the generic masked SpMSpV (parallel.spmv.
spmsv), each sparse push tier, and the dense full-scan stepper
(models.bfs.build_steppers) — timed on frontiers of increasing
density from a real R-MAT BFS, with every result cross-checked
against the dense stepper (the reference's `spy == spy_csc` pattern).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from combblas_tpu.models import bfs as B
from combblas_tpu.ops import generate
from combblas_tpu.ops import semiring as S
from combblas_tpu.parallel import distmat as dm
from combblas_tpu.parallel import distvec as dvec
from combblas_tpu.parallel import spmv as pspmv
from combblas_tpu.parallel.grid import ProcGrid, COL_AXIS

_IDENT = np.iinfo(np.int32).min


def run(grid: ProcGrid, scale: int = 14, edgefactor: int = 16,
        densities=(0.0005, 0.005, 0.05, 0.3), seed: int = 1,
        reps: int = 3, verbose: bool = True) -> list[dict]:
    """Time each SpMSpV strategy on random frontiers of the given
    densities; returns a list of result rows and cross-checks every
    strategy's parent candidates against the dense stepper."""
    n = 1 << scale
    r, c = generate.rmat_edges(jax.random.key(seed), scale, edgefactor)
    r, c = generate.symmetrize(r, c)
    a = dm.from_global_coo(S.LOR, grid, r, c, jnp.ones_like(r, jnp.bool_),
                           n, n)
    plan = B.plan_bfs(a)
    tiers, steppers = B.build_steppers(a, plan)
    names = [f"push_E{ec}" for ec, _ in tiers] + ["dense_scan"]
    rng = np.random.default_rng(seed)

    def spmsv_generic(act):
        xval = (jnp.arange(grid.pc, dtype=jnp.int32)[:, None] * a.tile_n
                + jnp.arange(a.tile_n, dtype=jnp.int32)[None, :])
        fr = dvec.DistSpVec(xval, act, grid, COL_AXIS, n)
        y = pspmv.spmsv(S.SELECT2ND_MAX_I32, a, fr)
        return jnp.where(y.active, y.data, _IDENT)

    results = []
    for dens in densities:
        flat = rng.random(grid.pc * a.tile_n) < dens
        flat[n:] = False
        act = jnp.asarray(flat.reshape(grid.pc, a.tile_n))
        golden = np.asarray(steppers[-1](act))
        cands = list(zip(names, steppers)) + [("spmsv_masked",
                                              spmsv_generic)]
        for name, fn in cands:
            # strategies with insufficient static budgets are skipped,
            # mirroring the switch's fit check
            if name.startswith("push_"):
                idx = names.index(name)
                ec, fc = tiers[idx]
                actdeg = np.einsum("ijk,jk->ij", np.asarray(plan.cdeg),
                                   flat.reshape(grid.pc, -1)
                                   .astype(np.int64))
                if actdeg.max() > ec or flat.reshape(
                        grid.pc, -1).sum(1).max() > fc:
                    continue
            out = fn(act)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fn(act)
                jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / reps
            got = np.asarray(out)
            np.testing.assert_array_equal(
                got.reshape(golden.shape), golden,
                err_msg=f"{name} disagrees at density {dens}")
            row = {"density": dens, "strategy": name, "ms": dt * 1e3,
                   "frontier": int(flat.sum())}
            results.append(row)
            if verbose:
                print(f"scale {scale} density {dens:<7} {name:<14} "
                      f"{dt * 1e3:8.2f} ms")
    return results


if __name__ == "__main__":
    run(ProcGrid.make())
