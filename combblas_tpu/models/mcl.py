"""MCL / HipMCL — Markov clustering by iterated pruned SpGEMM.

Capability parity: Applications/MCL.cpp (HipMCL :515: loop of
`MemEfficientSpGEMM` expansion :574, `Inflate` :447, `MakeColStochastic`
:390, `Chaos` convergence metric :408, `Interpret` cluster extraction
:373) and the per-phase `MCLPruneRecoverySelect` (ParFriends.h:186).

TPU-native re-design: the expansion step is the streaming phased SUMMA
(parallel.spgemm.spgemm_phased) with the prune/select/recovery hook
applied to each phase's column slice — columns of a phase slice are
true C columns, so the per-column semantics match the reference's
per-phase pruning exactly. Column statistics ride the distributed
Reduce; selection is the exact distributed Kselect1.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from combblas_tpu import obs
from combblas_tpu.obs import metrics as obm
from combblas_tpu.ops import semiring as S
from combblas_tpu.ops import tile_algebra as talg
from combblas_tpu.parallel import algebra as alg
from combblas_tpu.parallel import distmat as dm
from combblas_tpu.parallel import distvec as dv
from combblas_tpu.parallel import spgemm as spg
from combblas_tpu.models import cc as ccmod

_M_ITERS = obm.counter("mcl.iterations", "completed MCL iterations")
_M_CHAOS = obm.gauge("mcl.chaos", "chaos convergence metric per iteration")
_M_NNZ = obm.gauge("mcl.nnz", "iterated matrix nnz per iteration")


@dataclasses.dataclass(frozen=True)
class MclParams:
    """Clustering knobs (≅ HipMCL's ProcessParam, MCL.cpp:233-296).
    Frozen/hashable: the whole prune/select/recovery hook is jitted
    with the params as a static argument (one relay dispatch per
    expansion window instead of ~10 — each dispatch through a
    tunneled TPU costs ~0.3-0.5 s)."""
    inflation: float = 2.0          # -I
    prune_threshold: float = 1e-4   # -p  (cutoff below which entries drop)
    select: int = 1100              # -S  (max kept entries per column)
    recover_num: int = 1400         # -R  (recovery target per column)
    recover_pct: float = 0.9        # -pct (mass fraction triggering recovery)
    phases: Optional[int] = None    # -phases (None: auto from flop budget)
    phase_flop_budget: int = 2 ** 27
    #: -per-process-mem: per-device memory budget in GiB; when set it
    #: derives phase_flop_budget (≅ the auto-phase estimation from
    #: perProcessMemory, ParFriends.h:483-536). Each ESC expansion slot
    #: costs ~24 bytes through the sort (row+col+val in and out).
    per_process_mem_gb: Optional[float] = None
    max_iters: int = 100
    chaos_eps: float = 1e-3         # convergence threshold on chaos
    #: pin the iterated matrix's tile capacity to the first
    #: iteration's bucket (with headroom): the inflate/chaos/stochastic
    #: pipeline then compiles once instead of per capacity bucket.
    #: Honest measurement (scale 13, 1-core remote-compile host):
    #: 2117 s -> 1981 s (~6%) — the remaining wall time is the
    #: expansion/prune kernels recompiling per flops bucket, which
    #: genuinely shrinks as the matrix sparsifies. Kept on: strictly
    #: helps, and stabilizes shapes for long stable-phase runs.
    pin_caps: bool = True

    def effective_flop_budget(self, nproc: int = 1) -> int:
        """Phase flop budget. The memory knob is PER DEVICE while the
        phase count divides the GLOBAL flop total, so aggregate
        capacity scales with the device count (≅ the nprocs scaling in
        CalculateNumberOfPhases, ParFriends.h:733)."""
        if self.per_process_mem_gb is not None:
            return max(2 ** 20,
                       int(self.per_process_mem_gb * nproc * 2 ** 30 / 24))
        return self.phase_flop_budget


def _inv_or_zero(v):
    return jnp.where(v != 0, 1.0 / v, 0.0)


def _times(v, s):
    return v * s


@jax.jit
def make_col_stochastic(a: dm.DistSpMat) -> dm.DistSpMat:
    """Scale each column to sum 1 (≅ MakeColStochastic, MCL.cpp:390:
    Reduce(Column, plus) + safemultinv + DimApply)."""
    sums = alg.reduce(S.PLUS, a, "col")
    return alg.dim_apply(a, "col", sums.map(_inv_or_zero), _times)


# flight-recorder boundary: eager driver calls land in the dispatch
# ledger (sync=True so wall_s includes device wall); calls traced
# inside another jit (e.g. from `inflate`) pass straight through
make_col_stochastic = obs.instrument(
    make_col_stochastic, "mcl.make_col_stochastic", sync=True)


@jax.jit
def make_col_stochastic_block(bt):
    """`make_col_stochastic` on a BlockTile (the output of a
    block-planned expansion, e.g. `spgemm_phased(..., block_out=True)`):
    identical reduce + dim_apply pipeline through the tile_algebra
    format dispatch, staying in block form — no COO round-trip between
    expansion and inflation. The column sums use blocktile.reduce's
    canonical dense fold, so results are independent of the planner's
    (bm, bn) choice and bit-identical to the COO path for every
    order-insensitive monoid; float PLUS sums can differ from the COO
    chunked-scan grouping in the last ulp (same structure, same nnz)."""
    sums = talg.reduce(S.PLUS, bt, "col")
    return talg.dim_apply(bt, "col", _inv_or_zero(sums), _times)


make_col_stochastic_block = obs.instrument(
    make_col_stochastic_block, "mcl.make_col_stochastic_block", sync=True)


def _chaos_from(a: dm.DistSpMat):
    """Traced chaos expression, NaN-safe: an all-pruned (empty) column
    leaves colmax at the MAX identity (-inf) and colssq at 0 — the raw
    subtraction would be -inf (or NaN once an inf sneaks into the
    max/square pipeline) and poison the convergence test. Empty
    columns contribute chaos 0, matching the reference semantics of a
    converged (single-attractor) column."""
    colmax = alg.reduce(S.MAX, a, "col")
    colssq = alg.reduce(S.PLUS, a, "col", map_val=jnp.square)
    d = jnp.where(jnp.isfinite(colmax.data),
                  colmax.data - colssq.data, 0.0)
    return jnp.max(jnp.nan_to_num(d, nan=0.0, posinf=0.0, neginf=0.0))


@jax.jit
def _chaos_dev(a: dm.DistSpMat):
    return _chaos_from(a)


_chaos_dev = obs.instrument(_chaos_dev, "mcl.chaos_dev", sync=True)

_repin = obs.instrument(dm.with_capacity, "mcl.repin", sync=True)


def _update_cap_pin(cap_pin: Optional[int], mx: int,
                    ladder: "spg.CapLadder") -> int:
    """Cap-pin policy. A growth re-pin MUST mint its capacity through
    the run's CapLadder: the pre-r06 code computed a bare 1.25x/128
    bucket, so the next iteration's window planner re-planned against
    a stale rung set and cut fresh compile shapes every growth step."""
    if cap_pin is not None and mx <= cap_pin:
        return cap_pin
    want = -(-(mx * 5 // 4) // 128) * 128
    return ladder.fit(want, 128)


def chaos(a: dm.DistSpMat) -> float:
    """Convergence metric (≅ Chaos, MCL.cpp:408): max over columns of
    colMax - colSumOfSquares (0 when every column is a single 1). One
    fused dispatch + ONE scalar readback per call (a tunneled TPU
    pays ~100 ms per sync and ~0.3-0.5 s per dispatch)."""
    return float(np.asarray(_chaos_dev(a)))


@partial(jax.jit, static_argnames=("power",))
def inflate(a: dm.DistSpMat, power: float) -> dm.DistSpMat:
    """Hadamard power + re-normalization (≅ Inflate, MCL.cpp:447).
    Jitted with ``power`` static: the round-4 version rebuilt a
    ``partial(_pow, power=...)`` each call and passed it to the
    static-fn `alg.apply` — a fresh hash key, hence a full XLA
    recompile of the apply EVERY iteration (a large slice of the
    2117 s round-4 MCL wall time)."""
    powed = alg.apply(a, partial(_pow, power=power))
    return make_col_stochastic(powed)


def _pow(v, power):
    return jnp.power(v, power)


inflate = obs.instrument(inflate, "mcl.inflate", sync=True)


@partial(jax.jit, static_argnames=("power",))
def inflate_block(bt, power: float):
    """`inflate` on a BlockTile: Hadamard power over stored entries +
    block-form column re-normalization. With a block-planned expansion
    this keeps the whole expansion→inflate leg of an MCL mega-step in
    dense-block form; the conversion back to COO (if any) happens at
    the caller's phase boundary via `blocktile.from_blocks`."""
    powed = talg.apply(bt, partial(_pow, power=power))
    return make_col_stochastic_block(powed)


inflate_block = obs.instrument(inflate_block, "mcl.inflate_block",
                               sync=True)


def _repin_traced(a: dm.DistSpMat, new_cap: int) -> dm.DistSpMat:
    """Trace-safe `dm.with_capacity`: plain slice/concat (no
    device_put, no blocking fit check — the caller just read the tile
    counts and guarantees `new_cap` holds them). GSPMD propagates the
    operand sharding through the concat, so values and placement match
    the eager re-pin exactly."""
    if new_cap == a.cap:
        return a
    if new_cap < a.cap:
        return dm.DistSpMat(a.rows[:, :, :new_cap], a.cols[:, :, :new_cap],
                            a.vals[:, :, :new_cap], a.nnz, a.grid,
                            a.nrows, a.ncols, a.tile_m, a.tile_n)
    extra = new_cap - a.cap
    pr, pc = a.grid.pr, a.grid.pc
    rows = jnp.concatenate(
        [a.rows, jnp.full((pr, pc, extra), a.tile_m, jnp.int32)], axis=-1)
    cols = jnp.concatenate(
        [a.cols, jnp.full((pr, pc, extra), a.tile_n, jnp.int32)], axis=-1)
    vals = jnp.concatenate(
        [a.vals, jnp.zeros((pr, pc, extra), a.vals.dtype)], axis=-1)
    return dm.DistSpMat(rows, cols, vals, a.nnz, a.grid,
                        a.nrows, a.ncols, a.tile_m, a.tile_n)


def _megastep_body(a: dm.DistSpMat, *, power: float,
                   new_cap: Optional[int]):
    """Fused MCL iteration tail — re-pin + inflate (Hadamard power +
    column re-normalization) + chaos in ONE executable. The pre-r06
    loop issued these as four separate dispatches (repin, apply,
    stochastic, chaos) plus a blocking chaos readback; at ~0.3-0.5 s
    of tunnel latency per dispatch that glue dominated MCL's wall
    (the r05 63% residual). Returns (next_matrix, chaos_scalar); the
    caller reads the scalar DEFERRED, one iteration behind."""
    if new_cap is not None:
        a = _repin_traced(a, new_cap)
    powed = alg.apply(a, partial(_pow, power=power))
    a = make_col_stochastic(powed)
    return a, _chaos_from(a)


_megastep = jax.jit(_megastep_body, static_argnames=("power", "new_cap"),
                    donate_argnums=(0,))
_megastep = obs.instrument(_megastep, "mcl.megastep")
# donation audit: the donated matrix carry is what lets consecutive
# iterations run in-place. min_honored=1 (not full-leaf): a `new_cap`
# re-pin changes buffer shapes, so XLA can legally alias only the
# leaves whose layout survives — the audit asserts the carry is not
# SILENTLY copy-everything, not that every leaf aliases.
obs.memledger.declare_donation("mcl.megastep", (0,), min_honored=1)


@partial(jax.jit, static_argnames=("p",))
def mcl_prune_select_recover(c: dm.DistSpMat, p: MclParams) -> dm.DistSpMat:
    """Per-column prune/select/recovery (≅ MCLPruneRecoverySelect,
    ParFriends.h:186):

      1. drop entries below ``prune_threshold``;
      2. columns with more than ``select`` survivors keep only their
         top-``select`` values;
      3. columns whose surviving mass fell below ``recover_pct`` of the
         pre-prune mass relax back to their top-``recover_num`` values
         (recovery protects weakly-peaked columns from over-pruning).
    """
    mass0 = alg.reduce(S.PLUS, c, "col")
    # selection threshold: value of rank `select` per column (0 = none)
    sel_thr = alg.kselect1(c, p.select, fill=0.0)
    thr = sel_thr.map(partial(_floor_thr, floor=p.prune_threshold))
    pruned = alg.prune_column(c, thr, _lt)
    # recovery: columns whose kept mass dropped under recover_pct use
    # the (laxer) rank-recover_num threshold instead
    mass1 = alg.reduce(S.PLUS, pruned, "col")
    rec_thr = alg.kselect1(c, p.recover_num, fill=0.0)
    rec_thr = rec_thr.map(partial(_floor_thr, floor=0.0))
    need = dv.ewise_apply(mass1, mass0, partial(_needs_recovery,
                                                pct=p.recover_pct))
    thr2 = dv.ewise_apply(need, dv.ewise_apply(rec_thr, thr, _pack2),
                          _select_thr)
    return alg.prune_column(c, thr2, _lt)


mcl_prune_select_recover = obs.instrument(
    mcl_prune_select_recover, "mcl.prune_select_recover", sync=True)


def _floor_thr(v, floor):
    return jnp.maximum(v, floor)


def _lt(v, s):
    return v < s


def _needs_recovery(kept, orig, pct):
    return (orig > 0) & (kept < pct * orig)


def _pack2(a, b):
    # pack two f32 thresholds; complex trick avoided: stack on new axis
    return jnp.stack([a, b], axis=-1)


def _select_thr(need, packed):
    return jnp.where(need, packed[..., 0], packed[..., 1])


def mcl(a: dm.DistSpMat, params: MclParams = MclParams(),
        verbose: bool = False,
        cap_ladder: Optional[spg.CapLadder] = None, *,
        checkpoint_path: Optional[str] = None,
        checkpoint_every: int = 0,
        resume: bool = False,
        ) -> tuple[dv.DistVec, int, int]:
    """Cluster the graph ``a`` (≅ HipMCL, MCL.cpp:515). Returns
    (cluster labels r-aligned, #clusters, #iterations).

    Pipeline: add self-loops, column-normalize, then iterate
    {expand via phased pruned SpGEMM, inflate} until chaos < eps;
    interpret the attractor matrix by connected components of its
    support (≅ Interpret, MCL.cpp:373).

    ``cap_ladder``: pre-seeded `spg.CapLadder` (e.g. `CapLadder.load`
    of a previous run's rungs) — a warm ladder mints zero new rungs,
    so a repeat run re-traces/re-compiles zero expansion shapes. The
    ladder is mutated in place; callers can `save()` it afterwards.

    ``checkpoint_path``/``checkpoint_every``: persist the loop carry
    (iterated matrix, pinned capacity, ladder rungs, iteration count)
    through `resilience.checkpoint` every N iterations, at the loop
    head — exactly the state the loop holds entering iteration `it`.
    ``resume=True`` restarts from the newest complete checkpoint at
    the path (skipping setup); a resumed run walks the same iteration
    sequence as the uninterrupted one, so labels, cluster count and
    total iterations match. No complete checkpoint -> cold start.
    """
    if a.nrows != a.ncols:
        raise ValueError("mcl needs a square adjacency matrix")
    if checkpoint_every and not checkpoint_path:
        raise ValueError("checkpoint_every needs a checkpoint_path")
    with obs.span("mcl"):
        return _mcl_instrumented(a, params, verbose, cap_ladder,
                                 checkpoint_path=checkpoint_path,
                                 checkpoint_every=checkpoint_every,
                                 resume=resume)


#: per-nnz (flops, local bytes) models for the mcl.* ledger names —
#: pass counts over the 12-byte COO slots each executable streams
#: (megastep = repin + inflate + stochastic + chaos fused)
_MCL_COSTS = {
    "mcl.make_col_stochastic": (2.0, 24.0),
    "mcl.make_col_stochastic_block": (2.0, 24.0),
    "mcl.inflate": (4.0, 24.0),
    "mcl.inflate_block": (4.0, 24.0),
    "mcl.chaos_dev": (4.0, 12.0),
    "mcl.repin": (0.0, 24.0),
    "mcl.megastep": (8.0, 48.0),
    "mcl.prune_select_recover": (8.0, 60.0),
}


def _annotate_mcl_costs(nnz: int) -> None:
    """Cost-model registration for one MCL run, from the post-setup
    nnz (prune shrinks nnz monotonically, so this is a per-call upper
    bound — efficiency reads as a floor)."""
    for name, (f, lb) in _MCL_COSTS.items():
        obs.costmodel.annotate(name, flops=f * nnz, lbytes=lb * nnz)
    obs.costmodel.annotate("mcl.cap_readback", lbytes=4.0)
    obs.costmodel.annotate("mcl.chaos_deferred", lbytes=4.0)


def _mcl_instrumented(a, params, verbose, cap_ladder=None, *,
                      checkpoint_path=None, checkpoint_every=0,
                      resume=False):
    from combblas_tpu.resilience import checkpoint as ckpt_mod
    # span taxonomy per iteration (≅ MCL.cpp's printed per-iteration
    # stats): `mcl_expand` is structural — its children are the phased
    # SpGEMM driver's plan/window/sort spans plus the cap-pin readback
    # — so the expansion's dispatch/readback glue (the round-5 63%
    # mystery) shows up as named categories + an explicit residual
    grid = a.grid
    nproc = grid.pr * grid.pc
    # ONE capacity ladder for the whole run: iteration 1 (the largest —
    # prune shrinks nnz monotonically) mints the rungs; iterations 2..N
    # reuse them and hit the jit cache (VERDICT r4 missing #1: the
    # round-4 run spent ~90% of 2117 s in per-iteration recompiles)
    ladder = spg.CapLadder() if cap_ladder is None else cap_ladder
    it0 = 0
    cap_pin0 = None
    meta = (ckpt_mod.read_meta(checkpoint_path)
            if resume and checkpoint_path else None)
    if meta is not None and meta.get("solver") == "mcl":
        # resume: the checkpointed matrix IS the post-setup loop carry
        # entering iteration `it` — skip setup, re-seed the ladder so
        # every re-planned expansion lands on the original rungs
        with obs.span("mcl_resume", category="host_readback"):
            a, meta = ckpt_mod.load_mcl(S.PLUS, grid, checkpoint_path)
        it0 = int(meta.get("it", 0))
        cap_pin0 = meta.get("cap_pin")
        for r in meta.get("rungs", []):
            if int(r) not in ladder.rungs:
                ladder.rungs.append(int(r))
        ladder.rungs.sort()
    else:
        with obs.span("mcl_setup", category="device_execute"):
            a = a.astype(jnp.float32)
            a = alg.add_loops(a, 1.0)
            a = make_col_stochastic(a)
            obs.sync(a.vals)
    _annotate_mcl_costs(a.getnnz())
    hook = partial(mcl_prune_select_recover, p=params)
    ckpt = ((checkpoint_path, int(checkpoint_every), ladder)
            if checkpoint_path and checkpoint_every else None)
    if spg.sync_windows_enabled():
        a, it = _mcl_loop_sync(a, params, verbose, hook, ladder, nproc,
                               ckpt=ckpt, it0=it0, cap_pin0=cap_pin0)
    else:
        a, it = _mcl_loop_fused(a, params, verbose, hook, ladder, nproc,
                                ckpt=ckpt, it0=it0, cap_pin0=cap_pin0)
    with obs.span("mcl_interpret", category="device_execute"):
        labels, nclusters = interpret(a)
        obs.sync(labels.data)
    return labels, nclusters, it


def _maybe_checkpoint(ckpt, a, cap_pin, it, it0) -> None:
    """Loop-head checkpoint: persists (a, cap_pin, it) when the cadence
    lands on `it` (skipping the iteration we just resumed at — nothing
    new to say). The matrix fetch is a blocking host readback, so it is
    declared to the dispatch ledger like any other sync point."""
    if ckpt is None:
        return
    path, every, ladder = ckpt
    if it <= it0 or it % every != 0:
        return
    from combblas_tpu.resilience import checkpoint as ckpt_mod
    with obs.span("mcl_checkpoint", category="host_readback"), \
            obs.ledger.readback("mcl.checkpoint", int(a.cap) * 12):
        ckpt_mod.save_mcl(path, a, it=it, cap_pin=cap_pin,
                          rungs=ladder.rungs)


def _mcl_loop_sync(a, params, verbose, hook, ladder, nproc, *,
                   ckpt=None, it0=0, cap_pin0=None):
    """The r05 unfused reference loop (COMBBLAS_TPU_SYNC_WINDOWS=1):
    separate repin/inflate/chaos dispatches, blocking chaos readback
    every iteration. Kept as the fused mega-step's bit-exactness
    oracle (same env var gates the blocking window loop underneath)."""
    ch = float("inf")
    it = it0
    cap_pin = cap_pin0
    while ch > params.chaos_eps and it < params.max_iters:
        _maybe_checkpoint(ckpt, a, cap_pin, it, it0)
        with obs.span("mcl_expand", it=it):
            a = spg.spgemm_phased(
                S.PLUS_TIMES_F32, a, a, phases=params.phases,
                phase_flop_budget=params.effective_flop_budget(nproc),
                prune_hook=hook, cap_ladder=ladder)
            if params.pin_caps:
                # one host readback per iteration; the first (largest)
                # iteration usually sets the bucket — MCL's nnz shrinks
                # after pruning — but a later growth simply re-pins
                with obs.span("cap_readback", category="host_readback"), \
                        obs.ledger.readback("mcl.cap_readback", 4):
                    mx = int(np.asarray(a.nnz).max())
                cap_pin = _update_cap_pin(cap_pin, mx, ladder)
                with obs.span("repin", category="device_execute"):
                    a = _repin(a, cap_pin)
                    obs.sync(a.vals)
                _M_NNZ.set(mx)
            else:
                with obs.span("drain", category="device_execute"):
                    obs.sync(a.vals)
        with obs.span("mcl_inflate", category="device_execute", it=it):
            a = inflate(a, params.inflation)
            obs.sync(a.vals)
        with obs.span("mcl_chaos", category="host_readback", it=it):
            ch = chaos(a)
        it += 1
        _M_ITERS.inc()
        _M_CHAOS.set(ch)
        if verbose:
            print(f"mcl iter {it}: chaos {ch:.6f}, nnz {a.getnnz()}")
    return a, it


def _resolve_chaos(pending) -> float:
    ch_dev, handle = pending
    with obs.span("mcl_chaos", category="host_readback"), \
            handle.resolve():
        return float(np.asarray(ch_dev))


def _mcl_loop_fused(a, params, verbose, hook, ladder, nproc, *,
                    ckpt=None, it0=0, cap_pin0=None):
    """The async fused loop (default since r06): one `mcl.megastep`
    dispatch replaces the repin/inflate/stochastic/chaos tail, and the
    chaos scalar is read DEFERRED — enqueued after the mega-step,
    consumed at the head of the NEXT iteration (by then it's been home
    for a full expansion's worth of device time, so the resolve is
    free). Checking iteration k's chaos before iteration k+1's
    expansion is exactly the reference loop's `while ch > eps`
    ordering, so iteration counts (and everything downstream) are
    bit-identical.

    Checkpoints (when armed) land at the loop head AFTER the pending
    chaos is resolved and the continue decision is made: the persisted
    state (a, cap_pin, it, pending=None) is byte-for-byte the state a
    resumed loop constructs before its first expansion, which is what
    makes resume bit-exact by construction rather than by luck."""
    it = it0
    cap_pin = cap_pin0
    pending = None      # (chaos device scalar, deferred ledger handle)
    while it < params.max_iters:
        if pending is not None:
            ch = _resolve_chaos(pending)
            pending = None
            _M_CHAOS.set(ch)
            if verbose:
                print(f"mcl iter {it}: chaos {ch:.6f}")
            if not ch > params.chaos_eps:
                break
        _maybe_checkpoint(ckpt, a, cap_pin, it, it0)
        with obs.span("mcl_expand", it=it):
            a = spg.spgemm_phased(
                S.PLUS_TIMES_F32, a, a, phases=params.phases,
                phase_flop_budget=params.effective_flop_budget(nproc),
                prune_hook=hook, cap_ladder=ladder)
            new_cap = None
            nnz_host = None
            if params.pin_caps:
                # the ONE blocking readback the loop keeps: the re-pin
                # capacity is a static shape, so the host must know the
                # counts before it can dispatch the mega-step. Read the
                # whole nnz grid once — it also feeds the verbose print
                # (getnnz() would be a second blocking fetch).
                with obs.span("cap_readback", category="host_readback"), \
                        obs.ledger.readback("mcl.cap_readback", 4):
                    nnz_host = np.asarray(a.nnz)
                mx = int(nnz_host.max())
                cap_pin = _update_cap_pin(cap_pin, mx, ladder)
                if cap_pin != a.cap:
                    new_cap = cap_pin
                _M_NNZ.set(mx)
        with obs.span("mcl_megastep", category="dispatch", it=it):
            a, ch_dev = _megastep(a, power=params.inflation,
                                  new_cap=new_cap)
            try:
                ch_dev.copy_to_host_async()
            except AttributeError:      # pragma: no cover - old jax
                pass
            pending = (ch_dev,
                       obs.ledger.readback_deferred("mcl.chaos_deferred", 4))
        it += 1
        _M_ITERS.inc()
        if verbose and nnz_host is not None:
            print(f"mcl iter {it}: nnz {int(nnz_host.sum())} "
                  f"(chaos deferred)")
    if pending is not None:
        # max_iters exit: resolve the in-flight chaos for metrics
        ch = _resolve_chaos(pending)
        _M_CHAOS.set(ch)
        if verbose:
            print(f"mcl iter {it}: chaos {ch:.6f}")
    return a, it


def interpret(a: dm.DistSpMat) -> tuple[dv.DistVec, int]:
    """Extract clusters: connected components of the attractor
    matrix's symmetrized support (≅ Interpret, MCL.cpp:373)."""
    sym = alg.ewise_apply(a, dm.transpose(a), _add2, allow_a_null=True,
                          allow_b_null=True)
    return ccmod.connected_components(sym)


def _add2(x, y):
    return x + y
