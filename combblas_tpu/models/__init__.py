"""Graph applications built on the distributed primitives
(≅ the reference's Applications/ tree)."""
