"""Graph applications built on the distributed primitives
(≅ the reference's Applications/ tree): Graph500 direction-optimizing
BFS + variants (random-parent, min/max policy, filtered/semantic),
FastSV connected components, MCL/HipMCL clustering, betweenness
centrality, Luby (filtered) MIS, bipartite matchings (maximal greedy /
Karp-Sipser, maximum augmenting-path, auction AWPM), and RCM/minimum-
degree orderings."""
