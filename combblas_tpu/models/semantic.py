"""Semantic (attributed, filtered) graphs.

Capability parity: `SemanticGraph` (SemanticGraph.h — SpParMat over an
attributed edge type + a filter predicate, KDT-style) and the
TwitterEdge pattern (TwitterEdge.h:15: edge attributes consulted
inside the semiring multiply; FilteredBFS.cpp's on-the-fly vs
materialized filter comparison).

TPU-native re-design: the attribute IS the matrix value (any dtype —
e.g. a float timestamp); the predicate composes into the traversal
semirings (models.bfs_variants / models.mis already accept ``pred``).
`materialize()` bakes the filter into the sparsity for the
comparison path the reference benchmarks.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax

from combblas_tpu.models import bfs_variants as bv
from combblas_tpu.models import mis as mi
from combblas_tpu.parallel import algebra as alg
from combblas_tpu.parallel import distmat as dm
from combblas_tpu.parallel import distvec as dvec


@dataclasses.dataclass(frozen=True)
class SemanticGraph:
    """An edge-attributed graph + an edge filter predicate."""

    matrix: dm.DistSpMat                 # values = edge attributes
    pred: Callable                       # attr -> keep? (traceable)

    def bfs(self, root, policy: str = "max", key=None) -> dvec.DistVec:
        """Filtered BFS: only edges passing the predicate are
        traversed (≅ LatestRetwitterBFS, FilteredBFS.cpp:401)."""
        return bv.bfs_select(self.matrix, root, policy=policy, key=key,
                             pred=self.pred)

    def levels(self, root) -> dvec.DistVec:
        return bv.bfs_levels(self.matrix, root, pred=self.pred)

    def mis(self, key) -> dvec.DistVec:
        """Filtered MIS (≅ FilteredMIS.cpp)."""
        return mi.mis(self.matrix, key, pred=self.pred)

    def materialize(self) -> dm.DistSpMat:
        """Bake the filter into the sparsity (the reference's
        materialized-filter comparison path, FilteredBFS.cpp)."""
        pred = self.pred
        return alg.prune(self.matrix, _NegatedPred(pred))


class _NegatedPred:
    """Hashable wrapper so the jitted prune caches on the predicate
    object rather than retracing per lambda."""

    def __init__(self, pred):
        self.pred = pred

    def __call__(self, v):
        import jax.numpy as jnp
        return jnp.logical_not(self.pred(v))

    def __hash__(self):
        return hash(self.pred)

    def __eq__(self, other):
        return isinstance(other, _NegatedPred) and self.pred == other.pred
