"""Maximal independent set — Luby's algorithm (and the edge-filtered
variant over attributed graphs).

Capability parity: Applications/FilteredMIS.cpp:432 (Luby MIS by
random-value min over neighbors via SpMV, iterative removal; the
"filtered" part evaluates an edge predicate inside the semiring).

TPU-native re-design: one jitted `lax.while_loop`; per round, each
candidate draws a random priority, an SpMV takes the min priority over
*candidate* neighbors, and vertices beating every neighbor join the
set; winners' neighborhoods leave the candidate pool via a second
boolean SpMV. No host round-trips until convergence.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from combblas_tpu.ops import semiring as S
from combblas_tpu.ops.semiring import Semiring, MIN, LOR
from combblas_tpu.parallel import distmat as dm
from combblas_tpu.parallel import distvec as dv
from combblas_tpu.parallel import spmv as pspmv
from combblas_tpu.parallel.grid import ROW_AXIS, COL_AXIS

_F32MAX = jnp.finfo(jnp.float32).max


def _sel2nd(x, y):
    return y


def _filtered_semiring(pred, monoid):
    """multiply(edge_attr, x) = x where pred(edge_attr) else identity —
    the reference's semantic-graph trick of evaluating the edge filter
    inside the multiply (TwitterEdge.h / FilteredMIS edge filter)."""
    def mul(attr, x):
        keep = pred(attr)
        return jnp.where(keep, x, monoid.identity(x.dtype))
    return Semiring(f"filtered_{monoid.name}", monoid, mul)


@partial(jax.jit, static_argnames=("max_iters", "pred"))
def mis(a: dm.DistSpMat, key, max_iters: int = 1000,
        pred=None) -> dv.DistVec:
    """Boolean r-aligned membership vector of a maximal independent
    set of the symmetric graph ``a``. ``pred`` (on edge values)
    restricts the conflict graph to edges passing the filter
    (≅ FilteredMIS's semantic edges)."""
    if a.nrows != a.ncols:
        raise ValueError("mis needs a square symmetric adjacency matrix")
    from combblas_tpu.parallel import algebra as _alg
    # a self-loop would make a vertex its own conflict neighbor and
    # lock it out of the set forever; the reference's drivers strip
    # loops in preprocessing (FilteredMIS), here it's built in
    a = _alg.remove_loops(a)
    n = a.nrows
    grid = a.grid
    tile_m, tile_n = a.tile_m, a.tile_n
    rpad = grid.pr * tile_m - n
    cpad = grid.pc * tile_n - n

    keep_pred = pred if pred is not None else _always
    sr_min = _filtered_semiring(keep_pred, MIN)
    sr_or = _filtered_semiring(keep_pred, LOR)

    def to_cvec(flat, fill):
        return jnp.pad(flat, (0, cpad),
                       constant_values=fill).reshape(grid.pc, tile_n)

    def body(carry):
        in_set, cand, key, it = carry
        key, sub = jax.random.split(key)
        prio = jax.random.uniform(sub, (n,), jnp.float32, 1e-6, 1.0)
        prio = jnp.where(cand, prio, _F32MAX)
        # min candidate-neighbor priority
        x = dv.DistSpVec(to_cvec(prio, _F32MAX), to_cvec(cand, False),
                         grid, COL_AXIS, n)
        nbr_min = pspmv.spmsv(sr_min, a, x)
        nm = nbr_min.data.reshape(-1)[:n]
        nm = jnp.where(nbr_min.active.reshape(-1)[:n], nm, _F32MAX)
        winners = cand & (prio < nm)
        in_set = in_set | winners
        # winners' neighborhoods leave the pool
        wv = dv.DistSpVec(to_cvec(winners, False), to_cvec(winners, False),
                          grid, COL_AXIS, n)
        covered = pspmv.spmsv(sr_or, a, wv)
        cov = covered.active.reshape(-1)[:n] & \
            covered.data.reshape(-1)[:n].astype(bool)
        cand = cand & ~winners & ~cov
        return in_set, cand, key, it + 1

    def cond(carry):
        _, cand, _, it = carry
        return jnp.any(cand) & (it < max_iters)

    in0 = jnp.zeros((n,), bool)
    cand0 = jnp.ones((n,), bool)
    in_set, _, _, _ = lax.while_loop(
        cond, body, (in0, cand0, key, jnp.int32(0)))
    data = jnp.pad(in_set, (0, rpad)).reshape(grid.pr, tile_m)
    return dv.DistVec(data, grid, ROW_AXIS, n)


def _always(v):
    return jnp.ones(jnp.shape(v), bool)


def verify_mis(adj: np.ndarray, member: np.ndarray) -> None:
    """Host-side spec check: independence + maximality."""
    n = adj.shape[0]
    m = member.astype(bool)
    assert not (adj[np.ix_(m, m)] != 0).any(), "set not independent"
    # maximality: every non-member has a member neighbor
    nonm = ~m
    has_nbr = (adj[:, m] != 0).any(1)
    assert (has_nbr | m)[nonm].all(), "set not maximal"
