"""BFS variants: parent-selection policies, level BFS, and filtered
(semantic-graph) BFS.

Capability parity: Applications/RandomParentBFS.cpp (a random priority
rides the semiring; add = min-by-priority, :92-117),
SingleChildBFS.cpp (SelectMaxSRing traversal with discovered-pruning,
:116), FilteredBFS.cpp + TwitterEdge.h:15 (edge-attribute predicate
evaluated inside the semiring multiply — the SemanticGraph concept,
SemanticGraph.h), and the level/distance computation every ordering
app uses (RCM.cpp's SpMV<SelectMinSR> level loop :361).

TPU-native re-design: all variants share one jitted while_loop over
the masked SpMSpV; the parent policy is the reduction monoid (max /
min / min-random-priority with an inverse-permutation decode), and the
edge filter composes into the multiply. These run the clean SpMSpV
path — the tuned Graph500 kernel stays in models.bfs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from combblas_tpu.ops import semiring as S
from combblas_tpu.ops.semiring import Semiring, MAX, MIN
from combblas_tpu.parallel import distmat as dm
from combblas_tpu.parallel import distvec as dv
from combblas_tpu.parallel import spmv as pspmv
from combblas_tpu.parallel.grid import ROW_AXIS, COL_AXIS

NO_PARENT = -1
_I32MAX = jnp.iinfo(jnp.int32).max
_I32MIN = jnp.iinfo(jnp.int32).min


def _sel2nd(x, y):
    return y


def _filtered_sel2nd(pred, monoid):
    def mul(attr, x):
        return jnp.where(pred(attr), x, monoid.identity(x.dtype))
    return Semiring("filtered_sel2nd", monoid, mul)


@partial(jax.jit, static_argnames=("policy", "pred", "max_iters"))
def bfs_select(a: dm.DistSpMat, root, *, policy: str = "max",
               key=None, pred=None, max_iters: int = 2 ** 30):
    """Parents vector under a parent-selection ``policy``:

      * "max"    — highest-id parent wins (SelectMaxSRing; ≅ TopDown/
                   SingleChild traversals)
      * "min"    — lowest-id parent wins (SelectMinSRing1)
      * "random" — uniformly random parent among the frontier
                   neighbors (≅ RandomParentBFS's priority semiring):
                   ids are encoded through a random permutation, the
                   min *priority* wins, and the inverse permutation
                   decodes the winner. Needs ``key``.

    ``pred`` (on edge values) makes this a filtered/semantic BFS
    (≅ FilteredBFS: only edges passing the predicate are traversed).
    Returns an r-aligned parents DistVec (NO_PARENT = unreached).
    """
    if a.nrows != a.ncols:
        raise ValueError("bfs needs a square matrix")
    n = a.nrows
    grid = a.grid
    tile_m, tile_n = a.tile_m, a.tile_n
    rpad = grid.pr * tile_m - n
    cpad = grid.pc * tile_n - n
    root = jnp.asarray(root, jnp.int32)

    if policy == "random":
        if key is None:
            raise ValueError("policy='random' needs a PRNG key")
        perm = jax.random.permutation(key, n).astype(jnp.int32)
        inv = jnp.zeros((n,), jnp.int32).at[perm].set(
            jnp.arange(n, dtype=jnp.int32))
        encode = lambda ids: perm[jnp.clip(ids, 0, n - 1)]
        decode = lambda y: inv[jnp.clip(y, 0, n - 1)]
        monoid, ident = MIN, _I32MAX
    elif policy == "min":
        encode = decode = lambda ids: ids
        monoid, ident = MIN, _I32MAX
    elif policy == "max":
        encode = decode = lambda ids: ids
        monoid, ident = MAX, _I32MIN
    else:
        raise ValueError(f"unknown policy {policy!r}")

    keep = pred if pred is not None else None
    sr = (_filtered_sel2nd(keep, monoid) if keep is not None
          else Semiring(f"sel2nd_{monoid.name}", monoid, _sel2nd))

    ids = jnp.arange(n, dtype=jnp.int32)

    def body(carry):
        parents, act, it, _ = carry
        xval = jnp.pad(encode(ids), (0, cpad), constant_values=ident)
        x = dv.DistSpVec(xval.reshape(grid.pc, tile_n),
                         act.reshape(grid.pc, tile_n), grid, COL_AXIS, n)
        y = pspmv.spmsv(sr, a, x)
        yflat = y.data.reshape(-1)[:n]
        # freshness from the reduced VALUE, not the raw hit mask: with
        # an edge filter, a vertex whose frontier edges all fail the
        # predicate still registers a hit but reduces to the identity
        hit = y.active.reshape(-1)[:n] & (yflat != ident)
        fresh = hit & (parents == NO_PARENT)
        parents = jnp.where(fresh, decode(yflat), parents)
        act_new = jnp.pad(fresh, (0, cpad), constant_values=False)
        return parents, act_new, it + 1, jnp.any(fresh)

    def cond(carry):
        _, _, it, cont = carry
        return cont & (it < max_iters)

    parents0 = jnp.full((n,), NO_PARENT, jnp.int32).at[root].set(root)
    act0 = jnp.zeros((n + cpad,), bool).at[root].set(True)
    parents, _, _, _ = lax.while_loop(
        cond, body, (parents0, act0, jnp.int32(0), jnp.bool_(True)))
    data = jnp.pad(parents, (0, rpad), constant_values=NO_PARENT)
    return dv.DistVec(data.reshape(grid.pr, tile_m), grid, ROW_AXIS, n)


@partial(jax.jit, static_argnames=("pred", "max_iters"))
def bfs_levels(a: dm.DistSpMat, root, pred=None,
               max_iters: int = 2 ** 30) -> dv.DistVec:
    """Distance-in-hops vector (-1 = unreached) — the level loop RCM
    and the matchings build on (≅ RCM.cpp:361's SelectMinSR SpMV)."""
    if a.nrows != a.ncols:
        raise ValueError("bfs needs a square matrix")
    n = a.nrows
    grid = a.grid
    tile_m, tile_n = a.tile_m, a.tile_n
    rpad = grid.pr * tile_m - n
    cpad = grid.pc * tile_n - n
    root = jnp.asarray(root, jnp.int32)

    sr = (_filtered_sel2nd(pred, S.LOR) if pred is not None
          else S.BOOL_OR_AND)

    def body(carry):
        level, act, d, _ = carry
        x = dv.DistSpVec(act.reshape(grid.pc, tile_n),
                         act.reshape(grid.pc, tile_n), grid, COL_AXIS, n)
        y = pspmv.spmsv(sr, a, x)
        hit = y.active.reshape(-1)[:n] & y.data.reshape(-1)[:n].astype(bool)
        fresh = hit & (level < 0)
        level = jnp.where(fresh, d + 1, level)
        act_new = jnp.pad(fresh, (0, cpad), constant_values=False)
        return level, act_new, d + 1, jnp.any(fresh)

    def cond(carry):
        _, _, d, cont = carry
        return cont & (d < max_iters)

    level0 = jnp.full((n,), -1, jnp.int32).at[root].set(0)
    act0 = jnp.zeros((n + cpad,), bool).at[root].set(True)
    level, _, _, _ = lax.while_loop(
        cond, body, (level0, act0, jnp.int32(0), jnp.bool_(True)))
    data = jnp.pad(level, (0, rpad), constant_values=-1)
    return dv.DistVec(data.reshape(grid.pr, tile_m), grid, ROW_AXIS, n)
