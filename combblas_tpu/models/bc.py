"""Betweenness centrality — batched Brandes over SpMM.

Capability parity: Applications/BetwCent.cpp:146-230 (batched Brandes:
forward BFS-DAG construction via `PSpGEMM<PTBOOLINT>` on root batches
with `SubsRefCol`, per-level fringe stack, backward dependency tally
with `EWiseMult` and dense updates).

TPU-native re-design: a batch of roots is one dense (n, batch)
multi-vector, so the forward wave and the backward tally are SpMM
calls (parallel.densemat.spmm) — the reference's boolean SpGEMM on an
n×batch sparse fringe matrix becomes a dense batched SpMV riding
contiguous lanes; level masks are stored as a stack of dense bit
planes (the BFS-DAG stack of BetwCent.cpp:171).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from combblas_tpu.ops import semiring as S
from combblas_tpu.parallel import distmat as dm
from combblas_tpu.parallel import distvec as dv
from combblas_tpu.parallel import densemat as dn
from combblas_tpu.parallel.grid import ROW_AXIS, COL_AXIS


def _to_cmv(y: dn.DistMultiVec, a: dm.DistSpMat) -> dn.DistMultiVec:
    return dn.mv_realign(y, COL_AXIS, block=a.tile_n)


def bc_batch(a: dm.DistSpMat, at: dm.DistSpMat,
             roots: Sequence[int]) -> np.ndarray:
    """Partial BC scores (n,) from one batch of source vertices.

    Forward: nsp (shortest-path counts) grows level by level via
    A^T-SpMM on the current fringe; level masks are stacked. Backward:
    dependencies delta accumulate via A-SpMM of (1+delta)/nsp masked to
    the deeper level (the Brandes tally; ≅ BetwCent.cpp:181-219).
    Host-side level loop (depth is data-dependent); each level is one
    jitted distributed SpMM.
    """
    n = a.nrows
    b = len(roots)
    roots = np.asarray(roots, np.int64)

    nsp0 = np.zeros((n, b), np.float32)
    nsp0[roots, np.arange(b)] = 1.0
    nsp = dn.mv_from_global(a.grid, ROW_AXIS, nsp0)
    fringe = nsp
    visited = nsp0 != 0
    levels = []                                   # per-level (n,b) masks

    while True:
        y = dn.spmm(S.PLUS_TIMES_F32, at, _to_cmv(fringe, at))
        yg = y.to_global()
        fresh = (yg != 0) & ~visited
        if not fresh.any():
            break
        visited |= fresh
        levels.append(fresh)
        fg = np.where(fresh, yg, 0.0)
        nspg = nsp.to_global() + fg
        nsp = dn.mv_from_global(a.grid, ROW_AXIS, nspg)
        fringe = dn.mv_from_global(a.grid, ROW_AXIS, fg)

    nspg = nsp.to_global()
    inv_nsp = np.where(nspg != 0, 1.0 / np.maximum(nspg, 1e-30), 0.0)
    delta = np.zeros((n, b), np.float32)
    for d in range(len(levels) - 1, -1, -1):
        wd = levels[d]
        t1 = np.where(wd, (1.0 + delta) * inv_nsp, 0.0)
        t2 = dn.spmm(S.PLUS_TIMES_F32, a,
                     _to_cmv(dn.mv_from_global(a.grid, ROW_AXIS, t1), a)
                     ).to_global()
        pred_mask = levels[d - 1] if d > 0 else (nsp0 != 0)
        delta += np.where(pred_mask, nspg * t2, 0.0)

    # a root's own accumulation row is excluded from its column's tally
    delta[roots, np.arange(b)] = 0.0
    return delta.sum(1)


def betweenness_centrality(a: dm.DistSpMat, batch_size: int = 16,
                           sources: Optional[Sequence[int]] = None,
                           normalize: bool = False) -> np.ndarray:
    """BC scores for a directed graph ``a`` (boolean adjacency,
    a[i,j]=1 for edge i->j). ``sources=None`` runs every vertex as a
    source (exact BC); a subset gives the approximate batched variant
    the reference's CLI exposes (BetwCent.cpp main). Returns host (n,)
    scores (≅ the reference gathers them for output too)."""
    n = a.nrows
    a = a.astype(jnp.float32)       # bool adjacency -> arithmetic 0/1
    at = dm.transpose(a)
    srcs = np.arange(n) if sources is None else np.asarray(sources)
    scores = np.zeros(n, np.float32)
    for lo in range(0, len(srcs), batch_size):
        scores += bc_batch(a, at, srcs[lo:lo + batch_size])
    if normalize and n > 2:
        scores /= (n - 1) * (n - 2)
    return scores
