"""Betweenness centrality — batched Brandes over SpMM.

Capability parity: Applications/BetwCent.cpp:146-230 (batched Brandes:
forward BFS-DAG construction via `PSpGEMM<PTBOOLINT>` on root batches
with `SubsRefCol`, per-level fringe stack, backward dependency tally
with `EWiseMult` and dense updates).

TPU-native re-design: a batch of roots is one dense (n, batch)
multi-vector, so the forward wave and the backward tally are SpMM
calls (parallel.densemat.spmm) — the reference's boolean SpGEMM on an
n×batch sparse fringe matrix becomes a dense batched SpMV riding
contiguous lanes; level masks are stored as a stack of dense bit
planes (the BFS-DAG stack of BetwCent.cpp:171).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from combblas_tpu.ops import semiring as S
from combblas_tpu.parallel import distmat as dm
from combblas_tpu.parallel import distvec as dv
from combblas_tpu.parallel import densemat as dn
from combblas_tpu.parallel.grid import ROW_AXIS, COL_AXIS


def _to_cmv(y: dn.DistMultiVec, a: dm.DistSpMat) -> dn.DistMultiVec:
    return dn.mv_realign(y, COL_AXIS, block=a.tile_n)


@jax.jit
def _bc_fwd(y, visited, nsp):
    """One forward-level update on the r-aligned (nb, block, batch)
    layouts: fresh mask (bit-packed for the level stack — an unpacked
    bool plane per level is O(n*batch*diameter) HBM, which OOMs on
    high-diameter graphs), visited/nsp accumulation, next fringe, and
    the termination scalar — all device-side."""
    fresh = (y != 0) & ~visited
    fg = jnp.where(fresh, y, jnp.zeros((), y.dtype))
    return (jnp.packbits(fresh, axis=1), visited | fresh, nsp + fg, fg,
            jnp.any(fresh))


@jax.jit
def _bc_bwd_pre_packed(wd_packed, delta, inv_nsp):
    wd = jnp.unpackbits(wd_packed, axis=1,
                        count=delta.shape[1]).astype(bool)
    return jnp.where(wd, (1.0 + delta) * inv_nsp, 0.0)


@jax.jit
def _bc_bwd_post_packed(delta, pred_packed, nsp, t2):
    """Post step with the pred mask unpacked INSIDE the same dispatch
    (a separate unpack call would be one more ~0.3-0.5 s relay round
    trip per backward level). Delegates to `_bc_bwd_post` so the
    Brandes tally formula exists once; the nested jit inlines."""
    pred = jnp.unpackbits(pred_packed, axis=1,
                          count=delta.shape[1]).astype(bool)
    return _bc_bwd_post(delta, pred, nsp, t2)


@jax.jit
def _bc_bwd_post(delta, pred, nsp, t2):
    return delta + jnp.where(pred, nsp * t2, jnp.zeros((), t2.dtype))


def bc_batch(a: dm.DistSpMat, at: dm.DistSpMat,
             roots: Sequence[int]) -> np.ndarray:
    """Partial BC scores (n,) from one batch of source vertices.

    Forward: nsp (shortest-path counts) grows level by level via
    A^T-SpMM on the current fringe; level masks are stacked. Backward:
    dependencies delta accumulate via A-SpMM of (1+delta)/nsp masked to
    the deeper level (the Brandes tally; ≅ BetwCent.cpp:181-219).
    Host-side level loop (depth is data-dependent), but ALL state —
    nsp, fringe, visited, the level-mask stack, delta — stays on
    device across levels (≅ the reference keeping everything
    distributed, BetwCent.cpp:146-230); the only per-level host sync
    is the 1-byte termination scalar. The round-4 version round-
    tripped the full (n, batch) multivector through the host twice
    per level — ~100 ms relay latency + n·batch·4 B of WAN transfer
    each way on a tunneled TPU (VERDICT r4 weak #2).
    """
    n = a.nrows
    b = len(roots)
    roots = np.asarray(roots, np.int64)
    grid = a.grid

    nsp0 = np.zeros((n, b), np.float32)
    nsp0[roots, np.arange(b)] = 1.0
    nsp = dn.mv_from_global(grid, ROW_AXIS, nsp0)
    root_mask = nsp.map(lambda d: d != 0)         # device (root, col) bits
    fringe = nsp
    visited = root_mask.data
    levels = []              # per-level device (nb, blk/8, b) bit-packed

    while True:
        y = dn.spmm(S.PLUS_TIMES_F32, at, _to_cmv(fringe, at))
        fresh, visited, nsp_d, fg, any_fresh = _bc_fwd(
            y.data, visited, nsp.data)
        if not bool(np.asarray(any_fresh)):       # one scalar per level
            break
        nsp = dataclasses.replace(nsp, data=nsp_d)
        fringe = dataclasses.replace(nsp, data=fg)
        levels.append(fresh)

    inv_nsp = jnp.where(nsp.data != 0,
                        1.0 / jnp.maximum(nsp.data, 1e-30), 0.0)
    delta = jnp.zeros_like(nsp.data)
    for d in range(len(levels) - 1, -1, -1):
        t1 = _bc_bwd_pre_packed(levels[d], delta, inv_nsp)
        t2 = dn.spmm(S.PLUS_TIMES_F32, a,
                     _to_cmv(dataclasses.replace(nsp, data=t1), a))
        if d > 0:
            delta = _bc_bwd_post_packed(delta, levels[d - 1], nsp.data,
                                        t2.data)
        else:
            delta = _bc_bwd_post(delta, root_mask.data, nsp.data, t2.data)

    # a root's own accumulation row is excluded from its column's tally
    delta = jnp.where(root_mask.data, 0.0, delta)
    flat = delta.sum(-1).reshape(-1)[:n]          # ONE final readback
    return np.asarray(flat)


def betweenness_centrality(a: dm.DistSpMat, batch_size: int = 16,
                           sources: Optional[Sequence[int]] = None,
                           normalize: bool = False) -> np.ndarray:
    """BC scores for a directed graph ``a`` (boolean adjacency,
    a[i,j]=1 for edge i->j). ``sources=None`` runs every vertex as a
    source (exact BC); a subset gives the approximate batched variant
    the reference's CLI exposes (BetwCent.cpp main). Returns host (n,)
    scores (≅ the reference gathers them for output too)."""
    n = a.nrows
    a = a.astype(jnp.float32)       # bool adjacency -> arithmetic 0/1
    at = dm.transpose(a)
    srcs = np.arange(n) if sources is None else np.asarray(sources)
    scores = np.zeros(n, np.float32)
    for lo in range(0, len(srcs), batch_size):
        scores += bc_batch(a, at, srcs[lo:lo + batch_size])
    if normalize and n > 2:
        scores /= (n - 1) * (n - 2)
    return scores
