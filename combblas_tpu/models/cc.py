"""Connected components: FastSV (and the LACC-style hooking variant).

Capability parity: Applications/FastSV.cpp + FastSV.h:25-377 (the
Zhang-Azad-Buluç FastSV algorithm: Select2ndMin SpMV + stochastic
hooking + aggressive hooking + shortcutting, iterated to fixpoint)
and the `LabelCC` relabeling (FastSV.h:56).

TPU-native re-design: the parent vector f lives as one flat (n,)
int32 array inside a single jitted `lax.while_loop` — vectors are
O(n), tiny next to the matrix, so the reference's distributed
Assign/Extract vector machinery (CC.h:420-1018) collapses to
gathers/scatter-mins on the logical view, while the O(nnz) work (the
min-over-neighbors step) stays a distributed semiring SpMV over the
mesh. Zero host round-trips until convergence.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from combblas_tpu import obs
from combblas_tpu.ops import tile as tl
from combblas_tpu.ops import semiring as S
from combblas_tpu.parallel import distmat as dm
from combblas_tpu.parallel import distvec as dvec
from combblas_tpu.parallel import spmv as pspmv
from combblas_tpu.parallel.grid import ROW_AXIS, COL_AXIS

_I32MAX = jnp.iinfo(jnp.int32).max


def fastsv(a: dm.DistSpMat, max_iters: int = 100, *,
           checkpoint_path: str | None = None,
           checkpoint_every: int = 0,
           resume: bool = False) -> dvec.DistVec:
    """Component labels (min vertex id per component) of the symmetric
    graph ``a``; one jitted while_loop (≅ FastSV.h:25-377).

    Per iteration:
      1. mngf[u] = min over neighbors v of gf[v]   (Select2ndMin SpMV)
      2. stochastic hooking:  f[f[u]] <- min(f[f[u]], mngf[u])
      3. aggressive hooking:  f[u]    <- min(f[u],    mngf[u])
      4. shortcutting:        f[u]    <- min(f[u],    gf[u])
      5. gf = f[f];  converged when gf stops changing.

    On square meshes (pr == pc > 1) this dispatches to the SHARDED
    implementation (`_fastsv_sharded`): the parent vector is carried
    as O(n/p) pieces per device, with the reference's FullyDist
    two-level alignment and request-routed Assign/Extract
    (CC.h:420-1018) — see its docstring. Elsewhere (single tile,
    non-square grids) the parent array rides the while_loop as a flat
    replicated (n,) int32 — O(n) vertex state per device, fine
    through scale ~24 but contradicting the hypersparse scaling story
    above that (VERDICT r4 weak #3).

    ``checkpoint_path``/``checkpoint_every``: run the CHUNKED driver
    instead — `checkpoint_every` iterations per jitted chunk, the
    `(f, gf)` carry persisted through `resilience.checkpoint` between
    chunks, ``resume=True`` continuing from the newest complete
    checkpoint. The chunked driver always runs on the replicated
    substrate (bit-identical to the sharded one — cross-checked in
    tests since the shard round); chunk boundaries only cut the
    while_loop, so labels match the single-shot run exactly.
    """
    if a.nrows != a.ncols:
        raise ValueError(
            f"fastsv needs a square symmetric adjacency matrix, got "
            f"{a.nrows}x{a.ncols}")
    if checkpoint_path and checkpoint_every:
        return _fastsv_checkpointed(a, max_iters, checkpoint_path,
                                    int(checkpoint_every), resume)
    if a.grid.pr == a.grid.pc and a.grid.pr > 1 and a.tile_m == a.tile_n:
        if not isinstance(a.nnz, jax.core.Tracer):  # eager dispatches only
            _register_fastsv_collectives(a)
        return _fastsv_sharded(a, max_iters=max_iters)
    return _fastsv_replicated(a, max_iters=max_iters)


def _register_fastsv_collectives(a: dm.DistSpMat) -> None:
    """Register one ITERATION's collective descriptors for the sharded
    FastSV kernel with the mesh observatory.  The fixpoint loop runs a
    data-dependent number of iterations inside ``lax.while_loop``, so
    (like the bits-mesh BFS drivers) the registered set describes ONE
    body iteration and budgets/mesh.json does not band the drift ratio
    for cc.* names.  Per-device all_to_all payload = the (q-1)/q
    off-device fraction of the (q, blk) bucket matrix."""
    q = a.grid.pr
    blk = -(-a.tile_m // q)
    both = ROW_AXIS + COL_AXIS
    a2a = (q - 1) * 4 * blk
    descs = (
        # min_neighbor: transpose + column gather of the gf pieces
        dict(collective="ppermute", axis=both, dtype="int32",
             shape=(blk,), rung=0, bytes=4 * blk),
        dict(collective="all_gather", axis=ROW_AXIS, dtype="int32",
             shape=(q, blk), rung=1, bytes=a2a),
        dict(collective="pmin", axis=COL_AXIS, dtype="int32",
             shape=(a.tile_m,), rung=2, bytes=4 * a.tile_m),
        # stochastic hooking: request routing + mesh-row reduce
        dict(collective="all_to_all", axis=ROW_AXIS, dtype="int32",
             shape=(q, blk), rung=3, bytes=a2a),
        dict(collective="all_to_all", axis=ROW_AXIS, dtype="int32",
             shape=(q, blk), rung=4, bytes=a2a),
        dict(collective="pmin", axis=COL_AXIS, dtype="int32",
             shape=(a.tile_m,), rung=5, bytes=4 * a.tile_m),
        # pointer jumping: row slice + query/response routing
        dict(collective="all_gather", axis=COL_AXIS, dtype="int32",
             shape=(q, blk), rung=6, bytes=a2a),
        dict(collective="all_to_all", axis=ROW_AXIS, dtype="int32",
             shape=(q, blk), rung=7, bytes=a2a),
        dict(collective="all_to_all", axis=ROW_AXIS, dtype="int32",
             shape=(q, blk), rung=8, bytes=a2a),
        # convergence vote
        dict(collective="pmax", axis=both, dtype="int32",
             shape=(), rung=9, bytes=4),
    )
    obs.meshobs.register_collectives("cc.fastsv_sharded", descs)
    # predicted ICI bytes for ONE body iteration, so the drift join is
    # non-null for cc.* too; the measured/predicted ratio then counts
    # fixpoint iterations (which is why budgets do not band it)
    obs.costmodel.annotate("cc.fastsv_sharded",
                           cbytes=float(sum(d["bytes"] for d in descs)),
                           calls=1)
    annz = np.asarray(a.nnz)  # analysis: allow(sync-in-async) plan-time, once per driver call
    obs.meshobs.register_device_loads("cc.fastsv_sharded", nnz=annz)


def _replicated_fns(a: dm.DistSpMat, max_iters: int):
    """The replicated-parent iteration as (body, cond) while_loop fns
    over carry (f, gf, it, changed) — shared by the single-shot
    `_fastsv_replicated` and the chunked checkpoint driver so the two
    trace literally the same math."""
    n = a.nrows
    grid = a.grid
    tile_n = a.tile_n
    cpad = grid.pc * tile_n - n

    def to_cvec(flat):
        data = jnp.pad(flat, (0, cpad), constant_values=_I32MAX)
        return dvec.DistVec(data.reshape(grid.pc, tile_n), grid,
                            COL_AXIS, n)

    def min_neighbor_gf(gf):
        x = to_cvec(gf)
        y = pspmv.spmv(S.SELECT2ND_MIN_I32, a, x)   # r-aligned (pr, tile_m)
        return y.data.reshape(-1)[:n]               # isolated rows: INT32_MAX

    def body(carry):
        f, gf, it, _ = carry
        mngf = min_neighbor_gf(gf)
        # 2) stochastic hooking onto the (old) parent
        tgt = jnp.clip(f, 0, n - 1)
        f = f.at[tgt].min(mngf)
        # 3) aggressive hooking + 4) shortcutting
        f = jnp.minimum(f, jnp.minimum(mngf, gf))
        # 5) pointer jumping
        gf_new = f[jnp.clip(f, 0, n - 1)]
        changed = jnp.any(gf_new != gf)
        return f, gf_new, it + 1, changed

    def cond(carry):
        _, _, it, changed = carry
        return changed & (it < max_iters)

    return body, cond


def _emit_rvec(a: dm.DistSpMat, f) -> dvec.DistVec:
    """Final full path compression + row-axis DistVec emission (shared
    tail of the replicated paths: f is within one jump of the root at
    convergence; one more composition makes labels exact roots)."""
    n = a.nrows
    f = f[jnp.clip(f, 0, n - 1)]
    rpad = a.grid.pr * a.tile_m - n
    data = jnp.pad(f, (0, rpad), constant_values=_I32MAX)
    return dvec.DistVec(data.reshape(a.grid.pr, a.tile_m), a.grid,
                        ROW_AXIS, n)


@partial(jax.jit, static_argnames=("max_iters",))
def _fastsv_replicated(a: dm.DistSpMat, max_iters: int = 100) -> dvec.DistVec:
    """Replicated-parent FastSV (see `fastsv`)."""
    if a.nrows != a.ncols:
        raise ValueError(
            f"fastsv needs a square symmetric adjacency matrix, got "
            f"{a.nrows}x{a.ncols}")
    n = a.nrows
    body, cond = _replicated_fns(a, max_iters)
    f0 = jnp.arange(n, dtype=jnp.int32)
    f, _, _, _ = lax.while_loop(cond, body,
                                (f0, f0, jnp.int32(0), jnp.bool_(True)))
    return _emit_rvec(a, f)


@partial(jax.jit, static_argnames=("max_iters",))
def _fastsv_chunk(a: dm.DistSpMat, f, gf, max_iters: int):
    """Up to `max_iters` replicated FastSV iterations from an arbitrary
    (f, gf) carry: the chunked checkpoint driver's unit of device work.
    Returns (f, gf, iters_done, changed) — NO final compression (the
    carry must round-trip a checkpoint byte-exactly)."""
    body, cond = _replicated_fns(a, max_iters)
    return lax.while_loop(cond, body,
                          (f, gf, jnp.int32(0), jnp.bool_(True)))


def _fastsv_checkpointed(a, max_iters, path, every, resume):
    """Chunked FastSV with persisted carry (see `fastsv`)."""
    from combblas_tpu.resilience import checkpoint as ckpt_mod
    n = a.nrows
    grid = a.grid
    it_done = 0
    f = gf = None
    if resume:
        meta = ckpt_mod.read_meta(path)
        if meta is not None and meta.get("solver") == "fastsv":
            with obs.span("fastsv_resume", category="host_readback"):
                f, gf, meta = ckpt_mod.load_fastsv(grid, path)
            it_done = int(meta.get("it", 0))
    if f is None:
        f = jnp.arange(n, dtype=jnp.int32)
        gf = f
    changed = True
    while changed and it_done < max_iters:
        k = min(every, max_iters - it_done)
        f, gf, dit, ch = _fastsv_chunk(a, f, gf, max_iters=k)
        with obs.ledger.readback("cc.chunk_readback", 8):
            it_done += int(np.asarray(dit))
            changed = bool(np.asarray(ch))
        if changed and it_done < max_iters:
            with obs.span("fastsv_checkpoint", category="host_readback"), \
                    obs.ledger.readback("cc.checkpoint", 8 * n):
                ckpt_mod.save_fastsv(path, grid, f, gf,
                                     it=it_done, glen=n)
    return _emit_rvec(a, f)


@partial(jax.jit, static_argnames=("max_iters",))
def _fastsv_sharded(a: dm.DistSpMat, max_iters: int = 100) -> dvec.DistVec:
    """FastSV with the parent vector SHARDED to O(n/p) per device
    (VERDICT r4 weak #3 / next-round #9) — the reference's FullyDist
    design carried over whole: each device owns one of p = q² pieces
    of f, laid out so row slice i is the concatenation of row i's
    pieces (FullyDist.h:63-77 two-level distribution). Per iteration,
    inside ONE shard_map'd while_loop:

      * SpMV input alignment = transpose-ppermute + all_gather along
        the mesh column (TransposeVector ParFriends.h:1388 +
        AllGatherVector :1430), an O(n/q) transient;
      * stochastic hooking (f[f[u]] <- min) = request routing: each
        device buckets its (target, value) pairs by owner row slice,
        ONE all_to_all along the row axis delivers them (bucket
        capacity = piece size, exact: a device has only blk pairs),
        owners scatter-min into their row-slice accumulator, pmin
        across the mesh row completes it (≅ the Assign/ReduceAssign
        machinery, CC.h:420-1018);
      * pointer jumping (gf = f[f]) = the same routing as a
        request/response pair: queries out, f-lookups back, two
        all_to_alls (≅ Extract, CC.h:700).

    Carried state is O(n/p); transients are O(n/q) = O(n/√p), the
    same order as every SpMV's gathered input slice. Results are
    bit-identical to `_fastsv_replicated` (cross-checked in tests).
    Requires a square mesh with square vertex blocks (the reference
    requires square grids everywhere, CommGrid.h:44).
    """
    n = a.nrows
    grid = a.grid
    q = grid.pr
    tile_m, tile_n, cap = a.tile_m, a.tile_n, a.cap
    blk = -(-tile_m // q)                     # piece size: O(n/p)
    tpairs = [(i * q + j, j * q + i) for i in range(q) for j in range(q)]

    def kernel(rows, cols, vals, nnz):
        i = lax.axis_index(ROW_AXIS)
        j = lax.axis_index(COL_AXIS)
        t = tl.Tile(rows[0, 0], cols[0, 0], vals[0, 0], nnz[0, 0],
                    tile_m, tile_n)
        starts, seg_ends, nonempty = tl.row_structure(t)
        colsc = jnp.clip(t.cols, 0, tile_n - 1)
        tvalid = t.valid()
        # my piece: local slots [j*blk, j*blk+blk) of row slice i
        loc = j * blk + jnp.arange(blk, dtype=jnp.int32)
        piece_ok = loc < tile_m               # q*blk may overhang tile_m
        gids = i * tile_m + jnp.clip(loc, 0, tile_m - 1)

        def row_slice(x_p):
            """(blk,) pieces -> my full row slice (tile_m,): gather
            row i's pieces along the mesh row."""
            g = lax.all_gather(x_p, COL_AXIS)            # (q, blk)
            return g.reshape(-1)[:tile_m]

        def col_slice(x_p):
            """(blk,) pieces -> my full column slice (tile_n,):
            transpose-exchange then gather along the mesh column."""
            xt = lax.ppermute(x_p, (ROW_AXIS, COL_AXIS), tpairs)
            g = lax.all_gather(xt, ROW_AXIS)             # (q, blk)
            return g.reshape(-1)[:tile_n]

        def min_neighbor(gf_p):
            """mngf piece: Select2ndMin SpMV on the sharded tile."""
            x = col_slice(gf_p)
            contrib = jnp.where(tvalid, x[colsc], _I32MAX)
            y = tl.seg_reduce_sorted(S.SELECT2ND_MIN_I32.add, contrib,
                                     starts, seg_ends, nonempty)
            y = lax.pmin(y, COL_AXIS)                    # (tile_m,)
            pad = jnp.full((q * blk - tile_m,), _I32MAX, jnp.int32)
            return lax.dynamic_slice(jnp.concatenate([y, pad]),
                                     (j * blk,), (blk,))

        def bucketize(tgt, payload, valid):
            """Compact (tgt, payload) pairs into per-destination-row
            buckets (q, blk) + the slot map to un-route responses.
            Destination = tgt // tile_m (the owner row slice)."""
            dest = jnp.where(valid, jnp.clip(tgt // tile_m, 0, q - 1), q)
            order = jnp.argsort(dest, stable=True)
            ds, ts, ps = dest[order], tgt[order], payload[order]
            start = jnp.searchsorted(ds, jnp.arange(q + 1, dtype=jnp.int32),
                                     side="left").astype(jnp.int32)
            slot = ds * blk + (jnp.arange(blk, dtype=jnp.int32) - start[
                jnp.clip(ds, 0, q - 1)])
            slot = jnp.where(ds < q, slot, q * blk)
            bt = jnp.full((q * blk,), _I32MAX, jnp.int32
                          ).at[slot].set(ts, mode="drop")
            bp = jnp.full((q * blk,), _I32MAX, jnp.int32
                          ).at[slot].set(ps, mode="drop")
            return bt.reshape(q, blk), bp.reshape(q, blk), order, slot

        def scatter_min_global(f_p, tgt, val, valid):
            """f[tgt] <- min(f[tgt], val) across the whole mesh;
            returns the updated piece."""
            bt, bv, _, _ = bucketize(tgt, val, valid)
            rt = lax.all_to_all(bt, ROW_AXIS, 0, 0).reshape(-1)
            rv = lax.all_to_all(bv, ROW_AXIS, 0, 0).reshape(-1)
            tloc = jnp.where(rt < _I32MAX, rt - i * tile_m, tile_m)
            acc = jnp.full((tile_m,), _I32MAX, jnp.int32).at[
                jnp.clip(tloc, 0, tile_m)].min(rv, mode="drop")
            acc = lax.pmin(acc, COL_AXIS)                # (tile_m,)
            pad = jnp.full((q * blk - tile_m,), _I32MAX, jnp.int32)
            mine = lax.dynamic_slice(jnp.concatenate([acc, pad]),
                                     (j * blk,), (blk,))
            return jnp.minimum(f_p, mine)

        def gather_global(f_r, tgt, valid):
            """out[u] = f[tgt[u]] across the mesh (f_r = my row slice
            of the CURRENT f): queries route to the owner row, answers
            route back through the same buckets."""
            bt, _, order, slot = bucketize(tgt, tgt, valid)
            rt = lax.all_to_all(bt, ROW_AXIS, 0, 0)      # (q, blk)
            tloc = jnp.clip(rt.reshape(-1) - i * tile_m, 0, tile_m - 1)
            ans = jnp.where(rt.reshape(-1) < _I32MAX,
                            f_r[tloc], _I32MAX).reshape(q, blk)
            back = lax.all_to_all(ans, ROW_AXIS, 0, 0).reshape(-1)
            flat = jnp.concatenate([back, jnp.full((1,), _I32MAX,
                                                   jnp.int32)])
            res_sorted = flat[jnp.clip(slot, 0, q * blk)]
            return jnp.zeros((blk,), jnp.int32).at[order].set(res_sorted)

        def body(carry):
            f_p, gf_p, it, _ = carry
            mngf = min_neighbor(gf_p)
            # 2) stochastic hooking onto the (old) parent
            f_p2 = scatter_min_global(f_p, f_p, mngf, piece_ok)
            # 3) aggressive hooking + 4) shortcutting
            f_p2 = jnp.minimum(f_p2, jnp.minimum(mngf, gf_p))
            # 5) pointer jumping on the UPDATED f
            f_r = row_slice(f_p2)
            gf_new = gather_global(f_r, f_p2, piece_ok)
            gf_new = jnp.where(piece_ok, gf_new, _I32MAX)
            changed = lax.pmax(
                jnp.any(gf_new != gf_p).astype(jnp.int32),
                (ROW_AXIS, COL_AXIS)) > 0
            return f_p2, gf_new, it + 1, changed

        def cond(carry):
            _, _, it, changed = carry
            return changed & (it < max_iters)

        # valid slots self-rooted (padding vertices included: isolated,
        # they converge to themselves and are sliced off by glen)
        f0 = jnp.where(piece_ok, gids, _I32MAX)
        f_p, gf_p, _, _ = lax.while_loop(
            cond, body, (f0, f0, jnp.int32(0), jnp.bool_(True)))
        # final compression, then emit my row slice (replicated over j)
        f_r = row_slice(gather_global(row_slice(f_p), f_p, piece_ok))
        return f_r[None]

    f = jax.shard_map(
        kernel, mesh=grid.mesh,
        in_specs=(P(ROW_AXIS, COL_AXIS, None),) * 3
                 + (P(ROW_AXIS, COL_AXIS),),
        out_specs=P(ROW_AXIS, None),
        check_vma=False,
    )(a.rows, a.cols, a.vals, a.nnz)
    return dvec.DistVec(f, grid, ROW_AXIS, n)


# flight-recorder boundaries: eager driver calls (fastsv dispatches
# one of these; serve's label build goes through fastsv) land in the
# dispatch ledger; in-trace calls pass straight through
_fastsv_replicated = obs.instrument(
    _fastsv_replicated, "cc.fastsv_replicated", sync=True)
_fastsv_sharded = obs.instrument(
    _fastsv_sharded, "cc.fastsv_sharded", sync=True)
_fastsv_chunk = obs.instrument(
    _fastsv_chunk, "cc.fastsv_chunk", sync=True)


@partial(jax.jit, static_argnames=("max_iters",))
def lacc(a: dm.DistSpMat, max_iters: int = 100) -> dvec.DistVec:
    """Component labels by Awerbuch-Shiloach-style star hooking
    (≅ LACC, CC.h:420-1620): per iteration, a star test gates
    conditional hooking of star roots onto strictly-smaller neighbor
    parents (one Select2ndMin SpMV), then shortcutting — all vector
    steps on the flat parent array inside one jitted while_loop.
    Unlike the reference there is no unconditional-hooking phase: the
    strictly-decreasing min-hook is monotone, so termination and
    correctness hold without it (at the cost of the reference's
    O(log n) round bound).

    FastSV (above) is the faster variant; LACC is kept for parity and
    as an independent cross-check of component structure.
    """
    if a.nrows != a.ncols:
        raise ValueError(
            f"lacc needs a square symmetric adjacency matrix, got "
            f"{a.nrows}x{a.ncols}")
    n = a.nrows
    grid = a.grid
    tile_n, tile_m = a.tile_n, a.tile_m
    cpad = grid.pc * tile_n - n

    def to_cvec(flat):
        data = jnp.pad(flat, (0, cpad), constant_values=_I32MAX)
        return dvec.DistVec(data.reshape(grid.pc, tile_n), grid,
                            COL_AXIS, n)

    def star_mask(f):
        """star[u]: u belongs to a depth-<=1 tree — the classic
        Shiloach-Vishkin star test (≅ StarCheckAfterHooking,
        CC.h:1035): every deep vertex poisons its grandparent's flag,
        then every vertex inherits its GRANDparent's flag (a star's
        root is never poisoned; any deep tree's upper vertices are)."""
        gf = f[jnp.clip(f, 0, n - 1)]
        deep = gf != f                              # depth >= 2
        poisoned = jnp.zeros((n,), bool).at[
            jnp.clip(gf, 0, n - 1)].max(deep, mode="drop")
        st = ~poisoned
        return st[jnp.clip(gf, 0, n - 1)]           # inherit from gp

    def body(carry):
        f, it, _ = carry
        star = star_mask(f)
        # min neighbor parent (Select2ndMin SpMV over f)
        x = to_cvec(f)
        y = pspmv.spmv(S.SELECT2ND_MIN_I32, a, x)
        mnp = y.data.reshape(-1)[:n]
        # conditional hooking: star roots hook onto a strictly smaller
        # neighbor parent
        can = star & (mnp < f)
        tgt = jnp.clip(f, 0, n - 1)
        hooked = f.at[jnp.where(can, tgt, n)].min(
            jnp.where(can, mnp, _I32MAX), mode="drop")
        # shortcutting
        f2 = hooked[jnp.clip(hooked, 0, n - 1)]
        changed = jnp.any(f2 != f)
        return f2, it + 1, changed

    def cond(carry):
        _, it, changed = carry
        return changed & (it < max_iters)

    f0 = jnp.arange(n, dtype=jnp.int32)
    f, _, _ = lax.while_loop(cond, body, (f0, jnp.int32(0),
                                          jnp.bool_(True)))
    # full compression (trees are shallow; a few jumps close any gap)
    for _ in range(2):
        f = f[jnp.clip(f, 0, n - 1)]
    rpad = grid.pr * tile_m - n
    data = jnp.pad(f, (0, rpad), constant_values=_I32MAX)
    return dvec.DistVec(data.reshape(grid.pr, tile_m), grid, ROW_AXIS, n)


lacc = obs.instrument(lacc, "cc.lacc", sync=True)


def label_cc(labels: dvec.DistVec) -> tuple[dvec.DistVec, int]:
    """Relabel component roots to contiguous 0..ncomp-1 ids
    (≅ LabelCC, FastSV.h:56). Host-side (app driver boundary)."""
    with obs.ledger.readback("cc.labels_readback", 4 * labels.glen):
        lg = np.asarray(labels.to_global())
    uniq, inv = np.unique(lg, return_inverse=True)
    out = dvec.from_global(labels.grid, labels.axis,
                           jnp.asarray(inv.astype(np.int32)))
    return out, int(len(uniq))


def connected_components(a: dm.DistSpMat) -> tuple[dvec.DistVec, int]:
    """FastSV + contiguous relabel: (labels, #components)
    (≅ FastSV.cpp main flow)."""
    with obs.span("cc_fastsv", category="device_execute"):
        labels = fastsv(a)
        obs.sync(labels.data)
    # label_cc fetches the whole label vector to host (np.unique there
    # is host_compute, but the fetch dominates at scale)
    with obs.span("cc_relabel", category="host_readback"):
        return label_cc(labels)
