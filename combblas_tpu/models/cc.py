"""Connected components: FastSV (and the LACC-style hooking variant).

Capability parity: Applications/FastSV.cpp + FastSV.h:25-377 (the
Zhang-Azad-Buluç FastSV algorithm: Select2ndMin SpMV + stochastic
hooking + aggressive hooking + shortcutting, iterated to fixpoint)
and the `LabelCC` relabeling (FastSV.h:56).

TPU-native re-design: the parent vector f lives as one flat (n,)
int32 array inside a single jitted `lax.while_loop` — vectors are
O(n), tiny next to the matrix, so the reference's distributed
Assign/Extract vector machinery (CC.h:420-1018) collapses to
gathers/scatter-mins on the logical view, while the O(nnz) work (the
min-over-neighbors step) stays a distributed semiring SpMV over the
mesh. Zero host round-trips until convergence.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from combblas_tpu.ops import semiring as S
from combblas_tpu.parallel import distmat as dm
from combblas_tpu.parallel import distvec as dvec
from combblas_tpu.parallel import spmv as pspmv
from combblas_tpu.parallel.grid import ROW_AXIS, COL_AXIS

_I32MAX = jnp.iinfo(jnp.int32).max


@partial(jax.jit, static_argnames=("max_iters",))
def fastsv(a: dm.DistSpMat, max_iters: int = 100) -> dvec.DistVec:
    """Component labels (min vertex id per component) of the symmetric
    graph ``a``; one jitted while_loop (≅ FastSV.h:25-377).

    Per iteration:
      1. mngf[u] = min over neighbors v of gf[v]   (Select2ndMin SpMV)
      2. stochastic hooking:  f[f[u]] <- min(f[f[u]], mngf[u])
      3. aggressive hooking:  f[u]    <- min(f[u],    mngf[u])
      4. shortcutting:        f[u]    <- min(f[u],    gf[u])
      5. gf = f[f];  converged when gf stops changing.

    Design note (deliberate divergence from the reference's
    distributed Assign/Extract vector primitives, CC.h:420-1018): the
    parent array rides the while_loop as a flat replicated (n,) int32
    — the hooking indirections (f[f[u]]) become local gathers instead
    of cross-rank Extract round trips. Per-device memory is O(n)
    vertex state (4 bytes/vertex: 64 MB at scale 24, 1 GB at scale
    28), a bound the 16 GB HBM accommodates through every Graph500
    scale this framework targets; the O(nnz) edge work stays sharded.
    """
    if a.nrows != a.ncols:
        raise ValueError(
            f"fastsv needs a square symmetric adjacency matrix, got "
            f"{a.nrows}x{a.ncols}")
    n = a.nrows
    grid = a.grid
    tile_n, tile_m = a.tile_n, a.tile_m
    cpad = grid.pc * tile_n - n

    def to_cvec(flat):
        data = jnp.pad(flat, (0, cpad), constant_values=_I32MAX)
        return dvec.DistVec(data.reshape(grid.pc, tile_n), grid,
                            COL_AXIS, n)

    def min_neighbor_gf(gf):
        x = to_cvec(gf)
        y = pspmv.spmv(S.SELECT2ND_MIN_I32, a, x)   # r-aligned (pr, tile_m)
        return y.data.reshape(-1)[:n]               # isolated rows: INT32_MAX

    def body(carry):
        f, gf, it, _ = carry
        mngf = min_neighbor_gf(gf)
        # 2) stochastic hooking onto the (old) parent
        tgt = jnp.clip(f, 0, n - 1)
        f = f.at[tgt].min(mngf)
        # 3) aggressive hooking + 4) shortcutting
        f = jnp.minimum(f, jnp.minimum(mngf, gf))
        # 5) pointer jumping
        gf_new = f[jnp.clip(f, 0, n - 1)]
        changed = jnp.any(gf_new != gf)
        return f, gf_new, it + 1, changed

    def cond(carry):
        _, _, it, changed = carry
        return changed & (it < max_iters)

    f0 = jnp.arange(n, dtype=jnp.int32)
    f, _, _, _ = lax.while_loop(cond, body,
                                (f0, f0, jnp.int32(0), jnp.bool_(True)))
    # final full path compression (f is within one jump of the root at
    # convergence; one more composition makes labels exact roots)
    f = f[jnp.clip(f, 0, n - 1)]
    rpad = grid.pr * tile_m - n
    data = jnp.pad(f, (0, rpad), constant_values=_I32MAX)
    return dvec.DistVec(data.reshape(grid.pr, tile_m), grid, ROW_AXIS, n)


@partial(jax.jit, static_argnames=("max_iters",))
def lacc(a: dm.DistSpMat, max_iters: int = 100) -> dvec.DistVec:
    """Component labels by Awerbuch-Shiloach-style star hooking
    (≅ LACC, CC.h:420-1620): per iteration, a star test gates
    conditional hooking of star roots onto strictly-smaller neighbor
    parents (one Select2ndMin SpMV), then shortcutting — all vector
    steps on the flat parent array inside one jitted while_loop.
    Unlike the reference there is no unconditional-hooking phase: the
    strictly-decreasing min-hook is monotone, so termination and
    correctness hold without it (at the cost of the reference's
    O(log n) round bound).

    FastSV (above) is the faster variant; LACC is kept for parity and
    as an independent cross-check of component structure.
    """
    if a.nrows != a.ncols:
        raise ValueError(
            f"lacc needs a square symmetric adjacency matrix, got "
            f"{a.nrows}x{a.ncols}")
    n = a.nrows
    grid = a.grid
    tile_n, tile_m = a.tile_n, a.tile_m
    cpad = grid.pc * tile_n - n

    def to_cvec(flat):
        data = jnp.pad(flat, (0, cpad), constant_values=_I32MAX)
        return dvec.DistVec(data.reshape(grid.pc, tile_n), grid,
                            COL_AXIS, n)

    def star_mask(f):
        """star[u]: u belongs to a depth-<=1 tree — the classic
        Shiloach-Vishkin star test (≅ StarCheckAfterHooking,
        CC.h:1035): every deep vertex poisons its grandparent's flag,
        then every vertex inherits its GRANDparent's flag (a star's
        root is never poisoned; any deep tree's upper vertices are)."""
        gf = f[jnp.clip(f, 0, n - 1)]
        deep = gf != f                              # depth >= 2
        poisoned = jnp.zeros((n,), bool).at[
            jnp.clip(gf, 0, n - 1)].max(deep, mode="drop")
        st = ~poisoned
        return st[jnp.clip(gf, 0, n - 1)]           # inherit from gp

    def body(carry):
        f, it, _ = carry
        star = star_mask(f)
        # min neighbor parent (Select2ndMin SpMV over f)
        x = to_cvec(f)
        y = pspmv.spmv(S.SELECT2ND_MIN_I32, a, x)
        mnp = y.data.reshape(-1)[:n]
        # conditional hooking: star roots hook onto a strictly smaller
        # neighbor parent
        can = star & (mnp < f)
        tgt = jnp.clip(f, 0, n - 1)
        hooked = f.at[jnp.where(can, tgt, n)].min(
            jnp.where(can, mnp, _I32MAX), mode="drop")
        # shortcutting
        f2 = hooked[jnp.clip(hooked, 0, n - 1)]
        changed = jnp.any(f2 != f)
        return f2, it + 1, changed

    def cond(carry):
        _, it, changed = carry
        return changed & (it < max_iters)

    f0 = jnp.arange(n, dtype=jnp.int32)
    f, _, _ = lax.while_loop(cond, body, (f0, jnp.int32(0),
                                          jnp.bool_(True)))
    # full compression (trees are shallow; a few jumps close any gap)
    for _ in range(2):
        f = f[jnp.clip(f, 0, n - 1)]
    rpad = grid.pr * tile_m - n
    data = jnp.pad(f, (0, rpad), constant_values=_I32MAX)
    return dvec.DistVec(data.reshape(grid.pr, tile_m), grid, ROW_AXIS, n)


def label_cc(labels: dvec.DistVec) -> tuple[dvec.DistVec, int]:
    """Relabel component roots to contiguous 0..ncomp-1 ids
    (≅ LabelCC, FastSV.h:56). Host-side (app driver boundary)."""
    lg = np.asarray(labels.to_global())
    uniq, inv = np.unique(lg, return_inverse=True)
    out = dvec.from_global(labels.grid, labels.axis,
                           jnp.asarray(inv.astype(np.int32)))
    return out, int(len(uniq))


def connected_components(a: dm.DistSpMat) -> tuple[dvec.DistVec, int]:
    """FastSV + contiguous relabel: (labels, #components)
    (≅ FastSV.cpp main flow)."""
    return label_cc(fastsv(a))
