"""Mesh observatory: measured collective traffic, per-device
attribution, and the predicted-vs-measured ICI drift join.

Every observability layer so far aggregates at the PROCESS level. The
scale-out work (3D grids, the TPU re-measure campaign) lives or dies
on PER-DEVICE behavior: the cost model prices ICI bytes analytically,
but nothing ever measured what a `psum`/`all_gather`/`ppermute`
actually moved per mesh axis, and tile load skew — the CombBLAS 2.0
motivation for 3D grids — was invisible. This module is the mesh-level
eye:

* COLLECTIVE-TRAFFIC LEDGER. Planners call `register_collectives(name,
  descs)` with the static per-dispatch descriptor list of the
  executable they just planned — one `(collective, axis, dtype, shape,
  rung, bytes)` row per collective the compiled body will run. A
  dispatch sink installed into `obs.ledger` (same disarmed-cost
  contract as the fault hook: one module-global load + `is None`)
  accumulates those descriptor bytes per `(name, collective, axis)` at
  every recorded dispatch — so measured exchanged bytes per mesh axis
  are first-class, with NO work on the dispatch path beyond a dict
  update.
* DRIFT JOIN. `drift(name)` divides the measured bytes by the cost
  model's analytic prediction (`costmodel.cost_for(name)["cbytes"]` ×
  dispatch count). Where the planner annotates exact exchange volumes
  (SUMMA's `_record_bcasts`, `summa3d`) the ratio is 1.0 by
  construction on any backend; where the model is a coarse per-row
  family constant (SpMV fan stages, bits-BFS) the ratio measures model
  quality. Analysis pass 9 (`analysis/meshbudget.py`) gates the exact
  names with a `mesh-ici-drift` band.
* PER-DEVICE ATTRIBUTION. `register_device_loads(name, flops=, nnz=)`
  takes the planner's exact per-tile work grids (`plan_spgemm`'s
  `f_ij` totals, per-tile nnz) keyed by mesh coordinate labels
  ("r0c1"); `skew_summary` reduces them to max/mean imbalance + the
  straggler device, and `attribution_fraction` reports how much ledger
  wall is carried by names with device rows (the ≥0.9 e2e pin). Real
  meshes can add measured per-device walls via `record_device_wall`.

Measured-byte convention: descriptor `bytes` is the PER-DEVICE payload
of one execution of that collective, matching the call site's existing
accounting (`spgemm._bcast_payload_bytes` for masked-psum broadcasts;
(participants-1) × shard bytes for all_gather). "Measured" here means
"descriptor bytes accumulated at real dispatches" — exact on emulated
meshes where the compiled body is the plan, and the join point where
hardware counters can land later without changing any consumer.

Everything is process-global like the ledger/cost model; `reset()`
clears it (tests). Registration REPLACES a name's descriptors (the
latest plan describes the next dispatch), mirroring how `plan →
dispatch` sequences interleave.
"""

from __future__ import annotations

import threading

_LOCK = threading.Lock()

#: name -> tuple of descriptor dicts, each
#:   {collective, axis, dtype, shape, rung, bytes[, src, dst]}
_DESCS: dict = {}

#: name -> {(collective, axis): [bytes_total, events]}
_MEASURED: dict = {}

#: name -> dispatch count seen by the sink
_DISPATCHES: dict = {}

#: name -> {"flops": {label: v}, "nnz": {label: v}}
_LOADS: dict = {}

#: device label -> [wall_s_total, samples]  (real-mesh sampling)
_DEVICE_WALLS: dict = {}

_SINK_INSTALLED = False

_DESC_KEYS = ("collective", "axis", "dtype", "shape", "rung", "bytes")


def _normalize_desc(d: dict) -> dict:
    missing = [k for k in _DESC_KEYS if k not in d]
    if missing:
        raise ValueError(f"collective descriptor missing {missing}: {d}")
    out = {"collective": str(d["collective"]), "axis": str(d["axis"]),
           "dtype": str(d["dtype"]),
           "shape": tuple(int(x) for x in d["shape"]),
           "rung": int(d["rung"]), "bytes": int(d["bytes"])}
    for opt in ("src", "dst"):
        if d.get(opt) is not None:
            out[opt] = str(d[opt])
    return out


def _sink(name: str) -> None:
    """Dispatch sink (installed into obs.ledger): accumulate the
    registered descriptor bytes of `name`. Runs on the hot dispatch
    path only when the ledger records, so stay allocation-light."""
    descs = _DESCS.get(name)
    if descs is None:
        return
    with _LOCK:
        _DISPATCHES[name] = _DISPATCHES.get(name, 0) + 1
        meas = _MEASURED.setdefault(name, {})
        for d in descs:
            key = (d["collective"], d["axis"])
            row = meas.get(key)
            if row is None:
                meas[key] = [d["bytes"], 1]
            else:
                row[0] += d["bytes"]
                row[1] += 1


def _ensure_sink() -> None:
    global _SINK_INSTALLED
    if _SINK_INSTALLED:
        return
    from combblas_tpu.obs import ledger as _ledger
    _ledger.set_dispatch_sink(_sink)
    _SINK_INSTALLED = True


def register_collectives(name: str, descs) -> None:
    """Register the static per-dispatch collective descriptors of one
    ledger executable name (REPLACES any previous registration — the
    latest plan describes the next dispatch). Each descriptor:
    {collective, axis, dtype, shape, rung, bytes[, src, dst]}."""
    rows = tuple(_normalize_desc(d) for d in descs)
    _ensure_sink()
    with _LOCK:
        _DESCS[name] = rows


def descriptors(name: str | None = None):
    """Registered descriptors: tuple for one name (or () if absent),
    else the whole registry as {name: (descs...)}."""
    with _LOCK:
        if name is not None:
            return _DESCS.get(name, ())
        return dict(_DESCS)


def register_device_loads(name: str, *, flops=None, nnz=None,
                          labels=None) -> None:
    """Register static per-device load metrics for a ledger name.
    `flops`/`nnz` are 2D (pr, pc) or 3D (l, pr, pc) array-likes of
    per-tile work, or pre-labeled {label: value} dicts. Mesh-coord
    labels are minted "r{i}c{j}" (3D: "l{k}r{i}c{j}") unless `labels`
    (a same-shape nest of strings) overrides. REPLACES per name."""
    import numpy as np

    def to_map(grid):
        if grid is None:
            return None
        if isinstance(grid, dict):
            return {str(k): float(v) for k, v in grid.items()}
        arr = np.asarray(grid)  # analysis: allow(sync-in-async) plan-time registration, once per matrix
        out = {}
        if arr.ndim == 2:
            for i in range(arr.shape[0]):
                for j in range(arr.shape[1]):
                    lbl = (labels[i][j] if labels is not None
                           else f"r{i}c{j}")
                    out[lbl] = float(arr[i, j])
        elif arr.ndim == 3:
            for k in range(arr.shape[0]):
                for i in range(arr.shape[1]):
                    for j in range(arr.shape[2]):
                        lbl = (labels[k][i][j] if labels is not None
                               else f"l{k}r{i}c{j}")
                        out[lbl] = float(arr[k, i, j])
        else:
            raise ValueError(
                f"device loads must be 2D/3D or a dict, got "
                f"shape {arr.shape}")
        return out

    row = {}
    f = to_map(flops)
    n = to_map(nnz)
    if f is not None:
        row["flops"] = f
    if n is not None:
        row["nnz"] = n
    if not row:
        raise ValueError("register_device_loads needs flops= or nnz=")
    with _LOCK:
        _LOADS[name] = row


def device_loads(name: str | None = None):
    with _LOCK:
        if name is not None:
            return dict(_LOADS.get(name, {}))
        return {k: dict(v) for k, v in _LOADS.items()}


def record_device_wall(device: str, wall_s: float) -> None:
    """Accumulate one measured per-device wall sample (real meshes:
    profiler-derived device execution time). Emulated-mesh tests and
    CPU runs never call this — static loads carry attribution there."""
    with _LOCK:
        row = _DEVICE_WALLS.setdefault(str(device), [0.0, 0])
        row[0] += float(wall_s)
        row[1] += 1


def device_walls() -> dict:
    """{device: {"wall_s": total, "samples": n}} of recorded samples."""
    with _LOCK:
        return {k: {"wall_s": v[0], "samples": v[1]}
                for k, v in _DEVICE_WALLS.items()}


def measured(name: str | None = None):
    """Accumulated measured bytes: for one name,
    {(collective, axis): {"bytes": total, "events": n}}; for all names
    the nested dict keyed by name."""
    def fmt(m):
        return {k: {"bytes": v[0], "events": v[1]} for k, v in m.items()}
    with _LOCK:
        if name is not None:
            return fmt(_MEASURED.get(name, {}))
        return {n: fmt(m) for n, m in _MEASURED.items()}


def dispatches(name: str) -> int:
    with _LOCK:
        return _DISPATCHES.get(name, 0)


def bytes_by_axis(name: str | None = None) -> dict:
    """Measured bytes folded per mesh axis ({axis: bytes}), for one
    name or across every registered name."""
    out: dict = {}
    with _LOCK:
        items = ([(name, _MEASURED.get(name, {}))] if name is not None
                 else list(_MEASURED.items()))
        for _, meas in items:
            for (_coll, axis), row in meas.items():
                out[axis] = out.get(axis, 0) + row[0]
    return out


def drift(name: str):
    """measured/predicted ICI-byte ratio for one name: descriptor
    bytes accumulated at dispatch over the cost model's per-call
    `cbytes` × dispatch count. None when the name has no measurement
    or no (nonzero) prediction — pass 9 treats a missing join on a
    gated name as STALE, not as a pass."""
    from combblas_tpu.obs import costmodel as _costmodel
    with _LOCK:
        meas = _MEASURED.get(name)
        n = _DISPATCHES.get(name, 0)
        got = sum(v[0] for v in meas.values()) if meas else 0
    if not n or not got:
        return None
    c = _costmodel.cost_for(name)
    if c is None or c["cbytes"] <= 0:
        return None
    return got / (c["cbytes"] * n)


def drift_table() -> dict:
    """{name: ratio-or-None} over every name with a registration."""
    with _LOCK:
        names = sorted(set(_DESCS) | set(_MEASURED))
    return {n: drift(n) for n in names}


def skew_summary() -> dict:
    """Per-name load-imbalance gauges from the registered per-device
    grids: for each metric, max/mean (1.0 = perfectly balanced; the
    3D-grid papers' skew number) and the straggler device label. Real
    measured walls (when sampled) ride along under "wall"."""
    out: dict = {}
    with _LOCK:
        loads = {k: {m: dict(g) for m, g in v.items()}
                 for k, v in _LOADS.items()}
        walls = {k: list(v) for k, v in _DEVICE_WALLS.items()}
    for name, metrics in loads.items():
        row = {}
        for metric, grid in metrics.items():
            vals = list(grid.values())
            if not vals:
                continue
            mean = sum(vals) / len(vals)
            worst = max(grid.items(), key=lambda kv: kv[1])
            row[metric] = {
                "max_over_mean": round(worst[1] / mean, 4) if mean > 0
                else 1.0,
                "straggler": worst[0],
                "devices": len(vals),
            }
        if row:
            out[name] = row
    if walls:
        tot = {k: v[0] for k, v in walls.items()}
        mean = sum(tot.values()) / len(tot)
        worst = max(tot.items(), key=lambda kv: kv[1])
        out["device_wall"] = {"wall": {
            "max_over_mean": round(worst[1] / mean, 4) if mean > 0
            else 1.0,
            "straggler": worst[0],
            "devices": len(tot),
        }}
    return out


def attribution_fraction(rows=None, ledger=None) -> float:
    """Fraction of total ledger wall carried by names that registered
    per-device load rows — the mesh-level counterpart of
    `costmodel.attributable_fraction` (the e2e test pins ≥0.9 for a
    SUMMA-phase run). Zero-wall rows count as attributed."""
    if rows is None:
        from combblas_tpu.obs import ledger as _ledger
        rows = _ledger.top_k(k=1 << 20, ledger=ledger,
                             join_costs=False)
    total = sum(r["total_s"] for r in rows)
    if total <= 0:
        return 1.0
    with _LOCK:
        covered = set(_LOADS)
    got = sum(r["total_s"] for r in rows if r["name"] in covered)
    return got / total


def join_rows(rows: list) -> list:
    """Decorate `ledger.top_k` rows in place with the mesh join:
    `mesh_bytes` (measured collective bytes across the row's
    dispatches) and `drift` (measured/predicted; None when either side
    is missing). Names with no registration get None for both."""
    with _LOCK:
        meas = {n: sum(v[0] for v in m.values())
                for n, m in _MEASURED.items()}
    for row in rows:
        name = row["name"]
        row["mesh_bytes"] = meas.get(name)
        row["drift"] = drift(name) if name in meas else None
    return rows


def mesh_summary(ledger=None) -> dict:
    """The bench-artifact `mesh_summary` block (what analysis pass 9
    grades, and the /varz "mesh" payload): per-name measured bytes per
    (collective, axis) with descriptor counts, per-axis totals, the
    drift table, skew gauges, and the device-attribution fraction."""
    with _LOCK:
        desc_counts = {n: len(d) for n, d in _DESCS.items()}
    names = {}
    for name, meas in measured().items():
        per_axis: dict = {}
        flat = {}
        for (coll, axis), row in meas.items():
            per_axis[axis] = per_axis.get(axis, 0) + row["bytes"]
            flat[f"{coll}/{axis}"] = dict(row)
        names[name] = {
            "dispatches": dispatches(name),
            "descriptors": desc_counts.get(name, 0),
            "measured": flat,
            "bytes_by_axis": per_axis,
            "drift": drift(name),
        }
    return {
        "names": names,
        "bytes_by_axis": bytes_by_axis(),
        "drift": drift_table(),
        "skew": skew_summary(),
        "attribution_frac": round(
            attribution_fraction(ledger=ledger), 4),
        "registered_names": sorted(desc_counts),
    }


def refresh_gauges() -> None:
    """Publish the observatory as /metrics gauges (scrape-time):
    `mesh.bytes{name,axis}`, `mesh.drift{name}` (only names whose join
    exists), `mesh.skew{name,metric}`, and `mesh.attribution_frac`."""
    from combblas_tpu.obs import metrics as _metrics
    g_bytes = _metrics.gauge(
        "mesh.bytes", "measured collective bytes per ledger name "
        "and mesh axis")
    for name, meas in measured().items():
        per_axis: dict = {}
        for (_coll, axis), row in meas.items():
            per_axis[axis] = per_axis.get(axis, 0) + row["bytes"]
        for axis, b in per_axis.items():
            g_bytes.set(b, name=name, axis=axis)
    g_drift = _metrics.gauge(
        "mesh.drift", "measured/predicted ICI bytes per ledger name")
    for name, ratio in drift_table().items():
        if ratio is not None:
            g_drift.set(ratio, name=name)
    g_skew = _metrics.gauge(
        "mesh.skew", "per-device load imbalance (max/mean)")
    for name, row in skew_summary().items():
        for metric, s in row.items():
            g_skew.set(s["max_over_mean"], name=name, metric=metric)
    _metrics.gauge(
        "mesh.attribution_frac",
        "ledger-wall fraction carried by device-attributed names"
    ).set(attribution_fraction())


def reset() -> None:
    with _LOCK:
        _DESCS.clear()
        _MEASURED.clear()
        _DISPATCHES.clear()
        _LOADS.clear()
        _DEVICE_WALLS.clear()
