"""Unified host/device timeline: correlate spans, ledger dispatches,
and `jax.profiler` annotations on one clock, and split the span-level
`unaccounted` residual into *dispatch-glue* (host wall overlapped by a
recorded device interaction) vs *host-idle* (wall no recorded activity
explains).

Everything here is pure post-processing over `trace.TRACER` records and
`ledger.LEDGER` records — both stamp `time.perf_counter()` so their
intervals compose directly. Deferred readbacks (records with a
non-None `t_enq`) are attributed at RESOLVE time: their `[t0, t0 +
wall_s)` interval is only the wall the host actually blocked, so
occupancy / glue math never counts the enqueue->resolve flight window
as host blocking; `deferred_readback_stats` reports that residency
separately. The only live piece is `region(...)`, which
brackets a code region with an `obs.span` AND a `jax.profiler.
TraceAnnotation` carrying the same region id, so device-side profiler
timelines (when a profiler trace is being captured) correlate back to
span records by name.
"""

from __future__ import annotations

import contextlib
import itertools

from combblas_tpu.obs import ledger as _ledger
from combblas_tpu.obs import trace as _trace

_REGION_SEQ = itertools.count(1)


@contextlib.contextmanager
def region(name: str, category: str | None = None, **attrs):
    """`obs.span` + `jax.profiler.TraceAnnotation` with a shared region
    id (`rN`), so profiler timelines correlate to span records. Falls
    back to a plain span when the profiler is unavailable. Zero
    overhead when tracing is disabled."""
    if not _trace._ENABLED:
        yield _trace._NOOP
        return
    rid = f"r{next(_REGION_SEQ)}"
    ann = None
    try:
        from jax.profiler import TraceAnnotation
        ann = TraceAnnotation(f"{name}#{rid}")
    except Exception:       # pragma: no cover - profiler unavailable
        ann = None
    with _trace.span(name, category, region_id=rid, **attrs) as sp:
        if ann is not None:
            with ann:
                yield sp
        else:
            yield sp


# ------------------------------------------------------------- intervals

def _union(intervals):
    """Merge overlapping [t0, t1) intervals; returns merged list."""
    ivs = sorted((a, b) for a, b in intervals if b > a)
    out = []
    for a, b in ivs:
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def _clip(intervals, lo, hi):
    return [(max(a, lo), min(b, hi)) for a, b in intervals
            if min(b, hi) > max(a, lo)]


def _subtract(base, holes):
    """base minus union(holes); all interval lists."""
    out = []
    holes = _union(holes)
    for a, b in base:
        cur = a
        for h0, h1 in holes:
            if h1 <= cur or h0 >= b:
                continue
            if h0 > cur:
                out.append((cur, h0))
            cur = max(cur, h1)
            if cur >= b:
                break
        if cur < b:
            out.append((cur, b))
    return out


def _span_len(intervals) -> float:
    return sum(b - a for a, b in intervals)


def _ledger_intervals(records=None, ledger=None):
    recs = records if records is not None else \
        (ledger if ledger is not None else _ledger.LEDGER).snapshot()
    return [(r.t0, r.t0 + r.wall_s) for r in recs]


# ------------------------------------------------------------- occupancy

def occupancy(t0: float | None = None, t1: float | None = None,
              span_name: str | None = None, records=None,
              tracer=None, ledger=None) -> dict:
    """Device-occupancy of a region: fraction of [t0, t1) overlapped by
    at least one recorded device interaction (ledger dispatch/readback
    walls — for `sync=True` instrumented sites these include device
    execution, so "busy" means the device or its dispatch path was).

    The region is either explicit [t0, t1) or the hull of all span
    records named `span_name`. Returns {window_s, busy_s,
    busy_fraction, dispatches}."""
    if span_name is not None:
        tr = tracer if tracer is not None else _trace.TRACER
        recs = [r for r in tr.snapshot() if r.name == span_name]
        if not recs:
            return {"window_s": 0.0, "busy_s": 0.0,
                    "busy_fraction": 0.0, "dispatches": 0}
        t0 = min(r.t0 for r in recs)
        t1 = max(r.t1 for r in recs)
    if t0 is None or t1 is None or t1 <= t0:
        return {"window_s": 0.0, "busy_s": 0.0, "busy_fraction": 0.0,
                "dispatches": 0}
    ivs = _clip(_ledger_intervals(records, ledger), t0, t1)
    busy = _span_len(_union(ivs))
    return {"window_s": t1 - t0, "busy_s": busy,
            "busy_fraction": busy / (t1 - t0), "dispatches": len(ivs)}


def coverage(t0: float, t1: float, records=None, ledger=None) -> float:
    """Fraction of [t0, t1) covered by named ledger records — the
    attribution metric: how much of a region's wall the flight recorder
    can explain by executable name."""
    return occupancy(t0=t0, t1=t1, records=records,
                     ledger=ledger)["busy_fraction"]


# ------------------------------------------- deferred readbacks

def deferred_readback_stats(records=None, ledger=None) -> dict:
    """Aggregate deferred readbacks (records carrying `t_enq`):
    name -> {count, blocked_s, queue_s, mean_blocked_s}.

    `blocked_s` sums resolve-time walls — the host wall the value's
    consumption actually cost; `queue_s` sums enqueue->resolve
    residency — the device/host overlap the deferral bought (a
    blocking readback would have stalled the host for that long
    instead). An async pipeline is working when `queue_s` dwarfs
    `blocked_s`."""
    recs = records if records is not None else \
        (ledger if ledger is not None else _ledger.LEDGER).snapshot()
    out: dict = {}
    for r in recs:
        te = getattr(r, "t_enq", None)
        if te is None:
            continue
        row = out.setdefault(r.name, {"count": 0, "blocked_s": 0.0,
                                      "queue_s": 0.0})
        row["count"] += 1
        row["blocked_s"] += r.wall_s
        row["queue_s"] += max(r.t0 - te, 0.0)
    for row in out.values():
        row["mean_blocked_s"] = row["blocked_s"] / row["count"]
        row["blocked_s"] = round(row["blocked_s"], 6)
        row["queue_s"] = round(row["queue_s"], 6)
        row["mean_blocked_s"] = round(row["mean_blocked_s"], 6)
    return out


# -------------------------------------------------- resident memory

def resident_watermark(t0: float | None = None,
                       t1: float | None = None) -> dict:
    """Peak/mean live-buffer bytes over a perf_counter window, from the
    memledger's watermark sample series (same clock as span and ledger
    records, so `resident_watermark(span.t0, span.t1)` prices a span's
    residency). None bounds are open. {samples, peak_bytes, mean_bytes}
    — zeros when no sample landed in the window (cadence off or window
    too narrow), never a guess."""
    from combblas_tpu.obs import memledger as _memledger
    pts = [(t, b) for t, b in _memledger.watermark_series()
           if (t0 is None or t >= t0) and (t1 is None or t <= t1)]
    if not pts:
        return {"samples": 0, "peak_bytes": 0, "mean_bytes": 0}
    vals = [b for _, b in pts]
    return {"samples": len(vals), "peak_bytes": max(vals),
            "mean_bytes": int(sum(vals) / len(vals))}


# ------------------------------------------------- unaccounted split

def split_unaccounted(tracer=None, ledger=None) -> dict:
    """Decompose the span-level `unaccounted` residual (self time of
    category-less spans) into:

      dispatch_glue_s — residual wall overlapped by a ledger record
                        (the host was driving a named dispatch/readback
                        the span taxonomy didn't categorize);
      host_idle_s     — residual wall with NO recorded activity (pure
                        python glue, GC, scheduling, ...).

    Exact per-thread interval arithmetic: for each category-less span
    record we reconstruct its SELF intervals (its window minus direct
    children on the same thread) and intersect with ledger intervals.
    """
    tr = tracer if tracer is not None else _trace.TRACER
    spans = tr.snapshot()
    led_ivs = _union(_ledger_intervals(None, ledger))
    glue = 0.0
    idle = 0.0
    by_parent: dict = {}
    for r in spans:
        if len(r.path) >= 2:
            by_parent.setdefault((r.tid, r.path[:-1]), []).append(r)
    for r in spans:
        if r.category is not None:
            continue
        kids = by_parent.get((r.tid, r.path), ())
        # symmetric timer-jitter tolerance on BOTH edges (a child whose
        # t0 lands 1ns before its parent's is still a child — the old
        # asymmetric filter dropped it and double-counted its wall as
        # parent self time), then clip to the parent window so the
        # tolerated overhang can't subtract wall outside it.
        holes = [(max(k.t0, r.t0), min(k.t1, r.t1)) for k in kids
                 if k.t0 >= r.t0 - 1e-9 and k.t1 <= r.t1 + 1e-9
                 and min(k.t1, r.t1) > max(k.t0, r.t0)]
        self_ivs = _subtract([(r.t0, r.t1)], holes)
        covered = 0.0
        for a, b in self_ivs:
            covered += _span_len(_union(_clip(led_ivs, a, b)))
        tot = _span_len(self_ivs)
        glue += covered
        idle += max(tot - covered, 0.0)
    return {"dispatch_glue_s": glue, "host_idle_s": idle,
            "unaccounted_s": glue + idle}
