"""Exporters for span records: breakdowns, report tree, JSON-lines,
Chrome trace (Perfetto), and the jax.profiler bridge.

All readers take an optional `tracer` (default: the process-wide
`trace.TRACER`) and operate on a snapshot, so exporting while spans
are still being recorded is safe.
"""

from __future__ import annotations

import contextlib
import json

from combblas_tpu.obs import trace as _trace
from combblas_tpu.obs.trace import UNACCOUNTED, SpanRecord, Tracer


def _records(tracer: Tracer | None) -> list[SpanRecord]:
    return (_trace.TRACER if tracer is None else tracer).snapshot()


# ---------------------------------------------------------------------------
# Category breakdown — the headline artifact (BENCH `phase_breakdown`)
# ---------------------------------------------------------------------------

def phase_breakdown(tracer: Tracer | None = None,
                    records: list[SpanRecord] | None = None) -> dict:
    """{category: seconds} over every record's SELF time, plus
    `"unaccounted"` (self time of category-less spans, region roots
    included) and `"total"` (summed top-level span durations). The
    invariant sum(categories) + unaccounted == total holds exactly,
    so the residual is an honest measurement, not a guess."""
    recs = records if records is not None else _records(tracer)
    out = {UNACCOUNTED: 0.0}
    total = 0.0
    for r in recs:
        key = r.category if r.category is not None else UNACCOUNTED
        out[key] = out.get(key, 0.0) + r.self_s
        if r.depth == 0:
            total += r.total_s
    # self_s clamps tiny negative jitter to 0, which can leave the
    # parts a hair over the whole; fold the difference into the
    # residual so the invariant is exact
    out[UNACCOUNTED] = max(total - sum(v for k, v in out.items()
                                       if k != UNACCOUNTED), 0.0)
    out["total"] = total
    return out


def unaccounted_s(tracer: Tracer | None = None) -> float:
    return phase_breakdown(tracer)[UNACCOUNTED]


# ---------------------------------------------------------------------------
# Human report tree (self/total per span path)
# ---------------------------------------------------------------------------

def report(tracer: Tracer | None = None,
           records: list[SpanRecord] | None = None) -> dict:
    """Aggregate records by PATH into a nested tree:
    {name: {"calls", "total_s", "self_s", "category", "children": {...}}}.
    Paths aggregate across repeats (every window/iteration of a loop
    folds into one node)."""
    recs = records if records is not None else _records(tracer)
    root: dict = {}
    for r in sorted(recs, key=lambda r: len(r.path)):
        level = root
        for name in r.path[:-1]:
            node = level.get(name)
            if node is None:   # orphan (parent open or dropped): stub it
                node = level[name] = {"calls": 0, "total_s": 0.0,
                                      "self_s": 0.0, "category": None,
                                      "children": {}}
            level = node["children"]
        node = level.setdefault(r.path[-1], {
            "calls": 0, "total_s": 0.0, "self_s": 0.0,
            "category": r.category, "children": {}})
        node["calls"] += 1
        node["total_s"] += r.total_s
        node["self_s"] += r.self_s
    return root


def format_report(tracer: Tracer | None = None, indent: int = 2,
                  min_s: float = 0.0) -> str:
    """Render the report tree for terminals: one line per span path,
    total/self seconds, call count, category."""
    lines = [f"{'span':<44} {'total_s':>10} {'self_s':>10} "
             f"{'calls':>7}  category"]

    def walk(tree: dict, depth: int):
        for name, node in sorted(tree.items(),
                                 key=lambda kv: -kv[1]["total_s"]):
            if node["total_s"] >= min_s:
                label = " " * (indent * depth) + name
                lines.append(
                    f"{label:<44} {node['total_s']:>10.4f} "
                    f"{node['self_s']:>10.4f} {node['calls']:>7}  "
                    f"{node['category'] or '-'}")
            walk(node["children"], depth + 1)

    walk(report(tracer), 0)
    bd = phase_breakdown(tracer)
    total = bd.pop("total")
    lines.append(f"{'-- breakdown --':<44} {total:>10.4f}")
    for k, v in sorted(bd.items(), key=lambda kv: -kv[1]):
        pct = 100.0 * v / total if total else 0.0
        lines.append(f"  {k:<42} {v:>10.4f} {pct:>9.1f}%")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# JSON-lines event log (round-trippable)
# ---------------------------------------------------------------------------

def to_jsonl(path, tracer: Tracer | None = None) -> int:
    """One JSON object per completed span; returns the record count."""
    recs = _records(tracer)
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r.to_dict()) + "\n")
    return len(recs)


def read_jsonl(path) -> list[SpanRecord]:
    out = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            d = json.loads(line)
            out.append(SpanRecord(
                d["name"], d["category"], d["t0"], d["t1"], d["depth"],
                tuple(d["path"]), d["tid"], d["attrs"], d["children_s"]))
    return out


# ---------------------------------------------------------------------------
# Chrome trace (chrome://tracing / https://ui.perfetto.dev)
# ---------------------------------------------------------------------------

def chrome_trace(path, tracer: Tracer | None = None) -> int:
    """Emit complete ("ph": "X") events, microsecond timestamps
    rebased to the earliest span. Category and attrs land in `args`;
    `cat` enables Perfetto's category filter."""
    recs = _records(tracer)
    t_base = min((r.t0 for r in recs), default=0.0)
    events = [{
        "name": r.name,
        "cat": r.category or "other",
        "ph": "X",
        "ts": (r.t0 - t_base) * 1e6,
        "dur": r.total_s * 1e6,
        "pid": 0,
        "tid": r.tid % 2 ** 31,   # Chrome wants a small-ish int
        "args": {"path": "/".join(r.path), "self_s": round(r.self_s, 6),
                 **r.attrs},
    } for r in recs]
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)


# ---------------------------------------------------------------------------
# jax.profiler bridge (XLA op-level breakdown; TensorBoard/xprof)
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def profiler_trace(logdir: str):
    """jax.profiler trace context — the XLA-level phase breakdown
    (open the logdir with TensorBoard / xprof). The spans above answer
    "where did the wall clock go"; this answers "which XLA ops"."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
