"""Exporters for span records: breakdowns, report tree, JSON-lines,
Chrome trace (Perfetto), and the jax.profiler bridge.

All readers take an optional `tracer` (default: the process-wide
`trace.TRACER`) and operate on a snapshot, so exporting while spans
are still being recorded is safe.
"""

from __future__ import annotations

import contextlib
import json

from combblas_tpu.obs import trace as _trace
from combblas_tpu.obs.trace import UNACCOUNTED, SpanRecord, Tracer


def _records(tracer: Tracer | None) -> list[SpanRecord]:
    return (_trace.TRACER if tracer is None else tracer).snapshot()


# ---------------------------------------------------------------------------
# Category breakdown — the headline artifact (BENCH `phase_breakdown`)
# ---------------------------------------------------------------------------

def phase_breakdown(tracer: Tracer | None = None,
                    records: list[SpanRecord] | None = None) -> dict:
    """{category: seconds} over every record's SELF time, plus
    `"unaccounted"` (self time of category-less spans, region roots
    included) and `"total"` (summed top-level span durations). The
    invariant sum(categories) + unaccounted == total holds exactly,
    so the residual is an honest measurement, not a guess."""
    recs = records if records is not None else _records(tracer)
    out = {UNACCOUNTED: 0.0}
    total = 0.0
    for r in recs:
        key = r.category if r.category is not None else UNACCOUNTED
        out[key] = out.get(key, 0.0) + r.self_s
        if r.depth == 0:
            total += r.total_s
    # self_s clamps tiny negative jitter to 0, which can leave the
    # parts a hair over the whole; fold the difference into the
    # residual so the invariant is exact
    out[UNACCOUNTED] = max(total - sum(v for k, v in out.items()
                                       if k != UNACCOUNTED), 0.0)
    out["total"] = total
    return out


def unaccounted_s(tracer: Tracer | None = None) -> float:
    return phase_breakdown(tracer)[UNACCOUNTED]


def dispatch_summary(k: int = 10, ledger=None) -> dict:
    """The BENCH-artifact block next to `phase_breakdown`: top-K
    executables by total wall from the dispatch ledger (with the
    roofline cost-model join on each row), plus totals and the
    aggregate `efficiency` verdict {attributable_frac, eff,
    bound_wall_s, backend} that `obs.regress` folds into the bench
    trajectory. {top: [...], dispatches, readbacks, compiles,
    recorded, dropped, efficiency, memory, mesh} — `memory` is the
    compact capacity verdict (peak resident, census coverage,
    headroom); the full census + donation audit lives in
    `memory_summary`. `mesh` is the mesh observatory's compact verdict
    (per-axis measured bytes, the drift table, attribution fraction);
    the full per-name block lives in `obs.meshobs.mesh_summary`."""
    from combblas_tpu.obs import costmodel as _costmodel
    from combblas_tpu.obs import ledger as _ledger
    from combblas_tpu.obs import memledger as _memledger
    from combblas_tpu.obs import meshobs as _meshobs
    led = ledger if ledger is not None else _ledger.LEDGER
    recs = led.snapshot()
    all_rows = _ledger.top_k(1 << 20, by="wall", records=recs,
                             join_costs=False)
    return {
        "top": _ledger.top_k(k, by="wall", records=recs),
        "dispatches": sum(1 for r in recs if r.kind == "dispatch"),
        "readbacks": sum(1 for r in recs if r.kind == "readback"),
        "compiles": sum(1 for r in recs if r.compiled),
        "recorded": led.total,
        "dropped": led.dropped,
        "efficiency": _costmodel.efficiency_summary(rows=all_rows),
        "memory": {
            **_memledger.headroom(),
            "census_coverage": _memledger.census_coverage(records=recs),
        },
        "mesh": {
            "bytes_by_axis": _meshobs.bytes_by_axis(),
            "drift": _meshobs.drift_table(),
            "attribution_frac": round(
                _meshobs.attribution_fraction(rows=all_rows), 4),
            "registered_names": sorted(_meshobs.descriptors()),
        },
    }


def memory_summary(k: int = 8, ledger=None, full: bool = True) -> dict:
    """The bench-artifact `memory_summary` block (what analysis pass 6
    and `obs.regress` grade): capacity verdict against the backend's
    `hbm_bytes`, compile-time census coverage over the dispatch ledger,
    top-K footprints by temp-byte ceiling, per-span live-buffer
    watermarks, and (full=True) the donation audit. Collect it while
    the ledger snapshot still holds the run — the census itself
    survives `obs.set_enabled(False)` but coverage is judged against
    ledger records."""
    from combblas_tpu.obs import ledger as _ledger
    from combblas_tpu.obs import memledger as _memledger
    led = ledger if ledger is not None else _ledger.LEDGER
    out = _memledger.summary(ledger=led, k=k, full=full)
    wm = _memledger.span_watermarks()
    if wm:
        out["span_watermarks"] = {
            name: b for name, b in sorted(
                wm.items(), key=lambda kv: -kv[1])[:k]}
    return out


# ---------------------------------------------------------------------------
# Human report tree (self/total per span path)
# ---------------------------------------------------------------------------

def report(tracer: Tracer | None = None,
           records: list[SpanRecord] | None = None) -> dict:
    """Aggregate records by PATH into a nested tree:
    {name: {"calls", "total_s", "self_s", "category", "children": {...}}}.
    Paths aggregate across repeats (every window/iteration of a loop
    folds into one node)."""
    recs = records if records is not None else _records(tracer)
    root: dict = {}
    for r in sorted(recs, key=lambda r: len(r.path)):
        level = root
        for name in r.path[:-1]:
            node = level.get(name)
            if node is None:   # orphan (parent open or dropped): stub it
                node = level[name] = {"calls": 0, "total_s": 0.0,
                                      "self_s": 0.0, "category": None,
                                      "children": {}}
            level = node["children"]
        node = level.setdefault(r.path[-1], {
            "calls": 0, "total_s": 0.0, "self_s": 0.0,
            "category": r.category, "children": {}})
        node["calls"] += 1
        node["total_s"] += r.total_s
        node["self_s"] += r.self_s
    return root


def format_report(tracer: Tracer | None = None, indent: int = 2,
                  min_s: float = 0.0) -> str:
    """Render the report tree for terminals: one line per span path,
    total/self seconds, call count, category."""
    lines = [f"{'span':<44} {'total_s':>10} {'self_s':>10} "
             f"{'calls':>7}  category"]

    def walk(tree: dict, depth: int):
        for name, node in sorted(tree.items(),
                                 key=lambda kv: -kv[1]["total_s"]):
            if node["total_s"] >= min_s:
                label = " " * (indent * depth) + name
                lines.append(
                    f"{label:<44} {node['total_s']:>10.4f} "
                    f"{node['self_s']:>10.4f} {node['calls']:>7}  "
                    f"{node['category'] or '-'}")
            walk(node["children"], depth + 1)

    walk(report(tracer), 0)
    bd = phase_breakdown(tracer)
    total = bd.pop("total")
    lines.append(f"{'-- breakdown --':<44} {total:>10.4f}")
    for k, v in sorted(bd.items(), key=lambda kv: -kv[1]):
        pct = 100.0 * v / total if total else 0.0
        lines.append(f"  {k:<42} {v:>10.4f} {pct:>9.1f}%")
    hist_lines = _histogram_lines()
    if hist_lines:
        lines.append(f"{'-- histograms --':<44} "
                     f"{'count':>10} {'p50':>10} {'p90':>10} {'p99':>10}")
        lines.extend(hist_lines)
    return "\n".join(lines)


def _fmt_q(v) -> str:
    return "-" if v is None else f"{v:.4g}"


def _histogram_lines() -> list[str]:
    """One line per histogram series in the registry: count + p50/p90/
    p99 from the bounded sample window (metrics.Histogram)."""
    from combblas_tpu.obs import metrics as _metrics
    lines = []
    for name, snap in sorted(_metrics.REGISTRY.snapshot().items()):
        if snap["type"] != "histogram":
            continue
        for s in snap["series"]:
            labels = ",".join(f"{k}={v}" for k, v in
                              sorted(s["labels"].items()))
            label = f"{name}{{{labels}}}" if labels else name
            lines.append(
                f"  {label:<42} {s['count']:>10} "
                f"{_fmt_q(s['p50']):>10} {_fmt_q(s['p90']):>10} "
                f"{_fmt_q(s['p99']):>10}")
    return lines


# ---------------------------------------------------------------------------
# JSON-lines event log (round-trippable)
# ---------------------------------------------------------------------------

def to_jsonl(path, tracer: Tracer | None = None,
             include_metrics: bool = True) -> int:
    """One JSON object per completed span; returns the record count.
    A trailing `{"type": "metrics", ...}` line carries the registry
    snapshot (counters/gauges/histograms incl. p50/p90/p99) when it is
    non-empty — `read_jsonl` skips it, so span round-trips hold."""
    recs = _records(tracer)
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r.to_dict()) + "\n")
        if include_metrics:
            from combblas_tpu.obs import metrics as _metrics
            snap = _metrics.REGISTRY.snapshot()
            if snap:
                f.write(json.dumps({"type": "metrics",
                                    "metrics": snap}) + "\n")
    return len(recs)


def read_jsonl(path) -> list[SpanRecord]:
    out = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            d = json.loads(line)
            if "type" in d:      # metrics (or other non-span) line
                continue
            out.append(SpanRecord(
                d["name"], d["category"], d["t0"], d["t1"], d["depth"],
                tuple(d["path"]), d["tid"], d["attrs"], d["children_s"]))
    return out


def read_jsonl_metrics(path) -> dict | None:
    """The registry snapshot embedded by `to_jsonl`, or None."""
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            d = json.loads(line)
            if d.get("type") == "metrics":
                return d["metrics"]
    return None


# ---------------------------------------------------------------------------
# Chrome trace (chrome://tracing / https://ui.perfetto.dev)
# ---------------------------------------------------------------------------

def chrome_trace(path, tracer: Tracer | None = None,
                 include_ledger: bool = True,
                 include_mesh: bool = True) -> int:
    """Emit complete ("ph": "X") events, microsecond timestamps
    rebased to the earliest span. Category and attrs land in `args`;
    `cat` enables Perfetto's category filter.

    Ledger dispatches ride along as X events on a synthetic
    `pid=1` "dispatch" track, and every record carrying a trace id
    additionally emits async FLOW events ("b"/"e" with `id` = the
    trace id) so one request's dispatches link across threads in
    Perfetto's flow view.

    `include_mesh` adds the mesh observatory's per-device view on
    `pid=2`: one track per device label with registered loads
    (`obs.meshobs.register_device_loads`), an X event per dispatch of
    every device-attributed executable, and per-rung collective FLOW
    events tying a broadcast's source track to its destination —
    descriptors naming foreign or missing device ids still render (a
    synthetic track id is minted), they just don't line up with a
    load-attributed track."""
    recs = _records(tracer)
    led_recs = []
    if include_ledger:
        from combblas_tpu.obs import ledger as _ledger
        led_recs = _ledger.LEDGER.snapshot()
    t_base = min((r.t0 for r in recs + led_recs), default=0.0)
    events = [{
        "name": r.name,
        "cat": r.category or "other",
        "ph": "X",
        "ts": (r.t0 - t_base) * 1e6,
        "dur": r.total_s * 1e6,
        "pid": 0,
        "tid": r.tid % 2 ** 31,   # Chrome wants a small-ish int
        "args": {"path": "/".join(r.path), "self_s": round(r.self_s, 6),
                 **r.attrs},
    } for r in recs]
    for r in led_recs:
        base = {
            "name": r.name,
            "cat": f"ledger_{r.kind}",
            "ts": (r.t0 - t_base) * 1e6,
            "pid": 1,
            "tid": r.tid % 2 ** 31,
            "args": {"seq": r.seq, "path": "/".join(r.path),
                     "arg_bytes": r.arg_bytes, "out_bytes": r.out_bytes,
                     "compiled": r.compiled,
                     "trace_id": r.trace_id or ""},
        }
        events.append({**base, "ph": "X", "dur": r.wall_s * 1e6})
        if r.trace_id:
            # async begin/end pair: Perfetto draws a flow arrow per
            # trace id spanning every dispatch that carried it
            try:
                fid = int(r.trace_id.lstrip("t"), 16) & 0x7FFFFFFF
            except ValueError:      # externally-minted id: any string
                fid = hash(r.trace_id) & 0x7FFFFFFF
            events.append({**base, "ph": "b", "id": fid,
                           "cat": "request"})
            events.append({**base, "ph": "e", "id": fid,
                           "cat": "request",
                           "ts": (r.t0 + r.wall_s - t_base) * 1e6})
    if include_mesh and led_recs:
        events.extend(_mesh_events(led_recs, t_base))
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)


def _mesh_events(led_recs, t_base: float) -> list:
    """Per-device Chrome-trace tracks (pid=2) from the mesh
    observatory: thread-name metadata per known device label, one X
    event per (device-attributed dispatch, device) carrying the static
    loads, and per-rung collective flow events linking a descriptor's
    `src` track to its `dst` track. Device ids outside the load
    registry (foreign) or absent (missing) get a synthetic hashed
    track id — never a crash."""
    from combblas_tpu.obs import meshobs as _meshobs
    loads = _meshobs.device_loads()
    descs = _meshobs.descriptors()
    known = sorted({dev for row in loads.values()
                    for grid in row.values() for dev in grid})
    tid_of = {dev: i for i, dev in enumerate(known)}
    # missing src/dst: a dedicated sentinel track one past the foreign
    # hash range (1024..1024+0x7FFF), never a real device's track
    none_tid = 1024 + 0x8000

    def dev_tid(label):
        if label is None:
            return none_tid
        t = tid_of.get(label)
        # foreign id: a stable synthetic track clear of the real ones
        return t if t is not None else 1024 + (hash(label) & 0x7FFF)

    events = [{"ph": "M", "pid": 2, "name": "process_name",
               "args": {"name": "mesh devices"}},
              {"ph": "M", "pid": 2, "tid": none_tid,
               "name": "thread_name", "args": {"name": "<no device>"}}]
    for dev, t in tid_of.items():
        events.append({"ph": "M", "pid": 2, "tid": t,
                       "name": "thread_name", "args": {"name": dev}})
    for r in led_recs:
        if r.kind != "dispatch":
            continue
        row = loads.get(r.name)
        if row:
            per_dev: dict = {}
            for metric, grid in row.items():
                for dev, v in grid.items():
                    per_dev.setdefault(dev, {})[metric] = v
            for dev, metrics in per_dev.items():
                events.append({
                    "name": r.name, "cat": "mesh_device", "ph": "X",
                    "ts": (r.t0 - t_base) * 1e6,
                    "dur": max(r.wall_s, 1e-6) * 1e6,
                    "pid": 2, "tid": dev_tid(dev),
                    "args": {"device": dev, "seq": r.seq, **metrics},
                })
        for d in descs.get(r.name, ()):
            fid = (r.seq * 131 + d["rung"]) & 0x7FFFFFFF
            base = {
                "name": f"{r.name}/{d['collective']}@{d['axis']}",
                "cat": "collective", "pid": 2, "id": fid,
                "args": {"seq": r.seq, "rung": d["rung"],
                         "bytes": d["bytes"], "axis": d["axis"],
                         "dtype": d["dtype"],
                         "shape": list(d["shape"]),
                         "src": d.get("src"), "dst": d.get("dst")},
            }
            events.append({**base, "ph": "b",
                           "tid": dev_tid(d.get("src")),
                           "ts": (r.t0 - t_base) * 1e6})
            events.append({**base, "ph": "e",
                           "tid": dev_tid(d.get("dst")),
                           "ts": (r.t0 + max(r.wall_s, 1e-6)
                                  - t_base) * 1e6})
    return events


# ---------------------------------------------------------------------------
# jax.profiler bridge (XLA op-level breakdown; TensorBoard/xprof)
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def profiler_trace(logdir: str):
    """jax.profiler trace context — the XLA-level phase breakdown
    (open the logdir with TensorBoard / xprof). The spans above answer
    "where did the wall clock go"; this answers "which XLA ops"."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
