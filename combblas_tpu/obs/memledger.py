"""HBM memory ledger: compile-time footprint census, live-buffer
watermarks, and the donation audit.

The flight recorder (PR 6) attributes *time* and the roofline ledger
(PR 10) attributes *FLOPs and bytes per second*; this module closes the
third roofline axis — memory CAPACITY. Three instruments, all built on
facts XLA already computed:

* **Compile-time footprint census.** Every XLA compile in the process
  funnels through one choke point (`jax._src.compiler.
  compile_or_get_cached`); a one-time wrapper records each loaded
  executable's `CompiledMemoryStats` (argument / output / temp /
  generated-code / donation-alias bytes) plus the HLO module name and
  the compiling thread. `obs.ledger.instrument` wrappers then CLAIM the
  census entries their call produced (same thread, recorded during the
  call), so footprints join `DispatchRecord`s, `top_k`, `format_table`,
  and `dispatch_summary` under the ledger's own executable names —
  including every PlanCache build, whose plans are instrumented
  wrappers already. Capturing at the compile hook is free: the stats
  are a handful of attribute reads next to a multi-second compile.
* **Live-buffer watermarks.** `jax.live_arrays()` sampled at span
  close (env-gated cadence, `COMBBLAS_TPU_MEM_WATERMARK=N` = every Nth
  close) yields per-span HBM watermarks and a monotone peak-resident
  gauge — the measured side the footprint census predicts.
* **Donation audit.** Call sites that declare `donate_argnums` register
  via `declare_donation(name, argnums)`; `audit_donations()` cross-
  checks each declared name against its compiled executables'
  `input_output_alias` HLO header (parsed at record time — the
  executable type is not weakref-able) and the census's
  `alias_size_in_bytes`, flagging declared-but-unhonored donations
  with the executable name and arg indices. `min_honored` exists
  because donation is legitimately partial when output shapes change
  (mcl.megastep's `new_cap` re-pin): the audit asserts "at least N
  parameters aliased", not full-leaf coverage.

Analysis pass 6 (`analysis/membudget.py`) gates the resulting
`memory_summary` artifact blocks against `budgets/memory.json` and the
`hbm_bytes` field of `utils.config.backend_peaks`.

Everything is lazy about jax: importing this module costs nothing, and
the census hook installs on the first `ensure_installed()` (which
`obs.ledger.instrument` calls at wrap time). COMBBLAS_TPU_MEM_CENSUS=0
disables recording entirely.
"""

from __future__ import annotations

import itertools
import os
import re
import threading
import time

#: one `{out_idx}: (param, {param_idx}, kind)` entry in an HLO module
#: header's `input_output_alias={...}` section; group 1 = param number
_ALIAS_ENTRY = re.compile(r"\{[\d,\s]*\}\s*:\s*\((\d+)")

#: census stops recording past this many compiles (a process has a few
#: hundred distinct executables; this is a runaway backstop, surfaced
#: via `census_dropped`)
_CENSUS_CAP = 4096

_LOCK = threading.Lock()
_CENSUS: list = []            # FootprintRecord, append-only until reset
_CENSUS_DROPPED = 0
_CENSUS_SEQ = itertools.count()
_INSTALLED = False
#: name -> aggregated footprint dict (claimed names survive ledger
#: wraps and cache clears — once an executable is attributed, its
#: footprint stays known)
_BY_NAME: dict = {}

_DONATIONS: dict = {}         # name -> {"argnums": tuple, "min_honored"}

# -- live-buffer watermarks -------------------------------------------------
_WM_EVERY = int(os.environ.get("COMBBLAS_TPU_MEM_WATERMARK", "0") or 0)
_WM_TICK = itertools.count()
_WM_SAMPLES = 0
_PEAK_RESIDENT = 0
_SPAN_WM: dict = {}           # span name -> max live bytes at a close
_WM_SERIES: list = []         # (perf_counter, bytes) samples, bounded
_WM_SERIES_CAP = 4096


def census_enabled() -> bool:
    return os.environ.get("COMBBLAS_TPU_MEM_CENSUS", "1").lower() \
        not in ("0", "false")


class FootprintRecord:
    """One compiled executable's memory analysis (immutable except for
    the ledger-name claim)."""

    __slots__ = ("seq", "module", "name", "tid", "t0", "arg_bytes",
                 "out_bytes", "temp_bytes", "code_bytes", "alias_bytes",
                 "alias_params")

    def __init__(self, seq, module, tid, t0, arg_bytes, out_bytes,
                 temp_bytes, code_bytes, alias_bytes, alias_params=None):
        self.seq = seq
        self.module = module          # HLO module name ("jit__place3")
        self.name = None              # ledger name once claimed
        self.tid = tid
        self.t0 = t0
        self.arg_bytes = arg_bytes
        self.out_bytes = out_bytes
        self.temp_bytes = temp_bytes
        self.code_bytes = code_bytes
        self.alias_bytes = alias_bytes  # donated bytes XLA aliased
        self.alias_params = alias_params  # tuple of aliased parameter
        #                                   numbers from the HLO header
        #                                   (None: header unparsable)

    @property
    def total_bytes(self) -> int:
        """Resident footprint ceiling of one execution: arguments +
        outputs + temporaries (aliased argument bytes are not double-
        counted by XLA's output size)."""
        return self.arg_bytes + self.out_bytes + self.temp_bytes

    def to_dict(self) -> dict:
        return {"seq": self.seq, "module": self.module, "name": self.name,
                "arg_bytes": self.arg_bytes, "out_bytes": self.out_bytes,
                "temp_bytes": self.temp_bytes,
                "code_bytes": self.code_bytes,
                "alias_bytes": self.alias_bytes,
                "alias_params": list(self.alias_params)
                if self.alias_params is not None else None}

    def __repr__(self):
        return (f"FootprintRecord(#{self.seq} {self.module!r} "
                f"name={self.name!r} total={self.total_bytes})")


def _record_executable(ex) -> None:
    """Drop one census record for a freshly compiled executable. Never
    raises — a census failure must not break a compile."""
    global _CENSUS_DROPPED
    try:
        # re-check at record time: the hook stays installed for the
        # process lifetime, so the env gate must also silence it live
        if not census_enabled():
            return
        st = ex.get_compiled_memory_stats()
        module, alias_params = "?", None
        try:
            hm = ex.hlo_modules()[0]
            module = hm.name
            # the HLO header lists the aliases XLA actually HONORED
            # (`input_output_alias={ {0}: (0, {}, may-alias) }`) —
            # LoadedExecutable is not weakref-able, so extract now;
            # to_string is microseconds next to the compile it follows
            header = hm.to_string().split("\n", 1)[0]
            if "input_output_alias" in header:
                seg = header.split("input_output_alias=", 1)[1]
                alias_params = tuple(sorted(
                    {int(m.group(1))
                     for m in _ALIAS_ENTRY.finditer(seg)}))
            else:
                alias_params = ()
        except Exception:
            pass
        rec = FootprintRecord(
            next(_CENSUS_SEQ), module, threading.get_ident(),
            time.perf_counter(),
            int(st.argument_size_in_bytes), int(st.output_size_in_bytes),
            int(st.temp_size_in_bytes),
            int(st.generated_code_size_in_bytes),
            int(st.alias_size_in_bytes), alias_params)
        with _LOCK:
            if len(_CENSUS) < _CENSUS_CAP:
                _CENSUS.append(rec)
            else:
                _CENSUS_DROPPED += 1
    except Exception:
        pass


def ensure_installed() -> bool:
    """Install the compile-hook once (idempotent). Every XLA compile —
    jit dispatch misses, AOT `.compile()`, PlanCache builds — funnels
    through `jax._src.compiler.compile_or_get_cached`; wrapping it is
    the only way to see ALL executables without re-lowering (an AOT
    re-lower would be a full second compile). Returns True when the
    hook is active."""
    global _INSTALLED
    if _INSTALLED:
        return True
    if not census_enabled():
        return False
    with _LOCK:
        if _INSTALLED:
            return True
        try:
            from jax._src import compiler as _compiler
        except Exception:      # pragma: no cover - exotic jax
            return False
        orig = _compiler.compile_or_get_cached

        def _hooked(*args, **kwargs):
            ex = orig(*args, **kwargs)
            _record_executable(ex)
            return ex

        _hooked.__wrapped__ = orig
        _compiler.compile_or_get_cached = _hooked
        _INSTALLED = True
        return True


def census_len() -> int:
    """Cheap pre-call snapshot for claim bracketing (list len is
    GIL-atomic)."""
    return len(_CENSUS)


def census_dropped() -> int:
    return _CENSUS_DROPPED


def claim_census(pre_len: int, name: str, tid: int | None = None):
    """Attribute census entries recorded since ``pre_len`` on the
    calling thread to ledger name ``name`` (innermost instrumented
    wrapper wins: nested wrappers claim before their callers see the
    entries). Returns the summed footprint ceiling of newly claimed
    executables, or None when nothing was claimed."""
    if pre_len < 0:
        return None
    tid = threading.get_ident() if tid is None else tid
    total = None
    with _LOCK:
        for rec in _CENSUS[pre_len:]:
            if rec.name is None and rec.tid == tid:
                rec.name = name
                agg = _BY_NAME.get(name)
                if agg is None:
                    agg = _BY_NAME[name] = {
                        "name": name, "executables": 0, "modules": [],
                        "arg_bytes": 0, "out_bytes": 0, "temp_bytes": 0,
                        "code_bytes": 0, "alias_bytes": 0,
                        "total_bytes": 0}
                agg["executables"] += 1
                if rec.module not in agg["modules"]:
                    agg["modules"].append(rec.module)
                # ceilings, not sums: a name compiled at several shapes
                # costs at most its largest executable per dispatch
                for k in ("arg_bytes", "out_bytes", "temp_bytes",
                          "code_bytes", "alias_bytes", "total_bytes"):
                    agg[k] = max(agg[k], getattr(
                        rec, k if k != "total_bytes" else "total_bytes"))
                total = (total or 0) + rec.total_bytes
    return total


def footprint_for(name: str):
    """Aggregated compile-time footprint for a ledger name:
    {arg_bytes, out_bytes, temp_bytes, code_bytes, alias_bytes,
    total_bytes, executables, modules} — per-field MAX across the
    name's claimed executables (the per-dispatch ceiling) — or None
    when no executable was ever attributed to the name."""
    with _LOCK:
        agg = _BY_NAME.get(name)
        return dict(agg) if agg else None


def census_snapshot() -> list:
    with _LOCK:
        return list(_CENSUS)


def census_stats() -> dict:
    with _LOCK:
        claimed = sum(1 for r in _CENSUS if r.name is not None)
        return {"executables": len(_CENSUS), "claimed": claimed,
                "dropped": _CENSUS_DROPPED, "names": len(_BY_NAME)}


def census_coverage(ledger=None, records=None) -> dict:
    """Did the census land where it could have? Over the dispatch-kind
    names in a ledger, `expected` counts names whose compile happened
    INSIDE an instrumented wrapper (>=1 record with compiled=True — the
    only compiles the census can attribute); `covered` counts expected
    names carrying a footprint. `frac` = covered/expected (1.0 when
    nothing compiled in-wrapper: a warm cache is not a census failure).
    The e2e test and the bench `memory_summary` blocks pin frac >= 0.9
    on cold phased-SpGEMM runs, where every dispatched executable
    compiles in-wrapper."""
    if records is None:
        from combblas_tpu.obs import ledger as _ledger
        records = (ledger if ledger is not None
                   else _ledger.LEDGER).snapshot()
    names = set()
    expected = set()
    for r in records:
        if r.kind != "dispatch":
            continue
        names.add(r.name)
        if r.compiled:
            expected.add(r.name)
    with _LOCK:
        covered = {n for n in expected if n in _BY_NAME}
        known = {n for n in names if n in _BY_NAME}
    return {"names": len(names), "expected": len(expected),
            "covered": len(covered), "with_footprint": len(known),
            "frac": round(len(covered) / len(expected), 4)
            if expected else 1.0}


# ---------------------------------------------------------------------------
# donation audit
# ---------------------------------------------------------------------------

def declare_donation(name: str, argnums, min_honored: int = 1,
                     waiver: str | None = None) -> None:
    """Register that the executable behind ledger name ``name`` is
    built with ``donate_argnums=argnums``. The audit then requires at
    least ``min_honored`` aliased parameters on every compiled
    executable attributed to the name (default 1: shape-changing paths
    like megastep's re-pin legally alias only part of the donation).

    ``waiver`` documents a site where XLA provably CANNOT alias (e.g.
    capacity grow/shrink: output bytes differ from input bytes, yet the
    donation still invalidates the input eagerly, which is the point).
    A waived site that fails ``min_honored`` reports status "waived"
    with the reason, not "unhonored" — declared, explained, visible."""
    with _LOCK:
        _DONATIONS[name] = {"argnums": tuple(argnums),
                            "min_honored": int(min_honored),
                            "waiver": waiver}


def declared_donations() -> dict:
    with _LOCK:
        return {k: dict(v) for k, v in _DONATIONS.items()}


def audit_donations(names=None) -> list:
    """Cross-check every declared donation against its compiled
    executables. One row per declared name:

        {name, argnums, min_honored, executables, honored_params,
         alias_bytes, status, ok}

    status: "honored" (>= min_honored aliased parameters on every
    attributed executable), "unhonored" (an executable aliased fewer —
    the declaration is a lie XLA silently ignored, the buffer is NOT
    released), "waived" (aliased fewer, but the declaration carries a
    documented waiver — ok=True), "unobserved" (no executable
    attributed yet — ok=None, not a failure: the site never dispatched
    this run)."""
    with _LOCK:
        decls = {k: dict(v) for k, v in _DONATIONS.items()
                 if names is None or k in names}
        by_name: dict = {}
        for rec in _CENSUS:
            if rec.name in decls:
                by_name.setdefault(rec.name, []).append(rec)
    out = []
    for name in sorted(decls):
        d = decls[name]
        recs = by_name.get(name, [])
        row = {"name": name, "argnums": list(d["argnums"]),
               "min_honored": d["min_honored"],
               "executables": len(recs), "honored_params": [],
               "alias_bytes": 0}
        if not recs:
            row["status"], row["ok"] = "unobserved", None
            out.append(row)
            continue
        ok = True
        honored: set = set()
        for rec in recs:
            if rec.alias_params is None:
                # header unparsable: the census's alias byte count
                # still tells us whether ANY donation was honored
                n = 1 if rec.alias_bytes > 0 else 0
            else:
                honored |= set(rec.alias_params)
                n = len(rec.alias_params)
            row["alias_bytes"] = max(row["alias_bytes"], rec.alias_bytes)
            if n < d["min_honored"]:
                ok = False
        row["honored_params"] = sorted(honored)
        if ok:
            row["status"], row["ok"] = "honored", True
        elif d.get("waiver"):
            row["status"], row["ok"] = "waived", True
            row["waiver"] = d["waiver"]
        else:
            row["status"], row["ok"] = "unhonored", False
        out.append(row)
    return out


# ---------------------------------------------------------------------------
# live-buffer watermarks
# ---------------------------------------------------------------------------

def sample_live_bytes():
    """Total bytes of live committed jax Arrays, or None when jax is
    unavailable. One pass over `jax.live_arrays()` — cheap attribute
    reads, no device syncs."""
    try:
        import jax
        return sum(int(getattr(a, "nbytes", 0) or 0)
                   for a in jax.live_arrays())
    except Exception:
        return None


def set_watermark_cadence(every: int) -> None:
    """Sample live bytes at every Nth span close (0 = off). Installs
    the span-close hook on first arm."""
    global _WM_EVERY
    _WM_EVERY = max(int(every), 0)
    if _WM_EVERY > 0:
        from combblas_tpu.obs import trace as _trace
        _trace.set_span_close_hook(_on_span_close)


def watermark_cadence() -> int:
    return _WM_EVERY


def _on_span_close(rec) -> None:
    """trace.Tracer hook: sample at the configured cadence and fold
    into the per-span watermark + peak gauge. Monotone-safe under
    concurrent spans: all folds are max-updates under one lock."""
    every = _WM_EVERY
    if every <= 0:
        return
    if next(_WM_TICK) % every:
        return
    note_live_sample(span=rec.name)


def note_live_sample(span: str | None = None):
    """Take one live-buffer sample NOW and fold it into the peak gauge
    (and the span watermark when ``span`` is given). Returns the sample
    bytes (None when unavailable). Bench harnesses call this at their
    high-water moments even when cadence sampling is off."""
    global _PEAK_RESIDENT, _WM_SAMPLES
    b = sample_live_bytes()
    if b is None:
        return None
    now = time.perf_counter()
    with _LOCK:
        _WM_SAMPLES += 1
        if b > _PEAK_RESIDENT:
            _PEAK_RESIDENT = b
        if span is not None and b > _SPAN_WM.get(span, -1):
            _SPAN_WM[span] = b
        if len(_WM_SERIES) < _WM_SERIES_CAP:
            _WM_SERIES.append((now, b))
    return b


def peak_resident_bytes() -> int:
    return _PEAK_RESIDENT


def watermark_samples() -> int:
    return _WM_SAMPLES


def span_watermarks() -> dict:
    """span name -> max live bytes observed at one of its closes."""
    with _LOCK:
        return dict(_SPAN_WM)


def watermark_series() -> list:
    """Time-ordered (perf_counter, live_bytes) samples (bounded at
    4096; timeline.resident_watermark windows over these)."""
    with _LOCK:
        return list(_WM_SERIES)


# ---------------------------------------------------------------------------
# capacity verdict + summary block
# ---------------------------------------------------------------------------

def hbm_bytes(peaks=None) -> float:
    if peaks is None:
        from combblas_tpu.utils.config import backend_peaks
        peaks = backend_peaks()
    return float(peaks.hbm_bytes)


def headroom(peaks=None) -> dict:
    """{hbm_bytes, peak_resident_bytes, largest_footprint_bytes,
    headroom_frac}: the fraction of capacity NOT spoken for by the
    worst of (measured peak, largest single-executable footprint)."""
    cap = hbm_bytes(peaks)
    with _LOCK:
        largest = max((a["total_bytes"] for a in _BY_NAME.values()),
                      default=0)
    worst = max(_PEAK_RESIDENT, largest)
    return {"hbm_bytes": cap, "peak_resident_bytes": _PEAK_RESIDENT,
            "largest_footprint_bytes": largest,
            "headroom_frac": round(max(1.0 - worst / cap, 0.0), 4)
            if cap > 0 else None}


def configured_headroom_frac() -> float:
    """COMBBLAS_TPU_MEM_HEADROOM (default 0.8): the fraction of
    `backend_peaks().hbm_bytes` a single plan's implied working set may
    claim before a planner emits `obs.mem_headroom_warn`. Read per
    call so tests can flip it without re-importing."""
    try:
        return float(os.environ.get("COMBBLAS_TPU_MEM_HEADROOM", "0.8"))
    except ValueError:
        return 0.8


def warn_working_set(working_set_bytes: int, kind: str) -> bool:
    """Planner-side OOM-risk check: compare an implied working set
    against `hbm_bytes * configured_headroom_frac()`; when it does not
    fit, bump the `obs.mem_headroom_warn` counter (labeled by ``kind``)
    and record the offending estimate on a gauge. Returns True when
    the warning fired. This is the cheap PLAN-time signal; the
    membudget gate and the live watermarks confirm at run time."""
    budget = hbm_bytes() * configured_headroom_frac()
    if working_set_bytes <= budget:
        return False
    from combblas_tpu.obs import metrics as _metrics
    _metrics.counter(
        "obs.mem_headroom_warn",
        "plans whose implied working set exceeded the configured "
        "fraction of the backend's HBM capacity").inc(kind=kind)
    _metrics.gauge(
        "obs.mem_working_set_bytes",
        "last working-set estimate that tripped the headroom warning"
    ).set(int(working_set_bytes), kind=kind)
    return True


def top_footprints(k: int = 8) -> list:
    """Top-K claimed names by temp-byte ceiling (the budget pass's
    per-executable currency)."""
    with _LOCK:
        rows = [dict(a) for a in _BY_NAME.values()]
    rows.sort(key=lambda a: a["temp_bytes"], reverse=True)
    return rows[:max(k, 0)]


def summary(ledger=None, k: int = 8, full: bool = True) -> dict:
    """The `memory_summary` block bench artifacts embed next to
    `dispatch_summary` (and pass 6 gates): capacity verdict, census
    coverage, top footprints, and (full=True) the donation audit. Takes
    one fresh live-buffer sample so `peak_resident_bytes` is never
    vacuously zero when cadence sampling is off."""
    note_live_sample()
    out = {
        **headroom(),
        "watermark_samples": _WM_SAMPLES,
        "census": census_stats(),
        "census_coverage": census_coverage(ledger=ledger),
        "top": top_footprints(k),
    }
    if full:
        audit = audit_donations()
        out["donation_audit"] = {
            "declared": len(audit),
            "unhonored": [r["name"] for r in audit if r["ok"] is False],
            "waived": [r["name"] for r in audit
                       if r["status"] == "waived"],
            "unobserved": [r["name"] for r in audit if r["ok"] is None],
            "entries": audit,
        }
    return out


def reset(donations: bool = False) -> None:
    """Clear the census, attributions, and watermarks (tests). The
    donation REGISTRY survives by default — declarations happen at
    import time and don't recur."""
    global _CENSUS_DROPPED, _PEAK_RESIDENT, _WM_SAMPLES
    with _LOCK:
        _CENSUS.clear()
        _BY_NAME.clear()
        _SPAN_WM.clear()
        _WM_SERIES.clear()
        _CENSUS_DROPPED = 0
        _PEAK_RESIDENT = 0
        _WM_SAMPLES = 0
        if donations:
            _DONATIONS.clear()


# env-armed cadence must also install the span-close hook — without
# this, COMBBLAS_TPU_MEM_WATERMARK set before import arms the counter
# but never samples
if _WM_EVERY > 0:
    set_watermark_cadence(_WM_EVERY)
