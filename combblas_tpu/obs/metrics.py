"""Metrics registry: labeled counters, gauges, and histograms.

The quantities that are NOT wall time — nnz processed, flops planned,
bytes read back from device, compile-cache (CapLadder) hits/misses,
phase counts. Prometheus-shaped (name + sorted label set -> series)
but in-process only: `REGISTRY.snapshot()` returns plain dicts for the
bench JSON artifacts.

Gated on the same process-wide flag as spans (`trace.set_enabled`):
disabled updates are one flag check. Registration itself is always
allowed (module-level handles are cheap and keep hot loops free of
dict lookups).
"""

from __future__ import annotations

import bisect
import threading

from combblas_tpu.obs import trace as _trace


def _key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class Counter:
    """Monotonically increasing sum per label set."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._series: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, value: float = 1, **labels) -> None:
        if not _trace._ENABLED:
            return
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        k = _key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0) + value

    def value(self, **labels) -> float:
        return self._series.get(_key(labels), 0)

    def snapshot(self) -> dict:
        return {"type": "counter", "help": self.help,
                "series": [{"labels": dict(k), "value": v}
                           for k, v in sorted(self._series.items())]}

    def reset(self) -> None:
        with self._lock:
            self._series.clear()


class Gauge:
    """Last-write-wins value per label set."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._series: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels) -> None:
        if not _trace._ENABLED:
            return
        with self._lock:
            self._series[_key(labels)] = value

    def value(self, **labels):
        return self._series.get(_key(labels))

    def snapshot(self) -> dict:
        return {"type": "gauge", "help": self.help,
                "series": [{"labels": dict(k), "value": v}
                           for k, v in sorted(self._series.items())]}

    def reset(self) -> None:
        with self._lock:
            self._series.clear()


#: power-of-4 default bounds: 1 .. 4^15 ≈ 1.07e9 covers counts from
#: single entries to the 2^30 expansion ceiling in 16 buckets
_DEFAULT_BOUNDS = tuple(4 ** k for k in range(16))


class Histogram:
    """Cumulative-bucket histogram per label set (Prometheus shape:
    bucket[i] counts observations <= bounds[i]; +Inf is implicit via
    `count`). Tracks sum/count/min/max too."""

    def __init__(self, name: str, help: str = "",
                 bounds: tuple = _DEFAULT_BOUNDS):
        self.name = name
        self.help = help
        self.bounds = tuple(sorted(bounds))
        self._series: dict[tuple, dict] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels) -> None:
        if not _trace._ENABLED:
            return
        k = _key(labels)
        with self._lock:
            s = self._series.get(k)
            if s is None:
                s = self._series[k] = {
                    "buckets": [0] * len(self.bounds), "sum": 0.0,
                    "count": 0, "min": value, "max": value}
            i = bisect.bisect_left(self.bounds, value)
            if i < len(self.bounds):
                s["buckets"][i] += 1
            s["sum"] += value
            s["count"] += 1
            s["min"] = min(s["min"], value)
            s["max"] = max(s["max"], value)

    def series(self, **labels) -> dict | None:
        s = self._series.get(_key(labels))
        if s is None:
            return None
        # cumulative buckets on read (updates stay O(1) per observe)
        cum, tot = [], 0
        for b in s["buckets"]:
            tot += b
            cum.append(tot)
        return {**s, "buckets": cum, "bounds": list(self.bounds)}

    def snapshot(self) -> dict:
        return {"type": "histogram", "help": self.help,
                "bounds": list(self.bounds),
                "series": [{"labels": dict(k), **self.series(**dict(k))}
                           for k in sorted(self._series)]}

    def reset(self) -> None:
        with self._lock:
            self._series.clear()


class Registry:
    """Name -> metric map. Re-registering a name returns the existing
    metric (so module-level handles in different files can share one
    series) but a TYPE clash is an error."""

    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_make(self, cls, name, *args, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, *args, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_make(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_make(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  bounds: tuple = _DEFAULT_BOUNDS) -> Histogram:
        return self._get_or_make(Histogram, name, help, bounds)

    def snapshot(self) -> dict:
        """{name: snapshot} for every metric that has data."""
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.snapshot() for name, m in items
                if m.snapshot()["series"]}

    def reset(self) -> None:
        """Clear every metric's series (registrations persist)."""
        with self._lock:
            items = list(self._metrics.values())
        for m in items:
            m.reset()


REGISTRY = Registry()

counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
