"""Metrics registry: labeled counters, gauges, and histograms.

The quantities that are NOT wall time — nnz processed, flops planned,
bytes read back from device, compile-cache (CapLadder) hits/misses,
phase counts. Prometheus-shaped (name + sorted label set -> series)
but in-process only: `REGISTRY.snapshot()` returns plain dicts for the
bench JSON artifacts.

Gated on the same process-wide flag as spans (`trace.set_enabled`):
disabled updates are one flag check. Registration itself is always
allowed (module-level handles are cheap and keep hot loops free of
dict lookups).

Thread-safety contract (the serve workers emit from multiple threads):
every mutation AND every read of a metric's series dict happens under
that metric's lock — snapshots copy under the lock and then format
outside it, so a concurrent `observe` can never tear an iteration.
The registry's name->metric map is likewise locked. (Span stacks are
per-thread already — `trace.Tracer` keeps them in `threading.local`.)
"""

from __future__ import annotations

import bisect
import math
import threading

from combblas_tpu.obs import trace as _trace


def _key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class Counter:
    """Monotonically increasing sum per label set."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._series: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, value: float = 1, **labels) -> None:
        if not _trace._ENABLED:
            return
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        k = _key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0) + value

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(_key(labels), 0)

    def snapshot(self) -> dict:
        with self._lock:
            items = sorted(self._series.items())
        return {"type": "counter", "help": self.help,
                "series": [{"labels": dict(k), "value": v}
                           for k, v in items]}

    def reset(self) -> None:
        with self._lock:
            self._series.clear()


class Gauge:
    """Last-write-wins value per label set."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._series: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels) -> None:
        if not _trace._ENABLED:
            return
        with self._lock:
            self._series[_key(labels)] = value

    def value(self, **labels):
        with self._lock:
            return self._series.get(_key(labels))

    def snapshot(self) -> dict:
        with self._lock:
            items = sorted(self._series.items())
        return {"type": "gauge", "help": self.help,
                "series": [{"labels": dict(k), "value": v}
                           for k, v in items]}

    def reset(self) -> None:
        with self._lock:
            self._series.clear()


#: power-of-4 default bounds: 1 .. 4^15 ≈ 1.07e9 covers counts from
#: single entries to the 2^30 expansion ceiling in 16 buckets
_DEFAULT_BOUNDS = tuple(4 ** k for k in range(16))

#: per-series cap of raw samples kept for percentile summaries. Beyond
#: the cap the buffer becomes a ring over the MOST RECENT observations
#: (a sliding window — for serving latency the recent window is the
#: interesting one anyway).
_RESERVOIR = 2048

_QUANTILES = (("p50", 0.50), ("p90", 0.90), ("p99", 0.99))


class P2Quantile:
    """Streaming quantile estimate via the P² algorithm (Jain &
    Chlamtac 1985): five markers whose heights approximate the
    [min, p/2, p, (1+p)/2, max] quantile curve, adjusted per
    observation with a parabolic (fallback linear) step. O(1) memory
    and update; exact below five samples. Unlike the sliding reservoir
    this summarizes the FULL run, so unbounded soaks keep honest tail
    percentiles."""

    __slots__ = ("p", "_n", "_q", "_npos", "_dn")

    def __init__(self, p: float):
        self.p = p
        self._n = 0          # samples seen
        self._q = []         # marker heights (sorted)
        self._npos = [1, 2, 3, 4, 5]            # actual positions
        self._dn = (0.0, p / 2, p, (1 + p) / 2, 1.0)  # position incs

    def observe(self, x: float) -> None:
        self._n += 1
        if self._n <= 5:
            bisect.insort(self._q, x)
            return
        q, npos = self._q, self._npos
        if x < q[0]:
            q[0] = x
            k = 0
        elif x >= q[4]:
            q[4] = x
            k = 3
        else:
            k = 0
            while x >= q[k + 1]:
                k += 1
        for i in range(k + 1, 5):
            npos[i] += 1
        # desired positions drift by dn per sample; nudge the three
        # interior markers toward them by at most one slot
        for i in (1, 2, 3):
            want = 1 + (self._n - 1) * self._dn[i]
            d = want - npos[i]
            if ((d >= 1 and npos[i + 1] - npos[i] > 1)
                    or (d <= -1 and npos[i - 1] - npos[i] < -1)):
                d = 1 if d >= 1 else -1
                qn = self._parabolic(i, d)
                if not (q[i - 1] < qn < q[i + 1]):
                    qn = self._linear(i, d)
                q[i] = qn
                npos[i] += d

    def _parabolic(self, i: int, d: int) -> float:
        q, n = self._q, self._npos
        return q[i] + d / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + d) * (q[i + 1] - q[i])
            / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - d) * (q[i] - q[i - 1])
            / (n[i] - n[i - 1]))

    def _linear(self, i: int, d: int) -> float:
        q, n = self._q, self._npos
        return q[i] + d * (q[i + d] - q[i]) / (n[i + d] - n[i])

    def value(self) -> float | None:
        if self._n == 0:
            return None
        if self._n <= 5:
            # exact nearest-rank while the markers are raw samples
            i = min(self._n - 1,
                    max(0, math.ceil(self.p * self._n) - 1))
            return self._q[i]
        return self._q[2]


def _percentiles(samples: list) -> dict:
    """Nearest-rank p50/p90/p99 of a raw-sample list (empty -> None)."""
    if not samples:
        return {q: None for q, _ in _QUANTILES}
    srt = sorted(samples)
    out = {}
    for name, p in _QUANTILES:
        i = min(len(srt) - 1, max(0, math.ceil(p * len(srt)) - 1))
        out[name] = srt[i]
    return out


class Histogram:
    """Cumulative-bucket histogram per label set (Prometheus shape:
    bucket[i] counts observations <= bounds[i]; +Inf is implicit via
    `count`). Tracks sum/count/min/max, plus a bounded raw-sample
    window (`_RESERVOIR` most recent) from which `series()` reports
    p50/p90/p99 — so latency percentiles are readable straight from a
    snapshot without bucket interpolation. `use_sketch(True)` switches
    the percentile source to streaming P² sketches (full-run, O(1)
    memory) for this metric; the reservoir stays the default."""

    def __init__(self, name: str, help: str = "",
                 bounds: tuple = _DEFAULT_BOUNDS):
        self.name = name
        self.help = help
        self.bounds = tuple(sorted(bounds))
        self._series: dict[tuple, dict] = {}
        self._sketch = False
        self._lock = threading.Lock()

    def use_sketch(self, on: bool = True) -> None:
        """Toggle P² streaming quantiles for this metric. Sketches
        start accumulating at the NEXT observe; series already holding
        sketch state keep it (toggling off just stops reporting from
        it)."""
        with self._lock:
            self._sketch = bool(on)

    def observe(self, value: float, **labels) -> None:
        if not _trace._ENABLED:
            return
        k = _key(labels)
        with self._lock:
            s = self._series.get(k)
            if s is None:
                s = self._series[k] = {
                    "buckets": [0] * len(self.bounds), "sum": 0.0,
                    "count": 0, "min": value, "max": value,
                    "samples": []}
            i = bisect.bisect_left(self.bounds, value)
            if i < len(self.bounds):
                s["buckets"][i] += 1
            samples = s["samples"]
            if len(samples) < _RESERVOIR:
                samples.append(value)
            else:
                samples[s["count"] % _RESERVOIR] = value
            if self._sketch:
                sk = s.get("sketch")
                if sk is None:
                    sk = s["sketch"] = {
                        q: P2Quantile(p) for q, p in _QUANTILES}
                for est in sk.values():
                    est.observe(value)
            s["sum"] += value
            s["count"] += 1
            s["min"] = min(s["min"], value)
            s["max"] = max(s["max"], value)

    def series(self, **labels) -> dict | None:
        with self._lock:
            s = self._series.get(_key(labels))
            if s is None:
                return None
            # copy under the lock; format outside it
            pcts = None
            if self._sketch and "sketch" in s:
                pcts = {q: est.value()
                        for q, est in s["sketch"].items()}
            s = {**s, "buckets": list(s["buckets"]),
                 "samples": list(s["samples"])}
        # cumulative buckets on read (updates stay O(1) per observe)
        cum, tot = [], 0
        for b in s["buckets"]:
            tot += b
            cum.append(tot)
        samples = s.pop("samples")
        s.pop("sketch", None)
        if pcts is None:
            pcts = _percentiles(samples)
        return {**s, "buckets": cum, "bounds": list(self.bounds),
                **pcts}

    def snapshot(self) -> dict:
        with self._lock:
            keys = sorted(self._series)
        return {"type": "histogram", "help": self.help,
                "bounds": list(self.bounds),
                "series": [{"labels": dict(k), **self.series(**dict(k))}
                           for k in keys]}

    def reset(self) -> None:
        with self._lock:
            self._series.clear()


class Registry:
    """Name -> metric map. Re-registering a name returns the existing
    metric (so module-level handles in different files can share one
    series) but a TYPE clash is an error."""

    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_make(self, cls, name, *args, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, *args, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_make(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_make(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  bounds: tuple = _DEFAULT_BOUNDS) -> Histogram:
        return self._get_or_make(Histogram, name, help, bounds)

    def snapshot(self) -> dict:
        """{name: snapshot} for every metric that has data."""
        with self._lock:
            items = list(self._metrics.items())
        snaps = {name: m.snapshot() for name, m in items}
        return {name: s for name, s in snaps.items() if s["series"]}

    def reset(self) -> None:
        """Clear every metric's series (registrations persist)."""
        with self._lock:
            items = list(self._metrics.values())
        for m in items:
            m.reset()


REGISTRY = Registry()

counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
