"""Metrics registry: labeled counters, gauges, and histograms.

The quantities that are NOT wall time — nnz processed, flops planned,
bytes read back from device, compile-cache (CapLadder) hits/misses,
phase counts. Prometheus-shaped (name + sorted label set -> series)
but in-process only: `REGISTRY.snapshot()` returns plain dicts for the
bench JSON artifacts.

Gated on the same process-wide flag as spans (`trace.set_enabled`):
disabled updates are one flag check. Registration itself is always
allowed (module-level handles are cheap and keep hot loops free of
dict lookups).

Thread-safety contract (the serve workers emit from multiple threads):
every mutation AND every read of a metric's series dict happens under
that metric's lock — snapshots copy under the lock and then format
outside it, so a concurrent `observe` can never tear an iteration.
The registry's name->metric map is likewise locked. (Span stacks are
per-thread already — `trace.Tracer` keeps them in `threading.local`.)
"""

from __future__ import annotations

import bisect
import math
import threading

from combblas_tpu.obs import trace as _trace


def _key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class Counter:
    """Monotonically increasing sum per label set."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._series: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, value: float = 1, **labels) -> None:
        if not _trace._ENABLED:
            return
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        k = _key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0) + value

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(_key(labels), 0)

    def snapshot(self) -> dict:
        with self._lock:
            items = sorted(self._series.items())
        return {"type": "counter", "help": self.help,
                "series": [{"labels": dict(k), "value": v}
                           for k, v in items]}

    def reset(self) -> None:
        with self._lock:
            self._series.clear()


class Gauge:
    """Last-write-wins value per label set."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._series: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels) -> None:
        if not _trace._ENABLED:
            return
        with self._lock:
            self._series[_key(labels)] = value

    def value(self, **labels):
        with self._lock:
            return self._series.get(_key(labels))

    def snapshot(self) -> dict:
        with self._lock:
            items = sorted(self._series.items())
        return {"type": "gauge", "help": self.help,
                "series": [{"labels": dict(k), "value": v}
                           for k, v in items]}

    def reset(self) -> None:
        with self._lock:
            self._series.clear()


#: power-of-4 default bounds: 1 .. 4^15 ≈ 1.07e9 covers counts from
#: single entries to the 2^30 expansion ceiling in 16 buckets
_DEFAULT_BOUNDS = tuple(4 ** k for k in range(16))

#: per-series cap of raw samples kept for percentile summaries. Beyond
#: the cap the buffer becomes a ring over the MOST RECENT observations
#: (a sliding window — for serving latency the recent window is the
#: interesting one anyway).
_RESERVOIR = 2048

_QUANTILES = (("p50", 0.50), ("p90", 0.90), ("p99", 0.99))


def _percentiles(samples: list) -> dict:
    """Nearest-rank p50/p90/p99 of a raw-sample list (empty -> None)."""
    if not samples:
        return {q: None for q, _ in _QUANTILES}
    srt = sorted(samples)
    out = {}
    for name, p in _QUANTILES:
        i = min(len(srt) - 1, max(0, math.ceil(p * len(srt)) - 1))
        out[name] = srt[i]
    return out


class Histogram:
    """Cumulative-bucket histogram per label set (Prometheus shape:
    bucket[i] counts observations <= bounds[i]; +Inf is implicit via
    `count`). Tracks sum/count/min/max, plus a bounded raw-sample
    window (`_RESERVOIR` most recent) from which `series()` reports
    p50/p90/p99 — so latency percentiles are readable straight from a
    snapshot without bucket interpolation."""

    def __init__(self, name: str, help: str = "",
                 bounds: tuple = _DEFAULT_BOUNDS):
        self.name = name
        self.help = help
        self.bounds = tuple(sorted(bounds))
        self._series: dict[tuple, dict] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels) -> None:
        if not _trace._ENABLED:
            return
        k = _key(labels)
        with self._lock:
            s = self._series.get(k)
            if s is None:
                s = self._series[k] = {
                    "buckets": [0] * len(self.bounds), "sum": 0.0,
                    "count": 0, "min": value, "max": value,
                    "samples": []}
            i = bisect.bisect_left(self.bounds, value)
            if i < len(self.bounds):
                s["buckets"][i] += 1
            samples = s["samples"]
            if len(samples) < _RESERVOIR:
                samples.append(value)
            else:
                samples[s["count"] % _RESERVOIR] = value
            s["sum"] += value
            s["count"] += 1
            s["min"] = min(s["min"], value)
            s["max"] = max(s["max"], value)

    def series(self, **labels) -> dict | None:
        with self._lock:
            s = self._series.get(_key(labels))
            if s is None:
                return None
            # copy under the lock; format outside it
            s = {**s, "buckets": list(s["buckets"]),
                 "samples": list(s["samples"])}
        # cumulative buckets on read (updates stay O(1) per observe)
        cum, tot = [], 0
        for b in s["buckets"]:
            tot += b
            cum.append(tot)
        samples = s.pop("samples")
        return {**s, "buckets": cum, "bounds": list(self.bounds),
                **_percentiles(samples)}

    def snapshot(self) -> dict:
        with self._lock:
            keys = sorted(self._series)
        return {"type": "histogram", "help": self.help,
                "bounds": list(self.bounds),
                "series": [{"labels": dict(k), **self.series(**dict(k))}
                           for k in keys]}

    def reset(self) -> None:
        with self._lock:
            self._series.clear()


class Registry:
    """Name -> metric map. Re-registering a name returns the existing
    metric (so module-level handles in different files can share one
    series) but a TYPE clash is an error."""

    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_make(self, cls, name, *args, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, *args, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_make(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_make(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  bounds: tuple = _DEFAULT_BOUNDS) -> Histogram:
        return self._get_or_make(Histogram, name, help, bounds)

    def snapshot(self) -> dict:
        """{name: snapshot} for every metric that has data."""
        with self._lock:
            items = list(self._metrics.items())
        snaps = {name: m.snapshot() for name, m in items}
        return {name: s for name, s in snaps.items() if s["series"]}

    def reset(self) -> None:
        """Clear every metric's series (registrations persist)."""
        with self._lock:
            items = list(self._metrics.values())
        for m in items:
            m.reset()


REGISTRY = Registry()

counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
