"""Roofline cost-model registry: expected costs joined to the ledger.

The flight recorder (`obs.ledger`) measures *actuals* — wall per
executable name. The planners already compute the *expected* work
exactly at plan time: per-window multiply counts in `WinPlan.flops`,
per-stage exchange bytes in `plan_bcast`, nnz-proportional traversal
costs for SpMV/BFS. This module is the join point: planners call
`annotate(name, flops=..., lbytes=..., cbytes=...)` as they plan, and
`join_rows` decorates `top_k` aggregates with achieved FLOP/s, achieved
B/s, a compute-/memory-/ICI-bound classification, and the roofline
efficiency fraction

    eff = max(flops/peak_flops, lbytes/peak_mem, cbytes/peak_ici)
          / measured_wall

against the per-backend peak table in `utils.config.backend_peaks`.

Conventions (coarse by design — the point is attribution and trend,
not a cycle-accurate simulator):

* `annotate` ACCUMULATES: totals and a call count. Per-call expected
  cost is totals/calls, so both styles work — exact per-window
  accumulation (phased SpGEMM annotates every window it plans) and
  one-shot per-call registration (`annotate_matrix` registers the
  nnz-proportional cost of one SpMV and relies on calls=1).
* one semiring multiply-add counts as 2 flops; a COO slot is 12 bytes
  (i32 row + i32 col + f32 val).
* plan-time records with zero wall (e.g. the `spgemm.bcast/*` byte
  ledger rows) join as annotated-but-rate-free: they count toward
  attributable coverage, not toward achieved-rate statistics.

The registry is process-global like the default ledger; `reset()`
clears it (tests), and `snapshot()`/`registry_size()` feed `/varz`.

NOTE: `utils.config` is imported lazily inside functions — at module
level it would cycle (utils.config -> models.mcl -> parallel.spgemm
-> obs -> costmodel).
"""

from __future__ import annotations

import threading

_LOCK = threading.Lock()

#: name -> [flops_total, local_bytes_total, collective_bytes_total, calls]
_COSTS: dict = {}


def annotate(name: str, *, flops: float = 0, lbytes: float = 0,
             cbytes: float = 0, calls: int = 1) -> None:
    """Accumulate an expected-cost annotation for a ledger executable
    name. Safe to call from any planner thread; cheap enough for
    per-window plan loops."""
    with _LOCK:
        row = _COSTS.get(name)
        if row is None:
            _COSTS[name] = [float(flops), float(lbytes), float(cbytes),
                            int(calls)]
        else:
            row[0] += flops
            row[1] += lbytes
            row[2] += cbytes
            row[3] += calls


def cost_for(name: str):
    """Per-call expected cost for a name: dict(flops, lbytes, cbytes,
    calls) or None when the name was never annotated."""
    with _LOCK:
        row = _COSTS.get(name)
        if row is None:
            return None
        f, lb, cb, n = row
    n = max(n, 1)
    return {"flops": f / n, "lbytes": lb / n, "cbytes": cb / n,
            "calls": n}


def registry_size() -> int:
    with _LOCK:
        return len(_COSTS)


def snapshot() -> dict:
    """name -> {flops, lbytes, cbytes, calls} totals (for /varz)."""
    with _LOCK:
        rows = {k: list(v) for k, v in _COSTS.items()}
    return {k: {"flops": v[0], "lbytes": v[1], "cbytes": v[2],
                "calls": v[3]} for k, v in rows.items()}


def reset() -> None:
    with _LOCK:
        _COSTS.clear()


def roofline_time_s(flops: float, lbytes: float, cbytes: float,
                    peaks=None) -> tuple:
    """(best-case time, bound class) for a cost triple: the roofline
    time is the max of the three component times, the bound class is
    which component set it ("compute" | "memory" | "ici")."""
    if peaks is None:
        from combblas_tpu.utils.config import backend_peaks
        peaks = backend_peaks()
    t_c = flops / peaks.flops_per_s
    t_m = lbytes / peaks.mem_bytes_per_s
    t_i = cbytes / peaks.ici_bytes_per_s
    t = max(t_c, t_m, t_i)
    bound = ("compute" if t == t_c else
             "memory" if t == t_m else "ici")
    return t, bound


def join_rows(rows: list, peaks=None) -> list:
    """Decorate `ledger.top_k` rows in place with the cost-model join:

        annotated   bool — a cost annotation exists for the name
        flops       expected flops across the row's calls (or None)
        gflops_s    achieved GFLOP/s (None when unannotated/zero-wall)
        gbytes_s    achieved local GB/s (ditto)
        bound       "compute" | "memory" | "ici" (roofline argmax)
        eff         roofline-efficiency fraction in [0, ~1] (can
                    exceed 1 when the coarse model under-counts work)

    Rows whose name has no annotation get annotated=False and None for
    every derived field — `format_table` renders those blank."""
    if peaks is None:
        from combblas_tpu.utils.config import backend_peaks
        peaks = backend_peaks()
    for row in rows:
        c = cost_for(row["name"])
        if c is None:
            row["annotated"] = False
            row["flops"] = row["gflops_s"] = row["gbytes_s"] = None
            row["bound"] = row["eff"] = None
            continue
        row["annotated"] = True
        n = row.get("count", 1)
        flops = c["flops"] * n
        lbytes = c["lbytes"] * n
        cbytes = c["cbytes"] * n
        row["flops"] = flops
        t_roof, bound = roofline_time_s(flops, lbytes, cbytes, peaks)
        row["bound"] = bound
        wall = row.get("total_s") or 0.0
        if wall <= 0:
            row["gflops_s"] = row["gbytes_s"] = row["eff"] = None
            continue
        row["gflops_s"] = round(flops / wall / 1e9, 3)
        row["gbytes_s"] = round((lbytes + cbytes) / wall / 1e9, 3)
        row["eff"] = round(min(t_roof / wall, 99.0), 4)
    return rows


def attributable_fraction(rows=None, ledger=None) -> float:
    """Fraction of total ledger wall carried by names that have a cost
    annotation — the "is the recorder explained" number the e2e test
    pins at >= 0.9 for a phased-SpGEMM run. Zero-wall rows count as
    attributed (they are plan-time byte records)."""
    if rows is None:
        from combblas_tpu.obs import ledger as _ledger
        rows = _ledger.top_k(k=1 << 20, ledger=ledger)
    total = sum(r["total_s"] for r in rows)
    if total <= 0:
        return 1.0
    got = sum(r["total_s"] for r in rows
              if cost_for(r["name"]) is not None)
    return got / total


def efficiency_summary(rows=None, ledger=None, peaks=None) -> dict:
    """Aggregate roofline verdict over a set of top_k rows (defaults:
    every name in the default ledger): wall-weighted efficiency over
    annotated rows, attributable fraction, and per-bound-class wall
    split. This is the block `export.dispatch_summary` embeds in every
    bench artifact."""
    if peaks is None:
        from combblas_tpu.utils.config import backend_peaks
        peaks = backend_peaks()
    if rows is None:
        from combblas_tpu.obs import ledger as _ledger
        rows = _ledger.top_k(k=1 << 20, ledger=ledger)
    rows = join_rows(list(rows), peaks=peaks)
    wall_all = sum(r["total_s"] for r in rows)
    wall_ann = sum(r["total_s"] for r in rows if r["annotated"])
    eff_wall = sum(r["total_s"] * r["eff"] for r in rows
                   if r.get("eff") is not None)
    eff_base = sum(r["total_s"] for r in rows
                   if r.get("eff") is not None)
    by_bound: dict = {}
    for r in rows:
        if r["bound"] is not None:
            by_bound[r["bound"]] = round(
                by_bound.get(r["bound"], 0.0) + r["total_s"], 6)
    return {
        "attributable_frac": round(wall_ann / wall_all, 4)
        if wall_all > 0 else 1.0,
        "eff": round(eff_wall / eff_base, 4) if eff_base > 0 else None,
        "annotated_names": sum(r["annotated"] for r in rows),
        "names": len(rows),
        "bound_wall_s": by_bound,
        "backend": (peaks.name if peaks is not None else None),
    }


def efficiency_by(key_fn, rows=None, ledger=None, peaks=None) -> dict:
    """Wall-weighted efficiency grouped by `key_fn(name)` (None keys
    are skipped). serve uses this to publish per-request-kind gauges:
    key_fn maps "serve.bfs.bits/w32.l32" -> "bfs"."""
    if rows is None:
        from combblas_tpu.obs import ledger as _ledger
        rows = _ledger.top_k(k=1 << 20, ledger=ledger)
    rows = join_rows(list(rows), peaks=peaks)
    num: dict = {}
    den: dict = {}
    for r in rows:
        if r.get("eff") is None:
            continue
        key = key_fn(r["name"])
        if key is None:
            continue
        num[key] = num.get(key, 0.0) + r["total_s"] * r["eff"]
        den[key] = den.get(key, 0.0) + r["total_s"]
    return {k: round(num[k] / den[k], 4) for k in num if den[k] > 0}


def capacity_summary(peaks=None, k: int = 5) -> dict:
    """The CAPACITY side of the roofline (companion to
    `efficiency_summary`'s rate side): the backend's `hbm_bytes`
    ceiling against the memledger's measured peak-resident bytes and
    largest compile-time footprint, plus the top-K footprints by temp
    bytes. {hbm_bytes, peak_resident_bytes, largest_footprint_bytes,
    headroom_frac, backend, top_footprints}."""
    if peaks is None:
        from combblas_tpu.utils.config import backend_peaks
        peaks = backend_peaks()
    from combblas_tpu.obs import memledger as _memledger
    return {
        **_memledger.headroom(peaks),
        "backend": peaks.name,
        "top_footprints": _memledger.top_footprints(k),
    }


# ---------------------------------------------------------------------------
# Family annotators (per-call nnz-proportional models)
# ---------------------------------------------------------------------------

#: COO slot: i32 row + i32 col + f32 val
_SLOT = 12

#: per-call (flops, lbytes, cbytes) factors per nnz for the SpMV/BFS
#: families. One traversal touches each stored edge about once: 2
#: flops (semiring multiply+add) and one slot read + one accumulator
#: update per edge; mesh variants ship one frontier-sized vector per
#: fan stage (folded into cbytes_per_row below).
_MATRIX_FAMILIES = {
    # name: (flops/nnz, lbytes/nnz, cbytes/row, lbytes/row)
    "spmv.spmv":          (2.0, _SLOT + 4, 0.0, 8.0),
    "spmv.spmsv":         (2.0, _SLOT + 4, 0.0, 8.0),
    "spmv.local":         (2.0, _SLOT + 4, 0.0, 8.0),
    "spmv.fanout":        (0.0, 4.0, 4.0, 0.0),
    "spmv.fanin":         (0.0, 4.0, 4.0, 0.0),
    "bfs.bfs":            (2.0, _SLOT, 0.0, 8.0),
    "bfs.batch":          (2.0, _SLOT, 0.0, 8.0),
    "bfs.bits":           (2.0, _SLOT, 0.0, 1.0),
    "bfs.batch_bits":     (2.0, _SLOT, 0.0, 1.0),
    "bfs.bits_mesh":      (2.0, _SLOT, 1.0, 1.0),
    "bfs.batch_bits_mesh": (2.0, _SLOT, 1.0, 1.0),
    "bfs.plan_core":      (0.0, _SLOT, 0.0, 0.0),
    # graph500's fused traversal+stats executable: one BFS plus a
    # degree-weighted visited/edge reduction (4 extra bytes/row)
    "bfs.run_with_stats": (2.0, _SLOT, 0.0, 12.0),
    "bfs.degree_readback": (0.0, 0.0, 0.0, 4.0),
}

#: flat per-call byte costs (scalar readbacks — latency, not volume)
_MATRIX_FLAT = {
    "bfs.stats_readback": 8.0,
}


def annotate_matrix(a, names=None, calls: int = 1) -> None:
    """Register per-call costs for the nnz-proportional SpMV/BFS
    executables operating on matrix `a` (a DistSpMat — anything with
    `getnnz()` and `nrows` — or a plain (nnz, nrows) tuple). Called by
    `plan_bfs` and the SpMV drivers at plan time; re-planning the same
    matrix re-accumulates totals AND calls, so the per-call rate stays
    right."""
    if isinstance(a, tuple):
        nnz, nrows = a
    else:
        try:
            nnz = int(a.getnnz())
        except Exception:
            # plan_bfs runs under jit when `bfs` plans lazily: the nnz
            # counters are tracers there, so no host readback exists.
            # Skip the annotation — the eager plan-time call sites
            # (explicit plan_bfs, serve, spmsv_timed) still register.
            return
        nrows = int(a.nrows)
    fams = _MATRIX_FAMILIES if names is None else {
        k: v for k, v in _MATRIX_FAMILIES.items() if k in names}
    for name, (f_nnz, lb_nnz, cb_row, lb_row) in fams.items():
        annotate(name,
                 flops=f_nnz * nnz * calls,
                 lbytes=(lb_nnz * nnz + lb_row * nrows) * calls,
                 cbytes=cb_row * nrows * calls,
                 calls=calls)
    for name, lb in _MATRIX_FLAT.items():
        if names is None or name in names:
            annotate(name, lbytes=lb * calls, calls=calls)
