"""Continuous-benchmark trajectory: normalize every committed bench
artifact into ONE canonical, schema-validated series and detect
regressions against it.

The repo accumulates heterogeneous bench artifacts (`BENCH_r*.json`
Graph500 runs, `MCL_BENCH_*.json`, `MULTICHIP_*.json`,
`SERVE_BENCH.json`, `BITS_BENCH.json`, `ESC_MICROBENCH.json`) whose
shapes drifted across PRs — pre-PR-6 artifacts carry no
`dispatch_summary` at all, serve/multichip artifacts carry summaries
but no span residual. This module is the single place that knows all
of those shapes:

* `normalize_artifact(name, doc)` — one canonical run row per
  artifact (run id, workload, scale, backend, wall, headline value,
  dispatch/compile counts, exchanged bytes, efficiency, peak resident
  bytes + census coverage from the `memory_summary` block) with an
  explicit `schema` grade: "full" (dispatch_summary AND
  unaccounted_s), "partial" (summary only), "legacy" (pre-PR-6 —
  flagged, never crashed on) — plus an independent `mem_schema` grade
  for the memory block (None on pre-memledger artifacts: legacy
  artifacts keep their grade, nothing is retroactively rejected);
* `build_trajectory(root)` — the committed `BENCH_TRAJECTORY.json`
  (`scripts/bench_registry.py` is the CLI);
* `validate_run(run)` / `validate_artifact(doc)` — the schema gate:
  fresh artifacts missing `dispatch_summary` or `unaccounted_s` are
  REJECTED (SchemaError) unless explicitly allowed as partial;
* `compare(fresh, trajectory, bands)` — per-metric noise-banded
  regression verdicts (direction-aware: GTEPS up is good, wall down
  is good), consumed by `analysis.perfgate` (pass 5) and
  `bench_registry.py --check`.
"""

from __future__ import annotations

import json
import math
import pathlib
import re

SCHEMA_VERSION = "bench-trajectory/v1"

#: glob -> workload. Order matters: first match wins.
ARTIFACT_GLOBS = (
    ("BENCH_r*.json", "bfs"),
    ("MCL_BENCH_*.json", "mcl"),
    ("MULTICHIP_*.json", "multichip"),
    ("SERVE_BENCH*.json", "serve"),
    ("BITS_BENCH*.json", "bits"),
    ("ESC_MICROBENCH*.json", "esc"),
    ("CHAOS_r*.json", "chaos"),
)

#: canonical run-row fields (None allowed unless listed in _REQUIRED)
RUN_FIELDS = ("run_id", "artifact", "workload", "seq", "scale",
              "backend", "wall_s", "value", "unit", "dispatches",
              "compiles", "exchanged_bytes", "efficiency",
              "attributable_frac", "unaccounted_s", "schema",
              "peak_resident_bytes", "mem_census_frac", "mem_schema")

_REQUIRED = ("run_id", "artifact", "workload", "schema")

_SCHEMAS = ("full", "partial", "legacy")

#: memory-block grades: "full" = memory_summary with census coverage
#: AND donation audit; "partial" = a memory_summary missing one of
#: those; None = recorded before the memory ledger existed (legacy —
#: flagged, never crashed on, and the row keeps its `schema` grade)
_MEM_SCHEMAS = ("full", "partial", None)


class SchemaError(ValueError):
    """A bench artifact or trajectory violates the canonical schema."""


# ---------------------------------------------------------------------------
# artifact-shape helpers
# ---------------------------------------------------------------------------

def _collect_summaries(doc):
    """Every dispatch_summary block in the document, wherever nested
    (SERVE_BENCH keeps them under closed_loop/open_loop, BITS_BENCH
    under serve_dense/serve_bits, MCL/ESC/MULTICHIP at top level)."""
    out = []

    def walk(node):
        if isinstance(node, dict):
            ds = node.get("dispatch_summary")
            if isinstance(ds, dict):
                out.append(ds)
            for v in node.values():
                walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)

    walk(doc)
    return out


def _collect_memory_summaries(doc):
    """Every memory_summary block, wherever nested (same walk as
    dispatch_summary: serve artifacts keep one per mode)."""
    out = []

    def walk(node):
        if isinstance(node, dict):
            ms = node.get("memory_summary")
            if isinstance(ms, dict):
                out.append(ms)
            for v in node.values():
                walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)

    walk(doc)
    return out


def _memory_of(doc):
    """(peak_resident_bytes, mem_census_frac, mem_schema) from the
    artifact's memory_summary blocks. Peak is the worst of measured
    live-buffer peak and largest single-executable footprint across
    blocks; census frac is the WORST coverage (the gate's view).
    Legacy artifacts (no block) grade None — kept, never rejected."""
    blocks = _collect_memory_summaries(doc)
    if not blocks:
        return None, None, None
    peak = 0
    fracs = []
    full = True
    for ms in blocks:
        peak = max(peak,
                   int(_num(ms.get("peak_resident_bytes")) or 0),
                   int(_num(ms.get("largest_footprint_bytes")) or 0))
        cc = ms.get("census_coverage")
        if isinstance(cc, dict) and _num(cc.get("frac")) is not None:
            fracs.append(float(cc["frac"]))
        else:
            full = False
        if not isinstance(ms.get("donation_audit"), dict):
            full = False
    frac = round(min(fracs), 4) if fracs else None
    return peak, frac, ("full" if full and fracs else "partial")


def _find_key(doc, key):
    """First value for `key` anywhere in the document (depth-first)."""
    if isinstance(doc, dict):
        if key in doc:
            return doc[key]
        for v in doc.values():
            got = _find_key(v, key)
            if got is not None:
                return got
    elif isinstance(doc, list):
        for v in doc:
            got = _find_key(v, key)
            if got is not None:
                return got
    return None


def _num(v):
    try:
        f = float(v)
        return f if math.isfinite(f) else None
    except (TypeError, ValueError):
        return None


def _seq_of(name: str):
    m = re.search(r"_r(\d+)\.json$", name)
    return int(m.group(1)) if m else None


def _scale_of(doc, name: str):
    sc = _num(_find_key(doc, "scale"))
    if sc is not None:
        return int(sc)
    # graph500 headline metrics encode it: ..._scale22_ef16_...
    metric = _find_key(doc, "metric") or ""
    m = re.search(r"scale(\d+)", str(metric))
    if m:
        return int(m.group(1))
    n = _num(_find_key(doc, "n"))
    if n and n > 1:
        return int(round(math.log2(n)))
    return None


def _backend_of(doc):
    plat = _find_key(doc, "platform")
    if isinstance(plat, str) and plat:
        return plat
    tail = doc.get("tail") if isinstance(doc, dict) else None
    if isinstance(tail, str):
        m = re.search(r'"platform"\s*:\s*"(\w+)"', tail)
        if m:
            return m.group(1)
    return None


def _exchange_bytes(doc, summaries):
    """Collective bytes on the wire: arg_bytes of the exchange-named
    ledger rows in any summary, plus the explicit hybrid-exchange
    accounting MULTICHIP artifacts carry."""
    total = 0
    seen = False
    for s in summaries:
        for row in s.get("top", ()):
            name = row.get("name", "")
            if name.startswith("spgemm.bcast") or \
                    name.startswith("spmv.fan"):
                total += int(row.get("arg_bytes", 0) or 0)
                seen = True
    hyb = _num(_find_key(doc, "hybrid_bytes"))
    if hyb is not None:
        total += int(hyb)
        seen = True
    return total if seen else None


def _efficiency_of(summaries):
    """(roofline eff, attributable fraction) — wall-weighted over the
    `efficiency` blocks `export.dispatch_summary` embeds (PR 10+
    artifacts only)."""
    effs = []
    fracs = []
    for s in summaries:
        blk = s.get("efficiency")
        if isinstance(blk, dict):
            if blk.get("eff") is not None:
                effs.append(float(blk["eff"]))
            if blk.get("attributable_frac") is not None:
                fracs.append(float(blk["attributable_frac"]))
    eff = round(sum(effs) / len(effs), 4) if effs else None
    frac = round(sum(fracs) / len(fracs), 4) if fracs else None
    return eff, frac


def _wall_of(doc, workload):
    w = _num(doc.get("wall_s")) if isinstance(doc, dict) else None
    if w is not None:
        return w
    if workload == "serve":
        cl = doc.get("closed_loop") or {}
        return _num(cl.get("wall_s"))
    if workload == "bits":
        sb = doc.get("serve_bits") or {}
        return _num(sb.get("wall_s"))
    if workload == "multichip":
        sp = doc.get("spgemm") or {}
        return _num(sp.get("wall_auto_s"))
    if workload == "mcl":
        u = doc.get("unit")
        if u in ("s", "seconds"):
            return _num(doc.get("value"))
    return None


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

def workload_of(name: str):
    p = pathlib.PurePath(name).name
    for pat, wl in ARTIFACT_GLOBS:
        if pathlib.PurePath(p).match(pat):
            return wl
    return None


def classify(doc) -> tuple:
    """(schema grade, missing keys) for an artifact document."""
    summaries = _collect_summaries(doc)
    has_ds = bool(summaries)
    has_un = _find_key(doc, "unaccounted_s") is not None
    if has_ds and has_un:
        return "full", []
    if has_ds:
        return "partial", ["unaccounted_s"]
    missing = ["dispatch_summary"] + ([] if has_un else ["unaccounted_s"])
    return "legacy", missing


def validate_artifact(doc, name: str = "<artifact>",
                      allow_partial: bool = False) -> str:
    """Schema gate for FRESH artifacts: anything missing
    `dispatch_summary` or `unaccounted_s` is rejected. Committed
    pre-PR-6 artifacts are never validated through here — they are
    flagged `schema: legacy` by `normalize_artifact` instead of
    crashing the build."""
    schema, missing = classify(doc)
    if schema == "full":
        return schema
    if schema == "partial" and allow_partial:
        return schema
    raise SchemaError(
        f"{name}: bench artifact missing {'/'.join(missing)} — "
        f"re-run the bench with obs enabled (schema grade: {schema})")


def normalize_artifact(name: str, doc) -> dict:
    """One canonical run row for a committed artifact. Never raises on
    shape drift: unparseable fields become None and the row carries
    its `schema` grade."""
    wl = workload_of(name)
    if wl is None:
        raise SchemaError(f"{name}: not a recognized bench artifact")
    if not isinstance(doc, dict):
        raise SchemaError(f"{name}: artifact root must be an object")
    summaries = _collect_summaries(doc)
    schema, _missing = classify(doc)

    # headline value: graph500 runner artifacts wrap it in `parsed`
    # (None when the run's tail was truncated — BENCH_r04)
    src = doc
    parsed = doc.get("parsed")
    if wl == "bfs" and isinstance(parsed, dict):
        src = parsed
    value = _num(src.get("value"))
    unit = src.get("unit") if isinstance(src.get("unit"), str) else None
    if wl == "bits" and value is None:
        value = _num(doc.get("per_root_speedup"))
        unit = unit or "x_per_root"
    if wl == "multichip" and value is None:
        # the cross-round regression metric: warm best-of-N spgemm
        # exchange wall. The top-level `wall_s` (r07+) spans the WHOLE
        # bench including compiles — internally consistent with
        # `unaccounted_s` but not comparable run-to-run, so the band
        # rides `value` instead
        sp = doc.get("spgemm") or {}
        value = _num(sp.get("wall_auto_s"))
        unit = unit or "s"

    dispatches = sum(int(s.get("dispatches", 0) or 0)
                     for s in summaries) if summaries else None
    compiles = sum(int(s.get("compiles", 0) or 0)
                   for s in summaries) if summaries else None
    eff, frac = _efficiency_of(summaries)
    peak_b, mem_frac, mem_schema = _memory_of(doc)
    stem = pathlib.PurePath(name).name[:-len(".json")] \
        if name.endswith(".json") else pathlib.PurePath(name).name
    row = {
        "run_id": stem,
        "artifact": pathlib.PurePath(name).name,
        "workload": wl,
        "seq": _seq_of(pathlib.PurePath(name).name),
        "scale": _scale_of(doc, name),
        "backend": _backend_of(doc),
        "wall_s": _wall_of(doc, wl),
        "value": value,
        "unit": unit,
        "dispatches": dispatches,
        "compiles": compiles,
        "exchanged_bytes": _exchange_bytes(doc, summaries),
        "efficiency": eff,
        "attributable_frac": frac,
        "unaccounted_s": _num(_find_key(doc, "unaccounted_s")),
        "schema": schema,
        "peak_resident_bytes": peak_b,
        "mem_census_frac": mem_frac,
        "mem_schema": mem_schema,
    }
    validate_run(row)
    return row


def validate_run(run: dict) -> None:
    """Canonical-row validation: required keys present, schema grade
    known, numerics numeric. Raises SchemaError."""
    if not isinstance(run, dict):
        raise SchemaError("run row must be an object")
    for k in _REQUIRED:
        if not run.get(k):
            raise SchemaError(f"run row missing required field {k!r}")
    if run["schema"] not in _SCHEMAS:
        raise SchemaError(f"{run['run_id']}: unknown schema grade "
                          f"{run['schema']!r}")
    if run.get("mem_schema") not in _MEM_SCHEMAS:
        raise SchemaError(f"{run['run_id']}: unknown memory-schema "
                          f"grade {run['mem_schema']!r}")
    unknown = set(run) - set(RUN_FIELDS)
    if unknown:
        raise SchemaError(f"{run['run_id']}: unknown fields "
                          f"{sorted(unknown)}")
    for k in ("wall_s", "value", "efficiency", "attributable_frac",
              "unaccounted_s", "peak_resident_bytes",
              "mem_census_frac"):
        v = run.get(k)
        if v is not None and _num(v) is None:
            raise SchemaError(f"{run['run_id']}: field {k} not numeric: "
                              f"{v!r}")


def build_trajectory(root, generated_by: str = "bench_registry") -> dict:
    """Normalize every committed artifact under `root` into the
    canonical trajectory document. Deterministic order: (workload,
    seq, run_id)."""
    root = pathlib.Path(root)
    runs = []
    seen = set()
    for pat, _wl in ARTIFACT_GLOBS:
        for p in sorted(root.glob(pat)):
            if p.name in seen:
                continue
            seen.add(p.name)
            try:
                doc = json.loads(p.read_text())
            except (OSError, ValueError) as e:
                raise SchemaError(f"{p.name}: unreadable artifact: {e}")
            runs.append(normalize_artifact(p.name, doc))
    runs.sort(key=lambda r: (r["workload"], r["seq"] or 0, r["run_id"]))
    return {"schema": SCHEMA_VERSION, "generated_by": generated_by,
            "runs": runs}


def load_trajectory(path) -> dict:
    path = pathlib.Path(path)
    try:
        doc = json.loads(path.read_text())
    except (OSError, ValueError) as e:
        raise SchemaError(f"{path.name}: unreadable trajectory: {e}")
    if not isinstance(doc, dict) or doc.get("schema") != SCHEMA_VERSION:
        raise SchemaError(f"{path.name}: expected schema "
                          f"{SCHEMA_VERSION!r}, got "
                          f"{doc.get('schema') if isinstance(doc, dict) else type(doc).__name__!r}")
    for run in doc.get("runs", ()):
        validate_run(run)
    return doc


# ---------------------------------------------------------------------------
# regression detection
# ---------------------------------------------------------------------------

#: default per-metric noise bands when the budget file doesn't narrow
#: them: fractional tolerance around the direction-aware baseline.
DEFAULT_BANDS = (
    {"workload": "*", "metric": "value", "direction": "higher",
     "band_frac": 0.25},
)


def _band_applies(band, run):
    wl = band.get("workload", "*")
    return wl in ("*", run.get("workload"))


def _baseline(runs, metric, direction):
    """Direction-aware best over prior runs (ignoring Nones)."""
    vals = [r.get(metric) for r in runs if r.get(metric) is not None]
    if not vals:
        return None
    return max(vals) if direction == "higher" else min(vals)


def compare(fresh: dict, trajectory: dict, bands=None) -> list:
    """Noise-banded regression verdicts for one fresh canonical run
    against the committed trajectory. Returns violation dicts:
    {workload, metric, direction, band_frac, baseline, fresh, message}.

    Baseline = direction-aware best among trajectory runs of the same
    workload (restricted to the fresh run's scale when prior runs at
    that scale exist — cross-scale numbers are not comparable). A
    `higher` metric regresses when fresh < baseline*(1-band); `lower`
    when fresh > baseline*(1+band)."""
    validate_run(fresh)
    bands = list(bands) if bands is not None else list(DEFAULT_BANDS)
    pool = [r for r in trajectory.get("runs", ())
            if r.get("workload") == fresh.get("workload")
            and r.get("run_id") != fresh.get("run_id")]
    same_scale = [r for r in pool
                  if fresh.get("scale") is not None
                  and r.get("scale") == fresh.get("scale")]
    if same_scale:
        pool = same_scale
    out = []
    for band in bands:
        if not _band_applies(band, fresh):
            continue
        metric = band.get("metric", "value")
        direction = band.get("direction", "higher")
        frac = float(band.get("band_frac", 0.25))
        fv = fresh.get(metric)
        if fv is None:
            continue
        base = _baseline(pool, metric, direction)
        if base is None:
            continue
        if direction == "higher":
            bad = fv < base * (1.0 - frac)
        else:
            bad = fv > base * (1.0 + frac)
        if bad:
            out.append({
                "workload": fresh.get("workload"),
                "metric": metric,
                "direction": direction,
                "band_frac": frac,
                "baseline": base,
                "fresh": fv,
                "message": (
                    f"{fresh['run_id']}: {metric}={fv:g} regressed "
                    f"past the {frac:.0%} noise band around "
                    f"baseline {base:g} ({direction} is better)"),
            })
    return out


def newest_runs(trajectory: dict) -> dict:
    """workload -> highest-seq run (runs without a seq count as 0)."""
    out: dict = {}
    for r in trajectory.get("runs", ()):
        wl = r["workload"]
        cur = out.get(wl)
        if cur is None or (r.get("seq") or 0) >= (cur.get("seq") or 0):
            out[wl] = r
    return out
