"""Live metrics endpoint: stdlib-HTTP `/metrics` (Prometheus text
exposition), `/varz` (full JSON snapshot), `/healthz`.

Serving-side observability must be scrapeable while the service is
under load, and must stay OFF the dispatch path: the endpoint runs on
its own daemon thread (stdlib `ThreadingHTTPServer`, port 0 picks a
free port), and every handler only READS — registry snapshots copy
under per-metric locks, ledger snapshots are lock-free reads — so a
scrape never blocks a worker and never touches a device.

`prometheus_text` / `parse_prometheus` are pure functions so tests can
verify the exposition format round-trips without sockets.
"""

from __future__ import annotations

import http.server
import json
import re
import threading
import time

from combblas_tpu.obs import ledger as _ledger
from combblas_tpu.obs import metrics as _metrics

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_OK = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: quantile key ("p50") -> Prometheus quantile label value ("0.5")
_Q_LABEL = {"p50": "0.5", "p90": "0.9", "p99": "0.99"}


def _san(name: str) -> str:
    """Metric-name sanitizer: dots (our namespacing) -> underscores,
    anything else invalid -> underscore."""
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not out or not _NAME_OK.match(out):
        out = "_" + out
    return out


def _esc(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def _labels(d: dict, extra: dict | None = None) -> str:
    items = dict(d)
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(f'{_san(str(k))}="{_esc(v)}"'
                    for k, v in sorted(items.items()))
    return "{" + body + "}"


def _num(v) -> str:
    if v is None:
        return "NaN"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_text(snapshot: dict | None = None) -> str:
    """Render a `REGISTRY.snapshot()`-shaped dict as Prometheus text
    exposition format (version 0.0.4). Histograms emit the standard
    `_bucket`/`_sum`/`_count` family plus a SEPARATE `<name>_quantile`
    gauge family carrying the p50/p90/p99 estimates (reservoir or P²
    sketch, whichever the metric uses) — quantiles on a histogram
    family itself would be invalid exposition."""
    snap = snapshot if snapshot is not None else \
        _metrics.REGISTRY.snapshot()
    out = []
    for name in sorted(snap):
        m = snap[name]
        pname = _san(name)
        help_txt = m.get("help") or name
        mtype = m["type"]
        out.append(f"# HELP {pname} {_esc(help_txt)}")
        out.append(f"# TYPE {pname} {mtype}")
        if mtype in ("counter", "gauge"):
            for s in m["series"]:
                out.append(f"{pname}{_labels(s['labels'])} "
                           f"{_num(s['value'])}")
            continue
        # histogram: cumulative buckets + sum/count
        qlines = []
        for s in m["series"]:
            lbl = s["labels"]
            for bound, cum in zip(s["bounds"], s["buckets"]):
                out.append(f"{pname}_bucket"
                           f"{_labels(lbl, {'le': _num(bound)})} {cum}")
            out.append(f"{pname}_bucket{_labels(lbl, {'le': '+Inf'})} "
                       f"{s['count']}")
            out.append(f"{pname}_sum{_labels(lbl)} {_num(s['sum'])}")
            out.append(f"{pname}_count{_labels(lbl)} {s['count']}")
            for q, qv in _Q_LABEL.items():
                if s.get(q) is not None:
                    qlines.append(
                        f"{pname}_quantile"
                        f"{_labels(lbl, {'quantile': qv})} "
                        f"{_num(s[q])}")
        if qlines:
            out.append(f"# HELP {pname}_quantile "
                       f"{_esc(help_txt)} (streaming quantiles)")
            out.append(f"# TYPE {pname}_quantile gauge")
            out.extend(qlines)
    return "\n".join(out) + "\n"


def _unescape_label(v: str) -> str:
    """Single-pass left-to-right label-value unescape (inverse of
    `_esc`). Sequential str.replace passes are ORDER-BUGGY here: a
    literal backslash followed by 'n' renders as '\\\\n' and a later
    '\\n'-replace pass would wrongly decode the already-unescaped
    backslash + 'n' into a newline."""
    out = []
    i = 0
    n = len(v)
    while i < n:
        ch = v[i]
        if ch == "\\" and i + 1 < n:
            nxt = v[i + 1]
            out.append("\n" if nxt == "n" else nxt)
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)\s*$")
_LABEL_PAIR = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> dict:
    """Strict-enough parser for tests: validates every line is a
    well-formed comment or sample, every sample's family has a # TYPE,
    and no duplicate series. Returns {(name, labels_tuple): value}."""
    typed = {}
    series = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                raise ValueError(f"line {lineno}: bad comment {line!r}")
            if not _NAME_OK.match(parts[2]):
                raise ValueError(f"line {lineno}: bad metric name "
                                 f"{parts[2]!r}")
            if parts[1] == "TYPE":
                if len(parts) != 4 or parts[3] not in (
                        "counter", "gauge", "histogram", "summary",
                        "untyped"):
                    raise ValueError(f"line {lineno}: bad TYPE {line!r}")
                typed[parts[2]] = parts[3]
            continue
        m = _SAMPLE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        name = m.group("name")
        base = name
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf) and name[: -len(suf)] in typed:
                base = name[: -len(suf)]
                break
        if base not in typed:
            raise ValueError(f"line {lineno}: sample {name!r} has no "
                             f"# TYPE declaration")
        raw = m.group("labels") or ""
        labels = tuple(sorted((k, _unescape_label(v))
                              for k, v in _LABEL_PAIR.findall(raw)))
        consumed = sum(len(k) + len(v) + 4 for k, v in
                       _LABEL_PAIR.findall(raw))
        if raw and consumed < len(raw.rstrip(",")):
            raise ValueError(f"line {lineno}: bad labels {raw!r}")
        key = (name, labels)
        if key in series:
            raise ValueError(f"line {lineno}: duplicate series {key}")
        val = m.group("value")
        series[key] = float("nan") if val == "NaN" else float(val)
    return series


def refresh_obs_gauges() -> None:
    """Publish the ledger's own health as metrics, refreshed at scrape
    time: `obs.ledger_dropped` (ring-overflow count — non-zero means
    the recorder silently truncated and dispatch counts under-report),
    `obs.ledger_total`, `obs.ledger_capacity`,
    `obs.instrumented_registry_size`, and
    `obs.costmodel_registry_size` (annotated-name count) — plus the
    memory ledger's capacity gauges: `obs.mem_peak_resident_bytes`
    (high-water live-buffer sample), `obs.mem_census_executables`
    (compiles seen by the footprint census), and
    `obs.mem_headroom_frac` (1 - worst(peak, largest footprint)/HBM)."""
    from combblas_tpu.obs import costmodel as _costmodel
    from combblas_tpu.obs import memledger as _memledger
    led = _ledger.LEDGER
    _metrics.gauge("obs.ledger_dropped",
                   "dispatch records lost to ring wrap").set(led.dropped)
    _metrics.gauge("obs.ledger_total",
                   "dispatch records ever written").set(led.total)
    _metrics.gauge("obs.ledger_capacity",
                   "dispatch ring capacity").set(led.capacity)
    _metrics.gauge("obs.instrumented_registry_size",
                   "instrumented executable names").set(
        len(_ledger.INSTRUMENTED))
    _metrics.gauge("obs.costmodel_registry_size",
                   "ledger names with cost annotations").set(
        _costmodel.registry_size())
    hr = _memledger.headroom()
    _metrics.gauge("obs.mem_peak_resident_bytes",
                   "peak live-buffer bytes sampled").set(
        hr["peak_resident_bytes"])
    _metrics.gauge("obs.mem_census_executables",
                   "compiles recorded by the footprint census").set(
        _memledger.census_len())
    if hr["headroom_frac"] is not None:
        _metrics.gauge("obs.mem_headroom_frac",
                       "1 - worst(peak, largest footprint) / hbm_bytes"
                       ).set(hr["headroom_frac"])
    # mesh observatory: measured collective bytes per (name, axis),
    # per-name drift ratios, load-skew, attribution coverage
    from combblas_tpu.obs import meshobs as _meshobs
    _meshobs.refresh_gauges()


def varz_snapshot(extra=None, top_k: int = 10) -> dict:
    """JSON-ready full snapshot: metrics registry + ledger top-K (with
    the roofline join) + cost-model coverage + the memory ledger's
    capacity block (headroom, census stats, top footprints — NOT the
    donation audit, which re-walks the census per declared name and
    stays off the scrape path; fetch it via `export.memory_summary`)
    + the mesh observatory's full block (measured collective bytes per
    (name, collective, axis), drift table, skew gauges) under "mesh"
    + whatever the hosting service adds via `extra()` (e.g.
    GraphService stats/plan-cache hit rates)."""
    from combblas_tpu.obs import costmodel as _costmodel
    from combblas_tpu.obs import memledger as _memledger
    from combblas_tpu.obs import meshobs as _meshobs
    refresh_obs_gauges()
    led = _ledger.LEDGER
    out = {
        "ts": time.time(),
        "metrics": _metrics.REGISTRY.snapshot(),
        "ledger": {
            "total": led.total,
            "dropped": led.dropped,
            "capacity": led.capacity,
            "top": _ledger.top_k(top_k),
            "instrumented": sorted(_ledger.INSTRUMENTED),
            "instrumented_count": len(_ledger.INSTRUMENTED),
        },
        "costmodel": {
            "registry_size": _costmodel.registry_size(),
            "efficiency": _costmodel.efficiency_summary(),
        },
        "memory": {
            **_memledger.headroom(),
            "census": _memledger.census_stats(),
            "watermark_samples": _memledger.watermark_samples(),
            "top_footprints": _memledger.top_footprints(top_k),
        },
        "mesh": _meshobs.mesh_summary(),
    }
    if extra is not None:
        try:
            out["service"] = extra()
        except Exception as e:          # scrape must not 500 on a race
            out["service"] = {"error": repr(e)}
    return out


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = "combblas-obs/1"

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):      # noqa: N802 (stdlib API name)
        path = self.path.split("?", 1)[0]
        try:
            if path == "/healthz":
                extra = self.server.varz_fn
                healthy = True
                if extra is not None:
                    svc = varz_snapshot(extra).get("service", {})
                    healthy = bool(svc.get("healthy", True)) \
                        if isinstance(svc, dict) else True
                self._send(200 if healthy else 503,
                           b"ok\n" if healthy else b"unhealthy\n",
                           "text/plain; charset=utf-8")
            elif path == "/metrics":
                self._refresh()
                body = prometheus_text().encode()
                self._send(200, body,
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/varz":
                self._refresh(skip_obs=True)   # varz_snapshot refreshes
                body = json.dumps(varz_snapshot(self.server.varz_fn),
                                  indent=1, default=str).encode()
                self._send(200, body, "application/json")
            else:
                self._send(404, b"not found\n",
                           "text/plain; charset=utf-8")
        except BrokenPipeError:          # scraper went away mid-write
            pass

    def _refresh(self, skip_obs: bool = False) -> None:
        """Scrape-time gauge refresh: the ledger-health gauges plus
        the host's `pre_scrape` hook (serve uses it to publish
        per-kind efficiency and SLO burn-rate). Never 500s a scrape."""
        try:
            if not skip_obs:
                refresh_obs_gauges()
            hook = getattr(self.server, "pre_scrape_fn", None)
            if hook is not None:
                hook()
        except Exception:
            pass

    def log_message(self, *a):           # keep worker stdout clean
        pass


class MetricsServer:
    """Daemon-thread HTTP server exposing /metrics, /varz, /healthz.

    `varz` is an optional zero-arg callable returning a JSON-ready dict
    merged into /varz under "service" (and consulted for a "healthy"
    key by /healthz). `pre_scrape` is an optional zero-arg callable
    run before each /metrics or /varz render so the host can refresh
    gauges that are only worth computing at scrape time (serve's
    per-kind efficiency and SLO burn-rate)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 varz=None, pre_scrape=None):
        self._httpd = http.server.ThreadingHTTPServer(
            (host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.varz_fn = varz
        self._httpd.pre_scrape_fn = pre_scrape
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-httpd",
            daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def serve_metrics(port: int = 0, host: str = "127.0.0.1",
                  varz=None, pre_scrape=None) -> MetricsServer:
    """Start the endpoint; returns the running server (port 0 = pick a
    free port; read `.port`/`.url`)."""
    return MetricsServer(port=port, host=host, varz=varz,
                         pre_scrape=pre_scrape)
