"""Dispatch ledger: a per-dispatch flight recorder at the jitted-
executable boundary.

The span tree (`obs.trace`) answers "which PHASE did the wall go to";
it cannot say which EXECUTABLE or which readback a residual went to —
the round-5 verdict's 63% unaccounted MCL expansion wall was exactly
that blindness. This module closes the gap: drivers wrap their jitted
callables once via `instrument(fn, name)` and every subsequent device
dispatch drops one `DispatchRecord` into a lock-free ring buffer —
sequence id, executable name, arg shapes/bytes, host call wall,
compile-triggered flag, readback bytes, enclosing span path, and the
current trace id.

Design constraints (all load-bearing):

* DISABLED MODE IS FREE. When the ledger is off the wrapper calls
  straight through — no arg inspection, no allocation, no device
  syncs. Hot serve paths keep the wrapper installed permanently.
* LOCK-FREE RECORDING. Slots are claimed with `itertools.count()`
  (GIL-atomic) and written into a preallocated list — no lock on the
  record path, so concurrent serve workers never serialize on the
  ledger. Readers (`snapshot`) tolerate slots being overwritten
  mid-read: the buffer wraps, old records are simply dropped.
* TRACE-SAFE. Instrumented functions are often *also* called inside
  other jitted functions (e.g. `make_col_stochastic` inside
  `inflate`'s traced body). Under tracing the wrapper passes straight
  through — a trace is not a dispatch.
* `sync=True` wrappers block on the result (data-dependent one-element
  readback via `trace.sync`) so `wall_s` includes device wall. Only
  driver-level call sites opt in; library wrappers keep async dispatch.
* DEFERRED READBACKS have two timestamps. An async-pipelined driver
  enqueues a device->host copy (`copy_to_host_async`) and consumes the
  value later; `readback_deferred()` mints a handle at ENQUEUE time and
  the eventual `.resolve()` bracket stamps the record's `t0`/`wall_s`
  at RESOLVE time (only the wall the host actually blocked), with the
  enqueue stamp kept in `t_enq`. Timeline attribution therefore never
  double-counts the in-flight window as host blocking, while
  `timeline.deferred_readback_stats` can still report queue residency
  (t0 - t_enq) — the overlap the deferral bought.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time

from combblas_tpu.obs import memledger as _memledger
from combblas_tpu.obs import trace as _trace

_LEDGER_ON = True   # sub-switch: ledger active iff this AND trace._ENABLED

#: chaos hook (resilience.faults.FaultInjector) — the instrument
#: wrappers and readback brackets are the choke points every hot
#: dispatch already flows through, so fault injection intercepts here.
#: Disarmed cost is one module-global load + `is None` per call.
_FAULT_HOOK = None


def set_fault_hook(hook) -> None:
    """Install/remove the fault-injection hook (see
    `combblas_tpu.resilience.faults.arm`). The hook object must expose
    `before_dispatch(name)` (may raise or sleep),
    `after_dispatch(name, out)` (may poison the output), and
    `stuck_readback(name)` (deferred handles that never report ready)."""
    global _FAULT_HOOK
    _FAULT_HOOK = hook


#: mesh-observatory sink (obs.meshobs) — called with the executable
#: name after every RECORDED dispatch so the collective-traffic ledger
#: accumulates the name's registered per-dispatch byte descriptors.
#: Same disarmed-cost contract as the fault hook: one module-global
#: load + `is None` check; never fires when the ledger is off.
_DISPATCH_SINK = None


def set_dispatch_sink(sink) -> None:
    """Install/remove the per-dispatch sink (a callable taking the
    ledger name; see `combblas_tpu.obs.meshobs`). The sink runs after
    the record is written, only for records that actually land."""
    global _DISPATCH_SINK
    _DISPATCH_SINK = sink


def set_enabled(on: bool) -> None:
    """Arm/disarm the ledger independently of span tracing (spans may
    stay on while the per-dispatch recorder is off, e.g. long soaks)."""
    global _LEDGER_ON
    _LEDGER_ON = bool(on)


def enabled() -> bool:
    return _LEDGER_ON and _trace._ENABLED


class DispatchRecord:
    """One recorded device interaction (immutable once written)."""

    __slots__ = ("seq", "name", "kind", "t0", "wall_s", "arg_shapes",
                 "arg_bytes", "out_bytes", "compiled", "path", "tid",
                 "trace_id", "t_enq", "mem_bytes")

    def __init__(self, seq, name, kind, t0, wall_s, arg_shapes, arg_bytes,
                 out_bytes, compiled, path, tid, trace_id, t_enq=None,
                 mem_bytes=None):
        self.seq = seq
        self.name = name
        self.kind = kind              # "dispatch" | "readback"
        self.t0 = t0
        self.wall_s = wall_s          # host call wall (incl. device if sync)
        self.arg_shapes = arg_shapes  # tuple of "dtype[dims]" strings
        self.arg_bytes = arg_bytes
        self.out_bytes = out_bytes    # readback bytes (kind == "readback")
        self.compiled = compiled      # True if this call triggered a compile
        self.path = path              # enclosing span path (tuple)
        self.tid = tid
        self.trace_id = trace_id
        self.t_enq = t_enq            # enqueue stamp (deferred readbacks)
        self.mem_bytes = mem_bytes    # compile-time footprint ceiling of
        #                               executables THIS call compiled
        #                               (memledger census; None otherwise)

    def to_dict(self) -> dict:
        return {"seq": self.seq, "name": self.name, "kind": self.kind,
                "t0": self.t0, "wall_s": self.wall_s,
                "arg_shapes": list(self.arg_shapes),
                "arg_bytes": self.arg_bytes, "out_bytes": self.out_bytes,
                "compiled": self.compiled, "path": list(self.path),
                "tid": self.tid, "trace_id": self.trace_id,
                "t_enq": self.t_enq, "mem_bytes": self.mem_bytes}

    def __repr__(self):
        return (f"DispatchRecord(#{self.seq} {self.name} {self.kind} "
                f"{self.wall_s * 1e3:.3f}ms compiled={self.compiled})")


class Ledger:
    """Bounded ring buffer of DispatchRecords. The default instance is
    `LEDGER`; tests may make private ones."""

    def __init__(self, capacity: int = 65536):
        if capacity <= 0:
            raise ValueError("ledger capacity must be positive")
        self.capacity = capacity
        self._buf = [None] * capacity
        self._seq = itertools.count()     # next slot; GIL-atomic claim

    def _claim(self) -> int:
        return next(self._seq)

    def _write(self, seq: int, rec: DispatchRecord) -> None:
        self._buf[seq % self.capacity] = rec

    @property
    def total(self) -> int:
        """Records ever written (≥ len(snapshot()) once wrapped)."""
        # count() has no peek; probe via repr — cheaper than a lock.
        return int(repr(self._seq)[6:-1])

    @property
    def dropped(self) -> int:
        return max(self.total - self.capacity, 0)

    def reset(self) -> None:
        self._buf = [None] * self.capacity
        self._seq = itertools.count()

    def snapshot(self) -> list:
        """Completed records in sequence order. Tolerates concurrent
        writers: a slot overwritten mid-snapshot shows its new record."""
        recs = [r for r in list(self._buf) if r is not None]
        recs.sort(key=lambda r: r.seq)
        return recs


LEDGER = Ledger()

#: registry of instrumented callables: name -> wrapper (introspection
#: + the "is this boundary covered" check in tpu_checklist --obs)
INSTRUMENTED: dict = {}
_REG_LOCK = threading.Lock()


def _leaf_stats(tree):
    """(shapes, bytes) over array leaves; cheap attribute reads only."""
    import jax
    shapes = []
    nbytes = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shp = getattr(leaf, "shape", None)
        dt = getattr(leaf, "dtype", None)
        if shp is None or dt is None:
            continue
        shapes.append(f"{dt}[{','.join(str(d) for d in shp)}]")
        sz = getattr(leaf, "size", 0)
        nbytes += int(sz) * getattr(dt, "itemsize", 1)
    return tuple(shapes), nbytes


def _trace_clean() -> bool:
    try:
        from jax._src.core import trace_state_clean
        return trace_state_clean()
    except Exception:       # pragma: no cover - very old/new jax
        return True


def record(name: str, kind: str, t0: float, wall_s: float,
           arg_shapes=(), arg_bytes=0, out_bytes=0, compiled=False,
           ledger: Ledger | None = None, t_enq: float | None = None) -> None:
    """Low-level entry: drop one record (used by `instrument` wrappers
    and by manual sites like readback loops). No-op when disabled."""
    if not (_LEDGER_ON and _trace._ENABLED):
        return
    led = ledger if ledger is not None else LEDGER
    seq = led._claim()
    led._write(seq, DispatchRecord(
        seq, name, kind, t0, wall_s, tuple(arg_shapes), arg_bytes,
        out_bytes, compiled, _trace.current_path(),
        threading.get_ident(), _trace.get_trace_id(), t_enq))
    sink = _DISPATCH_SINK
    if sink is not None and kind == "dispatch":
        sink(name)


@contextlib.contextmanager
def readback(name: str, out_bytes: int = 0,
             ledger: Ledger | None = None):
    """Bracket a manual device->host fetch (`int(np.asarray(...))`
    sites) so it lands in the ledger as a named readback. Zero
    overhead when disabled (the flag check is the only work)."""
    hook = _FAULT_HOOK
    if hook is not None and _trace_clean():
        hook.before_dispatch(name)
    if not (_LEDGER_ON and _trace._ENABLED):
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record(name, "readback", t0, time.perf_counter() - t0,
               out_bytes=out_bytes, ledger=ledger)


class _DeferredReadback:
    """Handle minted by `readback_deferred` at enqueue time. Bracket the
    eventual blocking consumption with `.resolve()`: the record lands
    with `t0`/`wall_s` stamped at RESOLVE time (the host wall actually
    blocked) and the enqueue stamp in `t_enq`. A handle whose value is
    never consumed (e.g. the pipeline fell back to a capacity rung
    because the count wasn't home) records nothing — no block happened,
    so there is nothing to attribute."""

    __slots__ = ("name", "out_bytes", "ledger", "t_enq", "_done")
    stuck = False

    def __init__(self, name, out_bytes, ledger, t_enq):
        self.name = name
        self.out_bytes = out_bytes
        self.ledger = ledger
        self.t_enq = t_enq
        self._done = False

    @contextlib.contextmanager
    def resolve(self):
        if self._done or not (_LEDGER_ON and _trace._ENABLED):
            yield
            return
        self._done = True
        t0 = time.perf_counter()
        try:
            yield
        finally:
            record(self.name, "readback", t0, time.perf_counter() - t0,
                   out_bytes=self.out_bytes, ledger=self.ledger,
                   t_enq=self.t_enq)


class _NoopDeferred:
    __slots__ = ()
    t_enq = None
    stuck = False

    @contextlib.contextmanager
    def resolve(self):
        yield


_NOOP_DEFERRED = _NoopDeferred()


class _StuckDeferred:
    """Handle minted under an armed "stuck" fault: it never reports
    ready, so ready-polling consumers (the phased-SpGEMM window loop)
    must take their fallback path. `resolve()` still yields — a
    consumer that blocks unconditionally is not the failure mode this
    models."""

    __slots__ = ()
    t_enq = None
    stuck = True

    @contextlib.contextmanager
    def resolve(self):
        yield


_STUCK_DEFERRED = _StuckDeferred()


def readback_deferred(name: str, out_bytes: int = 0,
                      ledger: Ledger | None = None):
    """Mint a deferred-readback handle at the moment an async
    device->host copy is enqueued (`Array.copy_to_host_async()`).
    Returns a handle whose `.resolve()` context manager brackets the
    eventual blocking consumption. Zero overhead when disabled (a
    shared no-op handle)."""
    hook = _FAULT_HOOK
    if hook is not None and _trace_clean() and hook.stuck_readback(name):
        return _STUCK_DEFERRED
    if not (_LEDGER_ON and _trace._ENABLED):
        return _NOOP_DEFERRED
    return _DeferredReadback(
        name, out_bytes, ledger if ledger is not None else LEDGER,
        time.perf_counter())


def instrument(fn, name: str, *, kind: str = "dispatch",
               sync: bool = False, ledger: Ledger | None = None):
    """Wrap a jitted callable so every eager call records a
    DispatchRecord. Returns the wrapper (also stored in INSTRUMENTED).

    * disabled mode: straight pass-through — no allocation, no arg
      inspection, no device syncs;
    * inside a jit trace: pass-through (a trace is not a dispatch);
    * `sync=True`: block on the result via `trace.sync` so wall_s
      includes device execution (driver-level sites only);
    * compile detection: `fn._cache_size()` delta when jit exposes it.
    """
    if kind not in ("dispatch", "readback"):
        raise ValueError(f"unknown ledger kind {kind!r}")
    cache_size = getattr(fn, "_cache_size", None)
    led = ledger if ledger is not None else LEDGER
    # arm the compile-time footprint census once any boundary is
    # instrumented: compiles triggered inside the wrapper get claimed
    # under `name` below (innermost wrapper wins for nested wraps)
    _memledger.ensure_installed()

    def wrapper(*args, **kwargs):
        hook = _FAULT_HOOK
        if hook is not None and not _trace_clean():
            hook = None          # a trace is not a dispatch: no injection
        if not (_LEDGER_ON and _trace._ENABLED):
            if hook is None:
                return fn(*args, **kwargs)
            hook.before_dispatch(name)       # may raise or sleep
            return hook.after_dispatch(name, fn(*args, **kwargs))
        if not _trace_clean():
            return fn(*args, **kwargs)
        if hook is not None:
            hook.before_dispatch(name)       # may raise or sleep
        pre = cache_size() if cache_size is not None else -1
        pre_census = _memledger.census_len()
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        if hook is not None:
            out = hook.after_dispatch(name, out)
        if sync:
            _trace.sync(out)
        wall = time.perf_counter() - t0
        shapes, abytes = _leaf_stats((args, kwargs))
        obytes = _leaf_stats(out)[1] if kind == "readback" else 0
        compiled = (cache_size() > pre) if cache_size is not None else False
        mem = _memledger.claim_census(pre_census, name)
        seq = led._claim()
        led._write(seq, DispatchRecord(
            seq, name, kind, t0, wall, shapes, abytes, obytes, compiled,
            _trace.current_path(), threading.get_ident(),
            _trace.get_trace_id(), mem_bytes=mem))
        sink = _DISPATCH_SINK
        if sink is not None and kind == "dispatch":
            sink(name)
        return out

    wrapper.__name__ = f"ledger[{name}]"
    wrapper.__wrapped__ = fn
    wrapper.ledger_name = name
    with _REG_LOCK:
        INSTRUMENTED[name] = wrapper
    return wrapper


def top_k(k: int = 10, by: str = "wall", ledger: Ledger | None = None,
          records=None, join_costs: bool = True) -> list[dict]:
    """Top-K executables by total wall (`by="wall"`) or call count
    (`by="count"`). Each row: name, count, total_s, mean_s, compiles,
    arg_bytes, out_bytes, mem_bytes/temp_bytes (the name's compile-time
    footprint ceiling from the memledger census; None when no executable
    was attributed) — plus the cost-model join (annotated, flops,
    gflops_s, gbytes_s, bound, eff; None when the name carries no
    annotation) unless `join_costs=False`."""
    recs = (ledger if ledger is not None else LEDGER).snapshot() \
        if records is None else records
    agg: dict = {}
    for r in recs:
        row = agg.get(r.name)
        if row is None:
            row = agg[r.name] = {"name": r.name, "count": 0,
                                 "total_s": 0.0, "compiles": 0,
                                 "arg_bytes": 0, "out_bytes": 0}
        row["count"] += 1
        row["total_s"] += r.wall_s
        row["compiles"] += bool(r.compiled)
        row["arg_bytes"] += r.arg_bytes
        row["out_bytes"] += r.out_bytes
    rows = sorted(agg.values(),
                  key=lambda d: d["total_s" if by == "wall" else "count"],
                  reverse=True)[:max(k, 0)]
    for row in rows:
        row["total_s"] = round(row["total_s"], 6)
        row["mean_s"] = round(row["total_s"] / row["count"], 6)
        fp = _memledger.footprint_for(row["name"])
        row["mem_bytes"] = fp["total_bytes"] if fp else None
        row["temp_bytes"] = fp["temp_bytes"] if fp else None
    if join_costs:
        from combblas_tpu.obs import costmodel
        from combblas_tpu.obs import meshobs
        costmodel.join_rows(rows)
        meshobs.join_rows(rows)
    return rows


def format_table(k: int = 10, by: str = "wall",
                 ledger: Ledger | None = None) -> str:
    """Human-readable top-K table (the `--gate`/README surface). The
    `eff` column is the roofline-efficiency fraction from the cost
    model, with the bound class (c/m/i); blank when the name carries
    no annotation. The `memMB` column is the name's compile-time
    footprint ceiling (args+outputs+temps of its largest executable,
    from the memledger census); blank when no executable was
    attributed (warm cache). The `drift` column is the mesh
    observatory's measured/predicted ICI-byte ratio (obs.meshobs);
    blank when the name registered no collective descriptors."""
    rows = top_k(k, by=by, ledger=ledger)
    led = ledger if ledger is not None else LEDGER
    out = [f"dispatch ledger: {led.total} records "
           f"({led.dropped} wrapped out), top {len(rows)} by {by}:"]
    out.append(f"  {'executable':40s} {'count':>7s} {'total_s':>10s} "
               f"{'mean_ms':>9s} {'compiles':>8s} {'eff':>8s} "
               f"{'memMB':>8s} {'drift':>7s}")
    for r in rows:
        if r.get("eff") is not None:
            eff = f"{r['eff']:.3f}/{r['bound'][0]}"
        elif r.get("annotated"):
            eff = "ann"        # annotated but zero-wall (plan records)
        else:
            eff = ""
        mem = (f"{r['mem_bytes'] / 1e6:8.1f}"
               if r.get("mem_bytes") is not None else f"{'':8s}")
        dr = (f"{r['drift']:7.3f}"
              if r.get("drift") is not None else f"{'':7s}")
        out.append(f"  {r['name'][:40]:40s} {r['count']:7d} "
                   f"{r['total_s']:10.4f} {r['mean_s'] * 1e3:9.3f} "
                   f"{r['compiles']:8d} {eff:>8s} {mem} {dr}")
    return "\n".join(out)


def reset(ledger: Ledger | None = None) -> None:
    (ledger if ledger is not None else LEDGER).reset()
