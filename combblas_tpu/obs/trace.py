"""Span-based tracing: nested, named, categorized wall-clock spans.

Capability parity: CombBLAS 2.0's `cblas_*` TIMING accumulators
(CombBLAS.h:78-100) and the PAPI fan-out/local/fan-in/merge phase
matrices (papi_combblas_globals.h) — generalized from four fixed
buckets to a tree, because the round-5 verdict showed the fixed
buckets miss the majority of real wall time (dispatch glue, readbacks,
host planning between stamps).

Model:

* A span is a named `with` region; spans nest into a tree per thread.
* Each span carries an optional CATEGORY (one of `CATEGORIES`). A
  span's SELF time — its duration minus the summed durations of its
  direct children — is attributed to its category. Self time of
  category-less spans (structural groupings and region roots) is the
  explicit `unaccounted` residual. So for any instrumented region,
  wall clock == sum over categories + unaccounted, exactly.
* Thread-safe: each thread keeps its own open-span stack; completed
  records append to one process-wide bounded list under a lock.
* ZERO overhead when disabled: `span()` returns a shared no-op
  context (one module-flag check, no allocation, no device syncs) —
  the same contract as the old `timing._ENABLED` gate.
"""

from __future__ import annotations

import functools
import itertools
import threading
import time

#: where a span's self time lands in `export.phase_breakdown()`:
#:   compile        — XLA/jaxpr compilation (cache misses)
#:   dispatch       — program launch / relay round trips
#:   device_execute — on-device kernel time (span must sync to be honest)
#:   host_readback  — device->host value fetches
#:   host_compute   — host-side planning / numpy work
#:   transfer       — host->device or cross-device data movement
CATEGORIES = ("compile", "dispatch", "device_execute", "host_readback",
              "host_compute", "transfer")

#: the residual key in phase breakdowns (not a CATEGORY: it is computed,
#: never assigned)
UNACCOUNTED = "unaccounted"

_ENABLED = False


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """One process-wide switch arming spans AND the legacy timing
    syncs (utils.timing delegates here)."""
    global _ENABLED
    _ENABLED = bool(on)


class SpanRecord:
    """One completed span (immutable once recorded)."""

    __slots__ = ("name", "category", "t0", "t1", "depth", "path", "tid",
                 "attrs", "children_s")

    def __init__(self, name, category, t0, t1, depth, path, tid, attrs,
                 children_s):
        self.name = name
        self.category = category
        self.t0 = t0
        self.t1 = t1
        self.depth = depth
        self.path = path          # tuple of ancestor names incl. self
        self.tid = tid
        self.attrs = attrs
        self.children_s = children_s

    @property
    def total_s(self) -> float:
        return self.t1 - self.t0

    @property
    def self_s(self) -> float:
        # clamp: clock jitter on near-empty spans must not go negative
        return max(self.total_s - self.children_s, 0.0)

    def to_dict(self) -> dict:
        return {"name": self.name, "category": self.category,
                "t0": self.t0, "t1": self.t1, "depth": self.depth,
                "path": list(self.path), "tid": self.tid,
                "attrs": self.attrs, "children_s": self.children_s}

    def __repr__(self):
        return (f"SpanRecord({'/'.join(self.path)!r}, "
                f"cat={self.category}, total={self.total_s:.6f}s, "
                f"self={self.self_s:.6f}s)")


class Tracer:
    """Process-wide span collector: per-thread open-span stacks, one
    bounded record list. The default instance is `TRACER`; tests may
    make private ones."""

    def __init__(self, max_records: int = 1_000_000):
        self.max_records = max_records
        self.records: list[SpanRecord] = []
        self.dropped = 0
        self._lock = threading.Lock()
        self._tls = threading.local()

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _record(self, rec: SpanRecord) -> None:
        with self._lock:
            if len(self.records) < self.max_records:
                self.records.append(rec)
            else:
                self.dropped += 1
        hook = _SPAN_CLOSE_HOOK          # module global: set after class def
        if hook is not None:
            try:
                hook(rec)
            except Exception:            # an observer must not break spans
                pass

    def reset(self) -> None:
        """Drop completed records (open spans are unaffected — their
        records land after the reset, orphaned but harmless)."""
        with self._lock:
            self.records.clear()
            self.dropped = 0

    def snapshot(self) -> list[SpanRecord]:
        with self._lock:
            return list(self.records)


TRACER = Tracer()

#: optional observer invoked with each completed SpanRecord (outside
#: the tracer lock). Sole current client: memledger's live-buffer
#: watermark sampler. One slot, not a list — keep the close path flat.
_SPAN_CLOSE_HOOK = None


def set_span_close_hook(fn) -> None:
    """Install (or clear, with None) the span-close observer. The hook
    must never raise and should be cheap relative to a span close; it
    runs on the closing thread after the record lands."""
    global _SPAN_CLOSE_HOOK
    _SPAN_CLOSE_HOOK = fn


class _NoopSpan:
    """Shared disabled-mode context: no allocation, no record, and a
    no-op `set` so call sites never branch on the enable flag."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        pass


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "category", "attrs", "tracer", "_t0", "_path",
                 "_depth", "_children")

    def __init__(self, name, category, attrs, tracer):
        if category is not None and category not in CATEGORIES:
            raise ValueError(f"unknown span category {category!r}; "
                             f"pick one of {CATEGORIES} or None")
        self.name = name
        self.category = category
        self.attrs = attrs
        self.tracer = tracer

    def set(self, **attrs):
        """Annotate mid-span (e.g. an nnz known only after a readback)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        st = self.tracer._stack()
        if st:
            parent = st[-1]
            self._path = parent._path + (self.name,)
            self._depth = parent._depth + 1
        else:
            self._path = (self.name,)
            self._depth = 0
        self._children = 0.0
        st.append(self)
        self._t0 = time.perf_counter()   # last: setup cost -> parent self
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()         # first: teardown -> parent self
        st = self.tracer._stack()
        # tolerate a torn stack (enable toggled mid-nest, leaked spans)
        if self in st:
            del st[st.index(self):]
        if st:
            st[-1]._children += t1 - self._t0
        self.tracer._record(SpanRecord(
            self.name, self.category, self._t0, t1, self._depth,
            self._path, threading.get_ident(), self.attrs,
            self._children))
        return False


def span(name: str, category: str | None = None,
         tracer: Tracer | None = None, **attrs):
    """Open a named span. `category` attributes the span's SELF time in
    breakdowns (None = structural: self time counts as unaccounted).
    Extra kwargs become attributes on the record. When tracing is
    disabled this returns a shared no-op context — zero overhead."""
    if not _ENABLED:
        return _NOOP
    return _Span(name, category, attrs, tracer if tracer is not None
                 else TRACER)


def sync(x) -> None:
    """Force completion with a tiny data-DEPENDENT readback: on
    remote-TPU relays block_until_ready can ack before execution
    finishes, so honest span boundaries fetch a value (one element,
    via a device-side slice — not the whole array). No-op when
    tracing is disabled."""
    if not _ENABLED:
        return
    import numpy as np

    import jax
    for leaf in jax.tree_util.tree_leaves(x):
        if hasattr(leaf, "ravel") and getattr(leaf, "size", 0) > 0:
            np.asarray(leaf.ravel()[0])
            return


def current_path(tracer: Tracer | None = None) -> tuple:
    """Path of the innermost open span on THIS thread (() at top level).
    Cheap: one thread-local read; used by the dispatch ledger to stamp
    records with their enclosing span."""
    st = (tracer if tracer is not None else TRACER)._stack()
    return st[-1]._path if st else ()


def traced(name: str | None = None, category: str | None = None,
           tracer: Tracer | None = None, **attrs):
    """Decorator form of `span` — instrument a function without
    indenting its body::

        @obs.traced("bfs_plan", "host_compute")
        def plan(...): ...

    `name` defaults to the function's __name__. Also usable bare
    (`@obs.traced` / `@obs.traced()`). Disabled mode costs one flag
    check per call (the wrapper calls straight through)."""
    if callable(name):                       # bare @obs.traced
        fn, name = name, None
        return traced(None, category, tracer)(fn)

    def deco(fn):
        span_name = name if name is not None else fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _ENABLED:
                return fn(*args, **kwargs)
            with span(span_name, category, tracer, **attrs):
                return fn(*args, **kwargs)
        wrapper.__wrapped__ = fn
        return wrapper
    return deco


# ---------------------------------------------------------------- trace ids
# Per-request correlation tokens: serve stamps one on each request at
# admission, sets it on whichever thread is doing that request's work,
# and the ledger/span layers copy the current id onto their records so
# one request's activity links across queue -> batcher -> engine threads.

_TRACE_SEQ = itertools.count(1)   # itertools.count is GIL-atomic
_TRACE_TLS = threading.local()


def new_trace_id() -> str:
    """Mint a process-unique trace id (cheap, lock-free)."""
    return f"t{next(_TRACE_SEQ):08x}"


def set_trace_id(trace_id: str | None) -> None:
    """Bind `trace_id` to the current thread (None clears)."""
    _TRACE_TLS.tid = trace_id


def get_trace_id() -> str | None:
    """The trace id bound to the current thread, or None."""
    return getattr(_TRACE_TLS, "tid", None)


def reset(tracer: Tracer | None = None) -> None:
    (tracer if tracer is not None else TRACER).reset()
