"""Observability: span tracing, metrics, and exporters.

The round-5 verdict found ~63% of MCL's expansion wall time was
dispatch/readback overhead invisible to the four fixed `utils.timing`
accumulators. This subsystem supersedes them:

* `obs.trace` — nested, named, CATEGORIZED wall-clock spans with an
  explicit `unaccounted` residual, so a region's clock always adds up;
* `obs.metrics` — labeled counters/gauges/histograms (nnz, flops,
  bytes read back, compile-cache hits, phase counts);
* `obs.export` — report tree, JSON-lines log, Chrome-trace/Perfetto
  emitter, and the `jax.profiler` bridge;
* `obs.costmodel` — roofline cost-model registry: planners annotate
  ledger names with expected flops/bytes at plan time; `top_k` /
  `format_table` / `/varz` join them into achieved FLOP/s, B/s, and
  efficiency fractions against `utils.config.backend_peaks`;
* `obs.memledger` — HBM memory ledger: compile-time footprint census
  (`CompiledMemoryStats` per executable, claimed by `instrument`
  wrappers), live-buffer watermarks via `jax.live_arrays()`, and the
  `donate_argnums` honor audit — the capacity axis of the roofline,
  gated by analysis pass 6;
* `obs.regress` — canonical bench trajectory (BENCH_TRAJECTORY.json)
  normalizers and the noise-banded regression detector behind
  `scripts/bench_registry.py` and analysis pass 5;
* `obs.meshobs` — mesh observatory: static per-dispatch collective
  descriptors registered at plan time, measured exchanged bytes per
  (name, collective, axis) accumulated at dispatch, the
  predicted-vs-measured ICI drift join, and per-device load/skew
  attribution — gated by analysis pass 9.

Everything is gated on ONE process-wide flag (`set_enabled`, the same
contract as the old `timing._ENABLED`): disabled call sites cost one
flag check and perform no device syncs. `utils.timing` remains as a
thin compatibility shim over this package.

Quick start::

    from combblas_tpu import obs
    obs.set_enabled(True)
    with obs.span("my_region"):
        run_workload()
    print(obs.export.format_report())
    print(obs.export.phase_breakdown())      # {category: s, "unaccounted": s}
    obs.export.chrome_trace("trace.json")    # open in ui.perfetto.dev
"""

from combblas_tpu.obs import (
    costmodel, export, httpd, ledger, memledger, meshobs, metrics,
    regress, timeline, trace,
)
from combblas_tpu.obs.trace import (
    CATEGORIES, TRACER, Tracer, current_path, enabled, get_trace_id,
    new_trace_id, reset, set_enabled, set_trace_id, span, sync, traced,
)
from combblas_tpu.obs.metrics import REGISTRY, counter, gauge, histogram
from combblas_tpu.obs.export import (
    chrome_trace, dispatch_summary, format_report, memory_summary,
    phase_breakdown, profiler_trace, report, read_jsonl,
    read_jsonl_metrics, to_jsonl,
)
from combblas_tpu.obs.ledger import LEDGER, Ledger, instrument
from combblas_tpu.obs.httpd import (
    MetricsServer, parse_prometheus, prometheus_text, serve_metrics,
)
