// Native Matrix Market parser: the hot loop of file ingestion.
//
// Capability parity: src/mmio.c (banner parsing, ~382 LoC C) plus the
// line-parsing inner loop of SpParMat::ParallelReadMM (SpParMat.cpp:3922).
// The reference splits the byte range over MPI ranks; here one fast
// native pass fills pinned numpy buffers that the caller then shards
// onto the device mesh (the tuple-shuffle of SparseCommon happens on
// device in distmat.from_global_coo).
//
// Built by combblas_tpu/io/_native.py via g++ -O3 -shared -fPIC and
// loaded through ctypes (no pybind11 in this environment).

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cctype>

namespace {

// if fgets truncated (no newline captured), drop the rest of the
// physical line so the next read starts on a fresh line
void finish_line(FILE* f, const char* line) {
  size_t len = strlen(line);
  if (len > 0 && line[len - 1] == '\n') return;
  int ch;
  while ((ch = fgetc(f)) != EOF && ch != '\n') {}
}

struct Banner {
  bool coordinate = false;
  bool pattern = false;
  bool real = false;
  bool integer = false;
  bool complex_ = false;
  bool general = false;
  bool symmetric = false;
  bool skew = false;
  bool hermitian = false;
};

bool parse_banner(FILE* f, Banner* b) {
  char line[1024];
  if (!fgets(line, sizeof line, f)) return false;
  if (strncmp(line, "%%MatrixMarket", 14) != 0) return false;
  finish_line(f, line);
  for (char* p = line; *p; ++p) *p = (char)tolower((unsigned char)*p);
  b->coordinate = strstr(line, "coordinate") != nullptr;
  b->pattern = strstr(line, "pattern") != nullptr;
  b->real = strstr(line, "real") != nullptr;
  b->integer = strstr(line, "integer") != nullptr;
  b->complex_ = strstr(line, "complex") != nullptr;
  b->general = strstr(line, "general") != nullptr;
  b->symmetric = strstr(line, "symmetric") != nullptr;
  b->skew = strstr(line, "skew-symmetric") != nullptr;
  if (b->skew) b->symmetric = false;
  b->hermitian = strstr(line, "hermitian") != nullptr;
  return true;
}

// skip comment lines, leave the stream at the size line
bool skip_comments(FILE* f) {
  long pos;
  char line[1024];
  for (;;) {
    pos = ftell(f);
    if (!fgets(line, sizeof line, f)) return false;
    if (line[0] != '%') {
      fseek(f, pos, SEEK_SET);
      return true;
    }
    finish_line(f, line);   // over-long comment: drop its tail too
  }
}

}  // namespace

extern "C" {

// header_out[8]: nrows, ncols, nnz_declared, pattern, symmetric, skew,
// hermitian, complex. Returns 0 ok, negative error code otherwise.
int mm_read_header(const char* path, long long* header_out) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  Banner b;
  if (!parse_banner(f, &b) || !b.coordinate) { fclose(f); return -2; }
  if (!skip_comments(f)) { fclose(f); return -3; }
  long long m, n, nnz;
  if (fscanf(f, "%lld %lld %lld", &m, &n, &nnz) != 3) { fclose(f); return -4; }
  header_out[0] = m;
  header_out[1] = n;
  header_out[2] = nnz;
  header_out[3] = b.pattern;
  header_out[4] = b.symmetric;
  header_out[5] = b.skew;
  header_out[6] = b.hermitian;
  header_out[7] = b.complex_;
  fclose(f);
  return 0;
}

// Fill rows/cols (0-based) and vals (1.0 for pattern files; real part
// for complex). Returns entries read, or negative error code.
long long mm_read_body(const char* path, int* rows, int* cols, double* vals,
                       long long max_nnz) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  Banner b;
  if (!parse_banner(f, &b) || !b.coordinate) { fclose(f); return -2; }
  if (!skip_comments(f)) { fclose(f); return -3; }
  long long m, n, nnz;
  if (fscanf(f, "%lld %lld %lld", &m, &n, &nnz) != 3) { fclose(f); return -4; }
  // consume the rest of the size line
  int ch;
  while ((ch = fgetc(f)) != EOF && ch != '\n') {}

  long long count = 0;
  char line[4096];
  while (count < max_nnz && fgets(line, sizeof line, f)) {
    finish_line(f, line);   // one physical line == one record
    char* p = line;
    while (*p == ' ' || *p == '\t') ++p;
    if (*p == '\0' || *p == '\n' || *p == '%') continue;
    char* end;
    long r = strtol(p, &end, 10);
    if (end == p) { fclose(f); return -5; }
    p = end;
    long c = strtol(p, &end, 10);
    if (end == p) { fclose(f); return -5; }
    p = end;
    double v = 1.0;
    if (!b.pattern) {
      v = strtod(p, &end);
      if (end == p) { fclose(f); return -5; }
    }
    rows[count] = (int)(r - 1);   // Matrix Market is 1-based
    cols[count] = (int)(c - 1);
    vals[count] = v;
    ++count;
  }
  fclose(f);
  return count;
}

// Write a coordinate file (real general). Returns 0 ok.
int mm_write(const char* path, const int* rows, const int* cols,
             const double* vals, long long nnz, long long nrows,
             long long ncols, int pattern) {
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  fprintf(f, "%%%%MatrixMarket matrix coordinate %s general\n",
          pattern ? "pattern" : "real");
  fprintf(f, "%lld %lld %lld\n", nrows, ncols, nnz);
  for (long long i = 0; i < nnz; ++i) {
    if (pattern) {
      fprintf(f, "%d %d\n", rows[i] + 1, cols[i] + 1);
    } else {
      fprintf(f, "%d %d %.17g\n", rows[i] + 1, cols[i] + 1, vals[i]);
    }
  }
  fclose(f);
  return 0;
}

}  // extern "C"
