// Native Matrix Market parser: the hot loop of file ingestion.
//
// Capability parity: src/mmio.c (banner parsing, ~382 LoC C) plus the
// line-parsing inner loop of SpParMat::ParallelReadMM (SpParMat.cpp:3922).
// The reference splits the byte range over MPI ranks; here one fast
// native pass fills pinned numpy buffers that the caller then shards
// onto the device mesh (the tuple-shuffle of SparseCommon happens on
// device in distmat.from_global_coo).
//
// Built by combblas_tpu/io/_native.py via g++ -O3 -shared -fPIC and
// loaded through ctypes (no pybind11 in this environment).

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cctype>
#include <cerrno>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// if fgets truncated (no newline captured), drop the rest of the
// physical line so the next read starts on a fresh line
void finish_line(FILE* f, const char* line) {
  size_t len = strlen(line);
  if (len > 0 && line[len - 1] == '\n') return;
  int ch;
  while ((ch = fgetc(f)) != EOF && ch != '\n') {}
}

struct Banner {
  bool coordinate = false;
  bool pattern = false;
  bool real = false;
  bool integer = false;
  bool complex_ = false;
  bool general = false;
  bool symmetric = false;
  bool skew = false;
  bool hermitian = false;
};

bool parse_banner(FILE* f, Banner* b) {
  char line[1024];
  if (!fgets(line, sizeof line, f)) return false;
  if (strncmp(line, "%%MatrixMarket", 14) != 0) return false;
  finish_line(f, line);
  for (char* p = line; *p; ++p) *p = (char)tolower((unsigned char)*p);
  b->coordinate = strstr(line, "coordinate") != nullptr;
  b->pattern = strstr(line, "pattern") != nullptr;
  b->real = strstr(line, "real") != nullptr;
  b->integer = strstr(line, "integer") != nullptr;
  b->complex_ = strstr(line, "complex") != nullptr;
  b->general = strstr(line, "general") != nullptr;
  b->symmetric = strstr(line, "symmetric") != nullptr;
  b->skew = strstr(line, "skew-symmetric") != nullptr;
  if (b->skew) b->symmetric = false;
  b->hermitian = strstr(line, "hermitian") != nullptr;
  return true;
}

// skip comment lines, leave the stream at the size line
bool skip_comments(FILE* f) {
  long pos;
  char line[1024];
  for (;;) {
    pos = ftell(f);
    if (!fgets(line, sizeof line, f)) return false;
    if (line[0] != '%') {
      fseek(f, pos, SEEK_SET);
      return true;
    }
    finish_line(f, line);   // over-long comment: drop its tail too
  }
}

}  // namespace

extern "C" {

// header_out[8]: nrows, ncols, nnz_declared, pattern, symmetric, skew,
// hermitian, complex. Returns 0 ok, negative error code otherwise.
int mm_read_header(const char* path, long long* header_out) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  Banner b;
  if (!parse_banner(f, &b) || !b.coordinate) { fclose(f); return -2; }
  if (!skip_comments(f)) { fclose(f); return -3; }
  long long m, n, nnz;
  if (fscanf(f, "%lld %lld %lld", &m, &n, &nnz) != 3) { fclose(f); return -4; }
  header_out[0] = m;
  header_out[1] = n;
  header_out[2] = nnz;
  header_out[3] = b.pattern;
  header_out[4] = b.symmetric;
  header_out[5] = b.skew;
  header_out[6] = b.hermitian;
  header_out[7] = b.complex_;
  fclose(f);
  return 0;
}

// Fill rows/cols (0-based) and vals (1.0 for pattern files; real part
// for complex). Returns entries read, or negative error code.
long long mm_read_body(const char* path, int* rows, int* cols, double* vals,
                       long long max_nnz) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  Banner b;
  if (!parse_banner(f, &b) || !b.coordinate) { fclose(f); return -2; }
  if (!skip_comments(f)) { fclose(f); return -3; }
  long long m, n, nnz;
  if (fscanf(f, "%lld %lld %lld", &m, &n, &nnz) != 3) { fclose(f); return -4; }
  // consume the rest of the size line
  int ch;
  while ((ch = fgetc(f)) != EOF && ch != '\n') {}

  long long count = 0;
  char line[4096];
  while (count < max_nnz && fgets(line, sizeof line, f)) {
    finish_line(f, line);   // one physical line == one record
    char* p = line;
    while (*p == ' ' || *p == '\t') ++p;
    if (*p == '\0' || *p == '\n' || *p == '%') continue;
    char* end;
    long r = strtol(p, &end, 10);
    if (end == p) { fclose(f); return -5; }
    p = end;
    long c = strtol(p, &end, 10);
    if (end == p) { fclose(f); return -5; }
    p = end;
    double v = 1.0;
    if (!b.pattern) {
      v = strtod(p, &end);
      if (end == p) { fclose(f); return -5; }
    }
    rows[count] = (int)(r - 1);   // Matrix Market is 1-based
    cols[count] = (int)(c - 1);
    vals[count] = v;
    ++count;
  }
  fclose(f);
  return count;
}

// Byte-range-parallel body read (the reference's ParallelReadMM recipe,
// SpParMat.cpp:3922 + SpParHelper.h:110 check_newline, with threads in
// the role of MPI ranks): mmap the file, split the data region into
// nthreads byte ranges, fix each range start to the next line boundary,
// then two parallel passes — count records per range, prefix-sum the
// output offsets, parse in place with strtol/strtod (no per-line copy).
// A record belongs to the range containing its line's first byte; the
// last line of a range may be read past the range end.
// Returns entries read, or a negative error code.
long long mm_read_body_par(const char* path, int* rows, int* cols,
                           double* vals, long long max_nnz, int nthreads) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  Banner b;
  if (!parse_banner(f, &b) || !b.coordinate) { fclose(f); return -2; }
  if (!skip_comments(f)) { fclose(f); return -3; }
  long long m, n, nnz;
  if (fscanf(f, "%lld %lld %lld", &m, &n, &nnz) != 3) { fclose(f); return -4; }
  int ch;
  while ((ch = fgetc(f)) != EOF && ch != '\n') {}
  long data_start = ftell(f);
  fclose(f);

  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return -1; }
  size_t fsize = (size_t)st.st_size;
  if ((size_t)data_start >= fsize) { close(fd); return 0; }
  char* base = (char*)mmap(nullptr, fsize, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return -6;

  if (nthreads < 1) nthreads = 1;
  size_t span = fsize - (size_t)data_start;
  if (span / 65536 + 1 < (size_t)nthreads)
    nthreads = (int)(span / 65536 + 1);   // tiny files: fewer ranges

  // range boundaries, snapped forward to line starts (check_newline)
  std::vector<size_t> lo(nthreads + 1);
  for (int t = 0; t <= nthreads; ++t) {
    size_t p = (size_t)data_start + span * (size_t)t / (size_t)nthreads;
    if (t > 0 && t < nthreads) {
      const char* nl = (const char*)memchr(base + p, '\n', fsize - p);
      p = nl ? (size_t)(nl - base) + 1 : fsize;
    }
    lo[t] = (t == nthreads) ? fsize : p;
  }

  // is this line (starting at p) a record? (skip blanks/comments)
  auto is_record = [&](size_t p) {
    while (p < fsize && (base[p] == ' ' || base[p] == '\t')) ++p;
    return p < fsize && base[p] != '\n' && base[p] != '\r' &&
           base[p] != '%';
  };

  std::vector<long long> counts(nthreads, 0);
  std::vector<int> errs(nthreads, 0);

  auto count_pass = [&](int t) {
    long long c = 0;
    for (size_t p = lo[t]; p < lo[t + 1]; ) {
      if (is_record(p)) ++c;
      const char* nl = (const char*)memchr(base + p, '\n', fsize - p);
      p = nl ? (size_t)(nl - base) + 1 : fsize;
    }
    counts[t] = c;
  };
  {
    std::vector<std::thread> ths;
    for (int t = 0; t < nthreads; ++t) ths.emplace_back(count_pass, t);
    for (auto& th : ths) th.join();
  }
  std::vector<long long> offs(nthreads + 1, 0);
  for (int t = 0; t < nthreads; ++t) offs[t + 1] = offs[t] + counts[t];
  long long total = offs[nthreads];
  if (total > max_nnz) { munmap(base, fsize); return -7; }

  bool pattern = b.pattern;
  auto parse_pass = [&](int t) {
    long long i = offs[t];
    for (size_t p = lo[t]; p < lo[t + 1]; ) {
      const char* nl = (const char*)memchr(base + p, '\n', fsize - p);
      size_t next = nl ? (size_t)(nl - base) + 1 : fsize;
      if (is_record(p)) {
        // strtol stops at the newline; reading past the range end is
        // fine (the map extends to fsize and lines never cross it).
        // A final line with no newline could run off the map when
        // fsize is page-aligned — bounce it through a local buffer.
        // A record line that doesn't fit the buffer cannot be parsed
        // faithfully: flag a parse error, never truncate silently
        // (truncation could drop the value field and read "1 2 3.5e8"
        // as "1 2 3.5" with no diagnostic).
        char tail[4096];
        char* q = base + p;
        if (!nl) {
          size_t len = fsize - p;
          if (len >= sizeof tail) { errs[t] = 1; return; }
          memcpy(tail, base + p, len);
          tail[len] = '\0';
          q = tail;
        }
        char* end;
        long r = strtol(q, &end, 10);
        if (end == q) { errs[t] = 1; return; }
        q = end;
        long c = strtol(q, &end, 10);
        if (end == q) { errs[t] = 1; return; }
        double v = 1.0;
        if (!pattern) {
          q = end;
          v = strtod(q, &end);
          if (end == q) { errs[t] = 1; return; }
        }
        rows[i] = (int)(r - 1);
        cols[i] = (int)(c - 1);
        vals[i] = v;
        ++i;
      }
      p = next;
    }
  };
  {
    std::vector<std::thread> ths;
    for (int t = 0; t < nthreads; ++t) ths.emplace_back(parse_pass, t);
    for (auto& th : ths) th.join();
  }
  munmap(base, fsize);
  for (int t = 0; t < nthreads; ++t)
    if (errs[t]) return -5;
  return total;
}

// Write a coordinate file (real general). Returns 0 ok.
int mm_write(const char* path, const int* rows, const int* cols,
             const double* vals, long long nnz, long long nrows,
             long long ncols, int pattern) {
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  fprintf(f, "%%%%MatrixMarket matrix coordinate %s general\n",
          pattern ? "pattern" : "real");
  fprintf(f, "%lld %lld %lld\n", nrows, ncols, nnz);
  for (long long i = 0; i < nnz; ++i) {
    if (pattern) {
      fprintf(f, "%d %d\n", rows[i] + 1, cols[i] + 1);
    } else {
      fprintf(f, "%d %d %.17g\n", rows[i] + 1, cols[i] + 1, vals[i]);
    }
  }
  fclose(f);
  return 0;
}

}  // extern "C"
