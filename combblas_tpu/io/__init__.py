"""File I/O: Matrix Market (native-parser-backed), vector files, and
binary checkpoints (≅ reference L7, SURVEY §2.7)."""

from combblas_tpu.io.mmio import (
    MMHeader, read_mm_header, read_mm_coo, read_mm, write_mm,
    read_vec, write_vec, save_matrix, load_matrix, save_vector,
    load_vector,
)
