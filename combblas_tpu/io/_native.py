"""Build + load the native Matrix Market parser (ctypes).

The reference vendors mmio as C (src/mmio.c) built by CMake; here the
parser compiles on first use with g++ into combblas_tpu/io/_build/ and
is loaded via ctypes (this environment has no pybind11). A missing
toolchain degrades gracefully: `load()` returns None and callers fall
back to the pure-Python parser.
"""

from __future__ import annotations

import ctypes
import pathlib

from combblas_tpu.utils.native import load_native

_SRC = pathlib.Path(__file__).parent / "_mmparse.cpp"

_lib = None
_tried = False


def _configure(lib):
    lib.mm_read_header.restype = ctypes.c_int
    lib.mm_read_header.argtypes = [ctypes.c_char_p,
                                   ctypes.POINTER(ctypes.c_longlong)]
    lib.mm_read_body.restype = ctypes.c_longlong
    lib.mm_read_body.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_double),
        ctypes.c_longlong]
    lib.mm_read_body_par.restype = ctypes.c_longlong
    lib.mm_read_body_par.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_double),
        ctypes.c_longlong, ctypes.c_int]
    lib.mm_write.restype = ctypes.c_int
    lib.mm_write.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_double),
        ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
        ctypes.c_int]


def load():
    """The loaded CDLL, building it if needed; None if unavailable."""
    global _lib, _tried
    if not _tried:
        _tried = True
        _lib = load_native(_SRC, _configure)
    return _lib
