"""Build + load the native Matrix Market parser (ctypes).

The reference vendors mmio as C (src/mmio.c) built by CMake; here the
parser compiles on first use with g++ into combblas_tpu/io/_build/ and
is loaded via ctypes (this environment has no pybind11). A missing
toolchain degrades gracefully: `load()` returns None and callers fall
back to the pure-Python parser.
"""

from __future__ import annotations

import ctypes
import hashlib
import pathlib
import subprocess

_DIR = pathlib.Path(__file__).parent
_SRC = _DIR / "_mmparse.cpp"
_BUILD = _DIR / "_build"

_lib = None
_tried = False


def load():
    """The loaded CDLL, building it if needed; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        tag = hashlib.sha1(_SRC.read_bytes()).hexdigest()[:12]
        so = _BUILD / f"_mmparse_{tag}.so"
        if not so.exists():
            _BUILD.mkdir(exist_ok=True)
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", str(_SRC), "-o", str(so)],
                check=True, capture_output=True, timeout=120)
        lib = ctypes.CDLL(str(so))
        lib.mm_read_header.restype = ctypes.c_int
        lib.mm_read_header.argtypes = [ctypes.c_char_p,
                                       ctypes.POINTER(ctypes.c_longlong)]
        lib.mm_read_body.restype = ctypes.c_longlong
        lib.mm_read_body.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_double),
            ctypes.c_longlong]
        lib.mm_write.restype = ctypes.c_int
        lib.mm_write.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_double),
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_int]
        _lib = lib
    except Exception:
        _lib = None
    return _lib
