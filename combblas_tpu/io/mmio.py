"""Matrix Market read/write for distributed matrices and vectors.

Capability parity: `ParallelReadMM` (SpParMat.cpp:3922),
`ParallelWriteMM` (SpParMat.h:278), mmio banner handling (src/mmio.c),
vector read/write (FullyDistSpVec.cpp:1209,1310).

TPU-native re-design: parsing is one native pass (io/_mmparse.cpp via
ctypes; pure-Python fallback) into host numpy buffers; distribution is
the on-device tuple shuffle of `distmat.from_global_coo` (the
SparseCommon AlltoAll of SpParMat.cpp:2835 as one sharded build). The
reference's MPI-IO byte-range splitting has no analogue: a TPU host
owns file I/O, the mesh owns placement.
"""

from __future__ import annotations

import dataclasses
import pathlib
from typing import Optional

import jax.numpy as jnp
import numpy as np

from combblas_tpu.io import _native
from combblas_tpu.ops.semiring import Monoid, PLUS
from combblas_tpu.parallel import distmat as dm
from combblas_tpu.parallel import distvec as dv
from combblas_tpu.parallel.grid import ProcGrid, ROW_AXIS


@dataclasses.dataclass
class MMHeader:
    nrows: int
    ncols: int
    nnz: int
    pattern: bool
    symmetric: bool
    skew: bool
    hermitian: bool
    complex_: bool


def read_mm_header(path) -> MMHeader:
    path = str(path)
    lib = _native.load()
    if lib is not None:
        import ctypes
        hdr = (ctypes.c_longlong * 8)()
        rc = lib.mm_read_header(path.encode(), hdr)
        if rc != 0:
            raise ValueError(f"not a Matrix Market coordinate file "
                             f"({path}, rc={rc})")
        return MMHeader(int(hdr[0]), int(hdr[1]), int(hdr[2]),
                        bool(hdr[3]), bool(hdr[4]), bool(hdr[5]),
                        bool(hdr[6]), bool(hdr[7]))
    return _py_header(path)


def _py_header(path) -> MMHeader:
    with open(path) as f:
        banner = f.readline()
        if not banner.startswith("%%MatrixMarket"):
            raise ValueError(f"not a Matrix Market file: {path}")
        low = banner.lower()
        if "coordinate" not in low:
            raise ValueError("only coordinate (sparse) files supported")
        line = f.readline()
        while line.startswith("%"):
            line = f.readline()
        m, n, nnz = (int(x) for x in line.split())
        return MMHeader(m, n, nnz, "pattern" in low,
                        "symmetric" in low and "skew" not in low,
                        "skew-symmetric" in low, "hermitian" in low,
                        "complex" in low)


def read_mm_coo(path, nthreads: Optional[int] = None,
                ) -> tuple[np.ndarray, np.ndarray, np.ndarray, MMHeader]:
    """(rows, cols, vals, header) with symmetric/skew completion already
    applied (≅ the symmetric completion inside ParallelReadMM). Complex
    files keep the real part, like the reference's double handler.

    The native path is byte-range parallel (the reference's MPI-IO
    recipe, SpParMat.cpp:3922 + check_newline SpParHelper.h:110, with
    host threads in the role of ranks): the file is mmap'd, split at
    line boundaries, counted then parsed in place — no per-line copy.
    ``nthreads`` defaults to ``min(16, os.cpu_count())`` — capped at 16
    because byte-range splitting saturates well before that — and must
    be >= 1 when given explicitly."""
    path = str(path)
    if nthreads is not None and nthreads < 1:
        raise ValueError(f"nthreads must be >= 1, got {nthreads}")
    h = read_mm_header(path)
    lib = _native.load()
    if lib is not None:
        import ctypes
        import os
        nt = nthreads if nthreads is not None \
            else min(16, os.cpu_count() or 1)
        rows = np.empty(h.nnz, np.int32)
        cols = np.empty(h.nnz, np.int32)
        vals = np.empty(h.nnz, np.float64)
        got = lib.mm_read_body_par(
            path.encode(),
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            h.nnz, nt)
        if got < 0:
            raise ValueError(f"parse error in {path} (rc={got})")
        rows, cols, vals = rows[:got], cols[:got], vals[:got]
    else:
        data = []
        with open(path) as f:
            f.readline()
            line = f.readline()
            while line.startswith("%"):
                line = f.readline()
            for line in f:
                parts = line.split()
                if not parts or parts[0].startswith("%"):
                    continue
                r, c = int(parts[0]) - 1, int(parts[1]) - 1
                v = float(parts[2]) if (len(parts) > 2 and not h.pattern) \
                    else 1.0
                data.append((r, c, v))
        arr = np.array(data, np.float64).reshape(-1, 3)
        rows = arr[:, 0].astype(np.int32)
        cols = arr[:, 1].astype(np.int32)
        vals = arr[:, 2]

    if h.symmetric or h.skew or h.hermitian:
        off = rows != cols
        mr, mc, mv = cols[off], rows[off], vals[off]
        if h.skew:
            mv = -mv
        rows = np.concatenate([rows, mr])
        cols = np.concatenate([cols, mc])
        vals = np.concatenate([vals, mv])
    return rows, cols, vals, h


def read_mm(add: Monoid, grid: ProcGrid, path, dtype=jnp.float32,
            cap: Optional[int] = None) -> dm.DistSpMat:
    """Parse + distribute (≅ ParallelReadMM, SpParMat.cpp:3922)."""
    rows, cols, vals, h = read_mm_coo(path)
    return dm.from_global_coo(add, grid, rows, cols,
                              jnp.asarray(vals.astype(dtype)),
                              h.nrows, h.ncols, cap=cap)


def write_mm(path, a: dm.DistSpMat, pattern: bool = False) -> None:
    """Gather + write coordinate file (≅ ParallelWriteMM,
    SpParMat.h:278 — rank-0 gather variant; the byte-offset-coordinated
    parallel write has no analogue on a single-host mesh)."""
    rows, cols, vals = dm.to_global_coo(a)
    path = str(path)
    lib = _native.load()
    vals64 = np.asarray(vals, np.float64)
    rows = np.ascontiguousarray(rows, np.int32)
    cols = np.ascontiguousarray(cols, np.int32)
    vals64 = np.ascontiguousarray(vals64)
    if lib is not None:
        import ctypes
        rc = lib.mm_write(
            path.encode(),
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            vals64.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            len(rows), a.nrows, a.ncols, int(pattern))
        if rc != 0:
            raise OSError(f"mm_write failed (rc={rc})")
        return
    with open(path, "w") as f:
        kind = "pattern" if pattern else "real"
        f.write(f"%%MatrixMarket matrix coordinate {kind} general\n")
        f.write(f"{a.nrows} {a.ncols} {len(rows)}\n")
        for r, c, v in zip(rows, cols, vals64):
            if pattern:
                f.write(f"{r + 1} {c + 1}\n")
            else:
                f.write(f"{r + 1} {c + 1} {v:.17g}\n")


def read_labeled_tuples(add: Monoid, grid: ProcGrid, path,
                        dtype=jnp.float32):
    """String-labeled edge list -> (matrix, labels) (≅
    ReadGeneralizedTuples, SpParMat.cpp:3824: labels hashed to
    contiguous vertex ids; the returned list maps id -> label, the
    FullyDistVec<char[]> of the reference). Lines: "src dst [weight]";
    '#'/'%' comments skipped."""
    ids: dict = {}
    labels: list = []
    rows, cols, vals = [], [], []

    def intern(lbl):
        i = ids.get(lbl)
        if i is None:
            i = len(labels)
            ids[lbl] = i
            labels.append(lbl)
        return i

    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts or parts[0][0] in "#%":
                continue
            rows.append(intern(parts[0]))
            cols.append(intern(parts[1]))
            vals.append(float(parts[2]) if len(parts) > 2 else 1.0)
    n = len(labels)
    a = dm.from_global_coo(
        add, grid, np.asarray(rows, np.int32), np.asarray(cols, np.int32),
        jnp.asarray(np.asarray(vals).astype(dtype)), n, n)
    return a, labels


def convert_mm_to_binary(src, dst, add: Monoid = PLUS,
                         grid: Optional[ProcGrid] = None) -> None:
    """.mtx -> binary checkpoint (≅ binaryconvert/ CLI tools)."""
    grid = grid or ProcGrid.make()
    save_matrix(dst, read_mm(add, grid, src))


def convert_binary_to_mm(src, dst, add: Monoid = PLUS,
                         grid: Optional[ProcGrid] = None) -> None:
    """binary checkpoint -> .mtx."""
    grid = grid or ProcGrid.make()
    write_mm(dst, load_matrix(add, grid, src))


# ---------------------------------------------------------------------------
# Vector I/O (≅ FullyDistSpVec::ParallelRead/Write, :1209/1310)
# ---------------------------------------------------------------------------

def write_vec(path, v: dv.DistVec) -> None:
    """index value lines, 1-based (the reference's vector format)."""
    vals = v.to_global()
    with open(path, "w") as f:
        f.write(f"{v.glen}\n")
        for i, x in enumerate(vals):
            f.write(f"{i + 1} {x}\n")


def read_vec(grid: ProcGrid, path, axis: str = ROW_AXIS,
             dtype=jnp.float32) -> dv.DistVec:
    with open(path) as f:
        glen = int(f.readline())
        out = np.zeros(glen, np.float64)
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                out[int(parts[0]) - 1] = float(parts[1])
    return dv.from_global(grid, axis, jnp.asarray(out.astype(dtype)))


# ---------------------------------------------------------------------------
# Binary checkpoint (≅ ParallelBinaryWrite SpParMat.cpp:620 /
# checkpoint-by-persistence, SURVEY §5)
# ---------------------------------------------------------------------------

def save_matrix(path, a: dm.DistSpMat) -> None:
    """One-file binary snapshot of a distributed matrix (tiles +
    layout metadata). Grid-shape-independent restore: entries are
    stored as global COO."""
    rows, cols, vals = dm.to_global_coo(a)
    np.savez_compressed(path, rows=rows, cols=cols, vals=vals,
                        shape=np.array([a.nrows, a.ncols], np.int64))


def load_matrix(add: Monoid, grid: ProcGrid, path,
                cap: Optional[int] = None) -> dm.DistSpMat:
    with np.load(path) as z:
        nrows, ncols = (int(x) for x in z["shape"])
        return dm.from_global_coo(add, grid, z["rows"], z["cols"],
                                  jnp.asarray(z["vals"]), nrows, ncols,
                                  cap=cap, dedup=False)


def save_vector(path, v: dv.DistVec) -> None:
    np.savez_compressed(path, data=v.to_global(),
                        glen=np.int64(v.glen))


def load_vector(grid: ProcGrid, path, axis: str = ROW_AXIS) -> dv.DistVec:
    with np.load(path) as z:
        return dv.from_global(grid, axis, jnp.asarray(z["data"]))
