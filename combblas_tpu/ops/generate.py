"""Graph generation: R-MAT / Kronecker (Graph500) edge lists, on device.

Capability parity: DistEdgeList::GenGraph500Data (DistEdgeList.cpp:223)
wrapping the Graph500 v2.1 generator (RefGen21.h:271, graph500-1.2/
generator/*.c) plus `PermEdges`/`RenameVertices` (DistEdgeList.h:114-117).

TPU-native re-design: instead of a C library producing edge tuples on
each MPI rank, edges are generated as one vectorized JAX computation —
per recursion level, a uniform draw picks the quadrant for *all* edges at
once (VPU-wide), accumulating row/col bits. Vertex relabeling uses a
random permutation (jax.random.permutation) exactly like RenameVertices.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array


@partial(jax.jit, static_argnames=("scale", "edgefactor", "permute"))
def rmat_edges(key: Array, scale: int, edgefactor: int = 16,
               a: float = 0.57, b: float = 0.19, c: float = 0.19,
               permute: bool = True) -> tuple[Array, Array]:
    """R-MAT edge list: 2^scale vertices, edgefactor*2^scale directed edges.

    Defaults are the Graph500 parameters (a,b,c,d)=(.57,.19,.19,.05)
    (RefGen21.h / graph500 spec). Returns (rows, cols) int32 arrays of
    length m = edgefactor << scale. Self-loops and duplicates are kept
    (as in the reference; apps remove loops / dedup on matrix build).
    """
    n = 1 << scale
    m = edgefactor << scale
    kperm, key = jax.random.split(key)
    rows, cols = _rmat_bits(key, m, scale, a, b, c)
    if permute:
        perm = jax.random.permutation(kperm, n).astype(jnp.int32)
        rows = perm[rows]
        cols = perm[cols]
    return rows, cols


def _rmat_bits(key: Array, m: int, scale: int,
               a: float, b: float, c: float) -> tuple[Array, Array]:
    """The shared per-level quadrant draw: m edges, scale bit levels.
    Quadrants (0,0)/(0,1)/(1,0)/(1,1) with probability a/b/c/d."""
    def level(i, carry):
        rows, cols, key = carry
        key, sub = jax.random.split(key)
        u = jax.random.uniform(sub, (m,))
        rbit = u >= (a + b)
        cbit = ((u >= a) & (u < a + b)) | (u >= (a + b + c))
        rows = rows | (rbit.astype(jnp.int32) << i)
        cols = cols | (cbit.astype(jnp.int32) << i)
        return rows, cols, key

    rows = jnp.zeros((m,), jnp.int32)
    cols = jnp.zeros((m,), jnp.int32)
    rows, cols, _ = lax.fori_loop(0, scale, level, (rows, cols, key))
    return rows, cols


def symmetrize(rows: Array, cols: Array) -> tuple[Array, Array]:
    """A + A^T edge set (the Graph500 symmetricization step,
    TopDownBFS.cpp: `Apply(..)` after generation)."""
    return (jnp.concatenate([rows, cols]), jnp.concatenate([cols, rows]))


@partial(jax.jit, static_argnames=("scale", "edgefactor", "nchunks",
                                   "permute"))
def rmat_edges_chunk(key: Array, scale: int, edgefactor: int,
                     chunk: Array, nchunks: int,
                     a: float = 0.57, b: float = 0.19, c: float = 0.19,
                     permute: bool = True) -> tuple[Array, Array]:
    """Chunk ``chunk`` of ``nchunks`` of an R-MAT edge stream: the
    memory-scalable generator (≅ DistEdgeList's per-rank generation,
    DistEdgeList.cpp:223 — each rank/chunk draws its own slice of the
    stream). The union over all chunks of one ``key`` is a well-defined
    R-MAT sample of edgefactor*2^scale edges; chunk identity comes from
    `fold_in`, so any chunk regenerates independently (the recompute-
    not-communicate pattern: on a mesh, every device generates the same
    chunk and keeps only its own tile's entries). ``chunk`` is traced —
    one compile serves the whole stream."""
    n = 1 << scale
    m = edgefactor << scale
    mc = -(-m // nchunks)
    kperm, key = jax.random.split(key)
    key = jax.random.fold_in(key, chunk)
    rows, cols = _rmat_bits(key, mc, scale, a, b, c)
    # the last chunk may overrun m: mark the overrun invalid (out of
    # range) so tile builders drop it
    pos = chunk * mc + jnp.arange(mc, dtype=jnp.int32)
    rows = jnp.where(pos < m, rows, n)
    cols = jnp.where(pos < m, cols, n)
    if permute:
        perm = jax.random.permutation(kperm, n).astype(jnp.int32)
        rows = perm[jnp.clip(rows, 0, n - 1)] | (rows >> scale << scale)
        cols = perm[jnp.clip(cols, 0, n - 1)] | (cols >> scale << scale)
    return rows, cols
