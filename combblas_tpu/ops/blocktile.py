"""Block-sparse (BCSR / dense-block) tile format.

The padded-COO `Tile` is the canonical interchange format, but on
near-dense SpGEMM windows every intermediate still round-trips through
COO sort/compact tails even when the accumulator itself was dense
(PR-8's `dense_mxu` proved the MXU win and then paid the round trip
anyway). This module adds the second local format the ROADMAP names —
the JITSPMM direction (arxiv/2312.05639) over CombBLAS 2.0's semiring
surface (arxiv/2106.14402):

  * fixed ``(bm, bn)`` **dense value blocks** plus a block-index COO
    (per-block row/col *starts*), with a **static block capacity** so
    the whole structure is one jit/shard_map-stable pytree;
  * **monoid-zero padding** inside blocks: cells not marked in the
    ``touched`` plane carry ``add.identity`` so any reassociation-safe
    reduction over a raw block is a no-op on padding — and a separate
    0/1 ``touched`` plane (not a value comparison) preserves ESC's
    explicit-zero structure exactly, mirroring `densify_operand`;
  * **bit-exact converters** to/from `Tile`: `from_blocks` routes
    through `tl.from_coo`, whose overflow contract (drop the largest
    (row, col) coordinates) is the ESC sort-then-truncate order, and
    `to_blocks` drops the largest *block* coordinates at block-capacity
    saturation — the block-granular analogue, pinned by tests;
  * a window SpGEMM (`spgemm_colwindow_block`) whose output *stays in
    block form* — zero sorts, zero COO materialization; the planner
    converts at phase boundaries only (see parallel/spgemm.py).

Block invariants: blocks are (bm, bn)-aligned to the tile grid for
converter outputs (window-kernel outputs are row-aligned, column-offset
by the traced window base), sorted lexicographically by
(rstart, cstart), pairwise disjoint; dead block slots carry the
(nrows, ncols) start sentinel so they sort last, exactly like Tile
padding. The kernel family in `ops/pallas_kernels.py`
(`block_window_multiply`) is shape-specialized per (bm, bn, semiring)
through jit static arguments, the same mechanism `PlanCache` uses to
specialize executables per capacity bucket.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from combblas_tpu.ops import tile as tl
from combblas_tpu.ops.semiring import Monoid, Semiring

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BlockTile:
    """Dense-block sparse tile with static block shape and capacity.

    ``vals``/``touched`` are (bcap, bm, bn); the first ``nblk`` (traced)
    block slots are live, sorted lexicographically by (rstart, cstart)
    and pairwise disjoint; dead slots carry rstart==nrows,
    cstart==ncols. Within a block, ``touched[i, r, c] > 0`` marks a
    stored entry at global (rstart[i]+r, cstart[i]+c); untouched cells
    hold the monoid zero of the add monoid the tile was built under.
    """

    rstart: Array        # (bcap,) int32 — first global row of block
    cstart: Array        # (bcap,) int32 — first global col of block
    vals: Array          # (bcap, bm, bn) dtype
    touched: Array       # (bcap, bm, bn) int32 0/1
    nblk: Array          # () int32 — live block count
    nrows: int = dataclasses.field(metadata=dict(static=True))
    ncols: int = dataclasses.field(metadata=dict(static=True))

    @property
    def bcap(self) -> int:
        return self.vals.shape[0]

    @property
    def bm(self) -> int:
        return self.vals.shape[1]

    @property
    def bn(self) -> int:
        return self.vals.shape[2]

    @property
    def dtype(self):
        return self.vals.dtype

    def block_valid(self) -> Array:
        return jnp.arange(self.bcap, dtype=jnp.int32) < self.nblk

    def cell_valid(self) -> Array:
        """(bcap, bm, bn) bool: stored-entry mask (live block, touched
        cell, inside the tile bounds)."""
        r, c = _cell_coords(self)
        return ((self.touched > 0)
                & self.block_valid()[:, None, None]
                & (r < self.nrows) & (c < self.ncols))

    def nnz(self) -> Array:
        """Traced stored-entry count."""
        return jnp.sum(self.cell_valid()).astype(jnp.int32)


def _cell_coords(bt: BlockTile):
    """Global (row, col) of every cell, (bcap, bm, bn) i32 each."""
    shape = bt.vals.shape
    r = (bt.rstart[:, None, None]
         + lax.broadcasted_iota(jnp.int32, shape, 1))
    c = (bt.cstart[:, None, None]
         + lax.broadcasted_iota(jnp.int32, shape, 2))
    return r, c


def _grid(nrows: int, ncols: int, bm: int, bn: int):
    """(block rows, block cols) of the aligned grid, with an i32 guard
    on the block-id key space."""
    nbr = -(-nrows // bm)
    nbc = -(-ncols // bn)
    if nbr * nbc + 1 > 2**31 - 1:
        raise ValueError(
            f"block grid {nbr}x{nbc} overflows the i32 block-id space; "
            f"choose a larger block shape than ({bm}, {bn})")
    return nbr, nbc


def empty(nrows: int, ncols: int, *, bm: int, bn: int, bcap: int,
          dtype=jnp.float32) -> BlockTile:
    return BlockTile(
        rstart=jnp.full((bcap,), nrows, jnp.int32),
        cstart=jnp.full((bcap,), ncols, jnp.int32),
        vals=jnp.zeros((bcap, bm, bn), dtype),
        touched=jnp.zeros((bcap, bm, bn), jnp.int32),
        nblk=jnp.zeros((), jnp.int32),
        nrows=nrows, ncols=ncols)


# ---------------------------------------------------------------------------
# Converters — the bit-exactness boundary with the padded-COO Tile
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("add", "bm", "bn", "bcap"))
def to_blocks(add: Monoid, t: tl.Tile, *, bm: int, bn: int,
              bcap: int) -> BlockTile:
    """Pack a sorted COO tile into (bm, bn)-aligned dense blocks.

    Untouched cells are filled with ``add.identity`` (monoid-zero
    padding); explicit stored zeros stay distinguishable through the
    ``touched`` plane. Overflow contract at block-capacity saturation:
    when the tile touches more than ``bcap`` distinct blocks, the
    *largest* block ids — i.e. the largest (block-row, block-col)
    coordinates, whole blocks at a time — are dropped, the
    block-granular analogue of `from_coo`'s largest-coordinate drop.
    """
    nbr, nbc = _grid(t.nrows, t.ncols, bm, bn)
    sent = nbr * nbc
    v = t.valid()
    bid = jnp.where(v, (t.rows // bm) * nbc + (t.cols // bn), sent)
    ub = jnp.unique(bid, size=bcap, fill_value=sent)
    slot = jnp.clip(jnp.searchsorted(ub, bid), 0, bcap - 1).astype(jnp.int32)
    ok = v & (ub[slot] == bid)
    n = bcap * bm * bn
    fi = jnp.where(ok, slot * (bm * bn) + (t.rows % bm) * bn + (t.cols % bn),
                   n)
    zero = add.identity_scalar(t.dtype)
    vals = jnp.full((n,), zero, t.dtype).at[fi].set(
        t.vals, mode="drop").reshape(bcap, bm, bn)
    touched = jnp.zeros((n,), jnp.int32).at[fi].set(
        1, mode="drop").reshape(bcap, bm, bn)
    live_b = ub < sent
    rstart = jnp.where(live_b, (ub // nbc) * bm, t.nrows).astype(jnp.int32)
    cstart = jnp.where(live_b, (ub % nbc) * bn, t.ncols).astype(jnp.int32)
    nblk = jnp.sum(live_b).astype(jnp.int32)
    return BlockTile(rstart, cstart, vals, touched, nblk,
                     t.nrows, t.ncols)


@partial(jax.jit, static_argnames=("add", "cap", "dedup"))
def from_blocks(add: Monoid, bt: BlockTile, *, cap: int,
                dedup: bool = False) -> tl.Tile:
    """Unpack blocks into a sorted COO tile via `tl.from_coo`, so the
    output-capacity overflow order (drop the largest (row, col)) is
    identical to the ESC sort-then-truncate contract. Blocks are
    disjoint by invariant, so ``dedup=False`` is the default; pass
    ``dedup=True`` for untrusted block lists."""
    r, c = _cell_coords(bt)
    valid = bt.cell_valid()
    return tl.from_coo(add, r.ravel(), c.ravel(), bt.vals.ravel(),
                       nrows=bt.nrows, ncols=bt.ncols, cap=cap,
                       valid=valid.ravel(), dedup=dedup)


@jax.jit
def flatten(bt: BlockTile):
    """Sentinel-masked COO render of a block tile — the final-sort
    merge format of the phased loops: (rows, cols, vals, nlive) with
    dead cells at the (nrows, ncols) sentinel, vals zeroed at dead
    cells (the Tile padding-value convention)."""
    r, c = _cell_coords(bt)
    valid = bt.cell_valid()
    rows = jnp.where(valid, r, bt.nrows).ravel()
    cols = jnp.where(valid, c, bt.ncols).ravel()
    vals = jnp.where(valid, bt.vals,
                     jnp.zeros((), bt.dtype)).ravel()
    return rows, cols, vals, jnp.sum(valid).astype(jnp.int32)


def concat_blocks(parts: list) -> BlockTile:
    """Concatenate disjoint same-shape block tiles (e.g. per-window
    outputs over disjoint column ranges) into one BlockTile, restoring
    the (rstart, cstart) block sort order. Eager driver-level helper."""
    p0 = parts[0]
    if len(parts) == 1:
        return p0
    rstart = jnp.concatenate([p.rstart for p in parts])
    cstart = jnp.concatenate([p.cstart for p in parts])
    vals = jnp.concatenate([p.vals for p in parts])
    touched = jnp.concatenate([p.touched for p in parts])
    live = jnp.concatenate([p.block_valid() for p in parts])
    rs = jnp.where(live, rstart, p0.nrows)
    cs = jnp.where(live, cstart, p0.ncols)
    order = jnp.lexsort((cs, rs))
    nblk = jnp.sum(live).astype(jnp.int32)
    return BlockTile(rs[order], cs[order], vals[order], touched[order],
                     nblk, p0.nrows, p0.ncols)


@partial(jax.jit, static_argnames=("zero",))
def to_dense(bt: BlockTile, zero=0.0) -> Array:
    """(nrows, ncols) dense render, absent cells at ``zero`` — the
    canonical layout `reduce` folds over (plus the test/debug
    surface)."""
    r, c = _cell_coords(bt)
    valid = bt.cell_valid()
    n = bt.nrows * bt.ncols
    fi = jnp.where(valid, r * bt.ncols + c, n).ravel()
    out = jnp.full((n,), zero, bt.dtype)
    return out.at[fi].set(jnp.where(valid, bt.vals, 0).ravel(),
                          mode="drop").reshape(bt.nrows, bt.ncols)


# ---------------------------------------------------------------------------
# Block-level structural + EWise ops (the tile_algebra surface on blocks)
# ---------------------------------------------------------------------------

@jax.jit
def transpose(bt: BlockTile) -> BlockTile:
    """Swap block coordinates and transpose every block in place — no
    element sort (the block list re-sorts by the swapped starts, a
    bcap-length sort instead of a cap-length one)."""
    live = bt.block_valid()
    rs = jnp.where(live, bt.cstart, bt.ncols)
    cs = jnp.where(live, bt.rstart, bt.nrows)
    order = jnp.lexsort((cs, rs))
    return BlockTile(rs[order], cs[order],
                     bt.vals.transpose(0, 2, 1)[order],
                     bt.touched.transpose(0, 2, 1)[order],
                     bt.nblk, bt.ncols, bt.nrows)


@partial(jax.jit, static_argnames=("fn",))
def apply(bt: BlockTile, fn) -> BlockTile:
    """EWise map over stored entries only (≅ alg.apply): untouched
    cells keep their monoid-zero padding untouched, so the result is
    bit-identical to the COO-path apply on the stored set."""
    return dataclasses.replace(
        bt, vals=jnp.where(bt.touched > 0, fn(bt.vals), bt.vals))


@partial(jax.jit, static_argnames=("dim", "fn"))
def dim_apply(bt: BlockTile, dim: str, vec: Array, fn) -> BlockTile:
    """Scale stored entries by a per-row/col vector (≅ alg.dim_apply):
    ``fn(vals, vec[row-or-col])`` on touched cells."""
    r, c = _cell_coords(bt)
    idx = c if dim == "col" else r
    g = vec[jnp.clip(idx, 0, vec.shape[0] - 1)]
    return dataclasses.replace(
        bt, vals=jnp.where(bt.touched > 0, fn(bt.vals, g), bt.vals))


@partial(jax.jit, static_argnames=("add",))
def compact(bt: BlockTile, keep: Array, add: Monoid) -> BlockTile:
    """Drop stored entries where ``keep`` (same shape as vals) is False,
    reset dropped cells to the monoid zero, and compact fully-emptied
    blocks out of the live prefix (stable block order, so sortedness is
    preserved — the block analogue of alg.compact's stable argsort)."""
    touched = jnp.where(keep, bt.touched, 0)
    zero = jnp.asarray(add.identity_scalar(bt.dtype), bt.dtype)
    vals = jnp.where(touched > 0, bt.vals, zero)
    alive = (jnp.any(touched > 0, axis=(1, 2))) & bt.block_valid()
    order = jnp.argsort(~alive, stable=True)
    alive_s = alive[order]
    rs = jnp.where(alive_s, bt.rstart[order], bt.nrows)
    cs = jnp.where(alive_s, bt.cstart[order], bt.ncols)
    return BlockTile(rs, cs, vals[order], touched[order],
                     jnp.sum(alive).astype(jnp.int32), bt.nrows, bt.ncols)


@partial(jax.jit, static_argnames=("add", "pred"))
def prune_column(bt: BlockTile, thresh: Array, pred, add: Monoid
                 ) -> BlockTile:
    """Drop stored entries where ``pred(vals, thresh[col])`` holds
    (≅ alg.prune_column on blocks — MCL's per-column prune surface)."""
    _, c = _cell_coords(bt)
    tv = thresh[jnp.clip(c, 0, thresh.shape[0] - 1)]
    keep = (bt.touched > 0) & ~pred(bt.vals, tv)
    return compact(bt, keep, add)


@partial(jax.jit, static_argnames=("monoid", "axis"))
def reduce(monoid: Monoid, bt: BlockTile, axis: str) -> Array:
    """Per-column ("col") or per-row ("row") reduction over stored
    entries; absent lines stay at the monoid identity.

    Combine order is the CANONICAL dense fold over the logical
    (nrows, ncols) plane — a function of the tile's logical shape
    only, never of (bm, bn, bcap). The planner's per-window block
    shape therefore cannot perturb downstream numerics, and every
    order-insensitive monoid (integer add, min/max, bool or/and) is
    bit-identical to the Tile path. Float PLUS sums may differ from
    the COO chunked-scan grouping in the last ulp; `make_col_
    stochastic_block`'s docstring carries the caveat."""
    ident = monoid.identity_scalar(bt.dtype)
    dense = to_dense(bt, zero=ident)
    fold = {"add": jnp.sum, "min": jnp.min, "max": jnp.max,
            "or": jnp.max, "and": jnp.min}[monoid.kind]
    return fold(dense, axis=1 if axis == "row" else 0)


# ---------------------------------------------------------------------------
# Block window SpGEMM — the sort-free accumulator that STAYS in block form
# ---------------------------------------------------------------------------

def _window_grid(nrows: int, win_width: int, bm: int, bn: int):
    nrb = -(-nrows // bm)
    nwb = -(-win_width // bn)
    return nrb, nwb


def _pad_rows(plane: Array, m: int, fill):
    """Pad the leading (row) dim of a 2-D plane up to ``m``."""
    if plane.shape[0] == m:
        return plane
    pad = jnp.full((m - plane.shape[0], plane.shape[1]), fill, plane.dtype)
    return jnp.concatenate([plane, pad], axis=0)


def _densify_b_window(b: tl.Tile, clo, chi, W: int, carrier):
    """(k, W) value + presence planes of B's column window — the
    `_mxu_window` B render at an arbitrary carrier dtype."""
    k = b.nrows
    wcol = b.cols - clo
    bok = b.valid() & (wcol >= 0) & (wcol < jnp.minimum(chi - clo, W))
    fib = jnp.where(bok, b.rows * W + wcol, k * W)
    bvals = jnp.zeros((k * W,), carrier).at[fib].set(
        b.vals.astype(carrier), mode="drop").reshape(k, W)
    bpres = jnp.zeros((k * W,), jnp.float32).at[fib].set(
        1.0, mode="drop").reshape(k, W)
    return bvals, bpres


@partial(jax.jit, static_argnames=("sr", "flops_cap", "win_width", "bm",
                                   "bn", "mxu", "pallas_mode"))
def _spgemm_colwindow_block_impl(
        sr: Semiring, a: tl.Tile, b: tl.Tile, clo: Array, chi: Array, *,
        flops_cap: int, win_width: int, bm: int, bn: int,
        mxu: bool = False, b_struct=None, a_dense=None,
        pallas_mode: str = "off") -> BlockTile:
    """`spgemm_colwindow` whose accumulator IS the output: a block-dense
    (ceil(nrows/bm) x ceil(win_width/bn)) grid of (bm, bn) blocks over
    rows x [clo, clo+win_width) — ZERO sorts, zero COO materialization
    (`esc.block_window` pins it). Three bodies share the layout:

      * ``mxu=True``: the PR-8 `_mxu_window` matmul pair (value +
        presence), reshaped to blocks — exactly-representable monoids
        only (the `dense_mxu` float rule applies);
      * ``pallas_mode != "off"``: the shape-specialized Pallas family
        (`pk.block_window_multiply`), one executable per
        (bm, bn, semiring); the generic path combines k-lanes in
        ascending order = the ESC expansion order, so it is bit-exact
        even for float plus-times;
      * default: the XLA fused-key scatter reference — the
        `spgemm_colwindow_dense` body scattered straight into the
        padded block layout (duplicates combine in expansion-sequence
        order, bit-exact vs ESC always).

    The caller sizes ``flops_cap`` >= the window's flops (the planner
    guarantees it); output-capacity truncation happens at the phase
    boundary (`from_blocks`/final sort), never here.
    """
    assert a.ncols == b.nrows, "inner dimension mismatch (DIMMISMATCH)"
    tl._flops_cap_guard(flops_cap)
    kind = sr.add.kind
    if kind not in tl.ACCUM_KINDS:
        raise ValueError(
            f"block window accumulator needs a known monoid kind "
            f"(one of {tl.ACCUM_KINDS}), got {sr.add.name!r} with "
            f"kind={kind!r}; route user monoids to the ESC path")
    nrows = a.nrows
    nrb, nwb = _window_grid(nrows, win_width, bm, bn)
    M, W = nrb * bm, nwb * bn
    out_dtype = jax.eval_shape(
        sr.multiply, jax.ShapeDtypeStruct((), a.dtype),
        jax.ShapeDtypeStruct((), b.dtype)).dtype

    if mxu and pallas_mode == "off":
        if not tl.mxu_eligible(sr, a.dtype, b.dtype):
            raise ValueError(
                f"mxu=True needs a plus-times semiring over non-bool "
                f"operands, got {sr.name!r} ({a.dtype} x {b.dtype})")
        dense, touched = tl._mxu_window(sr, a, b, clo, chi, W, a_dense,
                                        out_dtype)
        dense = _pad_rows(dense.reshape(nrows, W), M,
                          jnp.zeros((), out_dtype))
        touched = _pad_rows(touched.reshape(nrows, W), M, 0)
    elif pallas_mode != "off":
        from combblas_tpu.ops import pallas_kernels as pk
        is_bool = out_dtype == jnp.bool_
        carrier = jnp.int32 if is_bool else out_dtype
        if a_dense is None or is_bool:
            a_dense = tl.densify_operand(a, dtype=carrier)
        avals, apres = a_dense
        bvals, bpres = _densify_b_window(b, clo, chi, W, carrier)
        if is_bool:
            mul = tl._widened_multiply(sr.multiply, a.dtype == jnp.bool_,
                                       b.dtype == jnp.bool_)
            cmb, ident = tl._widened_combine(sr.add, True)
        else:
            mul, cmb = sr.multiply, sr.add.combine
            ident = sr.add.identity_scalar(carrier)
        use_dot = mxu and tl.mxu_eligible(sr, a.dtype, b.dtype)
        dense, touched = pk.block_window_multiply(
            _pad_rows(avals.astype(carrier), M, ident),
            _pad_rows(apres, M, 0.0), bvals, bpres,
            bm=bm, bn=bn, multiply=mul, combine=cmb, ident_val=ident,
            use_dot=use_dot, interpret=pallas_mode == "interpret")
        if is_bool:
            dense = dense > 0
    else:
        info = (tl.fused_key_info(nrows, b.ncols, width=win_width)
                if tl.fused_keys_enabled() else None)
        if info is None:
            raise ValueError(
                f"block window accumulator needs the window-relative "
                f"fused-key codec (nrows={nrows}, win_width={win_width} "
                f"found no key dtype, or COMBBLAS_TPU_FUSED_KEY=0); "
                f"route to the ESC path")
        stride, kdt = info
        per, base = tl._window_counts(a, b, clo, chi, b_struct)
        key, cval, total = tl._expand_keyed(sr, a, b, per, base, flops_cap,
                                            stride=stride, kdt=kdt, clo=clo)
        n = M * W
        r = (key // stride).astype(jnp.int32)
        w = (key % stride).astype(jnp.int32)
        # scatter straight into the row-padded block layout: same update
        # order as the dense variant, so combines are bit-exact vs ESC
        fi = jnp.where((r < nrows) & (w < win_width), r * W + w, n)
        if kind in ("or", "and"):
            if out_dtype != jnp.bool_:
                raise ValueError(
                    f"or/and block accumulation expects bool products, "
                    f"got {out_dtype}")
            ident = int(bool(sr.add.identity_scalar(jnp.bool_)))
            flat = jnp.full((n,), ident, jnp.int32)
            flat = tl._monoid_scatter("max" if kind == "or" else "min",
                                      flat, fi, cval.astype(jnp.int32))
            flat = flat > 0
        else:
            flat = jnp.full((n,), sr.add.identity(out_dtype), out_dtype)
            flat = tl._monoid_scatter(kind, flat, fi, cval)
        touched = jnp.zeros((n,), jnp.int32).at[fi].max(
            jnp.ones((flops_cap,), jnp.int32), mode="drop").reshape(M, W)
        dense = flat.reshape(M, W)

    bcap = nrb * nwb
    vals = dense.reshape(nrb, bm, nwb, bn).transpose(0, 2, 1, 3).reshape(
        bcap, bm, bn)
    tch = touched.astype(jnp.int32).reshape(
        nrb, bm, nwb, bn).transpose(0, 2, 1, 3).reshape(bcap, bm, bn)
    ar = jnp.arange(bcap, dtype=jnp.int32)
    rstart = (ar // nwb) * bm
    cstart = jnp.asarray(clo, jnp.int32) + (ar % nwb) * bn
    return BlockTile(rstart, cstart, vals, tch,
                     jnp.asarray(bcap, jnp.int32), nrows, b.ncols)


def spgemm_colwindow_block(sr: Semiring, a: tl.Tile, b: tl.Tile, clo, chi,
                           *, flops_cap: int, win_width: int, bm: int,
                           bn: int, mxu: bool = False, b_struct=None,
                           a_dense=None) -> BlockTile:
    """Dispatcher: resolves COMBBLAS_TPU_PALLAS_BLOCK OUTSIDE the jit
    boundary (the PR-8 lesson / pass-7 env-in-trace rule) and forwards
    a static ``pallas_mode`` so env flips remint rather than alias
    executables."""
    from combblas_tpu.ops import pallas_kernels as pk
    if pk.block_enabled():
        pallas_mode = "interpret" if pk.block_interpret() else "tpu"
    else:
        pallas_mode = "off"
    return _spgemm_colwindow_block_impl(
        sr, a, b, clo, chi, flops_cap=flops_cap, win_width=win_width,
        bm=bm, bn=bn, mxu=mxu, b_struct=b_struct, a_dense=a_dense,
        pallas_mode=pallas_mode)


spgemm_colwindow_block._cache_size = _spgemm_colwindow_block_impl._cache_size
