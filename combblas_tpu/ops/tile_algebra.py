"""Tile-level matrix algebra: reductions, apply/prune, k-select, EWise.

Capability parity: the local bodies behind the reference's matrix
algebra surface — `Reduce` (SpParMat.cpp:886 walks local columns),
`Apply/Prune/PruneI/PruneColumn` (SpParMat.h:147-195, dcsc.h:92-97),
`Kselect1` per-column top-k (SpParMat.cpp:1191), `DimApply`
(SpParMat.h:108), and the Dcsc-level `EWiseMult`/`EWiseApply`/
`SetDifference` (Friends.h:748-1300).

TPU-native re-design: every op is a fully-vectorized pass over the
sorted-COO tile — keep-mask compaction replaces the reference's
realloc-and-copy loops, per-column ranking replaces its per-column
heap selection, and the two-tile EWise family is one tagged
concat+sort+adjacent-pair pass instead of a two-pointer merge loop.
All outputs keep the static-capacity invariant (ops.tile docstring).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from combblas_tpu.ops.semiring import Monoid, Semiring
from combblas_tpu.ops import tile as tl
from combblas_tpu.ops.tile import Tile

Array = jax.Array


def _as_blocktile(t):
    """The BlockTile instance when ``t`` is one, else None — the
    format dispatch of the reduce/apply/prune surface, so MCL-style
    pipelines run unchanged on either format (see ops.blocktile for
    each block body's combine-order contract)."""
    from combblas_tpu.ops import blocktile as bk
    return t if isinstance(t, bk.BlockTile) else None


# ---------------------------------------------------------------------------
# Keep-mask compaction (the shared body of the prune/EWise family)
# ---------------------------------------------------------------------------

def compact(t: Tile, keep: Array, cap: Optional[int] = None) -> Tile:
    """New tile holding exactly the entries where ``keep`` is set.

    ``keep`` must be False at padding. The stable live-first partition
    preserves (row, col) sortedness, so no re-sort is needed — this is
    the vectorized replacement for the reference's copy-compaction
    loops (e.g. Dcsc::Prune, dcsc.cpp).
    """
    cap = t.cap if cap is None else cap
    order = jnp.argsort(~keep, stable=True)
    keep_s = keep[order]
    rows = jnp.where(keep_s, t.rows[order], t.nrows)
    cols = jnp.where(keep_s, t.cols[order], t.ncols)
    vals = t.vals[order]
    out = Tile(rows, cols, vals, jnp.sum(keep).astype(jnp.int32),
               t.nrows, t.ncols)
    return out.with_capacity(cap) if cap != t.cap else out


# ---------------------------------------------------------------------------
# Reduce / Apply / Prune / DimApply (SpParMat.h:147-195 local bodies)
# ---------------------------------------------------------------------------

def reduce_rows(monoid: Monoid, t: Tile, map_val: Callable = None) -> Array:
    """Per-row reduction -> (nrows,): out[i] = fold(monoid, vals in row i).

    ``map_val`` optionally transforms each value before folding (the
    `__unary_op` of SpParMat::Reduce). Rows with no entries hold the
    identity. Runs on the scatter-free segmented-scan kernel (the tile
    is row-sorted).
    """
    v = t.valid()
    vals = map_val(t.vals) if map_val is not None else t.vals
    vals = jnp.where(v, vals, monoid.identity(vals.dtype))
    starts, seg_ends, nonempty = tl.row_structure(t)
    return tl.seg_reduce_sorted(monoid, vals, starts, seg_ends, nonempty)


def reduce_cols(monoid: Monoid, t: Tile, map_val: Callable = None) -> Array:
    """Per-column reduction -> (ncols,) (≅ Reduce(Column), SpParMat.cpp:886).

    Sorts by column once, then runs the same scatter-free kernel the
    row path uses.
    """
    v = t.valid()
    vals = map_val(t.vals) if map_val is not None else t.vals
    vals = jnp.where(v, vals, monoid.identity(vals.dtype))
    sc = jnp.where(v, t.cols, t.ncols)
    order = jnp.argsort(sc)          # stable not needed: fold is commutative
    sc = sc[order]
    vals = vals[order]
    prev = jnp.concatenate([jnp.full((1,), -1, jnp.int32), sc[:-1]])
    starts = sc != prev
    cptr = jnp.searchsorted(sc, jnp.arange(t.ncols + 1, dtype=jnp.int32),
                            side="left").astype(jnp.int32)
    seg_ends = cptr[1:] - 1
    nonempty = cptr[1:] > cptr[:-1]
    return tl.seg_reduce_sorted(monoid, vals, starts, seg_ends, nonempty)


def reduce(monoid: Monoid, t: Tile, dim: str,
           map_val: Callable = None) -> Array:
    """dim="row": out[i] over row i (length nrows); dim="col": out[j]
    over column j (length ncols). Accepts a BlockTile (canonical
    dense-fold combine order — see ops.blocktile.reduce)."""
    if (bt := _as_blocktile(t)) is not None:
        from combblas_tpu.ops import blocktile as bk
        if map_val is not None:
            bt = bk.apply(bt, map_val)
        return bk.reduce(monoid, bt, dim)
    if dim == "row":
        return reduce_rows(monoid, t, map_val)
    if dim == "col":
        return reduce_cols(monoid, t, map_val)
    raise ValueError(f"dim must be 'row' or 'col', got {dim!r}")


def apply(t: Tile, fn: Callable[[Array], Array]) -> Tile:
    """Elementwise value transform on live entries (≅ SpParMat::Apply).
    Accepts a BlockTile (stored entries only; padding stays put)."""
    import dataclasses
    if _as_blocktile(t) is not None:
        from combblas_tpu.ops import blocktile as bk
        return bk.apply(t, fn)
    vals = jnp.where(t.valid(), fn(t.vals), t.vals)
    return dataclasses.replace(t, vals=vals)


def prune(t: Tile, pred: Callable[[Array], Array],
          cap: Optional[int] = None) -> Tile:
    """Remove entries whose value satisfies ``pred`` (≅ Prune,
    SpParMat.h:174: "prune all entries whose predicate evaluates true")."""
    keep = t.valid() & ~pred(t.vals)
    return compact(t, keep, cap)


def prune_i(t: Tile, pred: Callable[[Array, Array, Array], Array],
            cap: Optional[int] = None,
            row_offset=0, col_offset=0) -> Tile:
    """Prune with an index-aware predicate pred(i, j, v) on *global*
    coordinates (≅ PruneI, SpParMat.h:180); offsets place the tile in
    the global matrix."""
    gi = t.rows + jnp.asarray(row_offset, jnp.int32)
    gj = t.cols + jnp.asarray(col_offset, jnp.int32)
    keep = t.valid() & ~pred(gi, gj, t.vals)
    return compact(t, keep, cap)


def prune_column(t: Tile, thresh: Array,
                 pred: Callable[[Array, Array], Array],
                 cap: Optional[int] = None,
                 add: Optional[Monoid] = None) -> Tile:
    """Per-column pruning: drop entry (i,j,v) iff pred(v, thresh[j])
    (≅ PruneColumn, SpParMat.h:190 / dcsc.h:96). ``thresh`` is a dense
    (ncols,) vector. Accepts a BlockTile; ``add`` names the monoid
    whose zero refills dropped cells there (default PLUS — MCL's)."""
    if _as_blocktile(t) is not None:
        from combblas_tpu.ops import blocktile as bk
        from combblas_tpu.ops.semiring import PLUS
        return bk.prune_column(t, thresh, pred, add if add is not None
                               else PLUS)
    cg = jnp.clip(t.cols, 0, t.ncols - 1)
    keep = t.valid() & ~pred(t.vals, thresh[cg])
    return compact(t, keep, cap)


def dim_apply(t: Tile, dim: str, vec: Array,
              fn: Callable[[Array, Array], Array]) -> Tile:
    """v_ij <- fn(v_ij, vec[i]) (dim="row") or fn(v_ij, vec[j])
    (dim="col") (≅ DimApply, SpParMat.h:108 — e.g. column scaling for
    MakeColStochastic, MCL.cpp:390). Accepts a BlockTile."""
    import dataclasses
    if _as_blocktile(t) is not None:
        from combblas_tpu.ops import blocktile as bk
        return bk.dim_apply(t, dim, vec, fn)
    if dim == "row":
        g = vec[jnp.clip(t.rows, 0, t.nrows - 1)]
    elif dim == "col":
        g = vec[jnp.clip(t.cols, 0, t.ncols - 1)]
    else:
        raise ValueError(f"dim must be 'row' or 'col', got {dim!r}")
    vals = jnp.where(t.valid(), fn(t.vals, g), t.vals)
    return dataclasses.replace(t, vals=vals)


# ---------------------------------------------------------------------------
# Per-column k-select (≅ Kselect1, SpParMat.cpp:1191)
# ---------------------------------------------------------------------------

def kselect_col(t: Tile, k, fill) -> Array:
    """Per-column k-th largest value -> (ncols,); columns with fewer
    than k entries get ``fill``.

    One sort by (col asc, val desc) + a rank gather — the vectorized
    replacement for the reference's per-column selection. ``k`` may be
    traced (clamped to >= 1). The returned thresholds feed
    `prune_column` to keep each column's top-k (ties keep extras, as
    in the reference's threshold-based PruneColumn usage).
    """
    return kselect_cols_raw(t.cols, t.vals, t.valid(), t.ncols, k, fill)


def kselect_cols_raw(cols: Array, vals: Array, valid: Array, ncols: int,
                     k, fill) -> Array:
    """`kselect_col` on raw (cols, vals, valid) arrays — the body is
    separate so the distributed Kselect1 can run it on an all-gathered
    multi-tile column slice (parallel.algebra.kselect1)."""
    k = jnp.maximum(jnp.asarray(k, jnp.int32), 1)
    n = cols.shape[0]
    sc = jnp.where(valid, cols, ncols)
    # ascending (col, val) sort; the k-th largest of column j is then at
    # cptr[j+1]-k — no value negation (exact for every dtype)
    order = jnp.lexsort((vals, sc))
    sc_s = sc[order]
    vals_s = vals[order]
    cptr = jnp.searchsorted(sc_s, jnp.arange(ncols + 1, dtype=jnp.int32),
                            side="left").astype(jnp.int32)
    pos = cptr[1:] - k                           # rank-k position per column
    has_k = pos >= cptr[:-1]                     # column has >= k entries
    out = vals_s[jnp.clip(pos, 0, n - 1)]
    return jnp.where(has_k, out, jnp.asarray(fill, vals.dtype))


def nnz_per_column(t: Tile) -> Array:
    """(ncols,) live-entry count per column (≅ Reduce(Column, plus, 1))."""
    v = t.valid()
    sc = jnp.where(v, t.cols, t.ncols)
    cptr = jnp.searchsorted(jnp.sort(sc),
                            jnp.arange(t.ncols + 1, dtype=jnp.int32),
                            side="left").astype(jnp.int32)
    return cptr[1:] - cptr[:-1]


def nnz_per_row(t: Tile) -> Array:
    """(nrows,) live-entry count per row (tile is row-sorted: free)."""
    rst = tl.row_starts(t)
    return rst[1:] - rst[:-1]


# ---------------------------------------------------------------------------
# Two-tile EWise family (≅ Friends.h:748-1300, ParFriends.h:2157-2243)
# ---------------------------------------------------------------------------
#
# All three ops share one skeleton: tag-concat the two sorted tiles,
# sort by (row, col, tag), and classify each position as a *pair first*
# (same coordinate as the next position — the A entry), *pair second*
# (the matching B entry), or a singleton of either side. Tiles are
# duplicate-free, so at most two entries share a coordinate and pairs
# are adjacent with A first.

def _ewise_classify(a: Tile, b: Tile):
    assert a.nrows == b.nrows and a.ncols == b.ncols, "DIMMISMATCH"
    va, vb = a.valid(), b.valid()
    rows = jnp.concatenate([jnp.where(va, a.rows, a.nrows),
                            jnp.where(vb, b.rows, b.nrows)])
    cols = jnp.concatenate([jnp.where(va, a.cols, a.ncols),
                            jnp.where(vb, b.cols, b.ncols)])
    tag = jnp.concatenate([jnp.zeros((a.cap,), jnp.int32),
                           jnp.ones((b.cap,), jnp.int32)])
    valid = jnp.concatenate([va, vb])
    order = jnp.lexsort((tag, cols, rows))
    rows, cols, tag, valid = rows[order], cols[order], tag[order], valid[order]
    nxt_same = jnp.concatenate([
        (rows[1:] == rows[:-1]) & (cols[1:] == cols[:-1]),
        jnp.zeros((1,), bool)])
    pair_first = nxt_same & valid                 # A entry with B match
    pair_second = jnp.concatenate([jnp.zeros((1,), bool),
                                   pair_first[:-1]])
    return rows, cols, tag, valid, order, pair_first, pair_second


def _gathered_vals(a: Tile, b: Tile, order: Array) -> Array:
    vals = jnp.concatenate([a.vals, b.vals.astype(a.vals.dtype)])
    return vals[order]


def ewise_mult(sr_multiply: Callable[[Array, Array], Array],
               a: Tile, b: Tile, exclude: bool = False,
               cap: Optional[int] = None) -> Tile:
    """exclude=False: intersection A .* B with ``sr_multiply``;
    exclude=True: entries of A whose coordinate is NOT in B (the BFS
    fringe masking op — ≅ EWiseMult(exclude), ParFriends.h:2174).
    Result has A's value dtype."""
    rows, cols, tag, valid, order, pf, ps = _ewise_classify(a, b)
    vals = _gathered_vals(a, b, order)
    if exclude:
        keep = valid & (tag == 0) & ~pf
        out_vals = vals
    else:
        nxt = jnp.concatenate([vals[1:], vals[:1]])
        out_vals = sr_multiply(vals, nxt)
        keep = pf
    cap = cap if cap is not None else a.cap
    return compact(Tile(jnp.where(valid, rows, a.nrows),
                        jnp.where(valid, cols, a.ncols),
                        out_vals, jnp.sum(valid).astype(jnp.int32),
                        a.nrows, a.ncols),
                   keep, cap)


def set_difference(a: Tile, b: Tile, cap: Optional[int] = None) -> Tile:
    """A \\ B on coordinates (≅ SetDifference, ParFriends.h:2157)."""
    return ewise_mult(lambda x, y: x, a, b, exclude=True, cap=cap)


def ewise_apply(a: Tile, b: Tile, fn: Callable[[Array, Array], Array],
                *, allow_a_null: bool = False, allow_b_null: bool = False,
                a_null=0, b_null=0, cap: Optional[int] = None,
                out_dtype=None, pass_presence: bool = False) -> Tile:
    """General union/intersection EWise (≅ EWiseApply with null
    handling, ParFriends.h:2194-2243):

      * coordinate in both:      fn(va, vb)
      * only in A:               fn(va, b_null)  if allow_b_null else drop
      * only in B:               fn(a_null, vb)  if allow_a_null else drop

    With ``pass_presence=True``, ``fn(va, vb, a_has, b_has)`` also
    receives boolean presence flags (the extended predicate form of the
    reference's EWiseApply) so asymmetric merges can distinguish "only
    in B" from "B holds the null value".
    """
    rows, cols, tag, valid, order, pf, ps = _ewise_classify(a, b)
    vals = _gathered_vals(a, b, order)
    out_dtype = out_dtype or a.dtype
    nxt = jnp.concatenate([vals[1:], vals[:1]])
    an = jnp.asarray(a_null, vals.dtype)
    bn = jnp.asarray(b_null, vals.dtype)
    only_a = valid & (tag == 0) & ~pf
    only_b = valid & (tag == 1) & ~ps
    if pass_presence:
        def call(va, vb, ah, bh):
            return fn(va, vb, ah, bh).astype(out_dtype)
        out_vals = jnp.where(
            pf, call(vals, nxt, True, True),
            jnp.where(only_a, call(vals, bn, True, False),
                      call(an, vals, False, True)))
    else:
        out_vals = jnp.where(
            pf, fn(vals, nxt).astype(out_dtype),
            jnp.where(only_a, fn(vals, bn).astype(out_dtype),
                      fn(an, vals).astype(out_dtype)))
    keep = pf
    if allow_b_null:
        keep = keep | only_a
    if allow_a_null:
        keep = keep | only_b
    # default capacity never drops: union output can reach a.nnz + b.nnz
    cap = cap if cap is not None else (
        a.cap + b.cap if (allow_a_null or allow_b_null) else max(a.cap, b.cap))
    return compact(Tile(jnp.where(valid, rows, a.nrows),
                        jnp.where(valid, cols, a.ncols),
                        out_vals, jnp.sum(valid).astype(jnp.int32),
                        a.nrows, a.ncols),
                   keep, cap)


# ---------------------------------------------------------------------------
# Column slice / concat (≅ Dcsc::ColSplit/ColConcatenate, dcsc.h:101-105 —
# the local bodies of phased SpGEMM, ParFriends.h:555)
# ---------------------------------------------------------------------------

def col_slice(t: Tile, lo: int, hi: int, cap: int) -> Tile:
    """Columns [lo, hi) as a new (nrows, hi-lo) tile (cols shifted)."""
    keep = t.valid() & (t.cols >= lo) & (t.cols < hi)
    ncols_new = hi - lo
    shifted = Tile(t.rows, jnp.where(keep, t.cols - lo, ncols_new),
                   t.vals, t.nnz, t.nrows, ncols_new)
    return compact(shifted, keep, cap)


def row_slice(t: Tile, lo: int, hi: int, cap: int) -> Tile:
    """Rows [lo, hi) as a new (hi-lo, ncols) tile (rows shifted;
    ≅ the row-split half of Dcsc splitting). Sorted order survives the
    uniform shift, so compaction alone suffices."""
    keep = t.valid() & (t.rows >= lo) & (t.rows < hi)
    nrows_new = hi - lo
    shifted = Tile(jnp.where(keep, t.rows - lo, nrows_new), t.cols,
                   t.vals, t.nnz, nrows_new, t.ncols)
    return compact(shifted, keep, cap)


def col_concat(tiles: list, cap: int) -> Tile:
    """Concatenate tiles horizontally (inverse of `col_slice` splits).

    Entries are disjoint by construction (distinct column ranges), so
    this is a merge without dedup."""
    nrows = tiles[0].nrows
    offs = []
    total = 0
    for t in tiles:
        assert t.nrows == nrows, "DIMMISMATCH"
        offs.append(total)
        total += t.ncols
    rows = jnp.concatenate([t.rows for t in tiles])
    cols = jnp.concatenate(
        [jnp.where(t.valid(), t.cols + off, total)
         for t, off in zip(tiles, offs)])
    vals = jnp.concatenate([t.vals for t in tiles])
    valid = jnp.concatenate([t.valid() for t in tiles])
    from combblas_tpu.ops.semiring import PLUS
    return tl.from_coo(PLUS, rows, cols, vals, nrows=nrows, ncols=total,
                       cap=cap, valid=valid, dedup=False)
