"""Pallas TPU kernels for the hot scan paths.

The chunk-column segmented scan (ops.tile.seg_scan_core) is the inner
loop of every SpMV/BFS/reduce kernel. XLA lowers
`lax.associative_scan` over the (L, 128) layout to ~log2(L) full
passes over HBM; this Pallas kernel computes the same inclusive
segmented scan in ONE pass — each (BL, 128) row block is scanned
in VMEM (Hillis-Steele, log2(BL) VPU steps), stitched with a carry
row kept in VMEM scratch across the sequential TPU grid. HBM traffic
drops from ~log2(L)x to ~1x read + 1x write.

Validated bit-exact against the XLA path on real v5e hardware (and
covered by interpret-mode tests everywhere), so it is ON by default
for TPU backends; COMBBLAS_TPU_PALLAS=0 disables it. The XLA path
remains the reference implementation. Mosaic constraints baked in
here: no i1 vregs (flags ride int32), no int8 vector compute (int8
data is widened in VMEM), and `vma` must be forwarded on out_shape
when called under shard_map.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

_BL = 512                      # row-block (multiple of 32: int8 tiling)


class _BoolCombine:
    """Run a boolean monoid combine on int8 carriers (bool data rides
    VMEM as int8). Hash/eq delegate to the wrapped combine so the jit
    cache keys stay stable."""

    def __init__(self, combine):
        self.combine = combine

    def __call__(self, a, b):
        return self.combine(a != 0, b != 0).astype(jnp.int8)

    def __hash__(self):
        return hash(("_BoolCombine", self.combine))

    def __eq__(self, other):
        return (isinstance(other, _BoolCombine)
                and self.combine == other.combine)


def enabled() -> bool:
    """Use the Pallas scan? Default ON for TPU backends (validated on
    v5e hardware: bit-exact vs the XLA path, ~4x fewer HBM passes);
    COMBBLAS_TPU_PALLAS=0 force-disables. Non-TPU backends always take
    the XLA path (interpret mode is for tests, via the explicit
    ``interpret=True`` argument)."""
    # deliberate trace-time read: the flag selects which kernel gets
    # traced; flips require jax.clear_caches() (tests do; see budget._Env)
    if os.environ.get("COMBBLAS_TPU_PALLAS", "") == "0":  # analysis: allow(env-in-trace)
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def is_batched(x) -> bool:
    """True when ``x`` is inside a vmap trace. The kernel's
    sequential-carry design (program_id(0) + one carry scratch) is not
    batch-safe — pallas_call's batching rule would add a grid dim the
    carry logic ignores — so vmapped callers (SpMM's width axis, the
    per-tile vmaps of the algebra layer) take the XLA path."""
    try:
        from jax._src.interpreters import batching  # jax 0.9: private
        return isinstance(x, batching.BatchTracer)
    except Exception:
        return True     # can't tell: stay on the safe XLA path


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the caller's varying-mesh-axes set —
    required for pallas_call under shard_map (check_vma=True); outside
    a shard_map the vma is empty and harmless."""
    vma = getattr(getattr(like, "aval", None), "vma", None)
    try:
        return jax.ShapeDtypeStruct(shape, dtype,
                                    vma=vma if vma is not None
                                    else frozenset())
    except TypeError:      # older jax: no vma kwarg
        return jax.ShapeDtypeStruct(shape, dtype)


def _block_seg_scan(x, f, combine, ident):
    """In-VMEM Hillis-Steele inclusive segmented scan of a (BL, C)
    block along axis 0. f marks segment starts (int32 0/1 — Mosaic
    cannot materialize i1 vectors for concatenate/store, so flags ride
    i32 vregs and only the `where` predicate is a transient mask);
    returns (scanned x, or-prefix of f, still int32)."""
    bl = x.shape[0]
    shift = 1
    while shift < bl:
        # pad with the segmented-scan IDENTITY (0, ident): values
        # combine(ident, x) == x stop naturally at the block top, and
        # the flag or-prefix stays exact (a set pad would falsely mark
        # every row as flag-covered and break the carry stitch)
        pad_x = jnp.full((shift, x.shape[1]), ident, x.dtype)
        pad_f = jnp.zeros((shift, f.shape[1]), jnp.int32)
        prev_x = jnp.concatenate([pad_x, x[:-shift]], axis=0)
        prev_f = jnp.concatenate([pad_f, f[:-shift]], axis=0)
        x = jnp.where(f != 0, x, combine(prev_x, x))
        f = f | prev_f
        shift *= 2
    return x, f


def _seg_scan_kernel(d_ref, f_ref, o_ref, of_ref, carry_ref, fcarry_ref,
                     *, combine, ident_val):
    import jax.experimental.pallas as pl

    i = pl.program_id(0)
    x = d_ref[...]      # int8/bool data pre-widened to int32 by the wrapper
    f = f_ref[...].astype(jnp.int32)
    ident = jnp.asarray(ident_val, x.dtype)        # python scalar -> const
    xx, ff = _block_seg_scan(x, f, combine, ident)

    @pl.when(i == 0)
    def _init():
        carry_ref[...] = jnp.full_like(carry_ref, ident_val)
        fcarry_ref[...] = jnp.zeros_like(fcarry_ref)

    carry = carry_ref[0:1, :].astype(x.dtype)      # (1, C)
    fcarry = fcarry_ref[0:1, :]
    xx = jnp.where(ff != 0, xx, combine(carry, xx))
    fftot = ff | fcarry                            # column or-prefix
    o_ref[...] = xx.astype(o_ref.dtype)
    of_ref[...] = fftot
    carry_ref[0:1, :] = xx[-1:, :].astype(carry_ref.dtype)
    fcarry_ref[0:1, :] = fftot[-1:, :]


@functools.partial(jax.jit, static_argnames=("combine", "ident_val",
                                             "interpret"))
def seg_scan_values(d2, f2, *, combine, ident_val,
                    interpret: bool = False):
    """Inclusive segmented scan matching tile.seg_scan_core's value
    output: columns of the (L, C) layout are CONSECUTIVE sequence
    chunks, so after the per-column Pallas pass a tiny (C,)-length
    cross-column carry scan stitches chunk boundaries exactly as the
    XLA reference does. ``combine`` must be a module-level binary jnp
    op; ``ident_val`` its identity as a python scalar."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from jax import lax

    L, C = d2.shape
    nblk = -(-L // _BL)
    padL = nblk * _BL
    if padL != L:
        d2 = jnp.pad(d2, ((0, padL - L), (0, 0)),
                     constant_values=ident_val)
        f2 = jnp.pad(f2, ((0, padL - L), (0, 0)), constant_values=True)
    # Mosaic cannot materialize i1 vregs (and int8 vector compute is
    # unreliable on v5e): ship flags — and bool/int8 data, e.g.
    # LOR-monoid tiles — as int32; results cast back outside.
    f2 = f2.astype(jnp.int32)
    was_bool = d2.dtype == jnp.bool_
    if was_bool:
        combine = _BoolCombine(combine)
        ident_val = int(bool(ident_val))
    narrow = d2.dtype if d2.dtype in (jnp.bool_, jnp.int8) else None
    if narrow is not None:
        d2 = d2.astype(jnp.int32)

    kernel = functools.partial(_seg_scan_kernel, combine=combine,
                               ident_val=ident_val)
    xx, ff32 = pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((_BL, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_BL, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((_BL, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_BL, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[_sds((padL, C), d2.dtype, d2),
                   _sds((padL, C), jnp.int32, d2)],
        scratch_shapes=[pltpu.VMEM((8, C), d2.dtype),
                        pltpu.VMEM((8, C), jnp.int32)],
        interpret=interpret,
    )(d2, f2)
    xx = xx[:L]
    ff = ff32[:L] > 0
    # cross-column (chunk-boundary) stitch — the (C,)-length carry scan
    # of tile.seg_scan_core, verbatim
    ident = jnp.asarray(ident_val, xx.dtype)

    def op(a, b):
        af, ax = a
        bf, bx = b
        return af | bf, jnp.where(bf, bx, combine(ax, bx))

    cf, cx = lax.associative_scan(op, (ff[-1], xx[-1]))
    prev = jnp.concatenate([jnp.full((1,), ident, xx.dtype), cx[:-1]])
    out = jnp.where(ff, xx, combine(prev[None, :], xx))
    if was_bool:
        return out > 0
    if narrow is not None:          # int8 rode i32 vregs; restore dtype
        return out.astype(narrow)
    return out


# ---------------------------------------------------------------------------
# Fused ESC expansion: propagate + B gathers + multiply + key encode,
# one VMEM-resident pass per (BL, 128) block
# ---------------------------------------------------------------------------
#
# The XLA fused expansion (tile._expand_finish_xla) is already one
# multi-channel scan, but XLA materializes each of its stages in HBM:
# the log2(L) scan passes, the two cap-sized B gathers, the multiply and
# the key encode each round-trip flops_cap-sized arrays. Because
# tile._expand_prep seeds every chunk-column's top row (making every
# column scan self-contained — no cross-column carry), the WHOLE back
# end fuses into one sequential-grid Pallas pass: per block, scan the 3
# channels in VMEM (shared flags, Hillis-Steele), gather B's cols/vals
# from a VMEM-resident copy of the B table, multiply, encode the fused
# sort key, and write exactly two outputs. HBM traffic: 4 channel reads
# + 2 writes per slot, vs ~log2(L)+6 array passes for the XLA back end.
#
# The B table must fit VMEM: gated on b.cap <= EXPAND_BMAX (2^19 slots
# = 2 MB cols + <=2 MB vals alongside ~2 MB of block buffers). The MCL
# and streaming planners bound window B caps well under this. i32 keys
# only (the caller checks); interpret mode covers tests off-TPU. The
# in-kernel flat gather is the one construct the seg-scan kernel does
# not already exercise on hardware, so this kernel is OFF by default on
# real TPUs until validated there: COMBBLAS_TPU_PALLAS_EXPAND=1 opts
# in, =interpret forces interpret mode (tests), =0 force-disables; the
# XLA fused back end remains the production default and the reference.

EXPAND_BMAX = 1 << 19          # max B-table slots kept VMEM-resident


def expand_mode() -> str:
    # trace-time kernel selector; flips require jax.clear_caches()
    return os.environ.get("COMBBLAS_TPU_PALLAS_EXPAND", "")  # analysis: allow(env-in-trace)


def expand_enabled() -> bool:
    """Use the Pallas fused-expansion kernel? Opt-IN on TPU backends
    (=1; unvalidated-on-hardware gather, see module comment), or
    anywhere under =interpret (tests); =0 / unset-off-TPU disable.
    COMBBLAS_TPU_PALLAS=0 still vetoes everything."""
    mode = expand_mode()
    if mode == "interpret":
        return os.environ.get("COMBBLAS_TPU_PALLAS", "") != "0"  # analysis: allow(env-in-trace) same clear_caches contract
    return mode == "1" and enabled()


def expand_interpret() -> bool:
    return expand_mode() == "interpret"


def _fused_expand_kernel(scal_ref, rowv_ref, dv_ref, av_ref, f_ref,
                         bc_ref, bv_ref, key_ref, cval_ref,
                         rcar, dcar, acar, fcar,
                         *, multiply, stride, nrows, L, flops_cap, bcap):
    import jax.experimental.pallas as pl
    from jax import lax

    i = pl.program_id(0)
    col_lo = scal_ref[0]
    total = scal_ref[1]
    f = f_ref[...]                 # start flags, pre-widened to int32
    row = rowv_ref[...]
    dl = dv_ref[...]
    av = av_ref[...]
    bl, C = row.shape
    # joint Hillis-Steele copy-forward: ONE flag or-prefix drives all
    # three channels (the zero pad is safe: uncovered top rows are
    # patched by the carry below, and with column-top seeding block 0
    # has no uncovered rows at all)
    shift = 1
    while shift < bl:

        def prev(x):
            return jnp.concatenate(
                [jnp.zeros((shift, C), x.dtype), x[:-shift]], axis=0)

        keep = f != 0
        row = jnp.where(keep, row, prev(row))
        dl = jnp.where(keep, dl, prev(dl))
        av = jnp.where(keep, av, prev(av))
        f = f | prev(f)
        shift *= 2

    @pl.when(i == 0)
    def _init():
        rcar[...] = jnp.zeros_like(rcar)
        dcar[...] = jnp.zeros_like(dcar)
        acar[...] = jnp.zeros_like(acar)
        fcar[...] = jnp.zeros_like(fcar)

    keep = f != 0
    row = jnp.where(keep, row, rcar[0:1, :])
    dl = jnp.where(keep, dl, dcar[0:1, :])
    av = jnp.where(keep, av, acar[0:1, :])
    ftot = f | fcar[0:1, :]
    rcar[0:1, :] = row[-1:, :]
    dcar[0:1, :] = dl[-1:, :]
    acar[0:1, :] = av[-1:, :]
    fcar[0:1, :] = ftot[-1:, :]

    lidx = lax.broadcasted_iota(jnp.int32, (bl, C), 0) + i * bl
    cidx = lax.broadcasted_iota(jnp.int32, (bl, C), 1)
    slot = cidx * L + lidx         # sequence position of (l, c)
    bidx = jnp.clip(dl + slot, 0, bcap - 1)
    tabc = bc_ref[...]
    tabv = bv_ref[...]
    bcol = tabc[bidx // 128, bidx % 128]
    bval = tabv[bidx // 128, bidx % 128]
    live = (lidx < L) & (slot < total) & (slot < flops_cap)
    kmax = (nrows + 1) * stride - 1
    key_ref[...] = jnp.where(live, row * stride + (bcol - col_lo),
                             jnp.asarray(kmax, jnp.int32))
    cval_ref[...] = multiply(av, bval).astype(cval_ref.dtype)


@functools.partial(jax.jit, static_argnames=("multiply", "stride", "nrows",
                                             "L", "flops_cap", "interpret"))
def fused_expand(rowv2, deltav2, avalv2, f2, bcols, bvals, col_lo, total,
                 *, multiply, stride: int, nrows: int, L: int,
                 flops_cap: int, interpret: bool = False):
    """One-pass fused ESC expansion over the seeded chunk-column layout
    from tile._expand_prep. Returns (key, cval) in sequence order,
    length flops_cap — bit-identical to tile._expand_finish_xla (same
    propagation recurrence, same gathers, same encode). bool/int8
    channels must be pre-widened to int32 by the caller (Mosaic has no
    i1/i8 vector compute); ``multiply`` must be cache-stable."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _, C = rowv2.shape
    nblk = -(-L // _BL)
    padL = nblk * _BL
    if padL != L:
        padr = ((0, padL - L), (0, 0))
        rowv2 = jnp.pad(rowv2, padr)
        deltav2 = jnp.pad(deltav2, padr)
        avalv2 = jnp.pad(avalv2, padr)
        f2 = jnp.pad(f2, padr, constant_values=True)
    f2 = f2.astype(jnp.int32)
    bcap = bcols.shape[0]
    bn = -(-bcap // 128)
    padB = bn * 128 - bcap
    if padB:
        bcols = jnp.pad(bcols, (0, padB))
        bvals = jnp.pad(bvals, (0, padB))
    out_dtype = jax.eval_shape(
        multiply, jax.ShapeDtypeStruct((), avalv2.dtype),
        jax.ShapeDtypeStruct((), bvals.dtype)).dtype
    scal = jnp.stack([jnp.asarray(col_lo, jnp.int32),
                      jnp.asarray(total, jnp.int32)])
    kernel = functools.partial(_fused_expand_kernel, multiply=multiply,
                               stride=stride, nrows=nrows, L=L,
                               flops_cap=flops_cap, bcap=bcap)
    blk = lambda: pl.BlockSpec((_BL, C), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)
    tab = lambda: pl.BlockSpec((bn, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)
    key, cval = pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((2,), lambda i: (0,), memory_space=pltpu.SMEM),
            blk(), blk(), blk(), blk(), tab(), tab(),
        ],
        out_specs=[blk(), blk()],
        out_shape=[_sds((padL, C), jnp.int32, rowv2),
                   _sds((padL, C), out_dtype, rowv2)],
        scratch_shapes=[pltpu.VMEM((8, C), jnp.int32),
                        pltpu.VMEM((8, C), jnp.int32),
                        pltpu.VMEM((8, C), avalv2.dtype),
                        pltpu.VMEM((8, C), jnp.int32)],
        interpret=interpret,
    )(scal, rowv2, deltav2, avalv2, f2,
      bcols.reshape(bn, 128), bvals.reshape(bn, 128))
    return (key[:L].T.reshape(-1)[:flops_cap],
            cval[:L].T.reshape(-1)[:flops_cap])


# ---------------------------------------------------------------------------
# Linear-probing hash accumulator: the mid-density SpGEMM window variant
# ---------------------------------------------------------------------------
#
# ESC sorts the whole |expansion|; the dense accumulator spends
# O(nrows * win_width) memory. In between — windows whose output is a
# few percent dense — the mtSpGEMM-style hash accumulator wins: stream
# the expansion's (fused key, value) pairs through a VMEM-resident
# linear-probing table (monoid combine on key collision, kmax-sentinel
# empty slots), then sort only the table_cap-sized survivor set. The
# sequential grid + persistent VMEM scratch make insertion order ==
# expansion order, so floating-point combines stay bit-exact vs ESC's
# stable-sort left-to-right order. Like the fused-expansion kernel this
# is opt-IN on hardware until validated there:
# COMBBLAS_TPU_PALLAS_HASH=1 opts in on TPU, =interpret forces
# interpret mode (CPU tests), unset/0 leaves the XLA segment-reduce
# fallback (ops.tile.spgemm_colwindow_hash) as the production default.

HASH_TMAX = 1 << 16            # max table slots kept VMEM-resident
_HASH_IB = 1024                # items per sequential grid step


def hash_mode() -> str:
    # trace-time kernel selector; flips require jax.clear_caches()
    return os.environ.get("COMBBLAS_TPU_PALLAS_HASH", "")  # analysis: allow(env-in-trace)


def hash_enabled() -> bool:
    """Use the Pallas hash accumulator? Opt-IN on TPU backends (=1), or
    anywhere under =interpret (tests); COMBBLAS_TPU_PALLAS=0 vetoes."""
    mode = hash_mode()
    if mode == "interpret":
        return os.environ.get("COMBBLAS_TPU_PALLAS", "") != "0"  # analysis: allow(env-in-trace) same clear_caches contract
    return mode == "1" and enabled()


def hash_interpret() -> bool:
    return hash_mode() == "interpret"


def hash_table_cap(out_cap: int) -> int:
    """Power-of-two table size >= 2 * out_cap: load factor <= 0.5 when
    the caller's out_cap bounds the true distinct-key count (the
    planner guarantees it), keeping probe chains short."""
    return max(128, 1 << (2 * max(int(out_cap), 1) - 1).bit_length())


def _hash_kernel(k_ref, v_ref, tk_out, tv_out, tk_ref, tv_ref,
                 *, table_cap, combine, ident_val, kmax):
    import jax.experimental.pallas as pl
    from jax import lax

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        tk_ref[...] = jnp.full(tk_ref.shape, kmax, tk_ref.dtype)
        tv_ref[...] = jnp.full(tv_ref.shape, ident_val, tv_ref.dtype)

    mask = jnp.int32(table_cap - 1)
    nitems = k_ref.shape[1]

    def insert(j, carry):
        k = k_ref[0, j]
        v = v_ref[0, j]

        def do():
            # Fibonacci multiplicative hash on the fused key; int32
            # wraparound is intentional, the mask keeps it nonnegative
            h = (k.astype(jnp.uint32)
                 * jnp.uint32(2654435761)).astype(jnp.int32) & mask

            # the cond must stay ref-free: interpret mode discharges the
            # loop, and while_p's discharge rule rejects ref reads in
            # the cond — so probe state (found/empty) rides the carry
            def cond(c):
                _, step, done = c
                return jnp.logical_not(done) & (step < table_cap)

            def body(c):
                slot, step, _ = c
                tk = tk_ref[0, slot]
                done = (tk == kmax) | (tk == k)
                return (jnp.where(done, slot, (slot + 1) & mask),
                        step + 1, done)

            slot, _, _ = lax.while_loop(
                cond, body, (h, jnp.int32(0), jnp.bool_(False)))
            tk = tk_ref[0, slot]
            # a full table (bounded probing exhausted) drops the item;
            # callers size table_cap >= 2x the true distinct-key count

            @pl.when(tk == kmax)
            def _new():
                tk_ref[0, slot] = k
                tv_ref[0, slot] = v

            @pl.when(tk == k)
            def _combine():
                tv_ref[0, slot] = combine(tv_ref[0, slot], v)

        pl.when(k != kmax)(do)
        return carry

    lax.fori_loop(0, nitems, insert, jnp.int32(0))
    tk_out[...] = tk_ref[...]
    tv_out[...] = tv_ref[...]


@functools.partial(jax.jit, static_argnames=("table_cap", "combine",
                                             "ident_val", "kmax",
                                             "interpret"))
def hash_accumulate(key, val, *, table_cap: int, combine, ident_val,
                    kmax: int, interpret: bool = False):
    """Accumulate (key, val) items into a linear-probing hash table.

    ``key`` (n,) int32 with dead slots carrying ``kmax``; ``val`` (n,)
    any Mosaic-vector dtype (bool/int8 must be pre-widened to int32 by
    the caller). Returns (table_keys, table_vals), each (table_cap,),
    with empty slots keyed ``kmax`` and valued ``ident_val``. Items are
    inserted in sequence order (sequential grid, persistent VMEM
    table), so collisions combine left-to-right like ESC's stable
    sort. ``combine``/``ident_val``/``kmax`` must be cache-stable
    static values."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = key.shape[0]
    nb = max(1, -(-n // _HASH_IB))
    padN = nb * _HASH_IB
    if padN != n:
        key = jnp.pad(key, (0, padN - n), constant_values=kmax)
        val = jnp.pad(val, (0, padN - n), constant_values=ident_val)
    kernel = functools.partial(_hash_kernel, table_cap=table_cap,
                               combine=combine, ident_val=ident_val,
                               kmax=kmax)
    blk = lambda: pl.BlockSpec((1, _HASH_IB), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)
    tblk = lambda: pl.BlockSpec((1, table_cap), lambda i: (0, 0),
                                memory_space=pltpu.VMEM)
    tk, tv = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[blk(), blk()],
        out_specs=[tblk(), tblk()],
        out_shape=[_sds((1, table_cap), jnp.int32, key),
                   _sds((1, table_cap), val.dtype, val)],
        scratch_shapes=[pltpu.VMEM((1, table_cap), jnp.int32),
                        pltpu.VMEM((1, table_cap), val.dtype)],
        interpret=interpret,
    )(key.reshape(nb, _HASH_IB), val.reshape(nb, _HASH_IB))
    return tk.reshape(-1), tv.reshape(-1)


# ---------------------------------------------------------------------------
# Shape-specialized block window multiply — the BCSR SpGEMM accumulator
# (ops/blocktile.py). One executable per (bm, bn, semiring) via jit
# static args, the same per-bucket specialization PlanCache applies to
# capacities. Layout: A^T planes (k, M) so the sequential k-lane walk of
# the generic path extracts second-minor rows (cheap in Mosaic — no
# minor-dim dynamic gather), B planes (k, W) natively row-extractable.
# ---------------------------------------------------------------------------

_BLOCK_KB = 128                # contraction depth per sequential grid step


def block_mode() -> str:
    # trace-time kernel selector; flips require jax.clear_caches()
    return os.environ.get("COMBBLAS_TPU_PALLAS_BLOCK", "")  # analysis: allow(env-in-trace)


def block_enabled() -> bool:
    """Use the Pallas block-window kernel? Opt-IN on TPU backends (=1),
    or anywhere under =interpret (tests); COMBBLAS_TPU_PALLAS=0 vetoes."""
    mode = block_mode()
    if mode == "interpret":
        return os.environ.get("COMBBLAS_TPU_PALLAS", "") != "0"  # analysis: allow(env-in-trace) same clear_caches contract
    return mode == "1" and enabled()


def block_interpret() -> bool:
    return block_mode() == "interpret"


def _block_window_kernel(av_ref, ap_ref, bv_ref, bp_ref, cv_out, ct_out,
                         acc_ref, cnt_ref, *, multiply, combine, ident_val,
                         use_dot, nkb):
    import jax.experimental.pallas as pl
    from jax import lax

    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.full(acc_ref.shape, ident_val, acc_ref.dtype)
        cnt_ref[...] = jnp.zeros(cnt_ref.shape, cnt_ref.dtype)

    av = av_ref[...]            # (KB, bm) — A^T slab
    ap = ap_ref[...]            # (KB, bm) f32 presence
    bv = bv_ref[...]            # (KB, bn)
    bp = bp_ref[...]            # (KB, bn) f32 presence

    if use_dot:
        # exactly-representable monoids: one MXU pass per slab (value
        # matmul + presence matmul, the PR-8 dense_mxu structure)
        acc_ref[...] = acc_ref[...] + lax.dot_general(
            av, bv, (((0,), (0,)), ((), ())),
            preferred_element_type=acc_ref.dtype)
        cnt_ref[...] = cnt_ref[...] + lax.dot_general(
            ap, bp, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        # generic semiring: combine k-lanes in ASCENDING order — the
        # ESC expansion-sequence order — so even float plus-times is
        # bit-exact vs the reference
        def lane(j, carry):
            acc, cnt = carry
            pa = jnp.transpose(lax.dynamic_slice_in_dim(av, j, 1, 0))
            qa = jnp.transpose(lax.dynamic_slice_in_dim(ap, j, 1, 0))
            pb = lax.dynamic_slice_in_dim(bv, j, 1, 0)
            qb = lax.dynamic_slice_in_dim(bp, j, 1, 0)
            present = (qa > 0) & (qb > 0)          # (bm, 1) & (1, bn)
            prod = jnp.where(present, multiply(pa, pb),
                             jnp.asarray(ident_val, acc.dtype))
            return combine(acc, prod), cnt + present.astype(jnp.float32)

        acc, cnt = lax.fori_loop(0, av.shape[0], lane,
                                 (acc_ref[...], cnt_ref[...]))
        acc_ref[...] = acc
        cnt_ref[...] = cnt

    @pl.when(k == nkb - 1)
    def _emit():
        cv_out[...] = acc_ref[...]
        ct_out[...] = (cnt_ref[...] > 0.5).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("bm", "bn", "multiply",
                                             "combine", "ident_val",
                                             "use_dot", "interpret"))
def block_window_multiply(avals, apres, bvals, bpres, *, bm: int, bn: int,
                          multiply, combine, ident_val, use_dot: bool,
                          interpret: bool = False):
    """Semiring multiply of densified planes into (bm, bn) output blocks.

    ``avals``/``apres``: (M, k) value + 0/1 f32 presence planes of A
    (M a multiple of bm); ``bvals``/``bpres``: (k, W) planes of the B
    column window (W a multiple of bn). Returns (cvals, ctouched),
    both (M, W), ctouched int32 0/1 — exactly the `_mxu_window`
    contract, blockwise. ``multiply``/``combine``/``ident_val`` must be
    cache-stable statics (bool data pre-widened to int32 carriers by
    the caller — Mosaic has no i1/i8 vector compute). ``use_dot``
    rides the MXU (plus-times only; floats under the dense_mxu
    exactness rule); otherwise k-lanes combine sequentially in
    ascending order, matching ESC's expansion-sequence combine order
    bit-exactly. Presence counts ride f32 (exact below 2^24
    products/cell — the `_mxu_window` caveat)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, k = avals.shape
    kb, W = bvals.shape
    assert k == kb, "inner dimension mismatch"
    assert M % bm == 0 and W % bn == 0, "planes must be block-padded"
    nrb, nwb = M // bm, W // bn
    nkb = max(1, -(-k // _BLOCK_KB))
    padK = nkb * _BLOCK_KB
    if padK != k:
        zpad = ((0, 0), (0, padK - k))
        avals = jnp.pad(avals, zpad)
        apres = jnp.pad(apres, zpad)
        kpad = ((0, padK - k), (0, 0))
        bvals = jnp.pad(bvals, kpad)
        bpres = jnp.pad(bpres, kpad)
    avT, apT = avals.T, apres.T             # (padK, M)

    kernel = functools.partial(_block_window_kernel, multiply=multiply,
                               combine=combine, ident_val=ident_val,
                               use_dot=use_dot, nkb=nkb)
    aspec = pl.BlockSpec((_BLOCK_KB, bm), lambda i, j, q: (q, i),
                         memory_space=pltpu.VMEM)
    bspec = pl.BlockSpec((_BLOCK_KB, bn), lambda i, j, q: (q, j),
                         memory_space=pltpu.VMEM)
    ospec = pl.BlockSpec((bm, bn), lambda i, j, q: (i, j),
                         memory_space=pltpu.VMEM)
    cv, ct = pl.pallas_call(
        kernel,
        grid=(nrb, nwb, nkb),
        in_specs=[aspec, aspec, bspec, bspec],
        out_specs=[ospec, ospec],
        out_shape=[_sds((M, W), avals.dtype, avals),
                   _sds((M, W), jnp.int32, avals)],
        scratch_shapes=[pltpu.VMEM((bm, bn), avals.dtype),
                        pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(avT, apT, bvals, bpres)
    return cv, ct
