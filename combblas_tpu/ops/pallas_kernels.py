"""Pallas TPU kernels for the hot scan paths.

The chunk-column segmented scan (ops.tile.seg_scan_core) is the inner
loop of every SpMV/BFS/reduce kernel. XLA lowers
`lax.associative_scan` over the (L, 128) layout to ~log2(L) full
passes over HBM; this Pallas kernel computes the same inclusive
segmented scan in ONE pass — each (BL, 128) row block is scanned
in VMEM (Hillis-Steele, log2(BL) VPU steps), stitched with a carry
row kept in VMEM scratch across the sequential TPU grid. HBM traffic
drops from ~log2(L)x to ~1x read + 1x write.

Safety: the kernel is OFF by default until validated on real TPU
hardware (set COMBBLAS_TPU_PALLAS=1 to enable on a TPU backend);
correctness is covered by interpret-mode tests that run everywhere.
The XLA path remains the reference implementation.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

_BL = 512                      # row-block (multiple of 32: int8 tiling)


class _BoolCombine:
    """Run a boolean monoid combine on int8 carriers (bool data rides
    VMEM as int8). Hash/eq delegate to the wrapped combine so the jit
    cache keys stay stable."""

    def __init__(self, combine):
        self.combine = combine

    def __call__(self, a, b):
        return self.combine(a != 0, b != 0).astype(jnp.int8)

    def __hash__(self):
        return hash(("_BoolCombine", self.combine))

    def __eq__(self, other):
        return (isinstance(other, _BoolCombine)
                and self.combine == other.combine)


def enabled() -> bool:
    """Use the Pallas scan? Opt-in via COMBBLAS_TPU_PALLAS=1 on a TPU
    backend (interpret-mode fallback elsewhere is slower than XLA)."""
    if os.environ.get("COMBBLAS_TPU_PALLAS", "0") != "1":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def is_batched(x) -> bool:
    """True when ``x`` is inside a vmap trace. The kernel's
    sequential-carry design (program_id(0) + one carry scratch) is not
    batch-safe — pallas_call's batching rule would add a grid dim the
    carry logic ignores — so vmapped callers (SpMM's width axis, the
    per-tile vmaps of the algebra layer) take the XLA path."""
    try:
        from jax._src.interpreters import batching  # jax 0.9: private
        return isinstance(x, batching.BatchTracer)
    except Exception:
        return True     # can't tell: stay on the safe XLA path


def _block_seg_scan(x, f, combine, ident):
    """In-VMEM Hillis-Steele inclusive segmented scan of a (BL, C)
    block along axis 0. f marks segment starts; returns (scanned x,
    or-prefix of f)."""
    bl = x.shape[0]
    shift = 1
    while shift < bl:
        # pad with the segmented-scan IDENTITY (False, ident): values
        # combine(ident, x) == x stop naturally at the block top, and
        # the flag or-prefix stays exact (a True pad would falsely mark
        # every row as flag-covered and break the carry stitch)
        pad_x = jnp.full((shift, x.shape[1]), ident, x.dtype)
        pad_f = jnp.zeros((shift, f.shape[1]), jnp.bool_)
        prev_x = jnp.concatenate([pad_x, x[:-shift]], axis=0)
        prev_f = jnp.concatenate([pad_f, f[:-shift]], axis=0)
        x = jnp.where(f, x, combine(prev_x, x))
        f = jnp.logical_or(f, prev_f)
        shift *= 2
    return x, f


def _seg_scan_kernel(d_ref, f_ref, o_ref, of_ref, carry_ref, fcarry_ref,
                     *, combine, ident_val):
    import jax.experimental.pallas as pl

    i = pl.program_id(0)
    x = d_ref[...]
    f = f_ref[...].astype(jnp.bool_)
    ident = jnp.asarray(ident_val, x.dtype)        # python scalar -> const
    xx, ff = _block_seg_scan(x, f, combine, ident)

    @pl.when(i == 0)
    def _init():
        carry_ref[...] = jnp.full_like(carry_ref, ident)
        fcarry_ref[...] = jnp.zeros_like(fcarry_ref)

    carry = carry_ref[0:1, :]                      # (1, C)
    fcarry = fcarry_ref[0:1, :] > 0
    xx = jnp.where(ff, xx, combine(carry, xx))
    fftot = jnp.logical_or(ff, fcarry)             # column or-prefix
    o_ref[...] = xx
    of_ref[...] = fftot.astype(jnp.int8)
    carry_ref[0:1, :] = xx[-1:, :]
    fcarry_ref[0:1, :] = fftot[-1:, :].astype(jnp.int8)


@functools.partial(jax.jit, static_argnames=("combine", "ident_val",
                                             "interpret"))
def seg_scan_values(d2, f2, *, combine, ident_val,
                    interpret: bool = False):
    """Inclusive segmented scan matching tile.seg_scan_core's value
    output: columns of the (L, C) layout are CONSECUTIVE sequence
    chunks, so after the per-column Pallas pass a tiny (C,)-length
    cross-column carry scan stitches chunk boundaries exactly as the
    XLA reference does. ``combine`` must be a module-level binary jnp
    op; ``ident_val`` its identity as a python scalar."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from jax import lax

    L, C = d2.shape
    nblk = -(-L // _BL)
    padL = nblk * _BL
    if padL != L:
        d2 = jnp.pad(d2, ((0, padL - L), (0, 0)),
                     constant_values=ident_val)
        f2 = jnp.pad(f2, ((0, padL - L), (0, 0)), constant_values=True)
    # Mosaic rejects bool VMEM operands: ship flags (and bool data,
    # e.g. LOR-monoid tiles) as int8; results cast back
    f2 = f2.astype(jnp.int8)
    was_bool = d2.dtype == jnp.bool_
    if was_bool:
        d2 = d2.astype(jnp.int8)
        combine = _BoolCombine(combine)
        ident_val = int(bool(ident_val))

    kernel = functools.partial(_seg_scan_kernel, combine=combine,
                               ident_val=ident_val)
    xx, ff8 = pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((_BL, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_BL, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((_BL, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_BL, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((padL, C), d2.dtype),
                   jax.ShapeDtypeStruct((padL, C), jnp.int8)],
        scratch_shapes=[pltpu.VMEM((8, C), d2.dtype),
                        pltpu.VMEM((8, C), jnp.int8)],
        interpret=interpret,
    )(d2, f2)
    xx = xx[:L]
    ff = ff8[:L] > 0
    # cross-column (chunk-boundary) stitch — the (C,)-length carry scan
    # of tile.seg_scan_core, verbatim
    ident = jnp.asarray(ident_val, xx.dtype)

    def op(a, b):
        af, ax = a
        bf, bx = b
        return af | bf, jnp.where(bf, bx, combine(ax, bx))

    cf, cx = lax.associative_scan(op, (ff[-1], xx[-1]))
    prev = jnp.concatenate([jnp.full((1,), ident, xx.dtype), cx[:-1]])
    out = jnp.where(ff, xx, combine(prev[None, :], xx))
    return (out > 0) if was_bool else out
