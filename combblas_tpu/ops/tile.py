"""Static-shape sparse tile: the local-storage layer (the "DER" concept).

Capability parity: the reference decouples distributed algorithms from
local storage through a CRTP interface (SpMat.h:55-174) with DCSC
(dcsc.h:47), CSC (csc.h:43) and COO (SpTuples.h:65) implementations, plus
local kernels mtSpGEMM.h (hash SpGEMM), SpImpl.h (SpMSpV), Friends.h
(SpMV/EWise) and MultiwayMerge.h (k-way merge).

TPU-native re-design: one canonical local format — a **padded,
(row, col)-sorted COO tile with a static capacity** — replaces the
DCSC/CSC family. Rationale:

  * XLA compiles static shapes: capacity is the compile-time bound, the
    live prefix length ``nnz`` is a traced scalar. The reference's
    "essentials-first" broadcast (GetEssentials, SpMat.h) that lets MPI
    preallocate becomes simply: every tile of a distributed matrix
    shares one capacity, so collectives are fixed-size.
  * Hypersparsity: DCSC compresses the column index so storage is
    O(nnz), not O(n). Sorted COO is already O(cap) with cap ~ nnz — and
    sortedness gives binary-searchable row pointers (`row_starts`),
    recovering CSR/DCSC-style row access vectorized.
  * All kernels are data-parallel gathers/segment-reductions/sorts —
    VPU-friendly — instead of the reference's per-column heap/hash loops.

Padding convention: entries [nnz, cap) have row == nrows and col == ncols
(one past the valid range) so they sort last and are dropped by
out-of-range scatters; values at padding are unspecified and every kernel
masks on ``arange(cap) < nnz``.

SpGEMM here is the ESC (expand-sort-compress) algorithm with a static
FLOP budget — the two-pass symbolic+numeric structure of the reference's
hash SpGEMM (mtSpGEMM.h:467, estimateNNZ_Hash :812) becomes a cheap
exact flop count (`spgemm_flops`) used as a shape oracle plus a fully
vectorized expansion.

Round-4 kernel notes (measured on a v5e chip): `lax.sort` with two i32
keys + one payload runs ~3 ns/slot; a chunked segmented scan ~1 ns; a
random gather ~9 ns; XLA scatter with monotone indices ~6 ns. The
SpGEMM pipeline is therefore built from sorts and scans with exactly
two gathers (the B-side expansion), and the A-side per-slot values are
*scan-propagated* (scatter one value per run start, copy it forward
with a segmented scan) instead of gathered.

Round-6 rework (this file + ops/pallas_kernels.py): sort cost scales
with the OPERAND count per pass, so every 2-key sort above collapses
onto ONE fused integer key — key = row*stride + (col - col_lo),
stride = width+1, with the padding sentinel kmax = (nrows+1)*stride-1
reserved so padding still sorts last (codec comment above
`fused_keys_enabled`). Each ESC sort pass now carries (key, payload)
instead of (row, col, payload) — 6 sorted operands -> 4 across the
expand sort + dedup re-sort — and rows/cols rematerialize by ONE
decode over out_cap, not the flops_cap-length expansion. The three
expansion seg_propagate scans fuse into one shared-flag multi-channel
scan (`_propagate_multi`), seeded at column tops so no cross-column
stitch remains; the same preparation feeds an optional Pallas kernel
(`pallas_kernels.fused_expand`) doing the scan + both B-side gathers
+ the semiring multiply in one VMEM pass per block. Measured by
scripts/esc_microbench.py -> ESC_MICROBENCH.json (per-slot timings +
per-variant pass accounting; tests/test_hlo_passes.py pins the pass
structure); bit-exactness of every variant is proven in
tests/test_fused_key.py + tests/test_pallas_expand.py.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from combblas_tpu.ops.semiring import Monoid, Semiring, MAX

Array = jax.Array

#: saturating add for shape-oracle prefix sums: min(a+b, 2^30-1) is
#: associative for nonnegatives below the cap, so prefixes are exact
#: below 2^30 and monotone above (those slots are dropped anyway)
SATADD = Monoid("satadd", lambda a, b: jnp.minimum(a + b, 2**30 - 1), 0)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Tile:
    """Padded sorted-COO sparse tile with static shape/capacity.

    rows/cols/vals have length ``cap`` (static); the first ``nnz``
    (traced scalar) entries are live, sorted lexicographically by
    (row, col), duplicate-free; padding has row==nrows, col==ncols.
    """

    rows: Array          # (cap,) int32
    cols: Array          # (cap,) int32
    vals: Array          # (cap,) dtype
    nnz: Array           # () int32
    nrows: int = dataclasses.field(metadata=dict(static=True))
    ncols: int = dataclasses.field(metadata=dict(static=True))

    @property
    def cap(self) -> int:
        return self.rows.shape[0]

    @property
    def dtype(self):
        return self.vals.dtype

    def valid(self) -> Array:
        return jnp.arange(self.cap, dtype=jnp.int32) < self.nnz

    def astype(self, dtype) -> "Tile":
        return dataclasses.replace(self, vals=self.vals.astype(dtype))

    def with_capacity(self, new_cap: int) -> "Tile":
        """Grow (pad) or shrink (truncate; caller must know nnz fits)."""
        if new_cap == self.cap:
            return self
        if new_cap > self.cap:
            extra = new_cap - self.cap
            return dataclasses.replace(
                self,
                rows=jnp.concatenate(
                    [self.rows, jnp.full((extra,), self.nrows, jnp.int32)]),
                cols=jnp.concatenate(
                    [self.cols, jnp.full((extra,), self.ncols, jnp.int32)]),
                vals=jnp.concatenate(
                    [self.vals, jnp.zeros((extra,), self.vals.dtype)]),
            )
        return dataclasses.replace(
            self, rows=self.rows[:new_cap], cols=self.cols[:new_cap],
            vals=self.vals[:new_cap], nnz=jnp.minimum(self.nnz, new_cap))


def empty(nrows: int, ncols: int, cap: int, dtype=jnp.float32) -> Tile:
    return Tile(
        rows=jnp.full((cap,), nrows, jnp.int32),
        cols=jnp.full((cap,), ncols, jnp.int32),
        vals=jnp.zeros((cap,), dtype),
        nnz=jnp.zeros((), jnp.int32),
        nrows=nrows, ncols=ncols)


# ---------------------------------------------------------------------------
# Construction (≅ SpTuples -> SpDCCols conversion: sort + dedup, SpTuples.h:88)
# ---------------------------------------------------------------------------

def _sortable(vals: Array) -> tuple[Array, Any]:
    """Bool values ride sorts as int8 (XLA sorts bool fine, but int8
    keeps downstream where/fill uniform); the Pallas scan boundary
    widens to int32 itself (no i1/i8 vector compute in Mosaic). The
    narrow dtype matters: the chunked builder sorts half-billion-entry
    merges, and an early int32 cast added 8 bytes/entry of footprint."""
    if vals.dtype == jnp.bool_:
        return vals.astype(jnp.int8), jnp.bool_
    return vals, None


def _unsortable(vals: Array, restore) -> Array:
    return vals.astype(restore) if restore is not None else vals


# ---------------------------------------------------------------------------
# Fused (row, col) sort keys — one comparator key instead of two
# ---------------------------------------------------------------------------
#
# lax.sort with num_keys=2 runs the comparator over BOTH key arrays at
# every compare-exchange; fusing (row, col) into one integer key halves
# the comparator bandwidth and drops one cap-sized operand from every
# sort in the ESC pipeline. Layout:
#
#     key = row * stride + (col - col_lo),  stride = width + 1
#
# with width = ncols for whole-tile sorts or the static column-window
# width for windowed SpGEMM (col_lo is the traced window base; a
# *static* width keeps the i32 path reachable for windows of huge
# matrices whose full nrows*ncols would overflow). The +1 in the
# stride reserves key space for the padding sentinel
#
#     kmax = (nrows + 1) * stride - 1
#
# which is strictly greater than every live key (live keys are at most
# (nrows-1)*stride + width = nrows*stride - 1 < kmax), so padding still
# sorts last — the Tile invariant. i32 keys require kmax <= 2^31-1;
# otherwise i64 (only when jax_enable_x64 — device x64 is disabled in
# this repo) or the 2-key reference path (fused_key_info -> None).

def fused_keys_enabled() -> bool:
    """Env opt-out: COMBBLAS_TPU_FUSED_KEY=0 forces the 2-key sorts.
    Trace-time read by design; flips require jax.clear_caches()."""
    return os.environ.get("COMBBLAS_TPU_FUSED_KEY", "") != "0"  # analysis: allow(env-in-trace)


def fused_key_info(nrows: int, ncols: int, width: Optional[int] = None):
    """(stride, key dtype) for the fused (row, col) key space of an
    (nrows, ncols)-shaped tile — or None when no integer dtype can hold
    the sentinel key (callers fall back to the 2-key sort). ``width``
    narrows the column span for window-relative keys (see module-level
    comment); it must bound ``col - col_lo`` for every live entry."""
    w = int(ncols if width is None else width)
    stride = w + 1
    kmax = (int(nrows) + 1) * stride - 1
    if kmax <= 2**31 - 1:
        return stride, jnp.int32
    if jax.config.jax_enable_x64 and kmax <= 2**63 - 1:
        return stride, jnp.int64
    return None


def encode_key(rows: Array, cols: Array, *, nrows: int, stride: int,
               dtype, col_lo=0) -> Array:
    """rows/cols -> fused sort key; any row >= nrows (the padding /
    masked-out sentinel) maps to kmax so it sorts last regardless of
    its col. ``col_lo`` may be traced (window base)."""
    kmax = (int(nrows) + 1) * int(stride) - 1
    k = (rows.astype(dtype) * jnp.asarray(stride, dtype)
         + (cols.astype(dtype) - jnp.asarray(col_lo, dtype)))
    return jnp.where(rows >= nrows, jnp.asarray(kmax, dtype), k)


def decode_key(key: Array, *, nrows: int, ncols: int, stride: int,
               col_lo=0) -> tuple[Array, Array]:
    """Fused key -> (rows, cols) int32; sentinel keys (row part >=
    nrows) decode to the canonical (nrows, ncols) padding coordinates."""
    r = (key // stride).astype(jnp.int32)
    c = (key % stride).astype(jnp.int32) + jnp.asarray(col_lo, jnp.int32)
    pad = r >= nrows
    return (jnp.where(pad, jnp.asarray(nrows, jnp.int32), r),
            jnp.where(pad, jnp.asarray(ncols, jnp.int32), c))


def _sort_compress_keyed(add: Monoid, key: Array, vals: Array, nlive: Array,
                         *, nrows: int, ncols: int, cap: int, dedup: bool,
                         stride: int, col_lo=0):
    """`sort_compress` on pre-encoded fused keys: every sort carries one
    key + one payload (num_keys=1), and rows/cols are materialized by a
    single decode at the very end — over cap, not the (often much
    larger) expansion length. Sentinel-keyed inputs must already carry
    kmax; ``nlive`` is the non-sentinel count."""
    vals, restore = _sortable(vals)
    key, vals = lax.sort((key, vals), num_keys=1)
    n = key.shape[0]
    kmax = jnp.asarray((int(nrows) + 1) * int(stride) - 1, key.dtype)
    pos = jnp.arange(n, dtype=jnp.int32)
    live = pos < nlive
    if dedup:
        same = key[1:] == key[:-1]
        starts = jnp.concatenate([jnp.ones((1,), bool), ~same])
        scanned = seg_scan_inclusive(add, vals, starts)
        is_last = jnp.concatenate([~same, jnp.ones((1,), bool)])
        nnz_full = jnp.sum(starts & live).astype(jnp.int32)
        key = jnp.where(is_last & live, key, kmax)
        key, vals = lax.sort((key, scanned), num_keys=1)
    else:
        nnz_full = nlive.astype(jnp.int32)
    if cap >= n:
        pad = cap - n
        key = jnp.concatenate([key, jnp.full((pad,), kmax, key.dtype)])
        vals = jnp.concatenate([vals, jnp.zeros((pad,), vals.dtype)])
    else:
        key, vals = key[:cap], vals[:cap]
    nnz = jnp.minimum(nnz_full, cap)
    vals = jnp.where(jnp.arange(cap, dtype=jnp.int32) < nnz, vals,
                     jnp.zeros((), vals.dtype))
    srows, scols = decode_key(key, nrows=nrows, ncols=ncols, stride=stride,
                              col_lo=col_lo)
    t = Tile(srows, scols, _unsortable(vals, restore), nnz, nrows, ncols)
    return t, nnz_full


def _sort_compress_2key(add: Monoid, srows: Array, scols: Array, vals: Array,
                        nlive: Array, *, nrows: int, ncols: int, cap: int,
                        dedup: bool = True):
    """2-key reference implementation of `sort_compress` — the pre-fused
    path, kept verbatim as the bit-exactness oracle and the fallback
    when `fused_key_info` finds no dtype for the key space."""
    vals, restore = _sortable(vals)
    srows, scols, vals = lax.sort((srows, scols, vals), num_keys=2)
    n = srows.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    live = pos < nlive
    if dedup:
        same = (srows[1:] == srows[:-1]) & (scols[1:] == scols[:-1])
        starts = jnp.concatenate([jnp.ones((1,), bool), ~same])
        scanned = seg_scan_inclusive(add, vals, starts)
        is_last = jnp.concatenate([~same, jnp.ones((1,), bool)])
        nnz_full = jnp.sum(starts & live).astype(jnp.int32)
        keep = is_last & live
        srows = jnp.where(keep, srows, nrows)
        scols = jnp.where(keep, scols, ncols)
        srows, scols, vals = lax.sort((srows, scols, scanned), num_keys=2)
    else:
        nnz_full = nlive.astype(jnp.int32)
    if cap >= n:
        pad = cap - n
        srows = jnp.concatenate([srows, jnp.full((pad,), nrows, jnp.int32)])
        scols = jnp.concatenate([scols, jnp.full((pad,), ncols, jnp.int32)])
        vals = jnp.concatenate([vals, jnp.zeros((pad,), vals.dtype)])
    else:
        srows, scols, vals = srows[:cap], scols[:cap], vals[:cap]
    nnz = jnp.minimum(nnz_full, cap)
    vals = jnp.where(jnp.arange(cap, dtype=jnp.int32) < nnz, vals,
                     jnp.zeros((), vals.dtype))
    t = Tile(srows, scols, _unsortable(vals, restore), nnz, nrows, ncols)
    return t, nnz_full


def sort_compress(add: Monoid, srows: Array, scols: Array, vals: Array,
                  nlive: Array, *, nrows: int, ncols: int, cap: int,
                  dedup: bool = True):
    """Shared COO→Tile compression: one sort (which compacts AND pads,
    because invalid entries carry the (nrows, ncols) sentinel that is
    also the padding convention), a segmented-scan dedup, and — only
    when deduping — a second sort to re-compact the surviving group
    tails. Inputs must already be sentinel-masked; ``nlive`` is the
    number of non-sentinel entries. Returns (tile, live_group_count).

    When the (nrows, ncols) key space fits an integer dtype the sorts
    run on one fused row*stride+col key (`_sort_compress_keyed`) —
    bit-exact vs the 2-key path because lax.sort is stable and the
    fused key induces the identical (row, col) lexicographic order, so
    both paths apply the identical permutation and combine duplicates
    in the identical left-to-right order.
    """
    info = fused_key_info(nrows, ncols) if fused_keys_enabled() else None
    if info is None:
        return _sort_compress_2key(add, srows, scols, vals, nlive,
                                   nrows=nrows, ncols=ncols, cap=cap,
                                   dedup=dedup)
    stride, kdt = info
    key = encode_key(srows, scols, nrows=nrows, stride=stride, dtype=kdt)
    return _sort_compress_keyed(add, key, vals, nlive, nrows=nrows,
                                ncols=ncols, cap=cap, dedup=dedup,
                                stride=stride)


@partial(jax.jit, static_argnames=("add", "nrows", "ncols", "cap", "dedup",
                                   "return_full"))
def from_coo(add: Monoid, rows: Array, cols: Array, vals: Array,
             *, nrows: int, ncols: int, cap: int,
             valid: Optional[Array] = None, dedup: bool = True,
             return_full: bool = False):
    """Build a sorted, deduplicated tile from unordered COO triples.

    Duplicates are combined with the ``add`` monoid (the reference's
    `BinOp` dedup in SpTuples.h:88). ``valid`` masks input entries;
    invalid and overflow (> cap live entries) are dropped — overflow
    drops the *largest* coordinates. With ``return_full=True`` also
    returns the pre-clamp live count so callers can *detect* overflow
    and re-plan (the realloc-on-demand semantics of SpTuples.h:88;
    see distmat.from_global_coo for the grow loop).
    """
    rows = rows.astype(jnp.int32)
    cols = cols.astype(jnp.int32)
    if valid is None:
        valid = (rows >= 0) & (rows < nrows) & (cols >= 0) & (cols < ncols)
    else:
        valid = valid & (rows >= 0) & (rows < nrows) & (cols >= 0) & (cols < ncols)
    srows = jnp.where(valid, rows, nrows)
    scols = jnp.where(valid, cols, ncols)
    nlive = jnp.sum(valid).astype(jnp.int32)
    t, nnz_full = sort_compress(add, srows, scols, vals, nlive,
                                nrows=nrows, ncols=ncols, cap=cap,
                                dedup=dedup)
    return (t, nnz_full) if return_full else t


@partial(jax.jit, static_argnames=("cap",))
def from_dense(dense: Array, zero: Array, cap: int) -> Tile:
    """Inverse of `to_dense`; entries equal to ``zero`` are implicit."""
    nrows, ncols = dense.shape
    live = dense != zero
    flat = dense.ravel()
    idx = jnp.arange(flat.shape[0], dtype=jnp.int32)
    order = jnp.argsort(~live.ravel(), stable=True)[:cap]
    sel = idx[order]
    valid = live.ravel()[order]
    rows = jnp.where(valid, sel // ncols, nrows)
    cols = jnp.where(valid, sel % ncols, ncols)
    vals = flat[order]
    nnz = jnp.minimum(jnp.sum(live), cap).astype(jnp.int32)
    # row-major flat order is already (row, col) lexicographic
    t = Tile(rows, cols, vals, nnz, int(nrows), int(ncols))
    # honor cap > nrows*ncols by padding (fixed-capacity invariant)
    return t.with_capacity(cap) if t.cap != cap else t


@jax.jit
def to_dense(t: Tile, zero: Array) -> Array:
    out = jnp.full((t.nrows, t.ncols), jnp.asarray(zero, t.dtype))
    return out.at[t.rows, t.cols].set(t.vals, mode="drop")


# ---------------------------------------------------------------------------
# Structural ops (SpMat interface: Transpose, Split/Merge — SpMat.h:61-158)
# ---------------------------------------------------------------------------

@jax.jit
def transpose(t: Tile) -> Tile:
    v = t.valid()
    rows = jnp.where(v, t.cols, t.ncols)
    cols = jnp.where(v, t.rows, t.nrows)
    vals, restore = _sortable(t.vals)
    info = fused_key_info(t.ncols, t.nrows) if fused_keys_enabled() else None
    if info is None:
        rows, cols, vals = lax.sort((rows, cols, vals), num_keys=2)
    else:
        stride, kdt = info
        key = encode_key(rows, cols, nrows=t.ncols, stride=stride, dtype=kdt)
        key, vals = lax.sort((key, vals), num_keys=1)
        rows, cols = decode_key(key, nrows=t.ncols, ncols=t.nrows,
                                stride=stride)
    return Tile(rows, cols, _unsortable(vals, restore), t.nnz,
                t.ncols, t.nrows)


def concat_merge(add: Monoid, tiles: list, cap: int, dedup: bool = True) -> Tile:
    """K-way merge of same-shape tiles (≅ MultiwayMerge.h:412): concat +
    one sort/dedup pass with the semiring add."""
    t0 = tiles[0]
    rows = jnp.concatenate([t.rows for t in tiles])
    cols = jnp.concatenate([t.cols for t in tiles])
    vals = jnp.concatenate([t.vals for t in tiles])
    valid = jnp.concatenate([t.valid() for t in tiles])
    return from_coo(add, rows, cols, vals, nrows=t0.nrows, ncols=t0.ncols,
                    cap=cap, valid=valid, dedup=dedup)


@jax.jit
def row_starts(t: Tile) -> Array:
    """CSR-style row pointer array (nrows+1,) via binary search —
    recovers DCSC/CSC column access (dcsc.h:127) on the sorted tile."""
    targets = jnp.arange(t.nrows + 1, dtype=jnp.int32)
    return jnp.searchsorted(t.rows, targets, side="left").astype(jnp.int32)


# ---------------------------------------------------------------------------
# Sorted segmented reduction without scatter (the TPU-fast local kernel)
# ---------------------------------------------------------------------------
#
# XLA lowers jax.ops.segment_* to scatter, which TPUs serialize — the
# round-1 BFS hot path spent ~all its time there. For data sorted by
# segment (our tile invariant), a segmented reduction is instead:
#   1. a chunk-column inclusive segmented scan: the sequence is split
#      into C contiguous chunks laid out as the *columns* of an (L, C)
#      array, and `lax.associative_scan` runs along axis 0 — the
#      TPU-fast major axis (minor-axis scans/rolls cross vector lanes
#      and are ~30x slower on real chips); a tiny (C,)-length carry
#      scan stitches the chunk boundaries;
#   2. one gather of each segment's last position (from row_starts).
# No scatter anywhere.

def _seg_op(monoid: Monoid):
    def op(a, b):
        af, ax = a
        bf, bx = b
        return af | bf, jnp.where(bf, bx, monoid.combine(ax, bx))
    return op


def to_chunked(x: Array, nchunks: int = 128, fill=0) -> Array:
    """Lay a 1D sequence out as an (L, C) chunk-column array: column c
    holds sequence positions c*L..(c+1)*L-1. Sequence position k lives
    at flat offset (k % L)*C + (k // L)."""
    n = x.shape[0]
    L = -(-n // nchunks)
    pad = L * nchunks - n
    if pad:
        x = jnp.concatenate([x, jnp.full((pad,), fill, x.dtype)])
    return x.reshape(nchunks, L).T


def chunked_pos(pos: Array, n: int, nchunks: int = 128) -> Array:
    """Map sequence positions to flat offsets in the to_chunked layout."""
    L = -(-n // nchunks)
    return (pos % L) * nchunks + (pos // L)


def seg_scan_core(monoid: Monoid, d2: Array, f2: Array):
    """Inclusive segmented scan over a chunk-column (L, C) layout:
    associative_scan along the TPU-fast major axis + a (C,)-length
    carry scan stitching chunk boundaries. Returns (scanned, prefix
    flags) both (L, C)."""
    ident = monoid.identity(d2.dtype)
    ff, xx = lax.associative_scan(_seg_op(monoid), (f2, d2), axis=0)
    cf, cx = lax.associative_scan(_seg_op(monoid), (ff[-1], xx[-1]))
    prev = jnp.concatenate([jnp.full((1,), ident, xx.dtype), cx[:-1]])
    xx = jnp.where(ff, xx, monoid.combine(prev[None, :], xx))
    return xx, ff


def seg_scan_values(monoid: Monoid, d2: Array, f2: Array) -> Array:
    """Values of the inclusive segmented scan over the chunk-column
    layout. Dispatches to the single-pass Pallas kernel on TPU
    backends (default on; COMBBLAS_TPU_PALLAS=0 disables —
    ops.pallas_kernels), otherwise the XLA associative-scan reference
    path."""
    from combblas_tpu.ops import pallas_kernels as pk
    if pk.enabled() and not pk.is_batched(d2):
        if d2.dtype in (jnp.bool_, jnp.int8):
            # Mosaic has no i1 vregs and int8 vector compute is
            # unreliable: widen to int32 at the kernel boundary only
            # (cached wrapper: a per-call lambda would miss the
            # compile cache on every call)
            cmb, ident = _widened_combine(monoid, d2.dtype == jnp.bool_)
            out = pk.seg_scan_values(d2.astype(jnp.int32), f2,
                                     combine=cmb, ident_val=ident)
            return out.astype(d2.dtype)
        return pk.seg_scan_values(d2, f2, combine=monoid.combine,
                                  ident_val=monoid.identity_scalar(d2.dtype))
    return seg_scan_core(monoid, d2, f2)[0]


@functools.lru_cache(maxsize=None)
def _widened_combine(monoid: Monoid, from_bool: bool):
    """int32-in/int32-out view of a bool/int8 monoid combine, for the
    Pallas scan kernel (stable identity for compile-cache hits)."""
    if from_bool:
        def cmb(a, b):
            return monoid.combine(a != 0, b != 0).astype(jnp.int32)
        ident = int(bool(monoid.identity_scalar(jnp.bool_)))
    else:
        def cmb(a, b):
            return monoid.combine(a, b).astype(jnp.int32)
        ident = monoid.identity_scalar(jnp.int32)
    return cmb, ident


def _seg_scan_2d(monoid: Monoid, data: Array, starts: Array,
                 nchunks: int):
    """Inclusive segmented scan; returns ((L, C) scanned array, L)
    where column c holds chunk c (sequence positions c*L..c*L+L-1)."""
    ident = monoid.identity(data.dtype)
    d2 = to_chunked(data, nchunks, fill=ident)
    f2 = to_chunked(starts, nchunks, fill=True)
    xx = seg_scan_values(monoid, d2, f2)
    return xx, d2.shape[0]


def seg_scan_inclusive(monoid: Monoid, data: Array, starts: Array,
                       nchunks: int = 128) -> Array:
    """Inclusive segmented scan of ``data`` (segments delimited by
    ``starts`` flags; data[i] begins a new segment iff starts[i])."""
    n = data.shape[0]
    xx, L = _seg_scan_2d(monoid, data, starts, nchunks)
    return xx.T.reshape(-1)[:n]


def seg_reduce_sorted(monoid: Monoid, data: Array, starts: Array,
                      seg_ends: Array, nonempty: Array,
                      nchunks: int = 128) -> Array:
    """Per-segment reduction of segment-sorted ``data``.

    ``seg_ends[s]`` is the index of segment s's last element
    (e.g. row_starts[s+1]-1); ``nonempty[s]`` masks segments with no
    elements (their output is the identity). Scatter-free: segmented
    scan + one gather straight out of the chunk-column layout.
    """
    n = data.shape[0]
    xx, L = _seg_scan_2d(monoid, data, starts, nchunks)
    pos = jnp.clip(seg_ends, 0, n - 1)
    out = xx.ravel()[(pos % L) * nchunks + (pos // L)]
    return jnp.where(nonempty, out, monoid.identity(data.dtype))


def seg_reduce_pre(monoid: Monoid, d2: Array, f2: Array,
                   ends_mapped: Array, nonempty: Array) -> Array:
    """seg_reduce_sorted for inputs already in the chunk-column layout
    (data and flags via `to_chunked`, positions via `chunked_pos`) —
    the zero-copy per-level path when the layout is precomputed."""
    xx = seg_scan_values(monoid, d2, f2)
    out = xx.ravel()[jnp.clip(ends_mapped, 0, xx.size - 1)]
    return jnp.where(nonempty, out, monoid.identity(d2.dtype))


def scan_inclusive(monoid: Monoid, data: Array, nchunks: int = 128) -> Array:
    """Unsegmented inclusive scan via the chunk-column layout (avoids
    jnp.cumsum / minor-axis scans, both serialized on TPU)."""
    starts = jnp.zeros(data.shape, bool).at[0].set(True)
    return seg_scan_inclusive(monoid, data, starts, nchunks)


#: copy-forward pseudo-monoid: combined with segment flags at run
#: starts, the segmented scan propagates each run-start value across
#: its run (combine keeps the accumulated = last-flagged value; the
#: _seg_op wrapper resets at flags). Associative; identity unused.
COPY_FWD = Monoid("copy_fwd", lambda a, b: a, 0)


def seg_propagate(data_at_starts: Array, starts: Array,
                  nchunks: int = 128) -> Array:
    """out[i] = data_at_starts[j] for the latest j <= i with starts[j].

    The scan-based replacement for an expansion gather `table[e[i]]`
    when e is the run id: scatter each run's value at its start slot
    (one small scatter), then copy it forward (~1 ns/slot vs ~9 ns/slot
    for the gather). Slots before the first start hold garbage — mask
    downstream. Works for any dtype/value (no monotonicity needed).
    """
    return seg_scan_inclusive(COPY_FWD, data_at_starts, starts, nchunks)


def expand_indices(counts: Array, nslots: int):
    """Run-length-decode: entry e with counts[e]>0 owns slots
    [offs[e], offs[e]+counts[e]); returns (e_of_slot, offs, total)
    where e_of_slot[s] is the owning entry (-1 before the first run).

    This is the shape-bounded expansion at the heart of ESC SpGEMM and
    frontier push. Implemented as one small scatter (len(counts)) plus
    a max-scan — NOT searchsorted, whose binary-search while-loop
    dominates the profile on TPU.
    """
    counts = jnp.minimum(counts, 2**30 - 1)
    incl = scan_inclusive(SATADD, counts)
    offs = jnp.concatenate([jnp.zeros((1,), counts.dtype), incl[:-1]])
    total = incl[-1]
    nent = counts.shape[0]
    tgt = jnp.where((counts > 0) & (offs < nslots), offs, nslots)
    marks = jnp.full((nslots + 1,), -1, jnp.int32)
    marks = marks.at[tgt].max(jnp.arange(nent, dtype=jnp.int32),
                              mode="drop")[:nslots]
    e_of_slot = scan_inclusive(MAX, marks)
    return e_of_slot, offs, total


@jax.jit
def row_structure(t: Tile):
    """Level-invariant row-segment metadata for `seg_reduce_sorted`:
    (starts flags over cap, seg_ends over nrows, nonempty over nrows).
    Compute once per matrix, reuse every SpMV/BFS level."""
    prev = jnp.concatenate([jnp.full((1,), -1, jnp.int32), t.rows[:-1]])
    starts = t.rows != prev
    rst = row_starts(t)
    seg_ends = rst[1:] - 1
    nonempty = rst[1:] > rst[:-1]
    return starts, seg_ends, nonempty


@jax.jit
def col_structure(t: Tile):
    """Column-sorted view for frontier-driven (push) traversal:
    (crows, ccols, cstarts, cdeg) where crows/ccols list the entries
    sorted by (col, row), cstarts is the CSC-style column pointer
    (ncols+1,), and cdeg the per-column degree. ≅ building the
    transpose's CSR — the reference keeps DCSC per orientation for the
    same reason."""
    v = t.valid()
    sc = jnp.where(v, t.cols, t.ncols)
    srw = jnp.where(v, t.rows, t.nrows)
    arange = jnp.arange(t.cap, dtype=jnp.int32)
    info = fused_key_info(t.ncols, t.nrows) if fused_keys_enabled() else None
    if info is None:
        ccols, crows, order = lax.sort((sc, srw, arange), num_keys=2)
    else:
        stride, kdt = info
        key = encode_key(sc, srw, nrows=t.ncols, stride=stride, dtype=kdt)
        key, order = lax.sort((key, arange), num_keys=1)
        ccols, crows = decode_key(key, nrows=t.ncols, ncols=t.nrows,
                                  stride=stride)
    cstarts = jnp.searchsorted(
        ccols, jnp.arange(t.ncols + 1, dtype=jnp.int32),
        side="left").astype(jnp.int32)
    cdeg = cstarts[1:] - cstarts[:-1]
    # order[k] = row-sorted position of col-sorted entry k: sorting a
    # payload by this key routes col-order data back to row order (the
    # permute-by-sort trick: lax.sort is ~3x faster than an nnz-sized
    # random gather on TPU)
    return crows, ccols, cstarts, cdeg, order


# ---------------------------------------------------------------------------
# SpMV / SpMSpV (≅ Friends.h:64 dcsc_gespmv, SpImpl.h kernels)
# ---------------------------------------------------------------------------

def spmv(sr: Semiring, t: Tile, x: Array) -> Array:
    """y = t ⊗ x over semiring ``sr``; x dense (ncols,), y dense (nrows,).

    Sparse vectors are represented densely with ``sr.zero()`` marking
    absent entries (the TPU-native SpMSpV: static shapes, mask instead
    of index lists — SpImpl.h's bucket/heapsort algorithms collapse into
    one gather + segment-reduce).
    """
    v = t.valid()
    xg = x[jnp.clip(t.cols, 0, t.ncols - 1)]
    contrib = sr.multiply(t.vals, xg)
    contrib = jnp.where(v, contrib, sr.add.identity(contrib.dtype))
    starts, seg_ends, nonempty = row_structure(t)
    return seg_reduce_sorted(sr.add, contrib, starts, seg_ends, nonempty)


def spmv_masked(sr: Semiring, t: Tile, x: Array, x_active: Array) -> Array:
    """SpMSpV with an explicit activity mask on x (fringe semantics).

    Inactive entries contribute the add identity — a no-op by the
    monoid law — and the reduction runs over the tile's sorted row
    segments via the scatter-free scan kernel.
    """
    y, _ = spmv_masked_hits(sr, t, x, x_active)
    return y


def spmv_masked_hits(sr: Semiring, t: Tile, x: Array,
                     x_active: Array) -> tuple[Array, Array]:
    """`spmv_masked` plus the per-row hit mask (any active in-edge),
    sharing one gather and one row-structure pass. Both reductions run
    the scatter-free segmented-scan kernel — no jax.ops.segment_* on
    this path (TPUs serialize scatter)."""
    v = t.valid()
    cg = jnp.clip(t.cols, 0, t.ncols - 1)
    act = x_active[cg] & v
    contrib = sr.multiply(t.vals, x[cg])
    contrib = jnp.where(act, contrib, sr.add.identity(contrib.dtype))
    starts, seg_ends, nonempty = row_structure(t)
    y = seg_reduce_sorted(sr.add, contrib, starts, seg_ends, nonempty)
    hits = seg_reduce_sorted(MAX, act.astype(jnp.int32), starts, seg_ends,
                             nonempty) > 0
    return y, hits


# ---------------------------------------------------------------------------
# SpGEMM (≅ mtSpGEMM.h LocalSpGEMMHash :467) — ESC2: sort/scan pipeline
# with a static FLOP budget. The symbolic/numeric two-pass of the
# reference's hash kernel maps to: exact flop count (shape oracle) +
# scan-propagated expansion + sort-compress. Only two gathers total
# (B-side cols/vals); A-side values ride segmented copy-forward scans.
# ---------------------------------------------------------------------------

@jax.jit
def spgemm_flops_per_entry(a: Tile, b: Tile) -> Array:
    """Per-a-entry multiply count of a·b (int32 vector, each < b.nnz)."""
    bptr = row_starts(b)
    acol = jnp.clip(a.cols, 0, a.ncols - 1)
    return (bptr[acol + 1] - bptr[acol]) * a.valid()


def spgemm_flops(a: Tile, b: Tile) -> int:
    """Exact multiply count of a·b (the symbolic pass / shape oracle;
    ≅ estimateNNZ_Hash mtSpGEMM.h:812 but exact and O(nnz log n)).

    Host-side planning call: sums in int64 on the host (in-graph int32
    accumulation would overflow past 2^31 flops at scale-22 workloads).
    """
    import numpy as np
    return int(np.asarray(spgemm_flops_per_entry(a, b), dtype=np.int64).sum())


def _flops_cap_guard(flops_cap: int):
    if flops_cap > 2**30 - 1:
        raise ValueError(
            f"flops_cap {flops_cap} > 2^30-1: expansion indices saturate — "
            "bound the per-call flop budget by splitting the multiply into "
            "phases (parallel.spgemm.spgemm_phased)")


def _esc2_expand(sr: Semiring, a: Tile, per: Array, base: Array, b: Tile,
                 flops_cap: int):
    """REFERENCE expansion: materialize the product expansion without
    per-slot A-side gathers, in sequence layout, via three separate
    copy-forward scans. This is the pre-fused bit-exactness oracle (and
    the fallback when `fused_key_info` finds no key dtype); the
    production path is `_expand_prep` + `_expand_finish_xla` / the
    Pallas `fused_expand` kernel, which compute the same values.

    ``per[e]``/``base[e]``: product count and B-array start index for A
    entry e. Each A entry owns a contiguous run of slots; its row,
    value, and B offset are scattered once at the run start and
    copy-forward-scanned across the run, so the only expansion-sized
    gathers are B's cols/vals at ``bidx = (base-offs) + slot``.
    Returns (crow, ccol, cval, total); slots >= total carry garbage.
    """
    incl = scan_inclusive(SATADD, per)
    offs = incl - per                      # exclusive prefix
    total = incl[-1]
    live_e = (per > 0) & (offs < flops_cap)
    tgt = jnp.where(live_e, offs, flops_cap)

    def scat(x):
        return jnp.zeros((flops_cap + 1,), x.dtype).at[tgt].set(
            x, mode="drop")[:flops_cap]

    starts = scat(jnp.ones(per.shape, jnp.int32)) > 0
    crow = seg_propagate(scat(a.rows), starts)
    delta = seg_propagate(scat(base - offs), starts)
    avals, restore = _sortable(a.vals)
    aval = _unsortable(seg_propagate(scat(avals), starts), restore)
    slots = jnp.arange(flops_cap, dtype=jnp.int32)
    bidx = jnp.clip(delta + slots, 0, b.cap - 1)
    ccol = b.cols[bidx]
    cval = sr.multiply(aval, b.vals[bidx])
    return crow, ccol, cval, total


def _expand_prep(a: Tile, per: Array, base: Array, flops_cap: int,
                 nchunks: int = 128):
    """Fused-expansion front end: scatter the per-A-entry run-start
    channels (row, B-offset delta, A value, start flag) STRAIGHT into
    the chunk-column (L, C) scan layout — one scatter per channel, no
    `to_chunked` transposes — and seed every live column's top row.

    Column-top seeding is what makes the downstream scan single-pass:
    sequence position c*L (the top of chunk-column c) is owned by the A
    entry whose run covers it (`searchsorted` on the inclusive flop
    prefix); scattering that entry's channel values at flat offset c
    with a set start flag makes every column's copy-forward scan
    self-contained, so NO cross-column carry stitch is needed — the
    property the Pallas kernel relies on to finish in one VMEM pass.
    When a real run start coincides with a column top the duplicate
    scatter writes provably equal values (the owner IS that entry), so
    XLA's nondeterministic duplicate order is harmless.

    Returns (rowv2, deltav2, avalv2, f2, total, L, restore) with the
    (L, C) channel arrays, avalv2 in `_sortable` carrier form.
    """
    C = nchunks
    L = -(-flops_cap // C)
    incl = scan_inclusive(SATADD, per)
    offs = incl - per                      # exclusive prefix
    total = incl[-1]
    live_e = (per > 0) & (offs < flops_cap)
    tgt = jnp.where(live_e, chunked_pos(offs, flops_cap, C), L * C)
    tops = jnp.arange(C, dtype=jnp.int32) * L      # column-top seq pos
    own = jnp.clip(jnp.searchsorted(incl, tops, side="right"),
                   0, per.shape[0] - 1).astype(jnp.int32)
    ttgt = jnp.where(tops < jnp.minimum(total, flops_cap),
                     jnp.arange(C, dtype=jnp.int32), L * C)
    cat = jnp.concatenate([tgt, ttgt])

    def scat(x):
        src = jnp.concatenate([x, x[own]])
        return jnp.zeros((L * C + 1,), x.dtype).at[cat].set(
            src, mode="drop")[:L * C].reshape(L, C)

    f2 = jnp.zeros((L * C + 1,), jnp.bool_).at[cat].set(
        True, mode="drop")[:L * C].reshape(L, C)
    avals, restore = _sortable(a.vals)
    return (scat(a.rows), scat(base - offs), scat(avals), f2, total, L,
            restore)


def _propagate_multi(f2: Array, chans):
    """One inclusive copy-forward scan over several channels sharing a
    single start-flag array — replaces N independent `seg_propagate`
    calls (each re-scanning the same flags) with one associative scan.
    Columns must be self-contained (see `_expand_prep` seeding): no
    cross-column stitch is applied."""
    def op(a, b):
        return (a[0] | b[0],) + tuple(
            jnp.where(b[0], bx, ax) for ax, bx in zip(a[1:], b[1:]))
    out = lax.associative_scan(op, (f2,) + tuple(chans), axis=0)
    return out[1:]


def _expand_finish_xla(sr: Semiring, b: Tile, rowv2: Array, deltav2: Array,
                       avalv2: Array, f2: Array, restore, total: Array,
                       L: int, flops_cap: int, nrows: int, stride: int,
                       kdt, col_lo) -> tuple[Array, Array]:
    """XLA back end of the fused expansion: one shared-flag multi-channel
    scan, the two B-side gathers, the semiring multiply, and the fused
    sort-key encode — emitted straight from the chunk-column layout.
    Returns (key, cval) in sequence order, length flops_cap."""
    C = f2.shape[1]
    rowp, deltap, avalp = _propagate_multi(f2, (rowv2, deltav2, avalv2))
    l = jnp.arange(L, dtype=jnp.int32)[:, None]
    c = jnp.arange(C, dtype=jnp.int32)[None, :]
    slot = c * L + l                       # sequence position of (l, c)
    bidx = jnp.clip(deltap + slot, 0, b.cap - 1)
    bcol = b.cols[bidx]
    cval = sr.multiply(_unsortable(avalp, restore), b.vals[bidx])
    live = (slot < total) & (slot < flops_cap)
    kmax = jnp.asarray((int(nrows) + 1) * int(stride) - 1, kdt)
    key = jnp.where(live,
                    rowp.astype(kdt) * jnp.asarray(stride, kdt)
                    + (bcol.astype(kdt) - jnp.asarray(col_lo, kdt)),
                    kmax)
    return key.T.reshape(-1)[:flops_cap], cval.T.reshape(-1)[:flops_cap]


@functools.lru_cache(maxsize=None)
def _widened_multiply(multiply, a_bool: bool, b_bool: bool):
    """int32-in/int32-out view of a semiring multiply whose operands
    ride int32 vregs in the Pallas expansion kernel (Mosaic has no
    i1/i8 vector compute). Cached so the jitted kernel's static
    ``multiply`` argument stays identical across calls."""
    if not (a_bool or b_bool):
        return multiply

    def mult(av, bv):
        out = multiply(av != 0 if a_bool else av,
                       bv != 0 if b_bool else bv)
        if out.dtype in (jnp.bool_, jnp.int8):
            out = out.astype(jnp.int32)
        return out
    return mult


def _expand_keyed(sr: Semiring, a: Tile, b: Tile, per: Array, base: Array,
                  flops_cap: int, *, stride: int, kdt, clo):
    """Fused-key expansion front half shared by the ESC tail and the
    dense/hash accumulator variants: (key, cval, total) in sequence
    order, length flops_cap, dead slots keyed kmax. Chooses the Pallas
    fused-expansion kernel exactly as the ESC path does."""
    rowv2, deltav2, avalv2, f2, total, L, restore = _expand_prep(
        a, per, base, flops_cap)
    from combblas_tpu.ops import pallas_kernels as pk
    if (pk.expand_enabled() and kdt == jnp.int32
            and not pk.is_batched(per) and b.cap <= pk.EXPAND_BMAX):
        a_bool = avalv2.dtype in (jnp.bool_, jnp.int8) and restore is not None
        b_bool = b.dtype == jnp.bool_
        widen_a = avalv2.dtype in (jnp.bool_, jnp.int8)
        widen_b = b.dtype in (jnp.bool_, jnp.int8)
        out_dtype = jax.eval_shape(
            sr.multiply,
            jax.ShapeDtypeStruct((), restore if restore is not None
                                 else avalv2.dtype),
            jax.ShapeDtypeStruct((), b.dtype)).dtype
        key, cval = pk.fused_expand(
            rowv2, deltav2,
            avalv2.astype(jnp.int32) if widen_a else avalv2,
            f2, b.cols,
            b.vals.astype(jnp.int32) if widen_b else b.vals,
            clo, total,
            multiply=_widened_multiply(sr.multiply, a_bool, b_bool),
            stride=stride, nrows=a.nrows, L=L, flops_cap=flops_cap,
            interpret=pk.expand_interpret())
        if cval.dtype != out_dtype:
            cval = cval.astype(out_dtype)
    else:
        key, cval = _expand_finish_xla(sr, b, rowv2, deltav2, avalv2, f2,
                                       restore, total, L, flops_cap,
                                       a.nrows, stride, kdt, clo)
    return key, cval, total


def _esc2_finish(sr: Semiring, a: Tile, b: Tile, per: Array, base: Array,
                 flops_cap: int, out_cap: int, dedup: bool, *,
                 col_lo=None, key_width: Optional[int] = None) -> Tile:
    """Expansion + compression tail shared by every SpGEMM entry point.

    ``key_width``/``col_lo`` select the window-relative fused-key codec
    (static width, traced base — spgemm_colwindow): keys are encoded as
    row*(width+1) + (col - col_lo), which keeps the i32 single-key path
    reachable for column windows of matrices whose full nrows*ncols
    exceeds 2^31. Without them the whole-tile codec is used. When no
    key dtype fits (`fused_key_info` -> None) or COMBBLAS_TPU_FUSED_KEY=0,
    the pre-fused reference pipeline runs instead.
    """
    width = b.ncols if key_width is None else key_width
    info = (fused_key_info(a.nrows, b.ncols, width=width)
            if fused_keys_enabled() else None)
    if info is None:
        crow, ccol, cval, total = _esc2_expand(sr, a, per, base, b,
                                               flops_cap)
        live = jnp.arange(flops_cap, dtype=jnp.int32) < total
        crow = jnp.where(live, crow, a.nrows)
        ccol = jnp.where(live, ccol, b.ncols)
        t, _ = _sort_compress_2key(sr.add, crow, ccol, cval,
                                   jnp.minimum(total, flops_cap),
                                   nrows=a.nrows, ncols=b.ncols,
                                   cap=out_cap, dedup=dedup)
        return t
    stride, kdt = info
    clo = jnp.zeros((), jnp.int32) if col_lo is None else col_lo
    key, cval, total = _expand_keyed(sr, a, b, per, base, flops_cap,
                                     stride=stride, kdt=kdt, clo=clo)
    t, _ = _sort_compress_keyed(sr.add, key, cval,
                                jnp.minimum(total, flops_cap),
                                nrows=a.nrows, ncols=b.ncols, cap=out_cap,
                                dedup=dedup, stride=stride, col_lo=clo)
    return t


def spgemm_ranged(sr: Semiring, a: Tile, b: Tile, *, a_lo: int, b_lo: int,
                  length: int, flops_cap: int, out_cap: int,
                  dedup: bool = True) -> Tile:
    """c = A[:, a_lo:a_lo+length] ⊗ B[b_lo:b_lo+length, :] — the ESC2
    multiply restricted to an inner-dimension window, without
    compacting either operand (entries outside the window are masked).

    This is the local body of streaming SUMMA on arbitrary grids
    (parallel.spgemm): a stage's inner interval spans [a_lo, a_lo+length)
    of A's local columns and [b_lo, b_lo+length) of B's local rows.
    Padding entries (row == nrows) sort past every window, so the
    searchsorted row pointers need no validity fixup.
    """
    _flops_cap_guard(flops_cap)
    targets = jnp.arange(length + 1, dtype=jnp.int32) + jnp.asarray(
        b_lo, jnp.int32)
    bptr = jnp.searchsorted(b.rows, targets, side="left").astype(jnp.int32)
    p = a.cols - jnp.asarray(a_lo, jnp.int32)      # inner window position
    in_range = a.valid() & (p >= 0) & (p < length)
    pcl = jnp.clip(p, 0, length - 1)
    per = jnp.where(in_range, bptr[pcl + 1] - bptr[pcl], 0)
    base = bptr[pcl]
    return _esc2_finish(sr, a, b, per, base, flops_cap, out_cap, dedup)


@partial(jax.jit, static_argnames=("sr", "flops_cap", "out_cap", "dedup"))
def spgemm(sr: Semiring, a: Tile, b: Tile, *, flops_cap: int, out_cap: int,
           dedup: bool = True) -> Tile:
    """c = a ⊗ b over ``sr`` (expand-scan-sort-compress, vectorized).

    ``flops_cap`` bounds the expansion (#scalar multiplies); products
    beyond it are dropped — size it with `spgemm_flops`. ``out_cap`` is
    the capacity of the result tile.
    """
    assert a.ncols == b.nrows, "inner dimension mismatch (DIMMISMATCH)"
    _flops_cap_guard(flops_cap)
    bptr = row_starts(b)
    acol = jnp.clip(a.cols, 0, a.ncols - 1)
    per = jnp.where(a.valid(), bptr[acol + 1] - bptr[acol], 0)
    base = bptr[acol]
    return _esc2_finish(sr, a, b, per, base, flops_cap, out_cap, dedup)


@partial(jax.jit, static_argnames=("sr", "eblk", "flops_cap", "out_cap",
                                   "dedup"))
def spgemm_rowblock(sr: Semiring, a: Tile, b: Tile, bptr: Array, elo: Array,
                    ehi: Array, *, eblk: int, flops_cap: int, out_cap: int,
                    dedup: bool = True) -> Tile:
    """c-rows block: A's entry range [elo, ehi) ⊗ b, with ``bptr`` =
    row_starts(b) HOISTED out of the loop (window-independent).
    ``eblk`` is the static slice width (>= ehi-elo for every block in
    a plan, so all blocks share one compiled kernel); entries in
    [ehi, elo+eblk) are masked out — without the ``ehi`` bound a
    bucketed eblk would over-read into the next block and double-count
    its products.

    The streaming dual of `spgemm_colwindow`: C is produced in
    row-aligned A-entry blocks instead of column windows. Per-block
    cost is O(eblk + flops_cap) — no O(A.cap)/O(B.cap) term — where
    the column-window kernel recomputes per-row window counts over ALL
    of B and gathers counts for ALL of A per call: at scale 22 that
    O(windows x cap) overhead alone is ~500B ops (measured ~20
    s/window; see PARITY.md "Scale-22 A*A: measured status").

    Caller contract (scripts/spgemm_stream.py rows mode plans this):
    cuts must lie on ROW boundaries of A (a C row's products then live
    in exactly one block, so per-block dedup is globally exact and
    block nnz sums to C's nnz), and A's capacity must be >=
    max(elo) + eblk so the dynamic_slice never clamps.
    """
    assert a.ncols == b.nrows, "inner dimension mismatch (DIMMISMATCH)"
    assert bptr.shape == (b.nrows + 1,), (
        f"bptr shape {bptr.shape} != (b.nrows+1,) = {(b.nrows + 1,)}: "
        "pass row_starts(b) for THIS b")
    _flops_cap_guard(flops_cap)
    elo = jnp.asarray(elo, jnp.int32)
    ehi = jnp.asarray(ehi, jnp.int32)
    ar = lax.dynamic_slice(a.rows, (elo,), (eblk,))
    ac = lax.dynamic_slice(a.cols, (elo,), (eblk,))
    av = lax.dynamic_slice(a.vals, (elo,), (eblk,))
    idx = jnp.arange(eblk, dtype=jnp.int32) + elo
    valid = (idx < a.nnz) & (idx < ehi)
    blk = Tile(jnp.where(valid, ar, a.nrows),
               jnp.where(valid, ac, a.ncols), av,
               jnp.sum(valid).astype(jnp.int32), a.nrows, a.ncols)
    acol = jnp.clip(blk.cols, 0, a.ncols - 1)
    per = jnp.where(valid, bptr[acol + 1] - bptr[acol], 0)
    base = bptr[acol]
    return _esc2_finish(sr, blk, b, per, base, flops_cap, out_cap, dedup)


def _window_counts(a: Tile, b: Tile, clo: Array, chi: Array, b_struct=None):
    """Per-A-entry product count and B start offset for the column
    window [clo, chi) — the shared front half of every column-window
    local kernel (ESC, dense, hash). Within each B row the window's
    entries are contiguous (the tile is (row, col)-sorted), so counts
    and starts come from two segmented reductions over B; ``b_struct``
    = row_structure(b) + (row_starts(b),) hoists the window-independent
    metadata out of the per-window call."""
    from combblas_tpu.ops.semiring import PLUS
    v = b.valid()
    inwin = (v & (b.cols >= clo) & (b.cols < chi)).astype(jnp.int32)
    before = (v & (b.cols < clo)).astype(jnp.int32)
    if b_struct is None:
        starts_b, seg_ends, nonempty = row_structure(b)
        bptr = row_starts(b)
    else:
        starts_b, seg_ends, nonempty, bptr = b_struct
    cnt_w = seg_reduce_sorted(PLUS, inwin, starts_b, seg_ends, nonempty)
    n_before = seg_reduce_sorted(PLUS, before, starts_b, seg_ends, nonempty)
    bstart_w = bptr[:-1] + n_before
    acol = jnp.clip(a.cols, 0, a.ncols - 1)
    per = jnp.where(a.valid(), cnt_w[acol], 0)
    base = bstart_w[acol]
    return per, base


@partial(jax.jit, static_argnames=("sr", "flops_cap", "out_cap", "dedup",
                                   "win_width"))
def spgemm_colwindow(sr: Semiring, a: Tile, b: Tile, clo: Array, chi: Array,
                     *, flops_cap: int, out_cap: int, dedup: bool = True,
                     win_width: Optional[int] = None,
                     b_struct=None) -> Tile:
    """c = a ⊗ B[:, clo:chi) with *dynamic* (traced) column bounds —
    the local body of single-tile phased SpGEMM (≅ MemEfficientSpGEMM's
    ColSplit windows, ParFriends.h:555), without materializing the B
    window: within each B row the window's entries are contiguous (the
    tile is (row, col)-sorted), so per-row window counts and start
    offsets come from two segmented reductions over B. Because clo/chi
    are traced, every phase with the same cap buckets reuses ONE
    compiled kernel. Output columns keep their global indices.

    ``win_width`` (static, >= chi-clo for every window in a plan)
    switches the ESC tail onto the window-relative fused-key codec —
    i32 single-key sorts even when nrows*ncols overflows 2^31 (the MCL
    hot loop's case). ``b_struct`` = (row_structure(b) + (row_starts(b),))
    hoists the window-independent B metadata out of the per-window call
    (it was recomputed from all of B every window otherwise).
    """
    assert a.ncols == b.nrows, "inner dimension mismatch (DIMMISMATCH)"
    _flops_cap_guard(flops_cap)
    per, base = _window_counts(a, b, clo, chi, b_struct)
    return _esc2_finish(sr, a, b, per, base, flops_cap, out_cap, dedup,
                        col_lo=clo if win_width is not None else None,
                        key_width=win_width)


# ---------------------------------------------------------------------------
# Density-adaptive local-kernel variants: sort-free window accumulators
# ---------------------------------------------------------------------------
#
# ESC pays O(flops * log flops) sort comparisons per window regardless
# of how compressible the expansion is. When a window's output density
# flops / (nrows * win_width) is high (MCL's expansion intermediates),
# a dense (nrows, win_width) accumulator costs O(flops) scatter + one
# O(nrows * win_width) sort-free compaction — no sorts, no segmented
# scans over the expansion (the mtSpGEMM.h accumulator-family idea,
# arxiv/1006.2183, TPU-shaped). `spgemm_colwindow_dense` is the monoid
# scatter variant with an MXU sub-variant (`mxu=True`) that turns
# plus-times windows into one real dot_general; `spgemm_colwindow_hash`
# is the mid-density linear-probing hash accumulator (Pallas kernel in
# ops/pallas_kernels.py, XLA segment fallback otherwise). All variants
# are bit-exact vs the ESC reference: they combine duplicates in the
# same expansion-sequence order, keep ESC's explicit-zero structure via
# a separate touched mask, and drop overflow in the same largest-
# (row, col) order (compaction positions are key-ordered).

#: monoid kinds the dense/hash accumulators can scatter/segment on;
#: user monoids (kind=None) stay on the ESC reference path
ACCUM_KINDS = ("add", "min", "max", "or", "and")


def _monoid_scatter(kind: str, buf: Array, fi: Array, vals: Array) -> Array:
    """One monoid-combining scatter into a flat accumulator; ``fi`` out
    of range drops (the dead-slot convention)."""
    upd = buf.at[fi]
    if kind == "add":
        return upd.add(vals, mode="drop")
    if kind == "min":
        return upd.min(vals, mode="drop")
    if kind == "max":
        return upd.max(vals, mode="drop")
    raise AssertionError(f"no scatter op for monoid kind {kind!r}")


def _dense_compact(vals_flat: Array, touched_flat: Array, *, stride: int,
                   clo, out_cap: int, nrows: int, ncols: int):
    """Sort-free compaction of a flat dense window accumulator into a
    sorted Tile: the flat row-major index IS the (row, col) lex order,
    so live-entry output positions are an unsegmented prefix scan (the
    chunk-column layout — zero sorts) and the gather-out is one
    monotone scatter. Overflow past ``out_cap`` drops the largest flat
    indices = the largest (row, col) coordinates, identical to ESC's
    sort-then-truncate order. Returns (tile, pre-clamp live count)."""
    live = touched_flat > 0
    incl = scan_inclusive(SATADD, live.astype(jnp.int32))
    nnz_full = incl[-1]
    pos = incl - 1                         # target slot of live entries
    tgt = jnp.where(live & (pos < out_cap), pos, out_cap)
    n = live.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    rows = jnp.full((out_cap + 1,), nrows, jnp.int32).at[tgt].set(
        idx // stride, mode="drop")[:out_cap]
    cols = jnp.full((out_cap + 1,), ncols, jnp.int32).at[tgt].set(
        jnp.asarray(clo, jnp.int32) + idx % stride, mode="drop")[:out_cap]
    vals = jnp.zeros((out_cap + 1,), vals_flat.dtype).at[tgt].set(
        vals_flat, mode="drop")[:out_cap]
    nnz = jnp.minimum(nnz_full, out_cap)
    vals = jnp.where(jnp.arange(out_cap, dtype=jnp.int32) < nnz, vals,
                     jnp.zeros((), vals.dtype))
    return Tile(rows, cols, vals, nnz, nrows, ncols), nnz_full


def mxu_eligible(sr: Semiring, a_dtype, b_dtype) -> bool:
    """True when a window's semiring lowers to a real matmul: plus-times
    over non-bool operands (the `dense_matmul` detection predicate)."""
    return (sr.add.kind == "add"
            and sr.multiply in (lax.mul, jnp.multiply)
            and jnp.dtype(a_dtype) != jnp.bool_
            and jnp.dtype(b_dtype) != jnp.bool_)


def densify_operand(a: Tile, dtype=None):
    """(values, presence) dense (nrows, ncols) renders of a tile for the
    MXU window variant. Window-independent: phased loops hoist ONE call
    and reuse it for every dense_mxu window. Presence is a separate 0/1
    f32 plane because the value render cannot distinguish a stored
    explicit zero from an absent entry — and ESC keeps stored zeros."""
    n = a.nrows * a.ncols
    fi = jnp.where(a.valid(), a.rows * a.ncols + a.cols, n)
    dt = a.dtype if dtype is None else dtype
    vals = jnp.zeros((n,), dt).at[fi].set(
        a.vals.astype(dt), mode="drop").reshape(a.nrows, a.ncols)
    pres = jnp.zeros((n,), jnp.float32).at[fi].set(
        1.0, mode="drop").reshape(a.nrows, a.ncols)
    return vals, pres


def _mxu_window(sr: Semiring, a: Tile, b: Tile, clo, chi, win_width: int,
                a_dense, out_dtype):
    """Dense MXU sub-variant body: densify the B window (A is hoistable),
    one real value matmul + one presence matmul (structure: which cells
    any product touched, counts exact in f32 below 2^24 products/cell).
    Requires the caller to have sized flops_cap >= the window's flops
    (the planner guarantees it): a matmul cannot replay ESC's expansion
    truncation."""
    k = a.ncols
    if a_dense is None:
        a_dense = densify_operand(a, dtype=out_dtype)
    avals, apres = a_dense
    wcol = b.cols - clo
    bok = b.valid() & (wcol >= 0) & (wcol < jnp.minimum(chi - clo, win_width))
    fib = jnp.where(bok, b.rows * win_width + wcol, k * win_width)
    bvals = jnp.zeros((k * win_width,), avals.dtype).at[fib].set(
        b.vals.astype(avals.dtype), mode="drop").reshape(k, win_width)
    bpres = jnp.zeros((k * win_width,), jnp.float32).at[fib].set(
        1.0, mode="drop").reshape(k, win_width)
    dense = jnp.matmul(avals, bvals,
                       precision=lax.Precision.HIGHEST).astype(out_dtype)
    cnt = jnp.matmul(apres, bpres, precision=lax.Precision.HIGHEST)
    return dense.reshape(-1), (cnt > 0.5).astype(jnp.int32).reshape(-1)


@partial(jax.jit, static_argnames=("sr", "flops_cap", "out_cap",
                                   "win_width", "mxu"))
def spgemm_colwindow_dense(sr: Semiring, a: Tile, b: Tile, clo: Array,
                          chi: Array, *, flops_cap: int, out_cap: int,
                          win_width: int, b_struct=None, mxu: bool = False,
                          a_dense=None) -> Tile:
    """`spgemm_colwindow` on a dense (nrows, win_width) accumulator —
    ZERO sorts, zero segmented scans over the expansion (the analysis
    budget `esc.dense_window` pins both). The expansion's fused keys
    decode straight to buffer coordinates; duplicates combine via one
    monoid scatter in expansion-sequence order (XLA applies scatter
    updates in operand order, matching ESC's stable-sort combine
    order), a separate touched mask preserves ESC's explicit-zero
    structure, and the tail is the sort-free `_dense_compact`.

    ``mxu=True`` (plus-times only, `mxu_eligible`) swaps the scatter
    for one real matmul over densified operands; ``a_dense`` =
    `densify_operand(a, dtype=<product dtype>)` hoists the window-
    independent A render. Floating-point note: the matmul reassociates
    the += reduction, so dense_mxu is bit-exact vs ESC only for
    exactly-representable sums (integers, small-int-valued floats);
    the scatter variant (`mxu=False`) is bit-exact always.
    """
    assert a.ncols == b.nrows, "inner dimension mismatch (DIMMISMATCH)"
    _flops_cap_guard(flops_cap)
    kind = sr.add.kind
    if kind not in ACCUM_KINDS:
        raise ValueError(
            f"dense window accumulator needs a known monoid kind "
            f"(one of {ACCUM_KINDS}), got {sr.add.name!r} with "
            f"kind={kind!r}; route user monoids to the ESC path")
    nrows = a.nrows
    out_dtype = jax.eval_shape(
        sr.multiply, jax.ShapeDtypeStruct((), a.dtype),
        jax.ShapeDtypeStruct((), b.dtype)).dtype
    if mxu:
        if not mxu_eligible(sr, a.dtype, b.dtype):
            raise ValueError(
                f"mxu=True needs a plus-times semiring over non-bool "
                f"operands, got {sr.name!r} ({a.dtype} x {b.dtype})")
        dense, touched = _mxu_window(sr, a, b, clo, chi, win_width,
                                     a_dense, out_dtype)
    else:
        info = (fused_key_info(nrows, b.ncols, width=win_width)
                if fused_keys_enabled() else None)
        if info is None:
            raise ValueError(
                f"dense window accumulator needs the window-relative "
                f"fused-key codec (nrows={nrows}, win_width={win_width} "
                f"found no key dtype, or COMBBLAS_TPU_FUSED_KEY=0); "
                f"route to the ESC path")
        stride, kdt = info
        per, base = _window_counts(a, b, clo, chi, b_struct)
        key, cval, total = _expand_keyed(sr, a, b, per, base, flops_cap,
                                         stride=stride, kdt=kdt, clo=clo)
        n = nrows * win_width
        r = (key // stride).astype(jnp.int32)
        w = (key % stride).astype(jnp.int32)
        # dead slots carry kmax -> (nrows, win_width): out of range, drop
        fi = jnp.where((r < nrows) & (w < win_width),
                       r * win_width + w, n)
        if kind in ("or", "and"):
            if out_dtype != jnp.bool_:
                raise ValueError(
                    f"or/and dense accumulation expects bool products, "
                    f"got {out_dtype}")
            # bool rides an int32 carrier: or == max, and == min over 0/1
            ident = int(bool(sr.add.identity_scalar(jnp.bool_)))
            dense = jnp.full((n,), ident, jnp.int32)
            dense = _monoid_scatter("max" if kind == "or" else "min",
                                    dense, fi, cval.astype(jnp.int32))
            dense = dense > 0
        else:
            dense = jnp.full((n,), sr.add.identity(out_dtype), out_dtype)
            dense = _monoid_scatter(kind, dense, fi, cval)
        touched = jnp.zeros((n,), jnp.int32).at[fi].max(
            jnp.ones((flops_cap,), jnp.int32), mode="drop")
    t, _ = _dense_compact(dense, touched, stride=win_width, clo=clo,
                          out_cap=out_cap, nrows=nrows, ncols=b.ncols)
    return t


@partial(jax.jit, static_argnames=("sr", "flops_cap", "out_cap",
                                   "win_width", "pallas_mode"))
def _spgemm_colwindow_hash_impl(sr: Semiring, a: Tile, b: Tile, clo: Array,
                                chi: Array, *, flops_cap: int, out_cap: int,
                                win_width: int, b_struct=None,
                                pallas_mode: str = "off") -> Tile:
    """`spgemm_colwindow` on a linear-probing hash accumulator keyed on
    the fused window-relative integer key — the mtSpGEMM hybrid's
    mid-density regime. With the Pallas kernel enabled
    (COMBBLAS_TPU_PALLAS_HASH=1, or =interpret for CPU tests) the
    expansion streams through a VMEM table (monoid combine on key
    collision, kmax-sentinel empty slots) and the only sort left is the
    table_cap-sized output compaction — |C| log |C|, not
    |expansion| log |expansion|. When Pallas is off, an XLA
    segment-reduce over the dense key space computes the identical
    result (update order == expansion order on both paths, so
    bit-exactness vs ESC holds) with the sort-free dense compaction.

    Overflow contract: the Pallas table drops late INSERTIONS when the
    distinct-key count exceeds table_cap (bounded probing) — callers
    must size out_cap >= the true output nnz (the planner does); the
    XLA fallback replays ESC's exact largest-coordinate drop order.
    """
    assert a.ncols == b.nrows, "inner dimension mismatch (DIMMISMATCH)"
    _flops_cap_guard(flops_cap)
    kind = sr.add.kind
    if kind not in ACCUM_KINDS:
        raise ValueError(
            f"hash window accumulator needs a known monoid kind "
            f"(one of {ACCUM_KINDS}), got {sr.add.name!r} with "
            f"kind={kind!r}; route user monoids to the ESC path")
    nrows = a.nrows
    info = (fused_key_info(nrows, b.ncols, width=win_width)
            if fused_keys_enabled() else None)
    if info is None or info[1] != jnp.int32:
        raise ValueError(
            f"hash window accumulator needs the i32 window-relative "
            f"key codec (nrows={nrows}, win_width={win_width}); "
            f"route to the ESC path")
    stride, kdt = info
    per, base = _window_counts(a, b, clo, chi, b_struct)
    key, cval, total = _expand_keyed(sr, a, b, per, base, flops_cap,
                                     stride=stride, kdt=kdt, clo=clo)
    kmax = (nrows + 1) * stride - 1
    from combblas_tpu.ops import pallas_kernels as pk
    table_cap = pk.hash_table_cap(out_cap)
    if (pallas_mode != "off" and not pk.is_batched(per)
            and table_cap <= pk.HASH_TMAX):
        widen = cval.dtype in (jnp.bool_, jnp.int8)
        if widen:
            cmb, ident = _widened_combine(sr.add, cval.dtype == jnp.bool_)
        else:
            cmb, ident = sr.add.combine, sr.add.identity_scalar(cval.dtype)
        tk, tv = pk.hash_accumulate(
            key, cval.astype(jnp.int32) if widen else cval,
            table_cap=table_cap, combine=cmb, ident_val=ident,
            kmax=kmax, interpret=pallas_mode == "interpret")
        if widen:
            tv = tv.astype(cval.dtype)
        nlive = jnp.sum(tk != kmax).astype(jnp.int32)
        t, _ = _sort_compress_keyed(sr.add, tk, tv, nlive, nrows=nrows,
                                    ncols=b.ncols, cap=out_cap,
                                    dedup=False, stride=stride, col_lo=clo)
        return t
    # XLA fallback: one segment-reduce over the dense key space (dead
    # slots carry kmax >= nseg and drop), then the sort-free compaction
    nseg = nrows * stride
    acc = sr.add.segment_reduce(cval, key, nseg)
    cnt = jax.ops.segment_sum(jnp.ones((flops_cap,), jnp.int32), key, nseg)
    t, _ = _dense_compact(acc, cnt, stride=stride, clo=clo,
                          out_cap=out_cap, nrows=nrows, ncols=b.ncols)
    return t


def spgemm_colwindow_hash(sr: Semiring, a: Tile, b: Tile, clo: Array,
                         chi: Array, *, flops_cap: int, out_cap: int,
                         win_width: int, b_struct=None) -> Tile:
    """See `_spgemm_colwindow_hash_impl`. This thin dispatcher resolves
    COMBBLAS_TPU_PALLAS_HASH *outside* the jit and passes it as a static
    arg: an env read inside the traced function is invisible to the jit
    cache, so flipping the flag after a compile would silently reuse
    the other path's executable (the trap `jax.clear_caches()` guards
    against for COMBBLAS_TPU_FUSED_KEY — keyed away here instead)."""
    from combblas_tpu.ops import pallas_kernels as pk
    if pk.hash_enabled():
        mode = "interpret" if pk.hash_interpret() else "tpu"
    else:
        mode = "off"
    return _spgemm_colwindow_hash_impl(sr, a, b, clo, chi,
                                       flops_cap=flops_cap,
                                       out_cap=out_cap,
                                       win_width=win_width,
                                       b_struct=b_struct,
                                       pallas_mode=mode)


spgemm_colwindow_hash._cache_size = _spgemm_colwindow_hash_impl._cache_size
