"""Static-permutation bit router (Beneš network).

Capability parity: the reference moves per-edge payloads between
column-sorted and row-sorted edge order inside its local kernels with
per-element scatters under OpenMP (Friends.h:64, BFSFriends.h:458,
SpImpl.h:60-145).  Per-element scatter/gather serializes on TPU, and a
comparison sort re-derives the *same static permutation* every call at
O(n log^2 n) data movement.  TPU-native redesign: the permutation is
known once the matrix is built, so we compile it — once, on the host —
into Beneš-network swap masks (`plan_route`, via the native
ops/_benes.cpp or a pure-Python fallback), and every application is
then 2*log2(n)-1 word-parallel delta-swap stages over 32x-packed bit
words (`apply_route`): no gather, no scatter, no sort, ~1/30th the
HBM traffic of the int32 sort it replaces.

The payload is one BIT per slot (exactly what the BFS dense stepper
routes — frontier membership); wider payloads can route bit-planes
independently.
"""

from __future__ import annotations

import ctypes
import dataclasses
import functools
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from combblas_tpu.utils.native import load_native

_SRC = pathlib.Path(__file__).parent / "_benes.cpp"

_lib = None
_tried = False


def _configure(lib):
    lib.benes_route.restype = ctypes.c_int
    lib.benes_route.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_uint32)]


def _load():
    """ctypes handle to the native router; None if g++ unavailable."""
    global _lib, _tried
    if not _tried:
        _tried = True
        _lib = load_native(_SRC, _configure)
    return _lib


def _benes_masks_py(perm: np.ndarray) -> np.ndarray:
    """Pure-Python mask computation (same algorithm as _benes.cpp);
    fallback when the native toolchain is missing.  O(n log n) with
    Python-level cycle walks — fine for tests, slow at scale."""
    n = len(perm)
    m = n.bit_length() - 1
    nstages = 2 * m - 1
    nwords = max(n >> 5, 1)
    masks = np.zeros((nstages, nwords), np.uint32)

    def set_bit(t, i):
        masks[t, i >> 5] |= np.uint32(1 << (i & 31))

    cur = np.array(perm, np.int64)  # analysis: allow(sync-in-async) host mask planning, route built once
    for d in range(m - 1):
        nn = n >> d
        h = nn >> 1
        nxt = np.empty_like(cur)
        for b in range(1 << d):
            base = b * nn
            P = cur[base:base + nn]
            inv = np.empty(nn, np.int64)
            inv[P] = np.arange(nn)
            C = np.full(nn, -1, np.int8)
            for start in range(nn):
                if C[start] != -1:
                    continue
                x, c = start, 0
                while C[x] == -1:
                    C[x] = c
                    y = x ^ h
                    C[y] = c ^ 1
                    x = int(inv[P[y] ^ h])
            for i in range(h):
                lo, hi = i, i + h
                if C[lo] == 1:
                    set_bit(d, base + i)
                x0 = lo if C[lo] == 0 else hi
                x1 = lo + hi - x0
                nxt[base + i] = P[x0] & (h - 1)
                nxt[base + h + i] = P[x1] & (h - 1)
            for o in range(h):
                if C[inv[o]] != 0:
                    set_bit(nstages - 1 - d, base + o)
        cur = nxt
    for b in range(n >> 1):
        if cur[2 * b] == 1:
            set_bit(m - 1, 2 * b)
    return masks


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class RoutePlan:
    """Compiled Beneš masks for one fixed permutation of ``n`` slots
    (padded to ``npad`` = next power of two; the padding routes
    identically).  ``masks``: (2*log2(npad)-1, npad/32) uint32 — or,
    when ``compact``, (2*log2(npad)-1, npad/64): a Beneš stage only
    ever sets mask bits at pair-LOW slots ((slot & stride) == 0), so
    the hi half of every stage's mask is structurally zero and the
    masks pack 2:1 (`compact_masks`), halving both the plan's HBM
    residency and the per-stage mask stream — the dominant route
    traffic."""

    masks: jax.Array
    n: int = dataclasses.field(metadata=dict(static=True))
    npad: int = dataclasses.field(metadata=dict(static=True))
    compact: bool = dataclasses.field(default=False,
                                      metadata=dict(static=True))

    @property
    def nstages(self) -> int:
        return 2 * (self.npad.bit_length() - 1) - 1


def plan_route(perm: np.ndarray) -> RoutePlan:
    """Compile ``perm`` (out[perm[i]] = in[i]) into Beneš swap masks.

    Host-side, once per permutation (for BFS: once per matrix, inside
    the untimed Graph500 kernel-1 — ≅ OptimizeForGraph500,
    SpParMat.cpp:3285).  Cost O(n log n); the native router does
    ~2^27 slots in tens of seconds, the Python fallback is for small n.
    Masks are stored compact (2:1) when the network is large enough
    for the (R, 128) word layout.
    """
    masks, n, npad = plan_route_masks(perm)
    if npad >= _COMPACT_MIN_NPAD:
        comp = compact_masks(masks, npad)
        return RoutePlan(jnp.asarray(tile_masks(jnp.asarray(comp))),
                         n, npad, compact=True)
    return RoutePlan(jnp.asarray(masks), n, npad)


def plan_route_masks(perm: np.ndarray) -> tuple[np.ndarray, int, int]:
    """Host-side mask computation: (numpy masks, n, npad). Use this
    (rather than `plan_route`) when the caller device_puts the masks
    itself — e.g. sharded across a mesh — so they are never staged on
    the default device."""
    perm = np.asarray(perm, np.int32)  # analysis: allow(sync-in-async) host mask planning, route built once
    n = int(perm.shape[0])
    if n < 2:
        raise ValueError("route needs at least 2 slots")
    npad = 1 << max(5, (n - 1).bit_length())
    if npad != n:
        full = np.concatenate(
            [perm, np.arange(n, npad, dtype=np.int32)])
    else:
        full = perm
    m = npad.bit_length() - 1
    nstages = 2 * m - 1
    nwords = npad >> 5
    lib = _load()
    if lib is not None:
        masks = np.zeros((nstages, nwords), np.uint32)
        full = np.ascontiguousarray(full)
        rc = lib.benes_route(
            full.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            npad, masks.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
        if rc != 0:
            raise ValueError(f"benes_route failed (rc={rc}): not a "
                             "permutation?" if rc == -2 else f"rc={rc}")
    else:
        if full.min() < 0 or full.max() >= npad or \
                not np.all(np.bincount(full, minlength=npad) == 1):
            raise ValueError("perm is not a permutation")
        masks = _benes_masks_py(full)
    return masks, n, npad


def _stride(t: int, m: int, npad: int) -> int:
    return npad >> (t + 1) if t < m else npad >> (2 * m - 1 - t)


# --------------------------------------------------------------------------
# Mask compaction: every stage's mask bits live only at pair-LOW slots
# ((slot & stride) == 0), so each stage packs 2:1. The packing pairs the
# top/bottom HALVES of the word array elementwise — full word w pairs
# with w + nwords/2 — with the bottom half's valid bits shifted onto the
# top half's structurally-zero pair-high positions:
#   stride 2^e, e<5 : bit-shift within the word (<< 2^e)
#   5<=e<12 (lanes) : cyclic lane roll by 2^(e-5) within each 128-lane row
#   e>=12 (rows)    : row shift by 2^(e-12) within each aligned pair group
# All three shifts land valid bits exactly on the complementary pattern,
# so pack = OR and unpack = (mask & pattern) / (unshift & pattern) —
# two cheap VPU ops per stage in the kernels that stream them.
# --------------------------------------------------------------------------

_COMPACT_MIN_NPAD = 1 << 13   # below this the (R,128) row layout (and
#                               the Pallas kernel) don't exist; full
#                               masks are tiny there anyway


def _patt_word(e: int) -> int:
    """uint32 with bits at in-word pair-low positions ((bit & 2^e)==0)."""
    p = 0
    for i in range(32):
        if not (i >> e) & 1:
            p |= 1 << i
    return p


def compact_masks(masks: np.ndarray, npad: int) -> np.ndarray:
    """(nstages, npad/32) full masks -> (nstages, npad/64) compact.
    Host-side numpy, once per plan."""
    m = npad.bit_length() - 1
    nstages, nwords = masks.shape
    assert nwords == npad >> 5 and nwords >= 256, (nwords, npad)
    half = nwords >> 1
    out = np.empty((nstages, half), np.uint32)
    for t in range(nstages):
        e = _stride(t, m, npad).bit_length() - 1
        top, bot = masks[t, :half], masks[t, half:]
        if e < 5:
            out[t] = top | (bot << (1 << e))
        elif e < 12:
            dw = 1 << (e - 5)
            b2 = bot.reshape(-1, 128)
            out[t] = (top.reshape(-1, 128)
                      | np.roll(b2, dw, axis=1)).reshape(-1)
        else:
            dr = 1 << (e - 12)
            t2, b2 = top.reshape(-1, 128), bot.reshape(-1, 128)
            if dr >= t2.shape[0]:     # outermost stage: bottom is empty
                assert not bot.any()
                out[t] = top
            else:
                out[t] = (t2 | np.roll(b2, dr, axis=0)).reshape(-1)
    return out


def _decompact_stage(c: jax.Array, e: int, npad: int) -> jax.Array:
    """One stage's (npad/64,) compact mask -> (npad/32,) full mask
    (XLA path; the Pallas kernel decompacts per strip instead)."""
    if e < 5:
        patt = jnp.uint32(_patt_word(e))
        top, bot = c & patt, (c >> (1 << e)) & patt
    elif e < 12:
        dw = 1 << (e - 5)
        c2 = c.reshape(-1, 128)
        lane = jnp.arange(128, dtype=jnp.int32)
        lp = jnp.where((lane & dw) == 0, jnp.uint32(0xFFFFFFFF),
                       jnp.uint32(0))
        top = (c2 & lp).reshape(-1)
        bot = (jnp.roll(c2, -dw, axis=1) & lp).reshape(-1)
    else:
        dr = 1 << (e - 12)
        c2 = c.reshape(-1, 128)
        if dr >= c2.shape[0]:
            top, bot = c, jnp.zeros_like(c)
        else:
            row = jnp.arange(c2.shape[0], dtype=jnp.int32)[:, None]
            rp = jnp.where((row & dr) == 0, jnp.uint32(0xFFFFFFFF),
                           jnp.uint32(0))
            top = (c2 & rp).reshape(-1)
            bot = (jnp.roll(c2, -dr, axis=0) & rp).reshape(-1)
    return jnp.concatenate([top, bot])


def mask_npad(mask_words: int, compact: bool) -> int:
    """npad of a stored mask row of ``mask_words`` uint32 words."""
    return mask_words * (64 if compact else 32)


def tile_masks(masks: jax.Array) -> jax.Array:
    """Pre-tile flat (nstages, w) masks to (nstages, w/128, 128) — the
    Pallas operand layout. Call OUTSIDE the traversal loop: on TPU's
    tiled physical layouts the reshape is a full relayout copy of the
    mask tensor, and letting apply_route_pallas do it per call cost
    424 MB of copy per route at scale 22 (route measured 3.8 ms vs
    1.0 ms with pre-tiled masks). No-op when the layout 3D form
    doesn't exist (w % 128 != 0) or masks are already tiled."""
    return tile_masks_batched(masks) if masks.ndim == 2 else masks


def tile_masks_batched(masks):
    """The one encoding of the Pallas operand-layout pre-tiling,
    (..., nstages, w) -> (..., nstages, w/128, 128): used per-tile by
    `tile_masks` (jax, ndim 2) and at plan time on batched host
    tensors (numpy, leading grid dims) so per-root traversals never
    pay the relayout."""
    if masks.shape[-1] % 128 == 0:
        return masks.reshape(*masks.shape[:-1], -1, 128)
    return masks


# --------------------------------------------------------------------------
# Pallas application: the packed bit-vector stays resident in VMEM for
# all 2*log2(npad)-1 stages; only the masks stream from HBM (one stage
# per sequential grid step, double-buffered). HBM traffic drops from
# ~3 arrays/stage (XLA) to ~1 mask/stage + one W read + one W write.
# Delta-swaps are expressed as rolls (lane rolls for word-distance
# < 128, sublane rolls above) — no reshapes, no Mosaic relayouts.
# --------------------------------------------------------------------------

def _stage_swap(e: int, w, mk):
    """One Beneš stage at bit-stride 2^e on (R, 128) uint32 words.
    Mask bits are set only at pair-lo positions, which makes the
    roll-based pairing safe: rolled-in garbage lands where mask = 0."""
    from combblas_tpu.ops.bitseg import _roll
    if e < 5:                      # within-word delta swap
        s = 1 << e
        delta = ((w >> s) ^ w) & mk
        return w ^ delta ^ (delta << s)
    if e < 12:                     # lane-dimension word swap
        d = 1 << (e - 5)
        p = _roll(w, -d, 1)
        delta = (w ^ p) & mk
        return w ^ delta ^ _roll(delta, d, 1)
    d = 1 << (e - 12)              # sublane-dimension word swap
    p = _roll(w, -d, 0)
    delta = (w ^ p) & mk
    return w ^ delta ^ _roll(delta, d, 0)


_RBLR = 512    # strip rows for the route kernel: every stage either
#               keeps its swap pairs inside one aligned strip (bit,
#               lane, and small row strides — powers of two never
#               straddle aligned power-of-two strips) or pairs whole
#               strips; full-array vector ops are avoided because
#               Mosaic compile time explodes with the sublane extent


def _mask_strip(m_ref, i, e, blr, half, compact):
    """Full (blr, 128) mask for data strip ``i`` of stage-exponent
    ``e`` — fetched directly, or decompacted from the 2:1 packed
    top|shifted-bottom layout (see compact_masks)."""
    import jax.experimental.pallas as pl
    from combblas_tpu.ops.bitseg import _roll

    if not compact:
        return m_ref[0, pl.ds(i * blr, blr), :]
    ci = jnp.where(i < half, i, i - half)
    c = m_ref[0, pl.ds(ci * blr, blr), :]
    top = i < half
    if e < 5:
        patt = jnp.uint32(_patt_word(e))
        return jnp.where(top, c & patt, (c >> (1 << e)) & patt)
    if e < 12:
        dw = 1 << (e - 5)
        lane = lax.broadcasted_iota(jnp.int32, (blr, 128), 1)
        sel = jnp.where(top, c, _roll(c, -dw, 1))
        return jnp.where((lane & dw) == 0, sel, jnp.uint32(0))
    # in-strip row stage: 2*dr <= blr, so the local row index has
    # the same dr-bit as the global one (strips are 2dr-aligned)
    dr = 1 << (e - 12)
    row = lax.broadcasted_iota(jnp.int32, (blr, 128), 0)
    sel = jnp.where(top, c, _roll(c, -dr, 0))
    return jnp.where((row & dr) == 0, sel, jnp.uint32(0))


def _mask_strip_big(m_ref, lo, step, blr, half, compact):
    """Mask strip for a `_big` (strip-pair) stage: a pair-lo strip is
    all-valid rows; compact masks store it at strip `lo` (top half)
    or `lo - half + step` (bottom: B[j] = C[j+dr])."""
    import jax.experimental.pallas as pl

    if compact:
        cs = jnp.where(lo < half, lo, lo - half + step)
        return m_ref[0, pl.ds(cs * blr, blr), :]
    return m_ref[0, pl.ds(lo * blr, blr), :]


def _route_kernel(m_ref, w_ref, *rest, mexp, nstages, blr, compact):
    import jax.experimental.pallas as pl

    # optional AND-mask input (fused `route(w) & v` — saves a separate
    # elementwise kernel launch per BFS level): (m, w, v?, o).
    # The routing state lives directly in the revisited OUTPUT block —
    # a separate VMEM scratch pushed the resident set past what lets
    # Mosaic double-buffer the mask stream (measured 3.84 -> 1.04 ms
    # per apply at npad=2^27 from removing it).
    if len(rest) == 2:
        v_ref, o_ref = rest
    else:
        v_ref, (o_ref,) = None, rest

    t = pl.program_id(0)
    r = o_ref.shape[0]
    nstrips = r // blr
    half = nstrips // 2
    k = jnp.abs(mexp - 1 - t)

    def mask_strip(i, e):
        return _mask_strip(m_ref, i, e, blr, half, compact)

    @pl.when(t == 0)
    def _init():
        def body(i, _):
            rows = pl.ds(i * blr, blr)
            o_ref[rows, :] = w_ref[rows, :]
            return 0

        lax.fori_loop(0, nstrips, body, 0)

    for e in range(mexp):
        # bit (e<5) and lane (e<12) strides stay within a row; row
        # strides 2^(e-12) stay within an aligned strip iff the pair
        # block 2*2^(e-12) fits it
        in_strip = e < 12 or 2 * (1 << (e - 12)) <= blr
        if in_strip or nstrips == 1:
            @pl.when(k == e)
            def _small(e=e):
                def body(i, _):
                    rows = pl.ds(i * blr, blr)
                    a = o_ref[rows, :]
                    mk = mask_strip(i, e)
                    o_ref[rows, :] = _stage_swap(e, a, mk)
                    return 0

                lax.fori_loop(0, nstrips, body, 0)
        else:
            @pl.when(k == e)
            def _big(e=e):
                step = (1 << (e - 12)) // blr   # strips between pair
                def body(i, _):
                    blk, off = i // step, i % step
                    lo = blk * 2 * step + off
                    rlo = pl.ds(lo * blr, blr)
                    rhi = pl.ds((lo + step) * blr, blr)
                    a = o_ref[rlo, :]
                    b = o_ref[rhi, :]
                    mk = _mask_strip_big(m_ref, lo, step, blr, half,
                                         compact)
                    delta = (a ^ b) & mk
                    o_ref[rlo, :] = a ^ delta
                    o_ref[rhi, :] = b ^ delta
                    return 0

                lax.fori_loop(0, nstrips // 2, body, 0)

    if v_ref is not None:
        @pl.when(t == nstages - 1)
        def _vmask():
            def body(i, _):
                rows = pl.ds(i * blr, blr)
                o_ref[rows, :] = o_ref[rows, :] & v_ref[rows, :]
                return 0

            lax.fori_loop(0, nstrips, body, 0)


def apply_route_pallas(rp: RoutePlan, words: jax.Array,
                       interpret: bool = False,
                       and_mask: jax.Array | None = None) -> jax.Array:
    """`apply_route` as a single Pallas kernel (TPU): the state lives
    in the revisited output block for all stages, masks streamed
    (route_pallas_ok documents the VMEM budget; apply_route_best
    gates on the device's actual VMEM). ``and_mask`` (same shape as
    words) fuses a final `routed & and_mask` pass — one fewer kernel
    launch on the BFS level path."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m = rp.npad.bit_length() - 1
    nstages = rp.nstages
    nwords = rp.npad >> 5
    r = max(nwords // 128, 1)
    w2 = words.reshape(r, 128)
    mr = r // 2 if rp.compact else r   # mask rows per stage
    m3 = rp.masks.reshape(nstages, mr, 128)
    # compact decompaction selects strips by top/bottom half, so the
    # strip grid must split the halves evenly: blr <= r/2
    kernel = functools.partial(_route_kernel, mexp=m, nstages=nstages,
                               blr=min(_RBLR, mr), compact=rp.compact)
    in_specs = [
        pl.BlockSpec((1, mr, 128), lambda t: (t, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((r, 128), lambda t: (0, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [m3, w2]
    if and_mask is not None:
        in_specs.append(pl.BlockSpec((r, 128), lambda t: (0, 0),
                                     memory_space=pltpu.VMEM))
        args.append(and_mask.reshape(r, 128))
    out = pl.pallas_call(
        kernel,
        grid=(nstages,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((r, 128), lambda t: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_sds((r, 128), jnp.uint32, words),
        compiler_params=_vmem_params(),
        interpret=interpret,
    )(*args)
    return out.reshape(-1)


def _route_kernel_pair(m_ref, w_ref, o_ref, *, mexp, blr, compact):
    """Routes TWO independent bit planes through one mask stream —
    the parent-extraction path routes 23 column-id planes through the
    SAME network, and per-plane launches re-pay the full mask stream
    each time (measured 51 ms for 23 singles vs 18 ms paired at
    npad=2^27). P=2 keeps the resident W set (2 in + 2 out blocks)
    inside the VMEM budget route_pallas_ok(extra_arrays=2) checks."""
    import jax.experimental.pallas as pl

    t = pl.program_id(0)
    r = o_ref.shape[1]
    nstrips = r // blr
    half = nstrips // 2
    k = jnp.abs(mexp - 1 - t)

    @pl.when(t == 0)
    def _init():
        for q in range(2):
            def body(i, _):
                rows = pl.ds(i * blr, blr)
                o_ref[q, rows, :] = w_ref[q, rows, :]
                return 0

            lax.fori_loop(0, nstrips, body, 0)

    for e in range(mexp):
        in_strip = e < 12 or 2 * (1 << (e - 12)) <= blr
        if in_strip or nstrips == 1:
            @pl.when(k == e)
            def _small(e=e):
                def body(i, _):
                    rows = pl.ds(i * blr, blr)
                    mk = _mask_strip(m_ref, i, e, blr, half, compact)
                    for q in range(2):
                        o_ref[q, rows, :] = _stage_swap(
                            e, o_ref[q, rows, :], mk)
                    return 0

                lax.fori_loop(0, nstrips, body, 0)
        else:
            @pl.when(k == e)
            def _big(e=e):
                step = (1 << (e - 12)) // blr
                def body(i, _):
                    blk, off = i // step, i % step
                    lo = blk * 2 * step + off
                    rlo = pl.ds(lo * blr, blr)
                    rhi = pl.ds((lo + step) * blr, blr)
                    mk = _mask_strip_big(m_ref, lo, step, blr, half,
                                         compact)
                    for q in range(2):
                        a = o_ref[q, rlo, :]
                        b = o_ref[q, rhi, :]
                        delta = (a ^ b) & mk
                        o_ref[q, rlo, :] = a ^ delta
                        o_ref[q, rhi, :] = b ^ delta
                    return 0

                lax.fori_loop(0, nstrips // 2, body, 0)


def apply_route_pallas_pair(rp: RoutePlan, words2: jax.Array,
                            interpret: bool = False) -> jax.Array:
    """Route a (2, npad/32) pair of planes through one kernel launch
    (one shared mask stream). Bit-identical to routing each plane
    with apply_route_pallas."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m = rp.npad.bit_length() - 1
    nstages = rp.nstages
    nwords = rp.npad >> 5
    r = max(nwords // 128, 1)
    w3 = words2.reshape(2, r, 128)
    mr = r // 2 if rp.compact else r
    m3 = rp.masks.reshape(nstages, mr, 128)
    kernel = functools.partial(_route_kernel_pair, mexp=m,
                               blr=min(_RBLR, mr), compact=rp.compact)
    out = pl.pallas_call(
        kernel,
        grid=(nstages,),
        in_specs=[
            pl.BlockSpec((1, mr, 128), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, r, 128), lambda t: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((2, r, 128), lambda t: (0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_sds((2, r, 128), jnp.uint32, words2),
        compiler_params=_vmem_params(),
        interpret=interpret,
    )(m3, w3)
    return out.reshape(2, -1)


def _device_vmem_bytes() -> int:
    """Per-core VMEM of the attached TPU (conservative default when
    undiscoverable). v2/v3 have 16/32 MB; v4/v5 have 128."""
    try:
        d = jax.devices()[0]
        kind = getattr(d, "device_kind", "") or ""
    except Exception:
        kind = ""
    k = kind.lower()
    if "v2" in k:
        return 16 * 1024 * 1024
    if "v3" in k:
        return 32 * 1024 * 1024
    return 128 * 1024 * 1024


def _vmem_params():
    """Raise the scoped-VMEM ceiling: the resident-W kernels hold
    several full word arrays (default limit is 16 MB; the generation's
    physical VMEM bounds it — 7/8 of it, leaving headroom)."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams")
    return cls(vmem_limit_bytes=_device_vmem_bytes() * 7 // 8)


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the caller's varying-mesh-axes set
    (required for pallas_call under shard_map)."""
    vma = getattr(getattr(like, "aval", None), "vma", None)
    try:
        return jax.ShapeDtypeStruct(shape, dtype,
                                    vma=vma if vma is not None
                                    else frozenset())
    except TypeError:
        return jax.ShapeDtypeStruct(shape, dtype)


def apply_route(rp: RoutePlan, words: jax.Array) -> jax.Array:
    """Route packed bit-words through the network: 2*log2(npad)-1
    word-parallel delta-swap stages.  ``words``: (npad/32,) uint32 as
    produced by `pack_bits`.  Returns routed words; bit perm[i] of the
    output equals bit i of the input."""
    m = rp.npad.bit_length() - 1
    for t in range(rp.nstages):
        s = _stride(t, m, rp.npad)
        if rp.compact:
            mt = _decompact_stage(rp.masks[t].reshape(-1),
                                  s.bit_length() - 1, rp.npad)
        else:
            mt = rp.masks[t].reshape(-1)
        if s >= 32:
            d = s >> 5
            w2 = words.reshape(-1, 2, d)
            a, b = w2[:, 0, :], w2[:, 1, :]
            ml = mt.reshape(-1, 2, d)[:, 0, :]
            delta = (a ^ b) & ml
            words = jnp.stack([a ^ delta, b ^ delta], axis=1).reshape(-1)
        else:
            delta = ((words >> s) ^ words) & mt
            words = words ^ delta ^ (delta << s)
    return words


def route_pallas_ok(rp: RoutePlan, extra_arrays: int = 0) -> bool:
    """Whether the VMEM-resident Pallas route kernel applies: TPU
    backend, the (R, 128) layout exists (npad >= 2^13), and the
    VMEM budget fits — W in+out + double-buffered mask stream
    = (3 with compact masks, else 4) x npad/8 bytes, plus
    ``extra_arrays`` more full-size residents (e.g. the fused
    and_mask input), gated on the actual device generation's VMEM
    (v2/v3 cap lower instead of failing to compile — advisor round-3
    finding)."""
    from combblas_tpu.ops import pallas_kernels as pk
    arrays = (3 if rp.compact else 4) + extra_arrays
    npad_max = _device_vmem_bytes() // arrays * 8
    return pk.enabled() and (1 << 13) <= rp.npad <= npad_max


def apply_route_best(rp: RoutePlan, words: jax.Array) -> jax.Array:
    """Route via the VMEM-resident Pallas kernel on TPU backends (when
    the network is big enough for the (R, 128) layout), else the XLA
    stage loop. Both are bit-identical."""
    if route_pallas_ok(rp):
        return apply_route_pallas(rp, words)
    return apply_route(rp, words)


def apply_route_multi(rp: RoutePlan, words: jax.Array) -> jax.Array:
    """Route an (npad/32, W) lane MATRIX of packed bit-planes through
    the network in one pass — every lane traverses the same delta-swap
    stages, with each stage's mask decompacted ONCE and broadcast over
    the lane axis. Lane w of the output is bit-identical to
    apply_route(rp, words[:, w])."""
    m = rp.npad.bit_length() - 1
    w = words.shape[1]
    for t in range(rp.nstages):
        s = _stride(t, m, rp.npad)
        if rp.compact:
            mt = _decompact_stage(rp.masks[t].reshape(-1),
                                  s.bit_length() - 1, rp.npad)
        else:
            mt = rp.masks[t].reshape(-1)
        if s >= 32:
            d = s >> 5
            w2 = words.reshape(-1, 2, d, w)
            a, b = w2[:, 0], w2[:, 1]
            ml = mt.reshape(-1, 2, d)[:, 0, :, None]
            delta = (a ^ b) & ml
            words = jnp.stack([a ^ delta, b ^ delta],
                              axis=1).reshape(-1, w)
        else:
            mt = mt[:, None]
            delta = ((words >> s) ^ words) & mt
            words = words ^ delta ^ (delta << s)
    return words


def apply_route_multi_best(rp: RoutePlan, words: jax.Array) -> jax.Array:
    """Lane-matrix route dispatch: on TPU (layout permitting) pair
    lanes through the VMEM-resident pair kernel under lax.map — each
    launch shares one mask stream between two planes — else the XLA
    lane-broadcast stage loop. Bit-identical either way."""
    w = int(words.shape[1])
    if w >= 2 and route_pallas_ok(rp, extra_arrays=2):
        lanes = words.T                      # (W, nwords)
        if w % 2:
            lanes = jnp.concatenate([lanes, lanes[-1:]])
        pairs = lanes.reshape(-1, 2, lanes.shape[1])
        out = jax.lax.map(lambda p: apply_route_pallas_pair(rp, p),
                          pairs)
        return out.reshape(-1, out.shape[-1])[:w].T
    return apply_route_multi(rp, words)


def pack_bits(bits: jax.Array, npad: int) -> jax.Array:
    """(n,) bool/int8 -> (npad/32,) uint32, little-endian bit order
    (bit i of word w = slot 32w+i), zero-padded."""
    n = bits.shape[0]
    b8 = bits.astype(jnp.uint8)
    if npad != n:
        b8 = jnp.pad(b8, (0, npad - n))
    nyb = b8.reshape(-1, 8)
    bytes_ = (nyb << jnp.arange(8, dtype=jnp.uint8)).sum(
        axis=1, dtype=jnp.uint8)
    return lax.bitcast_convert_type(
        bytes_.reshape(-1, 4), jnp.uint32).reshape(-1)


def unpack_bits(words: jax.Array, n: int) -> jax.Array:
    """(npad/32,) uint32 -> (n,) int8 of 0/1, inverse of pack_bits."""
    bytes_ = lax.bitcast_convert_type(words, jnp.uint8).reshape(-1, 1)
    bits = (bytes_ >> jnp.arange(8, dtype=jnp.uint8)) & jnp.uint8(1)
    return bits.reshape(-1)[:n].astype(jnp.int8)


def pack_bits_multi(bits: jax.Array, npad: int) -> jax.Array:
    """(n, W) bool/int8 -> (npad/32, W) uint32: `pack_bits` per lane
    (column), same little-endian bit order in every lane — lane w of
    the output is exactly pack_bits(bits[:, w], npad)."""
    n, w = bits.shape
    b8 = bits.astype(jnp.uint8)
    if npad != n:
        b8 = jnp.pad(b8, ((0, npad - n), (0, 0)))
    nyb = b8.reshape(-1, 8, w)
    bytes_ = (nyb << jnp.arange(8, dtype=jnp.uint8)[None, :, None]).sum(
        axis=1, dtype=jnp.uint8)
    return lax.bitcast_convert_type(
        bytes_.reshape(-1, 4, w).transpose(0, 2, 1), jnp.uint32)


def unpack_bits_multi(words: jax.Array, n: int) -> jax.Array:
    """(npad/32, W) uint32 -> (n, W) int8, inverse of pack_bits_multi
    (lane w = unpack_bits(words[:, w], n))."""
    w = words.shape[1]
    bytes_ = lax.bitcast_convert_type(words, jnp.uint8)   # (nw, W, 4)
    bits = (bytes_[..., None] >> jnp.arange(8, dtype=jnp.uint8)) \
        & jnp.uint8(1)                                    # (nw, W, 4, 8)
    return bits.transpose(0, 2, 3, 1).reshape(-1, w)[:n].astype(jnp.int8)
