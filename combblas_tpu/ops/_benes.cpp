// Beneš routing-network setup for static permutations.
//
// TPU rationale: applying a *fixed* permutation to per-edge payloads is
// the hot routing step of the BFS dense stepper (frontier bits move
// from column-sorted to row-sorted edge order — the reference instead
// scatters per edge inside its OpenMP loops, BFSFriends.h:458,
// Friends.h:64).  XLA's per-element gathers/scatters serialize on TPU
// (~8 ns/element) and a comparison sort re-derives the same static
// permutation every level at O(n log^2 n) data movement.  A Beneš
// network realizes ANY permutation of n = 2^m slots with 2m-1
// "delta-swap" stages; with one mask bit per pair the runtime is pure
// word-parallel XOR/AND on 32x-packed bit words — no gather, no sort,
// ~1/30th the traffic of the int32 sort it replaces.
//
// This file computes the per-stage swap masks on the host (the classic
// looping algorithm), once per matrix at plan time; application lives
// in ops/route.py as jnp bit arithmetic.
//
// Layout contract (must match route.py):
//   stage t in [0, 2m-1); stride(t) = n >> (t+1)        for t <  m,
//                         stride(t) = n >> (2m-1-t)     for t >= m.
//   Stage t swaps pair (i, i+s) iff bit i of masks[t] is set; mask
//   bits are only ever set at positions with (i & s) == 0.
//   Bit i of the packed mask = word[i>>5] bit (i&31)  (little-endian
//   bit order, matching jnp.unpackbits(bitorder="little")).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

inline void set_bit(uint32_t* m, int64_t i) {
  m[i >> 5] |= (1u << (i & 31));
}

}  // namespace

extern "C" {

// perm[i] = destination slot of input slot i; a permutation of [0, n).
// n must be a power of two >= 2.  masks: caller-zeroed buffer of
// (2*log2(n) - 1) * (n/32) uint32 words, stage-major.
// Returns 0 on success, -1 on bad n, -2 if perm is not a permutation.
int benes_route(const int32_t* perm, int64_t n, uint32_t* masks) {
  if (n < 2 || (n & (n - 1))) return -1;
  int m = 0;
  while ((int64_t(1) << m) < n) ++m;
  const int nstages = 2 * m - 1;
  const int64_t nwords = n >> 5;  // n >= 32 below; n < 32 handled at end

  std::vector<int32_t> cur(perm, perm + n), nxt(n), inv(n);
  std::vector<int8_t> color(n);

  // validate
  std::memset(color.data(), 0, n);
  for (int64_t i = 0; i < n; ++i) {
    if (perm[i] < 0 || perm[i] >= n || color[perm[i]]) return -2;
    color[perm[i]] = 1;
  }

  auto mask_ptr = [&](int t) -> uint32_t* {
    // For tiny n (< 32) the caller still hands one word per stage.
    int64_t w = nwords > 0 ? nwords : 1;
    return masks + int64_t(t) * w;
  };

  for (int d = 0; d < m - 1; ++d) {
    const int64_t nn = n >> d;   // block size at this depth
    const int64_t h = nn >> 1;   // stage stride
    uint32_t* Min = mask_ptr(d);
    uint32_t* Mout = mask_ptr(nstages - 1 - d);
    const int64_t nblocks = int64_t(1) << d;
    for (int64_t b = 0; b < nblocks; ++b) {
      const int64_t base = b * nn;
      int32_t* P = cur.data() + base;  // block-local perm, values in [0, nn)
      int32_t* I = inv.data() + base;
      int8_t* C = color.data() + base;
      for (int64_t i = 0; i < nn; ++i) I[P[i]] = (int32_t)i;
      std::memset(C, -1, nn);
      // 2-color the constraint cycles: input-pair edges (x, x^h) must
      // differ; output-pair edges (I[o], I[o^h]) must differ.  Each
      // vertex has degree 2, cycles are even, so the alternating walk
      // below is always consistent.
      for (int64_t start = 0; start < nn; ++start) {
        if (C[start] != -1) continue;
        int64_t x = start;
        int8_t c = 0;
        while (C[x] == -1) {
          C[x] = c;                       // x routed via subnetwork c
          const int64_t y = x ^ h;        // input-pair partner
          C[y] = (int8_t)(c ^ 1);
          x = I[P[y] ^ h];                // output-pair partner of y
          // x must differ from y's color -> same color as before
        }
      }
      // input-stage masks + next-depth subperms.  Subnetwork 0 (color
      // 0) occupies the low half [base, base+h), subnetwork 1 the high
      // half — preserving block-contiguous layout for depth d+1.
      int32_t* N0 = nxt.data() + base;
      int32_t* N1 = nxt.data() + base + h;
      for (int64_t i = 0; i < h; ++i) {
        const int64_t lo = i, hi = i + h;
        if (C[lo] == 1) set_bit(Min, base + i);  // swap so color-0 sits low
        const int64_t x0 = (C[lo] == 0) ? lo : hi;  // via subnetwork 0
        const int64_t x1 = lo + hi - x0;            // via subnetwork 1
        N0[i] = (int32_t)((int64_t)P[x0] & (h - 1));
        N1[i] = (int32_t)((int64_t)P[x1] & (h - 1));
      }
      // output-stage masks: output pair (o, o+h); the element arriving
      // low came through subnetwork 0; swap iff it belongs at o+h.
      for (int64_t o = 0; o < h; ++o) {
        const int64_t a = I[o];  // input mapping to output o
        // the subnetwork-0 element of this pair lands at slot o low;
        // it is a if C[a]==0 else the partner I[o+h]
        if (C[a] != 0) set_bit(Mout, base + o);
      }
    }
    cur.swap(nxt);
  }
  // innermost depth: blocks of 2, single middle stage t = m-1
  {
    uint32_t* Mmid = mask_ptr(m - 1);
    const int64_t nblocks = n >> 1;
    for (int64_t b = 0; b < nblocks; ++b) {
      if (cur[2 * b] == 1) set_bit(Mmid, 2 * b);
    }
  }
  return 0;
}

}  // extern "C"
