"""Word-parallel segmented OR scans over packed bit vectors.

Capability parity: the per-edge frontier/visited bookkeeping that the
reference keeps in BitMap/BitMapFringe words (BitMap.h:1-168,
BitMapFringe.h:41) and updates with word-level operations inside its
bottom-up step (BFSFriends.h:458). TPU-native redesign: the BFS
dense phase (models/bfs.py) keeps ALL per-edge state as 32x-packed
bits and needs two primitives over them — an inclusive segmented OR
scan (propagate "some neighbor is active" to each row's end slot) and
its backward twin (fill the whole row run with the row's final bit).
Both are Kogge-Stone prefix networks on (value, no-boundary) bit
pairs: log2(n) stages of pure shift/AND/OR word arithmetic — no
gather, no scatter, no per-element work.

Layout: a bit vector of npad = 32 * nwords slots as (nwords,) uint32,
little-endian bit order (bit i of word w = slot 32w + i), matching
ops/route.py pack_bits. Segment STARTS are marked in a static packed
flag vector (bit set = this slot begins a new segment).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _shift_up(x: jax.Array, d: int) -> jax.Array:
    """Packed shift toward higher slot indices by d bits (zeros in);
    slot i of the result = slot i-d of x."""
    wd, bd = d // 32, d % 32
    if wd:
        x = jnp.concatenate([jnp.zeros((wd,), x.dtype), x[:-wd]])
    if bd:
        prev = jnp.concatenate([jnp.zeros((1,), x.dtype), x[:-1]])
        x = (x << bd) | (prev >> (32 - bd))
    return x


def _shift_down(x: jax.Array, d: int) -> jax.Array:
    """Packed shift toward lower slot indices: slot i = slot i+d of x."""
    wd, bd = d // 32, d % 32
    if wd:
        x = jnp.concatenate([x[wd:], jnp.zeros((wd,), x.dtype)])
    if bd:
        nxt = jnp.concatenate([x[1:], jnp.zeros((1,), x.dtype)])
        x = (x >> bd) | (nxt << (32 - bd))
    return x


def seg_or_scan_bits(x: jax.Array, starts: jax.Array) -> jax.Array:
    """Inclusive segmented OR scan: out bit i = OR of x over
    [segment_start(i), i]. ``x``/``starts``: (nwords,) uint32."""
    n = int(x.shape[0]) * 32
    y = x
    nb = ~starts                      # "no boundary at this slot"
    d = 1
    while d < n:
        y = y | (nb & _shift_up(y, d))
        nb = nb & _shift_up(nb, d)
        d <<= 1
    return y


def seg_or_fill_bits(x: jax.Array, starts: jax.Array) -> jax.Array:
    """Segment-wide OR: out bit i = OR of x over i's WHOLE segment
    (forward scan, then a backward OR-prefix blocked at starts — the
    segment end's total flows down over every slot of its segment)."""
    n = int(x.shape[0]) * 32
    y = seg_or_scan_bits(x, starts)
    nb = _shift_down(~starts, 1)      # no start in (i, i+1]
    d = 1
    while d < n:
        y = y | (nb & _shift_down(y, d))
        nb = nb & _shift_down(nb, d)  # no start in (i, i+2d]
        d <<= 1
    return y


# --------------------------------------------------------------------------
# Pallas fused kernel: both scans of seg_or_fill_bits in ONE grid step
# with everything VMEM-resident — the Kogge-Stone stages are pure VPU
# compute, so HBM traffic is just x + starts in, result out.
# Works on the (R, 128) word layout (flat word w = (w // 128, w % 128)).
# --------------------------------------------------------------------------

def _rows_shift(x, k, down: bool):
    """Shift rows of (R, 128) by k (zeros shifted in). down=True moves
    row r-k's data to row r (toward higher flat order)."""
    if k == 0:
        return x
    r = x.shape[0]
    if k >= r:
        return jnp.zeros_like(x)
    pad = jnp.zeros((k, x.shape[1]), x.dtype)
    return (jnp.concatenate([pad, x[:-k]], 0) if down
            else jnp.concatenate([x[k:], pad], 0))


def _lane_up(x, wd):
    """Word shift toward higher flat index by wd words on (R, 128)."""
    rs, ls = wd // 128, wd % 128
    base = _rows_shift(x, rs, True)
    if ls == 0:
        return base
    carry = _rows_shift(x, rs + 1, True)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, x.shape[1]), 1)
    return jnp.where(lane >= ls, jnp.roll(base, ls, axis=1),
                     jnp.roll(carry, ls, axis=1))


def _lane_down(x, wd):
    """Word shift toward lower flat index by wd words on (R, 128)."""
    rs, ls = wd // 128, wd % 128
    base = _rows_shift(x, rs, False)
    if ls == 0:
        return base
    carry = _rows_shift(x, rs + 1, False)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, x.shape[1]), 1)
    return jnp.where(lane < x.shape[1] - ls,
                     jnp.roll(base, -ls, axis=1),
                     jnp.roll(carry, -ls, axis=1))


def _up2(x, d):
    """Bit shift toward higher flat slot index by d on (R, 128)."""
    wd, b = d // 32, d % 32
    w = _lane_up(x, wd)
    if b == 0:
        return w
    prev = _lane_up(x, wd + 1)
    return (w << b) | (prev >> (32 - b))


def _down2(x, d):
    """Bit shift toward lower flat slot index by d on (R, 128)."""
    wd, b = d // 32, d % 32
    w = _lane_down(x, wd)
    if b == 0:
        return w
    nxt = _lane_down(x, wd + 1)
    return (w >> b) | (nxt << (32 - b))


def _fill_kernel(x_ref, s_ref, o_ref, *, nbits):
    x = x_ref[...]
    s = s_ref[...]
    y = x
    nb = ~s
    d = 1
    while d < nbits:
        y = y | (nb & _up2(y, d))
        nb = nb & _up2(nb, d)
        d <<= 1
    nbd = _down2(~s, 1)
    d = 1
    while d < nbits:
        y = y | (nbd & _down2(y, d))
        nbd = nbd & _down2(nbd, d)
        d <<= 1
    o_ref[...] = y


def seg_or_fill_pallas(x: jax.Array, starts: jax.Array,
                       interpret: bool = False) -> jax.Array:
    """seg_or_fill_bits as one VMEM-resident Pallas step. ``x``,
    ``starts``: (nwords,) uint32 with nwords a multiple of 128."""
    import functools
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from combblas_tpu.ops.route import _sds

    nwords = int(x.shape[0])
    r = nwords // 128
    kernel = functools.partial(_fill_kernel, nbits=nwords * 32)
    out = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=_sds((r, 128), jnp.uint32, x),
        interpret=interpret,
    )(x.reshape(r, 128), starts.reshape(r, 128))
    return out.reshape(-1)


def seg_or_fill_best(x: jax.Array, starts: jax.Array) -> jax.Array:
    """Dispatch: Pallas on TPU when the layout allows, else XLA."""
    from combblas_tpu.ops import pallas_kernels as pk
    if pk.enabled() and x.shape[0] % 128 == 0 and x.shape[0] >= 128:
        return seg_or_fill_pallas(x, starts)
    return seg_or_fill_bits(x, starts)


def row_end_bits(y: jax.Array, starts: jax.Array, nbits: int) -> jax.Array:
    """Bits of ``y`` at segment END slots (slot before the next start,
    or the final valid slot), other slots zeroed. ``nbits`` = number
    of live slots (the rest is padding). Used by the mesh variant of
    the edge-space BFS, where per-tile row results must be extracted
    to vertex space before the cross-tile OR (single-tile BFS stays
    in edge space and never needs it)."""
    nxt_start = _shift_down(starts, 1)
    # the last live slot ends its segment too
    w, b = (nbits - 1) // 32, (nbits - 1) % 32
    lastmask = jnp.zeros_like(y).at[w].set(jnp.uint32(1 << b))
    return y & (nxt_start | lastmask)
