"""Word-parallel segmented OR scans over packed bit vectors.

Capability parity: the per-edge frontier/visited bookkeeping that the
reference keeps in BitMap/BitMapFringe words (BitMap.h:1-168,
BitMapFringe.h:41) and updates with word-level operations inside its
bottom-up step (BFSFriends.h:458). TPU-native redesign: the BFS
dense phase (models/bfs.py) keeps ALL per-edge state as 32x-packed
bits and needs two primitives over them — an inclusive segmented OR
scan (propagate "some neighbor is active" to each row's end slot) and
its backward twin (fill the whole row run with the row's final bit).
Both are Kogge-Stone prefix networks on (value, no-boundary) bit
pairs: log2(n) stages of pure shift/AND/OR word arithmetic — no
gather, no scatter, no per-element work.

Layout: a bit vector of npad = 32 * nwords slots as (nwords,) uint32,
little-endian bit order (bit i of word w = slot 32w + i), matching
ops/route.py pack_bits. Segment STARTS are marked in a static packed
flag vector (bit set = this slot begins a new segment).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _shift_up(x: jax.Array, d: int) -> jax.Array:
    """Packed shift toward higher slot indices by d bits (zeros in);
    slot i of the result = slot i-d of x."""
    wd, bd = d // 32, d % 32
    if wd:
        x = jnp.concatenate([jnp.zeros((wd,), x.dtype), x[:-wd]])
    if bd:
        prev = jnp.concatenate([jnp.zeros((1,), x.dtype), x[:-1]])
        x = (x << bd) | (prev >> (32 - bd))
    return x


def _shift_down(x: jax.Array, d: int) -> jax.Array:
    """Packed shift toward lower slot indices: slot i = slot i+d of x."""
    wd, bd = d // 32, d % 32
    if wd:
        x = jnp.concatenate([x[wd:], jnp.zeros((wd,), x.dtype)])
    if bd:
        nxt = jnp.concatenate([x[1:], jnp.zeros((1,), x.dtype)])
        x = (x >> bd) | (nxt << (32 - bd))
    return x


def seg_or_scan_bits(x: jax.Array, starts: jax.Array) -> jax.Array:
    """Inclusive segmented OR scan: out bit i = OR of x over
    [segment_start(i), i]. ``x``/``starts``: (nwords,) uint32."""
    n = int(x.shape[0]) * 32
    y = x
    nb = ~starts                      # "no boundary at this slot"
    d = 1
    while d < n:
        y = y | (nb & _shift_up(y, d))
        nb = nb & _shift_up(nb, d)
        d <<= 1
    return y


def seg_or_fill_bits(x: jax.Array, starts: jax.Array) -> jax.Array:
    """Segment-wide OR: out bit i = OR of x over i's WHOLE segment
    (forward scan, then a backward OR-prefix blocked at starts — the
    segment end's total flows down over every slot of its segment)."""
    n = int(x.shape[0]) * 32
    y = seg_or_scan_bits(x, starts)
    nb = _shift_down(~starts, 1)      # no start in (i, i+1]
    d = 1
    while d < n:
        y = y | (nb & _shift_down(y, d))
        nb = nb & _shift_down(nb, d)  # no start in (i, i+2d]
        d <<= 1
    return y


# --------------------------------------------------------------------------
# Pallas fused kernel: both scans of seg_or_fill_bits in ONE grid step
# with everything VMEM-resident — the Kogge-Stone stages are pure VPU
# compute, so HBM traffic is just x + starts in, result out.
# Works on the (R, 128) word layout (flat word w = (w // 128, w % 128)).
# --------------------------------------------------------------------------

def _roll(x, shift, axis):
    """Rotate, preferring the hardware roll inside Mosaic kernels —
    concatenate-based shifts make Mosaic compile time explode with the
    sublane extent (hours at 2^27 slots), a single tpu rotate stays
    flat."""
    try:
        from jax.experimental.pallas import tpu as pltpu
        roll = pltpu.roll
    except (ImportError, AttributeError):   # API drift: make it LOUD
        raise RuntimeError(
            "pltpu.roll disappeared from this JAX version; the blocked "
            "bit kernels depend on the hardware roll (concatenate-based "
            "shifts take Mosaic hours to compile at 2^26+ slots)")
    if isinstance(shift, int) and shift < 0:
        shift += x.shape[axis]      # pltpu.roll wants non-negative
    return roll(x, shift, axis)


def _rows_shift(x, k, down: bool):
    """Shift rows of (R, 128) by k (zeros shifted in). down=True moves
    row r-k's data to row r (toward higher flat order)."""
    if k == 0:
        return x
    r = x.shape[0]
    if k >= r:
        return jnp.zeros_like(x)
    row = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], 1), 0)
    if down:
        return jnp.where(row >= k, _roll(x, k, 0), 0)
    return jnp.where(row < r - k, _roll(x, -k, 0), 0)


def _lane_up(x, wd):
    """Word shift toward higher flat index by wd words on (R, 128)."""
    rs, ls = wd // 128, wd % 128
    base = _rows_shift(x, rs, True)
    if ls == 0:
        return base
    carry = _rows_shift(x, rs + 1, True)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, x.shape[1]), 1)
    return jnp.where(lane >= ls, _roll(base, ls, 1),
                     _roll(carry, ls, 1))


def _lane_down(x, wd):
    """Word shift toward lower flat index by wd words on (R, 128)."""
    rs, ls = wd // 128, wd % 128
    base = _rows_shift(x, rs, False)
    if ls == 0:
        return base
    carry = _rows_shift(x, rs + 1, False)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, x.shape[1]), 1)
    return jnp.where(lane < x.shape[1] - ls,
                     _roll(base, -ls, 1),
                     _roll(carry, -ls, 1))


def _up2(x, d):
    """Bit shift toward higher flat slot index by d on (R, 128)."""
    wd, b = d // 32, d % 32
    w = _lane_up(x, wd)
    if b == 0:
        return w
    prev = _lane_up(x, wd + 1)
    return (w << b) | (prev >> (32 - b))


def _down2(x, d):
    """Bit shift toward lower flat slot index by d on (R, 128)."""
    wd, b = d // 32, d % 32
    w = _lane_down(x, wd)
    if b == 0:
        return w
    nxt = _lane_down(x, wd + 1)
    return (w >> b) | (nxt << (32 - b))


_BLR = 512     # rows per streamed block: keeps every in-kernel roll
#                distance small so Mosaic compile time stays flat in the
#                total size (full-array rolls at 2^27 slots took Mosaic
#                over an hour; blocked kernels compile in seconds)


def _block_or_scan(x, s, nbits_blk, up: bool):
    """In-block segmented OR scan (inclusive) plus the block's
    carry-admission mask M (bit i set = no segment boundary between
    the block's entry edge and slot i). up=False is the mirrored
    backward scan (entry edge = the block's last slot)."""
    shift = _up2 if up else _down2
    y = x
    nb = ~s if up else shift(~s, 1)
    d = 1
    while d < nbits_blk:
        y = y | (nb & shift(y, d))
        nb = nb & shift(nb, d)
        d <<= 1
    # forward: a start AT slot i blocks the incoming carry at i;
    # backward: a start at slot i+1 blocks carry descending into i
    blockers = s if up else shift(s, 1)
    cov = blockers
    d = 1
    while d < nbits_blk:
        cov = cov | shift(cov, d)
        d <<= 1
    return y, ~cov


def _fill_fwd_kernel(x_ref, s_ref, o_ref, carry_ref, *, nbits_blk):
    import jax.experimental.pallas as pl

    t = pl.program_id(0)
    x = x_ref[...]
    s = s_ref[...]
    y, m = _block_or_scan(x, s, nbits_blk, up=True)

    @pl.when(t == 0)
    def _init():
        carry_ref[...] = jnp.zeros_like(carry_ref)

    y = y | (m & carry_ref[0, 0])
    o_ref[...] = y
    last = y[-1:, -1:] >> 31               # bit 31 of the final word
    carry_ref[...] = jnp.where(last > 0, jnp.uint32(0xFFFFFFFF),
                               jnp.uint32(0))


def _fill_bwd_kernel(y_ref, s_ref, o_ref, carry_ref, *, nbits_blk):
    import jax.experimental.pallas as pl

    t = pl.program_id(0)
    y0 = y_ref[...]
    s = s_ref[...]
    y, m = _block_or_scan(y0, s, nbits_blk, up=False)

    @pl.when(t == 0)
    def _init():
        carry_ref[...] = jnp.zeros_like(carry_ref)

    y = y | (m & carry_ref[0, 0])
    o_ref[...] = y
    # carry down across the boundary: the first slot's value, unless
    # that slot itself starts a segment
    first = (y[0:1, 0:1] & ~s[0:1, 0:1]) & jnp.uint32(1)
    carry_ref[...] = jnp.where(first > 0, jnp.uint32(0xFFFFFFFF),
                               jnp.uint32(0))


def _fill_blocking(x: jax.Array, starts: jax.Array, *extras):
    """Shared block-layout setup of the streamed fill passes:
    (r, 128) views padded to whole blocks — pads are inert
    (self-segmenting starts=all-ones, zero data). Returns
    (blr, nblk, padr, r, nbits_blk, x2, s2, *extras2)."""
    nwords = int(x.shape[0])
    r = nwords // 128
    blr = min(_BLR, r)
    nblk = -(-r // blr)
    padr = nblk * blr
    arrs = [x.reshape(r, 128), starts.reshape(r, 128)] + [
        e.reshape(r, 128) for e in extras]
    if padr != r:
        pads = [0, 0xFFFFFFFF] + [0] * len(extras)
        arrs = [jnp.pad(a, ((0, padr - r), (0, 0)),
                        constant_values=jnp.uint32(p))
                for a, p in zip(arrs, pads)]
    return (blr, nblk, padr, r, blr * 128 * 32, *arrs)


def _fill_fwd_call(blr, nblk, padr, nbits_blk, x2, s2, like,
                   interpret):
    """The forward fill pass launch, shared by the plain and the
    BFS-fused fills."""
    import functools
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from combblas_tpu.ops.route import _sds

    return pl.pallas_call(
        functools.partial(_fill_fwd_kernel, nbits_blk=nbits_blk),
        grid=(nblk,),
        in_specs=[pl.BlockSpec((blr, 128), lambda t: (t, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((blr, 128), lambda t: (t, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((blr, 128), lambda t: (t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_sds((padr, 128), jnp.uint32, like),
        scratch_shapes=[pltpu.VMEM((1, 1), jnp.uint32)],
        interpret=interpret,
    )(x2, s2)


def seg_or_fill_pallas(x: jax.Array, starts: jax.Array,
                       interpret: bool = False) -> jax.Array:
    """seg_or_fill_bits as two block-streamed Pallas passes: forward
    segmented scan, then the backward fill with the grid iterated in
    reverse block order (the index_map flips). A (1, 1) carry word in
    scratch stitches blocks. ``x``, ``starts``: (nwords,) uint32 with
    nwords a multiple of 128."""
    import functools
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from combblas_tpu.ops.route import _sds

    blr, nblk, padr, r, nbits_blk, x2, s2 = _fill_blocking(x, starts)
    fwd = _fill_fwd_call(blr, nblk, padr, nbits_blk, x2, s2, x,
                         interpret)

    bwd = pl.pallas_call(
        functools.partial(_fill_bwd_kernel, nbits_blk=nbits_blk),
        grid=(nblk,),
        in_specs=[pl.BlockSpec((blr, 128),
                               lambda t, n=nblk: (n - 1 - t, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((blr, 128),
                               lambda t, n=nblk: (n - 1 - t, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((blr, 128),
                               lambda t, n=nblk: (n - 1 - t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_sds((padr, 128), jnp.uint32, x),
        scratch_shapes=[pltpu.VMEM((1, 1), jnp.uint32)],
        interpret=interpret,
    )(fwd, s2)
    return bwd[:r].reshape(-1)


def seg_or_fill_best(x: jax.Array, starts: jax.Array) -> jax.Array:
    """Dispatch: Pallas on TPU when the layout allows, else XLA."""
    from combblas_tpu.ops import pallas_kernels as pk
    if pk.enabled() and x.shape[0] % 128 == 0 and x.shape[0] >= 128:
        return seg_or_fill_pallas(x, starts)
    return seg_or_fill_bits(x, starts)


def _fill_bwd_bfs_kernel(y_ref, s_ref, vb_ref, vis_ref, pc_ref, hit_ref,
                         n2_ref, vis2_ref, pc2_ref, flag_ref, carry_ref,
                         *, nbits_blk):
    """Backward fill pass fused with the BFS level tail: from the
    forward-scanned hit bits, per block compute
      filled  = segment-wide OR (backward pass of seg_or_fill)
      new2    = filled & ~visited & vb
      visited' = visited | new2;  pcand' = pcand | (hit & new2)
      flag   |= any(new2)
    — one kernel launch instead of the ~6 elementwise XLA kernels the
    unfused level body dispatches (launch overhead dominated the
    level: measured 1.37 ms of glue vs 0.44 ms of route+fill at
    scale 20)."""
    import jax.experimental.pallas as pl

    t = pl.program_id(0)
    y0 = y_ref[...]
    s = s_ref[...]
    y, m = _block_or_scan(y0, s, nbits_blk, up=False)

    @pl.when(t == 0)
    def _init():
        carry_ref[...] = jnp.zeros_like(carry_ref)
        flag_ref[...] = jnp.zeros_like(flag_ref)

    filled = y | (m & carry_ref[0, 0])
    first = (filled[0:1, 0:1] & ~s[0:1, 0:1]) & jnp.uint32(1)
    carry_ref[...] = jnp.where(first > 0, jnp.uint32(0xFFFFFFFF),
                               jnp.uint32(0))
    new2 = filled & ~vis_ref[...] & vb_ref[...]
    n2_ref[...] = new2
    vis2_ref[...] = vis_ref[...] | new2
    pc2_ref[...] = pc_ref[...] | (hit_ref[...] & new2)
    anyb = jnp.any(new2 != 0)      # bool reduce (Mosaic rejects
    #                                unsigned-int reductions)
    flag_ref[...] = flag_ref[...] | jnp.where(anyb, jnp.uint32(1),
                                              jnp.uint32(0))


def seg_or_fill_bfs_pallas(hit: jax.Array, starts: jax.Array,
                           vb: jax.Array, visited: jax.Array,
                           pcand: jax.Array, interpret: bool = False):
    """The edge-space BFS level tail as two Pallas launches: the
    standard forward fill pass, then `_fill_bwd_bfs_kernel`. Returns
    (new2, visited', pcand', flag) with flag a uint32 scalar-shaped
    (1,1) array, nonzero iff the new frontier is nonempty (replaces
    the cond's full-array jnp.any pass)."""
    import functools
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from combblas_tpu.ops.route import _sds

    (blr, nblk, padr, r, nbits_blk,
     h2, s2, vb2, vis2, pc2) = _fill_blocking(hit, starts, vb,
                                              visited, pcand)
    fwd = _fill_fwd_call(blr, nblk, padr, nbits_blk, h2, s2, hit,
                         interpret)

    rev = pl.BlockSpec((blr, 128), lambda t, n=nblk: (n - 1 - t, 0),
                       memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_fill_bwd_bfs_kernel, nbits_blk=nbits_blk),
        grid=(nblk,),
        in_specs=[rev] * 6,
        out_specs=(rev, rev, rev,
                   pl.BlockSpec((1, 1), lambda t: (0, 0),
                                memory_space=pltpu.VMEM)),
        out_shape=(_sds((padr, 128), jnp.uint32, hit),
                   _sds((padr, 128), jnp.uint32, hit),
                   _sds((padr, 128), jnp.uint32, hit),
                   _sds((1, 1), jnp.uint32, hit)),
        scratch_shapes=[pltpu.VMEM((1, 1), jnp.uint32)],
        interpret=interpret,
    )(fwd, s2, vb2, vis2, pc2, h2)
    new2, visited2, pcand2, flag = out
    return (new2[:r].reshape(-1), visited2[:r].reshape(-1),
            pcand2[:r].reshape(-1), flag)


def _iso_bwd_kernel(pc_ref, s_ref, o_ref, carry_ref, *, nbits_blk):
    """Reverse-streamed pass isolating each segment's HIGHEST set bit:
    iso = x & ~(backward-EXCLUSIVE segment OR). carry: the open
    segment's OR entering from the right."""
    import jax.experimental.pallas as pl

    t = pl.program_id(0)
    x = pc_ref[...]
    s = s_ref[...]

    @pl.when(t == 0)
    def _init():
        carry_ref[0, 0] = jnp.uint32(0)

    carry_in = carry_ref[0, 0]
    y, m = _block_or_scan(x, s, nbits_blk, up=False)
    y = y | (m & carry_in)
    # exclusive = inclusive of the NEXT slot (segment-blocked). The
    # block's very last slot has no in-block next: its cross-block
    # "set bits strictly to the right" is carry_in under the
    # open-segment admission mask.
    blr = x.shape[0]
    rowi = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    lanei = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    lastw = jnp.where((rowi == blr - 1) & (lanei == 127),
                      jnp.uint32(0x80000000), jnp.uint32(0))
    excl = (_down2(y, 1) & ~_down2(s, 1)) | (m & carry_in & lastw)
    o_ref[...] = x & ~excl
    first_open = (y[0, 0] & ~s[0, 0]) & jnp.uint32(1)
    carry_ref[0, 0] = jnp.where(first_open > 0, jnp.uint32(0xFFFFFFFF),
                                jnp.uint32(0))


def _plane_fill_kernel(iso_ref, s_ref, cb_ref, o_ref, carry_ref, *,
                       nbits_blk):
    """Backward-inclusive segment OR of (iso & colbit_plane), one
    (plane, block) grid cell at a time — at every segment START slot
    the output bit equals the plane's bit of the segment's isolated
    (maximum) column. Grid = (nplanes, nblk) with blocks reverse-
    streamed within each plane; the carry resets per plane."""
    import jax.experimental.pallas as pl

    tb = pl.program_id(1)

    @pl.when(tb == 0)
    def _init():
        carry_ref[0, 0] = jnp.uint32(0)

    x = iso_ref[...] & cb_ref[0]
    s = s_ref[...]
    cin = carry_ref[0, 0]
    y, m = _block_or_scan(x, s, nbits_blk, up=False)
    y = y | (m & cin)
    o_ref[0] = y
    fo = (y[0, 0] & ~s[0, 0]) & jnp.uint32(1)
    carry_ref[0, 0] = jnp.where(fo > 0, jnp.uint32(0xFFFFFFFF),
                                jnp.uint32(0))


def parent_planes_pallas(pcand: jax.Array, starts: jax.Array,
                         colbits: jax.Array,
                         interpret: bool = False) -> jax.Array:
    """(nbits+1, nwords): backward-filled parent-column bitplanes.
    ``colbits``: (nbits, nwords) static column-id bitplanes in flat
    row-sorted edge order (bit at slot i of plane b = bit b of
    cols[i]). Output plane b < nbits carries, at each row's start
    slot, bit b of the row's maximum pcand-marked column; the last
    plane carries "row has any candidate". All other slots are
    row-constant fill (harmless — the start-compact route reads only
    start slots). Two kernels (iso, then a (plane, block) grid) so
    each body holds ONE scan network — a 23-plane unrolled body
    crashed the TPU compiler. Gather-free by construction: the caller
    routes start-slot bits to row positions with a precompiled Beneš
    permutation instead of gathering per row (measured 73 ms for a
    4M-row gather — routes are ~1 ms)."""
    import functools
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from combblas_tpu.ops.route import _sds

    nbits = int(colbits.shape[0])
    nplanes = nbits + 1
    blr, nblk, padr, r, nbits_blk, x2, s2 = _fill_blocking(pcand, starts)
    cb = colbits.reshape(nbits, r, 128)
    # plane nbits is "iso itself": append an all-ones plane
    cb = jnp.concatenate(
        [cb, jnp.full((1, r, 128), 0xFFFFFFFF, jnp.uint32)])
    if padr != r:
        cb = jnp.pad(cb, ((0, 0), (0, padr - r), (0, 0)))
    rev = pl.BlockSpec((blr, 128), lambda t, n=nblk: (n - 1 - t, 0),
                       memory_space=pltpu.VMEM)
    iso = pl.pallas_call(
        functools.partial(_iso_bwd_kernel, nbits_blk=nbits_blk),
        grid=(nblk,),
        in_specs=[rev, rev],
        out_specs=rev,
        out_shape=_sds((padr, 128), jnp.uint32, pcand),
        scratch_shapes=[pltpu.SMEM((1, 1), jnp.uint32)],
        interpret=interpret,
    )(x2, s2)

    rev2 = pl.BlockSpec((blr, 128), lambda p, t, n=nblk: (n - 1 - t, 0),
                        memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_plane_fill_kernel, nbits_blk=nbits_blk),
        grid=(nplanes, nblk),
        in_specs=[rev2, rev2,
                  pl.BlockSpec((1, blr, 128),
                               lambda p, t, n=nblk: (p, n - 1 - t, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, blr, 128),
                               lambda p, t, n=nblk: (p, n - 1 - t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_sds((nplanes, padr, 128), jnp.uint32, pcand),
        scratch_shapes=[pltpu.SMEM((1, 1), jnp.uint32)],
        interpret=interpret,
    )(iso, s2, cb)
    return out[:, :r].reshape(nplanes, -1)


# --------------------------------------------------------------------------
# Multi-lane variants: the same segmented OR networks over a bitplane
# MATRIX (nwords, W) — lane w is an independent packed bit vector (one
# BFS root's frontier in the batched traversal), all lanes sharing ONE
# static segment layout. The Kogge-Stone stages broadcast the (nwords,)
# no-boundary masks over the lane axis, so W roots cost one wave of
# word arithmetic instead of W scans.
# --------------------------------------------------------------------------

def _shift_up_multi(x: jax.Array, d: int) -> jax.Array:
    """_shift_up along axis 0 of an (nwords, W) lane matrix."""
    wd, bd = d // 32, d % 32
    if wd:
        x = jnp.concatenate(
            [jnp.zeros((wd,) + x.shape[1:], x.dtype), x[:-wd]])
    if bd:
        prev = jnp.concatenate(
            [jnp.zeros((1,) + x.shape[1:], x.dtype), x[:-1]])
        x = (x << bd) | (prev >> (32 - bd))
    return x


def _shift_down_multi(x: jax.Array, d: int) -> jax.Array:
    """_shift_down along axis 0 of an (nwords, W) lane matrix."""
    wd, bd = d // 32, d % 32
    if wd:
        x = jnp.concatenate(
            [x[wd:], jnp.zeros((wd,) + x.shape[1:], x.dtype)])
    if bd:
        nxt = jnp.concatenate(
            [x[1:], jnp.zeros((1,) + x.shape[1:], x.dtype)])
        x = (x >> bd) | (nxt << (32 - bd))
    return x


def seg_or_scan_bits_multi(x: jax.Array, starts: jax.Array) -> jax.Array:
    """Lane-parallel inclusive segmented OR scan. ``x``: (nwords, W)
    uint32 lane matrix; ``starts``: (nwords,) shared segment starts."""
    n = int(x.shape[0]) * 32
    y = x
    nb = (~starts)[:, None]           # shared mask, (nwords, 1)
    d = 1
    while d < n:
        y = y | (nb & _shift_up_multi(y, d))
        nb = nb & _shift_up_multi(nb, d)
        d <<= 1
    return y


def seg_or_fill_bits_multi(x: jax.Array, starts: jax.Array) -> jax.Array:
    """Lane-parallel segment-wide OR (seg_or_fill_bits over every lane
    of an (nwords, W) matrix in one pass)."""
    n = int(x.shape[0]) * 32
    y = seg_or_scan_bits_multi(x, starts)
    nb = _shift_down_multi((~starts)[:, None], 1)
    d = 1
    while d < n:
        y = y | (nb & _shift_down_multi(y, d))
        nb = nb & _shift_down_multi(nb, d)
        d <<= 1
    return y


def _fill_fwd_multi_kernel(x_ref, s_ref, o_ref, carry_ref, *, nbits_blk):
    """Forward fill pass on a (lane, block) grid cell. Blocks stream
    innermost (the TPU grid iterates the LAST dim fastest), so the
    carry word is sequential within each lane and resets at each
    lane's first block."""
    import jax.experimental.pallas as pl

    t = pl.program_id(1)
    x = x_ref[0]
    s = s_ref[...]
    y, m = _block_or_scan(x, s, nbits_blk, up=True)

    @pl.when(t == 0)
    def _init():
        carry_ref[0, 0] = jnp.uint32(0)

    y = y | (m & carry_ref[0, 0])
    o_ref[0] = y
    last = y[-1, -1] >> 31             # bit 31 of the final word
    carry_ref[0, 0] = jnp.where(last > 0, jnp.uint32(0xFFFFFFFF),
                                jnp.uint32(0))


def _fill_bwd_multi_kernel(y_ref, s_ref, o_ref, carry_ref, *, nbits_blk):
    """Backward fill pass on a (lane, block) grid cell (blocks arrive
    reverse-streamed via the index map); per-lane carry reset."""
    import jax.experimental.pallas as pl

    t = pl.program_id(1)
    y0 = y_ref[0]
    s = s_ref[...]
    y, m = _block_or_scan(y0, s, nbits_blk, up=False)

    @pl.when(t == 0)
    def _init():
        carry_ref[0, 0] = jnp.uint32(0)

    y = y | (m & carry_ref[0, 0])
    o_ref[0] = y
    first = (y[0, 0] & ~s[0, 0]) & jnp.uint32(1)
    carry_ref[0, 0] = jnp.where(first > 0, jnp.uint32(0xFFFFFFFF),
                                jnp.uint32(0))


def seg_or_fill_multi_pallas(x: jax.Array, starts: jax.Array,
                             interpret: bool = False) -> jax.Array:
    """seg_or_fill_bits_multi as two block-streamed Pallas passes on a
    (W, nblk) grid — one launch serves every lane, with the shared
    ``starts`` block fetched once per grid cell. ``x``: (nwords, W)
    with nwords a multiple of 128; ``starts``: (nwords,)."""
    import functools
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from combblas_tpu.ops.route import _sds

    nwords, w = int(x.shape[0]), int(x.shape[1])
    r = nwords // 128
    blr = min(_BLR, r)
    nblk = -(-r // blr)
    padr = nblk * blr
    x3 = x.T.reshape(w, r, 128)
    s2 = starts.reshape(r, 128)
    if padr != r:
        x3 = jnp.pad(x3, ((0, 0), (0, padr - r), (0, 0)))
        s2 = jnp.pad(s2, ((0, padr - r), (0, 0)),
                     constant_values=jnp.uint32(0xFFFFFFFF))
    nbits_blk = blr * 128 * 32

    lane = pl.BlockSpec((1, blr, 128), lambda p, t: (p, t, 0),
                        memory_space=pltpu.VMEM)
    shared = pl.BlockSpec((blr, 128), lambda p, t: (t, 0),
                          memory_space=pltpu.VMEM)
    fwd = pl.pallas_call(
        functools.partial(_fill_fwd_multi_kernel, nbits_blk=nbits_blk),
        grid=(w, nblk),
        in_specs=[lane, shared],
        out_specs=lane,
        out_shape=_sds((w, padr, 128), jnp.uint32, x),
        scratch_shapes=[pltpu.SMEM((1, 1), jnp.uint32)],
        interpret=interpret,
    )(x3, s2)

    lane_r = pl.BlockSpec((1, blr, 128),
                          lambda p, t, n=nblk: (p, n - 1 - t, 0),
                          memory_space=pltpu.VMEM)
    shared_r = pl.BlockSpec((blr, 128),
                            lambda p, t, n=nblk: (n - 1 - t, 0),
                            memory_space=pltpu.VMEM)
    bwd = pl.pallas_call(
        functools.partial(_fill_bwd_multi_kernel, nbits_blk=nbits_blk),
        grid=(w, nblk),
        in_specs=[lane_r, shared_r],
        out_specs=lane_r,
        out_shape=_sds((w, padr, 128), jnp.uint32, x),
        scratch_shapes=[pltpu.SMEM((1, 1), jnp.uint32)],
        interpret=interpret,
    )(fwd, s2)
    return bwd[:, :r].reshape(w, -1).T


def seg_or_fill_multi_best(x: jax.Array, starts: jax.Array) -> jax.Array:
    """Dispatch: Pallas on TPU when the layout allows, else XLA."""
    from combblas_tpu.ops import pallas_kernels as pk
    if pk.enabled() and x.shape[0] % 128 == 0 and x.shape[0] >= 128:
        return seg_or_fill_multi_pallas(x, starts)
    return seg_or_fill_bits_multi(x, starts)


def row_end_bits(y: jax.Array, starts: jax.Array, nbits: int) -> jax.Array:
    """Bits of ``y`` at segment END slots (slot before the next start,
    or the final valid slot), other slots zeroed. ``nbits`` = number
    of live slots (the rest is padding). Used by the mesh variant of
    the edge-space BFS, where per-tile row results must be extracted
    to vertex space before the cross-tile OR (single-tile BFS stays
    in edge space and never needs it)."""
    nxt_start = _shift_down(starts, 1)
    # the last live slot ends its segment too
    w, b = (nbits - 1) // 32, (nbits - 1) % 32
    lastmask = jnp.zeros_like(y).at[w].set(jnp.uint32(1 << b))
    return y & (nxt_start | lastmask)
