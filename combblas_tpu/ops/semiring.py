"""Semirings and monoids as JAX-traceable operator bundles.

Capability parity with the reference's algebra layer:
  * semiring structs with add/multiply/identity — Semirings.h:51-257
  * functor library mapped to reduction ops     — Operations.h:46-301
  * functor -> MPI_Op mapping (MPIOp.h:68)      — here: monoid ->
    per-mesh-axis collective (psum/pmax/pmin) + segment reduction.

The TPU-native re-design: instead of C++ templates instantiated per
semiring, a `Semiring` is a pytree-free dataclass of pure functions that
JAX traces straight into the local kernels (tile.py) and into the
shard_map collectives (parallel/*). A monoid carries three execution
strategies, all semantically `fold(combine, identity, ...)`:

  - ``combine(a, b)``         scalar/elementwise combine (traced)
  - ``segment_reduce(...)``   within-tile reduction keyed by row/col id
  - ``axis_reduce(x, axis_name)`` cross-device reduction along a mesh axis

Known monoids (plus/min/max/or/and) dispatch to XLA's native
segment/collective primitives; arbitrary user monoids fall back to a
sorted-scan segment reduction and an all_gather+fold collective, so user
extensibility (the reference's headline feature) is preserved without
giving up fused fast paths.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


Array = jax.Array
_NEG_INF = float("-inf")
_POS_INF = float("inf")


def _identity_array(value, dtype):
    """Identity element as a scalar of the right dtype (inf -> dtype max)."""
    dtype = jnp.dtype(dtype)
    if value == _POS_INF:
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.array(jnp.inf, dtype)
        return jnp.array(jnp.iinfo(dtype).max, dtype)
    if value == _NEG_INF:
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.array(-jnp.inf, dtype)
        return jnp.array(jnp.iinfo(dtype).min, dtype)
    return jnp.array(value, dtype)


@dataclasses.dataclass(frozen=True)
class Monoid:
    """A commutative monoid (combine, identity) with fused fast paths.

    ``kind`` selects XLA-native implementations for the five standard
    monoids; kind=None means "user monoid": correct generic fallbacks.
    """

    name: str
    combine: Callable[[Array, Array], Array]
    identity_value: Any                    # python scalar (may be +-inf)
    kind: Optional[str] = None             # "add"|"min"|"max"|"or"|"and"|None
    # Optional semantic hint: combine is idempotent (a+a == a). True for
    # min/max/or/and; lets some algorithms skip dedup passes.
    idempotent: bool = False

    # -- scalar/elementwise ------------------------------------------------
    def identity(self, dtype) -> Array:
        return _identity_array(self.identity_value, dtype)

    def identity_scalar(self, dtype):
        """Identity as a PYTHON scalar (inf -> dtype extremum). Safe
        to call inside jit/shard_map traces — unlike `identity`, which
        stages a device constant — so kernels (e.g. the Pallas scan)
        can bake it in as a compile-time literal."""
        dtype = jnp.dtype(dtype)
        v = self.identity_value
        if v == _POS_INF and not jnp.issubdtype(dtype, jnp.floating):
            return int(jnp.iinfo(dtype).max)
        if v == _NEG_INF and not jnp.issubdtype(dtype, jnp.floating):
            return int(jnp.iinfo(dtype).min)
        if dtype == jnp.bool_:
            return bool(v)
        if jnp.issubdtype(dtype, jnp.integer):
            return int(v)
        return float(v)

    def fill(self, shape, dtype) -> Array:
        return jnp.full(shape, self.identity(dtype), dtype)

    # -- within-tile: segment reduction ------------------------------------
    def segment_reduce(self, data: Array, segment_ids: Array,
                       num_segments: int, *, sorted_ids: bool = False) -> Array:
        """fold(combine) of ``data`` grouped by ``segment_ids``.

        Out-of-range ids (e.g. padding pointed at ``num_segments``) are
        dropped. Segments with no contribution hold the identity.
        """
        if self.kind == "add":
            # jax segment_sum fills empty segments with 0 == identity.
            return jax.ops.segment_sum(
                data, segment_ids, num_segments,
                indices_are_sorted=sorted_ids)
        if self.kind == "max":
            out = jax.ops.segment_max(
                data, segment_ids, num_segments,
                indices_are_sorted=sorted_ids)
            return out  # segment_max fills empties with dtype min == identity
        if self.kind == "min":
            return jax.ops.segment_min(
                data, segment_ids, num_segments,
                indices_are_sorted=sorted_ids)
        if self.kind == "or":
            # segment_max fills empty segments with int32 min; compare > 0
            # (not astype) so empties land on the OR identity False.
            out = jax.ops.segment_max(
                data.astype(jnp.int32), segment_ids, num_segments,
                indices_are_sorted=sorted_ids)
            return (out > 0).astype(data.dtype)
        if self.kind == "and":
            # empty segments fill with int32 max -> True == AND identity
            out = jax.ops.segment_min(
                data.astype(jnp.int32), segment_ids, num_segments,
                indices_are_sorted=sorted_ids)
            return (out > 0).astype(data.dtype)
        return self._segment_reduce_generic(data, segment_ids, num_segments,
                                            sorted_ids=sorted_ids)

    def _segment_reduce_generic(self, data, segment_ids, num_segments, *,
                                sorted_ids):
        """Sorted segmented scan for arbitrary user monoids."""
        if not sorted_ids:
            order = jnp.argsort(segment_ids)
            segment_ids = segment_ids[order]
            data = data[order]
        n = data.shape[0]
        starts = jnp.concatenate(
            [jnp.ones((1,), bool), segment_ids[1:] != segment_ids[:-1]])

        def scan_op(a, b):
            a_start, a_val = a
            b_start, b_val = b
            val = jnp.where(b_start, b_val, self.combine(a_val, b_val))
            return (a_start | b_start, val)

        _, acc = lax.associative_scan(scan_op, (starts, data))
        is_last = jnp.concatenate(
            [segment_ids[:-1] != segment_ids[1:], jnp.ones((1,), bool)])
        # scatter segment tails; drop out-of-range (padding) segments
        tgt = jnp.where(is_last, segment_ids, num_segments)
        out = self.fill((num_segments,), data.dtype)
        return out.at[tgt].set(acc, mode="drop")

    # -- whole-array reduction --------------------------------------------
    def reduce(self, data: Array, axis=None) -> Array:
        if self.kind == "add":
            return jnp.sum(data, axis=axis)
        if self.kind == "max":
            return jnp.max(data, axis=axis)
        if self.kind == "min":
            return jnp.min(data, axis=axis)
        if self.kind == "or":
            return jnp.max(data, axis=axis)
        if self.kind == "and":
            return jnp.min(data, axis=axis)
        flat = jnp.moveaxis(data, axis, -1) if axis is not None else data.ravel()
        return lax.reduce(flat, self.identity(data.dtype),
                          self.combine, (flat.ndim - 1,))

    # -- cross-device: mesh-axis collective (the MPIOp analogue) -----------
    def axis_reduce(self, x: Array, axis_name) -> Array:
        if self.kind == "add":
            return lax.psum(x, axis_name)
        if self.kind in ("max", "or"):
            return lax.pmax(x, axis_name)
        if self.kind in ("min", "and"):
            return lax.pmin(x, axis_name)
        gathered = lax.all_gather(x, axis_name)  # (axis_size, ...)
        return lax.reduce(gathered, self.identity(x.dtype),
                          self.combine, (0,))


# ---------------------------------------------------------------------------
# Standard monoids (Operations.h functor library equivalents)
# ---------------------------------------------------------------------------

PLUS = Monoid("plus", lax.add, 0, kind="add")
TIMES_MONOID = Monoid("times", lax.mul, 1)
MIN = Monoid("min", lax.min, _POS_INF, kind="min", idempotent=True)
MAX = Monoid("max", lax.max, _NEG_INF, kind="max", idempotent=True)
LOR = Monoid("lor", jnp.logical_or, False, kind="or", idempotent=True)
LAND = Monoid("land", jnp.logical_and, True, kind="and", idempotent=True)


@dataclasses.dataclass(frozen=True)
class Semiring:
    """(add-monoid, multiply) with identity annihilation.

    Contract (≅ the reference's semiring concept, Semirings.h): ``add`` is
    a commutative monoid; ``multiply(a, b)`` maps missing operands
    (represented as ``add.identity``) to ``add.identity`` — i.e. the add
    identity annihilates — so padded/masked entries vanish in reductions.
    Kernels additionally mask padding explicitly, so user multiplies that
    violate annihilation (e.g. select2nd) still work on tiles; the axiom
    only matters for the dense-vector formulations.
    """

    name: str
    add: Monoid
    multiply: Callable[[Array, Array], Array]
    # dtype the add identity/annihilator lives in, for convenience fills
    dtype: Any = jnp.float32

    def zero(self, dtype=None) -> Array:
        return self.add.identity(dtype or self.dtype)

    def fill_zero(self, shape, dtype=None) -> Array:
        return self.add.fill(shape, dtype or self.dtype)


def _sel2nd(a, b):
    del a
    return b


def _sel1st(a, b):
    del b
    return a


# -- stock semirings (Semirings.h:51-257 equivalents) ------------------------
PLUS_TIMES_F64 = Semiring("plus_times_f64", PLUS, lax.mul, jnp.float64)
PLUS_TIMES_F32 = Semiring("plus_times_f32", PLUS, lax.mul, jnp.float32)
PLUS_TIMES_I32 = Semiring("plus_times_i32", PLUS, lax.mul, jnp.int32)
#: tropical / shortest path (MinPlusSRing, Semirings.h:236)
MIN_PLUS_F32 = Semiring("min_plus_f32", MIN, lax.add, jnp.float32)
MAX_TIMES_F32 = Semiring("max_times_f32", MAX, lax.mul, jnp.float32)
#: BFS parent propagation (SelectMaxSRing, Semirings.h:166)
SELECT2ND_MAX_I32 = Semiring("select2nd_max_i32", MAX, _sel2nd, jnp.int32)
SELECT2ND_MIN_I32 = Semiring("select2nd_min_i32", MIN, _sel2nd, jnp.int32)
#: FastSV hooking (Select2ndMinSR, FastSV.h:25)
MIN_SELECT2ND_I32 = SELECT2ND_MIN_I32
MAX_SELECT2ND_F32 = Semiring("select2nd_max_f32", MAX, _sel2nd, jnp.float32)
#: boolean reachability (BoolCopy*SRing / PTBOOL patterns)
BOOL_OR_AND = Semiring("bool_or_and", LOR, jnp.logical_and, jnp.bool_)


def dense_matmul(sr: Semiring, a: Array, b: Array, k_block: int = 128) -> Array:
    """Dense semiring matmul c[i,j] = add_k mul(a[i,k], b[k,j]).

    PlusTimes lowers to a plain MXU matmul; general semirings run a
    blocked broadcast-reduce over k (the reference has no dense GEMM —
    this is the golden-model kernel for tests and the dense fallback for
    small tiles).
    """
    if sr.add.kind == "add" and sr.multiply in (lax.mul, jnp.multiply):
        return jnp.matmul(a, b, precision=lax.Precision.HIGHEST)
    m, k = a.shape
    k2, n = b.shape
    assert k == k2
    nblk = -(-k // k_block)
    kpad = nblk * k_block
    ident = sr.add.identity(jnp.result_type(a.dtype, b.dtype))
    a = jnp.pad(a, ((0, 0), (0, kpad - k)), constant_values=ident)
    b = jnp.pad(b, ((0, kpad - k), (0, 0)), constant_values=ident)

    def body(i, acc):
        ablk = lax.dynamic_slice(a, (0, i * k_block), (m, k_block))
        bblk = lax.dynamic_slice(b, (i * k_block, 0), (k_block, n))
        prod = sr.multiply(ablk[:, :, None], bblk[None, :, :])
        # mask padded k-lanes explicitly: user multiplies need not
        # annihilate the identity (e.g. int min_plus: MAX+x wraps)
        kvalid = i * k_block + jnp.arange(k_block) < k
        prod = jnp.where(kvalid[None, :, None], prod, ident)
        return sr.add.combine(acc, sr.add.reduce(prod, axis=1))

    acc0 = jnp.full((m, n), ident)
    return lax.fori_loop(0, nblk, body, acc0)


def plus_times(dtype) -> Semiring:
    return Semiring(f"plus_times_{jnp.dtype(dtype).name}", PLUS, lax.mul, dtype)


def min_plus(dtype) -> Semiring:
    return Semiring(f"min_plus_{jnp.dtype(dtype).name}", MIN, lax.add, dtype)


def select2nd_max(dtype) -> Semiring:
    return Semiring(f"select2nd_max_{jnp.dtype(dtype).name}", MAX, _sel2nd, dtype)


def select2nd_min(dtype) -> Semiring:
    return Semiring(f"select2nd_min_{jnp.dtype(dtype).name}", MIN, _sel2nd, dtype)
