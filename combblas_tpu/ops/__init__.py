"""Local (per-device) algebra and kernels: semirings, segment reductions,
static-shape sparse tiles, and graph generation."""
