"""Donation-aware retry with deterministic backoff.

Why a *factory* and not the classic `retry(fn, *args)`: the hot
dispatches this wraps donate input buffers (`donate_argnums`), and a
donated buffer is CONSUMED by the attempt — successful or not, the
arrays passed to a failed dispatch may already be deleted. A retry
that re-submits the same objects would crash with a deleted-buffer
error (or worse, alias freed memory on hardware). The contract here:
`make_call(attempt)` returns a zero-arg thunk whose arguments were
re-materialized for THIS attempt (rebuilt from host state, re-sliced
from an undonated source, or built by a donation-disabled variant of
the executable). Callers whose dispatches do not donate can close
over their args freely.

Classification: `classify(exc) -> bool` (default
`faults.is_transient`) decides retry-worthiness. Permanent failures
re-raise immediately; transient ones retry up to
`policy.max_attempts` with exponential backoff, clipped to a request
deadline when one is given — a retry that cannot finish before the
deadline is not attempted.

Accounting: every retry lands in the dispatch ledger as a
`kind="retry"` record under `<name>.retry` (visible in `top_k` /
`format_table`, ignored by the dispatch/readback totals) and on the
`resilience_retries` counter, labeled by site and outcome.
"""

from __future__ import annotations

import dataclasses
import time

from combblas_tpu import obs
from combblas_tpu.obs import ledger as _ledger
from combblas_tpu.resilience import faults as _faults

_retries = obs.counter(
    "resilience_retries",
    "retry attempts by the resilience layer, by site and outcome")


class RetryBudgetExceeded(RuntimeError):
    """All attempts were spent (or the deadline left no room for
    another). Carries the last underlying failure as `__cause__`."""


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 3          # total attempts, first call included
    backoff_s: float = 0.02        # sleep before attempt 2
    backoff_mult: float = 2.0      # exponential growth per attempt
    max_backoff_s: float = 0.5

    def backoff_for(self, attempt: int) -> float:
        """Sleep before `attempt` (1-based; attempt 1 never sleeps).
        Deterministic — no jitter, so chaos runs replay exactly."""
        if attempt <= 1:
            return 0.0
        return min(self.backoff_s * self.backoff_mult ** (attempt - 2),
                   self.max_backoff_s)


def retry_call(make_call, *, policy: RetryPolicy | None = None,
               classify=None, deadline: float | None = None,
               name: str = "call", on_retry=None):
    """Run `make_call(attempt)()` with transient-failure retries.

    * `make_call(attempt)` — factory invoked once per attempt (1-based);
      must re-materialize any donated arguments (see module docstring).
    * `classify(exc)` — True = transient (retryable); default
      `faults.is_transient`.
    * `deadline` — absolute `time.monotonic()` stamp; backoff sleeps
      and further attempts are abandoned once it cannot be met.
    * `on_retry(attempt, exc)` — observer hook (breaker integration).

    Returns the successful attempt's result. Permanent failures
    re-raise with their original type; exhausted/deadline-blocked
    retries raise `RetryBudgetExceeded` with the last failure as
    `__cause__` (so upstream classifiers treat the give-up as
    permanent instead of retrying the retrier).
    """
    policy = policy or RetryPolicy()
    classify = classify or _faults.is_transient
    attempts = max(int(policy.max_attempts), 1)
    last = None
    for attempt in range(1, attempts + 1):
        if attempt > 1:
            pause = policy.backoff_for(attempt)
            if deadline is not None:
                room = deadline - time.monotonic()
                if room <= pause:       # cannot even finish the sleep
                    break
            t0 = time.perf_counter()
            if pause:
                time.sleep(pause)
            _ledger.record(f"{name}.retry", "retry", t0,
                           time.perf_counter() - t0)
            _retries.inc(site=name, outcome="attempt")
            if on_retry is not None:
                on_retry(attempt, last)
        try:
            out = make_call(attempt)()
            if attempt > 1:
                _retries.inc(site=name, outcome="recovered")
            return out
        except Exception as e:                # noqa: BLE001 - classified
            last = e
            if not classify(e):
                _retries.inc(site=name, outcome="permanent")
                raise
    _retries.inc(site=name, outcome="exhausted")
    raise RetryBudgetExceeded(
        f"{name}: no attempt left (spent {attempts}, "
        f"deadline={'set' if deadline is not None else 'none'})") from last
