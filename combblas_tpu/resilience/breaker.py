"""Per-kind circuit breaker (closed / open / half-open).

Layered UNDER the predictive shed in `GraphService`: the shed predicts
"this batch would miss its deadline"; the breaker observes "this kind
is actually failing" and stops burning device time (and retry budget)
on a kind that is down — requests fail fast with `CircuitOpenError`
until a recovery probe succeeds.

State machine (consecutive-failure flavor — deterministic, no sliding
windows, which keeps chaos soaks replayable):

* CLOSED    — traffic flows; `failure_threshold` CONSECUTIVE failures
              trip it to OPEN (any success resets the streak).
* OPEN      — `allow()` is False until `recovery_s` has elapsed since
              the trip, then the breaker moves to HALF_OPEN.
* HALF_OPEN — up to `half_open_max` probe calls are admitted; a
              success closes the breaker, a failure re-opens it (fresh
              recovery clock).

The clock is injectable for tests (`clock=time.monotonic` default).
Thread-safe: serve workers and metric scrapers share instances.
"""

from __future__ import annotations

import threading
import time

from combblas_tpu import obs

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

_transitions = obs.counter(
    "resilience_breaker_transitions",
    "circuit-breaker state transitions, by kind and new state")
_rejections = obs.counter(
    "resilience_breaker_rejections",
    "calls rejected by an open circuit breaker, by kind")


class CircuitOpenError(RuntimeError):
    """Raised (by callers of `allow()`) for traffic rejected while the
    breaker is open: the kind is failing, fail fast instead of
    queueing onto a broken path."""


class CircuitBreaker:
    def __init__(self, kind: str = "", *, failure_threshold: int = 5,
                 recovery_s: float = 1.0, half_open_max: int = 1,
                 clock=time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.kind = kind
        self.failure_threshold = int(failure_threshold)
        self.recovery_s = float(recovery_s)
        self.half_open_max = max(int(half_open_max), 1)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0            # consecutive, while closed
        self._opened_at = 0.0
        self._probes = 0              # admitted while half-open
        self._trips = 0

    def _to(self, state: str) -> None:
        # lock held by caller
        if state == self._state:
            return
        self._state = state
        _transitions.inc(kind=self.kind, state=state)

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        if (self._state == OPEN
                and self._clock() - self._opened_at >= self.recovery_s):
            self._to(HALF_OPEN)
            self._probes = 0

    def allow(self) -> bool:
        """True when a call may proceed. While half-open, admits at
        most `half_open_max` in-flight probes; further traffic is
        rejected until a probe reports back."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and self._probes < self.half_open_max:
                self._probes += 1
                return True
            _rejections.inc(kind=self.kind)
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state == HALF_OPEN:
                self._to(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            now = self._clock()
            if self._state == HALF_OPEN:
                self._opened_at = now
                self._to(OPEN)
                return
            self._failures += 1
            if self._state == CLOSED and \
                    self._failures >= self.failure_threshold:
                self._failures = 0
                self._opened_at = now
                self._trips += 1
                self._to(OPEN)

    def snapshot(self) -> dict:
        with self._lock:
            self._maybe_half_open()
            return {"kind": self.kind, "state": self._state,
                    "consecutive_failures": self._failures,
                    "trips": self._trips,
                    "open_for_s": (round(self._clock() - self._opened_at, 3)
                                   if self._state == OPEN else 0.0)}
