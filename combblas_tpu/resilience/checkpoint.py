"""Iterative-solver checkpoint/resume over the `io/mmio` binary surface.

CombBLAS 2.0 treats checkpoint-by-persistence as THE resilience
mechanism at scale (SURVEY §5): a long solver run periodically
persists its loop-carry state, and a faulted run resumes mid-iteration
instead of restarting from zero. This module is that mechanism for the
two iterative solvers:

* MCL   — carry is the iterated matrix `a` plus the pinned capacity
          and the iteration counter (`models/mcl._mcl_loop_fused`
          checkpoints at the loop head, after the chaos decision —
          exactly the state the loop itself would hold entering
          iteration `it`, so resume is bit-exact by construction).
* FastSV — carry is the `(f, gf)` label vectors plus the completed
          iteration count (`models/cc.fastsv` runs a chunked driver
          when checkpointing is requested).

Layout: a checkpoint is a PREFIX, not a single file —
`<prefix>.meta.json` (written LAST, atomically via `os.replace`) plus
mmio binary payloads (`<prefix>.a.npz`, `<prefix>.f.npz`, ...). A
crash mid-save leaves stale payloads but no new meta, so `latest()`
readers never observe a torn checkpoint.
"""

from __future__ import annotations

import json
import os

from combblas_tpu import obs
from combblas_tpu.io import mmio
from combblas_tpu.parallel import distvec as dv
from combblas_tpu.parallel.grid import ROW_AXIS

FORMAT = 1

_saves = obs.counter("resilience_checkpoint_saves",
                     "solver checkpoints written, by solver")
_resumes = obs.counter("resilience_checkpoint_resumes",
                       "solver runs resumed from a checkpoint, by solver")


def _write_meta(prefix, meta: dict) -> None:
    tmp = f"{prefix}.meta.json.tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, f"{prefix}.meta.json")


def read_meta(prefix) -> dict | None:
    """The checkpoint's metadata, or None when no complete checkpoint
    exists at `prefix` (meta is written last — its presence is the
    commit point)."""
    try:
        with open(f"{prefix}.meta.json") as f:
            meta = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None
    if meta.get("format") != FORMAT:
        return None
    return meta


# -- MCL ------------------------------------------------------------------

def save_mcl(prefix, a, *, it: int, cap_pin, rungs=None) -> None:
    """Snapshot the MCL loop carry entering iteration `it`: the
    iterated matrix (global-COO binary), the pinned capacity the loop
    would re-fit against, and the CapLadder rungs minted so far (so a
    resumed run re-plans with the same capacities)."""
    mmio.save_matrix(f"{prefix}.a.npz", a)
    _write_meta(prefix, {
        "format": FORMAT, "solver": "mcl", "it": int(it),
        "cap_pin": int(cap_pin) if cap_pin is not None else None,
        "rungs": sorted(int(r) for r in rungs) if rungs else [],
        "nnz_cap": int(a.cap)})
    _saves.inc(solver="mcl")


def load_mcl(add, grid, prefix):
    """Returns `(a, meta)` — the matrix restored with its checkpointed
    capacity (shape-stable resume) and the metadata dict. Raises
    FileNotFoundError when no complete checkpoint exists."""
    meta = read_meta(prefix)
    if meta is None or meta.get("solver") != "mcl":
        raise FileNotFoundError(f"no MCL checkpoint at {prefix!r}")
    a = mmio.load_matrix(add, grid, f"{prefix}.a.npz",
                         cap=meta.get("nnz_cap"))
    _resumes.inc(solver="mcl")
    return a, meta


# -- FastSV ---------------------------------------------------------------

def save_fastsv(prefix, grid, f, gf, *, it: int, glen: int) -> None:
    """Snapshot the FastSV carry after `it` completed iterations. The
    label vectors are global arrays inside the jitted loop; they ride
    the mmio vector surface as row-axis DistVecs."""
    mmio.save_vector(f"{prefix}.f.npz", dv.from_global(grid, ROW_AXIS, f))
    mmio.save_vector(f"{prefix}.gf.npz", dv.from_global(grid, ROW_AXIS, gf))
    _write_meta(prefix, {"format": FORMAT, "solver": "fastsv",
                         "it": int(it), "glen": int(glen)})
    _saves.inc(solver="fastsv")


def load_fastsv(grid, prefix):
    """Returns `(f, gf, meta)` with `f`/`gf` as global jnp arrays."""
    import jax.numpy as jnp
    meta = read_meta(prefix)
    if meta is None or meta.get("solver") != "fastsv":
        raise FileNotFoundError(f"no FastSV checkpoint at {prefix!r}")
    f = jnp.asarray(mmio.load_vector(grid, f"{prefix}.f.npz").to_global())
    gf = jnp.asarray(mmio.load_vector(grid, f"{prefix}.gf.npz").to_global())
    _resumes.inc(solver="fastsv")
    return f, gf, meta
