"""Deterministic fault injection at the `obs.ledger` choke points.

Every hot dispatch already flows through an `obs.ledger.instrument`
wrapper, and every blocking/async device->host fetch through
`obs.ledger.readback` / `readback_deferred`. This module arms a single
module-global hook inside `obs.ledger` (checked with one `is None`
load — free while disarmed) and injects failures per a committed JSON
fault schedule:

```json
{
  "seed": 1,
  "rules": [
    {"match": "serve.*",            "kind": "transient", "p": 0.10, "max": 8},
    {"match": "mcl.megastep",       "kind": "oom",       "at": [3]},
    {"match": "serve.bfs*",         "kind": "latency",   "every": 4,
     "latency_s": 0.005},
    {"match": "spgemm.nnz_deferred","kind": "stuck",     "at": [1, 2]},
    {"match": "serve.spmv*",        "kind": "nan",       "at": [0]}
  ]
}
```

Rule fields:

* `match`      — fnmatch pattern over the ledger site name (required).
* `kind`       — one of `transient` (raises `TransientFault`), `oom`
                 (raises `InjectedOom` with a RESOURCE_EXHAUSTED-shaped
                 message), `latency` (sleeps `latency_s`), `stuck`
                 (the deferred-readback handle never reports ready, so
                 pipelines must take their fallback path), `nan`
                 (poisons float outputs with NaN).
* exactly one trigger: `at` (explicit 0-based per-site call indices),
  `every` (every k-th call), or `p` (pseudo-random per call, derived
  deterministically from `(seed, rule_index, site, call_index)` — NO
  global RNG state, so concurrency and call interleaving across
  different sites cannot change decisions).
* `after`      — skip the first N calls (default 0).
* `max`        — cap on total fires for the rule (default unbounded).
* `latency_s`  — sleep duration for `kind == "latency"`.

Determinism contract: a site's decisions depend only on the schedule
and on that site's own call ordinal for the rule — both stable across
runs for deterministic drivers. Counters are per `(rule, site)` and
updated under one lock (the fault path is not a hot path; the
*disarmed* path is the one that must stay free).
"""

from __future__ import annotations

import contextlib
import fnmatch
import hashlib
import json
import threading
import time

from combblas_tpu import obs
from combblas_tpu.obs import ledger as _ledger

FAULT_KINDS = ("transient", "oom", "latency", "stuck", "nan")

#: kinds evaluated before a dispatch/readback executes
_PRE_KINDS = ("transient", "oom", "latency")

_faults_injected = obs.counter(
    "resilience_faults_injected",
    "faults injected by the chaos layer, by kind")


class InjectedFault(RuntimeError):
    """Base class for every failure raised by the fault injector."""


class TransientFault(InjectedFault):
    """A retry-worthy injected failure (models a flaky dispatch)."""


class InjectedOom(InjectedFault):
    """An allocation failure shaped like XLA's RESOURCE_EXHAUSTED."""

    def __init__(self, site: str, nbytes: int = 1 << 30):
        super().__init__(
            f"RESOURCE_EXHAUSTED: Out of memory while trying to "
            f"allocate {nbytes} bytes. [injected at {site}]")


def is_oom_error(exc: BaseException) -> bool:
    """True for injected OOMs and for real XLA RESOURCE_EXHAUSTED
    failures (matched on the status string — jaxlib raises them as
    `XlaRuntimeError`, whose class identity is version-dependent)."""
    if isinstance(exc, InjectedOom):
        return True
    return "RESOURCE_EXHAUSTED" in str(exc)


def is_transient(exc: BaseException) -> bool:
    """Default transient-vs-permanent classifier for the retry layer.
    Transient: injected transients, OOMs (a retry at lower capacity or
    after a competing batch drains can succeed), and runtime statuses
    that name a retryable condition. Everything else (shape errors,
    TypeError, ...) is permanent — retrying cannot help."""
    if isinstance(exc, TransientFault):
        return True
    if is_oom_error(exc):
        return True
    msg = str(exc)
    return any(tag in msg for tag in ("UNAVAILABLE", "ABORTED",
                                      "DEADLINE_EXCEEDED"))


class _Rule:
    __slots__ = ("index", "match", "kind", "at", "every", "p", "after",
                 "max", "latency_s", "fired")

    def __init__(self, index: int, spec: dict):
        self.index = index
        self.match = spec["match"]
        self.kind = spec["kind"]
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"rule {index}: unknown fault kind "
                             f"{self.kind!r} (want one of {FAULT_KINDS})")
        self.at = frozenset(spec["at"]) if "at" in spec else None
        self.every = int(spec["every"]) if "every" in spec else None
        self.p = float(spec["p"]) if "p" in spec else None
        triggers = sum(x is not None for x in (self.at, self.every, self.p))
        if triggers != 1:
            raise ValueError(f"rule {index} ({self.match!r}): need exactly "
                             f"one of at/every/p, got {triggers}")
        self.after = int(spec.get("after", 0))
        self.max = spec.get("max")
        self.latency_s = float(spec.get("latency_s", 0.001))
        self.fired = 0


def _hash_frac(seed: int, rule_index: int, site: str, k: int) -> float:
    """Deterministic uniform-[0,1) draw for (seed, rule, site, call#)."""
    h = hashlib.sha256(
        f"{seed}:{rule_index}:{site}:{k}".encode()).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


class FaultInjector:
    """Evaluates a fault schedule against ledger site names. Install
    with `arm()` / the `injected()` context manager."""

    def __init__(self, schedule: dict):
        self.seed = int(schedule.get("seed", 0))
        self.rules = [_Rule(i, spec)
                      for i, spec in enumerate(schedule.get("rules", []))]
        self._counts: dict = {}       # (rule_index, site) -> calls seen
        self._lock = threading.Lock()
        self.injected: dict = {k: 0 for k in FAULT_KINDS}

    @classmethod
    def from_json(cls, path) -> "FaultInjector":
        with open(path) as f:
            return cls(json.load(f))

    # -- decision core ---------------------------------------------------

    def _fire(self, site: str, kinds) -> "_Rule | None":
        """First matching rule of one of `kinds` that fires for this
        call. Each matching rule's per-site call counter advances once
        per check, fired or not — that is what makes `at`/`every`
        indices meaningful per site."""
        hit = None
        with self._lock:
            for r in self.rules:
                if r.kind not in kinds or not fnmatch.fnmatch(site, r.match):
                    continue
                key = (r.index, site)
                k = self._counts.get(key, 0)
                self._counts[key] = k + 1
                if hit is not None or k < r.after:
                    continue
                if r.max is not None and r.fired >= r.max:
                    continue
                if r.at is not None:
                    fire = k in r.at
                elif r.every is not None:
                    fire = (k + 1) % r.every == 0
                else:
                    fire = _hash_frac(self.seed, r.index, site, k) < r.p
                if fire:
                    r.fired += 1
                    self.injected[r.kind] += 1
                    hit = r
        if hit is not None:
            _faults_injected.inc(kind=hit.kind)
        return hit

    # -- ledger hook surface (called from obs.ledger) --------------------

    def before_dispatch(self, site: str) -> None:
        """Pre-call injection: latency, transient, OOM. May raise."""
        r = self._fire(site, _PRE_KINDS)
        if r is None:
            return
        if r.kind == "latency":
            time.sleep(r.latency_s)
        elif r.kind == "transient":
            raise TransientFault(f"injected transient fault at {site} "
                                 f"(rule {r.index})")
        else:
            raise InjectedOom(site)

    def after_dispatch(self, site: str, out):
        """Post-call injection: NaN-poison float array leaves."""
        r = self._fire(site, ("nan",))
        if r is None:
            return out
        return _poison(out)

    def stuck_readback(self, site: str) -> bool:
        """True when a deferred readback minted at `site` must never
        report ready (the pipeline has to take its fallback path)."""
        return self._fire(site, ("stuck",)) is not None

    def stats(self) -> dict:
        with self._lock:
            return {"seed": self.seed,
                    "injected": dict(self.injected),
                    "rules": [{"match": r.match, "kind": r.kind,
                               "fired": r.fired} for r in self.rules]}


def _poison(out):
    """Replace every inexact array leaf with NaNs of the same
    shape/dtype. Non-float leaves (indices, counts) pass through —
    poisoning those would be a shape/validity fault, not a data one."""
    import jax
    import jax.numpy as jnp

    def leaf(x):
        dt = getattr(x, "dtype", None)
        if dt is not None and jnp.issubdtype(dt, jnp.inexact):
            return jnp.full_like(x, jnp.nan)
        return x

    return jax.tree_util.tree_map(leaf, out)


# -- arming ---------------------------------------------------------------

_ACTIVE: FaultInjector | None = None


def arm(injector: FaultInjector) -> FaultInjector:
    """Install `injector` as the process-wide fault hook."""
    global _ACTIVE
    _ACTIVE = injector
    _ledger.set_fault_hook(injector)
    return injector


def disarm() -> None:
    """Remove the fault hook (the ledger hot path is free again)."""
    global _ACTIVE
    _ACTIVE = None
    _ledger.set_fault_hook(None)


def active() -> FaultInjector | None:
    return _ACTIVE


@contextlib.contextmanager
def injected(schedule):
    """Arm a schedule (dict, FaultInjector, or JSON path) for the
    duration of the block; always disarms on exit."""
    if isinstance(schedule, FaultInjector):
        inj = schedule
    elif isinstance(schedule, dict):
        inj = FaultInjector(schedule)
    else:
        inj = FaultInjector.from_json(schedule)
    arm(inj)
    try:
        yield inj
    finally:
        disarm()


def load_schedule(path) -> FaultInjector:
    return FaultInjector.from_json(path)
