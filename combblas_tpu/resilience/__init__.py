"""Resilience layer: deterministic fault injection + layered recovery.

The serving north star is a system that keeps answering under partial
failure. This package supplies both halves of that story:

* `faults` — a seedable, deterministic fault-injection layer that
  intercepts at the existing `obs.ledger` choke points (`instrument`
  wrappers, `readback`, `readback_deferred`) and injects, per a
  committed JSON schedule: transient RuntimeErrors, RESOURCE_EXHAUSTED-
  shaped OOMs, added latency, never-resolving deferred readbacks, and
  NaN poisoning. Zero cost while disarmed (a single module-global
  `is None` check on the hot path).
* `retry` — donation-aware retry-with-backoff: the caller supplies a
  *factory* that re-materializes arguments per attempt (donated
  buffers are consumed by a dispatch, successful or not), transient
  vs permanent classification, and deadline-aware backoff.
* `breaker` — a per-kind closed/open/half-open circuit breaker used by
  `GraphService` on top of the predictive shed.
* `checkpoint` — iterative-solver snapshot/resume (MCL, FastSV) over
  the `io/mmio` binary surface; bit-exact mid-iteration resume.

Error taxonomy (importable from the package root):

* `InjectedFault`      — base class for every injected failure
* `TransientFault`     — retry-worthy injected RuntimeError
* `InjectedOom`        — RESOURCE_EXHAUSTED-shaped allocation failure
* `is_oom_error(exc)`  — matches injected AND real XLA OOMs
* `is_transient(exc)`  — the retry layer's default classifier
"""

from combblas_tpu.resilience.faults import (  # noqa: F401
    FaultInjector,
    InjectedFault,
    InjectedOom,
    TransientFault,
    arm,
    disarm,
    injected,
    is_oom_error,
    is_transient,
    load_schedule,
)
from combblas_tpu.resilience.retry import (  # noqa: F401
    RetryBudgetExceeded,
    RetryPolicy,
    retry_call,
)
from combblas_tpu.resilience.breaker import (  # noqa: F401
    CircuitBreaker,
    CircuitOpenError,
)
from combblas_tpu.resilience import checkpoint  # noqa: F401

__all__ = [
    "FaultInjector", "InjectedFault", "InjectedOom", "TransientFault",
    "arm", "disarm", "injected", "is_oom_error", "is_transient",
    "load_schedule",
    "RetryBudgetExceeded", "RetryPolicy", "retry_call",
    "CircuitBreaker", "CircuitOpenError",
    "checkpoint",
]
