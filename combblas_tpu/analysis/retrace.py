"""Pass 2 — retrace-drift detector.

The serving invariant is "steady-state traffic never compiles": after
`GraphService.warmup()`, every dispatch must hit the jit cache. jax's
cache key over non-static args is the abstract signature — dtype,
shape, and the easily-drifted WEAK-TYPE bit (a raw Python scalar
traces weak-typed; `jnp.int32(x)` traces strong) — so two call sites
that `PlanCache` files under one `PlanKey` but that prepare arguments
differently silently double the compile count.

The detector replays the serve layer's argument-preparation recipes
(runtime executor AND warmup prefill, per kind x bucket over the
configured ladder) WITHOUT executing anything, computes each point's
jit-cache signature, and flags:

* `retrace-drift` — two points in the same plan-cache group with
  different signatures (the avoidable recompile);
* `retrace-py-scalar` — a raw Python scalar in a traced position
  (weak-type leakage waiting to happen);
* `retrace-extra-compile` — the distinct-signature count per entry
  differs from the committed expectation in
  `analysis/budgets/retrace_serve.json` (a bucket-ladder change that
  silently doubles compiles fails here).

`empirical_compile_count` cross-checks the signature model for a
callable by actually jitting it with a trace counter — used by the
tests on cheap entries only.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Any, Callable, Optional

import numpy as np

from combblas_tpu.analysis import core
from combblas_tpu.analysis.core import Finding

EXPECT_FILE = pathlib.Path(__file__).parent / "budgets" / "retrace_serve.json"

_LANE_W = 32


@dataclasses.dataclass(frozen=True)
class SweepPoint:
    """One simulated dispatch: ``entry`` names the executable family
    (expected-compile-count accounting), ``group`` the PlanKey-level
    identity (points sharing a group MUST share one jit signature),
    ``origin`` where the args came from (runtime executor / warmup)."""

    entry: str
    group: str
    origin: str
    args: tuple


def leaf_signature(leaf) -> tuple:
    """jit-cache identity of one argument leaf: (dtype, shape,
    weak_type). Raw Python scalars are weak-typed and tagged."""
    import jax.numpy as jnp
    if isinstance(leaf, (bool, int, float, complex)):
        return ("py-scalar", type(leaf).__name__, (), True)
    if isinstance(leaf, np.ndarray) or isinstance(leaf, np.generic):
        return (str(leaf.dtype), tuple(np.shape(leaf)), False)
    if isinstance(leaf, jnp.ndarray):
        return (str(leaf.dtype), tuple(leaf.shape),
                bool(getattr(leaf, "weak_type", False)))
    # other aval-like leaves (ShapeDtypeStruct)
    return (str(getattr(leaf, "dtype", type(leaf).__name__)),
            tuple(getattr(leaf, "shape", ())),
            bool(getattr(leaf, "weak_type", False)))


def signature(args: tuple) -> tuple:
    """Full jit-cache signature of an argument tuple: pytree structure
    + per-leaf signatures."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (str(treedef), tuple(leaf_signature(lf) for lf in leaves))


def py_scalar_leaves(args: tuple) -> list[int]:
    import jax
    leaves, _ = jax.tree_util.tree_flatten(args)
    return [i for i, lf in enumerate(leaves)
            if isinstance(lf, (bool, int, float, complex))]


# ---------------------------------------------------------------------------
# the serve sweep: replicate engine.py's argument preparation exactly
# ---------------------------------------------------------------------------

def build_serve_sweep(buckets: Optional[tuple] = None,
                      n: int = 256) -> list[SweepPoint]:
    """Sweep points for every serve executor over the bucket ladder.
    Argument recipes mirror `serve/engine.py` line-for-line — if the
    engine's prep drifts from this model, the empirical cross-check in
    tests/test_analysis.py catches it."""
    import jax.numpy as jnp

    from combblas_tpu.utils.config import ServeConfig
    if buckets is None:
        buckets = ServeConfig().buckets
    pts: list[SweepPoint] = []
    for b in buckets:
        # bfs dense: _run_bfs pads roots then fn(jnp.asarray(roots_p),
        # jnp.int32(ml)); warmup: fn(jnp.zeros((eb,), i32), jnp.int32(1))
        roots = np.zeros((b,), np.int32)
        pts.append(SweepPoint("bfs-dense", f"bfs-dense/w{b}", "runtime",
                              (jnp.asarray(roots), jnp.int32(7))))
        pts.append(SweepPoint("bfs-dense", f"bfs-dense/w{b}", "warmup",
                              (jnp.zeros((b,), jnp.int32), jnp.int32(1))))
        # bfs bits: bucket aligns UP to the 32-root lane width, so the
        # whole ladder shares ONE executable
        eb = -(-b // _LANE_W) * _LANE_W
        pts.append(SweepPoint(
            "bfs-bits", f"bfs-bits/w{eb}", "runtime",
            (jnp.asarray(np.zeros((eb,), np.int32)), jnp.int32(7))))
        pts.append(SweepPoint(
            "bfs-bits", f"bfs-bits/w{eb}", "warmup",
            (jnp.zeros((eb,), jnp.int32), jnp.int32(1))))
        # cc: fn(labels, jnp.asarray(verts_p)) vs warmup
        # fn(labels, jnp.zeros((b,), i32)); labels is a strong i32[n]
        labels = jnp.zeros((n,), jnp.int32)
        pts.append(SweepPoint(
            "cc", f"cc/w{b}", "runtime",
            (labels, jnp.asarray(np.zeros((b,), np.int32)))))
        pts.append(SweepPoint(
            "cc", f"cc/w{b}", "warmup",
            (labels, jnp.zeros((b,), jnp.int32))))
        # spmv: run(a, jnp.asarray(arr, sr.dtype)) with arr (glen, W);
        # the matrix operand is identical either way — model just the
        # stacked batch operand
        pts.append(SweepPoint(
            "spmv:plus_times_f32", f"spmv/w{b}", "runtime",
            (jnp.asarray(np.zeros((n, b)), jnp.float32),)))
        pts.append(SweepPoint(
            "spmv:plus_times_f32", f"spmv/w{b}", "warmup",
            (jnp.asarray(np.zeros((n, b)), jnp.float32),)))
    return pts


def analyze_sweep(points: list[SweepPoint],
                  expected: Optional[dict] = None,
                  file: str = "", text: str = "") -> list[Finding]:
    """Evaluate sweep points: per-group signature agreement, Python-
    scalar leakage, and per-entry compile counts vs ``expected``."""
    def ln(needle: str) -> int:
        if text:
            for i, l in enumerate(text.splitlines(), start=1):
                if needle in l:
                    return i
        return 1

    out: list[Finding] = []
    by_group: dict[str, dict] = {}
    by_entry: dict[str, set] = {}
    for p in points:
        sig = signature(p.args)
        by_group.setdefault(p.group, {}).setdefault(sig, []).append(p)
        by_entry.setdefault(p.entry, set()).add(sig)
        leaks = py_scalar_leaves(p.args)
        if leaks:
            out.append(Finding(
                core.RETRACE_PY_SCALAR, file or "<sweep>", ln(p.entry),
                f"{p.group} ({p.origin}): raw Python scalar in traced "
                f"position(s) {leaks} — weak-type cache key; wrap in "
                f"jnp.asarray / jnp.int32", p.entry))

    for group, sigs in sorted(by_group.items()):
        if len(sigs) > 1:
            detail = []
            for sig, ps in sigs.items():
                origins = ",".join(p.origin for p in ps)
                detail.append(f"[{origins}] leaves={sig[1]}")
            # name the drifting leaf kind when it is the weak-type bit
            leafsets = [set(s[1]) for s in sigs]
            weak = any(a[:2] == b[:2] and a[-1] != b[-1]
                       for a in leafsets[0].union(*leafsets)
                       for b in leafsets[0].union(*leafsets))
            why = ("weak-type drift" if weak
                   else "shape/dtype mismatch")
            out.append(Finding(
                core.RETRACE_DRIFT, file or "<sweep>",
                ln(group.split("/")[0]),
                f"plan-cache group {group} maps to {len(sigs)} distinct "
                f"jit cache keys ({why}): " + "; ".join(sorted(detail)),
                group.split("/")[0]))

    if expected is not None:
        for entry, sigs in sorted(by_entry.items()):
            want = expected.get(entry)
            if want is None:
                out.append(Finding(
                    core.RETRACE_EXTRA_COMPILE, file or "<sweep>", 1,
                    f"entry {entry!r} has no committed expected compile "
                    f"count (measured {len(sigs)}); add it to "
                    f"retrace_serve.json", entry))
            elif len(sigs) != want:
                out.append(Finding(
                    core.RETRACE_EXTRA_COMPILE, file or "<sweep>",
                    ln(entry),
                    f"entry {entry!r} compiles {len(sigs)} distinct "
                    f"signatures over the ladder, committed expectation "
                    f"is {want}", entry))
    return out


def run_retrace(expect_file=None) -> list[Finding]:
    """The gate's retrace pass: serve sweep vs the committed
    expectations artifact."""
    path = pathlib.Path(expect_file or EXPECT_FILE)
    text = path.read_text()
    data = json.loads(text)
    buckets = tuple(data.get("buckets") or ()) or None
    expected = data.get("expected_compiles", {})
    allow = set(data.get("allow", ()))
    pts = build_serve_sweep(buckets=buckets)
    findings = analyze_sweep(pts, expected, str(path), text)
    return [f for f in findings if f.rule not in allow]


# ---------------------------------------------------------------------------
# empirical cross-check
# ---------------------------------------------------------------------------

def empirical_compile_count(fn: Callable, arg_sets: list[tuple]) -> int:
    """Actually jit ``fn`` and count traces over ``arg_sets`` (each
    cache miss re-enters the Python body). Executes — callers keep the
    fixture tiny. Returns the number of traces; equal to the number of
    distinct `signature()`s iff the static model is faithful."""
    import jax
    n = [0]

    def counted(*args):
        n[0] += 1
        return fn(*args)

    jitted = jax.jit(counted)  # analysis: allow(cache-key-unstable) fresh cache IS the point: empirical compile counter
    for args in arg_sets:
        jax.block_until_ready(jitted(*args))
    return n[0]


def group_points(points: list[SweepPoint],
                 entry: str) -> dict[str, list[SweepPoint]]:
    out: dict[str, list[SweepPoint]] = {}
    for p in points:
        if p.entry == entry:
            out.setdefault(p.group, []).append(p)
    return out


__all__ = ["SweepPoint", "signature", "leaf_signature",
           "build_serve_sweep", "analyze_sweep", "run_retrace",
           "empirical_compile_count", "group_points", "EXPECT_FILE"]
