"""Pass 3 — lock-order / threading lint.

An AST pass over the package that builds the lock-acquisition graph
and reports the three deadlock shapes that have actually bitten this
codebase (PR 4's ~60%-flaky tier-1 hang was a jit dispatch racing
worker collectives under a shared lock):

* `lock-cycle` — two code paths acquire the same pair of locks in
  opposite orders (or re-acquire a non-reentrant lock they already
  hold);
* `jit-under-lock` — a blocking jax dispatch (any `jax.*`/`jnp.*`
  call, or a known kernel driver like `fastsv`/`plan_bfs`/`spmm`)
  while a lock is held: every other thread needing that lock now
  waits on device latency, and on the CPU mesh a concurrent
  collective deadlocks outright;
* `bare-acquire` — `.acquire()` without a try/finally release: an
  exception between the two leaks the lock forever.

Scope and resolution are deliberately conservative: locks are
`threading.Lock/RLock/Condition` attributes (a Condition constructed
over a lock aliases that lock); held-ness is lexical (`with lock:`
nesting); calls resolve interprocedurally only through RECEIVERS WITH
KNOWN TYPES (`self.queue = RequestQueue(...)` makes `self.queue.put`
resolve to `RequestQueue.put`) plus module aliases — name-guessing
across untyped receivers would drown the report in noise. Lock
closures are transitive over resolved calls.

Waive a finding with ``# analysis: allow(<rule>)`` on the flagged
line, the line above, or the enclosing ``with`` statement's line.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import Optional

from combblas_tpu.analysis import core
from combblas_tpu.analysis.core import Finding

#: terminal call names treated as blocking device dispatch even when
#: the receiver cannot be typed (the repo's kernel drivers)
DISPATCH_NAMES = frozenset({
    "fastsv", "bfs", "bfs_batch", "bfs_batch_bits", "bfs_bits",
    "bfs_bits_mesh", "spgemm", "spgemm_phased", "spgemm_colwindow",
    "spmm", "spmv", "spmsv", "plan_bfs", "block_until_ready",
    "device_put", "jit",
})

#: obs factory terminals -> the metric class their result carries
FACTORY_TYPES = {"counter": "Counter", "gauge": "Gauge",
                 "histogram": "Histogram"}

_LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition"}
_LOCK_METHODS_IGNORED = frozenset({
    "release", "wait", "wait_for", "notify", "notify_all", "locked"})


def _dotted(node) -> Optional[list[str]]:
    """Attribute chain as names: self.queue.put -> [self, queue, put]."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


@dataclasses.dataclass
class ClassInfo:
    name: str
    module: str
    locks: dict = dataclasses.field(default_factory=dict)
    # attr -> (canonical id, kind); Condition-over-lock aliases resolve
    # to the aliased lock's canonical id
    attr_types: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class CallEvent:
    line: int
    held: tuple                      # ((lock id, with line), ...)
    terminal: str
    target: Optional[tuple] = None   # ("method", class, name) when typed
    jax_rooted: bool = False


@dataclasses.dataclass
class FuncInfo:
    key: tuple                       # (module, class or "", name)
    file: str
    direct_locks: set = dataclasses.field(default_factory=set)
    acquires: list = dataclasses.field(default_factory=list)
    # (lock id, line, held tuple)
    calls: list = dataclasses.field(default_factory=list)
    bare: list = dataclasses.field(default_factory=list)
    # (lock id, line, held tuple) for .acquire() without try/finally


class _Module:
    def __init__(self, path: pathlib.Path, pkg_root: pathlib.Path):
        self.path = path
        self.source = path.read_text()
        self.tree = ast.parse(self.source, filename=str(path))
        try:
            rel = path.relative_to(pkg_root.parent)
            self.name = str(rel.with_suffix("")).replace("/", ".")
        except ValueError:
            self.name = path.stem
        self.aliases: dict[str, str] = {}      # local name -> dotted module/obj
        self.module_locks: dict[str, tuple] = {}   # var -> (canonical, kind)
        self.module_var_types: dict[str, str] = {}
        self.suppressions = core.scan_suppressions(self.source)


class Analyzer:
    def __init__(self, paths):
        self.modules: list[_Module] = []
        self.classes: dict[str, ClassInfo] = {}
        self.lock_kinds: dict[str, str] = {}
        self.funcs: dict[tuple, FuncInfo] = {}
        roots = [pathlib.Path(p) for p in paths]
        for root in roots:
            files = ([root] if root.is_file()
                     else sorted(root.rglob("*.py")))
            for f in files:
                self.modules.append(_Module(f, root if root.is_dir()
                                            else root.parent))

    # -- phase 1: imports, lock attrs, attr types ----------------------

    def _collect_imports(self, m: _Module) -> None:
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    m.aliases[al.asname or al.name.split(".")[0]] = al.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for al in node.names:
                    m.aliases[al.asname or al.name] = (
                        f"{node.module}.{al.name}")

    def _lock_ctor(self, call: ast.Call, m: _Module) -> Optional[str]:
        d = _dotted(call.func)
        if not d:
            return None
        root = m.aliases.get(d[0], d[0])
        full = ".".join([root] + d[1:])
        for ctor, kind in _LOCK_CTORS.items():
            if full == f"threading.{ctor}":
                return kind
        return None

    def _collect_class(self, m: _Module, cls: ast.ClassDef) -> None:
        info = self.classes.setdefault(cls.name,
                                       ClassInfo(cls.name, m.name))
        for fn in [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign) or not node.targets:
                    continue
                tgt = node.targets[0]
                d = _dotted(tgt)
                if not (d and len(d) == 2 and d[0] == "self"):
                    continue
                attr = d[1]
                val = node.value
                if not isinstance(val, ast.Call):
                    continue
                kind = self._lock_ctor(val, m)
                if kind == "Condition" and val.args:
                    base = _dotted(val.args[0])
                    if (base and len(base) == 2 and base[0] == "self"
                            and base[1] in info.locks):
                        # Condition over an existing lock: alias it
                        info.locks[attr] = info.locks[base[1]]
                        continue
                if kind is not None:
                    cid = f"{cls.name}.{attr}"
                    info.locks[attr] = (cid, kind)
                    self.lock_kinds[cid] = kind
                    continue
                ctor = _dotted(val.func)
                if ctor and ctor[-1] in FACTORY_TYPES and len(ctor) > 1:
                    info.attr_types[attr] = FACTORY_TYPES[ctor[-1]]
                elif ctor and ctor[-1][:1].isupper():
                    info.attr_types[attr] = ctor[-1]

    def _collect_module_scope(self, m: _Module) -> None:
        for node in m.tree.body:
            if not isinstance(node, ast.Assign) or not node.targets:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            val = node.value
            if not isinstance(val, ast.Call):
                continue
            kind = self._lock_ctor(val, m)
            if kind is not None:
                cid = f"{m.name}.{tgt.id}"
                m.module_locks[tgt.id] = (cid, kind)
                self.lock_kinds[cid] = kind
                continue
            ctor = _dotted(val.func)
            if ctor and ctor[-1] in FACTORY_TYPES:
                m.module_var_types[tgt.id] = FACTORY_TYPES[ctor[-1]]
            elif ctor and ctor[-1][:1].isupper():
                m.module_var_types[tgt.id] = ctor[-1]

    # -- phase 2: per-function walks -----------------------------------

    def _lock_ref(self, expr, m: _Module,
                  cls: Optional[ClassInfo]) -> Optional[str]:
        d = _dotted(expr)
        if not d:
            return None
        if (cls is not None and len(d) == 2 and d[0] == "self"
                and d[1] in cls.locks):
            return cls.locks[d[1]][0]
        if len(d) == 1 and d[0] in m.module_locks:
            return m.module_locks[d[0]][0]
        return None

    def _walk_function(self, m: _Module, cls: Optional[ClassDef],
                       fn, fi: FuncInfo) -> None:
        local_types: dict[str, str] = dict(m.module_var_types)

        def resolve_call(call: ast.Call) -> CallEvent:
            d = _dotted(call.func)
            ev = CallEvent(call.lineno, (), d[-1] if d else "<expr>")
            if not d:
                return ev
            root = d[0]
            if root == "self" and cls is not None:
                if len(d) == 2:
                    ev.target = ("method", cls.name, d[1])
                elif len(d) == 3 and d[1] in cls.attr_types:
                    ev.target = ("method", cls.attr_types[d[1]], d[2])
            elif root in local_types and len(d) == 2:
                ev.target = ("method", local_types[root], d[1])
            elif root in m.aliases:
                full = ".".join([m.aliases[root]] + d[1:])
                if full == "jax" or full.startswith(("jax.",)):
                    ev.jax_rooted = True
            return ev

        def scan(node, held):
            """Record calls/acquires in ``node`` without descending
            into nested function/lambda bodies (they run later, not
            under these locks)."""
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d and len(d) >= 2 and d[-1] == "acquire":
                    lid = self._lock_ref(node.func.value, m, cls)
                    if lid is not None:
                        fi.direct_locks.add(lid)
                        fi.acquires.append((lid, node.lineno, held))
                        if not self._release_in_finally(m, node, lid,
                                                        cls):
                            fi.bare.append((lid, node.lineno, held))
                        for a in node.args:
                            scan(a, held)
                        return
                if d and len(d) >= 2 and d[-1] in _LOCK_METHODS_IGNORED:
                    if self._lock_ref(node.func.value, m, cls):
                        for a in node.args:
                            scan(a, held)
                        return
                ev = resolve_call(node)
                ev.held = held
                fi.calls.append(ev)
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                ctor = _dotted(node.value.func)
                if (ctor and len(ctor) == 1 and ctor[0] in self.classes
                        and node.targets
                        and isinstance(node.targets[0], ast.Name)):
                    local_types[node.targets[0].id] = ctor[0]
            for child in ast.iter_child_nodes(node):
                scan(child, held)

        def walk_stmts(stmts, held):
            for st in stmts:
                if isinstance(st, (ast.With, ast.AsyncWith)):
                    new = list(held)
                    for item in st.items:
                        lid = self._lock_ref(item.context_expr, m, cls)
                        if lid is not None:
                            fi.direct_locks.add(lid)
                            fi.acquires.append(
                                (lid, st.lineno, tuple(new)))
                            new.append((lid, st.lineno))
                        else:
                            scan(item.context_expr, tuple(new))
                    walk_stmts(st.body, tuple(new))
                elif isinstance(st, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue            # nested defs analyzed separately? no
                elif isinstance(st, ast.Try):
                    scan_parts = (st.body, st.orelse, st.finalbody)
                    for part in scan_parts:
                        walk_stmts(part, held)
                    for h in st.handlers:
                        walk_stmts(h.body, held)
                elif isinstance(st, (ast.If, ast.For, ast.AsyncFor,
                                     ast.While)):
                    scan(getattr(st, "test", None) or
                         getattr(st, "iter", None), held)
                    walk_stmts(st.body, held)
                    walk_stmts(st.orelse, held)
                else:
                    scan(st, held)

        walk_stmts(fn.body, ())

    def _release_in_finally(self, m: _Module, acq: ast.Call, lid: str,
                            cls) -> bool:
        """True iff this .acquire() is paired with a try/finally
        release: either an ancestor Try releases it in finalbody, or
        the statement right after the acquire is such a Try."""
        parents = getattr(self, "_parents", None)
        if parents is None:
            return False
        node = acq

        def releases(try_node) -> bool:
            for n in try_node.finalbody:
                for c in ast.walk(n):
                    if (isinstance(c, ast.Call)
                            and isinstance(c.func, ast.Attribute)
                            and c.func.attr == "release"
                            and self._lock_ref(c.func.value, m, cls)
                            == lid):
                        return True
            return False

        # ancestor Trys
        cur = node
        stmt = None
        while cur in parents:
            cur = parents[cur]
            if stmt is None and isinstance(cur, ast.stmt):
                stmt = cur
            if isinstance(cur, ast.Try) and releases(cur):
                return True
        # next-sibling Try
        if stmt is not None and stmt in parents:
            body = getattr(parents[stmt], "body", [])
            if stmt in body:
                i = body.index(stmt)
                for nxt in body[i + 1:]:
                    if isinstance(nxt, ast.Try):
                        return releases(nxt)
                    break
        return False

    # -- phase 3/4: closure, edges, findings ---------------------------

    def run(self) -> list[tuple[Finding, tuple]]:
        """Analyze; returns (finding, scope_lines) pairs — scope lines
        are the enclosing-with lines eligible to carry a suppression.
        Use `run_lockorder` for the suppression-filtered list."""
        for m in self.modules:
            self._collect_imports(m)
            for node in m.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._collect_class(m, node)
            self._collect_module_scope(m)

        for m in self.modules:
            self._parents = {c: p for p in ast.walk(m.tree)
                             for c in ast.iter_child_nodes(p)}
            for node in m.tree.body:
                if isinstance(node, ast.ClassDef):
                    cls = self.classes[node.name]
                    for fn in node.body:
                        if isinstance(fn, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                            fi = FuncInfo((m.name, node.name, fn.name),
                                          str(m.path))
                            self.funcs[fi.key] = fi
                            self._walk_function(m, cls, fn, fi)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    fi = FuncInfo((m.name, "", node.name), str(m.path))
                    self.funcs[fi.key] = fi
                    self._walk_function(m, None, node, fi)
        self._parents = None

        # transitive lock closure over typed calls
        method_locks: dict[tuple, set] = {
            (k[1], k[2]): set(fi.direct_locks)
            for k, fi in self.funcs.items()}
        for k, fi in self.funcs.items():
            method_locks.setdefault((k[1], k[2]), set()).update(
                fi.direct_locks)
        changed = True
        while changed:
            changed = False
            for k, fi in self.funcs.items():
                mine = method_locks[(k[1], k[2])]
                for ev in fi.calls:
                    if ev.target and ev.target[0] == "method":
                        tgt = (ev.target[1], ev.target[2])
                        extra = method_locks.get(tgt, set()) - mine
                        if extra:
                            mine |= extra
                            changed = True

        results: list[tuple[Finding, tuple]] = []
        edges: dict[tuple, tuple] = {}   # (src, dst) -> (file, line, scope)

        def add_edge(src, dst, file, line, scope):
            if src == dst:
                if self.lock_kinds.get(src) != "RLock":
                    results.append((Finding(
                        core.LOCK_CYCLE, file, line,
                        f"non-reentrant lock {src} acquired while "
                        f"already held (self-deadlock)"), scope))
                return
            edges.setdefault((src, dst), (file, line, scope))

        for k, fi in self.funcs.items():
            for lid, line, held in fi.bare:
                results.append((Finding(
                    core.BARE_ACQUIRE, fi.file, line,
                    f"{lid}.acquire() without try/finally release — "
                    f"an exception here leaks the lock"), ()))
            for lid, line, held in fi.acquires:
                for hlid, hline in held:
                    add_edge(hlid, lid, fi.file, line,
                             tuple(hl for _, hl in held))
            for ev in fi.calls:
                if not ev.held:
                    continue
                scope = tuple(hl for _, hl in ev.held)
                if ev.jax_rooted or ev.terminal in DISPATCH_NAMES:
                    heldnames = ", ".join(l for l, _ in ev.held)
                    results.append((Finding(
                        core.JIT_UNDER_LOCK, fi.file, ev.line,
                        f"blocking jax dispatch `{ev.terminal}` while "
                        f"holding {heldnames}: waiters stall on device "
                        f"latency; concurrent collectives can deadlock "
                        f"(the PR-4 hang shape)"), scope))
                if ev.target and ev.target[0] == "method":
                    for lid in method_locks.get(
                            (ev.target[1], ev.target[2]), ()):
                        for hlid, hline in ev.held:
                            add_edge(hlid, lid, fi.file, ev.line, scope)

        results += self._find_cycles(edges)
        return results

    def _find_cycles(self, edges) -> list[tuple[Finding, tuple]]:
        graph: dict[str, list[str]] = {}
        for (src, dst) in edges:
            graph.setdefault(src, []).append(dst)
        out = []
        seen_cycles = set()
        for start in sorted(graph):
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in graph.get(node, ()):
                    if nxt == start:
                        cyc = tuple(sorted(path))
                        if cyc in seen_cycles:
                            continue
                        seen_cycles.add(cyc)
                        sites = []
                        cycle = path + [start]
                        for a, b in zip(cycle, cycle[1:]):
                            f, l, _ = edges[(a, b)]
                            sites.append(f"{a}->{b} at {f}:{l}")
                        f0, l0, scope0 = edges[(cycle[0], cycle[1])]
                        out.append((Finding(
                            core.LOCK_CYCLE, f0, l0,
                            "lock-order cycle: " + "; ".join(sites)),
                            scope0))
                    elif nxt not in path:
                        stack.append((nxt, path + [nxt]))
        return out


def run_lockorder(paths=None) -> list[Finding]:
    """Lint the package (default `combblas_tpu/`); returns findings
    that survive `# analysis: allow(...)` suppressions. Block scope
    comes from `core.FileSuppressions` (every enclosing `with` line);
    the analyzer's own scope tuples (held-lock with lines, which may
    anchor in a DIFFERENT function for cycle edges) ride along as
    extra scope."""
    if paths is None:
        paths = [pathlib.Path(__file__).parents[1]]
    an = Analyzer(paths)
    raw = an.run()
    sup_cache: dict[str, core.FileSuppressions] = {}
    out = []
    for finding, scope in raw:
        fs = sup_cache.get(finding.file)
        if fs is None:
            fs = core.FileSuppressions(
                pathlib.Path(finding.file).read_text())
            sup_cache[finding.file] = fs
        if not fs.covers(finding, scope):
            out.append(finding)
    return out


# keep the annotation import honest for linters
ClassDef = ast.ClassDef
