"""Pass 7 — trace-hazard & collective-safety lint.

An interprocedural AST pass over the package (plus a small jaxpr arm
for the mesh entry points) that enforces the four discipline
properties the last three perf PRs each re-learned the hard way:

* `sync-in-async` — a blocking host sync (`.item()`, `np.asarray`,
  `block_until_ready`, `jax.device_get`, `float()`/`int()`/`bool()`
  over a device readback, implicit `__bool__` on a device field)
  reachable from a REGISTERED async hot path (`budgets/
  trace_hazard.json` `async_roots`: the phased window loop, the MCL
  fused mega-step, serve dispatch) that is not routed through the
  sanctioned ledger brackets (`obs.ledger.readback(...)` /
  `readback_deferred(...).resolve()`). One stray `.item()` in the
  window loop re-serializes every dispatch (the PR-7 pipeline's whole
  win).
* `env-in-trace` — an `os.environ` / `os.getenv` read inside any
  function reachable from traced code (jit-decorated, wrapped by
  `jax.jit(...)`, passed to `jax.shard_map` / `lax` control flow, or
  called from such a function). An env read at trace time is
  invisible to the jit cache: flipping the flag later silently reuses
  the stale executable — the exact PR-8 bug, which aliased the Pallas
  hash path onto the XLA fallback.
* `cache-key-unstable` — the static extension of the pass-2 retrace
  detector: `jax.jit(...)` evaluated inside a function body (a fresh
  compile cache per call), a traced function reading a module-level
  mutable container that the package also mutates (the trace
  snapshots it; later mutation = silent stale answer), and call sites
  passing a literal lambda/list/dict in a declared `static_argnums`/
  `static_argnames` position (a fresh cache key per call).
* `collective-axis` / `collective-transpose` — every resolvable
  `psum`/`all_gather`/`ppermute`/`pvary`/`axis_index` axis inside a
  `shard_map` body is checked against the axis names its own
  `in_specs`/`out_specs` declare (and the global axis vocabulary
  `r`/`c`/`l`); multi-axis `ppermute` (the square-mesh transpose
  pairing in `bfs_batch_bits_mesh` / `fastsv`) must be declared in
  `budgets/trace_hazard.json` `transpose_pairs`, so the 3D /
  rectangular-mesh work fails loudly instead of silently misrouting.

Resolution is deliberately conservative, in the `lockorder.py` style:
calls resolve through bare names (nested > module scope), `self.`
methods, and module aliases; a name that cannot be resolved is
skipped, never guessed. Lambda bodies are scanned as part of their
enclosing function. Nested defs are assumed called by their parent
(true for every hot path here; conservative elsewhere).

Waive a finding with ``# analysis: allow(<rule>)`` on the flagged
line, the line above, or any enclosing ``with`` statement's line
(`core.FileSuppressions`); budget-anchored findings are waived via
the JSON ``"allow"`` lists.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
from typing import Optional

from combblas_tpu.analysis import core
from combblas_tpu.analysis.core import Finding

BUDGET_FILE = (pathlib.Path(__file__).parent / "budgets"
               / "trace_hazard.json")

#: fully-qualified callables whose function argument is traced
TRACE_ENTRIES = frozenset({
    "jax.jit", "jax.vmap", "jax.pmap", "jax.grad", "jax.value_and_grad",
    "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "jax.checkpoint", "jax.remat",
    "jax.lax.while_loop", "jax.lax.fori_loop", "jax.lax.scan",
    "jax.lax.cond", "jax.lax.switch", "jax.lax.map",
    "jax.lax.associative_scan",
})

#: collective terminals checked inside shard_map bodies, mapped to the
#: positional index of their axis-name argument
COLLECTIVES = {
    "psum": 1, "pmax": 1, "pmin": 1, "pmean": 1, "psum_scatter": 1,
    "all_gather": 1, "ppermute": 1, "all_to_all": 1, "pvary": 1,
    "axis_index": 0, "pbroadcast": 1, "axis_size": 0,
}

#: context-manager terminals that sanction a blocking readback (the
#: obs.ledger flight-recorder brackets)
_SANCTIONED_CTX = frozenset({"readback", "readback_deferred", "resolve"})

#: attribute terminals treated as device-resident fields for the
#: implicit-__bool__ / int()/float() arms (the Tile/DistSpMat payload)
_DEVICE_ATTRS = frozenset({"nnz", "vals", "rows", "cols", "data"})

#: receiver-method terminals that return HOST values — poll/metadata
#: calls that look like readbacks but never block
_NONBLOCKING_TERMINALS = frozenset({"is_ready", "is_deleted"})


def _dotted(node) -> Optional[list[str]]:
    """Attribute chain as names: jax.lax.psum -> [jax, lax, psum]."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _line_of(text: str, anchor: str, fallback: int = 1) -> int:
    for i, ln in enumerate(text.splitlines(), start=1):
        if anchor in ln:
            return i
    return fallback


def load_budget(path=None) -> dict:
    path = pathlib.Path(path or BUDGET_FILE)
    return json.loads(path.read_text())


@dataclasses.dataclass
class CallEdge:
    line: int
    target: Optional[tuple]          # (module, qual) when resolved
    terminal: str


@dataclasses.dataclass
class SyncSite:
    line: int
    what: str                        # human label, e.g. ".item()"
    sanctioned: bool                 # inside a ledger readback bracket


@dataclasses.dataclass
class FuncNode:
    key: tuple                       # (module name, dotted qual)
    file: str
    line: int
    node: object                     # the ast def node
    cls: Optional[str] = None        # enclosing class name, if a method
    parent: Optional[tuple] = None   # enclosing function's key
    nested: list = dataclasses.field(default_factory=list)
    calls: list = dataclasses.field(default_factory=list)
    env_reads: list = dataclasses.field(default_factory=list)  # (line, what)
    sync_sites: list = dataclasses.field(default_factory=list)
    traced: bool = False             # jitted / passed to a trace entry
    jit_static: Optional[dict] = None  # {"argnums": [...], "argnames": [...]}

    @property
    def full(self) -> str:
        return f"{self.key[0]}.{self.key[1]}"


def _qual_match(full: str, pattern: str) -> bool:
    """Dotted suffix match in either direction, so budget qualnames
    written against the package match fixture/tmp modules whose
    module name is just the file stem."""
    return (full == pattern or full.endswith("." + pattern)
            or pattern.endswith("." + full))


class _Module:
    def __init__(self, path: pathlib.Path, pkg_root: pathlib.Path):
        self.path = path
        self.source = path.read_text()
        self.tree = ast.parse(self.source, filename=str(path))
        try:
            rel = path.relative_to(pkg_root.parent)
            self.name = str(rel.with_suffix("")).replace("/", ".")
        except ValueError:
            self.name = path.stem
        self.aliases: dict[str, str] = {}
        self.constants: dict[str, str] = {}   # NAME -> string constant
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    self.aliases[al.asname or al.name.split(".")[0]] = (
                        al.name)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for al in node.names:
                    self.aliases[al.asname or al.name] = (
                        f"{node.module}.{al.name}")
        for node in self.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                self.constants[node.targets[0].id] = node.value.value

    def resolve(self, d: list[str]) -> str:
        """Dotted chain -> fully-qualified name via the import map."""
        root = self.aliases.get(d[0], d[0])
        return ".".join([root] + d[1:])


class Analyzer:
    """Build the function/call graph, then check the four rule
    families. `run()` returns RAW findings (no suppression filtering —
    the seen-and-waived audit tests rely on that); `run_tracehazard`
    applies `core.FileSuppressions` and the budget allow lists."""

    def __init__(self, paths, budget: Optional[dict] = None):
        self.budget = budget if budget is not None else load_budget()
        self.budget_file = str(BUDGET_FILE)
        self.modules: list[_Module] = []
        self.funcs: dict[tuple, FuncNode] = {}
        self.mutated_globals: set[tuple] = set()   # (module, name)
        self.mutable_globals: dict[tuple, int] = {}  # (module, name) -> line
        for root in [pathlib.Path(p) for p in paths]:
            files = ([root] if root.is_file()
                     else sorted(root.rglob("*.py")))
            for f in files:
                self.modules.append(_Module(
                    f, root if root.is_dir() else root.parent))

    # -- phase 1: the function table -----------------------------------

    def _collect_funcs(self, m: _Module) -> None:
        def rec(stmts, qual, cls, parent):
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = f"{qual}.{st.name}" if qual else st.name
                    fn = FuncNode((m.name, q), str(m.path), st.lineno,
                                  st, cls=cls, parent=parent)
                    self.funcs[fn.key] = fn
                    if parent is not None:
                        self.funcs[parent].nested.append(fn.key)
                    rec(st.body, q, cls, fn.key)
                elif isinstance(st, ast.ClassDef):
                    q = f"{qual}.{st.name}" if qual else st.name
                    rec(st.body, q, st.name, parent)
                else:
                    for blk in ("body", "orelse", "finalbody"):
                        rec(getattr(st, blk, []) or [], qual, cls, parent)
                    for h in getattr(st, "handlers", []) or []:
                        rec(h.body, qual, cls, parent)
        rec(m.tree.body, "", None, None)
        for node in m.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, (ast.Dict, ast.List,
                                                ast.Set, ast.DictComp,
                                                ast.ListComp,
                                                ast.SetComp))):
                self.mutable_globals[(m.name, node.targets[0].id)] = (
                    node.lineno)

    # -- resolution helpers --------------------------------------------

    def _resolve_name(self, m: _Module, fn: Optional[FuncNode],
                      name: str) -> Optional[tuple]:
        """Bare name -> FuncNode key: nested siblings of the enclosing
        chain first, then module scope, then from-imports."""
        cur = fn
        while cur is not None:
            for k in cur.nested:
                if k[1].rsplit(".", 1)[-1] == name:
                    return k
            cur = self.funcs.get(cur.parent) if cur.parent else None
        if (m.name, name) in self.funcs:
            return (m.name, name)
        full = m.aliases.get(name)
        if full:
            return self._match_full(full)
        return None

    def _match_full(self, full: str) -> Optional[tuple]:
        for mod in self.modules:
            pre = mod.name + "."
            if full.startswith(pre):
                qual = full[len(pre):]
                if (mod.name, qual) in self.funcs:
                    return (mod.name, qual)
        return None

    def _resolve_call(self, m: _Module, fn: FuncNode,
                      call: ast.Call) -> CallEdge:
        d = _dotted(call.func)
        ev = CallEdge(call.lineno, None, d[-1] if d else "<expr>")
        if not d:
            return ev
        if len(d) == 1:
            ev.target = self._resolve_name(m, fn, d[0])
        elif d[0] == "self" and fn.cls is not None and len(d) == 2:
            # method on the enclosing class (qual may be Class.meth)
            holder = fn.key[1].rsplit(".", 2)
            for cand in (f"{fn.cls}.{d[1]}",):
                if (m.name, cand) in self.funcs:
                    ev.target = (m.name, cand)
            _ = holder
        else:
            ev.target = self._match_full(m.resolve(d))
        return ev

    # -- phase 2: per-function walk ------------------------------------

    def _walk_function(self, m: _Module, fn: FuncNode) -> None:
        node = fn.node

        # decorators: jit-decorated -> traced; jit decorator on a
        # NESTED def is also a per-call jit (cache-key arm)
        for dec in node.decorator_list:
            info = self._jit_call_info(m, dec)
            if info is not None:
                fn.traced = True
                fn.jit_static = info
                if fn.parent is not None:
                    fn.calls.append(CallEdge(dec.lineno, None,
                                             "jit-in-body"))

        def iter_no_defs(n):
            """Walk an expression/statement subtree without entering
            nested def bodies (lambdas ARE entered — they execute in
            the enclosing context often enough to matter)."""
            stack = [n]
            while stack:
                cur = stack.pop()
                yield cur
                for child in ast.iter_child_nodes(cur):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        continue
                    stack.append(child)

        def scan_expr(n, sanctioned):
            if n is None:
                return
            for sub in iter_no_defs(n):
                if isinstance(sub, ast.Call):
                    self._scan_call(m, fn, sub, sanctioned)
                elif isinstance(sub, ast.Subscript):
                    d = _dotted(sub.value)
                    if d and m.resolve(d) == "os.environ":
                        fn.env_reads.append((sub.lineno, "os.environ[...]"))
                elif isinstance(sub, (ast.If, ast.While)):
                    self._implicit_bool(fn, sub.test)
                elif isinstance(sub, (ast.Assign, ast.AugAssign)):
                    self._note_global_mutation(m, sub)

        def walk_stmts(stmts, sanctioned):
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(st, (ast.With, ast.AsyncWith)):
                    sanct = sanctioned
                    for item in st.items:
                        ce = item.context_expr
                        scan_expr(ce, sanctioned)
                        if (isinstance(ce, ast.Call)
                                and (_dotted(ce.func) or ["?"])[-1]
                                in _SANCTIONED_CTX):
                            sanct = True
                    walk_stmts(st.body, sanct)
                elif isinstance(st, ast.Try):
                    for part in (st.body, st.orelse, st.finalbody):
                        walk_stmts(part, sanctioned)
                    for h in st.handlers:
                        walk_stmts(h.body, sanctioned)
                elif isinstance(st, (ast.If, ast.While)):
                    scan_expr(st.test, sanctioned)
                    self._implicit_bool(fn, st.test)
                    walk_stmts(st.body, sanctioned)
                    walk_stmts(st.orelse, sanctioned)
                elif isinstance(st, (ast.For, ast.AsyncFor)):
                    scan_expr(st.iter, sanctioned)
                    walk_stmts(st.body, sanctioned)
                    walk_stmts(st.orelse, sanctioned)
                else:
                    scan_expr(st, sanctioned)

        walk_stmts(node.body, False)

    def _jit_call_info(self, m: _Module, expr) -> Optional[dict]:
        """`jax.jit` / `partial(jax.jit, ...)` expression -> static-arg
        info dict, else None."""
        if isinstance(expr, ast.Call):
            d = _dotted(expr.func)
            if d is None:
                return None
            full = m.resolve(d)
            if full == "jax.jit":
                return self._static_info(expr)
            if full in ("functools.partial", "partial") and expr.args:
                inner = _dotted(expr.args[0])
                if inner and m.resolve(inner) == "jax.jit":
                    return self._static_info(expr)
            return None
        d = _dotted(expr)
        if d and m.resolve(d) == "jax.jit":
            return {"argnums": (), "argnames": ()}
        return None

    @staticmethod
    def _static_info(call: ast.Call) -> dict:
        def lits(kwname):
            for kw in call.keywords:
                if kw.arg == kwname:
                    vals = []
                    nodes = (kw.value.elts
                             if isinstance(kw.value, (ast.Tuple, ast.List))
                             else [kw.value])
                    for e in nodes:
                        if isinstance(e, ast.Constant):
                            vals.append(e.value)
                    return tuple(vals)
            return ()
        return {"argnums": lits("static_argnums"),
                "argnames": lits("static_argnames")}

    def _implicit_bool(self, fn: FuncNode, test) -> None:
        d = _dotted(test)
        if d and len(d) >= 2 and d[-1] in _DEVICE_ATTRS:
            fn.sync_sites.append(SyncSite(
                test.lineno, f"implicit __bool__ on .{d[-1]}", False))

    def _note_global_mutation(self, m: _Module, st) -> None:
        tgt = st.target if isinstance(st, ast.AugAssign) else (
            st.targets[0] if st.targets else None)
        if isinstance(tgt, ast.Subscript) and isinstance(tgt.value,
                                                        ast.Name):
            self.mutated_globals.add((m.name, tgt.value.id))

    def _scan_call(self, m: _Module, fn: FuncNode, call: ast.Call,
                   sanctioned: bool) -> None:
        d = _dotted(call.func)
        if d is None:
            return
        full = m.resolve(d)
        terminal = d[-1]

        # mutation terminals on module globals (.append/.update/...)
        if (terminal in ("append", "update", "add", "extend", "insert",
                         "setdefault", "pop", "clear")
                and len(d) == 2):
            self.mutated_globals.add((m.name, d[0]))

        # env reads
        if full == "os.getenv" or full.startswith("os.environ."):
            fn.env_reads.append((call.lineno, full))

        # sync terminals
        if terminal == "item" and not call.args and len(d) >= 2:
            fn.sync_sites.append(SyncSite(call.lineno, ".item()",
                                          sanctioned))
        elif full in ("numpy.asarray", "numpy.array"):
            # a literal list/tuple/genexp argument is host-side
            # construction, not a device readback
            arg0 = call.args[0] if call.args else None
            if not isinstance(arg0, (ast.List, ast.ListComp, ast.Tuple,
                                     ast.GeneratorExp, ast.Constant)):
                fn.sync_sites.append(SyncSite(
                    call.lineno, f"{d[0]}.{terminal}(...)", sanctioned))
        elif terminal == "block_until_ready":
            fn.sync_sites.append(SyncSite(
                call.lineno, "block_until_ready", sanctioned))
        elif full == "jax.device_get":
            fn.sync_sites.append(SyncSite(call.lineno, "jax.device_get",
                                          sanctioned))
        elif (len(d) == 1 and terminal in ("float", "int", "bool")
                and call.args):
            arg = call.args[0]
            ad = _dotted(arg)
            if (ad and len(ad) >= 2 and ad[-1] in _DEVICE_ATTRS):
                fn.sync_sites.append(SyncSite(
                    call.lineno,
                    f"{terminal}() over device field .{ad[-1]}",
                    sanctioned))

        # trace entries: mark function-valued args as traced
        if full in TRACE_ENTRIES or terminal == "shard_map":
            cands = list(call.args[:1])
            if full in ("jax.lax.while_loop", "jax.lax.cond"):
                cands = list(call.args[:2])
            elif full == "jax.lax.switch":
                cands = list(call.args[1:2])
                if (len(call.args) >= 2
                        and isinstance(call.args[1],
                                       (ast.Tuple, ast.List))):
                    cands = list(call.args[1].elts)
            elif full == "jax.lax.fori_loop":
                cands = list(call.args[2:3])
            for a in cands:
                tgt = None
                if isinstance(a, ast.Name):
                    tgt = self._resolve_name(m, fn, a.id)
                if tgt is not None:
                    tfn = self.funcs[tgt]
                    tfn.traced = True
                    if full == "jax.jit":
                        tfn.jit_static = self._static_info(call)

        # per-call jit construction (cache-key arm): any jax.jit
        # evaluated inside a def body builds a fresh compile cache
        info = self._jit_call_info(m, call)
        if info is not None and isinstance(call.func, (ast.Attribute,
                                                       ast.Name)):
            d2 = _dotted(call.func)
            if d2 and m.resolve(d2) == "jax.jit":
                fn.calls.append(CallEdge(call.lineno, None,
                                         "jit-in-body"))

        fn.calls.append(self._resolve_call(m, fn, call))

    # -- phase 3: closures ---------------------------------------------

    def _closure(self, roots: list[tuple]) -> dict[tuple, tuple]:
        """BFS over call edges + parent->nested edges; returns
        reached key -> predecessor key (roots map to themselves)."""
        pred: dict[tuple, tuple] = {r: r for r in roots}
        work = list(roots)
        while work:
            k = work.pop()
            fn = self.funcs.get(k)
            if fn is None:
                continue
            succs = list(fn.nested)
            succs += [ev.target for ev in fn.calls
                      if ev.target is not None]
            for s in succs:
                if s not in pred:
                    pred[s] = k
                    work.append(s)
        return pred

    def _chain(self, pred: dict, key: tuple, limit: int = 6) -> str:
        names = [key[1].rsplit(".", 1)[-1]]
        cur = key
        while pred.get(cur) != cur and len(names) < limit:
            cur = pred[cur]
            names.append(cur[1].rsplit(".", 1)[-1])
        return " <- ".join(names)

    # -- phase 4: findings ---------------------------------------------

    def run(self) -> list[Finding]:
        for m in self.modules:
            self._collect_funcs(m)
        mod_by_name = {m.name: m for m in self.modules}
        for k, fn in self.funcs.items():
            self._walk_function(mod_by_name[k[0]], fn)

        out: list[Finding] = []
        out += self._check_sync_in_async()
        out += self._check_env_in_trace()
        out += self._check_cache_keys(mod_by_name)
        out += self._check_collectives(mod_by_name)
        return out

    def _async_roots(self) -> tuple[list[tuple], list[Finding]]:
        roots, findings = [], []
        try:
            btext = pathlib.Path(self.budget_file).read_text()
        except OSError:
            btext = ""
        for ent in self.budget.get("async_roots", ()):
            q = ent["qualname"]
            hits = [k for k, f in self.funcs.items()
                    if _qual_match(f.full, q)]
            if not hits:
                findings.append(Finding(
                    core.TRACE_STALE, self.budget_file,
                    _line_of(btext, q),
                    f"async root {q!r} matches no function in the "
                    f"scanned tree — update trace_hazard.json"))
            roots += hits
        return roots, findings

    def _check_sync_in_async(self) -> list[Finding]:
        roots, out = self._async_roots()
        pred = self._closure(roots)
        for k in pred:
            fn = self.funcs.get(k)
            if fn is None:
                continue
            for site in fn.sync_sites:
                if site.sanctioned:
                    continue
                out.append(Finding(
                    core.SYNC_IN_ASYNC, fn.file, site.line,
                    f"blocking host sync {site.what} on the async hot "
                    f"path ({self._chain(pred, k)}) outside an "
                    f"obs.ledger.readback/readback_deferred bracket — "
                    f"this re-serializes the dispatch pipeline",
                    entry=fn.full))
        return out

    def _check_env_in_trace(self) -> list[Finding]:
        roots = [k for k, f in self.funcs.items() if f.traced]
        pred = self._closure(roots)
        out = []
        for k in pred:
            fn = self.funcs.get(k)
            if fn is None:
                continue
            for line, what in fn.env_reads:
                out.append(Finding(
                    core.ENV_IN_TRACE, fn.file, line,
                    f"{what} read inside traced code "
                    f"({self._chain(pred, k)}): the value is baked "
                    f"into the executable at trace time and invisible "
                    f"to the jit cache — flipping it later silently "
                    f"reuses the stale compile (the PR-8 bug shape)",
                    entry=fn.full))
        return out

    def _check_cache_keys(self, mod_by_name) -> list[Finding]:
        out = []
        for k, fn in self.funcs.items():
            for ev in fn.calls:
                if ev.terminal == "jit-in-body":
                    out.append(Finding(
                        core.CACHE_KEY_UNSTABLE, fn.file, ev.line,
                        f"jax.jit evaluated inside `{k[1]}` builds a "
                        f"FRESH compile cache per call — hoist to "
                        f"module scope or memoize via a plan cache",
                        entry=fn.full))
        # traced functions reading module-level mutable containers the
        # package also mutates: the trace snapshots the value
        for k, fn in self.funcs.items():
            if not fn.traced:
                continue
            m = mod_by_name[k[0]]
            reads = set()
            for sub in ast.walk(fn.node):
                if (isinstance(sub, ast.Name)
                        and isinstance(sub.ctx, ast.Load)):
                    g = (m.name, sub.id)
                    if (g in self.mutable_globals
                            and g in self.mutated_globals
                            and g not in reads):
                        reads.add(g)
                        out.append(Finding(
                            core.CACHE_KEY_UNSTABLE, fn.file,
                            sub.lineno,
                            f"traced `{k[1]}` closes over mutable "
                            f"module global `{sub.id}` (mutated "
                            f"elsewhere in the package): the compiled "
                            f"executable keeps the trace-time "
                            f"snapshot — a later mutation is a silent "
                            f"stale answer", entry=fn.full))
        # literal lambda/list/dict passed in a declared static position
        for m in self.modules:
            out += self._check_static_call_sites(m)
        return out

    def _check_static_call_sites(self, m: _Module) -> list[Finding]:
        """Call sites of jit-wrapped names: a literal lambda/list/dict
        in a static_argnums/static_argnames position mints a fresh
        cache key per call."""
        out = []
        wrapped: dict[str, tuple] = {}   # local name -> (static info, params)
        for node in ast.walk(m.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                info = self._jit_call_info(m, node.value)
                if info is None or not node.value.args:
                    continue
                inner = _dotted(node.value.args[0] if m.resolve(
                    _dotted(node.value.func) or ["?"]) != "jax.jit"
                    else node.value.args[0])
                # jax.jit(f, ...): wrapped fn is args[0]; partial form
                # has jax.jit at args[0] and no wrapped fn yet
                tgt = None
                fd = _dotted(node.value.args[0])
                if fd and len(fd) == 1:
                    tgt = self._resolve_name(m, None, fd[0])
                _ = inner
                if tgt is None:
                    continue
                params = [a.arg for a in self.funcs[tgt].node.args.args]
                wrapped[node.targets[0].id] = (info, params)
        for k, fn in self.funcs.items():
            if k[0] != m.name:
                continue
            name = k[1].rsplit(".", 1)[-1]
            if fn.jit_static is not None and not fn.traced:
                continue
            if fn.jit_static is not None:
                params = [a.arg for a in fn.node.args.args]
                wrapped.setdefault(name, (fn.jit_static, params))
        for node in ast.walk(m.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in wrapped):
                continue
            info, params = wrapped[node.func.id]
            static_pos = set(info["argnums"])
            static_pos |= {params.index(n) for n in info["argnames"]
                           if n in params}
            for i, a in enumerate(node.args):
                if i in static_pos and isinstance(
                        a, (ast.Lambda, ast.List, ast.Dict, ast.Set)):
                    out.append(Finding(
                        core.CACHE_KEY_UNSTABLE, str(m.path),
                        a.lineno,
                        f"literal {type(a).__name__.lower()} passed in "
                        f"static position {i} of jitted "
                        f"`{node.func.id}`: a fresh object per call = "
                        f"a fresh cache key per call (retrace drift)"))
            for kw in node.keywords:
                if kw.arg in info["argnames"] and isinstance(
                        kw.value, (ast.Lambda, ast.List, ast.Dict,
                                   ast.Set)):
                    out.append(Finding(
                        core.CACHE_KEY_UNSTABLE, str(m.path),
                        kw.value.lineno,
                        f"literal {type(kw.value).__name__.lower()} "
                        f"passed as static `{kw.arg}` of jitted "
                        f"`{node.func.id}`: a fresh object per call = "
                        f"a fresh cache key per call (retrace drift)"))
        return out

    # -- collective safety ---------------------------------------------

    def _axis_strings(self, m: _Module, fn: Optional[FuncNode], expr,
                      local_assigns: dict, depth: int = 0) -> tuple:
        """(resolved axis strings, unknown literal strings). Resolves
        Name refs through module constants, imported axis constants,
        and single local assignments."""
        resolved, unknown = [], []
        vocab = set(self.budget.get("axis_vocabulary", ()))
        if expr is None or depth > 6:
            return (), ()

        def rec(e, depth):
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                (resolved if e.value in vocab else unknown).append(
                    (e.value, e.lineno))
            elif isinstance(e, (ast.Tuple, ast.List)):
                for el in e.elts:
                    rec(el, depth)
            elif isinstance(e, ast.Call):
                # P("r", None) partition specs: axis names are the args
                for a in e.args:
                    rec(a, depth)
            elif isinstance(e, ast.BinOp):
                # (P(...),) * 3 + (P(...),) spec arithmetic
                rec(e.left, depth)
                rec(e.right, depth)
            elif isinstance(e, ast.IfExp):
                rec(e.body, depth)
                rec(e.orelse, depth)
            elif isinstance(e, (ast.Name, ast.Attribute)):
                d = _dotted(e)
                if d is None:
                    return
                val = self._axis_const(m, d)
                if val is not None:
                    (resolved if val in vocab else unknown).append(
                        (val, e.lineno))
                elif (len(d) == 1 and d[0] in local_assigns
                        and depth < 6):
                    rec(local_assigns[d[0]], depth + 1)
        rec(expr, depth)
        return tuple(resolved), tuple(unknown)

    def _axis_const(self, m: _Module, d: list[str]) -> Optional[str]:
        if len(d) == 1 and d[0] in m.constants:
            return m.constants[d[0]]
        full = m.resolve(d)
        for mod in self.modules:
            pre = mod.name + "."
            if full.startswith(pre):
                name = full[len(pre):]
                if name in mod.constants:
                    return mod.constants[name]
        return None

    def _check_collectives(self, mod_by_name) -> list[Finding]:
        out: list[Finding] = []
        matched_pairs: set[int] = set()
        pairs = list(self.budget.get("transpose_pairs", ()))
        for m in self.modules:
            parents = {c: p for p in ast.walk(m.tree)
                       for c in ast.iter_child_nodes(p)}
            for node in ast.walk(m.tree):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                if not d or d[-1] != "shard_map":
                    continue
                out += self._check_one_shard_map(
                    m, node, parents, pairs, matched_pairs)
        try:
            btext = pathlib.Path(self.budget_file).read_text()
        except OSError:
            btext = ""
        for i, ent in enumerate(pairs):
            if i not in matched_pairs and not ent.get("allow_stale"):
                out.append(Finding(
                    core.TRACE_STALE, self.budget_file,
                    _line_of(btext, ent.get("function", "?")),
                    f"transpose_pairs entry "
                    f"{ent.get('module')}:{ent.get('function')} over "
                    f"axes {ent.get('axes')} matches no multi-axis "
                    f"ppermute in the tree — update "
                    f"trace_hazard.json"))
        return out

    def _enclosing_topdef(self, parents, node) -> Optional[str]:
        name = None
        cur = node
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = cur.name
        return name

    def _check_one_shard_map(self, m, call, parents, pairs,
                             matched_pairs) -> list[Finding]:
        out: list[Finding] = []
        topdef = self._enclosing_topdef(parents, call)
        # innermost enclosing FuncNode of the call site, so the body
        # name resolves LEXICALLY (several functions in one module
        # define a shard_map body named `f`)
        encl_fn = None
        cur = call
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for k, f in self.funcs.items():
                    if k[0] == m.name and f.node is cur:
                        encl_fn = f
                break
        # the body function: first positional arg
        body_node = None
        if call.args:
            a = call.args[0]
            if isinstance(a, ast.Lambda):
                body_node = a
            elif isinstance(a, ast.Name):
                fn_key = self._resolve_name(m, encl_fn, a.id)
                if fn_key is not None:
                    body_node = self.funcs[fn_key].node
        # local assignments in the enclosing function, for spec/axis
        # indirection (spec4 = P(...); tperm = [...])
        local_assigns: dict[str, object] = {}
        encl = encl_fn.node if encl_fn is not None else None
        if encl is not None:
            for sub in ast.walk(encl):
                if (isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Name)):
                    local_assigns[sub.targets[0].id] = sub.value

        spec_axes: set[str] = set()
        for kw in call.keywords:
            if kw.arg in ("in_specs", "out_specs"):
                res, _unk = self._axis_strings(m, None, kw.value,
                                               local_assigns)
                spec_axes |= {s for s, _ in res}
        if body_node is None:
            return out

        for sub in ast.walk(body_node):
            if not isinstance(sub, ast.Call):
                continue
            d = _dotted(sub.func)
            if not d or d[-1] not in COLLECTIVES:
                continue
            full = m.resolve(d)
            if not (full.startswith("jax.lax.") or full.startswith(
                    "jax.") and d[-1] in COLLECTIVES and len(d) >= 2):
                if len(d) == 1:
                    continue
            pos = COLLECTIVES[d[-1]]
            axis_expr = None
            if len(sub.args) > pos:
                axis_expr = sub.args[pos]
            else:
                for kw in sub.keywords:
                    if kw.arg in ("axis_name", "axes", "axis"):
                        axis_expr = kw.value
            if axis_expr is None:
                continue
            res, unk = self._axis_strings(m, None, axis_expr,
                                          local_assigns)
            for val, line in unk:
                out.append(Finding(
                    core.COLLECTIVE_AXIS, str(m.path), line,
                    f"`{d[-1]}` over unknown axis name {val!r} — not "
                    f"in the mesh axis vocabulary "
                    f"{sorted(self.budget.get('axis_vocabulary', ()))} "
                    f"(typo, or update trace_hazard.json)",
                    entry=topdef or ""))
            names = [v for v, _ in res]
            if spec_axes:
                for val, line in res:
                    if val not in spec_axes:
                        out.append(Finding(
                            core.COLLECTIVE_AXIS, str(m.path), line,
                            f"`{d[-1]}` over axis {val!r} but this "
                            f"shard_map's in/out specs only declare "
                            f"{sorted(spec_axes)} — on a mesh without "
                            f"{val!r} this hangs or silently "
                            f"misreduces", entry=topdef or ""))
            # transpose pairing = a SYNTACTIC tuple of >=2 axes (an
            # IfExp picking one axis per call is still single-axis)
            ax = axis_expr
            hops = 0
            while (isinstance(ax, ast.Name) and ax.id in local_assigns
                   and hops < 6):
                ax = local_assigns[ax.id]
                hops += 1
            multi = (isinstance(ax, (ast.Tuple, ast.List))
                     and len(ax.elts) >= 2)
            if d[-1] == "ppermute" and multi and len(set(names)) >= 2:
                hit = None
                for i, ent in enumerate(pairs):
                    if (_qual_match(m.name, ent.get("module", ""))
                            and topdef == ent.get("function")
                            and sorted(set(names))
                            == sorted(set(ent.get("axes", ())))):
                        hit = i
                        break
                if hit is not None:
                    matched_pairs.add(hit)
                else:
                    out.append(Finding(
                        core.COLLECTIVE_TRANSPOSE, str(m.path),
                        sub.lineno,
                        f"multi-axis ppermute over "
                        f"{sorted(set(names))} in `{topdef}` is the "
                        f"square-mesh transpose pairing — it silently "
                        f"misroutes on rectangular/3D meshes. Guard "
                        f"eligibility and declare it in "
                        f"trace_hazard.json transpose_pairs",
                        entry=topdef or ""))
        return out


# -- jaxpr arm: collective axes of a traced entry ----------------------

def jaxpr_collective_axes(jaxpr) -> set[str]:
    """All collective axis names appearing in a (Closed)Jaxpr,
    recursively through nested call/control-flow jaxprs — the dynamic
    cross-check the green mesh tests run against the declared axis
    vocabulary."""
    core_jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    axes: set[str] = set()

    def visit(j):
        for eqn in j.eqns:
            for key in ("axes", "axis_name", "axis_index_groups"):
                if key in ("axes", "axis_name") and key in eqn.params:
                    v = eqn.params[key]
                    vs = v if isinstance(v, (tuple, list)) else (v,)
                    axes.update(x for x in vs if isinstance(x, str))
            for v in eqn.params.values():
                sub = getattr(v, "jaxpr", None)
                if sub is not None and hasattr(sub, "eqns"):
                    visit(sub)
                elif hasattr(v, "eqns"):
                    visit(v)
                elif isinstance(v, (tuple, list)):
                    for vv in v:
                        sub = getattr(vv, "jaxpr", None)
                        if sub is not None and hasattr(sub, "eqns"):
                            visit(sub)
    visit(core_jaxpr)
    return axes


# -- entry point -------------------------------------------------------

def run_tracehazard(paths=None, budget_file=None) -> list[Finding]:
    """Run pass 7; returns findings surviving source suppressions
    (`core.FileSuppressions`, so a waiver on a `with` line covers its
    block) and the budget's `"allow"` rule list."""
    if paths is None:
        paths = [pathlib.Path(__file__).parents[1]]
    bfile = pathlib.Path(budget_file or BUDGET_FILE)
    budget = load_budget(bfile)
    an = Analyzer(paths, budget)
    an.budget_file = str(bfile)
    raw = an.run()
    allowed = set(budget.get("allow", ()))
    sup_cache: dict[str, core.FileSuppressions] = {}
    out = []
    for f in raw:
        if f.rule in allowed:
            continue
        if f.file == str(bfile):
            out.append(f)
            continue
        fs = sup_cache.get(f.file)
        if fs is None:
            try:
                fs = core.FileSuppressions(
                    pathlib.Path(f.file).read_text())
            except OSError:
                fs = core.FileSuppressions("")
            sup_cache[f.file] = fs
        if not fs.covers(f):
            out.append(f)
    return out
