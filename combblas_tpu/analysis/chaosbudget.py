"""Pass 8 — chaos-recovery budget over committed soak artifacts.

`scripts/chaos_bench.py` drives the committed fault schedule
(`scripts/chaos_schedule.json`) against a live serving workload, a
phased SpGEMM, and an MCL checkpoint/resume pair, and records the
outcome in a `chaos_summary` block inside `CHAOS_r*.json`. This pass
holds that block against `analysis/budgets/chaos.json`, committing the
resilience layer's recovery invariants the same way pass 4 commits
attribution coverage:

* **unresolved handles** — every future submitted under faults must
  resolve (result OR error). A hang is the one outcome the worker
  supervision layer exists to prevent; the ceiling is 0.
* **shed budget** — the faulted phase may shed load (breaker opens,
  predictive shed), but only up to a committed fraction. Unbounded
  shedding under bounded faults means recovery regressed into refusal.
* **bit-exactness** — once faults clear, the SAME service must return
  results bit-identical to the fault-free reference, the
  fault-recovered SpGEMM must match the clean product, and a resumed
  solver must match its uninterrupted run. Anything else means a fault
  leaked state (poisoned cache, stuck breaker, lost worker).
* **recovery floors** — the soak must actually bite: a minimum number
  of injected faults and observed retries (a soak that injected
  nothing proves nothing), and a floor on the fraction of faulted
  queries that still succeeded.
* **staleness** — a budget naming an artifact or a `chaos_summary`
  field that no longer exists is flagged rather than silently vacuous.

Budget JSON shape (one file may pin several artifacts)::

    {"artifacts": [{
        "artifact": "CHAOS_r*.json",   # repo-root relative; globs pick
                                       # newest by mtime
        "driver": "chaos",
        "unresolved_handles_max": 0,
        "shed_frac_max": 0.25,
        "require_bit_exact": true,     # serve results after clear AND
                                       # the faulted SpGEMM product
        "require_checkpoint_resume_exact": true,
        "min_faults_injected": 5,
        "min_retries": 1,
        "recovery_frac_min": 0.75,
        "allow": []                    # waived rule ids
    }]}

All checks are pure JSON reads — nothing here compiles or runs device
code. A numeric check whose `chaos_summary` field is absent flags
STALE (shape drift), never passes silently.
"""

from __future__ import annotations

import json
import pathlib

from combblas_tpu.analysis import core
from combblas_tpu.analysis.core import Finding
from combblas_tpu.analysis.obsbudget import (
    _line_of, _load_artifact, _resolve_artifact,
)

BUDGET_DIR = pathlib.Path(__file__).parent / "budgets"
REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]


def check_artifact(ent: dict, budget_text: str, budget_path: str,
                   root=None) -> list[Finding]:
    """All findings for one budget entry (the unit the self-test
    fixtures drive)."""
    allow = set(ent.get("allow", []))
    name = ent["artifact"]
    driver = ent.get("driver", name)
    findings: list[Finding] = []

    def add(rule, key, msg):
        if rule not in allow:
            findings.append(Finding(
                rule, budget_path, _line_of(budget_text, name, key),
                msg, entry=driver))

    path = _resolve_artifact(name, pathlib.Path(root or REPO_ROOT))
    if path is None:
        add(core.CHAOS_STALE, "artifact",
            f"artifact {name!r} not found — run scripts/chaos_bench.py "
            "to generate it, or drop the stale budget entry")
        return findings
    try:
        art = _load_artifact(path)
    except ValueError as e:
        add(core.CHAOS_STALE, "artifact", f"artifact unreadable: {e}")
        return findings
    cs = art.get("chaos_summary")
    if not isinstance(cs, dict):
        add(core.CHAOS_STALE, "artifact",
            f"{path.name}: no chaos_summary block — not a chaos soak "
            "artifact (rerun scripts/chaos_bench.py)")
        return findings

    def field(key: str, budget_key: str):
        """(value, present) of a summary field a budget check needs;
        absence is shape drift and flags STALE."""
        if key not in cs:
            add(core.CHAOS_STALE, budget_key,
                f"{path.name}: chaos_summary has no {key!r} field — "
                "the artifact shape drifted from the budget")
            return None, False
        return cs[key], True

    ceil = ent.get("unresolved_handles_max")
    if ceil is not None:
        v, ok = field("unresolved_handles", "unresolved_handles_max")
        if ok and int(v) > int(ceil):
            add(core.CHAOS_UNRESOLVED, "unresolved_handles_max",
                f"{path.name}: {int(v)} serve future(s) never resolved "
                f"under faults (ceiling {int(ceil)}) — the supervision "
                "layer let a request hang")

    frac_max = ent.get("shed_frac_max")
    if frac_max is not None:
        v, ok = field("shed_frac", "shed_frac_max")
        if ok and float(v) > float(frac_max):
            add(core.CHAOS_SHED, "shed_frac_max",
                f"{path.name}: faulted-phase shed fraction "
                f"{float(v):.1%} exceeds the committed ceiling "
                f"{float(frac_max):.1%} — recovery regressed into "
                "load refusal")

    if ent.get("require_bit_exact"):
        for key in ("bit_exact_after_clear", "spgemm_faulted_bit_exact"):
            v, ok = field(key, "require_bit_exact")
            if ok and not v:
                add(core.CHAOS_BIT_EXACT, "require_bit_exact",
                    f"{path.name}: {key} is false — a fault leaked "
                    "state into post-recovery results")

    if ent.get("require_checkpoint_resume_exact"):
        v, ok = field("checkpoint_resume_exact",
                      "require_checkpoint_resume_exact")
        if ok and not v:
            add(core.CHAOS_BIT_EXACT, "require_checkpoint_resume_exact",
                f"{path.name}: checkpoint_resume_exact is false — a "
                "resumed solver diverged from its uninterrupted run")

    for key, budget_key, what in (
            ("faults_injected", "min_faults_injected", "fault(s)"),
            ("retries", "min_retries", "retry/retries")):
        floor = ent.get(budget_key)
        if floor is None:
            continue
        v, ok = field(key, budget_key)
        if ok and int(v) < int(floor):
            add(core.CHAOS_RECOVERY, budget_key,
                f"{path.name}: only {int(v)} {what} recorded (floor "
                f"{int(floor)}) — the soak is vacuous; it no longer "
                "exercises the recovery paths it gates")

    floor = ent.get("recovery_frac_min")
    if floor is not None:
        v, ok = field("recovered_frac", "recovery_frac_min")
        if ok and float(v) < float(floor):
            add(core.CHAOS_RECOVERY, "recovery_frac_min",
                f"{path.name}: only {float(v):.1%} of faulted queries "
                f"recovered (floor {float(floor):.1%}) — retry/"
                "degradation stopped absorbing the committed schedule")
    return findings


def run_chaos(files=None, root=None) -> list[Finding]:
    """Run the chaos-recovery budget pass over the committed budgets
    (or an explicit fixture list); returns unsuppressed findings."""
    paths = ([pathlib.Path(f) for f in files] if files is not None
             else sorted(BUDGET_DIR.glob("chaos*.json")))
    findings: list[Finding] = []
    for p in paths:
        text = p.read_text()
        data = json.loads(text)
        for ent in data.get("artifacts", []):
            if "artifact" not in ent:
                raise ValueError(f"{p}: chaos budget entry without "
                                 "'artifact'")
            findings += check_artifact(ent, text, str(p), root=root)
    return findings
