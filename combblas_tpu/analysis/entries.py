"""Registered analysis entry points: the kernels the budget engine
lowers and checks.

Each entry is a named `EntrySpec` whose `build()` returns the jittable
callable plus concrete fixture arguments (small, deterministic, built
once and cached — trace-only lowering never executes them). Entries
may also expose *variants*: alternate arguments whose LOWERED OP
STRUCTURE must be identical to the primary one (the bits path's
lane-width invariance: lanes ride array shapes, never Python
unrolling).

Budget JSON files under `analysis/budgets/` reference entries by
name; `budget.run_budgets()` joins the two. Fixtures mirror the
shapes `tests/test_hlo_passes.py` historically pinned so the ported
ceilings keep their meaning.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Optional

import numpy as np

_REGISTRY: dict[str, "EntrySpec"] = {}


@dataclasses.dataclass(frozen=True)
class EntrySpec:
    """One analyzable kernel entry point."""

    name: str
    build: Callable[[], dict]    # -> {"fn":..., "args":..., "variants":{...}}
    doc: str = ""


def register(name: str, doc: str = ""):
    def deco(build):
        _REGISTRY[name] = EntrySpec(name, build, doc)
        return build
    return deco


def get(name: str) -> EntrySpec:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown analysis entry {name!r}; registered: "
            f"{sorted(_REGISTRY)}") from None


def names() -> list[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# fixtures (deterministic, cached; tiny — lowering only, never executed
# beyond construction)
# ---------------------------------------------------------------------------

def _rng():
    return np.random.default_rng(42)


@functools.lru_cache(maxsize=None)
def _tile_pair():
    import jax.numpy as jnp

    from combblas_tpu.ops import semiring as S  # noqa: F401
    from combblas_tpu.ops import tile as T
    rng = _rng()

    def one():
        d = rng.random((40, 40))
        d[rng.random((40, 40)) > 0.3] = 0
        return T.from_dense(jnp.asarray(d.astype(np.float32)),
                            jnp.asarray(0.0, jnp.float32), cap=600)
    return one(), one()


@functools.lru_cache(maxsize=None)
def _big_tile():
    """Tile whose FULL fused-key space overflows 2^31 — the window-
    relative codec must keep spgemm_colwindow on i32 keys."""
    import jax.numpy as jnp

    from combblas_tpu.ops import semiring as S
    from combblas_tpu.ops import tile as T
    rng = _rng()
    big, n = 1 << 17, 200
    r = jnp.asarray(rng.integers(0, big, n), jnp.int32)
    c = jnp.asarray(rng.integers(0, big, n), jnp.int32)
    v = jnp.ones((n,), jnp.float32)
    t = T.from_coo(S.PLUS, r, c, v, nrows=big, ncols=big, cap=256)
    assert T.fused_key_info(big, big) is None  # whole-tile key: no i32 dtype
    return t


@functools.lru_cache(maxsize=None)
def _graph_fixture():
    """256-vertex pattern-symmetric boolean graph on a 1x1 grid, with
    a routed BFS plan eligible for the packed-bit batch path."""
    import jax
    import jax.numpy as jnp

    from combblas_tpu.models import bfs as B
    from combblas_tpu.ops import semiring as S
    from combblas_tpu.parallel import distmat as DM
    from combblas_tpu.parallel.grid import ProcGrid
    rng = _rng()
    grid = ProcGrid.make(1, 1, jax.devices()[:1])
    n = 256
    r = rng.integers(0, n, 600).astype(np.int32)
    c = rng.integers(0, n, 600).astype(np.int32)
    rows = np.concatenate([r, c])
    cols = np.concatenate([c, r])
    a = DM.from_global_coo(S.LOR, grid, jnp.asarray(rows),
                           jnp.asarray(cols),
                           jnp.ones(len(rows), jnp.bool_), n, n)
    plan = B.plan_bfs(a, route=True)
    assert B.bits_batch_ok(a, plan), "graph fixture must be bits-eligible"
    return a, plan


@functools.lru_cache(maxsize=None)
def _spmv_fixture():
    """64-vertex float32 matrix + column-aligned operand vector on a
    1x1 grid (the serve engine's mesh for the batch executors)."""
    import jax
    import jax.numpy as jnp

    from combblas_tpu.ops import semiring as S
    from combblas_tpu.parallel import distmat as DM
    from combblas_tpu.parallel import distvec as dv
    from combblas_tpu.parallel.grid import COL_AXIS, ProcGrid
    rng = _rng()
    grid = ProcGrid.make(1, 1, jax.devices()[:1])
    n = 64
    r = jnp.asarray(rng.integers(0, n, 300), jnp.int32)
    c = jnp.asarray(rng.integers(0, n, 300), jnp.int32)
    a = DM.from_global_coo(S.PLUS, grid, r, c,
                           jnp.ones((300,), jnp.float32), n, n)
    x = dv.from_global(grid, COL_AXIS, jnp.asarray(
        rng.random(n).astype(np.float32)), block=a.tile_n)
    return a, x


@functools.lru_cache(maxsize=None)
def _mcl_fixture():
    """64-vertex col-stochastic float32 matrix on a 1x1 grid, capacity
    deliberately off the re-pin target so the mega-step's grow branch
    lowers (concat + sentinel fill), not the `new_cap == cap` no-op."""
    import jax

    from combblas_tpu.models import mcl as M
    from combblas_tpu.ops import semiring as S
    from combblas_tpu.parallel import distmat as DM
    from combblas_tpu.parallel.grid import ProcGrid
    rng = _rng()
    grid = ProcGrid.make(1, 1, jax.devices()[:1])
    n = 64
    d = rng.random((n, n)).astype(np.float32)
    d[rng.random((n, n)) > 0.2] = 0
    a = DM.from_dense(S.PLUS, grid, d, 0.0, cap=896)
    return M.make_col_stochastic(a)


@functools.lru_cache(maxsize=None)
def _route_fixture():
    import jax.numpy as jnp

    from combblas_tpu.ops import route as R
    rng = _rng()
    npad = 256
    perm = rng.permutation(npad).astype(np.int64)
    rp = R.plan_route(perm)
    words = {w: jnp.asarray(
        rng.integers(0, 1 << 32, (npad // 32, w), dtype=np.uint64)
        .astype(np.uint32)) for w in (8, 16)}
    return rp, words


# ---------------------------------------------------------------------------
# entries: ESC SpGEMM pipeline
# ---------------------------------------------------------------------------

@register("esc.spgemm", "ESC SpGEMM A*B on the default fused-key path")
def _esc_spgemm():
    from combblas_tpu.ops import semiring as S
    from combblas_tpu.ops import tile as T
    a, b = _tile_pair()
    fn = lambda a, b: T.spgemm(S.PLUS_TIMES_F32, a, b,   # noqa: E731
                               flops_cap=4096, out_cap=1024)
    return {"fn": fn, "args": (a, b)}


@register("esc.spgemm_2key", "reference 2-key ESC path "
          "(COMBBLAS_TPU_FUSED_KEY=0): 3 operands per sort")
def _esc_spgemm_2key():
    return _esc_spgemm()          # env override comes from the budget file


@register("esc.colwindow", "windowed SpGEMM with the window-relative "
          "i32 key codec (full key space overflows 2^31)")
def _esc_colwindow():
    import jax.numpy as jnp

    from combblas_tpu.ops import semiring as S
    from combblas_tpu.ops import tile as T
    t = _big_tile()

    def fn(t, clo, chi):
        return T.spgemm_colwindow(S.PLUS_TIMES_F32, t, t, clo, chi,
                                  flops_cap=2048, out_cap=512,
                                  win_width=128)
    return {"fn": fn,
            "args": (t, jnp.asarray(0, jnp.int32),
                     jnp.asarray(128, jnp.int32))}


@register("esc.dense_window", "sort-free dense-accumulator window "
          "variant: monoid scatter into an (nrows, win_width) buffer, "
          "prefix-scan compaction — the budget pins ZERO sorts")
def _esc_dense_window():
    import jax.numpy as jnp

    from combblas_tpu.ops import semiring as S
    from combblas_tpu.ops import tile as T
    a, b = _tile_pair()

    def fn(a, b, clo, chi):
        return T.spgemm_colwindow_dense(S.PLUS_TIMES_F32, a, b, clo, chi,
                                        flops_cap=2048, out_cap=512,
                                        win_width=40)
    return {"fn": fn,
            "args": (a, b, jnp.asarray(0, jnp.int32),
                     jnp.asarray(40, jnp.int32))}


@register("esc.hash_window", "hash-accumulator window variant on the "
          "XLA segment fallback (Pallas off: the default CPU lowering)")
def _esc_hash_window():
    import jax.numpy as jnp

    from combblas_tpu.ops import semiring as S
    from combblas_tpu.ops import tile as T
    a, b = _tile_pair()

    def fn(a, b, clo, chi):
        return T.spgemm_colwindow_hash(S.PLUS_TIMES_F32, a, b, clo, chi,
                                       flops_cap=2048, out_cap=512,
                                       win_width=40)
    return {"fn": fn,
            "args": (a, b, jnp.asarray(0, jnp.int32),
                     jnp.asarray(40, jnp.int32))}


@register("esc.block_window", "block-format window SpGEMM: monoid "
          "scatter straight into the padded (bm, bn) block layout — "
          "output STAYS in block form, so the budget pins ZERO sorts "
          "(no COO compaction tail at all)")
def _esc_block_window():
    import jax.numpy as jnp

    from combblas_tpu.ops import blocktile as BK
    from combblas_tpu.ops import semiring as S
    a, b = _tile_pair()

    def fn(a, b, clo, chi):
        return BK._spgemm_colwindow_block_impl(
            S.PLUS_TIMES_F32, a, b, clo, chi, flops_cap=2048,
            win_width=40, bm=8, bn=128, pallas_mode="off")
    return {"fn": fn,
            "args": (a, b, jnp.asarray(0, jnp.int32),
                     jnp.asarray(40, jnp.int32))}


# ---------------------------------------------------------------------------
# entries: SpMV / SpMM
# ---------------------------------------------------------------------------

@register("mcl.megastep", "fused MCL iteration tail: re-pin + inflate "
          "(Hadamard power + column re-normalization) + chaos, one "
          "executable with donated matrix carry")
def _mcl_megastep():
    from combblas_tpu.models import mcl as M
    a = _mcl_fixture()
    fn = lambda a: M._megastep_body(a, power=2.0,      # noqa: E731
                                    new_cap=1024)
    return {"fn": fn, "args": (a,)}


@register("spmv.plus_times_f32", "distributed dense-vector SpMV")
def _spmv():
    from combblas_tpu.ops import semiring as S
    from combblas_tpu.parallel import spmv as SV
    a, x = _spmv_fixture()
    return {"fn": lambda a, x: SV.spmv(S.PLUS_TIMES_F32, a, x),
            "args": (a, x)}


@register("spmm.plus_times_f32", "serve-engine SpMM: stacked operand "
          "columns through densemat.spmm (the spmv batch executor)")
def _spmm():
    import jax.numpy as jnp

    from combblas_tpu.ops import semiring as S
    from combblas_tpu.parallel import densemat as dmm
    from combblas_tpu.parallel.grid import COL_AXIS
    a, _ = _spmv_fixture()
    sr = S.PLUS_TIMES_F32
    grid, tn, glen = a.grid, a.tile_n, a.ncols

    def fn(a, arr):                         # arr: (glen, W) — engine shape
        data = jnp.pad(arr, ((0, grid.pc * tn - glen), (0, 0)))
        x = dmm.DistMultiVec(
            data.reshape(grid.pc, tn, arr.shape[1]), grid, COL_AXIS, glen)
        return dmm.spmm(sr, a, x).data

    arr = jnp.zeros((glen, 4), jnp.float32)
    return {"fn": fn, "args": (a, arr)}


# ---------------------------------------------------------------------------
# entries: BFS batch cores
# ---------------------------------------------------------------------------

@register("bfs.batch_dense", "dense-column multi-source BFS core "
          "(one while loop for the whole batch)")
def _bfs_batch():
    import jax.numpy as jnp

    from combblas_tpu.models import bfs as B
    a, plan = _graph_fixture()
    ml = jnp.int32(1 << 30)
    fn = lambda roots, ml: B.bfs_batch(a, roots, ml, plan=plan)  # noqa: E731
    return {"fn": fn, "args": (jnp.zeros((4,), jnp.int32), ml)}


@register("bfs.bits_core", "packed-bit multi-root BFS core: bitplane "
          "frontiers, 32 roots per word; lane-width invariant")
def _bfs_bits_core():
    import jax.numpy as jnp

    from combblas_tpu.models import bfs as B
    a, plan = _graph_fixture()
    ml = jnp.int32(1 << 30)
    fn = lambda roots, ml: B._bfs_batch_bits_core(  # noqa: E731
        a, plan, roots, ml)
    return {"fn": fn,
            "args": (jnp.zeros((8,), jnp.int32), ml),
            "variants": {"W=16": (fn, (jnp.zeros((16,), jnp.int32), ml))}}


# ---------------------------------------------------------------------------
# entries: bitseg / route multi-lane primitives
# ---------------------------------------------------------------------------

@register("bitseg.multi", "lane-parallel segmented OR scan+fill over an "
          "(nwords, W) bitplane matrix")
def _bitseg_multi():
    import jax.numpy as jnp

    from combblas_tpu.ops import bitseg as BS
    rng = _rng()
    nwords = 64

    def fn(x, starts):
        return (BS.seg_or_scan_bits_multi(x, starts),
                BS.seg_or_fill_bits_multi(x, starts))

    def mk(w):
        x = jnp.asarray(rng.integers(0, 1 << 32, (nwords, w),
                                     dtype=np.uint64).astype(np.uint32))
        s = jnp.asarray(rng.integers(0, 1 << 32, (nwords,),
                                     dtype=np.uint64).astype(np.uint32))
        return (x, s)

    return {"fn": fn, "args": mk(8), "variants": {"W=16": (fn, mk(16))}}


@register("route.multi", "Benes-network lane-matrix route: one shared "
          "mask decompaction serves every lane")
def _route_multi():
    from combblas_tpu.ops import route as R
    rp, words = _route_fixture()
    fn = lambda w: R.apply_route_multi(rp, w)   # noqa: E731
    return {"fn": fn, "args": (words[8],),
            "variants": {"W=16": (fn, (words[16],))}}


# ---------------------------------------------------------------------------
# entries: scale-out collectives (SUMMA exchange, mesh bits BFS)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _summa_fixture():
    """256-vertex symmetric float32 graph on the full 2x4 mesh plus
    its SUMMA caps — the hybrid-exchange collective budgets lower the
    whole distributed multiply."""
    import jax
    import jax.numpy as jnp

    from combblas_tpu.ops import semiring as S
    from combblas_tpu.parallel import distmat as DM
    from combblas_tpu.parallel import spgemm as SPG
    from combblas_tpu.parallel.grid import ProcGrid
    rng = _rng()
    grid = ProcGrid.make(2, 4, jax.devices()[:8])
    n = 256
    r = rng.integers(0, n, 600).astype(np.int32)
    c = rng.integers(0, n, 600).astype(np.int32)
    rows = np.concatenate([r, c])
    cols = np.concatenate([c, r])
    a = DM.from_global_coo(S.LOR, grid, jnp.asarray(rows),
                           jnp.asarray(cols),
                           jnp.ones(len(rows), jnp.bool_), n, n)
    a = a.astype(jnp.float32)
    fc, oc = SPG.plan_spgemm(a, a)
    return a, fc, oc


def _summa_exchange(mode):
    from combblas_tpu.ops import semiring as S
    from combblas_tpu.parallel import spgemm as SPG
    a, fc, oc = _summa_fixture()
    plan = SPG.plan_bcast(a, a, mode=mode)
    if mode == "sparse":
        assert any(v == "sparse" for st in plan for v in (st[0], st[2])), \
            "sparse fixture plan degenerated to dense rungs"

    def fn(a, b):
        return SPG.summa(S.PLUS_TIMES_F32, a, b, flops_cap=fc,
                         out_cap=oc, bcast_plan=plan)
    return {"fn": fn, "args": (a, a)}


@register("summa.hybrid", "distributed SUMMA with the sparse nnz-prefix "
          "tile exchange on every eligible stage (2x4 mesh)")
def _summa_hybrid():
    return _summa_exchange("sparse")


@register("summa.dense_exchange", "the same SUMMA product with every "
          "stage forced to the dense full-capacity broadcast — its "
          "collective ceilings must equal summa.hybrid's (the sparse "
          "exchange changes payload shapes, never collective counts)")
def _summa_dense_exchange():
    return _summa_exchange("dense")


@functools.lru_cache(maxsize=None)
def _mesh_graph_fixture():
    """256-vertex pattern-symmetric boolean graph on a routed 2x2
    mesh, eligible for the multi-tile packed-bit batch path."""
    import jax
    import jax.numpy as jnp

    from combblas_tpu.models import bfs as B
    from combblas_tpu.ops import semiring as S
    from combblas_tpu.parallel import distmat as DM
    from combblas_tpu.parallel.grid import ProcGrid
    rng = _rng()
    grid = ProcGrid.make(2, 2, jax.devices()[:4])
    n = 256
    r = rng.integers(0, n, 600).astype(np.int32)
    c = rng.integers(0, n, 600).astype(np.int32)
    rows = np.concatenate([r, c])
    cols = np.concatenate([c, r])
    a = DM.from_global_coo(S.LOR, grid, jnp.asarray(rows),
                           jnp.asarray(cols),
                           jnp.ones(len(rows), jnp.bool_), n, n)
    plan = B.plan_bfs(a, route=True)
    assert B.bits_fallback_reason(a, plan) is None, \
        "mesh graph fixture must be bits-eligible"
    return a, plan


@register("bfs.bits_mesh_core", "multi-tile packed-bit batch BFS core "
          "on a routed 2x2 mesh: one lane-word ppermute exchange + one "
          "all_gather per level, lane-width invariant")
def _bfs_bits_mesh_core():
    import jax.numpy as jnp

    from combblas_tpu.models import bfs as B
    a, plan = _mesh_graph_fixture()
    ml = jnp.int32(1 << 30)
    fn = lambda roots, ml: B._bfs_batch_bits_mesh_core(  # noqa: E731
        a, plan, roots, ml)
    return {"fn": fn,
            "args": (jnp.zeros((8,), jnp.int32), ml),
            "variants": {"W=16": (fn, (jnp.zeros((16,), jnp.int32), ml))}}
