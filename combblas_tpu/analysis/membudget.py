"""Pass 6 — memory-budget gate over bench `memory_summary` blocks.

The memory ledger (`combblas_tpu.obs.memledger`) gives every bench
artifact a `memory_summary` block: compile-time footprint census
(argument/output/temp bytes per executable, from XLA's own
memory_analysis), live-buffer watermarks, and the donation audit
(declared `donate_argnums` vs the aliases the compiled executable
actually honors). This pass commits that progress as an OOM-risk gate:
declarative ceilings in `analysis/budgets/memory.json` pin, per
artifact,

* per-executable TEMP-byte ceilings (`temp_ceilings`) — XLA scratch is
  the silent OOM driver: it appears in no array the program names, so
  a fusion regression that doubles scratch shows up nowhere else;
* the peak footprint as a FRACTION of the backend's committed
  `hbm_bytes` (`peak_frac_max`) — the worst of measured live-buffer
  peak and largest single-executable footprint must leave headroom;
* census coverage of the dispatch ledger (`census_coverage_min`) — a
  run whose compiled executables stopped landing in the census is
  flying blind, so coverage decay fails the gate, not a future OOM;
* the donation contract: any `donation_audit.unhonored` entry fails
  (a declared donation XLA silently ignored is a leaked buffer at
  every dispatch), and `donations_required` names must stay declared
  and never-unhonored (dropping the declaration is STALE).

Budget JSON shape (one file may pin several artifacts)::

    {"artifacts": [{
        "artifact": "ESC_MICROBENCH.json",  # repo-root relative; "*"
                                            # globs pick newest by mtime
        "driver": "esc",
        "require_memory_summary": true,     # false tolerates artifacts
                                            # recorded before the ledger
        "census_coverage_min": 0.9,
        "peak_frac_max": 0.5,
        "temp_ceilings": {"spgemm.colwindow": 8000000},
        "donations_required": ["spgemm.shrink_place3"],
        "allow": []                         # waived rule ids
    }]}

All checks are pure JSON reads — nothing here compiles or runs device
code. Ceilings are maxima (dropping below is improvement); the STALE
rule keeps the committed expectations honest in both directions.
"""

from __future__ import annotations

import json
import pathlib

from combblas_tpu.analysis import core
from combblas_tpu.analysis.core import Finding
from combblas_tpu.analysis.obsbudget import (
    _line_of, _load_artifact, _resolve_artifact,
)

BUDGET_DIR = pathlib.Path(__file__).parent / "budgets"
REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]


def _collect_memory_summaries(doc, out=None) -> list:
    """All `memory_summary` blocks anywhere in the artifact (serve
    artifacts nest one per mode, same convention as dispatch_summary)."""
    if out is None:
        out = []
    if isinstance(doc, dict):
        ms = doc.get("memory_summary")
        if isinstance(ms, dict):
            out.append(ms)
        for v in doc.values():
            _collect_memory_summaries(v, out)
    elif isinstance(doc, list):
        for v in doc:
            _collect_memory_summaries(v, out)
    return out


def _temp_by_name(summaries: list) -> dict:
    """executable name -> max temp bytes across summaries' top tables."""
    out: dict = {}
    for ms in summaries:
        for row in ms.get("top", []):
            name = row.get("name")
            if name:
                out[name] = max(out.get(name, 0),
                                int(row.get("temp_bytes", 0)))
    return out


def check_artifact(ent: dict, budget_text: str, budget_path: str,
                   root=None) -> list[Finding]:
    """All findings for one memory-budget entry (the unit the
    self-test fixtures drive)."""
    allow = set(ent.get("allow", []))
    name = ent["artifact"]
    driver = ent.get("driver", name)
    findings: list[Finding] = []

    def add(rule, key, msg):
        if rule not in allow:
            findings.append(Finding(
                rule, budget_path, _line_of(budget_text, name, key),
                msg, entry=driver))

    path = _resolve_artifact(name, pathlib.Path(root or REPO_ROOT))
    if path is None:
        add(core.MEM_STALE, "artifact",
            f"artifact {name!r} not found — the committed memory "
            "budget is stale")
        return findings
    try:
        art = _load_artifact(path)
    except ValueError as e:
        add(core.MEM_STALE, "artifact", f"artifact unreadable: {e}")
        return findings

    summaries = _collect_memory_summaries(art)
    if not summaries:
        if ent.get("require_memory_summary"):
            add(core.MEM_STALE, "require_memory_summary",
                f"{path.name}: no memory_summary block — rerun the "
                "bench with the memory ledger on (obs.export."
                "memory_summary next to dispatch_summary)")
        return findings

    # -- census coverage floor ------------------------------------------
    floor = ent.get("census_coverage_min")
    if floor is not None:
        fracs = [float(ms["census_coverage"]["frac"]) for ms in summaries
                 if isinstance(ms.get("census_coverage"), dict)
                 and "frac" in ms["census_coverage"]]
        if not fracs:
            add(core.MEM_STALE, "census_coverage_min",
                f"{path.name}: memory_summary has no census_coverage "
                "block — the artifact shape drifted from the budget")
        elif min(fracs) < float(floor):
            add(core.MEM_CENSUS, "census_coverage_min",
                f"{path.name}: footprint census covered "
                f"{min(fracs):.0%} of compiled ledger executables "
                f"(floor {float(floor):.0%}) — compile-time memory "
                "attribution regressed")

    # -- peak footprint vs committed HBM fraction -----------------------
    frac_max = ent.get("peak_frac_max")
    if frac_max is not None:
        worst_frac, worst = 0.0, None
        for ms in summaries:
            cap = float(ms.get("hbm_bytes") or 0)
            if cap <= 0:
                continue
            peak = max(int(ms.get("peak_resident_bytes", 0)),
                       int(ms.get("largest_footprint_bytes", 0)))
            if peak / cap > worst_frac:
                worst_frac, worst = peak / cap, peak
        if worst is None:
            add(core.MEM_STALE, "peak_frac_max",
                f"{path.name}: no memory_summary carries hbm_bytes — "
                "cannot judge the committed peak fraction")
        elif worst_frac > float(frac_max):
            add(core.MEM_PEAK, "peak_frac_max",
                f"{path.name}: peak footprint {worst} B is "
                f"{worst_frac:.1%} of the backend's HBM (ceiling "
                f"{float(frac_max):.0%}) — the bench is drifting "
                "toward OOM; see top_footprints for the claimants")

    # -- per-executable temp ceilings -----------------------------------
    temps = _temp_by_name(summaries)
    for ex, ceil in (ent.get("temp_ceilings") or {}).items():
        if ex not in temps:
            add(core.MEM_STALE, ex,
                f"{path.name}: temp ceiling names {ex!r} but no "
                "memory_summary footprint matches — the executable was "
                "renamed or fell out of the top table; update the "
                "budget")
        elif temps[ex] > int(ceil):
            add(core.MEM_TEMP, ex,
                f"{path.name}: executable {ex!r} temp scratch "
                f"{temps[ex]} B exceeds the committed ceiling "
                f"{int(ceil)} B — an XLA fusion/layout change grew "
                "silent scratch")

    # -- donation contract ----------------------------------------------
    audits = [ms["donation_audit"] for ms in summaries
              if isinstance(ms.get("donation_audit"), dict)]
    unhonored = sorted({n for a in audits
                        for n in a.get("unhonored", [])})
    for n in unhonored:
        add(core.MEM_DONATION, "artifact",
            f"{path.name}: declared donation on {n!r} was NOT honored "
            "by the compiled executable (no aliased parameter) — the "
            "input buffer is retained at every dispatch; fix the "
            "donation or declare a waiver at the declaration site")
    required = ent.get("donations_required") or []
    if required and not audits:
        add(core.MEM_STALE, "donations_required",
            f"{path.name}: donations are required but no "
            "memory_summary carries a donation_audit block")
    for want in required:
        declared = {e["name"] for a in audits
                    for e in a.get("entries", [])}
        if not audits:
            break
        if want not in declared:
            add(core.MEM_STALE, "donations_required",
                f"{path.name}: required donation {want!r} is no longer "
                "declared — the declare_donation call was dropped or "
                "renamed")
    return findings


def run_mem(files=None, root=None) -> list[Finding]:
    """Run the memory-budget pass over the committed budgets (or an
    explicit fixture list); returns unsuppressed findings."""
    paths = ([pathlib.Path(f) for f in files] if files is not None
             else sorted(BUDGET_DIR.glob("memory*.json")))
    findings: list[Finding] = []
    for p in paths:
        text = p.read_text()
        data = json.loads(text)
        for ent in data.get("artifacts", []):
            if "artifact" not in ent:
                raise ValueError(f"{p}: memory budget entry without "
                                 "'artifact'")
            findings += check_artifact(ent, text, str(p), root=root)
    return findings
