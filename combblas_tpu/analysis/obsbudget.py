"""Pass 4 — obs-residual budget pass over committed bench artifacts.

The flight recorder (`combblas_tpu.obs.ledger`) turned "63% of the MCL
expansion wall is unaccounted" from a mystery into a named executable
table. This pass commits that progress: declarative ceilings in
`analysis/budgets/obs_*.json` pin, per driver artifact,

* the `unaccounted_s` FRACTION of the total wall (the span residual no
  categorized span claimed) — regressions in attribution coverage or
  in dispatch glue fail the gate, not a future bench reader;
* dispatch COUNTS at committed artifact paths (e.g. the bits-BFS
  512-query burst's `serve_bits.dispatches`) — the serving layer's
  whole point is dispatch amortization, so a count creep is a perf
  bug even when wall clock hides it;
* per-executable call counts and required executable names from the
  artifact's `dispatch_summary` ledger block — a committed ledger
  expectation that stops matching (executable renamed, wrapper
  dropped) is flagged as STALE rather than silently vacuous.

Budget JSON shape (one file may pin several artifacts)::

    {"artifacts": [{
        "artifact": "SERVE_BENCH.json",     # repo-root relative; "*"
                                            # globs pick newest by mtime
                                            # (bench.py's embed rule)
        "driver": "serve",
        "unaccounted": {"path": "unaccounted_s", "total_path": "value",
                        "frac_max": 0.15, "missing_ok": true},
        "dispatch_ceilings": {"open_loop.dispatches": 20},
        "executable_ceilings": {"bfs.batch": 64},   # max ledger count
        "ledger_names": ["serve.bfs"],      # must appear (prefix match:
                                            # "serve.bfs" covers
                                            # "serve.bfs/w32")
        "require_dispatch_summary": false,  # tolerate TPU-era artifacts
                                            # recorded before the ledger
        "allow": []                         # waived rule ids
    }]}

All checks are pure JSON reads — nothing here compiles or runs device
code. Ceilings are maxima (dropping below is improvement); the STALE
rule is the only bidirectional one, by design: it exists to keep the
committed expectations honest.
"""

from __future__ import annotations

import json
import pathlib

from combblas_tpu.analysis import core
from combblas_tpu.analysis.core import Finding

BUDGET_DIR = pathlib.Path(__file__).parent / "budgets"
REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]


def _get_path(doc, dotted: str):
    """(value, found) of a dotted path into nested dicts."""
    cur = doc
    for part in dotted.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return None, False
    return cur, True


def _load_artifact(path: pathlib.Path):
    """Artifact JSON: whole file, else the LAST parseable line (bench
    scripts emit JSON-lines with the headline last)."""
    text = path.read_text()
    try:
        return json.loads(text)
    except ValueError:
        pass
    for ln in reversed(text.splitlines()):
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                return json.loads(ln)
            except ValueError:
                continue
    raise ValueError(f"{path}: no parseable JSON object")


def _collect_summaries(doc, out=None) -> list:
    """All `dispatch_summary` blocks anywhere in the artifact (serve
    artifacts nest one per mode)."""
    if out is None:
        out = []
    if isinstance(doc, dict):
        ds = doc.get("dispatch_summary")
        if isinstance(ds, dict):
            out.append(ds)
        for v in doc.values():
            _collect_summaries(v, out)
    elif isinstance(doc, list):
        for v in doc:
            _collect_summaries(v, out)
    return out


def _exec_counts(summaries: list) -> dict:
    """executable name -> max recorded count across summaries."""
    counts: dict = {}
    for ds in summaries:
        for row in ds.get("top", []):
            name = row.get("name")
            if name:
                counts[name] = max(counts.get(name, 0),
                                   int(row.get("count", 0)))
    return counts


def _name_covered(want: str, names) -> bool:
    """Exact match, or prefix match at a path boundary ("serve.bfs"
    covers "serve.bfs/w32" and "serve.bfs.l32/w64")."""
    for n in names:
        if n == want or n.startswith(want + "/") or \
                n.startswith(want + "."):
            return True
    return False


def _line_of(text: str, anchor: str, key: str) -> int:
    """Line of ``key`` inside the budget block containing ``anchor``
    (same convention as budget._line_of: findings point at the violated
    number)."""
    lines = text.splitlines()
    start = 0
    for i, ln in enumerate(lines):
        if anchor in ln:
            start = i
            break
    for i in range(start, len(lines)):
        if f'"{key}"' in lines[i]:
            return i + 1
    return start + 1


def _resolve_artifact(name: str, root: pathlib.Path):
    """Artifact path; globs resolve to the newest match by mtime (the
    same rule bench.py uses to embed MCL_BENCH_*.json)."""
    if any(ch in name for ch in "*?["):
        cands = sorted(root.glob(name),
                       key=lambda p: (p.stat().st_mtime, p.name))
        return cands[-1] if cands else None
    p = root / name
    return p if p.exists() else None


def check_artifact(ent: dict, budget_text: str, budget_path: str,
                   root=None) -> list[Finding]:
    """All findings for one budget entry (the unit the self-test
    fixtures drive)."""
    allow = set(ent.get("allow", []))
    name = ent["artifact"]
    driver = ent.get("driver", name)
    findings: list[Finding] = []

    def add(rule, key, msg):
        if rule not in allow:
            findings.append(Finding(
                rule, budget_path, _line_of(budget_text, name, key),
                msg, entry=driver))

    path = _resolve_artifact(name, pathlib.Path(root or REPO_ROOT))
    if path is None:
        add(core.OBS_STALE, "artifact",
            f"artifact {name!r} not found — the committed obs budget "
            "is stale")
        return findings
    try:
        art = _load_artifact(path)
    except ValueError as e:
        add(core.OBS_STALE, "artifact", f"artifact unreadable: {e}")
        return findings

    u = ent.get("unaccounted")
    if u:
        val, ok1 = _get_path(art, u.get("path", "unaccounted_s"))
        tot, ok2 = _get_path(art, u.get("total_path", "value"))
        if not (ok1 and ok2):
            if not u.get("missing_ok", False):
                add(core.OBS_STALE, "unaccounted",
                    f"{path.name}: no {u.get('path')!r}/"
                    f"{u.get('total_path')!r} fields — rerun the bench "
                    "with the obs recorder on, or mark missing_ok")
        elif tot and float(val) / float(tot) > float(u["frac_max"]):
            add(core.OBS_RESIDUAL, "frac_max",
                f"{path.name}: unaccounted {float(val):.4g}s is "
                f"{float(val) / float(tot):.1%} of {float(tot):.4g}s "
                f"total (ceiling {float(u['frac_max']):.0%}) — the "
                "residual grew; see the ledger top-K table for where")

    for dotted, ceil in (ent.get("dispatch_ceilings") or {}).items():
        v, ok = _get_path(art, dotted)
        if not ok:
            add(core.OBS_STALE, dotted.rsplit(".", 1)[-1],
                f"{path.name}: committed count path {dotted!r} missing "
                "— the artifact shape drifted from the budget")
        elif int(v) > int(ceil):
            add(core.OBS_DISPATCH_COUNT, dotted.rsplit(".", 1)[-1],
                f"{path.name}: {dotted} = {int(v)} exceeds the "
                f"committed ceiling {int(ceil)} — dispatch count crept "
                "(batching/fusion regression)")

    summaries = _collect_summaries(art)
    wants_ledger = (ent.get("executable_ceilings")
                    or ent.get("ledger_names")
                    or ent.get("require_dispatch_summary"))
    if not summaries:
        if ent.get("require_dispatch_summary"):
            add(core.OBS_STALE, "require_dispatch_summary",
                f"{path.name}: no dispatch_summary block — rerun the "
                "bench with the dispatch ledger on")
        return findings
    if not wants_ledger:
        return findings
    counts = _exec_counts(summaries)
    for ex, ceil in (ent.get("executable_ceilings") or {}).items():
        if ex not in counts:
            add(core.OBS_STALE, ex,
                f"{path.name}: ledger expectation {ex!r} matched no "
                "recorded executable — the wrapper was renamed or "
                "dropped; update the budget")
        elif counts[ex] > int(ceil):
            add(core.OBS_DISPATCH_COUNT, ex,
                f"{path.name}: executable {ex!r} dispatched "
                f"{counts[ex]}x (ceiling {int(ceil)})")
    for want in ent.get("ledger_names") or []:
        if not _name_covered(want, counts):
            add(core.OBS_STALE, "ledger_names",
                f"{path.name}: required executable {want!r} absent "
                f"from the dispatch ledger (recorded: "
                f"{sorted(counts)[:8]}...) — instrumentation coverage "
                "regressed or the name changed")
    return findings


def run_obs(files=None, root=None) -> list[Finding]:
    """Run the obs-residual budget pass over the committed budgets (or
    an explicit fixture list); returns unsuppressed findings."""
    paths = ([pathlib.Path(f) for f in files] if files is not None
             else sorted(BUDGET_DIR.glob("obs_*.json")))
    findings: list[Finding] = []
    for p in paths:
        text = p.read_text()
        data = json.loads(text)
        for ent in data.get("artifacts", []):
            if "artifact" not in ent:
                raise ValueError(f"{p}: obs budget entry without "
                                 "'artifact'")
            findings += check_artifact(ent, text, str(p), root=root)
    return findings
