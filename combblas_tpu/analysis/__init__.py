"""`combblas_tpu.analysis` — static-analysis gate for the repo's
structural invariants.

Nine passes, one verdict (see `scripts/analyze.py --gate` and the
README "Static analysis" section):

1. **Budget engine** (`budget.run_budgets`) — lowers registered
   kernel entry points (`entries.py`) and checks the jaxpr + StableHLO
   against declarative JSON budgets in `analysis/budgets/`: exact sort
   counts and sorted-operand arity, gather/scatter/while ceilings,
   forbidden dtypes (i64) and ops (host callbacks), lane-width
   invariance for the packed-bit path.
2. **Retrace-drift detector** (`retrace.run_retrace`) — replays the
   serve layer's argument-prep recipes over the bucket ladder and
   flags avoidable recompiles: weak-type drift, Python-scalar
   leakage, plan-cache groups whose jit cache keys diverge, compile
   counts that drift from `budgets/retrace_serve.json`.
3. **Lock-order lint** (`lockorder.run_lockorder`) — AST pass over
   the package building the lock-acquisition graph: ordering cycles,
   blocking jit dispatch under a held lock (the PR-4 deadlock shape),
   bare `acquire()` without try/finally.
4. **obs-residual budgets** (`obsbudget.run_obs`) — committed
   ceilings over bench artifacts: `unaccounted_s` fraction of the
   wall, dispatch counts at artifact paths (e.g. the bits-BFS
   512-query burst), per-executable ledger counts, and required
   instrumentation coverage (`ledger_names`).
5. **perf-regression gate** (`perfgate.run_perf`) — the committed
   `BENCH_TRAJECTORY.json` (built by `scripts/bench_registry.py` from
   every bench artifact via `obs.regress`) held against
   `budgets/perf_regression.json`: trajectory coverage/staleness,
   roofline-efficiency floors on schema-full runs, and direction-aware
   noise bands around each workload's newest-vs-baseline runs.
6. **memory-budget gate** (`membudget.run_mem`) — committed OOM-risk
   ceilings over bench `memory_summary` blocks (`budgets/memory.json`):
   per-executable XLA temp-scratch ceilings, peak footprint as a
   fraction of the backend's `hbm_bytes`, footprint-census coverage
   floors, and the donation contract (no declared `donate_argnums`
   the compiled executable silently ignored).
7. **trace-hazard & collective-safety lint** (`tracehazard.run_tracehazard`)
   — interprocedural AST pass (`budgets/trace_hazard.json`): blocking
   host syncs reachable from the registered async hot paths outside
   the `obs.ledger.readback` brackets (the PR-7 pipeline property),
   `os.environ` reads inside traced code (the PR-8 stale-executable
   shape), unstable jit cache keys (per-call `jax.jit`, mutable
   closure captures, literal static args), and shard_map collectives
   checked against their declared mesh axes — with the square-mesh
   transpose ppermute pairings pinned in the budget so rectangular/3D
   mesh work fails loudly.
8. **chaos-recovery budget** (`chaosbudget.run_chaos`) — committed
   resilience invariants over the `CHAOS_r*.json` soak artifacts
   (`budgets/chaos.json`): zero unresolved serve futures under the
   committed fault schedule, faulted-phase shed within its ceiling,
   bit-exact results once faults clear (serve traffic, fault-recovered
   SpGEMM, resumed MCL), and vacuity floors on injected-fault/retry
   counts so the soak keeps exercising the paths it gates.
9. **mesh-observatory budget** (`meshbudget.run_mesh`) — committed
   communication invariants over the bench `mesh_summary` blocks
   (`budgets/mesh.json`): per-device load/wall skew ceilings (with the
   straggler named), a floor on the ledger-wall fraction carrying
   per-device attribution, per-axis measured ICI byte ceilings, and a
   band on the predicted-vs-measured drift ratio per ledger name — on
   emulated meshes measurement equals the registered descriptors by
   construction, so drift leaving the band means the analytic cost
   model rotted, not the wire.

All passes are trace/AST/JSON only — nothing here compiles or
executes device code — and every finding carries `file:line`, a rule
id, and a suppression syntax (`# analysis: allow(<rule>)` in source,
`"allow"` lists in the JSON budgets).
"""

from __future__ import annotations

from combblas_tpu.analysis.core import (  # noqa: F401
    ALL_RULES, Finding, format_report, is_suppressed, scan_suppressions,
)


def run_budgets(**kw):
    from combblas_tpu.analysis import budget
    return budget.run_budgets(**kw)


def run_retrace(**kw):
    from combblas_tpu.analysis import retrace
    return retrace.run_retrace(**kw)


def run_lockorder(**kw):
    from combblas_tpu.analysis import lockorder
    return lockorder.run_lockorder(**kw)


def run_obs(**kw):
    from combblas_tpu.analysis import obsbudget
    return obsbudget.run_obs(**kw)


def run_perf(**kw):
    from combblas_tpu.analysis import perfgate
    return perfgate.run_perf(**kw)


def run_mem(**kw):
    from combblas_tpu.analysis import membudget
    return membudget.run_mem(**kw)


def run_tracehazard(**kw):
    from combblas_tpu.analysis import tracehazard
    return tracehazard.run_tracehazard(**kw)


def run_chaos(**kw):
    from combblas_tpu.analysis import chaosbudget
    return chaosbudget.run_chaos(**kw)


def run_mesh(**kw):
    from combblas_tpu.analysis import meshbudget
    return meshbudget.run_mesh(**kw)


def run_all(passes=("budgets", "retrace", "locks", "obs", "perf",
                    "mem", "trace", "chaos", "mesh")) -> list[Finding]:
    """Run the selected passes; returns all unsuppressed findings
    (empty = gate passes)."""
    out: list[Finding] = []
    if "budgets" in passes:
        out += run_budgets()
    if "retrace" in passes:
        out += run_retrace()
    if "locks" in passes:
        out += run_lockorder()
    if "obs" in passes:
        out += run_obs()
    if "perf" in passes:
        out += run_perf()
    if "mem" in passes:
        out += run_mem()
    if "trace" in passes:
        out += run_tracehazard()
    if "chaos" in passes:
        out += run_chaos()
    if "mesh" in passes:
        out += run_mesh()
    return out
