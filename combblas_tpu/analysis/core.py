"""Shared vocabulary of the static-analysis subsystem: findings,
rule ids, and the suppression syntax.

A *finding* is one violation: a rule id, a ``file:line`` anchor, and a
human message. The three passes (budget engine, retrace-drift
detector, lock-order lint) all emit findings; `scripts/analyze.py
--gate` exits non-zero iff any UNSUPPRESSED finding survives.

Suppression syntax (the only escape hatch, so every waiver is
greppable)::

    some_code()          # analysis: allow(jit-under-lock)

The comment applies to its own line, the line directly above the
flagged one, or — for findings inside a ``with`` block — the ``with``
statement's line (block scope). Budget/retrace rules are suppressed
declaratively instead, via an ``"allow": [...]`` list in the JSON
budget entry, so the waiver lives next to the number it waives.
"""

from __future__ import annotations

import ast
import dataclasses
import re

# -- rule catalog -----------------------------------------------------------
# budget engine (pass 1)
SORT_COUNT = "sort-count"            # exact stablehlo.sort op count
SORT_ARITY = "sort-arity"            # operands per sort / total sorted operands
OP_CEILING = "op-ceiling"            # gather/scatter/dynamic_slice/while ceilings
FORBID_DTYPE = "forbid-dtype"        # e.g. i64 tensors with x64 off
FORBID_OP = "forbid-op"              # host callbacks etc. in jitted paths
LANE_INVARIANCE = "lane-invariance"  # bits-path op structure free of lane width

# retrace-drift detector (pass 2)
RETRACE_DRIFT = "retrace-drift"          # one plan-cache slot, >1 jit cache key
RETRACE_PY_SCALAR = "retrace-py-scalar"  # raw Python scalar in a traced position
RETRACE_EXTRA_COMPILE = "retrace-extra-compile"  # compile count != committed

# lock-order / threading lint (pass 3)
LOCK_CYCLE = "lock-cycle"            # ordering cycle in the lock graph
JIT_UNDER_LOCK = "jit-under-lock"    # blocking jax dispatch while a lock is held
BARE_ACQUIRE = "bare-acquire"        # .acquire() without try/finally release

# obs-residual budget pass (pass 4)
OBS_RESIDUAL = "obs-residual"            # unaccounted_s fraction over ceiling
OBS_DISPATCH_COUNT = "obs-dispatch-count"  # dispatch count over ceiling
OBS_STALE = "obs-stale-artifact"         # budget names an artifact/path/
#                                          executable that no longer exists

# perf-regression gate over the bench trajectory (pass 5)
PERF_EFFICIENCY = "perf-efficiency-floor"   # roofline eff / attributable
#                                             fraction below committed floor
PERF_REGRESSION = "perf-regression-band"    # newest run outside the noise
#                                             band around the baseline
PERF_STALE = "perf-stale-trajectory"        # BENCH_TRAJECTORY.json missing,
#                                             unreadable, or not covering a
#                                             committed artifact

# trace-hazard & collective-safety lint (pass 7)
SYNC_IN_ASYNC = "sync-in-async"          # blocking host sync reachable from a
#                                          registered async hot path, outside
#                                          an obs.ledger.readback bracket
ENV_IN_TRACE = "env-in-trace"            # os.environ / utils.config read
#                                          inside traced code (the PR-8 shape)
CACHE_KEY_UNSTABLE = "cache-key-unstable"  # jit cache keyed on an unstable
#                                            value: per-call jax.jit, mutable
#                                            closure capture, literal static arg
COLLECTIVE_AXIS = "collective-axis"      # collective inside a shard_map body
#                                          over an axis its specs don't declare
COLLECTIVE_TRANSPOSE = "collective-transpose"  # multi-axis ppermute (the
#                                          square-mesh transpose pairing) not
#                                          covered by the trace_hazard budget
TRACE_STALE = "trace-stale-budget"       # trace_hazard.json names a function
#                                          / site that no longer exists

# chaos-recovery budget over CHAOS_r*.json soak artifacts (pass 8)
CHAOS_UNRESOLVED = "chaos-unresolved-handles"  # a serve future never
#                                          resolved under faults — the
#                                          hang supervision must prevent
CHAOS_SHED = "chaos-shed-budget"         # faulted-phase shed fraction
#                                          over the committed ceiling
CHAOS_BIT_EXACT = "chaos-bit-exact"      # results after faults clear
#                                          (or a resumed solver) drifted
#                                          from the fault-free reference
CHAOS_RECOVERY = "chaos-recovery-floor"  # the soak is vacuous (too few
#                                          faults injected / retries) or
#                                          recovered fraction below floor
CHAOS_STALE = "chaos-stale-artifact"     # chaos budget names an
#                                          artifact/summary field that
#                                          no longer exists

# mesh-observatory budget over bench mesh_summary blocks (pass 9)
MESH_SKEW = "mesh-skew-budget"           # per-device load/wall skew (or
#                                          attribution coverage) beyond
#                                          the committed ceiling
MESH_BYTES = "mesh-bytes-budget"         # measured per-axis ICI bytes
#                                          over the committed ceiling
MESH_DRIFT = "mesh-ici-drift"            # measured/predicted collective
#                                          bytes left the committed band
MESH_STALE = "mesh-stale-artifact"       # mesh budget names an artifact
#                                          / ledger name / axis / metric
#                                          that no longer exists

# memory-budget gate over bench memory_summary blocks (pass 6)
MEM_TEMP = "mem-temp-ceiling"            # per-executable temp bytes over
#                                          the committed ceiling
MEM_PEAK = "mem-peak-budget"             # per-bench peak footprint over
#                                          the committed HBM fraction
MEM_DONATION = "mem-donation-unhonored"  # declared donate_argnums the
#                                          compiled executable ignored
MEM_CENSUS = "mem-census-coverage"       # footprint census coverage of
#                                          the dispatch ledger below floor
MEM_STALE = "mem-stale-artifact"         # memory budget names an artifact
#                                          / executable / donation that no
#                                          longer exists

ALL_RULES = (
    SORT_COUNT, SORT_ARITY, OP_CEILING, FORBID_DTYPE, FORBID_OP,
    LANE_INVARIANCE, RETRACE_DRIFT, RETRACE_PY_SCALAR,
    RETRACE_EXTRA_COMPILE, LOCK_CYCLE, JIT_UNDER_LOCK, BARE_ACQUIRE,
    OBS_RESIDUAL, OBS_DISPATCH_COUNT, OBS_STALE,
    PERF_EFFICIENCY, PERF_REGRESSION, PERF_STALE,
    MEM_TEMP, MEM_PEAK, MEM_DONATION, MEM_CENSUS, MEM_STALE,
    SYNC_IN_ASYNC, ENV_IN_TRACE, CACHE_KEY_UNSTABLE, COLLECTIVE_AXIS,
    COLLECTIVE_TRANSPOSE, TRACE_STALE,
    CHAOS_UNRESOLVED, CHAOS_SHED, CHAOS_BIT_EXACT, CHAOS_RECOVERY,
    CHAOS_STALE,
    MESH_SKEW, MESH_BYTES, MESH_DRIFT, MESH_STALE,
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a file:line."""

    rule: str
    file: str
    line: int
    message: str
    entry: str = ""      # kernel/entry-point name when one applies

    def format(self) -> str:
        where = f"{self.file}:{self.line}"
        tag = f" ({self.entry})" if self.entry else ""
        return f"{where}: [{self.rule}]{tag} {self.message}"


_ALLOW_RE = re.compile(r"#\s*analysis:\s*allow\(([^)]*)\)")


def scan_suppressions(source: str) -> dict[int, set[str]]:
    """Map 1-indexed line -> set of rule ids waived on that line."""
    out: dict[int, set[str]] = {}
    for i, ln in enumerate(source.splitlines(), start=1):
        m = _ALLOW_RE.search(ln)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out[i] = rules
    return out


def is_suppressed(finding: Finding, suppressions: dict[int, set[str]],
                  scope_lines: tuple[int, ...] = ()) -> bool:
    """True iff the finding's rule is waived on its own line, the line
    above it, or any of the caller-provided ``scope_lines`` (the lint
    passes the enclosing ``with`` statement lines)."""
    for ln in (finding.line, finding.line - 1, *scope_lines):
        rules = suppressions.get(ln)
        if rules and (finding.rule in rules or "*" in rules):
            return True
    return False


def with_scope_map(tree: ast.AST) -> dict[int, tuple[int, ...]]:
    """Map each 1-indexed source line to the lines of every ``with``
    statement lexically enclosing it. This is the block-scope half of
    the suppression contract — ``# analysis: allow(rule)`` on a
    ``with`` line covers the whole block — hoisted here so EVERY AST
    pass honors it, not just the lock lint (which used to carry its
    own copy keyed off held locks)."""
    out: dict[int, tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        end = getattr(node, "end_lineno", None) or node.lineno
        for ln in range(node.lineno, end + 1):
            out[ln] = out.get(ln, ()) + (node.lineno,)
    return out


class FileSuppressions:
    """One file's suppression view: the ``# analysis: allow(...)``
    line comments plus the with-block scope map. AST passes build one
    per file and ask `covers(finding)`; passes that track extra scope
    of their own (the lock lint's held-with lines) pass it through
    ``extra_scope``."""

    def __init__(self, source: str):
        self.lines = scan_suppressions(source)
        try:
            self.scopes = with_scope_map(ast.parse(source))
        except SyntaxError:
            self.scopes = {}

    def covers(self, finding: Finding,
               extra_scope: tuple[int, ...] = ()) -> bool:
        scope = self.scopes.get(finding.line, ()) + tuple(extra_scope)
        return is_suppressed(finding, self.lines, scope)


def format_report(findings: list[Finding], header: str = "") -> str:
    lines = []
    if header:
        lines.append(header)
    if not findings:
        lines.append("  no findings")
    for f in findings:
        lines.append("  " + f.format())
    return "\n".join(lines)
