"""Pass 9 — mesh-observatory budget over committed bench artifacts.

`scripts/multichip_bench.py` runs the multi-device drivers on an
emulated (or real) mesh and commits a `mesh_summary` block — the
observatory's (`obs.meshobs`) view of that run: measured exchanged
bytes per (ledger name, collective, axis), the predicted-vs-measured
ICI drift join against the roofline cost model, per-device load skew,
and the fraction of ledger wall carrying per-device attribution. This
pass holds that block against `analysis/budgets/mesh.json`, committing
the communication story the same way pass 4 commits attribution
coverage and pass 6 commits the memory story:

* **skew ceilings** — per-device load (nnz, flops) and wall skew,
  expressed as max-over-mean per metric, must stay under a committed
  ceiling. A silent straggler is exactly what per-device attribution
  exists to surface; the budget makes growth a finding, with the
  straggler device named in the message.
* **attribution floor** — the fraction of the dispatch-ledger wall
  attributed to per-device load rows must stay above a floor.
  Attribution that silently decays back to a blind aggregate defeats
  the observatory.
* **per-axis byte budgets** — measured bytes exchanged along each mesh
  axis ("r", "c", "l", "rc") per run must stay under a committed
  ceiling. A collective added to a hot loop shows up here before it
  shows up on a wall clock.
* **drift band** — measured/predicted bytes per ledger name must stay
  inside a committed band. On emulated meshes the measurement equals
  the registered descriptors by construction, so drift catches
  *model* rot: a planner whose analytic cbytes stopped matching what
  its kernel actually exchanges. bfs.*/cc.* names (while_loop drivers
  with data-dependent trip counts) are deliberately not banded.
* **staleness** — a budget naming an artifact, ledger name, axis, or
  skew metric that no longer exists is flagged rather than silently
  vacuous.

Budget JSON shape (one file may pin several artifacts)::

    {"artifacts": [{
        "artifact": "MULTICHIP_r*.json",  # repo-root relative; globs
                                          # pick newest by mtime
        "driver": "multichip",
        "require_mesh_summary": true,
        "attribution_frac_min": 0.9,
        "skew_max": {"nnz": 3.0, "wall": 4.0},
        "axis_bytes_max": {"r": 4.0e6, "c": 4.0e6},
        "drift_band": {"spgemm.summa": [0.95, 1.05]},
        "allow": []                       # waived rule ids
    }]}

All checks are pure JSON reads — nothing here compiles or runs device
code. A numeric check whose `mesh_summary` field is absent flags
STALE (shape drift), never passes silently.
"""

from __future__ import annotations

import json
import pathlib

from combblas_tpu.analysis import core
from combblas_tpu.analysis.core import Finding
from combblas_tpu.analysis.obsbudget import (
    _line_of, _load_artifact, _resolve_artifact,
)

BUDGET_DIR = pathlib.Path(__file__).parent / "budgets"
REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]


def check_artifact(ent: dict, budget_text: str, budget_path: str,
                   root=None) -> list[Finding]:
    """All findings for one budget entry (the unit the self-test
    fixtures drive)."""
    allow = set(ent.get("allow", []))
    name = ent["artifact"]
    driver = ent.get("driver", name)
    findings: list[Finding] = []

    def add(rule, key, msg):
        if rule not in allow:
            findings.append(Finding(
                rule, budget_path, _line_of(budget_text, name, key),
                msg, entry=driver))

    path = _resolve_artifact(name, pathlib.Path(root or REPO_ROOT))
    if path is None:
        add(core.MESH_STALE, "artifact",
            f"artifact {name!r} not found — run "
            "scripts/multichip_bench.py to generate it, or drop the "
            "stale budget entry")
        return findings
    try:
        art = _load_artifact(path)
    except ValueError as e:
        add(core.MESH_STALE, "artifact", f"artifact unreadable: {e}")
        return findings
    ms = art.get("mesh_summary")
    if not isinstance(ms, dict):
        if ent.get("require_mesh_summary"):
            add(core.MESH_STALE, "require_mesh_summary",
                f"{path.name}: no mesh_summary block — the artifact "
                "predates the mesh observatory (rerun "
                "scripts/multichip_bench.py)")
        return findings

    floor = ent.get("attribution_frac_min")
    if floor is not None:
        v = ms.get("attribution_frac")
        if v is None:
            add(core.MESH_STALE, "attribution_frac_min",
                f"{path.name}: mesh_summary has no attribution_frac "
                "field — the artifact shape drifted from the budget")
        elif float(v) < float(floor):
            add(core.MESH_SKEW, "attribution_frac_min",
                f"{path.name}: only {float(v):.1%} of the dispatch "
                f"ledger wall carries per-device attribution (floor "
                f"{float(floor):.1%}) — the observatory went blind on "
                "part of the run")

    # mesh_summary.skew is nested {ledger name: {metric: stats}} (with
    # sampled device walls under the pseudo-name "device_wall", metric
    # "wall"); the ceiling applies to the WORST name per metric.
    worst: dict = {}
    for nm, metrics in (ms.get("skew") or {}).items():
        if not isinstance(metrics, dict):
            continue
        for metric, row in metrics.items():
            if not isinstance(row, dict) or "max_over_mean" not in row:
                continue
            v = float(row["max_over_mean"])
            if metric not in worst or v > worst[metric][0]:
                worst[metric] = (v, f"{nm}:{row.get('straggler', '?')}")
    for metric, ceil in sorted((ent.get("skew_max") or {}).items()):
        if metric not in worst:
            add(core.MESH_STALE, "skew_max",
                f"{path.name}: mesh_summary.skew has no {metric!r} "
                "metric under any ledger name — the budget names a "
                "load metric the run no longer records")
            continue
        v, who = worst[metric]
        if v > float(ceil):
            add(core.MESH_SKEW, "skew_max",
                f"{path.name}: per-device {metric} skew {v:.2f}x "
                f"(max/mean) exceeds the committed ceiling "
                f"{float(ceil):.2f}x — straggler {who}")

    axis_bytes = ms.get("bytes_by_axis") or {}
    for axis, ceil in sorted((ent.get("axis_bytes_max") or {}).items()):
        if axis not in axis_bytes:
            add(core.MESH_STALE, "axis_bytes_max",
                f"{path.name}: mesh_summary.bytes_by_axis has no "
                f"{axis!r} axis — the budget names a mesh axis the "
                "run no longer exchanges on")
            continue
        v = float(axis_bytes[axis])
        if v > float(ceil):
            add(core.MESH_BYTES, "axis_bytes_max",
                f"{path.name}: {v:.3g} measured bytes on mesh axis "
                f"{axis!r} exceed the committed ceiling "
                f"{float(ceil):.3g} — a collective grew (or joined a "
                "hot loop) since the budget was set")

    drift = ms.get("drift") or {}
    for dn, band in sorted((ent.get("drift_band") or {}).items()):
        lo, hi = float(band[0]), float(band[1])
        v = drift.get(dn)
        if v is None:
            add(core.MESH_STALE, "drift_band",
                f"{path.name}: no measured/predicted drift for "
                f"{dn!r} — the ledger name is gone, was never "
                "dispatched, or lost its cost-model prediction")
            continue
        v = float(v)
        if not (lo <= v <= hi):
            add(core.MESH_DRIFT, "drift_band",
                f"{path.name}: {dn} measured/predicted ICI drift "
                f"{v:.3f} outside the committed band [{lo}, {hi}] — "
                "the analytic cost model no longer matches what the "
                "kernel exchanges")
    return findings


def run_mesh(files=None, root=None) -> list[Finding]:
    """Run the mesh-observatory budget pass over the committed budgets
    (or an explicit fixture list); returns unsuppressed findings."""
    paths = ([pathlib.Path(f) for f in files] if files is not None
             else sorted(BUDGET_DIR.glob("mesh*.json")))
    findings: list[Finding] = []
    for p in paths:
        text = p.read_text()
        data = json.loads(text)
        for ent in data.get("artifacts", []):
            if "artifact" not in ent:
                raise ValueError(f"{p}: mesh budget entry without "
                                 "'artifact'")
            findings += check_artifact(ent, text, str(p), root=root)
    return findings
