"""Pass 5 — perf-regression gate over the committed bench trajectory.

The obs-residual pass (pass 4) pins *attribution* — how much of the
wall the recorder explains. This pass pins *performance itself*: the
canonical trajectory `scripts/bench_registry.py` builds from every
committed bench artifact (`obs.regress`) is held against declarative
floors and noise bands in `analysis/budgets/perf_regression.json`:

* **staleness** — the committed `BENCH_TRAJECTORY.json` must exist,
  parse against the canonical schema, and carry a run row for EVERY
  committed artifact the registry globs recognize. A bench landed
  without regenerating the trajectory is a gate failure, not a
  silently-shrinking baseline.
* **efficiency floors** — schema-`full` runs (the only ones that
  carry the cost-model join) must meet `min_attributable_frac` /
  `min_efficiency`. Null values are skipped (pre-PR-10 artifacts
  never crash the gate), so the floor bites exactly when a fresh
  instrumented run regresses its roofline verdict.
* **regression bands** — each workload's newest run (highest seq) is
  compared against the direction-aware best of its prior runs through
  `obs.regress.compare`: a `higher` metric (GTEPS) failing below
  baseline*(1-band) or a `lower` metric (wall) rising above
  baseline*(1+band) fails the gate.

Budget JSON shape (one object per file)::

    {"trajectory": "BENCH_TRAJECTORY.json",
     "efficiency_floors": [{"workload": "*", "schemas": ["full"],
                            "min_attributable_frac": 0.5,
                            "min_efficiency": 0.01}],
     "bands": [{"workload": "mcl", "metric": "wall_s",
                "direction": "lower", "band_frac": 0.5}],
     "allow": []}                      # waived rule ids

Everything here is pure JSON reads — nothing compiles or runs device
code.
"""

from __future__ import annotations

import json
import pathlib

from combblas_tpu.analysis import core
from combblas_tpu.analysis.core import Finding
from combblas_tpu.obs import regress

BUDGET_DIR = pathlib.Path(__file__).parent / "budgets"
REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]


def _line_of(text: str, key: str) -> int:
    """Line of the first occurrence of ``key`` in the budget file, so
    findings point at the violated number."""
    for i, ln in enumerate(text.splitlines()):
        if f'"{key}"' in ln:
            return i + 1
    return 1


def check_floors(data: dict, traj: dict) -> list:
    """(key, message) efficiency-floor violations — the unit the
    self-test fixture drives."""
    out = []
    for floor in data.get("efficiency_floors", ()):
        wl = floor.get("workload", "*")
        schemas = tuple(floor.get("schemas", ("full",)))
        for run in traj.get("runs", ()):
            if wl not in ("*", run.get("workload")):
                continue
            if run.get("schema") not in schemas:
                continue
            for metric, key in (("attributable_frac",
                                 "min_attributable_frac"),
                                ("efficiency", "min_efficiency")):
                floor_v = floor.get(key)
                v = run.get(metric)
                if floor_v is None or v is None:
                    continue   # pre-PR-10 runs carry no join: skip
                if float(v) < float(floor_v):
                    out.append((key, (
                        f"{run['run_id']}: {metric}={float(v):g} below "
                        f"the committed floor {float(floor_v):g} — the "
                        "roofline verdict regressed (see the artifact's "
                        "dispatch_summary.efficiency block)")))
    return out


def check_bands(data: dict, traj: dict) -> list:
    """(key, message) regression-band violations: newest run per
    workload vs the direction-aware baseline of its prior runs."""
    bands = data.get("bands")
    out = []
    for wl, run in sorted(regress.newest_runs(traj).items()):
        try:
            violations = regress.compare(run, traj, bands)
        except regress.SchemaError as e:
            out.append(("bands", f"{wl}: {e}"))
            continue
        for v in violations:
            out.append(("band_frac", v["message"]))
    return out


def check_coverage(traj: dict, root: pathlib.Path) -> list:
    """(key, message) staleness findings: committed artifacts the
    trajectory does not cover."""
    covered = {r.get("artifact") for r in traj.get("runs", ())}
    out = []
    seen = set()
    for pat, _wl in regress.ARTIFACT_GLOBS:
        for p in sorted(root.glob(pat)):
            if p.name in seen:
                continue
            seen.add(p.name)
            if p.name not in covered:
                out.append(("trajectory", (
                    f"{p.name} has no run row in the committed "
                    "trajectory — regenerate with "
                    "scripts/bench_registry.py")))
    return out


def check_budget(data: dict, budget_text: str, budget_path: str,
                 root=None) -> list[Finding]:
    """All findings for one perf budget file."""
    allow = set(data.get("allow", []))
    root = pathlib.Path(root or REPO_ROOT)
    findings: list[Finding] = []

    def add(rule, key, msg):
        if rule not in allow:
            findings.append(Finding(
                rule, budget_path, _line_of(budget_text, key), msg,
                entry="perf"))

    tr_name = data.get("trajectory", "BENCH_TRAJECTORY.json")
    tr_path = root / tr_name
    if not tr_path.exists():
        add(core.PERF_STALE, "trajectory",
            f"trajectory {tr_name!r} not found — run "
            "scripts/bench_registry.py to generate it")
        return findings
    try:
        traj = regress.load_trajectory(tr_path)
    except regress.SchemaError as e:
        add(core.PERF_STALE, "trajectory", f"unusable trajectory: {e}")
        return findings
    for key, msg in check_coverage(traj, root):
        add(core.PERF_STALE, key, msg)
    for key, msg in check_floors(data, traj):
        add(core.PERF_EFFICIENCY, key, msg)
    for key, msg in check_bands(data, traj):
        add(core.PERF_REGRESSION, key, msg)
    return findings


def run_perf(files=None, root=None) -> list[Finding]:
    """Run the perf-regression gate over the committed budgets (or an
    explicit fixture list); returns unsuppressed findings."""
    paths = ([pathlib.Path(f) for f in files] if files is not None
             else sorted(BUDGET_DIR.glob("perf_*.json")))
    findings: list[Finding] = []
    for p in paths:
        text = p.read_text()
        data = json.loads(text)
        findings += check_budget(data, text, str(p), root=root)
    return findings
