"""Pass 1 — declarative jaxpr/HLO budget engine.

Budgets are JSON files under `combblas_tpu/analysis/budgets/`, each
holding a list of kernel budgets::

    {"kernels": [{
        "entry": "esc.spgemm",            # entries.py registry name
        "env": {"COMBBLAS_TPU_FUSED_KEY": null},   # null = must be unset
        "sorts": {"count": 2, "operands_per_sort": 2,
                  "operands_total": 4},   # all EXACT
        "ceilings": {"gather": 20, "scatter": 10,
                     "dynamic_slice": 64, "while": 4},   # maxima
        "forbid_dtypes": ["i64"],
        "forbid_ops": ["callback"],       # substring match on jaxpr
                                          # primitives + custom_call targets
        "lane_invariance": true,          # variants must lower to the
                                          # same op histogram
        "allow": []                       # waived rule ids
    }]}

Sort budgets are EXACT in both directions: dropping below a pin means
the committed number is stale and must be re-measured, not silently
celebrated. Ceilings are maxima — dropping below them is improvement.
The numbers here are the single source of truth; tests
(`tests/test_hlo_passes.py`, `tests/test_analysis.py`) are thin shims
over this module.
"""

from __future__ import annotations

import json
import os
import pathlib
from typing import Optional

from combblas_tpu.analysis import core, entries, hlo
from combblas_tpu.analysis.core import Finding

BUDGET_DIR = pathlib.Path(__file__).parent / "budgets"

#: ops whose ceilings a budget may pin (budget key -> stablehlo op)
_CEILING_OPS = ("sort", "gather", "scatter", "dynamic_slice",
                "dynamic_update_slice", "while", "reduce", "iota",
                "custom_call", "all_reduce", "all_to_all")


def load_budget_file(path) -> tuple[list[dict], str]:
    text = pathlib.Path(path).read_text()
    data = json.loads(text)
    kernels = data.get("kernels", [])
    for k in kernels:
        if "entry" not in k:
            raise ValueError(f"{path}: kernel budget without 'entry'")
    return kernels, text


def _line_of(text: str, anchor: str, key: str) -> int:
    """Line of ``key`` inside the budget block that contains
    ``anchor`` (the entry name) — findings point at the violated
    number, not just the file."""
    lines = text.splitlines()
    start = 0
    for i, ln in enumerate(lines):
        if anchor in ln:
            start = i
            break
    for i in range(start, len(lines)):
        if f'"{key}"' in lines[i]:
            return i + 1
    return start + 1


class _Env:
    """Apply a budget's env overrides for the duration of the trace
    (null = ensure unset), clearing jit caches when anything changes —
    env-dependent branches (COMBBLAS_TPU_FUSED_KEY) are read at trace
    time."""

    def __init__(self, env: Optional[dict]):
        self.env = env or {}
        self._saved: dict = {}

    def __enter__(self):
        if not self.env:
            return self
        import jax
        for k, v in self.env.items():
            self._saved[k] = os.environ.get(k)
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        jax.clear_caches()
        return self

    def __exit__(self, *exc):
        if not self.env:
            return False
        import jax
        for k, old in self._saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        jax.clear_caches()
        return False


def _trace_views(fn, args) -> tuple[str, dict]:
    """(stablehlo text, jaxpr primitive histogram) from ONE trace when
    the AOT `.trace()` API is available, else two."""
    import jax
    jitted = jax.jit(fn)  # analysis: allow(cache-key-unstable) analysis-only trace, never dispatched
    if hasattr(jitted, "trace"):
        traced = jitted.trace(*args)
        txt = traced.lower().as_text()
        from collections import Counter
        hist: Counter = Counter()
        hlo._walk_jaxpr(traced.jaxpr.jaxpr, hist)
        return txt, dict(hist)
    return (jitted.lower(*args).as_text(),
            hlo.jaxpr_primitives(fn, *args))


def check_text(txt: str, kb: dict, file: str, text: str = "",
               prims: Optional[dict] = None,
               label: str = "") -> list[Finding]:
    """Evaluate one kernel budget against already-lowered StableHLO
    text (and optionally a jaxpr primitive histogram). Pure — the
    self-test feeds committed bad-pattern fixtures through here."""
    name = kb["entry"] + (f"[{label}]" if label else "")
    anchor = kb["entry"]
    ln = lambda key: _line_of(text, anchor, key) if text else 1  # noqa: E731
    out: list[Finding] = []
    ops = hlo.op_histogram(txt)

    sorts = kb.get("sorts")
    if sorts is not None:
        ar = hlo.sort_arities(txt)
        want = sorts.get("count")
        if want is not None and len(ar) != want:
            out.append(Finding(core.SORT_COUNT, file, ln("count"),
                               f"expected exactly {want} stablehlo.sort "
                               f"ops, lowering has {len(ar)}", name))
        per = sorts.get("operands_per_sort")
        if per is not None and any(x != per for x in ar):
            out.append(Finding(core.SORT_ARITY, file,
                               ln("operands_per_sort"),
                               f"expected {per} operands per sort, "
                               f"got arities {ar}", name))
        tot = sorts.get("operands_total")
        if tot is not None and sum(ar) != tot:
            out.append(Finding(core.SORT_ARITY, file,
                               ln("operands_total"),
                               f"expected {tot} total sorted operands, "
                               f"got {sum(ar)} ({ar})", name))

    for op, ceil in (kb.get("ceilings") or {}).items():
        got = ops.get(op, 0)
        if got > ceil:
            out.append(Finding(core.OP_CEILING, file, ln(op),
                               f"stablehlo.{op} count {got} exceeds "
                               f"ceiling {ceil}", name))

    for dt in kb.get("forbid_dtypes", ()):
        hits = hlo.find_dtype_tensors(txt, dt)
        if hits:
            out.append(Finding(core.FORBID_DTYPE, file,
                               ln("forbid_dtypes"),
                               f"{len(hits)} {dt} tensor(s) leaked into "
                               f"the lowering (e.g. {hits[0]})", name))

    patterns = tuple(kb.get("forbid_ops", ()))
    if patterns:
        bad = [t for t in hlo.custom_call_targets(txt)
               if any(p in t for p in patterns)]
        if prims is not None:
            bad += hlo.forbidden_primitives(prims, patterns)
        if bad:
            out.append(Finding(core.FORBID_OP, file, ln("forbid_ops"),
                               f"forbidden op(s) in jitted path: "
                               f"{sorted(set(bad))}", name))
    return out


def check_kernel(kb: dict, file: str, text: str = "") -> list[Finding]:
    """Build the kernel's registered entry, trace it (and its
    variants), and evaluate every budget in ``kb``."""
    spec = entries.get(kb["entry"])
    with _Env(kb.get("env")):
        built = spec.build()
        txt, prims = _trace_views(built["fn"], built["args"])
        out = check_text(txt, kb, file, text, prims)
        variants = built.get("variants") or {}
        if kb.get("lane_invariance") and variants:
            base_hist = hlo.op_histogram(txt)
            for label, (vfn, vargs) in variants.items():
                vtxt, vprims = _trace_views(vfn, vargs)
                out += check_text(vtxt, kb, file, text, vprims, label)
                vhist = hlo.op_histogram(vtxt)
                if vhist != base_hist:
                    diff = {op: (base_hist.get(op, 0), vhist.get(op, 0))
                            for op in set(base_hist) | set(vhist)
                            if base_hist.get(op, 0) != vhist.get(op, 0)}
                    out.append(Finding(
                        core.LANE_INVARIANCE, file,
                        _line_of(text, kb["entry"], "lane_invariance")
                        if text else 1,
                        f"op structure differs between lane widths "
                        f"(variant {label}): {diff}", kb["entry"]))
    allow = set(kb.get("allow", ()))
    return [f for f in out if f.rule not in allow]


def run_budgets(files=None, only_entry: Optional[str] = None
                ) -> list[Finding]:
    """Evaluate budget files (default: every kernel-type JSON in
    `BUDGET_DIR`) and return the surviving findings."""
    if files is None:
        files = sorted(p for p in BUDGET_DIR.glob("*.json"))
    out: list[Finding] = []
    for path in files:
        kernels, text = load_budget_file(path)
        for kb in kernels:
            if only_entry is not None and kb["entry"] != only_entry:
                continue
            out += check_kernel(kb, str(path), text)
    return out
