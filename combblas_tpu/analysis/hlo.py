"""Walkers over closed jaxprs and unoptimized StableHLO text.

The budget engine inspects the GENERATED program, not the source
(JITSPMM, arxiv 2312.05639: what matters is what the compiler was
handed). Two complementary views:

* the unoptimized StableHLO lowering (`lower_text`): op counts here
  are stable across XLA versions (no fusion heuristics run yet) and
  in 1:1 correspondence with the jnp-level ops a kernel emits — the
  right place to pin sort counts, sorted-operand arity, and
  gather/scatter/while ceilings;
* the closed jaxpr (`jaxpr_primitives`): the right place to catch
  forbidden PRIMITIVES — `pure_callback`/`io_callback` smuggled into
  a jitted path keeps its name in the jaxpr but lowers to an opaque
  `stablehlo.custom_call`, so the jaxpr view is the reliable one.
"""

from __future__ import annotations

import re
from collections import Counter

import jax


def lower_text(fn, *args) -> str:
    """Unoptimized StableHLO text of ``jit(fn)(*args)`` — trace only,
    nothing is compiled or executed."""
    return jax.jit(fn).lower(*args).as_text()  # analysis: allow(cache-key-unstable) analysis-only lowering, never dispatched


def op_histogram(txt: str) -> dict[str, int]:
    """{stablehlo op name: count}. Matches both the quoted generic
    form (``"stablehlo.sort"(...)``) and the pretty-printed form
    (``stablehlo.while``)."""
    return dict(Counter(re.findall(r"stablehlo\.([A-Za-z0-9_]+)", txt)))


def count_op(txt: str, op: str) -> int:
    return op_histogram(txt).get(op, 0)


def sort_arities(txt: str) -> list[int]:
    """Operand count of each stablehlo.sort (the sorted-bytes knob:
    the fused-key ESC pipeline carries key+payload = 2; the legacy
    2-key path carries row+col+payload = 3)."""
    return [m.group(1).count("%")
            for m in re.finditer(r'"stablehlo\.sort"\(([^)]*)\)', txt)]


def find_dtype_tensors(txt: str, dtype: str) -> list[str]:
    """Tensor TYPES of the given element dtype (e.g. "i64") — not MLIR
    attribute metadata: scalar literals like ``0 : i64`` never match
    the tensor<> pattern, and dense attribute literals (e.g. a
    collective's ``replica_groups = dense<0> : tensor<1x1xi64>``) are
    stripped first — they are compile-time metadata, not device
    arrays."""
    txt = re.sub(rf"dense<[^>]*>\s*:\s*tensor<[0-9x]*{dtype}>", "", txt)
    return re.findall(rf"tensor<[0-9x]*{dtype}>", txt)


def custom_call_targets(txt: str) -> list[str]:
    return re.findall(r'call_target_name\s*=\s*"([^"]+)"', txt)


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------

def _walk_jaxpr(jaxpr, hist: Counter) -> None:
    for eqn in jaxpr.eqns:
        hist[eqn.primitive.name] += 1
        for v in eqn.params.values():
            _walk_param(v, hist)


def _walk_param(v, hist: Counter) -> None:
    # sub-jaxprs hide under many param names (jaxpr, call_jaxpr,
    # cond_jaxpr, body_jaxpr, branches tuples, ...): duck-walk anything
    # that looks like a (Closed)Jaxpr, recurse into tuples/lists
    if hasattr(v, "eqns"):
        _walk_jaxpr(v, hist)
    elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
        _walk_jaxpr(v.jaxpr, hist)
    elif isinstance(v, (tuple, list)):
        for x in v:
            _walk_param(x, hist)


def jaxpr_primitives(fn, *args) -> dict[str, int]:
    """{primitive name: count} over the closed jaxpr of fn(*args),
    including every nested sub-jaxpr (while bodies, cond branches,
    inner pjit calls)."""
    closed = jax.make_jaxpr(fn)(*args)
    hist: Counter = Counter()
    _walk_jaxpr(closed.jaxpr, hist)
    return dict(hist)


def forbidden_primitives(prims: dict[str, int],
                         patterns: tuple[str, ...]) -> list[str]:
    """Primitive names matching any forbidden substring pattern (e.g.
    "callback" catches pure_callback/io_callback/debug_callback)."""
    out = []
    for name in sorted(prims):
        if any(pat in name for pat in patterns):
            out.append(name)
    return out
