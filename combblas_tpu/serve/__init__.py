"""Graph-query serving layer: request queue, dynamic batcher, plan
cache, deadlines.

The ROADMAP north star is serving heavy query traffic, but every
driver in `models/` is one-shot: each BFS/CC/SpMV pays its own
dispatch + readback round trip — the overhead class the round-5
verdict measured at ~63% of expansion wall time. This package is the
request-level layer that amortizes it, the same shape as an inference
serving stack:

* `serve.queue`   — thread-safe FIFO with admission control (bounded
  depth -> `QueueFullError` backpressure) and per-request deadlines;
* `serve.batcher` — coalesces concurrent same-kind queries into one
  device dispatch: BFS roots become the columns of a batched
  `bfs_batch` traversal, SpMV/SpMSpV operands stack into a
  `DistMultiVec` SpMM, CC label lookups share one gather. Batch
  widths are bucketed so every dispatch hits the jit cache;
* `serve.plans`   — the executable cache keyed (kind, semiring,
  bucket, mesh) with warm-up prefill;
* `serve.engine`  — `GraphService`: the worker loop wiring queue ->
  batcher -> dispatch -> readback, deadline degradation (partial BFS
  levels, queue shed), and full `combblas_tpu.obs` instrumentation.

Quick start::

    from combblas_tpu import serve
    svc = serve.GraphService(a)          # a: DistSpMat (symmetric)
    h1 = svc.submit_bfs(root=7)
    h2 = svc.submit_cc(vertex=42)
    parents = h1.result().parents        # blocks; np.ndarray (n,)
    label = h2.result()
    svc.stop()

Not imported from the package root (it pulls `models.bfs`): use
``from combblas_tpu import serve`` explicitly.
"""

from combblas_tpu.resilience.breaker import CircuitOpenError
from combblas_tpu.serve.queue import (
    DeadlineExceededError, QueueFullError, Request, RequestQueue,
    ResultHandle, ServeError, ServiceStoppedError, WorkerCrashedError,
)
from combblas_tpu.serve.batcher import Batch, DynamicBatcher, bucket_for
from combblas_tpu.serve.plans import PlanCache, PlanKey
from combblas_tpu.serve.engine import BfsResult, GraphService
