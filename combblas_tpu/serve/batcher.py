"""Dynamic batcher: coalesce concurrent same-kind queries into one
device dispatch.

Policy: the head-of-queue request fixes the batch kind; the batcher
pulls every queued request of that kind (up to the largest bucket)
and lingers up to ``batch_wait_s`` for stragglers — latency is traded
for occupancy only while the batch is not yet full. Expired requests
are shed at formation time (their handles get `DeadlineExceededError`;
the shed counter records why) so a dead request never occupies a
device slot.

Batch widths are BUCKETED (`bucket_for`): the executors pad every
batch up to the smallest configured bucket that fits, so a service
with buckets (1, 2, 4, 8, 16, 32) compiles at most 6 executables per
query kind and every dispatch is a jit-cache hit — the same
shape-bucketing discipline as `distmat._qbucket` for nnz capacity.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from combblas_tpu.serve.queue import (
    DeadlineExceededError, Request, RequestQueue,
)


def bucket_for(n: int, buckets: tuple) -> int:
    """Smallest configured bucket >= n (callers split batches larger
    than the top bucket, so n <= max(buckets) always holds there)."""
    if n < 1:
        raise ValueError("empty batch has no bucket")
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} exceeds largest bucket "
                     f"{max(buckets)}")


@dataclasses.dataclass
class Batch:
    """Formed batch: same-kind requests plus the padded width the
    executor will dispatch at."""

    kind: str
    requests: list
    bucket: int

    @property
    def occupancy(self) -> float:
        return len(self.requests) / self.bucket


class DynamicBatcher:
    """Pulls batches off a `RequestQueue`. ``on_shed(request,
    reason)`` is called for every request dropped at formation time
    (after its handle got the typed error) so the engine can count
    sheds without the batcher knowing about metrics."""

    def __init__(self, queue: RequestQueue, buckets: tuple,
                 batch_wait_s: float = 0.0, on_shed=None):
        self.queue = queue
        self.buckets = tuple(sorted(buckets))
        self.batch_wait_s = batch_wait_s
        self.on_shed = on_shed

    def _shed_expired(self, reqs: list) -> list:
        now = time.monotonic()
        live = []
        for r in reqs:
            if r.expired(now):
                r.handle.set_exception(DeadlineExceededError(
                    f"{r.kind} deadline expired after "
                    f"{now - r.enqueued_at:.4f}s in queue"))
                if self.on_shed is not None:
                    self.on_shed(r, "deadline")
            else:
                live.append(r)
        return live

    def form(self) -> Optional[Batch]:
        """Form the next batch, or None when the queue is empty (or
        everything pulled had expired). Non-blocking apart from the
        linger window."""
        kind = self.queue.head_kind()
        if kind is None:
            return None
        cap = self.buckets[-1]
        reqs = self.queue.take(kind, cap)
        if self.batch_wait_s > 0 and len(reqs) < cap:
            t_end = time.monotonic() + self.batch_wait_s
            while len(reqs) < cap:
                more = self.queue.take(kind, cap - len(reqs))
                reqs.extend(more)
                rem = t_end - time.monotonic()
                if rem <= 0:
                    break
                if not more:
                    time.sleep(min(rem, 5e-4))
        reqs = self._shed_expired(reqs)
        if not reqs:
            return None
        return Batch(kind, reqs, bucket_for(len(reqs), self.buckets))
