"""Plan cache: jitted executables keyed on (kind, semiring, bucket,
mesh), with warm-up prefill.

jax's jit cache already keys compiled executables on abstract shapes;
what it cannot answer is "will THIS dispatch compile or run?" — on
the emulated CPU mesh (and cold TPU pods) a first-touch compile is
seconds to minutes, which inside a serving loop is a deadline
massacre. The plan cache makes the executable set explicit: one entry
per (kind, semiring, bucket, mesh-shape), a `build` miss is the ONLY
place a compile can happen, and `GraphService.warmup()` walks every
(kind x bucket) with dummy batches so steady-state traffic never eats
a compile. Hit/miss counters flow to `obs` and the engine's stats.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, NamedTuple

from combblas_tpu import obs

_plan_hits = obs.counter("serve.plan_hits",
                         "plan-cache hits by kind/bucket")
_plan_misses = obs.counter("serve.plan_misses",
                           "plan-cache misses (compiles) by kind/bucket")


class PlanKey(NamedTuple):
    """Identity of one compiled executable."""

    kind: str          # "bfs" | "cc" | "spmv:<semiring>" | ...
    semiring: str      # semiring name, or "-" when kind implies it
    bucket: int        # padded batch width
    mesh: tuple        # (pr, pc) grid shape
    lanes: int = 0     # packed-bit lane width (bfs bits path: 32 roots
    #                    per uint32 word; 0 = dense/unpacked executable)


def _plan_name(key: PlanKey) -> str:
    sr = "" if key.semiring in ("-", "") else f".{key.semiring}"
    lanes = f".l{key.lanes}" if key.lanes else ""
    return f"serve.{key.kind}{sr}/w{key.bucket}{lanes}"


@dataclasses.dataclass
class PlanEntry:
    """One cache slot. `ready` is the single-flight latch: the first
    caller claims the slot (fn=None) and builds outside the lock;
    concurrent callers of the same key park on `ready` instead of
    building a duplicate. A builder that raises mid-compile must NOT
    poison the slot: the entry is removed (next caller rebuilds) and
    the exception is fanned to every parked waiter via `error`."""

    fn: Callable | None = None
    hits: int = 0
    ready: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    error: BaseException | None = None


class PlanCache:
    """key -> executor map. `get_or_build` is the single choke point:
    the builder runs at most once per key (single-flight — racing
    callers wait for the in-progress build), every later lookup is a
    hit. A failed build surfaces to the builder AND its waiters, and
    leaves no entry behind."""

    def __init__(self):
        self._plans: dict[PlanKey, PlanEntry] = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._plans)

    def keys(self) -> list:
        with self._lock:
            return list(self._plans)

    def get_or_build(self, key: PlanKey,
                     builder: Callable[[], Callable]) -> Callable:
        with self._lock:
            e = self._plans.get(key)
            if e is None:
                e = self._plans[key] = PlanEntry()
                lead = True
            else:
                lead = False
                if e.fn is not None:
                    e.hits += 1
                    _plan_hits.inc(kind=key.kind, bucket=key.bucket)
                    return e.fn
        if not lead:
            # single-flight waiter: park OUTSIDE the lock until the
            # lead's build settles, then share its outcome
            e.ready.wait()
            if e.error is not None:
                raise e.error
            with self._lock:
                e.hits += 1
                _plan_hits.inc(kind=key.kind, bucket=key.bucket)
                return e.fn
        # lead builder, OUTSIDE the lock (compiles are long; lookups of
        # other keys must not stall behind them). Every built executable
        # goes through the dispatch ledger — one wrapper per plan, named
        # by its key, so serve dispatches land in the flight recorder
        # with executable-level attribution (pass-through when the
        # ledger is disabled).
        try:
            fn = obs.instrument(builder(), _plan_name(key))
        except BaseException as exc:
            with self._lock:
                e.error = exc
                self._plans.pop(key, None)   # next caller rebuilds
            e.ready.set()
            raise
        with self._lock:
            e.fn = fn
            _plan_misses.inc(kind=key.kind, bucket=key.bucket)
        e.ready.set()
        return fn

    def stats(self) -> dict:
        with self._lock:
            return {f"{k.kind}/w{k.bucket}": e.hits
                    for k, e in sorted(self._plans.items())}

    def memory_stats(self) -> dict:
        """Per-plan compile-time HBM byte accounting, joined from the
        memledger's footprint census by the plan's ledger name. Returns
        {plans: {name: {arg,out,temp,total}_bytes}, by_kind:
        {kind: total_bytes}, total_bytes, temp_bytes, plans_with_footprint}.
        Plans whose executables never landed in the census (census off,
        or the plan wraps host-side work that never hit XLA) are simply
        absent from `plans` — the substrate a byte-aware eviction policy
        (multi-tenant LRU) charges per entry."""
        from combblas_tpu.obs import memledger as _memledger
        with self._lock:
            keys = list(self._plans)
        plans: dict = {}
        by_kind: dict = {}
        total = temp = 0
        for k in sorted(keys):
            fp = _memledger.footprint_for(_plan_name(k))
            if fp is None:
                continue
            row = {"arg_bytes": fp["arg_bytes"],
                   "out_bytes": fp["out_bytes"],
                   "temp_bytes": fp["temp_bytes"],
                   "total_bytes": fp["total_bytes"]}
            plans[_plan_name(k)] = row
            by_kind[k.kind] = by_kind.get(k.kind, 0) + row["total_bytes"]
            total += row["total_bytes"]
            temp += row["temp_bytes"]
        return {"plans": plans, "by_kind": by_kind,
                "total_bytes": total, "temp_bytes": temp,
                "plans_with_footprint": len(plans)}
