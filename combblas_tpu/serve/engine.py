"""`GraphService`: the worker loop wiring queue -> batcher ->
dispatch -> readback.

One daemon worker thread owns the device: it pulls a same-kind batch
off the queue (batcher.py), pads it to the jit bucket, runs ONE
compiled executable for the whole batch (plans.py), and fans results
back out to the per-request handles. Query kinds:

* **bfs** — eligible matrices (routed + 1x1 pattern-symmetric, OR a
  square routed mesh; cfg.bfs_bits / COMBBLAS_TPU_SERVE_BITS=0) batch
  through `models.bfs.bfs_batch_bits`: packed-bit bitplane frontiers,
  32 roots per uint32 word, buckets lane-aligned to 32 — on meshes
  the lane-packed words ride the explicit transpose exchange
  (`_bfs_batch_bits_mesh_core`). Everything else rides the columns of
  `models.bfs.bfs_batch` (one while_loop traversal for the whole
  batch, bit-exact vs per-root `bfs`); each degradation is surfaced
  in /varz (`bfs_bits.fallback_reason`).
  Deadlines degrade gracefully on both paths: the level budget is
  min-remaining-time / EWMA-per-level-estimate, and roots whose
  traversal was truncated return `BfsResult(complete=False)` with the
  partial parents rather than an error.
* **cc** — component labels are computed ONCE (lazy `cc.fastsv`, a
  single amortized dispatch); each batch of lookups is one device
  gather.
* **spmv:<semiring>** — operand vectors stack into the columns of one
  `densemat.spmm` (on square meshes the tall-and-skinny
  `densemat.spmm_tall` schedule: the stacked panel ships with one
  collective_permute, A stays put). SpMSpV queries densify (mask ->
  add-identity, which annihilates every shipped semiring's multiply)
  and coalesce into the SAME batches.

Instrumented through `combblas_tpu.obs` (queue-depth gauge,
batch-occupancy + latency histograms with p50/p90/p99, shed/dispatch
counters) AND a plain `stats` dict that counts regardless of whether
obs is enabled — tests and callers read `stats`, dashboards read obs.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from combblas_tpu import obs
from combblas_tpu.models import bfs as _bfs
from combblas_tpu.models import cc as _cc
from combblas_tpu.ops.semiring import PLUS_TIMES_F32, Semiring
from combblas_tpu.parallel import densemat as dmm
from combblas_tpu.parallel.grid import COL_AXIS, ROW_AXIS
from combblas_tpu.serve.batcher import Batch, DynamicBatcher, bucket_for
from combblas_tpu.serve.plans import PlanCache, PlanKey, _plan_name
from combblas_tpu.resilience.breaker import CircuitBreaker, CircuitOpenError
from combblas_tpu.resilience.retry import RetryPolicy, retry_call
from combblas_tpu.serve.queue import (
    DeadlineExceededError, QueueFullError, Request, RequestQueue,
    ResultHandle, ServiceStoppedError, WorkerCrashedError,
)
from combblas_tpu.utils.config import ServeConfig

#: packed-bit BFS lane width: one uint32 frontier word carries 32 roots
_LANE_W = 32

_queue_depth = obs.gauge("serve.queue_depth", "requests waiting")
_occupancy = obs.histogram(
    "serve.batch_occupancy", "filled fraction of the dispatched bucket",
    bounds=tuple((k + 1) / 8 for k in range(8)))
_latency = obs.histogram(
    "serve.latency_s", "submit->result wall seconds per request",
    bounds=tuple(1e-4 * 2 ** k for k in range(22)))
_dispatches = obs.counter("serve.dispatches",
                          "device dispatches by query kind")
_shed = obs.counter("serve.shed", "requests shed, by reason")
_queue_hw = obs.gauge("serve.queue_high_water",
                      "deepest the request queue has ever been")
_slo_burn = obs.gauge(
    "serve.slo_burn_rate",
    "error-budget burn rate by kind: (bad_frac)/(1-slo_target); "
    "1.0 = burning exactly at the sustainable rate")
_efficiency = obs.gauge(
    "serve.efficiency",
    "wall-weighted roofline efficiency of this kind's dispatches "
    "(obs.costmodel join over serve.* ledger names)")
_mem_headroom = obs.gauge(
    "serve.memory_headroom",
    "fraction of backend HBM not accounted for by the larger of "
    "peak live-buffer bytes and the largest compiled footprint")
_plan_bytes = obs.gauge(
    "serve.plan_cache_bytes",
    "compile-time HBM bytes of cached plan executables, by kind")
_worker_crashes = obs.counter(
    "serve.worker_crashes",
    "worker-thread crashes caught by the supervisor (each one drains "
    "queued futures with WorkerCrashedError and restarts the loop)")


@dataclasses.dataclass
class BfsResult:
    """One root's traversal result. ``complete`` is False when the
    deadline's level budget truncated the traversal — ``parents`` then
    holds every vertex reached within ``levels`` levels (a valid BFS
    prefix), not the full tree."""

    parents: np.ndarray     # (n,) int32, NO_PARENT where unreached
    levels: int             # levels the batch ran
    complete: bool
    root: int


class GraphService:
    """Batching query service over one distributed matrix.

    ``a`` must satisfy the same contract as `models.bfs.bfs` /
    `models.cc.fastsv`: incoming-edge orientation, symmetric for BFS
    parity with the reference. Construct, submit, read handles::

        svc = GraphService(a)
        handles = [svc.submit_bfs(r) for r in roots]
        results = [h.result() for h in handles]
        svc.stop()

    ``autostart=False`` leaves the worker stopped so tests can queue a
    known set of requests and `start()` deterministic batches.
    """

    def __init__(self, a, config: Optional[ServeConfig] = None, *,
                 plan=None, autostart: bool = True):
        self.a = a
        self.cfg = config or ServeConfig()
        self.queue = RequestQueue(self.cfg.max_queue_depth)
        self.plans = PlanCache()
        self.batcher = DynamicBatcher(
            self.queue, self.cfg.buckets, self.cfg.batch_wait_s,
            on_shed=self._note_shed)
        # plain-python mirror of the obs counters: obs only records
        # when tracing is enabled; `stats` always counts
        self.stats = {"queries": 0, "results": 0, "batches": 0,
                      "dispatches": 0, "warmup_dispatches": 0,
                      "shed": 0, "partials": 0, "rejected": 0,
                      "worker_restarts": 0, "retries": 0}
        self._stats_lock = threading.Lock()
        # per-kind SLO ledger: kind -> {"good": n, "bad": n}. A request
        # is good when it completes within cfg.slo_latency_s of
        # enqueue; shed/stopped requests are bad (they burned budget).
        self._slo: dict = {}
        self._nnz_cache: Optional[int] = None   # host nnz, synced once
        self._mesh = (a.grid.pr, a.grid.pc)
        self._bfs_level_est = self.cfg.bfs_level_est_s
        # per-kind EWMA dispatch-cost estimates (shed-before-dispatch
        # for cc/spmv; BFS degrades via the level budget instead)
        self._cost_est: dict = {}
        # BFS structure plans: resolved lazily on first BFS (routing is
        # host-side work best kept off the constructor). ``plan`` lets
        # callers hand in a prebuilt BfsPlan (routed or not).
        self._base_plan = plan
        self._bits_plan = None
        self._bits_reason = None      # why the bits path is off, if it is
        self._plans_resolved = False
        self._plan_lock = threading.Lock()
        if self.cfg.latency_sketch:
            _latency.use_sketch(True)
        self._cc_labels = None          # lazy device label vector
        self._cc_lock = threading.Lock()
        # resilience: supervision state + per-kind circuit breakers
        # (created lazily; breaker_threshold=0 disables the breaker)
        self._worker_dead = False
        self._breakers: dict = {}
        self._breaker_lock = threading.Lock()
        self._retry_policy = RetryPolicy(
            max_attempts=self.cfg.retry_max_attempts,
            backoff_s=self.cfg.retry_backoff_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._metrics_server = None
        if autostart:
            self.start()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._worker, name="graphservice-worker", daemon=True)
        self._thread.start()

    def stop(self, drain: bool = True) -> None:
        """Stop the worker. ``drain=True`` serves everything already
        queued first; ``drain=False`` fails pending requests with
        `ServiceStoppedError`."""
        if self._thread is None:
            return
        self._stop.set()
        if not drain:
            self._fail_pending()
        self._thread.join()
        self._thread = None
        self._fail_pending()    # anything that raced the final check
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None

    def start_metrics_server(self, port: int = 0,
                             host: str = "127.0.0.1"):
        """Expose `/metrics` (Prometheus), `/varz` (JSON), `/healthz`
        on a daemon thread — entirely off the dispatch path (handlers
        only read snapshots). Port 0 picks a free port; returns the
        running `obs.httpd.MetricsServer` (read `.url`). Stopped by
        `stop()`."""
        if self._metrics_server is None:
            self._metrics_server = obs.serve_metrics(
                port=port, host=host, varz=self._varz,
                pre_scrape=self._refresh_serve_gauges)
        return self._metrics_server

    def _varz(self) -> dict:
        """Service block of /varz, and the /healthz verdict: healthy
        iff the worker thread is actually alive (or the service was
        never started / cleanly stopped — a crashed worker is the
        unhealthy case)."""
        started = self._thread is not None
        with self._stats_lock:
            stats = dict(self.stats)
        with self._breaker_lock:
            breakers = {k: b.snapshot() for k, b in
                        sorted(self._breakers.items())}
        return {
            "healthy": ((not started) or self._thread.is_alive())
            and not self._worker_dead,
            "started": started,
            # degraded-with-restart-count: the worker crashed at least
            # once (queued futures were failed fast) but the service is
            # still taking traffic — dashboards distinguish "limping"
            # from the healthy=false "dead" verdict
            "resilience": {
                "worker_restarts": stats["worker_restarts"],
                "worker_dead": self._worker_dead,
                "degraded": stats["worker_restarts"] > 0,
                "retries": stats["retries"],
                "breakers": breakers,
            },
            "stats": stats,
            "queue_depth": len(self.queue),
            "queue_high_water": self.queue.high_water,
            "plan_cache": self.plans.stats(),
            "plans": len(self.plans),
            "cost_est_s": dict(self._cost_est),
            "bfs_level_est_s": self._bfs_level_est,
            # packed-bit path visibility: which BFS path this service
            # resolved to (and why not bits, if not), plus the
            # process-wide degradation counters (populated when obs
            # tracing is on) — fleet operators see the 32x economics
            # being lost without grepping logs
            "bfs_bits": {
                "path": ("bits" if self._bits_plan is not None
                         else ("unresolved" if not self._plans_resolved
                               else "dense")),
                "fallback_reason": self._bits_reason,
                "fallbacks": {
                    r: _bfs._M_BITS_FALLBACK.value(kind=r)
                    for r in _bfs.BITS_FALLBACK_REASONS},
            },
            # SLO verdict + per-kind roofline efficiency: the same
            # numbers the `serve.slo_burn_rate{kind}` /
            # `serve.efficiency{kind}` gauges publish on /metrics
            "slo": {
                "latency_s": self.cfg.slo_latency_s,
                "target": self.cfg.slo_target,
                "kinds": self._slo_snapshot(),
            },
            "efficiency": obs.costmodel.efficiency_by(self._serve_kind),
            # byte-level plan accounting: what each cached executable
            # costs in HBM (compile-time census join) plus the service
            # headroom verdict — the numbers a byte-aware LRU or a
            # multi-tenant packer would charge against
            "plan_memory": self.plans.memory_stats(),
            "memory_headroom": obs.memledger.headroom(),
        }

    # ------------------------------------------------------------------
    # SLO accounting + per-kind roofline gauges
    # ------------------------------------------------------------------

    def _slo_count(self, kind: str, good: bool) -> None:
        base = kind.split(":", 1)[0]     # spmv:<sr> pools under "spmv"
        with self._stats_lock:
            row = self._slo.setdefault(base, {"good": 0, "bad": 0})
            row["good" if good else "bad"] += 1

    def _slo_snapshot(self) -> dict:
        """kind -> {good, bad, bad_frac, burn_rate}. Burn rate is
        bad_frac/(1-slo_target): 1.0 burns the error budget exactly at
        the sustainable rate, >1 exhausts it early."""
        with self._stats_lock:
            slo = {k: dict(v) for k, v in self._slo.items()}
        denom = max(1.0 - self.cfg.slo_target, 1e-9)
        out = {}
        for kind, row in sorted(slo.items()):
            total = row["good"] + row["bad"]
            bad_frac = row["bad"] / total if total else 0.0
            out[kind] = {"good": row["good"], "bad": row["bad"],
                         "bad_frac": round(bad_frac, 6),
                         "burn_rate": round(bad_frac / denom, 4)}
        return out

    @staticmethod
    def _serve_kind(name: str) -> Optional[str]:
        """Ledger-name -> request-kind grouping for the efficiency
        gauges: "serve.bfs.bits/w32.l32" -> "bfs", "serve.cc/w8" ->
        "cc", "serve.spmv.plus_times_f32/w8" -> "spmv"; non-serve
        names -> None (excluded from the per-kind split)."""
        if not name.startswith("serve."):
            return None
        return name[len("serve."):].split(".", 1)[0].split("/", 1)[0]

    def _refresh_serve_gauges(self) -> None:
        """Pre-scrape hook (obs.httpd calls it right before rendering
        /metrics and /varz): publish the per-kind SLO burn-rate and
        roofline-efficiency gauges from current state — gauges stay
        fresh without any work on the dispatch path."""
        for kind, row in self._slo_snapshot().items():
            _slo_burn.set(row["burn_rate"], kind=kind)
        for kind, eff in obs.costmodel.efficiency_by(
                self._serve_kind).items():
            _efficiency.set(eff, kind=kind)
        hr = obs.memledger.headroom()
        if hr["headroom_frac"] is not None:
            _mem_headroom.set(hr["headroom_frac"])
        for kind, nbytes in self.plans.memory_stats()["by_kind"].items():
            _plan_bytes.set(nbytes, kind=kind)

    def _fail_pending(self) -> None:
        for r in self.queue.drain():
            r.handle.set_exception(
                ServiceStoppedError("service stopped"))
            self._note_shed(r, "stopped")

    def __enter__(self) -> "GraphService":
        return self

    def __exit__(self, *exc) -> None:
        self.stop(drain=exc[0] is None)

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------

    def _submit(self, kind: str, payload,
                deadline_s: Optional[float]) -> ResultHandle:
        # pre-start submission is allowed (autostart=False queues a
        # known set, then start() forms deterministic batches); only a
        # stopping/stopped service refuses
        if self._stop.is_set():
            raise ServiceStoppedError("service is stopped")
        if self._worker_dead:
            raise WorkerCrashedError(
                "serve worker is dead (crashed more than "
                f"{self.cfg.worker_max_restarts} times); refusing new "
                "work — restart the service")
        if deadline_s is None:
            deadline_s = self.cfg.default_deadline_s
        now = time.monotonic()
        deadline = None if deadline_s is None else now + deadline_s
        trace_id = obs.new_trace_id()
        h = ResultHandle(trace_id)
        req = Request(kind, payload, h, deadline, now, trace_id)
        try:
            self.queue.put(req)
        except QueueFullError:
            self._note_rejected(req, "queue_full")
            raise
        except DeadlineExceededError:
            self._note_rejected(req, "deadline")
            raise
        with self._stats_lock:
            self.stats["queries"] += 1
        _queue_depth.set(len(self.queue))
        _queue_hw.set(self.queue.high_water)
        return h

    def submit_bfs(self, root: int,
                   deadline_s: Optional[float] = None) -> ResultHandle:
        """BFS from ``root``; handle resolves to a `BfsResult`."""
        return self._submit("bfs", int(root), deadline_s)

    def submit_cc(self, vertex: int,
                  deadline_s: Optional[float] = None) -> ResultHandle:
        """Connected-component label of ``vertex`` (int; two vertices
        are connected iff their labels match)."""
        return self._submit("cc", int(vertex), deadline_s)

    def submit_spmv(self, x, sr: Semiring = PLUS_TIMES_F32,
                    deadline_s: Optional[float] = None) -> ResultHandle:
        """y = A (x) x for a dense host vector ``x`` (len ncols);
        handle resolves to the (nrows,) result array. Same-semiring
        queries coalesce into one SpMM."""
        x = np.asarray(x)
        if x.shape != (self.a.ncols,):
            raise ValueError(f"x must be ({self.a.ncols},)")
        if jnp.dtype(sr.dtype) != self.a.vals.dtype:
            raise ValueError(
                f"semiring dtype {jnp.dtype(sr.dtype)} does not match "
                f"matrix values {self.a.vals.dtype} (rebuild the "
                "matrix or pick a matching semiring)")
        return self._submit(f"spmv:{sr.name}", (x, sr), deadline_s)

    def submit_spmsv(self, indices, values,
                     sr: Semiring = PLUS_TIMES_F32,
                     deadline_s: Optional[float] = None) -> ResultHandle:
        """Sparse operand as (indices, values); densified with the
        add-identity (which annihilates multiply for every shipped
        semiring) so it batches with `submit_spmv` of the same
        semiring."""
        ident = sr.add.identity_scalar(sr.dtype)
        x = np.full((self.a.ncols,), ident,
                    dtype=np.dtype(jnp.dtype(sr.dtype).name))
        x[np.asarray(indices, np.int64)] = np.asarray(values)
        return self._submit(f"spmv:{sr.name}", (x, sr), deadline_s)

    # blocking conveniences
    def bfs(self, root: int, deadline_s: Optional[float] = None):
        return self.submit_bfs(root, deadline_s).result()

    def cc(self, vertex: int, deadline_s: Optional[float] = None):
        return self.submit_cc(vertex, deadline_s).result()

    def spmv(self, x, sr: Semiring = PLUS_TIMES_F32,
             deadline_s: Optional[float] = None):
        return self.submit_spmv(x, sr, deadline_s).result()

    # ------------------------------------------------------------------
    # worker loop
    # ------------------------------------------------------------------

    def _worker(self) -> None:
        """Supervisor: runs `_worker_loop` and, when it CRASHES (an
        exception escaping the per-batch fan-out — e.g. batch
        formation itself raising), fails every queued future fast with
        `WorkerCrashedError` instead of stranding clients on handles
        nobody will ever resolve, then restarts the loop up to
        `cfg.worker_max_restarts` times. Beyond that the service is
        dead: submissions refuse, /healthz goes false."""
        while True:
            try:
                self._worker_loop()
                return                       # clean stop/drain exit
            except BaseException as e:       # noqa: BLE001 — supervised
                _worker_crashes.inc()
                with self._stats_lock:
                    self.stats["worker_restarts"] += 1
                    restarts = self.stats["worker_restarts"]
                for r in self.queue.drain():
                    if not r.handle.done():
                        r.handle.set_exception(WorkerCrashedError(
                            f"serve worker crashed ({e!r}); request "
                            "failed fast"))
                        self._note_shed(r, "worker_crash")
                if restarts > self.cfg.worker_max_restarts:
                    self._worker_dead = True
                    return

    def _worker_loop(self) -> None:
        while True:
            if self._stop.is_set() and len(self.queue) == 0:
                return
            if not self.queue.wait_nonempty(self.cfg.drain_poll_s):
                continue
            batch = self.batcher.form()
            _queue_depth.set(len(self.queue))
            if batch is None:
                continue
            try:
                self._execute(batch)
            except BaseException as e:   # noqa: BLE001 — fan out, keep serving
                for r in batch.requests:
                    if not r.handle.done():
                        r.handle.set_exception(e)

    def _shed_predicted(self, batch: Batch) -> Optional[Batch]:
        """Shed-before-dispatch (cc/spmv): requests whose remaining
        deadline is below the kind's EWMA dispatch-cost estimate are
        doomed — joining the dispatch would only burn device time and
        delay the rest of the queue. They fail with
        DeadlineExceededError NOW; returns the surviving batch (None
        when everything shed, so the dispatch is skipped entirely).
        BFS is exempt: its level budget degrades to a partial result
        instead of an error."""
        est = self._cost_est.get(batch.kind)
        if est is None:
            return batch
        now = time.monotonic()
        keep = []
        for r in batch.requests:
            remain = r.remaining(now)
            if remain is not None and remain < est:
                r.handle.set_exception(DeadlineExceededError(
                    f"predicted {batch.kind} dispatch cost {est:.4f}s "
                    f"exceeds remaining deadline {remain:.4f}s"))
                self._note_shed(r, "predicted")
            else:
                keep.append(r)
        if not keep:
            return None
        if len(keep) == len(batch.requests):
            return batch
        return Batch(batch.kind, keep,
                     bucket_for(len(keep), self.cfg.buckets))

    def _breaker(self, kind: str) -> Optional[CircuitBreaker]:
        """Per-base-kind breaker ("spmv:<sr>" pools under "spmv", like
        the SLO ledger); None when disabled (breaker_threshold=0)."""
        if self.cfg.breaker_threshold <= 0:
            return None
        base = kind.split(":", 1)[0]
        with self._breaker_lock:
            br = self._breakers.get(base)
            if br is None:
                br = self._breakers[base] = CircuitBreaker(
                    base,
                    failure_threshold=self.cfg.breaker_threshold,
                    recovery_s=self.cfg.breaker_recovery_s,
                    half_open_max=self.cfg.breaker_half_open_max)
            return br

    def _execute(self, batch: Batch) -> None:
        if batch.kind != "bfs" and self.cfg.predictive_shed:
            batch = self._shed_predicted(batch)
            if batch is None:
                return
        # circuit breaker, AFTER the predictive shed: the shed predicts
        # a deadline miss, the breaker observes the kind actually
        # failing — open means fail fast instead of burning device time
        # (and retry budget) on a broken path
        br = self._breaker(batch.kind)
        if br is not None and not br.allow():
            for r in batch.requests:
                r.handle.set_exception(CircuitOpenError(
                    f"{batch.kind} circuit open after repeated dispatch "
                    "failures; failing fast until a recovery probe "
                    "succeeds"))
                self._note_shed(r, "breaker")
            return
        # propagate the request trace ids onto the worker thread: the
        # batch binds its head request's id thread-locally (ledger
        # records stamp it) and lists EVERY member id on the batch span
        # so one request's activity links queue -> batcher -> engine
        ids = [r.trace_id for r in batch.requests]
        obs.set_trace_id(ids[0])
        try:
            with obs.span("serve.batch", kind=batch.kind,
                          width=len(batch.requests), bucket=batch.bucket,
                          trace_ids=ids):
                try:
                    self._dispatch(batch)
                except BaseException:
                    if br is not None:
                        br.record_failure()
                    raise
                if br is not None:
                    br.record_success()
        finally:
            obs.set_trace_id(None)
        with self._stats_lock:
            self.stats["batches"] += 1
        _occupancy.observe(batch.occupancy, kind=batch.kind)

    def _dispatch(self, batch: Batch) -> None:
        """One batch -> device, with transient-failure retry. Each
        runner rebuilds its device arrays from the requests' host-side
        payloads, so every attempt re-materializes its arguments — the
        donation-aware retry contract (serve dispatches never donate,
        but the property must hold for any runner that starts to).
        Deadline-aware: no retry is attempted past the batch's tightest
        request deadline."""
        if batch.kind == "bfs":
            runner = self._run_bfs
        elif batch.kind == "cc":
            runner = self._run_cc
        elif batch.kind.startswith("spmv:"):
            runner = self._run_spmv
        else:
            raise ValueError(f"unknown query kind {batch.kind!r}")
        if self.cfg.retry_max_attempts <= 1:
            runner(batch)
            return
        deadlines = [r.deadline for r in batch.requests
                     if r.deadline is not None]

        def on_retry(attempt, exc):
            with self._stats_lock:
                self.stats["retries"] += 1

        retry_call(lambda attempt: lambda: runner(batch),
                   policy=self._retry_policy,
                   deadline=min(deadlines) if deadlines else None,
                   name=f"serve.{batch.kind.split(':', 1)[0]}",
                   on_retry=on_retry)

    def _finish(self, req: Request, value) -> None:
        req.handle.set_result(value)
        lat = time.monotonic() - req.enqueued_at
        _latency.observe(lat, kind=req.kind)
        self._slo_count(req.kind, lat <= self.cfg.slo_latency_s)
        with self._stats_lock:
            self.stats["results"] += 1

    def _note_shed(self, req: Request, reason: str) -> None:
        with self._stats_lock:
            self.stats["shed"] += 1
        _shed.inc(kind=req.kind, reason=reason)
        self._slo_count(req.kind, False)   # shed = error budget burned

    def _note_rejected(self, req: Request, reason: str) -> None:
        """Admission-time refusals (queue_full backpressure, dead on
        arrival). Counted separately from `shed`: the caller got the
        exception synchronously, nothing was ever queued — but the
        shed counter still carries the reason label so `/metrics`
        shows every loss mode in one family."""
        with self._stats_lock:
            self.stats["rejected"] += 1
        _shed.inc(kind=req.kind, reason=reason)

    def _count_dispatch(self, kind: str, warmup: bool = False) -> None:
        with self._stats_lock:
            self.stats["warmup_dispatches" if warmup
                       else "dispatches"] += 1
        _dispatches.inc(kind=kind, warmup=int(warmup))

    # ------------------------------------------------------------------
    # plan-time roofline annotations
    # ------------------------------------------------------------------

    def _host_nnz(self) -> int:
        """Matrix nnz on the host, synced at most once per service
        lifetime (plan builds are the only callers — the dispatch path
        never pays the readback)."""
        if self._nnz_cache is None:
            self._nnz_cache = int(self.a.getnnz())
        return self._nnz_cache

    def _annotate_plan(self, name: str, kind: str, width: int) -> None:
        """Register the expected per-dispatch cost of one serve plan
        under its ledger name (obs.costmodel conventions: 2 flops per
        semiring multiply-add, 12-byte COO slot). Called once per
        plan build; the cost-model join multiplies by the ledger's
        observed call count."""
        cm = obs.costmodel
        nnz, nrows = self._host_nnz(), int(self.a.nrows)
        on_mesh = self._mesh != (1, 1)
        if kind == "bfs":
            # one batched traversal touches each stored edge ~once;
            # frontier state is 8 B/vertex/root dense, ~1 bit packed
            words = -(-width // _LANE_W)
            packed = ".bits/" in name or name.endswith(f".l{_LANE_W}")
            fstate = 4.0 * nrows * words if packed else 8.0 * nrows * width
            cm.annotate(name, flops=2.0 * nnz,
                        lbytes=12.0 * nnz + fstate,
                        cbytes=fstate if on_mesh else 0.0)
        elif kind == "cc":
            # label gather: w index reads + w label writes
            cm.annotate(name, lbytes=8.0 * width)
        elif kind == "spmv":
            # dense-panel SpMM: every slot read once, one x gather and
            # one y update per (slot, column)
            cm.annotate(name, flops=2.0 * nnz * width,
                        lbytes=(12.0 + 8.0 * width) * nnz
                        + 8.0 * nrows * width,
                        cbytes=4.0 * nrows * width if on_mesh else 0.0)

    # ------------------------------------------------------------------
    # executors (one device dispatch per batch)
    # ------------------------------------------------------------------

    @staticmethod
    def _pad(arr: np.ndarray, bucket: int) -> np.ndarray:
        """Pad a batch axis up to the bucket by repeating entry 0 (a
        real query, so padding never introduces new compile shapes or
        out-of-range indices)."""
        pad = bucket - arr.shape[0]
        if pad == 0:
            return arr
        return np.concatenate([arr, np.repeat(arr[:1], pad, axis=0)])

    def _bfs_structure(self):
        """Resolve (base_plan, bits_plan) once, lazily. The bits plan
        exists iff the packed-bit batch path is wanted
        (cfg.bfs_bits, COMBBLAS_TPU_SERVE_BITS env) AND eligible
        (routed + pattern-symmetric on a 1x1 grid, or a square routed
        mesh with square vertex blocks — `models.bfs.bits_batch_ok`).
        When ineligible, the reason label lands in /varz
        (`bfs_bits.fallback_reason`)."""
        # Single-flight plan resolution: the tracing under this lock is
        # intentional — it runs ONCE per service lifetime, before any
        # worker dispatches, and serialization is the point (two threads
        # racing plan_bfs is exactly the concurrent-collective shape
        # that hung PR 4). Nothing else ever blocks on _plan_lock while
        # holding another lock, so no ordering edge is created.
        with self._plan_lock:  # analysis: allow(jit-under-lock)
            if not self._plans_resolved:
                mode = self.cfg.bfs_bits
                if os.environ.get("COMBBLAS_TPU_SERVE_BITS", "1") == "0":
                    mode = "off"
                if mode not in ("auto", "on", "off"):
                    raise ValueError(f"bfs_bits={mode!r}: expected "
                                     "'auto', 'on', or 'off'")
                if mode != "off":
                    cand = self._base_plan
                    # cheap structural gate before paying for routing:
                    # a non-square mesh (or non-square blocks) can
                    # never take the bits path, so don't plan for it
                    square = (self._mesh == (1, 1)
                              or (self._mesh[0] == self._mesh[1]
                                  and self.a.tile_m == self.a.tile_n))
                    if square and not _bfs.bits_batch_ok(self.a, cand):
                        cand = _bfs.plan_bfs(self.a, route=True)
                    if _bfs.bits_batch_ok(self.a, cand):
                        self._bits_plan = cand
                        if self._base_plan is None:
                            self._base_plan = cand
                    else:
                        self._bits_reason = (
                            "mesh" if not square
                            else _bfs.bits_fallback_reason(self.a, cand))
                else:
                    self._bits_reason = "disabled"
                if mode == "on" and self._bits_plan is None:
                    raise ValueError(
                        "bfs_bits='on' but the matrix is not eligible "
                        "for the packed-bit batch path (reason: "
                        f"{self._bits_reason}; needs a routed plan on "
                        "a 1x1 grid with verified pattern symmetry, "
                        "or a square routed mesh with square vertex "
                        "blocks; see models.bfs.bits_batch_ok)")
                if self._base_plan is None:
                    self._base_plan = _bfs.plan_bfs(self.a)
                self._plans_resolved = True
            return self._base_plan, self._bits_plan

    def _bfs_plan(self, bucket: int):
        """(effective bucket, executor) for a BFS batch. On the bits
        path the bucket aligns UP to a multiple of the 32-root lane
        width — the whole lane word travels regardless, so the extra
        slots are free — and the cache key carries the lane width."""
        base, bits = self._bfs_structure()
        if bits is not None:
            eb = -(-bucket // _LANE_W) * _LANE_W
            key = PlanKey("bfs", "bits", eb, self._mesh, _LANE_W)

            def build_bits():
                self._annotate_plan(_plan_name(key), "bfs", eb)
                return lambda roots, ml: _bfs.bfs_batch_bits(
                    self.a, roots, ml, plan=bits)
            return eb, self.plans.get_or_build(key, build_bits)
        key = PlanKey("bfs", "select2nd_max_i32", bucket, self._mesh)

        def build_dense():
            self._annotate_plan(_plan_name(key), "bfs", bucket)
            return lambda roots, ml: _bfs.bfs_batch(
                self.a, roots, ml, plan=base)
        return bucket, self.plans.get_or_build(key, build_dense)

    def _run_bfs(self, batch: Batch) -> None:
        reqs = batch.requests
        roots = np.array([r.payload for r in reqs], np.int32)
        # deadline -> level budget: enough levels to fit the tightest
        # remaining deadline at the current EWMA per-level estimate
        # (floor 1: always make progress). 0 = unbounded.
        ml = self.cfg.bfs_max_levels
        rem = [r.remaining() for r in reqs if r.deadline is not None]
        if rem:
            budget = max(1, int(min(rem) /
                                max(self._bfs_level_est, 1e-9)))
            ml = budget if ml <= 0 else min(ml, budget)
        bucket, fn = self._bfs_plan(batch.bucket)
        roots_p = self._pad(roots, bucket)
        t0 = time.monotonic()
        mv, lvl, done = fn(jnp.asarray(roots_p), jnp.int32(ml))
        parents = mv.to_global()              # blocks on readback
        wall = time.monotonic() - t0
        self._count_dispatch("bfs")
        with obs.ledger.readback("serve.bfs_readback",
                                 4 * int(np.size(lvl))
                                 + int(np.size(done))):
            lvl = np.asarray(lvl)
            done = np.asarray(done)
        # bits path: per-lane level counts; dense path: one scalar wave
        # count. The EWMA tracks the wave (max), each result reports
        # its own lane.
        levels = int(lvl.max()) if lvl.ndim else int(lvl)
        if levels > 0:
            self._bfs_level_est = (0.7 * self._bfs_level_est
                                   + 0.3 * wall / levels)
        for k, r in enumerate(reqs):
            complete = bool(done[k])
            if not complete:
                with self._stats_lock:
                    self.stats["partials"] += 1
            self._finish(r, BfsResult(
                parents[:, k], int(lvl[k]) if lvl.ndim else levels,
                complete, int(roots[k])))

    def _labels_device(self):
        """Component labels, computed once for the service lifetime
        (the single amortized dispatch every CC lookup shares)."""
        # Single-flight label build: fastsv under the lock is the
        # cheapest correct design — the alternative (build outside,
        # double-check inside) dispatches fastsv N times under a racing
        # warmup. All callers reach here from the one worker thread or
        # a warmup that runs before workers start; _cc_lock -> _stats
        # lock (via _count_dispatch) is the only out-edge and _stats is
        # a leaf lock.
        with self._cc_lock:  # analysis: allow(jit-under-lock)
            if self._cc_labels is None:
                labels = _cc.fastsv(self.a)
                self._cc_labels = jnp.asarray(labels.to_global())
                self._count_dispatch("cc_labels")
            return self._cc_labels

    def _update_cost(self, kind: str, wall: float) -> None:
        """EWMA per-dispatch wall estimate feeding _shed_predicted
        (same 0.7/0.3 blend as the BFS level estimate)."""
        old = self._cost_est.get(kind)
        self._cost_est[kind] = (wall if old is None
                                else 0.7 * old + 0.3 * wall)

    def _cc_plan(self, bucket: int):
        key = PlanKey("cc", "-", bucket, self._mesh)

        def build():
            self._annotate_plan(_plan_name(key), "cc", bucket)
            return jax.jit(lambda lab, ix: lab[ix])  # analysis: allow(cache-key-unstable) built once per PlanKey, PlanCache-cached
        return self.plans.get_or_build(key, build)

    def _run_cc(self, batch: Batch) -> None:
        reqs = batch.requests
        labels = self._labels_device()
        verts = np.array([r.payload for r in reqs], np.int32)
        verts_p = self._pad(verts, batch.bucket)
        fn = self._cc_plan(batch.bucket)
        t0 = time.monotonic()
        out_dev = fn(labels, jnp.asarray(verts_p))
        with obs.ledger.readback("serve.cc_readback", 4 * len(verts_p)):
            out = np.asarray(out_dev)
        self._update_cost("cc", time.monotonic() - t0)
        self._count_dispatch("cc")
        for k, r in enumerate(reqs):
            self._finish(r, int(out[k]))

    def _spmv_plan(self, sr: Semiring, bucket: int):
        key = PlanKey("spmv", sr.name, bucket, self._mesh)

        def build():
            self._annotate_plan(_plan_name(key), "spmv", bucket)
            grid, tn, glen = self.a.grid, self.a.tile_n, self.a.ncols
            nrows = self.a.nrows
            # square meshes take the tall-and-skinny schedule: the
            # stacked panel enters ROW-aligned (the serve-native
            # alignment) and hops once via collective_permute while
            # A's tiles stay put (densemat.spmm_tall)
            tall = grid.pr == grid.pc and self.a.tile_m == tn

            @partial(jax.jit)  # analysis: allow(cache-key-unstable) built once per PlanKey, PlanCache-cached
            def run(a, arr):                  # arr: (glen, W)
                if tall:
                    data = jnp.pad(
                        arr, ((0, grid.pr * tn - glen), (0, 0)))
                    x = dmm.DistMultiVec(
                        data.reshape(grid.pr, tn, arr.shape[1]), grid,
                        ROW_AXIS, glen)
                    return dmm.spmm_tall(sr, a, x).data
                data = jnp.pad(
                    arr, ((0, grid.pc * tn - glen), (0, 0)))
                x = dmm.DistMultiVec(
                    data.reshape(grid.pc, tn, arr.shape[1]), grid,
                    COL_AXIS, glen)
                return dmm.spmm(sr, a, x).data

            def call(arr):
                y_dev = run(self.a, jnp.asarray(arr, sr.dtype))
                with obs.ledger.readback(
                        "serve.spmv_readback",
                        int(y_dev.size) * y_dev.dtype.itemsize):
                    y = np.asarray(y_dev)
                return y.reshape(-1, arr.shape[1])[:nrows]
            return call
        return self.plans.get_or_build(key, build)

    def _run_spmv(self, batch: Batch) -> None:
        reqs = batch.requests
        sr = reqs[0].payload[1]
        xs = np.stack([r.payload[0] for r in reqs])    # (w, glen)
        xs = self._pad(xs, batch.bucket).T             # (glen, bucket)
        fn = self._spmv_plan(sr, batch.bucket)
        t0 = time.monotonic()
        y = fn(xs)                                     # (nrows, bucket)
        self._update_cost(f"spmv:{sr.name}", time.monotonic() - t0)
        self._count_dispatch(f"spmv:{sr.name}")
        for k, r in enumerate(reqs):
            self._finish(r, y[:, k])

    # ------------------------------------------------------------------
    # warm-up prefill
    # ------------------------------------------------------------------

    def warmup(self, kinds=("bfs", "cc"), buckets=None) -> int:
        """Compile every (kind x bucket) executable with a dummy batch
        so steady-state traffic never pays a first-touch compile.
        ``kinds`` entries are "bfs", "cc", or a `Semiring` (= spmv of
        that semiring). Returns the number of warm-up dispatches
        (counted in stats["warmup_dispatches"], not "dispatches")."""
        buckets = tuple(buckets or self.cfg.buckets)
        n = 0
        for kind in kinds:
            for b in buckets:
                if kind == "bfs":
                    eb, fn = self._bfs_plan(b)
                    mv, lvl, done = fn(
                        jnp.zeros((eb,), jnp.int32), jnp.int32(1))
                    jax.block_until_ready(mv.data)
                    self._count_dispatch("bfs", warmup=True)
                elif kind == "cc":
                    labels = self._labels_device()
                    fn = self._cc_plan(b)
                    np.asarray(fn(labels, jnp.zeros((b,), jnp.int32)))
                    self._count_dispatch("cc", warmup=True)
                elif isinstance(kind, Semiring):
                    self._spmv_plan(kind, b)(
                        np.zeros((self.a.ncols, b)))
                    self._count_dispatch(f"spmv:{kind.name}",
                                         warmup=True)
                else:
                    raise ValueError(f"unknown warmup kind {kind!r}")
                n += 1
        return n
