"""Thread-safe request queue with admission control, backpressure,
and per-request deadlines.

The queue is the service's only buffer: a bounded FIFO whose bound IS
the backpressure mechanism — `put` on a full queue raises
`QueueFullError` immediately (clients retry or shed load) instead of
queueing unboundedly and letting every deadline expire at once.
Deadlines are absolute `time.monotonic()` instants checked at three
points: admission (dead-on-arrival -> raise), batch formation
(expired in queue -> shed with `DeadlineExceededError` on the
handle), and in-flight (the engine degrades to a level-budgeted
partial BFS rather than erroring — see engine.py).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Any, Optional


class ServeError(Exception):
    """Base of the serving layer's typed errors."""


class QueueFullError(ServeError):
    """Admission control: queue at max depth — retry later (the
    backpressure signal)."""


class DeadlineExceededError(ServeError):
    """The request's deadline passed before it could be (fully)
    served."""


class ServiceStoppedError(ServeError):
    """Submitted to, or left pending in, a stopped service."""


class WorkerCrashedError(ServeError):
    """The worker thread died executing this request's queue (it is
    restarted up to `ServeConfig.worker_max_restarts` times; queued
    futures are failed fast instead of hanging forever)."""


class ResultHandle:
    """Future for one request: the worker thread fulfills it, the
    client blocks on `result()`. Carries the request's ``trace_id`` so
    clients can correlate their result with the spans/ledger records
    the service stamped along the way."""

    def __init__(self, trace_id: Optional[str] = None):
        self.trace_id = trace_id
        self._event = threading.Event()
        self._result: Any = None
        self._exc: Optional[BaseException] = None

    def set_result(self, value) -> None:
        self._result = value
        self._event.set()

    def set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block until fulfilled; returns the value or raises the
        request's error (TimeoutError if ``timeout`` elapses first)."""
        if not self._event.wait(timeout):
            raise TimeoutError("result not ready")
        if self._exc is not None:
            raise self._exc
        return self._result


@dataclasses.dataclass
class Request:
    """One queued query. ``kind`` is the batching key — only same-kind
    requests coalesce (e.g. "bfs", "cc", "spmv:plus_times_f32")."""

    kind: str
    payload: Any
    handle: ResultHandle
    deadline: Optional[float]       # absolute time.monotonic(), or None
    enqueued_at: float
    trace_id: Optional[str] = None  # correlation token, queue -> engine

    def remaining(self, now: Optional[float] = None) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.deadline - (time.monotonic() if now is None else now)

    def expired(self, now: Optional[float] = None) -> bool:
        r = self.remaining(now)
        return r is not None and r <= 0


class RequestQueue:
    """Bounded FIFO with kind-selective removal (the batcher pulls
    runs of same-kind requests without disturbing the order of the
    rest). All operations lock; `wait_nonempty` parks on a condition
    so the worker never spins on an empty queue."""

    def __init__(self, max_depth: int):
        if max_depth < 1:
            raise ValueError("max_depth must be >= 1")
        self.max_depth = max_depth
        self.high_water = 0         # deepest the queue has ever been
        self._q: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def put(self, req: Request) -> None:
        """Admit a request, or raise: `QueueFullError` at max depth,
        `DeadlineExceededError` when dead on arrival."""
        if req.expired():
            raise DeadlineExceededError(
                f"{req.kind} request dead on arrival")
        with self._lock:
            if len(self._q) >= self.max_depth:
                raise QueueFullError(
                    f"queue at max depth {self.max_depth}")
            self._q.append(req)
            if len(self._q) > self.high_water:
                self.high_water = len(self._q)
            self._nonempty.notify()

    def wait_nonempty(self, timeout: Optional[float] = None) -> bool:
        """Block until the queue has work (or ``timeout``); True iff
        non-empty on return."""
        with self._lock:
            if not self._q:
                self._nonempty.wait(timeout)
            return bool(self._q)

    def head_kind(self) -> Optional[str]:
        with self._lock:
            return self._q[0].kind if self._q else None

    def take(self, kind: str, limit: int) -> list:
        """Remove and return up to ``limit`` requests of ``kind``,
        scanning from the front (FIFO among that kind; other kinds
        keep their relative order)."""
        out = []
        with self._lock:
            if not self._q or limit <= 0:
                return out
            keep = collections.deque()
            while self._q and len(out) < limit:
                r = self._q.popleft()
                (out if r.kind == kind else keep).append(r)
            keep.extend(self._q)
            self._q = keep
        return out

    def drain(self) -> list:
        """Remove and return everything (shutdown path)."""
        with self._lock:
            out = list(self._q)
            self._q.clear()
        return out
