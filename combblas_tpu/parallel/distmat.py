"""Distributed sparse matrix: 2D block distribution over the grid.

Capability parity: `SpParMat<IT,NT,DER>` (SpParMat.h:67) — a local
matrix per process + a shared CommGrid; construction via the
tuple-shuffle `SparseCommon` (SpParMat.cpp:2835); `Transpose`
(SpParMat.cpp:3470); `LoadImbalance` (SpParMat.cpp:762); `PrintInfo`.

TPU-native re-design: the whole distributed matrix is ONE pytree of
stacked per-tile arrays with leading (pr, pc) grid dims, sharded
``P("r", "c", None)`` so each device holds exactly its tile. Every
tile shares one static capacity (the "essentials" pre-agreement of
SpParHelper::GetSetSizes becomes a compile-time bound). Distributed
ops open the pytree with shard_map; grid-level structural ops
(transpose) are array-level axis swaps that XLA lowers to the
pairwise device exchange the reference does by Sendrecv.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from combblas_tpu import obs
from combblas_tpu.ops import tile as tl
from combblas_tpu.ops.semiring import Monoid, Semiring
from combblas_tpu.parallel.grid import ProcGrid, ROW_AXIS, COL_AXIS

Array = jax.Array


def _ceil_div(a, b):
    return -(-a // b)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DistSpMat:
    """2D block-distributed sparse matrix (the SpParMat equivalent).

    rows/cols/vals: (pr, pc, cap) — tile (i, j) in slot [i, j], local
    coordinates, each tile a valid sorted COO tile (see ops.tile).
    nnz: (pr, pc) live counts. Logical size nrows×ncols; tiles are
    tile_m×tile_n with the last row/col of tiles padded.
    """

    rows: Array
    cols: Array
    vals: Array
    nnz: Array
    grid: ProcGrid = dataclasses.field(metadata=dict(static=True))
    nrows: int = dataclasses.field(metadata=dict(static=True))
    ncols: int = dataclasses.field(metadata=dict(static=True))
    tile_m: int = dataclasses.field(metadata=dict(static=True))
    tile_n: int = dataclasses.field(metadata=dict(static=True))

    # -- basic info --------------------------------------------------------
    @property
    def cap(self) -> int:
        return self.rows.shape[-1]

    @property
    def dtype(self):
        return self.vals.dtype

    def getnnz(self) -> int:
        """Global nonzero count (≅ SpParMat::getnnz)."""
        return int(np.asarray(self.nnz, dtype=np.int64).sum())

    def load_imbalance(self) -> float:
        """max/avg tile nnz (≅ LoadImbalance, SpParMat.cpp:762)."""
        nnz = np.asarray(self.nnz, dtype=np.float64)
        avg = nnz.mean()
        return float(nnz.max() / avg) if avg > 0 else 1.0

    def print_info(self, name="A"):
        print(f"{name}: {self.nrows} x {self.ncols}, nnz {self.getnnz()}, "
              f"grid {self.grid.pr}x{self.grid.pc}, tile "
              f"{self.tile_m}x{self.tile_n} cap {self.cap}, "
              f"imbalance {self.load_imbalance():.2f}")

    def tile_at(self, i: int, j: int) -> tl.Tile:
        """Host-side view of one tile (debug/test)."""
        return tl.Tile(self.rows[i, j], self.cols[i, j], self.vals[i, j],
                       self.nnz[i, j], self.tile_m, self.tile_n)

    def astype(self, dtype) -> "DistSpMat":
        return dataclasses.replace(self, vals=self.vals.astype(dtype))


# ---------------------------------------------------------------------------
# Construction (≅ SparseCommon tuple shuffle, SpParMat.cpp:2835)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=(
    "add", "grid", "nrows", "ncols", "cap", "dedup"))
def _build_tiles(add, grid, rows, cols, vals, nrows, ncols, cap, dedup):
    pr, pc = grid.pr, grid.pc
    tile_m = _ceil_div(nrows, pr)
    tile_n = _ceil_div(ncols, pc)
    ti = jnp.repeat(jnp.arange(pr, dtype=jnp.int32), pc)
    tj = jnp.tile(jnp.arange(pc, dtype=jnp.int32), pr)

    def one(i, j):
        mine = (rows // tile_m == i) & (cols // tile_n == j)
        return tl.from_coo(add, rows - i * tile_m, cols - j * tile_n, vals,
                           nrows=tile_m, ncols=tile_n, cap=cap,
                           valid=mine, dedup=dedup, return_full=True)
    batched, full = jax.vmap(one)(ti, tj)
    return (batched.rows.reshape(pr, pc, cap),
            batched.cols.reshape(pr, pc, cap),
            batched.vals.reshape(pr, pc, cap),
            batched.nnz.reshape(pr, pc),
            full.reshape(pr, pc))


def from_global_coo(add: Monoid, grid: ProcGrid, rows, cols, vals,
                    nrows: int, ncols: int, cap: Optional[int] = None,
                    dedup: bool = True, grow: bool = True) -> DistSpMat:
    """Distribute a global COO edge/triple list onto the grid.

    The owner of (r, c) is tile (r // tile_m, c // tile_n) — block
    distribution as in the reference (Owner, SpParMat.h:210). ``cap``
    is the shared per-tile capacity; if any tile's true (deduplicated)
    entry count exceeds it, the build **re-plans with an exact cap**
    (grow=True, the realloc-on-demand semantics of SpTuples.h:88) or
    raises (grow=False). No silent entry dropping, ever.
    """
    rows = jnp.asarray(rows, jnp.int32)
    cols = jnp.asarray(cols, jnp.int32)
    vals = jnp.asarray(vals)
    if rows.shape[0] == 0:
        # zero-entry input: one out-of-range (dropped) placeholder keeps
        # every kernel's shape machinery away from 0-length arrays. It
        # must sit beyond the PADDED dims (pr*tile_m, pc*tile_n) — the
        # logical (nrows, ncols) corner can fall inside the last tile's
        # padding and would survive as a phantom entry
        rows = jnp.full((1,), _ceil_div(nrows, grid.pr) * grid.pr,
                        jnp.int32)
        cols = jnp.full((1,), _ceil_div(ncols, grid.pc) * grid.pc,
                        jnp.int32)
        vals = jnp.zeros((1,), vals.dtype)
    if cap is None:
        per = _ceil_div(int(rows.shape[0]), grid.pr * grid.pc)
        cap = min(int(rows.shape[0]),
                  max(64, 2 * per))
    r, c, v, nnz, full = _build_tiles(add, grid, rows, cols, vals,
                                      nrows, ncols, cap, dedup)
    max_full = int(np.asarray(full).max())
    if max_full > cap:
        if not grow:
            raise ValueError(
                f"tile overflow: a tile holds {max_full} entries > cap "
                f"{cap}; pass a larger cap or grow=True")
        # exact re-plan: nnz_full is the true per-tile count (dedup runs
        # before the clamp), so one rebuild always suffices
        cap = -(-max_full // 128) * 128  # lane-aligned
        r, c, v, nnz, full = _build_tiles(add, grid, rows, cols, vals,
                                          nrows, ncols, cap, dedup)
    shard3 = grid.sharding(ROW_AXIS, COL_AXIS, None)
    shard2 = grid.sharding(ROW_AXIS, COL_AXIS)
    return DistSpMat(
        jax.device_put(r, shard3), jax.device_put(c, shard3),
        jax.device_put(v, shard3), jax.device_put(nnz, shard2),
        grid, nrows, ncols,
        _ceil_div(nrows, grid.pr), _ceil_div(ncols, grid.pc))


@partial(jax.jit, static_argnames=("add", "grid", "nrows", "ncols",
                                   "tile_m", "tile_n", "cap_out", "dedup",
                                   "banded"))
def _merge_chunk(add: Monoid, grid: ProcGrid, acc_r, acc_c, acc_v, acc_n,
                 rows, cols, vals, nrows: int, ncols: int,
                 tile_m: int, tile_n: int, cap_out: int, dedup: bool,
                 banded: bool = False, band_lo=0, band_hi=0):
    # band bounds are TRACED so all bands of one cap bucket share one
    # compiled program (a static band tuple would compile per band)
    """Fold one global-coordinate COO chunk into the per-tile
    accumulators: per tile, concat (acc live prefix sentinels intact) +
    the chunk's owned entries, one sort_compress. Returns the new
    stacked tiles plus per-tile true (pre-clamp) counts for growth."""
    pr, pc = grid.pr, grid.pc
    ti = jnp.repeat(jnp.arange(pr, dtype=jnp.int32), pc)
    tj = jnp.tile(jnp.arange(pc, dtype=jnp.int32), pr)

    def one(i, j, ar, ac, av, an):
        # explicit LOGICAL bounds, not just tile-index match: on grids
        # whose dims don't divide nrows/ncols, an out-of-range marker
        # (e.g. the generator's overrun sentinel n) can land inside the
        # last block's PADDING and would survive as a phantom entry
        inb = (rows >= 0) & (rows < nrows) & (cols >= 0) & (cols < ncols)
        mine = inb & (rows // tile_m == i) & (cols // tile_n == j)
        if banded:
            lrow = rows - i * tile_m
            mine = mine & (lrow >= band_lo) & (lrow < band_hi)
        lr = jnp.where(mine, rows - i * tile_m, tile_m)
        lc = jnp.where(mine, cols - j * tile_n, tile_n)
        crr = jnp.concatenate([ar, lr])
        ccc = jnp.concatenate([ac, lc])
        cvv = jnp.concatenate([av, vals.astype(av.dtype)])
        nlive = an + jnp.sum(mine).astype(jnp.int32)
        t, full = tl.sort_compress(add, crr, ccc, cvv, nlive,
                                   nrows=tile_m, ncols=tile_n,
                                   cap=cap_out, dedup=dedup)
        return t.rows, t.cols, t.vals, t.nnz, full

    r, c, v, n, full = jax.vmap(one)(ti, tj, acc_r.reshape(-1, acc_r.shape[-1]),
                                     acc_c.reshape(-1, acc_c.shape[-1]),
                                     acc_v.reshape(-1, acc_v.shape[-1]),
                                     acc_n.reshape(-1))
    # keep the accumulators mesh-sharded THROUGH the chunk loop: the
    # chunk is replicated (recompute-not-communicate), but each tile's
    # sort must run on its owner — an unsharded vmap would fold the
    # whole matrix on one device and OOM exactly at the scales this
    # builder exists for
    shard3 = grid.sharding(ROW_AXIS, COL_AXIS, None)
    shard2 = grid.sharding(ROW_AXIS, COL_AXIS)
    return (lax.with_sharding_constraint(r.reshape(pr, pc, cap_out), shard3),
            lax.with_sharding_constraint(c.reshape(pr, pc, cap_out), shard3),
            lax.with_sharding_constraint(v.reshape(pr, pc, cap_out), shard3),
            lax.with_sharding_constraint(n.reshape(pr, pc), shard2),
            full.reshape(pr, pc))


#: sort working set per COO slot during a band merge: the i64 fused
#: key + i32 row/col/val copies a bitonic-sort program keeps live at
#: once, empirically ~240 B. 16 GB HBM / 240 B reproduces the 1 << 26
#: per-band budget that survived the scale-24 build (a single-band
#: merge above it crashed the TPU compile helper).
_BAND_SLOT_BYTES = 240


def _band_slots() -> int:
    """Per-band sort budget for the chunked builder, derived from the
    backend's memory capacity (`backend_peaks().hbm_bytes`, so
    COMBBLAS_TPU_PEAKS recalibrates it without a code change) instead
    of the old hard-coded 1 << 26: largest power of two whose sort
    working set fits the chip, floored at 1 << 20. On a 16 GB TPU this
    lands exactly on the empirically safe 1 << 26."""
    try:
        from combblas_tpu.utils.config import backend_peaks
        n = int(float(backend_peaks().hbm_bytes) // _BAND_SLOT_BYTES)
    except Exception:       # peaks unavailable: the proven default
        return 1 << 26
    if n <= 0:
        return 1 << 20
    return max(1 << 20, 1 << (n.bit_length() - 1))


def from_coo_chunks(add: Monoid, grid: ProcGrid, chunk_fn, nchunks: int,
                    nrows: int, ncols: int, *, val_dtype=jnp.bool_,
                    cap: Optional[int] = None, dedup: bool = True,
                    est_total: Optional[int] = None,
                    row_bands: Optional[int] = None) -> DistSpMat:
    """Build a DistSpMat from a chunked COO stream without ever
    materializing the global edge list (≅ the DistEdgeList model:
    per-rank generation + SparseCommon shuffle, DistEdgeList.cpp:223 +
    SpParMat.cpp:2835 — here, chunks bound peak memory and owners
    filter instead of communicating).

    ``chunk_fn(k)`` returns (rows, cols, vals) in GLOBAL coordinates;
    out-of-range coordinates are dropped (the generator marks overrun
    that way). All chunks must share one static shape, so the per-chunk
    fold compiles once per capacity bucket; the capacity grows
    geometrically on overflow (one scalar readback per chunk) and only
    the offending chunk re-merges.

    ``row_bands`` splits each tile's row space into ascending bands
    with independent accumulators, bounding every merge sort to
    (band_cap + chunk) slots; the final tile is assembled with
    ascending dynamic_update_slice writes (each band's garbage tail is
    overwritten by the next band's live prefix) — no global sort ever
    runs, which is what lets a scale-24 matrix (~0.5G entries) build
    on one chip of `backend_peaks().hbm_bytes` capacity (16 GB on a
    v5e). Default: auto from the capacity estimate via `_band_slots`.
    """
    pr, pc = grid.pr, grid.pc
    tile_m = _ceil_div(nrows, pr)
    tile_n = _ceil_div(ncols, pc)
    if cap is None:
        est = est_total if est_total is not None else 0
        cap = max(1024, _ceil_div(est, pr * pc))
    cap = -(-cap // 128) * 128
    if row_bands is None:
        row_bands = max(1, _ceil_div(cap, _band_slots()))
    row_bands = min(row_bands, tile_m)
    # OOM-risk signal at build time: the band loop holds old + new
    # accumulators for ONE band plus the replicated chunk; warn when
    # even that bounded working set crowds the configured headroom
    # fraction of `backend_peaks().hbm_bytes`
    from combblas_tpu.obs import memledger as _memledger
    _memledger.warn_working_set(
        2 * _ceil_div(cap, row_bands) * 12, "from_coo")
    band_m = _ceil_div(tile_m, row_bands)
    bands = [(b * band_m, min((b + 1) * band_m, tile_m))
             for b in range(row_bands)]
    caps = [_qbucket(_ceil_div(cap, row_bands))] * row_bands

    shard3 = grid.sharding(ROW_AXIS, COL_AXIS, None)
    shard2 = grid.sharding(ROW_AXIS, COL_AXIS)

    def fresh(c):
        return (jax.device_put(
                    jnp.full((pr, pc, c), tile_m, jnp.int32), shard3),
                jax.device_put(
                    jnp.full((pr, pc, c), tile_n, jnp.int32), shard3),
                jax.device_put(jnp.zeros((pr, pc, c), val_dtype), shard3),
                jax.device_put(jnp.zeros((pr, pc), jnp.int32), shard2))

    accs: list = [None] * row_bands
    for k in range(nchunks):
        rows, cols, vals = chunk_fn(k)
        rows = jnp.asarray(rows, jnp.int32)
        cols = jnp.asarray(cols, jnp.int32)
        vals = jnp.asarray(vals, val_dtype)
        # bands run SEQUENTIALLY and each replaces its accumulator
        # before the next starts: batching all bands' merges first
        # would hold old+new accumulators for every band at once —
        # 2x the matrix footprint — and OOM'd the scale-24 build
        for b, band in enumerate(bands):
            if accs[b] is None:
                accs[b] = fresh(caps[b])
            prev = accs[b]
            bkw = dict(banded=row_bands > 1,
                       band_lo=jnp.int32(band[0]),
                       band_hi=jnp.int32(band[1]))
            out = _merge_chunk(add, grid, *prev, rows, cols, vals,
                               nrows, ncols, tile_m, tile_n, caps[b],
                               dedup, **bkw)
            max_full = int(np.asarray(out[4]).max())
            if max_full > caps[b]:
                # grow with headroom for the remaining stream (quarter-
                # octave bucket: bands land on shared compile shapes)
                # and re-merge THIS chunk only (prev acc is untouched)
                frac = (k + 1) / nchunks
                caps[b] = _qbucket(int(max_full / frac * 1.1))
                prev = tuple(
                    _grow_stack(x, caps[b], fill)
                    for x, fill in zip(prev[:3], (tile_m, tile_n, None))
                ) + (prev[3],)
                out = _merge_chunk(add, grid, *prev, rows, cols, vals,
                                   nrows, ncols, tile_m, tile_n, caps[b],
                                   dedup, **bkw)
                assert int(np.asarray(out[4]).max()) <= caps[b]
            accs[b] = out[:4]
            del prev, out

    if row_bands == 1:
        acc = accs[0]
        return DistSpMat(acc[0], acc[1], acc[2], acc[3],
                         grid, nrows, ncols, tile_m, tile_n)
    r, c, v, n = _assemble_bands(grid, accs, tile_m, tile_n)
    return DistSpMat(r, c, v, n, grid, nrows, ncols, tile_m, tile_n)


@partial(jax.jit, static_argnames=("grid", "tile_m", "tile_n"))
def _assemble_bands(grid: ProcGrid, accs, tile_m: int, tile_n: int):
    """Concatenate per-band accumulators into one padded sorted tile:
    ascending dynamic_update_slice at the running live offset — band
    b+1's write lands exactly where band b's live prefix ends, erasing
    band b's sentinel tail; a final sentinel write cleans the last
    band's tail. Sortedness is free (bands are ascending row ranges)."""
    pr, pc = grid.pr, grid.pc
    total_cap = sum(a[0].shape[-1] for a in accs)

    def one(parts):
        outr = jnp.full((total_cap,), tile_m, jnp.int32)
        outc = jnp.full((total_cap,), tile_n, jnp.int32)
        outv = jnp.zeros((total_cap,), parts[0][2].dtype)
        off = jnp.zeros((), jnp.int32)
        for (br, bc, bv, bn) in parts:
            outr = lax.dynamic_update_slice(outr, br, (off,))
            outc = lax.dynamic_update_slice(outc, bc, (off,))
            outv = lax.dynamic_update_slice(outv, bv, (off,))
            off = off + bn
        # erase the last band's garbage tail with one mask pass (an
        # update_slice would clamp near the end and clobber live data)
        k = jnp.arange(total_cap, dtype=jnp.int32)
        live = k < off
        outr = jnp.where(live, outr, tile_m)
        outc = jnp.where(live, outc, tile_n)
        outv = jnp.where(live, outv, jnp.zeros((), outv.dtype))
        return outr, outc, outv, off

    rs, cs, vs, ns = [], [], [], []
    for i in range(pr):
        for j in range(pc):
            parts = [(a[0][i, j], a[1][i, j], a[2][i, j], a[3][i, j])
                     for a in accs]
            r_, c_, v_, n_ = one(parts)
            rs.append(r_)
            cs.append(c_)
            vs.append(v_)
            ns.append(n_)
    shard3 = grid.sharding(ROW_AXIS, COL_AXIS, None)
    shard2 = grid.sharding(ROW_AXIS, COL_AXIS)
    r = lax.with_sharding_constraint(
        jnp.stack(rs).reshape(pr, pc, total_cap), shard3)
    c = lax.with_sharding_constraint(
        jnp.stack(cs).reshape(pr, pc, total_cap), shard3)
    v = lax.with_sharding_constraint(
        jnp.stack(vs).reshape(pr, pc, total_cap), shard3)
    n = lax.with_sharding_constraint(
        jnp.stack(ns).reshape(pr, pc), shard2)
    return r, c, v, n


def _qbucket(x: int) -> int:
    """Quarter-octave, 128-aligned capacity bucket: bands/regrowths
    land on few distinct compile shapes (2^k * {1, 1.25, 1.5, 1.75})."""
    x = max(x, 128)
    k = (x - 1).bit_length() - 1
    base = 1 << k
    step = max(base // 4, 128)
    out = base if x <= base else base + step * (-(-(x - base) // step))
    return -(-out // 128) * 128


def _grow_stack(x, new_cap, fill):
    pr, pc, cap = x.shape
    extra = new_cap - cap
    if extra <= 0:
        return x[:, :, :new_cap]
    pad = (jnp.full((pr, pc, extra), fill, x.dtype) if fill is not None
           else jnp.zeros((pr, pc, extra), x.dtype))
    return jnp.concatenate([x, pad], axis=-1)


def from_rmat(add: Monoid, grid: ProcGrid, key, scale: int,
              edgefactor: int = 16, *, symmetrize: bool = True,
              chunk_edges: int = 1 << 24, val_dtype=jnp.bool_,
              permute: bool = True, cap: Optional[int] = None,
              dedup: bool = True) -> DistSpMat:
    """Memory-scalable Graph500 matrix build: R-MAT generated and
    folded in chunks (≅ DistEdgeList::GenGraph500Data +
    SpParMat(DistEdgeList) without the global edge array — the peak
    intermediate is one chunk, not the 2*ef*2^scale edge list)."""
    from combblas_tpu.ops import generate
    n = 1 << scale
    m = edgefactor << scale
    nchunks = max(1, _ceil_div(m, chunk_edges))

    def chunk_fn(k):
        r, c = generate.rmat_edges_chunk(key, scale, edgefactor,
                                         jnp.int32(k), nchunks,
                                         permute=permute)
        if symmetrize:
            r, c = generate.symmetrize(r, c)
        return r, c, jnp.ones_like(r, val_dtype)

    sym_m = 2 * m if symmetrize else m
    # Graph500 R-MAT dedup removes only ~4-5% at ef16 (measured: scale
    # 22 sym keeps 128.3M of 134.2M); a tight estimate avoids capacity
    # growth, whose re-merge recompile costs ~30s per new bucket
    return from_coo_chunks(add, grid, chunk_fn, nchunks, n, n,
                           val_dtype=val_dtype, cap=cap, dedup=dedup,
                           est_total=int(sym_m * 0.98))


def with_capacity(a: DistSpMat, new_cap: int) -> DistSpMat:
    """Re-pad every tile to ``new_cap`` (sentinel rows/cols, zero
    vals). Shrinking requires all live entries to fit (checked).
    Iterative algorithms (MCL) pin their matrix capacity with this so
    every iteration reuses ONE compiled pipeline — per-iteration
    capacity buckets otherwise recompile ~10 programs per step, which
    on a 1-core host with remote compile dwarfs the device work."""
    if new_cap == a.cap:
        return a
    if new_cap < a.cap:
        mx = int(np.asarray(a.nnz).max())
        if mx > new_cap:
            raise ValueError(f"with_capacity({new_cap}) would drop "
                             f"entries: a tile holds {mx}")
        return DistSpMat(a.rows[:, :, :new_cap], a.cols[:, :, :new_cap],
                         a.vals[:, :, :new_cap], a.nnz, a.grid,
                         a.nrows, a.ncols, a.tile_m, a.tile_n)
    extra = new_cap - a.cap
    pr, pc = a.grid.pr, a.grid.pc
    shard3 = a.grid.sharding(ROW_AXIS, COL_AXIS, None)
    rows = jnp.concatenate(
        [a.rows, jnp.full((pr, pc, extra), a.tile_m, jnp.int32)], axis=-1)
    cols = jnp.concatenate(
        [a.cols, jnp.full((pr, pc, extra), a.tile_n, jnp.int32)], axis=-1)
    vals = jnp.concatenate(
        [a.vals, jnp.zeros((pr, pc, extra), a.vals.dtype)], axis=-1)
    return DistSpMat(
        jax.device_put(rows, shard3), jax.device_put(cols, shard3),
        jax.device_put(vals, shard3), a.nnz, a.grid,
        a.nrows, a.ncols, a.tile_m, a.tile_n)


def from_dense(add: Monoid, grid: ProcGrid, dense, zero,
               cap: Optional[int] = None) -> DistSpMat:
    """Test/golden-model constructor from a global dense array."""
    dense = np.asarray(dense)
    nrows, ncols = dense.shape
    rr, cc = np.nonzero(dense != np.asarray(zero))
    vv = dense[rr, cc]
    if cap is None:
        cap = max(64, int(len(rr)))
    return from_global_coo(add, grid, rr.astype(np.int32),
                           cc.astype(np.int32), jnp.asarray(vv),
                           nrows, ncols, cap=cap)


def to_global_coo(a: DistSpMat) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side (rows, cols, vals) in global coordinates (the
    gather-side of SparseCommon; feeds I/O writers and grid rebuilds).
    A deliberate full-matrix blocking readback — bracketed so it lands
    named on the ledger instead of as a stray sync (the checkpoint
    writer calls this from the MCL loop)."""
    with obs.ledger.readback("distmat.to_global_coo",
                             out_bytes=int(a.rows.nbytes + a.cols.nbytes
                                           + a.vals.nbytes)):
        rows = np.asarray(a.rows)
        cols = np.asarray(a.cols)
        vals = np.asarray(a.vals)
        nnz = np.asarray(a.nnz)
    rr, cc, vv = [], [], []
    for i in range(a.grid.pr):
        for j in range(a.grid.pc):
            k = nnz[i, j]
            rr.append(i * a.tile_m + rows[i, j, :k])
            cc.append(j * a.tile_n + cols[i, j, :k])
            vv.append(vals[i, j, :k])
    return (np.concatenate(rr), np.concatenate(cc), np.concatenate(vv))


def to_dense(a: DistSpMat, zero) -> np.ndarray:
    """Gather to a host dense array (test/debug only)."""
    out = np.full((a.grid.pr * a.tile_m, a.grid.pc * a.tile_n),
                  np.asarray(zero), dtype=np.asarray(a.vals).dtype)
    rows = np.asarray(a.rows)
    cols = np.asarray(a.cols)
    vals = np.asarray(a.vals)
    nnz = np.asarray(a.nnz)
    for i in range(a.grid.pr):
        for j in range(a.grid.pc):
            k = nnz[i, j]
            out[i * a.tile_m + rows[i, j, :k],
                j * a.tile_n + cols[i, j, :k]] = vals[i, j, :k]
    return out[:a.nrows, :a.ncols]


# ---------------------------------------------------------------------------
# Structural ops
# ---------------------------------------------------------------------------

def transpose(a: DistSpMat) -> DistSpMat:
    """A^T on any grid (≅ SpParMat::Transpose, SpParMat.cpp:3470).

    Square grids take the fast jitted path: grid-level block swap (an
    array axis swap XLA lowers to the pairwise device exchange the
    reference does by Sendrecv) + local tile transpose. Non-square
    grids fall back to a host-side global rebuild — tile shapes change
    (tile_m'=ceil(ncols/pr)), so entries genuinely reshuffle across all
    devices; the reference sidesteps this by only ever building square
    grids."""
    if a.grid.square:
        return _transpose_square(a)
    r, c, v = to_global_coo(a)
    from combblas_tpu.ops.semiring import PLUS
    return from_global_coo(PLUS, a.grid, c, r, jnp.asarray(v),
                           a.ncols, a.nrows, cap=a.cap, dedup=False)


@jax.jit
def _transpose_square(a: DistSpMat) -> DistSpMat:
    pr, pc, cap = a.grid.pr, a.grid.pc, a.cap
    batched = tl.Tile(a.rows.reshape(-1, cap), a.cols.reshape(-1, cap),
                      a.vals.reshape(-1, cap), a.nnz.reshape(-1),
                      a.tile_m, a.tile_n)
    t = jax.vmap(tl.transpose)(batched)
    rows = t.rows.reshape(pr, pc, cap).swapaxes(0, 1)
    cols = t.cols.reshape(pr, pc, cap).swapaxes(0, 1)
    vals = t.vals.reshape(pr, pc, cap).swapaxes(0, 1)
    nnz = t.nnz.reshape(pr, pc).swapaxes(0, 1)
    shard3 = a.grid.sharding(ROW_AXIS, COL_AXIS, None)
    shard2 = a.grid.sharding(ROW_AXIS, COL_AXIS)
    return DistSpMat(
        jax.lax.with_sharding_constraint(rows, shard3),
        jax.lax.with_sharding_constraint(cols, shard3),
        jax.lax.with_sharding_constraint(vals, shard3),
        jax.lax.with_sharding_constraint(nnz, shard2),
        a.grid, a.ncols, a.nrows, a.tile_n, a.tile_m)
