"""Distributed sparse matrix: 2D block distribution over the grid.

Capability parity: `SpParMat<IT,NT,DER>` (SpParMat.h:67) — a local
matrix per process + a shared CommGrid; construction via the
tuple-shuffle `SparseCommon` (SpParMat.cpp:2835); `Transpose`
(SpParMat.cpp:3470); `LoadImbalance` (SpParMat.cpp:762); `PrintInfo`.

TPU-native re-design: the whole distributed matrix is ONE pytree of
stacked per-tile arrays with leading (pr, pc) grid dims, sharded
``P("r", "c", None)`` so each device holds exactly its tile. Every
tile shares one static capacity (the "essentials" pre-agreement of
SpParHelper::GetSetSizes becomes a compile-time bound). Distributed
ops open the pytree with shard_map; grid-level structural ops
(transpose) are array-level axis swaps that XLA lowers to the
pairwise device exchange the reference does by Sendrecv.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from combblas_tpu.ops import tile as tl
from combblas_tpu.ops.semiring import Monoid, Semiring
from combblas_tpu.parallel.grid import ProcGrid, ROW_AXIS, COL_AXIS

Array = jax.Array


def _ceil_div(a, b):
    return -(-a // b)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DistSpMat:
    """2D block-distributed sparse matrix (the SpParMat equivalent).

    rows/cols/vals: (pr, pc, cap) — tile (i, j) in slot [i, j], local
    coordinates, each tile a valid sorted COO tile (see ops.tile).
    nnz: (pr, pc) live counts. Logical size nrows×ncols; tiles are
    tile_m×tile_n with the last row/col of tiles padded.
    """

    rows: Array
    cols: Array
    vals: Array
    nnz: Array
    grid: ProcGrid = dataclasses.field(metadata=dict(static=True))
    nrows: int = dataclasses.field(metadata=dict(static=True))
    ncols: int = dataclasses.field(metadata=dict(static=True))
    tile_m: int = dataclasses.field(metadata=dict(static=True))
    tile_n: int = dataclasses.field(metadata=dict(static=True))

    # -- basic info --------------------------------------------------------
    @property
    def cap(self) -> int:
        return self.rows.shape[-1]

    @property
    def dtype(self):
        return self.vals.dtype

    def getnnz(self) -> int:
        """Global nonzero count (≅ SpParMat::getnnz)."""
        return int(np.asarray(self.nnz, dtype=np.int64).sum())

    def load_imbalance(self) -> float:
        """max/avg tile nnz (≅ LoadImbalance, SpParMat.cpp:762)."""
        nnz = np.asarray(self.nnz, dtype=np.float64)
        avg = nnz.mean()
        return float(nnz.max() / avg) if avg > 0 else 1.0

    def print_info(self, name="A"):
        print(f"{name}: {self.nrows} x {self.ncols}, nnz {self.getnnz()}, "
              f"grid {self.grid.pr}x{self.grid.pc}, tile "
              f"{self.tile_m}x{self.tile_n} cap {self.cap}, "
              f"imbalance {self.load_imbalance():.2f}")

    def tile_at(self, i: int, j: int) -> tl.Tile:
        """Host-side view of one tile (debug/test)."""
        return tl.Tile(self.rows[i, j], self.cols[i, j], self.vals[i, j],
                       self.nnz[i, j], self.tile_m, self.tile_n)

    def astype(self, dtype) -> "DistSpMat":
        return dataclasses.replace(self, vals=self.vals.astype(dtype))


# ---------------------------------------------------------------------------
# Construction (≅ SparseCommon tuple shuffle, SpParMat.cpp:2835)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=(
    "add", "grid", "nrows", "ncols", "cap", "dedup"))
def _build_tiles(add, grid, rows, cols, vals, nrows, ncols, cap, dedup):
    pr, pc = grid.pr, grid.pc
    tile_m = _ceil_div(nrows, pr)
    tile_n = _ceil_div(ncols, pc)
    ti = jnp.repeat(jnp.arange(pr, dtype=jnp.int32), pc)
    tj = jnp.tile(jnp.arange(pc, dtype=jnp.int32), pr)

    def one(i, j):
        mine = (rows // tile_m == i) & (cols // tile_n == j)
        return tl.from_coo(add, rows - i * tile_m, cols - j * tile_n, vals,
                           nrows=tile_m, ncols=tile_n, cap=cap,
                           valid=mine, dedup=dedup, return_full=True)
    batched, full = jax.vmap(one)(ti, tj)
    return (batched.rows.reshape(pr, pc, cap),
            batched.cols.reshape(pr, pc, cap),
            batched.vals.reshape(pr, pc, cap),
            batched.nnz.reshape(pr, pc),
            full.reshape(pr, pc))


def from_global_coo(add: Monoid, grid: ProcGrid, rows, cols, vals,
                    nrows: int, ncols: int, cap: Optional[int] = None,
                    dedup: bool = True, grow: bool = True) -> DistSpMat:
    """Distribute a global COO edge/triple list onto the grid.

    The owner of (r, c) is tile (r // tile_m, c // tile_n) — block
    distribution as in the reference (Owner, SpParMat.h:210). ``cap``
    is the shared per-tile capacity; if any tile's true (deduplicated)
    entry count exceeds it, the build **re-plans with an exact cap**
    (grow=True, the realloc-on-demand semantics of SpTuples.h:88) or
    raises (grow=False). No silent entry dropping, ever.
    """
    rows = jnp.asarray(rows, jnp.int32)
    cols = jnp.asarray(cols, jnp.int32)
    vals = jnp.asarray(vals)
    if rows.shape[0] == 0:
        # zero-entry input: one out-of-range (dropped) placeholder keeps
        # every kernel's shape machinery away from 0-length arrays. It
        # must sit beyond the PADDED dims (pr*tile_m, pc*tile_n) — the
        # logical (nrows, ncols) corner can fall inside the last tile's
        # padding and would survive as a phantom entry
        rows = jnp.full((1,), _ceil_div(nrows, grid.pr) * grid.pr,
                        jnp.int32)
        cols = jnp.full((1,), _ceil_div(ncols, grid.pc) * grid.pc,
                        jnp.int32)
        vals = jnp.zeros((1,), vals.dtype)
    if cap is None:
        per = _ceil_div(int(rows.shape[0]), grid.pr * grid.pc)
        cap = min(int(rows.shape[0]),
                  max(64, 2 * per))
    r, c, v, nnz, full = _build_tiles(add, grid, rows, cols, vals,
                                      nrows, ncols, cap, dedup)
    max_full = int(np.asarray(full).max())
    if max_full > cap:
        if not grow:
            raise ValueError(
                f"tile overflow: a tile holds {max_full} entries > cap "
                f"{cap}; pass a larger cap or grow=True")
        # exact re-plan: nnz_full is the true per-tile count (dedup runs
        # before the clamp), so one rebuild always suffices
        cap = -(-max_full // 128) * 128  # lane-aligned
        r, c, v, nnz, full = _build_tiles(add, grid, rows, cols, vals,
                                          nrows, ncols, cap, dedup)
    shard3 = grid.sharding(ROW_AXIS, COL_AXIS, None)
    shard2 = grid.sharding(ROW_AXIS, COL_AXIS)
    return DistSpMat(
        jax.device_put(r, shard3), jax.device_put(c, shard3),
        jax.device_put(v, shard3), jax.device_put(nnz, shard2),
        grid, nrows, ncols,
        _ceil_div(nrows, grid.pr), _ceil_div(ncols, grid.pc))


def from_dense(add: Monoid, grid: ProcGrid, dense, zero,
               cap: Optional[int] = None) -> DistSpMat:
    """Test/golden-model constructor from a global dense array."""
    dense = np.asarray(dense)
    nrows, ncols = dense.shape
    rr, cc = np.nonzero(dense != np.asarray(zero))
    vv = dense[rr, cc]
    if cap is None:
        cap = max(64, int(len(rr)))
    return from_global_coo(add, grid, rr.astype(np.int32),
                           cc.astype(np.int32), jnp.asarray(vv),
                           nrows, ncols, cap=cap)


def to_global_coo(a: DistSpMat) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side (rows, cols, vals) in global coordinates (the
    gather-side of SparseCommon; feeds I/O writers and grid rebuilds)."""
    rows = np.asarray(a.rows)
    cols = np.asarray(a.cols)
    vals = np.asarray(a.vals)
    nnz = np.asarray(a.nnz)
    rr, cc, vv = [], [], []
    for i in range(a.grid.pr):
        for j in range(a.grid.pc):
            k = nnz[i, j]
            rr.append(i * a.tile_m + rows[i, j, :k])
            cc.append(j * a.tile_n + cols[i, j, :k])
            vv.append(vals[i, j, :k])
    return (np.concatenate(rr), np.concatenate(cc), np.concatenate(vv))


def to_dense(a: DistSpMat, zero) -> np.ndarray:
    """Gather to a host dense array (test/debug only)."""
    out = np.full((a.grid.pr * a.tile_m, a.grid.pc * a.tile_n),
                  np.asarray(zero), dtype=np.asarray(a.vals).dtype)
    rows = np.asarray(a.rows)
    cols = np.asarray(a.cols)
    vals = np.asarray(a.vals)
    nnz = np.asarray(a.nnz)
    for i in range(a.grid.pr):
        for j in range(a.grid.pc):
            k = nnz[i, j]
            out[i * a.tile_m + rows[i, j, :k],
                j * a.tile_n + cols[i, j, :k]] = vals[i, j, :k]
    return out[:a.nrows, :a.ncols]


# ---------------------------------------------------------------------------
# Structural ops
# ---------------------------------------------------------------------------

def transpose(a: DistSpMat) -> DistSpMat:
    """A^T on any grid (≅ SpParMat::Transpose, SpParMat.cpp:3470).

    Square grids take the fast jitted path: grid-level block swap (an
    array axis swap XLA lowers to the pairwise device exchange the
    reference does by Sendrecv) + local tile transpose. Non-square
    grids fall back to a host-side global rebuild — tile shapes change
    (tile_m'=ceil(ncols/pr)), so entries genuinely reshuffle across all
    devices; the reference sidesteps this by only ever building square
    grids."""
    if a.grid.square:
        return _transpose_square(a)
    r, c, v = to_global_coo(a)
    from combblas_tpu.ops.semiring import PLUS
    return from_global_coo(PLUS, a.grid, c, r, jnp.asarray(v),
                           a.ncols, a.nrows, cap=a.cap, dedup=False)


@jax.jit
def _transpose_square(a: DistSpMat) -> DistSpMat:
    pr, pc, cap = a.grid.pr, a.grid.pc, a.cap
    batched = tl.Tile(a.rows.reshape(-1, cap), a.cols.reshape(-1, cap),
                      a.vals.reshape(-1, cap), a.nnz.reshape(-1),
                      a.tile_m, a.tile_n)
    t = jax.vmap(tl.transpose)(batched)
    rows = t.rows.reshape(pr, pc, cap).swapaxes(0, 1)
    cols = t.cols.reshape(pr, pc, cap).swapaxes(0, 1)
    vals = t.vals.reshape(pr, pc, cap).swapaxes(0, 1)
    nnz = t.nnz.reshape(pr, pc).swapaxes(0, 1)
    shard3 = a.grid.sharding(ROW_AXIS, COL_AXIS, None)
    shard2 = a.grid.sharding(ROW_AXIS, COL_AXIS)
    return DistSpMat(
        jax.lax.with_sharding_constraint(rows, shard3),
        jax.lax.with_sharding_constraint(cols, shard3),
        jax.lax.with_sharding_constraint(vals, shard3),
        jax.lax.with_sharding_constraint(nnz, shard2),
        a.grid, a.ncols, a.nrows, a.tile_n, a.tile_m)
