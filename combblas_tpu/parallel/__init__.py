"""Distributed layer: process-grid mesh, distributed sparse matrices and
vectors, and the collective algorithms (SpMV, SUMMA SpGEMM) over them."""

from combblas_tpu.parallel.grid import ProcGrid
from combblas_tpu.parallel.distmat import DistSpMat
from combblas_tpu.parallel.distvec import DistVec, DistSpVec
