"""Distributed layer: 2D/3D process-grid meshes, distributed sparse
matrices/vectors/dense objects, and the collective algorithms over
them — SpMV/SpMSpV/SpMM, streaming & phased SUMMA SpGEMM, the matrix
algebra surface (Reduce/Apply/Prune/Kselect/DimApply/EWise), and
general indexing/assignment."""

from combblas_tpu.parallel.grid import ProcGrid
from combblas_tpu.parallel.distmat import DistSpMat
from combblas_tpu.parallel.distvec import DistVec, DistSpVec
from combblas_tpu.parallel.densemat import DistDense, DistMultiVec
