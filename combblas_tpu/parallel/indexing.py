"""General indexing and assignment on distributed sparse matrices.

Capability parity: `SubsRef_SR` — B = A(ri, ci) via two
boolean-semiring SpGEMMs with selection matrices (SpParMat.cpp:2028) —
and `SpAsgn` — A(ri, ci) = B via clear-then-scatter (SpParMat.cpp:2427).

TPU-native re-design: identical algebraic structure (selection-matrix
products are the right abstraction on any backend), running on the
streaming SUMMA; the "clear" half of SpAsgn is a PruneI against
row/column membership masks instead of the reference's subtraction
by a materialized old-submatrix.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from combblas_tpu.ops import semiring as S
from combblas_tpu.ops.semiring import Semiring, PLUS, LOR, MAX
from combblas_tpu.parallel import algebra as alg
from combblas_tpu.parallel import distmat as dm
from combblas_tpu.parallel import spgemm as spg
from combblas_tpu.parallel.grid import ProcGrid


def _sel2nd(x, y):
    return y


def _sel1st(x, y):
    return x


def _carry_srs(dtype):
    """(left-apply, right-apply) semirings that carry A's values through
    selection products (≅ BoolCopy2ndSRing / BoolCopy1stSRing,
    Semirings.h:51,97). Selection rows/columns have a single nonzero,
    so any idempotent-safe add works; bool values need a bool monoid."""
    if jnp.dtype(dtype) == jnp.bool_:
        return (Semiring("sel2nd_or", LOR, _sel2nd, jnp.bool_),
                Semiring("sel1st_or", LOR, _sel1st, jnp.bool_))
    return (Semiring("sel2nd_max", MAX, _sel2nd, dtype),
            Semiring("sel1st_max", MAX, _sel1st, dtype))


def selection_matrix(grid: ProcGrid, idx, n: int,
                     transpose: bool = False) -> dm.DistSpMat:
    """P with P[k, idx[k]] = 1 (shape (len(idx), n)); transpose=True
    builds P^T (n, len(idx)). Values are A-dtype-agnostic booleans."""
    idx = np.asarray(idx, np.int32)
    k = len(idx)
    rows = np.arange(k, dtype=np.int32)
    vals = jnp.ones((k,), jnp.bool_)
    if transpose:
        return dm.from_global_coo(LOR, grid, idx, rows, vals, n, k,
                                  dedup=False)
    return dm.from_global_coo(LOR, grid, rows, idx, vals, k, n,
                              dedup=False)


def subs_ref(a: dm.DistSpMat, ri, ci) -> dm.DistSpMat:
    """B = A(ri, ci) (≅ SubsRef_SR, SpParMat.cpp:2028): P·A·Q with
    row-selection P (len(ri) × nrows) and column-selection Q
    (ncols × len(ci)); the semiring copies A's values through."""
    sr2, sr1 = _carry_srs(a.dtype)
    p = selection_matrix(a.grid, ri, a.nrows)
    q = selection_matrix(a.grid, ci, a.ncols, transpose=True)
    pa = spg.spgemm(sr2, p, a)
    return spg.spgemm(sr1, pa, q)


def sp_asgn(a: dm.DistSpMat, ri, ci, b: dm.DistSpMat) -> dm.DistSpMat:
    """A(ri, ci) = B (≅ SpAsgn, SpParMat.cpp:2427): clear the (ri × ci)
    cross of A, then scatter B into it via P^T·B·Q^T. B's zeros (absent
    entries) clear the corresponding positions, as in the reference."""
    ri = np.asarray(ri, np.int32)
    ci = np.asarray(ci, np.int32)
    if (b.nrows, b.ncols) != (len(ri), len(ci)):
        raise ValueError(f"DIMMISMATCH: B is {b.nrows}x{b.ncols}, "
                         f"index sets are {len(ri)}x{len(ci)}")
    rmask = jnp.zeros((a.nrows,), bool).at[jnp.asarray(ri)].set(True)
    cmask = jnp.zeros((a.ncols,), bool).at[jnp.asarray(ci)].set(True)
    cleared = alg.prune_cross(a, rmask, cmask)

    sr2, sr1 = _carry_srs(b.dtype)
    pt = selection_matrix(a.grid, ri, a.nrows, transpose=True)
    qt = selection_matrix(a.grid, ci, a.ncols)
    sb = spg.spgemm(sr2, pt, b)                  # (nrows, len(ci))
    scat = spg.spgemm(sr1, sb, qt)               # (nrows, ncols)
    if scat.dtype != cleared.dtype:
        scat = scat.astype(cleared.dtype)
    return alg.ewise_apply(cleared, scat, _take_b_if_present,
                           allow_a_null=True, allow_b_null=True,
                           pass_presence=True)


def _take_b_if_present(va, vb, a_has, b_has):
    return jnp.where(b_has, vb, va)


def induced_subgraph(a: dm.DistSpMat, vertices) -> dm.DistSpMat:
    """The subgraph induced by a vertex subset — A(vs, vs)
    (≅ InducedSubgraphs2Procs' extraction core, SpParMat.h:111)."""
    return subs_ref(a, vertices, vertices)


def square(sr, a: dm.DistSpMat) -> dm.DistSpMat:
    """A ⊗ A (≅ SpParMat::Square, SpParMat.cpp:3398)."""
    return spg.spgemm(sr, a, a)
