"""The 2D (and 3D) process grid as a JAX device mesh.

Capability parity: `CommGrid` (CommGrid.h:44) builds a √p×√p grid with
row/col/diag sub-communicators and rank↔(i,j) arithmetic;
`CommGrid3D` (CommGrid3D.h:9) adds layers. `ProductGrid`
(src/CommGrid.cpp:164) checks grid compatibility for C = A·B and
returns the number of SUMMA stages.

TPU-native re-design: a `jax.sharding.Mesh` with named axes replaces
communicators entirely — "the row world" is simply collectives over
axis "c" (within a row, across columns), "the column world" axis "r",
and the diagonal is positional arithmetic on (r, c) indices inside
shard_map. Rank math, sub-communicator bookkeeping, and the MPI
type/op caches (MPIType.h, MPIOp.h) have no equivalent: sharding
specs and monoid collectives replace them.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROW_AXIS = "r"   # first mesh axis: which block-row a device owns
COL_AXIS = "c"   # second mesh axis: which block-column
LAYER_AXIS = "l"  # third mesh axis (3D grids): replication layer


@dataclasses.dataclass(frozen=True)
class ProcGrid:
    """A 2D device grid; the CommGrid equivalent.

    ``mesh`` has axes (ROW_AXIS, COL_AXIS) of shape (pr, pc). A device
    at mesh position (i, j) owns block-row i and block-column j of any
    matrix distributed on this grid.
    """

    mesh: Mesh

    @staticmethod
    def make(pr: Optional[int] = None, pc: Optional[int] = None,
             devices: Optional[Sequence] = None) -> "ProcGrid":
        """Build a grid over ``devices`` (default: all). With no shape
        given, picks the squarest pr×pc factorization of the device
        count (the reference requires perfectly square p; a mesh does
        not, but SpGEMM's stage structure still prefers square)."""
        devices = list(devices if devices is not None else jax.devices())
        p = len(devices)
        if pr is None and pc is None:
            pr = int(math.isqrt(p))
            while p % pr:
                pr -= 1
            pc = p // pr
        elif pr is None:
            pr = p // pc
        elif pc is None:
            pc = p // pr
        if pr * pc != p:
            raise ValueError(f"grid {pr}x{pc} != {p} devices")
        arr = np.array(devices).reshape(pr, pc)
        return ProcGrid(Mesh(arr, (ROW_AXIS, COL_AXIS)))

    @property
    def pr(self) -> int:
        return self.mesh.shape[ROW_AXIS]

    @property
    def pc(self) -> int:
        return self.mesh.shape[COL_AXIS]

    @property
    def square(self) -> bool:
        return self.pr == self.pc

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    # -- SUMMA compatibility (≅ ProductGrid, src/CommGrid.cpp:164) ---------
    def stages_with(self, other: "ProcGrid") -> int:
        """Stage count hint for a square-grid SUMMA. Non-square grids
        are supported by the streaming SUMMA (parallel.spgemm), whose
        stage structure comes from `_summa_intervals` instead (at most
        pr + pc - 1 stages)."""
        if self.mesh.devices.shape != other.mesh.devices.shape or \
           (self.mesh.devices != other.mesh.devices).any():
            raise ValueError("GRIDMISMATCH: operands on different grids")
        return max(self.pr, self.pc)

    def __hash__(self):
        return hash((self.mesh.devices.shape,
                     tuple(d.id for d in self.mesh.devices.flat)))

    def __eq__(self, other):
        return (isinstance(other, ProcGrid)
                and self.mesh.devices.shape == other.mesh.devices.shape
                and (self.mesh.devices == other.mesh.devices).all())
